"""FileIO implementations.

reference: paimon-common/.../fs/FileIO.java (SPI), fs/local/LocalFileIO.java.
Paths are plain strings; scheme prefix (``mem://``, ``file://`` or none)
selects the implementation via `get_file_io`.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
import uuid
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["FileIO", "FileStatus", "LocalFileIO", "MemoryFileIO",
           "get_file_io", "register_file_io"]


@dataclass
class FileStatus:
    path: str
    size: int
    is_dir: bool
    mtime_ms: int = 0


def safe_join(root: str, rel_path: str) -> str:
    """Join a user-supplied relative path under `root`, rejecting any
    traversal ('..', absolute paths) that would escape it."""
    rel = rel_path.lstrip("/")
    parts = [p for p in rel.split("/") if p not in ("", ".")]
    if any(p == ".." for p in parts):
        raise ValueError(f"Path escapes the root: {rel_path!r}")
    return f"{root.rstrip('/')}/{'/'.join(parts)}"


class FileIO:
    """Abstract file IO. All paths are absolute strings."""

    # -- reading -------------------------------------------------------------

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        data = self.read_bytes(path)
        return data[offset:offset + length]

    def read_ranges(self, path: str,
                    ranges: List[Tuple[int, int]]) -> List[bytes]:
        """Vectored read: many (offset, length) ranges in one call
        (reference fs/VectoredReadable — object stores coalesce these
        into ranged GETs; the local impl seeks within one open file).
        Default: one whole-file read, sliced."""
        data = self.read_bytes(path)
        return [bytes(data[o:o + ln]) for o, ln in ranges]

    # -- two-phase writes ----------------------------------------------------

    def new_two_phase_stream(self, path: str) -> "TwoPhaseOutputStream":
        """Write-then-publish stream: bytes go to an invisible staging
        location; `close_for_commit()` returns a committer whose
        commit() makes the file visible atomically and whose discard()
        leaves no trace (reference fs/TwoPhaseOutputStream.java,
        RenamingTwoPhaseOutputStream) — the building block for
        multi-file atomic operations."""
        return _BufferedTwoPhaseStream(self, path)

    def read_utf8(self, path: str) -> str:
        return self.read_bytes(path).decode("utf-8")

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def get_file_size(self, path: str) -> int:
        raise NotImplementedError

    def list_status(self, path: str) -> List[FileStatus]:
        raise NotImplementedError

    def list_files(self, path: str) -> List[str]:
        return [s.path for s in self.list_status(path) if not s.is_dir]

    def list_status_recursive(self, path: str) -> List["FileStatus"]:
        out: List[FileStatus] = []
        for st in self.list_status(path):
            if st.is_dir:
                out.extend(self.list_status_recursive(st.path))
            else:
                out.append(st)
        return out

    # -- writing -------------------------------------------------------------

    def write_bytes(self, path: str, data: bytes, overwrite: bool = True):
        raise NotImplementedError

    def write_utf8(self, path: str, text: str, overwrite: bool = True):
        self.write_bytes(path, text.encode("utf-8"), overwrite)

    def try_to_write_atomic(self, path: str, data: bytes) -> bool:
        """Atomically publish `data` at `path`; False if target exists.
        This is the commit CAS primitive (reference
        FileIO.tryToWriteAtomic).

        Contract: `data` must be writer-unique (snapshot JSON embeds
        commitUser uuid; lock files write a random token). On object
        stores, an ambiguous conditional PUT — server error after the
        write landed — is resolved by read-back content equality
        (RetryingObjectStoreBackend), which requires that byte-equal
        means same-writer (or that the operation is idempotent so a
        false positive is harmless)."""
        raise NotImplementedError

    def mkdirs(self, path: str) -> bool:
        raise NotImplementedError

    def delete(self, path: str, recursive: bool = False) -> bool:
        raise NotImplementedError

    def delete_quietly(self, path: str):
        # shielded from the request deadline: quiet deletes are the
        # abort/cleanup contract and run exactly when the deadline is
        # already spent — unshielded, the deadline check inside the
        # store op would raise, be swallowed here, and orphan the very
        # file this cleanup exists to remove (utils/deadline.py)
        from paimon_tpu.utils.deadline import deadline_shield
        try:
            with deadline_shield():
                self.delete(path, False)
        # lint-ok: swallow quiet delete IS the two-phase-commit
        # cleanup contract: best-effort removal whose failure must
        # never fail the caller (fsck collects any orphan later)
        except Exception:
            pass

    def rename(self, src: str, dst: str) -> bool:
        raise NotImplementedError

    def copy(self, src: str, dst: str, overwrite: bool = True):
        self.write_bytes(dst, self.read_bytes(src), overwrite)

    # -- helpers -------------------------------------------------------------

    def is_object_store(self) -> bool:
        return False


def reraise_with_path(e: BaseException, path: str, phase: str):
    """Re-raise `e` as the same exception type with the destination
    path in the message.  A failed part upload inside a two-phase
    stream otherwise surfaces a backend-generic error ("disk full",
    bare errno) with no file context — the caller staging dozens of
    files cannot tell WHICH upload died.  Exception types whose
    constructor rejects a single message fall back to the original."""
    try:
        wrapped = type(e)(f"two-phase {phase} for {path} failed: {e}")
    except Exception:
        raise e
    raise wrapped from e


class TwoPhaseOutputStream:
    """write() bytes, then close_for_commit() -> Committer.

    Contract: `close_for_commit()` performs (or completes) the staging
    upload — any upload failure it raises names the destination path
    in the exception message (see `reraise_with_path`)."""

    def write(self, data: bytes):
        raise NotImplementedError

    def close_for_commit(self) -> "TwoPhaseCommitter":
        raise NotImplementedError


class TwoPhaseCommitter:
    def commit(self):
        raise NotImplementedError

    def discard(self):
        raise NotImplementedError


class _BufferedTwoPhaseStream(TwoPhaseOutputStream):
    """Generic fallback: buffer in memory, publish via
    try_to_write_atomic on commit."""

    def __init__(self, file_io: "FileIO", path: str):
        self._io = file_io
        self._path = path
        self._parts: List[bytes] = []

    def write(self, data: bytes):
        self._parts.append(bytes(data))

    def close_for_commit(self) -> TwoPhaseCommitter:
        io_, path, blob = self._io, self._path, b"".join(self._parts)
        self._parts = []

        class C(TwoPhaseCommitter):
            def commit(self):
                try:
                    ok = io_.try_to_write_atomic(path, blob)
                except FileExistsError:
                    raise
                except Exception as e:      # noqa: BLE001 — re-typed
                    reraise_with_path(e, path, "publish")
                if not ok:
                    raise FileExistsError(path)

            def discard(self):
                pass

        return C()


class LocalFileIO(FileIO):
    """Local filesystem (reference fs/local/LocalFileIO.java)."""

    @staticmethod
    def _strip(path: str) -> str:
        if path.startswith("file://"):
            return path[len("file://"):]
        return path

    def read_bytes(self, path: str) -> bytes:
        with open(self._strip(path), "rb") as f:
            return f.read()

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        with open(self._strip(path), "rb") as f:
            f.seek(offset)
            return f.read(length)

    def read_ranges(self, path: str,
                    ranges: List[Tuple[int, int]]) -> List[bytes]:
        """One open, N seeks — never the whole file."""
        out = []
        with open(self._strip(path), "rb") as f:
            for offset, length in ranges:
                f.seek(offset)
                out.append(f.read(length))
        return out

    def new_two_phase_stream(self, path: str) -> "TwoPhaseOutputStream":
        return _LocalTwoPhaseStream(self, path)

    def exists(self, path: str) -> bool:
        return os.path.exists(self._strip(path))

    def get_file_size(self, path: str) -> int:
        return os.path.getsize(self._strip(path))

    def list_status(self, path: str) -> List[FileStatus]:
        p = self._strip(path)
        if not os.path.isdir(p):
            return []
        out = []
        for name in os.listdir(p):
            full = os.path.join(p, name)
            try:
                st = os.stat(full)
            except FileNotFoundError:
                # raced a concurrent writer/deleter: atomic-write .tmp
                # files and expiring snapshots vanish between listdir
                # and stat — a listing reflects SOME point in time
                continue
            out.append(FileStatus(full, st.st_size, os.path.isdir(full),
                                  int(st.st_mtime * 1000)))
        return out

    def write_bytes(self, path: str, data: bytes, overwrite: bool = True):
        p = self._strip(path)
        if not overwrite and os.path.exists(p):
            raise FileExistsError(p)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as f:
            f.write(data)

    def try_to_write_atomic(self, path: str, data: bytes) -> bool:
        p = self._strip(path)
        if os.path.exists(p):
            return False
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + "." + uuid.uuid4().hex + ".tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
            # On POSIX link() fails if the target exists -> CAS semantics
            # (rename() would silently overwrite).
            try:
                os.link(tmp, p)
                return True
            except FileExistsError:
                return False
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    def mkdirs(self, path: str) -> bool:
        os.makedirs(self._strip(path), exist_ok=True)
        return True

    def delete(self, path: str, recursive: bool = False) -> bool:
        p = self._strip(path)
        if not os.path.exists(p):
            return False
        if os.path.isdir(p):
            if recursive:
                shutil.rmtree(p)
            else:
                os.rmdir(p)
        else:
            os.remove(p)
        return True

    def rename(self, src: str, dst: str) -> bool:
        s, d = self._strip(src), self._strip(dst)
        if os.path.exists(d):
            return False
        os.makedirs(os.path.dirname(d), exist_ok=True)
        try:
            os.rename(s, d)
            return True
        except OSError:
            return False


class _LocalTwoPhaseStream(TwoPhaseOutputStream):
    """Stage in a hidden sibling file, fsync'd, published by rename
    (reference fs/RenamingTwoPhaseOutputStream.java)."""

    def __init__(self, file_io: "LocalFileIO", path: str):
        import uuid as _uuid
        self._io = file_io
        self._final = file_io._strip(path)
        os.makedirs(os.path.dirname(self._final), exist_ok=True)
        self._tmp = os.path.join(
            os.path.dirname(self._final),
            f".{os.path.basename(self._final)}."
            f"{_uuid.uuid4().hex}.inprogress")
        self._f = open(self._tmp, "wb")

    def write(self, data: bytes):
        self._f.write(data)

    def close_for_commit(self) -> TwoPhaseCommitter:
        try:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
        except OSError as e:
            # a torn staging write names the FINAL path it was for,
            # not just the hidden .inprogress temp
            reraise_with_path(e, self._final, "staging write")
        tmp, final = self._tmp, self._final

        class C(TwoPhaseCommitter):
            def commit(self):
                # link(2) fails with EEXIST instead of silently
                # overwriting like rename(2) — the same CAS primitive
                # try_to_write_atomic uses
                try:
                    os.link(tmp, final)
                except FileExistsError:
                    os.remove(tmp)
                    raise FileExistsError(final)
                os.remove(tmp)

            def discard(self):
                try:
                    os.remove(tmp)
                except OSError:
                    pass

        return C()


class MemoryFileIO(FileIO):
    """In-memory FileIO for tests (role of reference test LocalFileIO usage +
    TraceableFileIO). One shared namespace per instance."""

    def __init__(self):
        self._files: Dict[str, bytes] = {}
        self._lock = threading.RLock()

    @staticmethod
    def _strip(path: str) -> str:
        if path.startswith("mem://"):
            return path[len("mem://"):]
        return path

    def read_bytes(self, path: str) -> bytes:
        with self._lock:
            p = self._strip(path)
            if p not in self._files:
                raise FileNotFoundError(path)
            return self._files[p]

    def exists(self, path: str) -> bool:
        with self._lock:
            p = self._strip(path)
            if p in self._files:
                return True
            prefix = p.rstrip("/") + "/"
            return any(k.startswith(prefix) for k in self._files)

    def get_file_size(self, path: str) -> int:
        return len(self.read_bytes(path))

    def list_status(self, path: str) -> List[FileStatus]:
        with self._lock:
            prefix = self._strip(path).rstrip("/") + "/"
            seen = {}
            for k, v in self._files.items():
                if not k.startswith(prefix):
                    continue
                rest = k[len(prefix):]
                if "/" in rest:
                    d = prefix + rest.split("/", 1)[0]
                    seen[d] = FileStatus(d, 0, True)
                else:
                    seen[k] = FileStatus(k, len(v), False)
            return list(seen.values())

    def write_bytes(self, path: str, data: bytes, overwrite: bool = True):
        with self._lock:
            p = self._strip(path)
            if not overwrite and p in self._files:
                raise FileExistsError(path)
            self._files[p] = bytes(data)

    def try_to_write_atomic(self, path: str, data: bytes) -> bool:
        with self._lock:
            p = self._strip(path)
            if p in self._files:
                return False
            self._files[p] = bytes(data)
            return True

    def mkdirs(self, path: str) -> bool:
        return True

    def delete(self, path: str, recursive: bool = False) -> bool:
        with self._lock:
            p = self._strip(path)
            if p in self._files:
                del self._files[p]
                return True
            if recursive:
                prefix = p.rstrip("/") + "/"
                keys = [k for k in self._files if k.startswith(prefix)]
                for k in keys:
                    del self._files[k]
                return bool(keys)
            return False

    def rename(self, src: str, dst: str) -> bool:
        with self._lock:
            s, d = self._strip(src), self._strip(dst)
            if d in self._files or s not in self._files:
                return False
            self._files[d] = self._files.pop(s)
            return True

    def is_object_store(self) -> bool:
        return False


_REGISTRY: Dict[str, Callable[[], FileIO]] = {}
_local = LocalFileIO()


def register_file_io(scheme: str, factory: Callable[[], FileIO]):
    _REGISTRY[scheme] = factory


def get_file_io(path: str) -> FileIO:
    """Resolve a FileIO by path scheme (reference fs/FileIOLoader)."""
    if "://" in path:
        scheme = path.split("://", 1)[0]
        if scheme == "file":
            return _local
        if scheme in _REGISTRY:
            return _REGISTRY[scheme]()
        raise ValueError(f"No FileIO registered for scheme {scheme!r}")
    return _local
