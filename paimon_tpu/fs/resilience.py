"""Tail-tolerant object-store access: hedged reads + per-backend
circuit breakers + deadline-bounded IO waits.

Everything the robustness planes shipped so far reacts to *errors*
(parallel/fault.py taxonomy, utils/backoff.py ladders); this module
defends against *slowness* — the tail that dominates p99 on real
object stores ("The Tail at Scale"):

* **Hedged reads**: GET / ranged-GET / HEAD / LIST track an online
  per-op-class latency quantile; when a call has been in flight longer
  than the adaptive p-`read.hedge.quantile` delay, ONE hedge request
  is issued and the first successful response wins — the loser is
  abandoned, never cancelled mid-store-call.  Hedges are rate-capped
  (`read.hedge.max-ratio`, default 5% extra load) and are NEVER issued
  for mutating ops (PUT/DELETE): a duplicated conditional PUT could
  collide with its own write, a duplicated DELETE could erase a
  successor's object.
* **Circuit breakers**: one breaker per backend, closed -> open on
  consecutive-failure / windowed-error-rate thresholds.  An open
  circuit fails fast (CircuitOpenError, <10ms) instead of queueing
  retry ladders onto a sick store; after `store.breaker.open-ms` a
  half-open probe re-closes it on success.  The breaker composes UNDER
  `RetryingObjectStoreBackend`, whose ladder re-raises
  CircuitOpenError before any backoff sleep.
* **Deadlines**: hedged (pooled) calls wait with
  `utils/deadline.py`-bounded timeouts, so even a HUNG store request
  (stalled socket, not an error) is abandoned the moment the request's
  end-to-end budget is spent.

Composition order (maybe_wrap_resilience):

    RetryingObjectStoreBackend( ResilientObjectStoreBackend( store ) )

so every individual attempt — first try, ladder retry, hedge — is
breaker-accounted and latency-sampled.  Resilient wrappers are
memoized per inner backend: every table.copy() and serving request
shares ONE breaker + ONE latency model per physical store.

Brownout: the serving plane (service/brownout.py) flips the
process-wide `set_degraded(True)` switch under pressure, which
disables hedging (shedding our own extra load first) and shrinks the
scan pipeline's prefetch window.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from paimon_tpu.fs.object_store import (
    CircuitOpenError, ObjectStoreBackend, ObjectStoreFileIO,
    RetryingObjectStoreBackend, TransientStoreError,
)

__all__ = ["CircuitBreaker", "CircuitOpenError", "LatencyTracker",
           "ResilientObjectStoreBackend", "maybe_wrap_resilience",
           "set_degraded", "is_degraded", "breaker_states",
           "hedging_allowed"]


# -- process-wide brownout switch (service/brownout.py flips it) -------------
# aggregated across SOURCES: a process can host several serving
# planes (multiple KvQueryServers over one shared cache tier), and
# one server recovering — or stopping — must not silently clear
# another server's active brownout.  The process is degraded while
# ANY source says so.

_DEGRADED = False
_DEGRADED_LOCK = threading.Lock()
_DEGRADED_SOURCES: set = set()
_MANUAL = "__manual__"


def set_degraded_for(source, active: bool):
    """Mark one source (e.g. a BrownoutController) degraded or
    recovered; the process-wide switch is the OR over live sources."""
    global _DEGRADED
    with _DEGRADED_LOCK:
        if active:
            _DEGRADED_SOURCES.add(source)
        else:
            _DEGRADED_SOURCES.discard(source)
        _DEGRADED = bool(_DEGRADED_SOURCES)


def set_degraded(active: bool):
    """Brownout rung 1+: disable hedging process-wide (shed our own
    extra store load first) and shrink scan prefetch windows
    (parallel/scan_pipeline.py consults is_degraded).  Single-source
    convenience form (tests/manual ops)."""
    set_degraded_for(_MANUAL, active)


def is_degraded() -> bool:
    return _DEGRADED


def hedging_allowed() -> bool:
    return not _DEGRADED


# -- registry of live resilient backends (healthz / brownout signals) --------

_BACKENDS_LOCK = threading.Lock()
_BACKENDS: List["ResilientObjectStoreBackend"] = []


def _register_backend(b: "ResilientObjectStoreBackend"):
    import weakref
    with _BACKENDS_LOCK:
        _BACKENDS.append(weakref.ref(b))


def breaker_states() -> Dict[str, str]:
    """{backend name: breaker state} across every live resilient
    backend in the process — the healthz / brownout signal."""
    out: Dict[str, str] = {}
    with _BACKENDS_LOCK:
        live = [r() for r in _BACKENDS]
        _BACKENDS[:] = [r for r, b in zip(list(_BACKENDS), live)
                        if b is not None]
    for b in live:
        if b is not None and b.breaker is not None:
            out[b.name] = b.breaker.state
    return out


class LatencyTracker:
    """Online per-op-class latency quantiles for the hedge trigger —
    a thin registry of `metrics.Histogram` sliding windows (the same
    deque(maxlen)+locked-percentile machinery every other plane uses;
    a sort of <=512 floats per decision is noise next to a store
    round trip).  Only SUCCESSFUL latencies are recorded: a 503
    storm's fast errors would drag the quantile down and fire hedges
    into the very store that is melting."""

    def __init__(self, window: int = 512, min_samples: int = 20):
        self.window = window
        self.min_samples = min_samples
        self._lock = threading.Lock()
        self._hists: Dict[str, object] = {}

    def _hist(self, op_class: str):
        from paimon_tpu.metrics import Histogram
        with self._lock:
            h = self._hists.get(op_class)
            if h is None:
                h = self._hists[op_class] = Histogram(self.window)
            return h

    def record(self, op_class: str, latency_ms: float):
        self._hist(op_class).update(latency_ms)

    def samples(self, op_class: str) -> int:
        with self._lock:
            h = self._hists.get(op_class)
        return 0 if h is None else h.count

    def percentile_ms(self, op_class: str,
                      p: float) -> Optional[float]:
        """The p-th percentile of recent latencies, or None until
        `min_samples` successes have been observed (no hedging off a
        cold model)."""
        with self._lock:
            h = self._hists.get(op_class)
        if h is None or h.count < self.min_samples:
            return None
        return h.percentile(p)


class CircuitBreaker:
    """closed -> open -> half-open -> closed, per backend.

    * CLOSED: calls pass; `failure_threshold` CONSECUTIVE failures or
      a windowed error rate >= `error_rate` (over the last `window`
      outcomes, once the window is full) trips it OPEN.
    * OPEN: `allow()` is False — callers fail fast with
      CircuitOpenError, no store traffic, no retry-ladder sleeps.
      After `open_ms` the next `allow()` moves to HALF_OPEN.
    * HALF_OPEN: up to `half_open_probes` concurrent trial calls pass;
      the first success re-CLOSES (counters reset), any failure
      re-OPENS with a fresh `open_ms` timer.

    `clock` is injectable; every transition updates the per-backend
    `breaker_state` gauge (0 closed / 1 half-open / 2 open)."""

    CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
    _GAUGE_VALUE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

    def __init__(self, name: str = "store", *,
                 failure_threshold: int = 5, error_rate: float = 0.5,
                 window: int = 32, open_ms: float = 5000.0,
                 half_open_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.failure_threshold = max(1, int(failure_threshold))
        self.error_rate = float(error_rate)
        self.window = max(1, int(window))
        self.open_ms = float(open_ms)
        self.half_open_probes = max(1, int(half_open_probes))
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive = 0
        self._outcomes: deque = deque(maxlen=self.window)
        self._reopen_at = 0.0
        self._probes_left = 0
        self._half_open_at = 0.0
        from paimon_tpu.metrics import (
            RESILIENCE_BREAKER_FAST_FAILS, RESILIENCE_BREAKER_STATE,
            global_registry,
        )
        g = global_registry().resilience_metrics(name)
        self._g_state = g.gauge(RESILIENCE_BREAKER_STATE)
        self._g_state.set(0)
        self._c_fast_fails = global_registry().resilience_metrics() \
            .counter(RESILIENCE_BREAKER_FAST_FAILS)

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _set_state_locked(self, state: str):
        if state != self._state:
            # flight-recorder: breaker flips are the canonical
            # "something was wrong with the store" black-box event
            # (flight's lock is a leaf — safe under self._lock)
            from paimon_tpu.obs.flight import EV_BREAKER, record
            record(EV_BREAKER, backend=self.name, frm=self._state,
                   to=state)
        self._state = state
        self._g_state.set(self._GAUGE_VALUE[state])

    def _maybe_half_open_locked(self):
        now = self._clock()
        if self._state == self.OPEN and now >= self._reopen_at:
            self._set_state_locked(self.HALF_OPEN)
            self._probes_left = self.half_open_probes
            self._half_open_at = now
        elif self._state == self.HALF_OPEN and \
                self._probes_left <= 0 and \
                now >= self._half_open_at + self.open_ms / 1000.0:
            # probe-loss healing: a probe whose outcome was never
            # recorded (hung in a stalled store call — this plane's
            # own threat model — or an exception outside the recorded
            # taxonomy) would otherwise wedge the breaker in
            # HALF_OPEN with zero slots FOREVER; after another
            # open-ms of silence, grant fresh probes
            self._probes_left = self.half_open_probes
            self._half_open_at = now

    def allow(self) -> bool:
        """True when a call may proceed (CLOSED, or a HALF_OPEN probe
        slot).  False = fail fast; the caller raises
        CircuitOpenError without touching the store."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == self.OPEN:
                self._c_fast_fails.inc()
                return False
            if self._state == self.HALF_OPEN:
                if self._probes_left <= 0:
                    self._c_fast_fails.inc()
                    return False
                self._probes_left -= 1
            return True

    def record_success(self):
        with self._lock:
            self._consecutive = 0
            self._outcomes.append(0)
            if self._state == self.HALF_OPEN:
                # the probe came back healthy: close and forget the
                # sick window (old failures must not re-trip at once)
                self._set_state_locked(self.CLOSED)
                self._outcomes.clear()

    def record_failure(self):
        with self._lock:
            self._consecutive += 1
            self._outcomes.append(1)
            if self._state == self.HALF_OPEN:
                self._trip_locked()
                return
            if self._state != self.CLOSED:
                return
            rate_tripped = (
                len(self._outcomes) >= self.window and
                sum(self._outcomes) / len(self._outcomes)
                >= self.error_rate)
            if self._consecutive >= self.failure_threshold or \
                    rate_tripped:
                self._trip_locked()

    def _trip_locked(self):
        self._set_state_locked(self.OPEN)
        self._reopen_at = self._clock() + self.open_ms / 1000.0

    def force_open(self):
        """Test/ops hook: trip the breaker now."""
        with self._lock:
            self._trip_locked()


_HEDGEABLE = frozenset({"get", "range", "head", "list"})


class ResilientObjectStoreBackend(ObjectStoreBackend):
    """Backend wrapper carrying the breaker + hedged-read machinery.

    With hedging enabled, reads (get/range/head/list) run on a small
    internal pool so the caller's wait can be (a) hedged after the
    adaptive quantile delay and (b) bounded by the request deadline
    even when the underlying call HANGS (abandoned mid-flight).
    Mutations (put/delete) always run inline and are never hedged.
    With hedging disabled, reads are plain inline calls with breaker
    accounting only — deadline grace is then the cooperative one-op
    bound, and reads never queue behind the pool."""

    POOL_SIZE = 16

    def __init__(self, inner: ObjectStoreBackend, *,
                 name: str = "store",
                 breaker: Optional[CircuitBreaker] = None,
                 hedge_enabled: bool = False,
                 hedge_quantile: float = 95.0,
                 hedge_min_delay_ms: float = 1.0,
                 hedge_max_ratio: float = 0.05,
                 tracker: Optional[LatencyTracker] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.inner = inner
        self.name = name
        self.breaker = breaker
        self.hedge_enabled = hedge_enabled
        self.hedge_quantile = float(hedge_quantile)
        self.hedge_min_delay_ms = float(hedge_min_delay_ms)
        self.hedge_max_ratio = float(hedge_max_ratio)
        self.tracker = tracker or LatencyTracker()
        self._clock = clock
        self._lock = threading.Lock()
        self._pool = None
        self._ops = 0               # hedgeable calls (rate-cap base)
        self._hedges = 0            # hedges issued (rate-cap numerator)
        from paimon_tpu.metrics import (
            RESILIENCE_HEDGE_WAIT_MS, RESILIENCE_HEDGES_ABANDONED,
            RESILIENCE_HEDGES_ISSUED, RESILIENCE_HEDGES_WON,
            global_registry,
        )
        g = global_registry().resilience_metrics()
        self._m_issued = g.counter(RESILIENCE_HEDGES_ISSUED)
        self._m_won = g.counter(RESILIENCE_HEDGES_WON)
        self._m_abandoned = g.counter(RESILIENCE_HEDGES_ABANDONED)
        self._m_wait = g.histogram(RESILIENCE_HEDGE_WAIT_MS)
        _register_backend(self)

    # -- plumbing ------------------------------------------------------------

    def _get_pool(self):
        with self._lock:
            if self._pool is None:
                from paimon_tpu.parallel.executors import new_thread_pool
                self._pool = new_thread_pool(self.POOL_SIZE,
                                             f"paimon-hedge-{self.name}")
            return self._pool

    def _breaker_gate(self, what: str):
        if self.breaker is not None and not self.breaker.allow():
            raise CircuitOpenError(
                f"{self.name}: circuit open, failing fast ({what})")

    def _run_recorded(self, op_class: str, fn: Callable):
        """One actual store attempt: breaker outcome + latency sample.
        FileNotFoundError counts as a SUCCESS (the store answered
        authoritatively); deadline errors never reach here (waits are
        bounded outside the attempt)."""
        from paimon_tpu.fs.object_store import PreconditionFailed
        t0 = self._clock()
        try:
            result = fn()
        except (FileNotFoundError, PreconditionFailed):
            # the store answered authoritatively (absent key / lost
            # CAS): breaker SUCCESS — critically so for a half-open
            # probe, whose slot must never be consumed outcome-less
            if self.breaker is not None:
                self.breaker.record_success()
            self.tracker.record(op_class,
                                (self._clock() - t0) * 1000.0)
            raise
        except (TransientStoreError, OSError):
            if self.breaker is not None:
                self.breaker.record_failure()
            raise
        if self.breaker is not None:
            self.breaker.record_success()
        self.tracker.record(op_class, (self._clock() - t0) * 1000.0)
        return result

    # margin over the trigger quantile: firing AT p95 would hedge
    # ~5% of ops — the marginal just-past-p95 ones — at exactly the
    # 5% rate cap, starving the true stragglers the hedge exists for
    # (observed in the chaos bench: tail GETs denied budget while
    # jitter-top ops burned it).  1.5x p95 clears the normal latency
    # band entirely; a 20x straggler still hedges almost immediately.
    HEDGE_DELAY_MARGIN = 1.5

    def _hedge_delay_s(self, op_class: str) -> Optional[float]:
        """Adaptive hedge-fire delay, or None when hedging is off
        (disabled, browned out, cold model)."""
        if not self.hedge_enabled or not hedging_allowed():
            return None
        p = self.tracker.percentile_ms(op_class, self.hedge_quantile)
        if p is None:
            return None
        return max(p * self.HEDGE_DELAY_MARGIN,
                   self.hedge_min_delay_ms) / 1000.0

    def _hedge_budget_ok(self) -> bool:
        """Rate cap: hedges stay <= hedge_max_ratio of hedgeable
        calls, so the extra load on an already-slow store is bounded."""
        with self._lock:
            return self._hedges + 1 <= self.hedge_max_ratio * self._ops

    def _read(self, op_class: str, fn: Callable, what: str):
        from paimon_tpu.utils.deadline import (
            DeadlineExceededError, current_deadline,
        )
        dl = current_deadline()
        if dl is not None:
            # BEFORE the breaker gate: a spent deadline must not
            # consume a half-open probe slot it can never report on
            dl.check(what)
        self._breaker_gate(what)
        with self._lock:
            self._ops += 1
            if self._ops + self._hedges >= 1024:
                # decay the rate-cap accounting: a LIFETIME budget
                # would bank ~ratio x total-ops of unspent hedges
                # over a long healthy run and then dump them all onto
                # the store at the exact moment it degrades; halving
                # keeps the burst bounded (~ratio x 1024) while the
                # steady-state cap stays ratio-of-recent-ops
                self._ops //= 2
                self._hedges //= 2
        if not self.hedge_enabled:
            # plain inline call: no pool dispatch, breaker-accounted.
            # Breaker-only configs must not funnel every read through
            # the bounded hedge pool (and pay a thread handoff per
            # GET) just because a deadline is in scope — without
            # hedging, deadline grace is the cooperative one-op bound
            # (the pre-op checks here and in ObjectStoreFileIO);
            # hedge-enabled configs additionally get hung calls
            # ABANDONED mid-flight via the pooled wait below
            return self._run_recorded(op_class, fn)
        delay_s = self._hedge_delay_s(op_class)
        import concurrent.futures as cf
        pool = self._get_pool()
        primary = pool.submit(self._run_recorded, op_class, fn)
        futs = [primary]
        hedge = None
        if delay_s is not None:
            # phase 1: give the primary its p-quantile grace
            t = delay_s if dl is None \
                else min(delay_s, dl.remaining_s())
            done, _ = cf.wait([primary], timeout=t)
            # fire only when the primary really got its full quantile
            # grace — a deadline closer than the hedge delay means the
            # hedge could never finish in time anyway
            if not done and t >= delay_s and \
                    self._hedge_budget_ok() and \
                    (dl is None or not dl.exceeded()):
                with self._lock:
                    self._hedges += 1
                self._m_issued.inc()
                self._m_wait.update(delay_s * 1000.0)
                hedge = pool.submit(self._run_recorded, op_class, fn)
                futs.append(hedge)
        # phase 2: first SUCCESS wins, bounded by the deadline
        pending = set(futs)
        last_err: Optional[BaseException] = None
        while pending:
            timeout = None if dl is None else dl.remaining_s()
            done, not_done = cf.wait(pending, timeout=timeout,
                                     return_when=cf.FIRST_COMPLETED)
            if not done:
                # the deadline ran out with the op(s) still HUNG in
                # flight: abandon them (their threads drain in the
                # background, results discarded)
                self._m_abandoned.inc(len(not_done))
                raise DeadlineExceededError(
                    f"{what}: deadline exceeded with "
                    f"{len(not_done)} store call(s) still in flight "
                    f"({self.name})")
            pending = not_done
            for f in done:
                err = f.exception()
                if err is None:
                    if hedge is not None and f is hedge:
                        self._m_won.inc()
                    if pending:
                        self._m_abandoned.inc(len(pending))
                    # lint-ok: deadline-wait f is in the cf.wait done
                    # set: the result is already available, this call
                    # cannot block
                    return f.result()
                if isinstance(err, FileNotFoundError):
                    # an authoritative answer, not a failure: the key
                    # is absent — raising NOW is the win (waiting on
                    # the straggler, or letting its transient error
                    # overwrite this, would send the retry ladder
                    # after a key known to be missing)
                    if pending:
                        self._m_abandoned.inc(len(pending))
                    raise err
                last_err = err
        assert last_err is not None
        raise last_err

    def _mutate(self, fn: Callable, what: str):
        """Mutations: breaker-gated + breaker-accounted, NEVER hedged,
        never run through the pool — a duplicated conditional PUT
        collides with its own write and a duplicated DELETE can erase
        a successor object.  Deliberately NO deadline check either:
        the commit CAS gate and the durability barriers own write
        abort semantics, and the commit's deadline-abort CLEANUP runs
        exactly when the deadline is already spent — a check here
        would turn every one of its deletes into a silent no-op and
        orphan the aborted attempt's manifests."""
        self._breaker_gate(what)
        op_class = what.split(" ", 1)[0]
        return self._run_recorded(op_class, fn)

    def close(self):
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)

    # -- ObjectStoreBackend --------------------------------------------------

    def put(self, key: str, data: bytes, if_none_match: bool = False):
        return self._mutate(
            lambda: self.inner.put(key, data,
                                   if_none_match=if_none_match),
            f"put {key}")

    def get(self, key: str, offset: int = 0,
            length: Optional[int] = None) -> bytes:
        op_class = "range" if (offset or length is not None) else "get"
        return self._read(op_class,
                          lambda: self.inner.get(key, offset, length),
                          f"get {key}")

    def head(self, key: str) -> Optional[int]:
        return self._read("head", lambda: self.inner.head(key),
                          f"head {key}")

    def list(self, prefix: str) -> List[Tuple[str, int]]:
        return self._read("list", lambda: self.inner.list(prefix),
                          f"list {prefix}")

    def delete(self, key: str) -> bool:
        return self._mutate(lambda: self.inner.delete(key),
                            f"delete {key}")


# -- table wiring ------------------------------------------------------------

_SHARED_LOCK = threading.Lock()
_SHARED_RESILIENT: "object" = None      # WeakKeyDictionary, lazy
_NAME_SEQ = [0]


def _shared_resilient(store: ObjectStoreBackend, options
                      ) -> ResilientObjectStoreBackend:
    """One resilient wrapper per physical store per process: every
    table.copy() / serving request over the same backend shares one
    breaker and one latency model (first configuration wins, like
    shared_disk_tier).  The memo is weak on BOTH ends: the value is a
    weakref because the wrapper strongly references its key
    (wrapper.inner is the store), so a strong value would pin the key
    alive forever and the entry — with its breaker gauge series and
    lazily-built hedge pool — could never be collected after the last
    table over that backend dies."""
    global _SHARED_RESILIENT
    import weakref

    from paimon_tpu.options import CoreOptions
    with _SHARED_LOCK:
        if _SHARED_RESILIENT is None:
            _SHARED_RESILIENT = weakref.WeakKeyDictionary()
        ref = _SHARED_RESILIENT.get(store)
        existing = ref() if ref is not None else None
        if existing is not None:
            return existing
        _NAME_SEQ[0] += 1
        name = f"store-{_NAME_SEQ[0]}"
        breaker = None
        if options.get(CoreOptions.STORE_BREAKER_ENABLED):
            breaker = CircuitBreaker(
                name,
                failure_threshold=options.get(
                    CoreOptions.STORE_BREAKER_FAILURE_THRESHOLD),
                error_rate=options.get(
                    CoreOptions.STORE_BREAKER_ERROR_RATE),
                window=options.get(CoreOptions.STORE_BREAKER_WINDOW),
                open_ms=options.get(CoreOptions.STORE_BREAKER_OPEN_MS),
                half_open_probes=options.get(
                    CoreOptions.STORE_BREAKER_HALF_OPEN_PROBES))
        wrapped = ResilientObjectStoreBackend(
            store, name=name, breaker=breaker,
            hedge_enabled=options.get(CoreOptions.READ_HEDGE_ENABLED),
            hedge_quantile=options.get(CoreOptions.READ_HEDGE_QUANTILE),
            hedge_min_delay_ms=options.get(
                CoreOptions.READ_HEDGE_MIN_DELAY),
            hedge_max_ratio=options.get(
                CoreOptions.READ_HEDGE_MAX_RATIO))
        if breaker is not None:
            # registry gauges are immortal: when the last table over
            # this backend dies, reset its breaker_state series to
            # closed so a breaker that died OPEN cannot render a
            # phantom open circuit on /metrics forever (healthz
            # prunes dead backends; the scrape endpoint cannot)
            weakref.finalize(wrapped, breaker._g_state.set, 0)
        _SHARED_RESILIENT[store] = weakref.ref(wrapped)
        return wrapped


def maybe_wrap_resilience(file_io, options):
    """Thread the resilient backend under an object-store FileIO when
    `store.breaker.enabled` / `read.hedge.enabled` ask for it — the
    one construction point (table/table.py FileStoreTable.__init__,
    BEFORE the caching wrap so cache hits never pay breaker/hedge
    accounting).  A RetryingObjectStoreBackend stays OUTERMOST (same
    parameters, rebuilt over the resilient layer) so its ladder sees
    CircuitOpenError fail-fasts and every attempt it makes is
    individually breaker-accounted and latency-sampled."""
    from paimon_tpu.options import CoreOptions
    if options is None:
        return file_io
    if not (options.get(CoreOptions.STORE_BREAKER_ENABLED) or
            options.get(CoreOptions.READ_HEDGE_ENABLED)):
        return file_io
    from paimon_tpu.fs.caching import CachingFileIO
    if isinstance(file_io, CachingFileIO):
        # table.copy() on a cache-wrapped table hands us the wrapper:
        # thread resilience UNDER the cache (rewrap the inner FileIO,
        # keep the SAME cache state/tier) instead of silently
        # ignoring the breaker/hedge options
        inner = maybe_wrap_resilience(file_io.inner, options)
        if inner is file_io.inner:
            return file_io
        return CachingFileIO(inner,
                             capacity_bytes=file_io.state.capacity,
                             range_cache_bytes=file_io.state
                             .range_capacity,
                             state=file_io.state)
    if not isinstance(file_io, ObjectStoreFileIO):
        return file_io
    backend = file_io.backend
    retry_kw = None
    if isinstance(backend, RetryingObjectStoreBackend):
        retry_kw = dict(max_attempts=backend.max_attempts,
                        backoff_s=backend.backoff_s,
                        backoff_cap_s=backend.backoff_cap_s,
                        max_elapsed_s=backend.max_elapsed_s,
                        rng=backend._rng)
        backend = backend.inner
    if isinstance(backend, ResilientObjectStoreBackend):
        return file_io                 # already wired (table.copy())
    wrapped = _shared_resilient(backend, options)
    if retry_kw is not None:
        wrapped = RetryingObjectStoreBackend(wrapped, **retry_kw)
    return ObjectStoreFileIO(wrapped, scheme=file_io.scheme)
