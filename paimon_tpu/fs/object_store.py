"""Object-store FileIO: commit semantics without rename(2).

reference: paimon's object-store FileIOs (paimon-filesystems/ s3/oss/
gcs modules) differ from local filesystems in exactly the ways modeled
here — no atomic rename, flat keys instead of directories, LIST by
prefix, and conditional writes (If-None-Match: * / ETag preconditions)
as the only CAS primitive.  `ObjectStoreFileIO` adapts any
`ObjectStoreBackend` to the FileIO SPI:

- `try_to_write_atomic` = conditional PUT (the snapshot commit CAS) —
  no staging file + link(2) like LocalFileIO
- two-phase streams stage under a hidden key and publish with a
  conditional server-side copy, then delete the stage
- `mkdirs` is a no-op (keys are flat); directory listing derives from
  key prefixes

`LocalObjectStoreBackend` emulates a bucket on the local disk with the
same constraints (everything goes through put/get/list/head/delete +
preconditions, never rename), so the object-store commit path is fully
exercised in tests; a real S3/GCS backend only has to implement the
five backend calls.  Network egress is unavailable in this
environment, so no remote backend ships yet.
"""

from __future__ import annotations

import os
import threading
import uuid
from typing import Dict, List, Optional, Tuple

from paimon_tpu.fs.fileio import (
    FileIO, FileStatus, TwoPhaseCommitter, TwoPhaseOutputStream,
)

__all__ = ["ObjectStoreBackend", "LocalObjectStoreBackend",
           "ObjectStoreFileIO", "FlakyObjectStoreBackend",
           "LatencyInjectingObjectStoreBackend",
           "RetryingObjectStoreBackend", "TransientStoreError",
           "CircuitOpenError"]


class PreconditionFailed(Exception):
    pass


class TransientStoreError(Exception):
    """A retryable server error (HTTP 503 / SlowDown / 500)."""


class CircuitOpenError(TransientStoreError):
    """The per-backend circuit breaker (fs/resilience.py) is OPEN: the
    store is known-sick and the call failed fast WITHOUT touching it.
    Subclasses TransientStoreError so the fault taxonomy still files
    it as transient, but `RetryingObjectStoreBackend` re-raises it
    immediately — retrying against an open circuit would just sleep
    through the breaker's whole point (fail fast, shed load)."""


class ObjectStoreBackend:
    """Five calls every real object store offers."""

    def put(self, key: str, data: bytes,
            if_none_match: bool = False) -> None:
        """if_none_match=True -> fail with PreconditionFailed when the
        key already exists (S3 If-None-Match: *, GCS
        x-goog-if-generation-match: 0)."""
        raise NotImplementedError

    def get(self, key: str, offset: int = 0,
            length: Optional[int] = None) -> bytes:
        raise NotImplementedError

    def head(self, key: str) -> Optional[int]:
        """Size in bytes, or None when absent."""
        raise NotImplementedError

    def list(self, prefix: str) -> List[Tuple[str, int]]:
        """[(key, size)] under prefix."""
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        raise NotImplementedError


class LocalObjectStoreBackend(ObjectStoreBackend):
    """A 'bucket' on local disk with object-store semantics ONLY: flat
    keys (encoded to one directory level), no rename anywhere, and
    conditional PUT serialized by a lock (real stores serialize
    server-side)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        # staging lives OUTSIDE the flat key namespace so in-flight or
        # orphaned temp writes can never appear in listings
        self._staging = os.path.join(root, ".staging")
        os.makedirs(self._staging, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, key: str) -> str:
        # flat namespace: escape separators so no directories exist
        return os.path.join(self.root, key.replace("/", "%2F"))

    def put(self, key: str, data: bytes,
            if_none_match: bool = False) -> None:
        with self._lock:
            p = self._path(key)
            if if_none_match and os.path.exists(p):
                raise PreconditionFailed(key)
            tmp = os.path.join(self._staging, uuid.uuid4().hex)
            with open(tmp, "wb") as f:
                f.write(data)
            # emulates the server's atomic object swap (not a FileIO
            # rename: this is inside the backend, like the store's own
            # internal commit)
            os.replace(tmp, p)

    def get(self, key: str, offset: int = 0,
            length: Optional[int] = None) -> bytes:
        p = self._path(key)
        if not os.path.exists(p):
            raise FileNotFoundError(key)
        with open(p, "rb") as f:
            f.seek(offset)
            return f.read(length if length is not None else -1)

    def head(self, key: str) -> Optional[int]:
        p = self._path(key)
        return os.path.getsize(p) if os.path.exists(p) else None

    def list(self, prefix: str) -> List[Tuple[str, int]]:
        enc = prefix.replace("/", "%2F")
        out = []
        for name in os.listdir(self.root):
            p = os.path.join(self.root, name)
            if name.startswith(enc) and os.path.isfile(p):
                out.append((name.replace("%2F", "/"),
                            os.path.getsize(p)))
        return sorted(out)

    def delete(self, key: str) -> bool:
        p = self._path(key)
        if os.path.exists(p):
            os.remove(p)
            return True
        return False


class FlakyObjectStoreBackend(ObjectStoreBackend):
    """Fault-injecting wrapper modeling the two realities of a real
    store the plain emulation hides (VERDICT r3 weak #8):

    - **503 storms**: every call fails with TransientStoreError with
      probability `fail_rate` BEFORE taking effect, and mutations also
      fail with probability `ambiguous_rate` AFTER taking effect — the
      genuinely nasty case where the server applied the PUT but the
      client saw an error (S3 "SlowDown" mid-response), so a naive
      retry of a conditional PUT collides with its own write.
    - **eventually-consistent LIST**: a freshly PUT key stays invisible
      to `list()` for the next `list_lag` list calls (read-after-write
      on get/head stays strong — the pre-2020-S3 / OSS model).

    Deterministic under `seed` so failing schedules replay."""

    def __init__(self, inner: ObjectStoreBackend, seed: int = 0,
                 fail_rate: float = 0.0, ambiguous_rate: float = 0.0,
                 list_lag: int = 0):
        import random
        self.inner = inner
        self.rng = random.Random(seed)
        self.fail_rate = fail_rate
        self.ambiguous_rate = ambiguous_rate
        self.list_lag = list_lag
        self._list_calls = 0
        self._visible_after: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.stats = {"injected": 0, "ambiguous": 0, "lagged": 0}

    def _maybe_fail(self, op: str):
        with self._lock:
            if self.rng.random() < self.fail_rate:
                self.stats["injected"] += 1
                raise TransientStoreError(f"503 on {op}")

    def put(self, key: str, data: bytes, if_none_match: bool = False):
        self._maybe_fail(f"put {key}")
        # LIST lag applies only to keys that did not exist before: real
        # eventually-consistent stores may show a stale version of an
        # overwritten key in listings, but never its absence
        new_key = self.list_lag and self.inner.head(key) is None
        self.inner.put(key, data, if_none_match=if_none_match)
        with self._lock:
            if new_key:
                self._visible_after[key] = \
                    self._list_calls + self.list_lag
            if self.rng.random() < self.ambiguous_rate:
                self.stats["ambiguous"] += 1
                raise TransientStoreError(f"503 AFTER put {key}")

    def get(self, key: str, offset: int = 0,
            length: Optional[int] = None) -> bytes:
        self._maybe_fail(f"get {key}")
        return self.inner.get(key, offset, length)

    def head(self, key: str) -> Optional[int]:
        self._maybe_fail(f"head {key}")
        return self.inner.head(key)

    def list(self, prefix: str) -> List[Tuple[str, int]]:
        self._maybe_fail(f"list {prefix}")
        with self._lock:
            self._list_calls += 1
            calls = self._list_calls
            out = []
            for key, size in self.inner.list(prefix):
                if self._visible_after.get(key, 0) > calls:
                    self.stats["lagged"] += 1
                    continue
                out.append((key, size))
        return out

    def delete(self, key: str) -> bool:
        self._maybe_fail(f"delete {key}")
        ok = self.inner.delete(key)
        with self._lock:
            if self.rng.random() < self.ambiguous_rate:
                self.stats["ambiguous"] += 1
                raise TransientStoreError(f"503 AFTER delete {key}")
        return ok


class LatencyInjectingObjectStoreBackend(ObjectStoreBackend):
    """Latency-injecting wrapper: every backend call sleeps a
    configurable base + seeded jitter first, so benches and tests can
    model a REAL object store's per-request round trip (tens of ms)
    instead of local-disk timings — the difference the host-SSD cache
    tier and staged uploads exist to hide (benchmarks/tier_bench.py).

    `base_ms` is either one number for every op or a per-op dict keyed
    by 'put'/'get'/'head'/'list'/'delete' (missing ops pay 0, so e.g.
    only PUTs can be made slow).  Composable with
    FlakyObjectStoreBackend in either order: Flaky(Latency(store))
    charges the round trip before the 503 fires, like a real timeout.
    Thread-safe: the seeded rng is locked, sleeps happen outside.

    Chaos extensions (the tail-tolerance PR's injection surface —
    benchmarks/chaos_bench.py, tests/test_resilience.py):

    - **heavy tail**: with probability `tail_rate`, ops in `tail_ops`
      pay `tail_multiplier` x base instead of base — the "1% of GETs
      20x slow" shape hedged reads exist to beat.  When `pareto_alpha`
      is set the multiplier is drawn from a Pareto(alpha) distribution
      instead (a genuinely heavy tail: p99 >> p95 >> median, like real
      object-store stragglers).
    - **stuck requests**: with probability `stuck_rate`, the op HANGS
      for `stuck_ms` before proceeding — not an error, a stall.  No
      retry ladder fires; only a deadline-bounded wait (the resilient
      backend abandons the in-flight call) gets the caller out.

    The sleep is injectable (`sleep=`) so state-machine tests can run
    on a virtual clock."""

    def __init__(self, inner: ObjectStoreBackend, base_ms=10.0,
                 jitter_ms: float = 0.0, seed: int = 0,
                 tail_rate: float = 0.0, tail_multiplier: float = 20.0,
                 pareto_alpha: Optional[float] = None,
                 tail_ops: Tuple[str, ...] = ("get",),
                 stuck_rate: float = 0.0, stuck_ms: float = 0.0,
                 sleep=None):
        import random
        import time
        self.inner = inner
        self.base_ms = base_ms
        self.jitter_ms = jitter_ms
        self.tail_rate = tail_rate
        self.tail_multiplier = tail_multiplier
        self.pareto_alpha = pareto_alpha
        self.tail_ops = frozenset(tail_ops)
        self.stuck_rate = stuck_rate
        self.stuck_ms = stuck_ms
        self._sleep = sleep if sleep is not None else time.sleep
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.stats = {"delayed_calls": 0, "delay_ms_total": 0.0,
                      "tail_hits": 0, "stuck_hits": 0}

    def _delay(self, op: str):
        base = self.base_ms.get(op, 0.0) \
            if isinstance(self.base_ms, dict) else self.base_ms
        with self._lock:
            wait = base + (self._rng.random() * self.jitter_ms
                           if self.jitter_ms else 0.0)
            if op in self.tail_ops and self.tail_rate and \
                    self._rng.random() < self.tail_rate:
                mult = self._rng.paretovariate(self.pareto_alpha) \
                    if self.pareto_alpha is not None \
                    else self.tail_multiplier
                wait = base * mult
                self.stats["tail_hits"] += 1
            if self.stuck_rate and self._rng.random() < self.stuck_rate:
                wait += self.stuck_ms
                self.stats["stuck_hits"] += 1
            self.stats["delayed_calls"] += 1
            self.stats["delay_ms_total"] += wait
        if wait > 0:
            self._sleep(wait / 1000.0)

    def put(self, key: str, data: bytes, if_none_match: bool = False):
        self._delay("put")
        return self.inner.put(key, data, if_none_match=if_none_match)

    def get(self, key: str, offset: int = 0,
            length: Optional[int] = None) -> bytes:
        self._delay("get")
        return self.inner.get(key, offset, length)

    def head(self, key: str) -> Optional[int]:
        self._delay("head")
        return self.inner.head(key)

    def list(self, prefix: str) -> List[Tuple[str, int]]:
        self._delay("list")
        return self.inner.list(prefix)

    def delete(self, key: str) -> bool:
        self._delay("delete")
        return self.inner.delete(key)


class RetryingObjectStoreBackend(ObjectStoreBackend):
    """Client-side retry layer every real object-store FileIO carries
    (reference: hadoop-aws retry policies under the s3/oss FileIOs).
    Retries TransientStoreError with backoff; the ambiguous
    conditional-PUT case (error after effect) is resolved by read-back:
    if a retried If-None-Match PUT hits PreconditionFailed but the
    stored bytes equal ours, OUR write landed — report success.
    Snapshot JSON embeds commitUser uuid + millis, so byte-equality
    identifies the writer."""

    def __init__(self, inner: ObjectStoreBackend, max_attempts: int = 6,
                 backoff_s: float = 0.0,
                 backoff_cap_s: Optional[float] = None,
                 max_elapsed_s: Optional[float] = None,
                 rng=None):
        self.inner = inner
        self.max_attempts = max_attempts
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self.max_elapsed_s = max_elapsed_s
        self._rng = rng

    def _backoff(self):
        """Fresh capped decorrelated-jitter schedule per operation
        (utils/backoff.py — shared with FileStoreCommit's CAS retry
        wait and the mesh engine's per-bucket ladder)."""
        from paimon_tpu.utils.backoff import Backoff
        return Backoff(
            self.backoff_s * 1000.0,
            None if self.backoff_cap_s is None
            else self.backoff_cap_s * 1000.0,
            None if self.max_elapsed_s is None
            else self.max_elapsed_s * 1000.0,
            rng=self._rng)

    def _retry(self, fn, op: str):
        last = None
        backoff = self._backoff()
        for attempt in range(self.max_attempts):
            try:
                return fn()
            except CircuitOpenError:
                # the breaker below us says the store is sick: fail
                # fast instead of sleeping the whole ladder onto it —
                # the retry ladder consults breaker state BEFORE any
                # backoff wait (fs/resilience.py)
                raise
            except TransientStoreError as e:
                last = e
                if attempt + 1 >= self.max_attempts:
                    break               # terminal: no wait nobody uses
                if not backoff.pause():
                    raise TransientStoreError(
                        f"{op}: retry budget "
                        f"({self.max_elapsed_s}s) exhausted") from last
        raise TransientStoreError(
            f"{op}: {self.max_attempts} attempts exhausted") from last

    def put(self, key: str, data: bytes, if_none_match: bool = False):
        ambiguous = False
        last = None
        backoff = self._backoff()
        for attempt in range(self.max_attempts):
            try:
                return self.inner.put(key, data,
                                      if_none_match=if_none_match)
            except CircuitOpenError:
                raise               # fail fast: breaker open (see _retry)
            except TransientStoreError as e:
                last = e
                ambiguous = True       # effect may or may not be applied
                if attempt + 1 >= self.max_attempts:
                    break              # terminal: no wait nobody uses
                if not backoff.pause():
                    raise TransientStoreError(
                        f"put {key}: retry budget "
                        f"({self.max_elapsed_s}s) exhausted") from last
            except PreconditionFailed:
                if if_none_match and ambiguous:
                    # ambiguity resolution by read-back: valid ONLY
                    # because try_to_write_atomic payloads are
                    # writer-unique (FileIO contract) — snapshot JSON
                    # embeds commitUser uuid, lock files a random token
                    try:
                        if self.inner.get(key) == data:
                            return     # our own earlier attempt landed
                    except (FileNotFoundError, TransientStoreError):
                        continue
                raise
        raise TransientStoreError(
            f"put {key}: {self.max_attempts} attempts exhausted") \
            from last

    def get(self, key: str, offset: int = 0,
            length: Optional[int] = None) -> bytes:
        return self._retry(lambda: self.inner.get(key, offset, length),
                           f"get {key}")

    def head(self, key: str) -> Optional[int]:
        return self._retry(lambda: self.inner.head(key), f"head {key}")

    def list(self, prefix: str) -> List[Tuple[str, int]]:
        return self._retry(lambda: self.inner.list(prefix),
                           f"list {prefix}")

    def delete(self, key: str) -> bool:
        # delete is idempotent: a retry after an ambiguous error that
        # already applied just sees False (absent), which is the goal;
        # exhaustion raises like every other op so callers never
        # mistake a still-present key for a completed delete
        return self._retry(lambda: self.inner.delete(key),
                           f"delete {key}")


class ObjectStoreFileIO(FileIO):
    """FileIO over an ObjectStoreBackend (scheme e.g. 'objfs://')."""

    def __init__(self, backend: ObjectStoreBackend,
                 scheme: str = "objfs://"):
        self.backend = backend
        self.scheme = scheme

    def _key(self, path: str) -> str:
        if path.startswith(self.scheme):
            path = path[len(self.scheme):]
        return path.lstrip("/")

    # -- reads ---------------------------------------------------------------
    # every read checks the request deadline BEFORE its round trip: a
    # metadata walk (snapshot probes, manifest chain) is a sequence of
    # store ops with no other blocking wait between them, and on a
    # slow store each op can cost hundreds of ms — without this check
    # a timed-out request would ride the whole chain to completion.
    # The residual grace after a deadline trips is therefore bounded
    # by ONE op's latency (plus hedged ops abandon mid-call,
    # fs/resilience.py).  Writes deliberately have no check: their
    # cancellation points are the commit CAS gate and the durability
    # barriers, which own abort-vs-orphan semantics.

    @staticmethod
    def _check_deadline(what: str):
        from paimon_tpu.utils.deadline import check_deadline
        check_deadline(what)

    def read_bytes(self, path: str) -> bytes:
        self._check_deadline("read")
        return self.backend.get(self._key(path))

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        self._check_deadline("read")
        return self.backend.get(self._key(path), offset, length)

    def read_ranges(self, path, ranges):
        # ranged GETs, one per range (real stores coalesce via HTTP
        # multi-range; the per-call shape is the same)
        key = self._key(path)
        out = []
        for o, ln in ranges:
            self._check_deadline("read")
            out.append(self.backend.get(key, o, ln))
        return out

    def exists(self, path: str) -> bool:
        self._check_deadline("exists")
        key = self._key(path)
        if self.backend.head(key) is not None:
            return True
        return bool(self.backend.list(key.rstrip("/") + "/"))

    def get_file_size(self, path: str) -> int:
        self._check_deadline("size")
        size = self.backend.head(self._key(path))
        if size is None:
            raise FileNotFoundError(path)
        return size

    def list_status(self, path: str) -> List[FileStatus]:
        self._check_deadline("list")
        prefix = self._key(path).rstrip("/") + "/"
        out: Dict[str, FileStatus] = {}
        for key, size in self.backend.list(prefix):
            rest = key[len(prefix):]
            if "/" in rest:               # synthetic directory entry
                child = prefix + rest.split("/", 1)[0]
                out.setdefault(child, FileStatus(
                    f"{self.scheme}{child}", 0, True))
            else:
                out[key] = FileStatus(f"{self.scheme}{key}", size, False)
        return sorted(out.values(), key=lambda s: s.path)

    # -- writes --------------------------------------------------------------

    def write_bytes(self, path: str, data: bytes, overwrite: bool = True):
        key = self._key(path)
        if not overwrite and self.backend.head(key) is not None:
            raise FileExistsError(path)
        self.backend.put(key, data)

    def try_to_write_atomic(self, path: str, data: bytes) -> bool:
        """THE commit CAS on object stores: conditional PUT, no rename
        (reference object-store SnapshotCommit implementations)."""
        try:
            self.backend.put(self._key(path), data, if_none_match=True)
            return True
        except PreconditionFailed:
            return False

    def new_two_phase_stream(self, path: str) -> TwoPhaseOutputStream:
        io_, final = self, path
        stage = (f"{path}.{uuid.uuid4().hex}.staging")
        parts: List[bytes] = []

        class S(TwoPhaseOutputStream):
            def write(self, data: bytes):
                parts.append(bytes(data))

            def close_for_commit(self) -> TwoPhaseCommitter:
                from paimon_tpu.fs.fileio import reraise_with_path
                try:
                    # the part upload: close() is where the staged
                    # bytes actually hit the store, so a failure here
                    # must name the file it was for instead of the
                    # backend's generic error
                    io_.backend.put(io_._key(stage), b"".join(parts))
                except Exception as e:      # noqa: BLE001 — re-typed
                    reraise_with_path(e, final, "upload")

                class C(TwoPhaseCommitter):
                    def commit(self):
                        try:
                            blob = io_.backend.get(io_._key(stage))
                            io_.backend.put(io_._key(final), blob,
                                            if_none_match=True)
                        except PreconditionFailed:
                            io_.backend.delete(io_._key(stage))
                            raise FileExistsError(final)
                        except Exception as e:  # noqa: BLE001 — re-typed
                            reraise_with_path(e, final, "publish")
                        io_.backend.delete(io_._key(stage))

                    def discard(self):
                        io_.backend.delete(io_._key(stage))

                return C()

        return S()

    def mkdirs(self, path: str) -> bool:
        return True                        # flat keys: nothing to do

    def delete(self, path: str, recursive: bool = False) -> bool:
        key = self._key(path)
        ok = False
        if self.backend.head(key) is not None:
            ok = self.backend.delete(key)
        if recursive:
            # a key may exist BOTH as an object and as a prefix: drop
            # every child too
            for k, _ in self.backend.list(key.rstrip("/") + "/"):
                ok = self.backend.delete(k) or ok
        return ok

    def rename(self, src: str, dst: str) -> bool:
        # object stores have no rename: copy + delete per key
        # (non-atomic, which is exactly why commits use
        # try_to_write_atomic). Matches the FileIO contract: False when
        # src is absent or dst already exists; prefix (directory)
        # renames move every child key.
        skey, dkey = self._key(src), self._key(dst)
        if self.backend.head(dkey) is not None or                 self.backend.list(dkey.rstrip("/") + "/"):
            return False
        moved = False
        if self.backend.head(skey) is not None:
            self.backend.put(dkey, self.backend.get(skey))
            self.backend.delete(skey)
            moved = True
        prefix = skey.rstrip("/") + "/"
        for k, _ in self.backend.list(prefix):
            self.backend.put(dkey.rstrip("/") + "/" + k[len(prefix):],
                             self.backend.get(k))
            self.backend.delete(k)
            moved = True
        return moved

    def is_object_store(self) -> bool:
        return True
