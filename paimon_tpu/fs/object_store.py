"""Object-store FileIO: commit semantics without rename(2).

reference: paimon's object-store FileIOs (paimon-filesystems/ s3/oss/
gcs modules) differ from local filesystems in exactly the ways modeled
here — no atomic rename, flat keys instead of directories, LIST by
prefix, and conditional writes (If-None-Match: * / ETag preconditions)
as the only CAS primitive.  `ObjectStoreFileIO` adapts any
`ObjectStoreBackend` to the FileIO SPI:

- `try_to_write_atomic` = conditional PUT (the snapshot commit CAS) —
  no staging file + link(2) like LocalFileIO
- two-phase streams stage under a hidden key and publish with a
  conditional server-side copy, then delete the stage
- `mkdirs` is a no-op (keys are flat); directory listing derives from
  key prefixes

`LocalObjectStoreBackend` emulates a bucket on the local disk with the
same constraints (everything goes through put/get/list/head/delete +
preconditions, never rename), so the object-store commit path is fully
exercised in tests; a real S3/GCS backend only has to implement the
five backend calls.  Network egress is unavailable in this
environment, so no remote backend ships yet.
"""

from __future__ import annotations

import os
import threading
import uuid
from typing import Dict, List, Optional, Tuple

from paimon_tpu.fs.fileio import (
    FileIO, FileStatus, TwoPhaseCommitter, TwoPhaseOutputStream,
)

__all__ = ["ObjectStoreBackend", "LocalObjectStoreBackend",
           "ObjectStoreFileIO"]


class PreconditionFailed(Exception):
    pass


class ObjectStoreBackend:
    """Five calls every real object store offers."""

    def put(self, key: str, data: bytes,
            if_none_match: bool = False) -> None:
        """if_none_match=True -> fail with PreconditionFailed when the
        key already exists (S3 If-None-Match: *, GCS
        x-goog-if-generation-match: 0)."""
        raise NotImplementedError

    def get(self, key: str, offset: int = 0,
            length: Optional[int] = None) -> bytes:
        raise NotImplementedError

    def head(self, key: str) -> Optional[int]:
        """Size in bytes, or None when absent."""
        raise NotImplementedError

    def list(self, prefix: str) -> List[Tuple[str, int]]:
        """[(key, size)] under prefix."""
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        raise NotImplementedError


class LocalObjectStoreBackend(ObjectStoreBackend):
    """A 'bucket' on local disk with object-store semantics ONLY: flat
    keys (encoded to one directory level), no rename anywhere, and
    conditional PUT serialized by a lock (real stores serialize
    server-side)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        # staging lives OUTSIDE the flat key namespace so in-flight or
        # orphaned temp writes can never appear in listings
        self._staging = os.path.join(root, ".staging")
        os.makedirs(self._staging, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, key: str) -> str:
        # flat namespace: escape separators so no directories exist
        return os.path.join(self.root, key.replace("/", "%2F"))

    def put(self, key: str, data: bytes,
            if_none_match: bool = False) -> None:
        with self._lock:
            p = self._path(key)
            if if_none_match and os.path.exists(p):
                raise PreconditionFailed(key)
            tmp = os.path.join(self._staging, uuid.uuid4().hex)
            with open(tmp, "wb") as f:
                f.write(data)
            # emulates the server's atomic object swap (not a FileIO
            # rename: this is inside the backend, like the store's own
            # internal commit)
            os.replace(tmp, p)

    def get(self, key: str, offset: int = 0,
            length: Optional[int] = None) -> bytes:
        p = self._path(key)
        if not os.path.exists(p):
            raise FileNotFoundError(key)
        with open(p, "rb") as f:
            f.seek(offset)
            return f.read(length if length is not None else -1)

    def head(self, key: str) -> Optional[int]:
        p = self._path(key)
        return os.path.getsize(p) if os.path.exists(p) else None

    def list(self, prefix: str) -> List[Tuple[str, int]]:
        enc = prefix.replace("/", "%2F")
        out = []
        for name in os.listdir(self.root):
            p = os.path.join(self.root, name)
            if name.startswith(enc) and os.path.isfile(p):
                out.append((name.replace("%2F", "/"),
                            os.path.getsize(p)))
        return sorted(out)

    def delete(self, key: str) -> bool:
        p = self._path(key)
        if os.path.exists(p):
            os.remove(p)
            return True
        return False


class ObjectStoreFileIO(FileIO):
    """FileIO over an ObjectStoreBackend (scheme e.g. 'objfs://')."""

    def __init__(self, backend: ObjectStoreBackend,
                 scheme: str = "objfs://"):
        self.backend = backend
        self.scheme = scheme

    def _key(self, path: str) -> str:
        if path.startswith(self.scheme):
            path = path[len(self.scheme):]
        return path.lstrip("/")

    # -- reads ---------------------------------------------------------------

    def read_bytes(self, path: str) -> bytes:
        return self.backend.get(self._key(path))

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        return self.backend.get(self._key(path), offset, length)

    def read_ranges(self, path, ranges):
        # ranged GETs, one per range (real stores coalesce via HTTP
        # multi-range; the per-call shape is the same)
        key = self._key(path)
        return [self.backend.get(key, o, ln) for o, ln in ranges]

    def exists(self, path: str) -> bool:
        key = self._key(path)
        if self.backend.head(key) is not None:
            return True
        return bool(self.backend.list(key.rstrip("/") + "/"))

    def get_file_size(self, path: str) -> int:
        size = self.backend.head(self._key(path))
        if size is None:
            raise FileNotFoundError(path)
        return size

    def list_status(self, path: str) -> List[FileStatus]:
        prefix = self._key(path).rstrip("/") + "/"
        out: Dict[str, FileStatus] = {}
        for key, size in self.backend.list(prefix):
            rest = key[len(prefix):]
            if "/" in rest:               # synthetic directory entry
                child = prefix + rest.split("/", 1)[0]
                out.setdefault(child, FileStatus(
                    f"{self.scheme}{child}", 0, True))
            else:
                out[key] = FileStatus(f"{self.scheme}{key}", size, False)
        return sorted(out.values(), key=lambda s: s.path)

    # -- writes --------------------------------------------------------------

    def write_bytes(self, path: str, data: bytes, overwrite: bool = True):
        key = self._key(path)
        if not overwrite and self.backend.head(key) is not None:
            raise FileExistsError(path)
        self.backend.put(key, data)

    def try_to_write_atomic(self, path: str, data: bytes) -> bool:
        """THE commit CAS on object stores: conditional PUT, no rename
        (reference object-store SnapshotCommit implementations)."""
        try:
            self.backend.put(self._key(path), data, if_none_match=True)
            return True
        except PreconditionFailed:
            return False

    def new_two_phase_stream(self, path: str) -> TwoPhaseOutputStream:
        io_, final = self, path
        stage = (f"{path}.{uuid.uuid4().hex}.staging")
        parts: List[bytes] = []

        class S(TwoPhaseOutputStream):
            def write(self, data: bytes):
                parts.append(bytes(data))

            def close_for_commit(self) -> TwoPhaseCommitter:
                io_.backend.put(io_._key(stage), b"".join(parts))

                class C(TwoPhaseCommitter):
                    def commit(self):
                        blob = io_.backend.get(io_._key(stage))
                        try:
                            io_.backend.put(io_._key(final), blob,
                                            if_none_match=True)
                        except PreconditionFailed:
                            io_.backend.delete(io_._key(stage))
                            raise FileExistsError(final)
                        io_.backend.delete(io_._key(stage))

                    def discard(self):
                        io_.backend.delete(io_._key(stage))

                return C()

        return S()

    def mkdirs(self, path: str) -> bool:
        return True                        # flat keys: nothing to do

    def delete(self, path: str, recursive: bool = False) -> bool:
        key = self._key(path)
        ok = False
        if self.backend.head(key) is not None:
            ok = self.backend.delete(key)
        if recursive:
            # a key may exist BOTH as an object and as a prefix: drop
            # every child too
            for k, _ in self.backend.list(key.rstrip("/") + "/"):
                ok = self.backend.delete(k) or ok
        return ok

    def rename(self, src: str, dst: str) -> bool:
        # object stores have no rename: copy + delete per key
        # (non-atomic, which is exactly why commits use
        # try_to_write_atomic). Matches the FileIO contract: False when
        # src is absent or dst already exists; prefix (directory)
        # renames move every child key.
        skey, dkey = self._key(src), self._key(dst)
        if self.backend.head(dkey) is not None or                 self.backend.list(dkey.rstrip("/") + "/"):
            return False
        moved = False
        if self.backend.head(skey) is not None:
            self.backend.put(dkey, self.backend.get(skey))
            self.backend.delete(skey)
            moved = True
        prefix = skey.rstrip("/") + "/"
        for k, _ in self.backend.list(prefix):
            self.backend.put(dkey.rstrip("/") + "/" + k[len(prefix):],
                             self.backend.get(k))
            self.backend.delete(k)
            moved = True
        return moved

    def is_object_store(self) -> bool:
        return True
