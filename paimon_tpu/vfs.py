"""VFS: a filesystem view over a catalog's warehouse.

reference: paimon-vfs (Pvfs / PaimonVirtualFileSystem: a Hadoop
FileSystem exposing catalog tables as file trees through the REST
catalog). Paths: `/<db>/<table>/<relative file>`; table internals
(snapshot/, manifest/, bucket-*/...) are readable for inspection and
object/format tables are fully browsable.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from paimon_tpu.fs import get_file_io, safe_join

__all__ = ["Vfs"]


class VfsFileStatus:
    def __init__(self, path: str, size: int, is_dir: bool):
        self.path = path
        self.size = size
        self.is_dir = is_dir

    def __repr__(self):
        kind = "dir" if self.is_dir else "file"
        return f"VfsFileStatus({self.path!r}, {self.size}, {kind})"


class Vfs:
    def __init__(self, catalog):
        self.catalog = catalog

    def _resolve(self, path: str) -> Tuple[Optional[str], Optional[str],
                                           str]:
        parts = [p for p in path.split("/") if p]
        db = parts[0] if parts else None
        table = parts[1] if len(parts) > 1 else None
        rest = "/".join(parts[2:])
        return db, table, rest

    def _table_root(self, db: str, table: str) -> str:
        # FileSystemCatalog exposes table_path; REST clients resolve the
        # path through the server (reference Pvfs works over REST too)
        if hasattr(self.catalog, "table_path"):
            return self.catalog.table_path(f"{db}.{table}")
        return self.catalog.get_table(f"{db}.{table}").path

    def _file_io(self, root: str):
        return getattr(self.catalog, "file_io", None) or get_file_io(root)

    def listdir(self, path: str = "/") -> List[VfsFileStatus]:
        db, table, rest = self._resolve(path)
        if db is None:
            return [VfsFileStatus(f"/{d}", 0, True)
                    for d in self.catalog.list_databases()]
        if table is None:
            return [VfsFileStatus(f"/{db}/{t}", 0, True)
                    for t in self.catalog.list_tables(db)]
        root = self._table_root(db, table)
        target = safe_join(root, rest) if rest else root
        out = []
        for st in self._file_io(root).list_status(target):
            rel = st.path[len(root) + 1:]
            out.append(VfsFileStatus(f"/{db}/{table}/{rel}", st.size,
                                     st.is_dir))
        return out

    def open(self, path: str) -> bytes:
        db, table, rest = self._resolve(path)
        if not (db and table and rest):
            raise IsADirectoryError(path)
        root = self._table_root(db, table)
        return self._file_io(root).read_bytes(safe_join(root, rest))

    def exists(self, path: str) -> bool:
        db, table, rest = self._resolve(path)
        if db is None:
            return True
        if table is None:
            return db in self.catalog.list_databases()
        try:
            root = self._table_root(db, table)
        except Exception:
            return False
        target = safe_join(root, rest) if rest else root
        return self._file_io(root).exists(target)

    def size(self, path: str) -> int:
        db, table, rest = self._resolve(path)
        if not (db and table and rest):
            raise IsADirectoryError(path)
        root = self._table_root(db, table)
        return self._file_io(root).get_file_size(safe_join(root, rest))
