"""Variant: semi-structured values in the open binary variant format.

reference: paimon-common/.../data/variant/ (GenericVariant,
GenericVariantBuilder, VariantShreddingWriter, ~5k LoC Java) — the
Spark/Parquet "Variant" encoding: a value is (metadata, value) byte
strings where metadata is a key dictionary and value is a compact typed
tree.  This implementation covers the encoding subset paimon writes
(null/bool/int8-64/double/string/binary/object/array), JSON round-trip,
`$`-path access (variant_get), and columnar SHREDDING: extracting a
typed Arrow column per configured path with per-row residuals, and
re-assembly on read (VariantShreddingWriter / PaimonShreddingUtils).

Layout notes (open variant spec v1):
- metadata: header byte (version=1 | sorted<<4 | (offset_size-1)<<6),
  dict_size, dict_size+1 offsets, key bytes (all ints little-endian,
  offset_size wide).
- value: header byte = basic_type | type_info<<2.
  basic 0 primitive: info 0 null, 1 true, 2 false, 3 i8, 4 i16, 5 i32,
  6 i64, 7 double, 16 long string; basic 1 short string (info=len);
  basic 2 object: info = (offsz-1) | (idsz-1)<<2 | large<<4;
  basic 3 array: info = (offsz-1) | large<<2.
"""

from __future__ import annotations

import json
import re
import struct
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import pyarrow as pa

__all__ = ["Variant", "VariantBuilder", "variant_get", "shred_column",
           "unshred_column", "ShreddingPlan", "variant_arrow_type"]

_VERSION = 1


def _uint(n: int, width: int) -> bytes:
    return int(n).to_bytes(width, "little")


def _read_uint(b: bytes, pos: int, width: int) -> int:
    return int.from_bytes(b[pos:pos + width], "little")


def _min_width(n: int) -> int:
    if n < (1 << 8):
        return 1
    if n < (1 << 16):
        return 2
    if n < (1 << 24):
        return 3
    return 4


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------

class VariantBuilder:
    """Encode a python object (dict/list/str/int/float/bool/None/bytes)
    into (metadata, value)."""

    def __init__(self):
        self._keys: Dict[str, int] = {}

    def _key_id(self, k: str) -> int:
        if k not in self._keys:
            self._keys[k] = len(self._keys)
        return self._keys[k]

    def build(self, obj: Any) -> "Variant":
        value = self._encode(obj)
        keys = [k.encode() for k in self._keys]
        offsz = _min_width(sum(len(k) for k in keys) or 1)
        header = _VERSION | ((offsz - 1) << 6)
        out = [bytes([header]), _uint(len(keys), offsz)]
        off = 0
        offs = [0]
        for k in keys:
            off += len(k)
            offs.append(off)
        out += [_uint(o, offsz) for o in offs]
        out += keys
        return Variant(b"".join(out), value)

    def _encode(self, v: Any) -> bytes:
        if v is None:
            return bytes([0 | (0 << 2)])
        if v is True:
            return bytes([0 | (1 << 2)])
        if v is False:
            return bytes([0 | (2 << 2)])
        if isinstance(v, int):
            for info, fmt, lo, hi in ((3, "<b", -2**7, 2**7),
                                      (4, "<h", -2**15, 2**15),
                                      (5, "<i", -2**31, 2**31),
                                      (6, "<q", -2**63, 2**63)):
                if lo <= v < hi:
                    return bytes([0 | (info << 2)]) + struct.pack(fmt, v)
            raise ValueError(f"int out of int64 range: {v}")
        if isinstance(v, float):
            return bytes([0 | (7 << 2)]) + struct.pack("<d", v)
        if isinstance(v, str):
            raw = v.encode()
            if len(raw) < 64:
                return bytes([1 | (len(raw) << 2)]) + raw
            return bytes([0 | (16 << 2)]) + _uint(len(raw), 4) + raw
        if isinstance(v, (bytes, bytearray)):
            return bytes([0 | (15 << 2)]) + _uint(len(v), 4) + bytes(v)
        if isinstance(v, (list, tuple)):
            items = [self._encode(x) for x in v]
            total = sum(len(i) for i in items)
            offsz = _min_width(total or 1)
            large = len(items) > 255
            info = (offsz - 1) | (int(large) << 2)
            out = [bytes([3 | (info << 2)]),
                   _uint(len(items), 4 if large else 1)]
            off = 0
            offs = [0]
            for i in items:
                off += len(i)
                offs.append(off)
            out += [_uint(o, offsz) for o in offs]
            out += items
            return b"".join(out)
        if isinstance(v, dict):
            # the open variant spec requires object fields sorted by
            # key NAME (readers binary-search on it), not by field id
            fields = [(self._key_id(str(k)), self._encode(val))
                      for k, val in sorted(v.items(),
                                           key=lambda kv: str(kv[0]))]
            total = sum(len(fv) for _, fv in fields)
            offsz = _min_width(total or 1)
            idsz = _min_width(max((fid for fid, _ in fields),
                                  default=0) or 1)
            large = len(fields) > 255
            info = (offsz - 1) | ((idsz - 1) << 2) | (int(large) << 4)
            out = [bytes([2 | (info << 2)]),
                   _uint(len(fields), 4 if large else 1)]
            out += [_uint(fid, idsz) for fid, _ in fields]
            off = 0
            offs = [0]
            for _, fv in fields:
                off += len(fv)
                offs.append(off)
            out += [_uint(o, offsz) for o in offs]
            out += [fv for _, fv in fields]
            return b"".join(out)
        raise TypeError(f"cannot encode {type(v).__name__} as variant")


# ---------------------------------------------------------------------------
# the value
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Variant:
    metadata: bytes
    value: bytes

    # -- construction --------------------------------------------------------
    @staticmethod
    def from_object(obj: Any) -> "Variant":
        return VariantBuilder().build(obj)

    @staticmethod
    def from_json(text: str) -> "Variant":
        return Variant.from_object(json.loads(text))

    # -- metadata ------------------------------------------------------------
    def _dict_keys(self) -> List[str]:
        md = self.metadata
        header = md[0]
        if header & 0x0F != _VERSION:
            raise ValueError("unsupported variant metadata version")
        offsz = ((header >> 6) & 0x3) + 1
        n = _read_uint(md, 1, offsz)
        base = 1 + offsz
        offs = [_read_uint(md, base + i * offsz, offsz)
                for i in range(n + 1)]
        data = base + (n + 1) * offsz
        return [md[data + offs[i]:data + offs[i + 1]].decode()
                for i in range(n)]

    # -- decode --------------------------------------------------------------
    def to_object(self) -> Any:
        keys = self._dict_keys()
        obj, _ = _decode(self.value, 0, keys)
        return obj

    def to_json(self) -> str:
        return json.dumps(self.to_object(), default=_json_default)

    def get(self, path: str):
        """`$`-path access: $.a.b, $['a'], $.arr[0] (reference
        Variant.variantGet / VariantPathSegment)."""
        return _walk(self.to_object(), _parse_path(path))


def _json_default(o):
    if isinstance(o, (bytes, bytearray)):
        import base64
        return base64.b64encode(bytes(o)).decode()
    raise TypeError


def _decode(b: bytes, pos: int, keys: List[str]) -> Tuple[Any, int]:
    header = b[pos]
    basic = header & 0x3
    info = header >> 2
    if basic == 0:                                  # primitive
        p = pos + 1
        if info == 0:
            return None, p
        if info == 1:
            return True, p
        if info == 2:
            return False, p
        if info in (3, 4, 5, 6):
            width = {3: 1, 4: 2, 5: 4, 6: 8}[info]
            fmt = {3: "<b", 4: "<h", 5: "<i", 6: "<q"}[info]
            return struct.unpack_from(fmt, b, p)[0], p + width
        if info == 7:
            return struct.unpack_from("<d", b, p)[0], p + 8
        if info == 15:                              # binary
            ln = _read_uint(b, p, 4)
            return b[p + 4:p + 4 + ln], p + 4 + ln
        if info == 16:                              # long string
            ln = _read_uint(b, p, 4)
            return b[p + 4:p + 4 + ln].decode(), p + 4 + ln
        raise ValueError(f"unsupported variant primitive {info}")
    if basic == 1:                                  # short string
        ln = info
        return b[pos + 1:pos + 1 + ln].decode(), pos + 1 + ln
    if basic == 2:                                  # object
        offsz = (info & 0x3) + 1
        idsz = ((info >> 2) & 0x3) + 1
        large = (info >> 4) & 0x1
        p = pos + 1
        n = _read_uint(b, p, 4 if large else 1)
        p += 4 if large else 1
        fids = [_read_uint(b, p + i * idsz, idsz) for i in range(n)]
        p += n * idsz
        offs = [_read_uint(b, p + i * offsz, offsz)
                for i in range(n + 1)]
        p += (n + 1) * offsz
        out = {}
        for i in range(n):
            v, _ = _decode(b, p + offs[i], keys)
            out[keys[fids[i]]] = v
        return out, p + offs[n]
    # basic == 3: array
    offsz = (info & 0x3) + 1
    large = (info >> 2) & 0x1
    p = pos + 1
    n = _read_uint(b, p, 4 if large else 1)
    p += 4 if large else 1
    offs = [_read_uint(b, p + i * offsz, offsz) for i in range(n + 1)]
    p += (n + 1) * offsz
    out = []
    for i in range(n):
        v, _ = _decode(b, p + offs[i], keys)
        out.append(v)
    return out, p + offs[n]


_PATH_RE = re.compile(r"\.([A-Za-z_][A-Za-z0-9_]*)"
                      r"|\[\s*(\d+)\s*\]"
                      r"|\[\s*'([^']*)'\s*\]"
                      r"|\[\s*\"([^\"]*)\"\s*\]")


def _parse_path(path: str) -> List[Any]:
    if not path.startswith("$"):
        raise ValueError(f"variant path must start with $: {path!r}")
    out: List[Any] = []
    pos = 1
    while pos < len(path):
        m = _PATH_RE.match(path, pos)
        if not m:
            raise ValueError(f"bad variant path at {pos}: {path!r}")
        field, idx, q1, q2 = m.groups()
        if idx is not None:
            out.append(int(idx))
        else:
            out.append(field or q1 or q2)
        pos = m.end()
    return out


def variant_get(v: Optional[Variant], path: str):
    return None if v is None else v.get(path)


# ---------------------------------------------------------------------------
# Arrow integration + shredding
# ---------------------------------------------------------------------------

def variant_arrow_type() -> pa.DataType:
    """On-disk arrow shape of an unshredded variant column (the
    Spark/Parquet convention: struct<metadata, value>)."""
    return pa.struct([("metadata", pa.binary()), ("value", pa.binary())])


def column_from_objects(objs) -> pa.Array:
    """python objects -> arrow struct<metadata,value> column."""
    md, val = [], []
    for o in objs:
        if o is None:
            md.append(None)
            val.append(None)
        else:
            v = o if isinstance(o, Variant) else Variant.from_object(o)
            md.append(v.metadata)
            val.append(v.value)
    return pa.StructArray.from_arrays(
        [pa.array(md, pa.binary()), pa.array(val, pa.binary())],
        names=["metadata", "value"])


def column_to_variants(col) -> List[Optional[Variant]]:
    if isinstance(col, pa.ChunkedArray):
        col = col.combine_chunks()
    out = []
    for row in col.to_pylist():
        if row is None or row.get("metadata") is None:
            out.append(None)
        else:
            out.append(Variant(row["metadata"], row["value"]))
    return out


@dataclass
class ShreddingPlan:
    """Which paths shred into typed columns (reference
    VariantShreddingWritePlan): {'$.a.b': pa.int64(), ...}."""
    paths: Dict[str, pa.DataType]

    def field_name(self, path: str) -> str:
        return "typed_" + re.sub(r"[^A-Za-z0-9]+", "_",
                                 path[1:]).strip("_")


def _walk(obj, segs) -> Any:
    for seg in segs:
        if isinstance(seg, int):
            if not isinstance(obj, (list, tuple)) or \
                    not (0 <= seg < len(obj)):
                return None
            obj = obj[seg]
        else:
            if not isinstance(obj, dict) or seg not in obj:
                return None
            obj = obj[seg]
    return obj


def _coerce_exact(raw, typ: pa.DataType):
    """Shredding is LOSSLESS-only: a typed child holds the value only
    when the variant value already has that exact shape; anything lossy
    (9.99 into int64) stays residual-only (reference
    VariantShreddingWriter type-match semantics)."""
    if raw is None:
        return None
    if pa.types.is_boolean(typ):
        return raw if isinstance(raw, bool) else None
    if pa.types.is_integer(typ):
        if isinstance(raw, bool) or not isinstance(raw, int):
            return None
        try:
            return pa.scalar(raw, typ).as_py()
        except (pa.ArrowInvalid, OverflowError):
            return None
    if pa.types.is_floating(typ):
        return float(raw) if isinstance(raw, (int, float)) and \
            not isinstance(raw, bool) else None
    if pa.types.is_string(typ) or pa.types.is_large_string(typ):
        return raw if isinstance(raw, str) else None
    if pa.types.is_binary(typ) or pa.types.is_large_binary(typ):
        return bytes(raw) if isinstance(raw, (bytes, bytearray)) \
            else None
    try:
        return pa.scalar(raw, typ).as_py()
    except (pa.ArrowInvalid, pa.ArrowTypeError, OverflowError,
            TypeError):
        return None


def shred_column(col, plan: ShreddingPlan) -> pa.StructArray:
    """variant column -> struct<metadata, value, <typed...>> where each
    planned path becomes a typed child and rows keep their FULL variant
    residual in value (simple + lossless; reference shredding removes
    shredded fields from the residual as a size optimization).  Each
    row decodes ONCE; paths are parsed once."""
    variants = column_to_variants(col)
    paths = [(path, typ, _parse_path(path))
             for path, typ in plan.paths.items()]
    children: List[List[Any]] = [[] for _ in paths]
    md, val = [], []
    for v in variants:
        if v is None:
            md.append(None)
            val.append(None)
            for c in children:
                c.append(None)
            continue
        md.append(v.metadata)
        val.append(v.value)
        obj = v.to_object()
        for i, (_, typ, segs) in enumerate(paths):
            children[i].append(_coerce_exact(_walk(obj, segs), typ))
    arrays = [pa.array(md, pa.binary()), pa.array(val, pa.binary())]
    names = ["metadata", "value"]
    for (path, typ, _), vals in zip(paths, children):
        arrays.append(pa.array(vals, typ))
        names.append(plan.field_name(path))
    return pa.StructArray.from_arrays(arrays, names=names)


def unshred_column(col) -> pa.StructArray:
    """struct<metadata, value, typed...> -> plain variant column (the
    residual IS the full value here, so re-assembly is projection)."""
    if isinstance(col, pa.ChunkedArray):
        col = col.combine_chunks()
    return pa.StructArray.from_arrays(
        [col.field("metadata"), col.field("value")],
        names=["metadata", "value"])


def typed_path_column(col, plan: ShreddingPlan, path: str) -> pa.Array:
    """Read a shredded path WITHOUT decoding variants: the typed child
    column, straight from the struct (this is the point of shredding —
    predicate/projection on $.path at columnar speed)."""
    if isinstance(col, pa.ChunkedArray):
        col = col.combine_chunks()
    return col.field(plan.field_name(path))
