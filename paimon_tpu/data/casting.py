"""Cast rule matrix for schema evolution and value coercion.

reference: paimon-common/src/main/java/org/apache/paimon/casting/
CastExecutors.java — the rule table resolving (source, target) type
pairs to executors — and the individual rules (NumericPrimitiveCastRule,
StringToNumericPrimitiveCastRule, StringToBooleanCastRule,
NumericToBooleanCastRule, StringToDateCastRule, StringToTimestampCastRule,
DateToTimestampCastRule, NumericPrimitiveToTimestamp,
DecimalToDecimalCastRule, BinaryToStringCastRule, StringToBinaryCastRule,
BinaryToBinaryCastRule, StringToStringCastRule, *ToStringCastRule, ...).

Semantics follow the Java executors where they differ from Arrow:
- int -> narrower int: two's-complement bit truncation (Java (int)(long))
- float/double -> int: truncate toward zero, SATURATE at the target's
  min/max (Java float-to-integral conversion)
- numeric -> boolean: value != 0; boolean -> numeric: 1/0
- string -> boolean: BinaryStringUtils.toBoolean token set
- string -> numeric/temporal: trimmed, invalid input raises (the Java
  rules throw NumberFormatException / DateTimeException)
- char(n)/varchar(n): truncate to n; char pads with spaces
- binary(n): truncate/zero-pad to n
- anything -> string: Java-style rendering (true/false, ISO temporals)

Every cast is whole-column vectorized (Arrow compute / numpy); no
per-row Python except the JSON-ish complex->string renders.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from paimon_tpu.types import (
    ArrayType, BigIntType, BinaryType, BooleanType, CharType, DataType,
    DateType, DecimalType, DoubleType, FloatType, IntType,
    LocalZonedTimestampType, MapType, MultisetType, RowType, SmallIntType,
    TimeType, TimestampType, TinyIntType, VarBinaryType, VarCharType,
    data_type_to_arrow,
)

__all__ = ["can_cast", "cast_array", "CastError"]


class CastError(ValueError):
    pass


_INT_TYPES = (TinyIntType, SmallIntType, IntType, BigIntType)
_FLOAT_TYPES = (FloatType, DoubleType)
_STR_TYPES = (CharType, VarCharType)
_BIN_TYPES = (BinaryType, VarBinaryType)
_TS_TYPES = (TimestampType, LocalZonedTimestampType)

_INT_BITS = {TinyIntType: 8, SmallIntType: 16, IntType: 32,
             BigIntType: 64}
_NP_INT = {8: np.int8, 16: np.int16, 32: np.int32, 64: np.int64}

# reference utils/BinaryStringUtils.toBoolean token sets
_TRUE_TOKENS = {"true", "t", "yes", "y", "1"}
_FALSE_TOKENS = {"false", "f", "no", "n", "0"}


def _is_numeric(t: DataType) -> bool:
    return isinstance(t, _INT_TYPES + _FLOAT_TYPES + (DecimalType,))


def _chunked(arr) -> pa.ChunkedArray:
    if isinstance(arr, pa.ChunkedArray):
        return arr.combine_chunks()
    return arr


# -- individual rules --------------------------------------------------------

def _int_to_int(arr, src: DataType, dst: DataType):
    sb, db = _INT_BITS[type(src)], _INT_BITS[type(dst)]
    if db >= sb:
        return pc.cast(arr, data_type_to_arrow(dst))
    # Java narrowing = two's-complement truncation
    vals = np.asarray(_chunked(arr).fill_null(0)).astype(np.int64)
    out = vals.astype(_NP_INT[db])
    return pa.array(out, data_type_to_arrow(dst),
                    mask=np.asarray(pc.is_null(_chunked(arr))))


def _float_to_int(arr, src: DataType, dst: DataType):
    # JLS: float -> byte/short is float -> int (SATURATE at int bounds,
    # NaN -> 0) followed by int -> narrow (two's-complement truncation);
    # float -> long saturates at long bounds directly
    db = _INT_BITS[type(dst)]
    sat_bits = 64 if db == 64 else 32
    lo = -(1 << (sat_bits - 1))
    hi = (1 << (sat_bits - 1)) - 1
    vals = np.asarray(_chunked(arr).cast(pa.float64()).fill_null(0))
    trunc = np.trunc(vals)
    trunc = np.where(np.isnan(trunc), 0.0, trunc)
    # float64 cannot represent 2^63-1 (rounds up to 2^63), so a
    # float-space clip followed by astype would WRAP huge positives to
    # Long.MIN; saturate with explicit masks instead
    hi_f = float(1 << (sat_bits - 1))     # 2^(bits-1), exact in float
    hi_mask = trunc >= hi_f
    lo_mask = trunc <= float(lo)          # lo itself is exact
    safe = np.where(hi_mask | lo_mask, 0.0, trunc)
    out = np.where(hi_mask, hi, np.where(lo_mask, lo,
                                         safe.astype(np.int64)))
    return pa.array(out.astype(_NP_INT[db]), data_type_to_arrow(dst),
                    mask=np.asarray(pc.is_null(_chunked(arr))))


def _num_to_bool(arr, src, dst):
    base = _chunked(arr)
    if isinstance(src, DecimalType):
        base = base.cast(pa.float64())
    return pc.not_equal(base, pa.scalar(0, base.type)
                        if not pa.types.is_floating(base.type)
                        else pa.scalar(0.0, base.type))


def _bool_to_num(arr, src, dst):
    return pc.cast(pc.cast(arr, pa.int8()), data_type_to_arrow(dst))


def _str_to_bool(arr, src, dst):
    s = pc.utf8_lower(pc.utf8_trim_whitespace(_chunked(arr)))
    t = pc.is_in(s, value_set=pa.array(sorted(_TRUE_TOKENS)))
    f = pc.is_in(s, value_set=pa.array(sorted(_FALSE_TOKENS)))
    bad = pc.and_(pc.and_(pc.invert(t), pc.invert(f)), pc.is_valid(s))
    if pc.any(bad).as_py():
        val = s.filter(bad)[0].as_py()
        raise CastError(f"cannot cast string {val!r} to boolean")
    return pc.if_else(pc.is_valid(s), t, pa.nulls(len(s), pa.bool_()))


def _str_to_num(arr, src, dst):
    s = pc.utf8_trim_whitespace(_chunked(arr))
    try:
        if isinstance(dst, _INT_TYPES):
            # Java parses then range-checks; arrow safe cast does both
            return pc.cast(pc.cast(s, pa.int64()),
                           data_type_to_arrow(dst))
        return pc.cast(s, data_type_to_arrow(dst))
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError) as e:
        raise CastError(str(e)) from e


def _str_to_date(arr, src, dst):
    s = pc.utf8_trim_whitespace(_chunked(arr))
    try:
        return pc.cast(s, pa.date32())
    except pa.ArrowInvalid as e:
        raise CastError(str(e)) from e


def _str_to_time(arr, src, dst):
    s = pc.utf8_trim_whitespace(_chunked(arr))
    try:
        return pc.cast(s, pa.time32("ms")) \
            .cast(data_type_to_arrow(dst))
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
        # HH:MM[:SS[.fff]] manual parse, vectorized per component
        try:
            parts = pc.split_pattern(s, ":")
            lst = parts.to_pylist()
            out = []
            for p in lst:
                if p is None:
                    out.append(None)
                    continue
                h, m = int(p[0]), int(p[1])
                sec = float(p[2]) if len(p) > 2 else 0.0
                # round, not truncate: 0.57*1000 is 569.999... in float
                out.append((h * 3600 + m * 60) * 1000
                           + round(sec * 1000))
            return pa.array(out, pa.time32("ms")).cast(
                data_type_to_arrow(dst))
        except (ValueError, IndexError) as e:
            raise CastError(f"bad time literal: {e}") from e


def _str_to_ts(arr, src, dst):
    s = pc.utf8_trim_whitespace(_chunked(arr))
    try:
        return pc.cast(s, data_type_to_arrow(dst))
    except pa.ArrowInvalid as e:
        raise CastError(str(e)) from e


def _date_to_ts(arr, src, dst):
    return pc.cast(pc.cast(arr, pa.timestamp("ms")),
                   data_type_to_arrow(dst))


def _ts_to_date(arr, src, dst):
    return pc.cast(_chunked(arr), pa.date32(), safe=False)


def _ts_to_time(arr, src, dst):
    ms = pc.cast(_chunked(arr), pa.timestamp("ms"), safe=False)
    vals = np.asarray(ms.cast(pa.int64()))
    return pa.array((vals % 86_400_000).astype(np.int32),
                    pa.time32("ms"),
                    mask=np.asarray(pc.is_null(ms))) \
        .cast(data_type_to_arrow(dst))


def _num_to_ts(arr, src, dst):
    # reference NumericPrimitiveToTimestamp: epoch SECONDS
    secs = pc.cast(_chunked(arr), pa.int64())
    ms = pc.multiply(secs, pa.scalar(1000, pa.int64()))
    return pc.cast(ms, pa.timestamp("ms")).cast(data_type_to_arrow(dst))


def _to_decimal(arr, src, dst: DecimalType):
    try:
        base = _chunked(arr)
        if isinstance(src, _STR_TYPES):
            base = pc.utf8_trim_whitespace(base)
        elif isinstance(src, _INT_TYPES):
            # arrow demands precision headroom for int inputs; widen to
            # the max then narrow with the overflow check (Java
            # DecimalUtils.castFrom overflow -> error)
            base = pc.cast(base, pa.decimal128(38, dst.scale))
        return pc.cast(base, data_type_to_arrow(dst))
    except pa.ArrowInvalid as e:
        raise CastError(str(e)) from e


def _decimal_to_num(arr, src, dst):
    if isinstance(dst, _FLOAT_TYPES):
        return pc.cast(_chunked(arr), data_type_to_arrow(dst))
    # exact integral part (Java BigDecimal truncates toward zero, then
    # the long narrows by bit truncation) — no float64 detour, which
    # would corrupt >2^53 values
    import decimal as _dec
    db = _INT_BITS[type(dst)]
    base = _chunked(arr)
    vals = [None if v is None else
            int(v.to_integral_value(rounding=_dec.ROUND_DOWN))
            for v in base.to_pylist()]
    mask = np.array([v is None for v in vals])
    ints = np.array([0 if v is None else (v & ((1 << 64) - 1))
                     for v in vals], dtype=np.uint64).view(np.int64)
    return pa.array(ints.astype(_NP_INT[db]), data_type_to_arrow(dst),
                    mask=mask)


def _str_to_str(arr, src, dst):
    s = _chunked(arr).cast(pa.large_string()).cast(pa.string())
    length = getattr(dst, "length", None)
    if isinstance(dst, CharType):
        s = pc.utf8_slice_codeunits(s, 0, length)
        return pc.utf8_rpad(s, width=length, padding=" ")
    if isinstance(dst, VarCharType) and length is not None and \
            length < VarCharType.MAX_LENGTH:
        return pc.utf8_slice_codeunits(s, 0, length)
    return s


def _bin_to_bin(arr, src, dst):
    length = getattr(dst, "length", None)
    vals = _chunked(arr).cast(pa.large_binary()).to_pylist()
    if isinstance(dst, BinaryType) and length is not None:
        vals = [None if v is None else
                (v[:length] + b"\x00" * (length - len(v)))
                for v in vals]
    elif isinstance(dst, VarBinaryType) and length is not None and \
            length < VarBinaryType.MAX_LENGTH:
        vals = [None if v is None else v[:length] for v in vals]
    return pa.array(vals, data_type_to_arrow(dst))


def _str_to_bin(arr, src, dst):
    return _bin_to_bin(pc.cast(_chunked(arr), pa.large_binary()), src,
                       dst)


def _bin_to_str(arr, src, dst):
    try:
        return _str_to_str(_chunked(arr).cast(pa.large_string()), src,
                           dst)
    except pa.ArrowInvalid as e:
        raise CastError(str(e)) from e


def _any_to_string(arr, src, dst):
    base = _chunked(arr)
    if isinstance(src, BooleanType):
        out = pc.if_else(base, pa.scalar("true"), pa.scalar("false"))
        return _str_to_str(out, src, dst)
    if isinstance(src, (_INT_TYPES + (DecimalType, DateType))) or \
            isinstance(src, _TS_TYPES) or isinstance(src, TimeType):
        return _str_to_str(pc.cast(base, pa.string()), src, dst)
    if isinstance(src, _FLOAT_TYPES):
        # Java Double.toString always carries a decimal point
        # ("1.0", not "1"); python repr matches that shape (exponent
        # spelling differs only at extreme magnitudes)
        rendered = pa.array(
            [None if v is None else repr(float(v))
             for v in base.to_pylist()], pa.string())
        return _str_to_str(rendered, src, dst)
    if isinstance(src, (ArrayType, MapType, MultisetType, RowType)):
        import json

        def render(v):
            if v is None:
                return None
            return json.dumps(v, default=str, separators=(",", ":"))
        return _str_to_str(
            pa.array([render(v) for v in base.to_pylist()], pa.string()),
            src, dst)
    raise CastError(f"no to-string rule for {src}")


# -- rule resolution ---------------------------------------------------------

def _resolve(src: DataType, dst: DataType) -> Optional[Callable]:
    if type(src) is type(dst):
        if isinstance(src, _STR_TYPES):
            return _str_to_str
        if isinstance(src, _BIN_TYPES):
            return _bin_to_bin
        if isinstance(src, DecimalType):
            return _to_decimal
        return lambda a, s, d: pc.cast(_chunked(a),
                                       data_type_to_arrow(d))
    if isinstance(src, _INT_TYPES) and isinstance(dst, _INT_TYPES):
        return _int_to_int
    if isinstance(src, _INT_TYPES) and isinstance(dst, _FLOAT_TYPES):
        return lambda a, s, d: pc.cast(_chunked(a),
                                       data_type_to_arrow(d))
    if isinstance(src, _FLOAT_TYPES) and isinstance(dst, _FLOAT_TYPES):
        return lambda a, s, d: pc.cast(_chunked(a),
                                       data_type_to_arrow(d), safe=False)
    if isinstance(src, _FLOAT_TYPES) and isinstance(dst, _INT_TYPES):
        return _float_to_int
    if _is_numeric(src) and isinstance(dst, BooleanType):
        return _num_to_bool
    if isinstance(src, BooleanType) and _is_numeric(dst) and \
            not isinstance(dst, DecimalType):
        return _bool_to_num
    if isinstance(src, BooleanType) and isinstance(dst, DecimalType):
        return lambda a, s, d: _to_decimal(_bool_to_num(a, s, IntType()),
                                           IntType(), d)
    if isinstance(src, DecimalType) and _is_numeric(dst):
        return _decimal_to_num
    if _is_numeric(src) and isinstance(dst, DecimalType):
        return _to_decimal
    if isinstance(src, _STR_TYPES):
        if isinstance(dst, BooleanType):
            return _str_to_bool
        if isinstance(dst, DecimalType):
            return _to_decimal
        if _is_numeric(dst):
            return _str_to_num
        if isinstance(dst, DateType):
            return _str_to_date
        if isinstance(dst, TimeType):
            return _str_to_time
        if isinstance(dst, _TS_TYPES):
            return _str_to_ts
        if isinstance(dst, _BIN_TYPES):
            return _str_to_bin
        if isinstance(dst, _STR_TYPES):
            return _str_to_str
    if isinstance(dst, _STR_TYPES):
        if isinstance(src, _BIN_TYPES):
            return _bin_to_str
        return _any_to_string
    if isinstance(src, DateType) and isinstance(dst, _TS_TYPES):
        return _date_to_ts
    if isinstance(src, _TS_TYPES) and isinstance(dst, DateType):
        return _ts_to_date
    if isinstance(src, _TS_TYPES) and isinstance(dst, TimeType):
        return _ts_to_time
    if isinstance(src, _TS_TYPES) and isinstance(dst, _TS_TYPES):
        return lambda a, s, d: pc.cast(_chunked(a),
                                       data_type_to_arrow(d), safe=False)
    if isinstance(src, _INT_TYPES) and isinstance(dst, _TS_TYPES):
        return _num_to_ts
    if isinstance(src, _BIN_TYPES) and isinstance(dst, _BIN_TYPES):
        return _bin_to_bin
    return None


def can_cast(src: DataType, dst: DataType) -> bool:
    """reference CastExecutors.resolve != null."""
    return _resolve(src, dst) is not None


def cast_array(arr, src: DataType, dst: DataType):
    """Cast a column under the rule matrix; raises CastError when no
    rule exists or the data is invalid for the target."""
    rule = _resolve(src, dst)
    if rule is None:
        raise CastError(f"no cast rule {src} -> {dst} "
                        f"(reference CastExecutors.resolve)")
    out = rule(arr, src, dst)
    want = data_type_to_arrow(dst)
    if isinstance(out, pa.ChunkedArray):
        out = out.combine_chunks()
    if out.type != want:
        out = out.cast(want)
    return out
