"""Data plane (L1).

Rows are plain Python tuples at the edges; columnar batches are Arrow
RecordBatches on the host and struct-of-arrays jax arrays in HBM. The only
row-level binary codec kept from the reference wire format is BinaryRow
(paimon-common/.../data/BinaryRow.java:60), because manifests embed
partitions and min/max stats as BinaryRow bytes.
"""

from paimon_tpu.data.binary_row import (  # noqa: F401
    BinaryRowCodec, BINARY_ROW_EMPTY,
)
from paimon_tpu.data.row import GenericRow, InternalRow  # noqa: F401
