"""BinaryRow wire codec.

Wire format (reference paimon-common/.../data/BinaryRow.java:60 and
docs/docs/concepts/spec/manifest.md appendix):

  [4-byte big-endian arity]            -- only in serialized (manifest) form
  fixed part:
    byte 0: header (RowKind)
    null bitset: bit (i+8) set => field i null; width rounds (arity+8) bits
      up to 64-bit words
    arity * 8-byte slots, little-endian
  variable part: 8-byte-aligned var-length data

Var-length slot encoding: if len <= 7 the bytes live inline in the slot and
the top byte is 0x80|len; otherwise slot = (absolute_offset << 32) | len.
Decimal(p>18): 16-byte var area, big-endian signed unscaled.
Timestamp(p>3): slot = (offset << 32) | nano_of_milli; millis in var area.
"""

from __future__ import annotations

import struct
from datetime import date, datetime, time, timedelta
from decimal import Decimal
from typing import Any, List, Optional, Sequence, Tuple

from paimon_tpu.types import (
    ArrayType, BigIntType, BinaryType, BlobType, BooleanType, CharType,
    DataType, DateType, DecimalType, DoubleType, FloatType, IntType,
    LocalZonedTimestampType, MapType, MultisetType, RowType, SmallIntType,
    TimeType, TimestampType, TinyIntType, VarBinaryType, VarCharType,
)

__all__ = ["BinaryRowCodec", "BINARY_ROW_EMPTY"]

_HEADER_BITS = 8
_MAX_INLINE = 7
_EPOCH = date(1970, 1, 1)


def _bitset_width(arity: int) -> int:
    return ((arity + 63 + _HEADER_BITS) // 64) * 8


def _round_word(n: int) -> int:
    return ((n + 7) // 8) * 8


def _is_compact_decimal(t: DecimalType) -> bool:
    return t.precision <= 18


def _is_compact_ts(t) -> bool:
    return t.precision <= 3


class BinaryRowCodec:
    """Encode/decode tuples of Python values <-> BinaryRow bytes for a fixed
    list of field types. Supports the atomic types that appear in partition
    values and column stats."""

    def __init__(self, field_types: Sequence[DataType]):
        self.field_types = list(field_types)
        self.arity = len(self.field_types)
        self._null_bytes = _bitset_width(self.arity)
        self._fixed_size = self._null_bytes + self.arity * 8

    # -- encode --------------------------------------------------------------

    def to_bytes(self, values: Sequence[Any], row_kind: int = 0,
                 with_arity_prefix: bool = True) -> bytes:
        assert len(values) == self.arity, (len(values), self.arity)
        fixed = bytearray(self._fixed_size)
        fixed[0] = row_kind
        var_parts: List[bytes] = []
        var_off = 0

        for i, (v, t) in enumerate(zip(values, self.field_types)):
            slot = self._null_bytes + i * 8
            if v is None:
                idx = i + _HEADER_BITS
                fixed[idx // 8] |= 1 << (idx % 8)
                continue
            if isinstance(t, BooleanType):
                fixed[slot] = 1 if v else 0
            elif isinstance(t, TinyIntType):
                struct.pack_into("<b", fixed, slot, int(v))
            elif isinstance(t, SmallIntType):
                struct.pack_into("<h", fixed, slot, int(v))
            elif isinstance(t, (IntType, DateType, TimeType)):
                struct.pack_into("<i", fixed, slot, _to_int32(v, t))
            elif isinstance(t, BigIntType):
                struct.pack_into("<q", fixed, slot, int(v))
            elif isinstance(t, FloatType):
                struct.pack_into("<f", fixed, slot, float(v))
            elif isinstance(t, DoubleType):
                struct.pack_into("<d", fixed, slot, float(v))
            elif isinstance(t, DecimalType):
                var_off = self._put_decimal(v, t, fixed, slot, var_parts,
                                            var_off)
            elif isinstance(t, (TimestampType, LocalZonedTimestampType)):
                var_off = self._put_timestamp(v, t, fixed, slot, var_parts,
                                              var_off)
            elif isinstance(t, (CharType, VarCharType)):
                var_off = self._put_var(str(v).encode("utf-8"), fixed, slot,
                                        var_parts, var_off)
            elif isinstance(t, (BinaryType, VarBinaryType, BlobType)):
                var_off = self._put_var(bytes(v), fixed, slot, var_parts,
                                        var_off)
            else:
                raise ValueError(f"BinaryRow cannot encode type {t}")

        body = bytes(fixed) + b"".join(var_parts)
        if with_arity_prefix:
            return struct.pack(">i", self.arity) + body
        return body

    def _put_var(self, data: bytes, fixed: bytearray, slot: int,
                 var_parts: List[bytes], var_off: int) -> int:
        n = len(data)
        if n <= _MAX_INLINE:
            fixed[slot:slot + n] = data
            fixed[slot + 7] = 0x80 | n
            return var_off
        padded = data + b"\x00" * (_round_word(n) - n)
        abs_off = self._fixed_size + var_off
        struct.pack_into("<q", fixed, slot, (abs_off << 32) | n)
        var_parts.append(padded)
        return var_off + len(padded)

    def _put_decimal(self, v, t: DecimalType, fixed: bytearray, slot: int,
                     var_parts: List[bytes], var_off: int) -> int:
        d = v if isinstance(v, Decimal) else Decimal(str(v))
        unscaled = int(d.scaleb(t.scale).to_integral_value())
        if _is_compact_decimal(t):
            struct.pack_into("<q", fixed, slot, unscaled)
            return var_off
        nbytes = max(1, (unscaled.bit_length() + 8) // 8)
        data = unscaled.to_bytes(nbytes, "big", signed=True)
        padded = data + b"\x00" * (16 - len(data))
        abs_off = self._fixed_size + var_off
        struct.pack_into("<q", fixed, slot, (abs_off << 32) | len(data))
        var_parts.append(padded)
        return var_off + 16

    def _put_timestamp(self, v, t, fixed: bytearray, slot: int,
                       var_parts: List[bytes], var_off: int) -> int:
        millis, nanos = _to_millis_nanos(v)
        if _is_compact_ts(t):
            struct.pack_into("<q", fixed, slot, millis)
            return var_off
        abs_off = self._fixed_size + var_off
        struct.pack_into("<q", fixed, slot, (abs_off << 32) | nanos)
        var_parts.append(struct.pack("<q", millis))
        return var_off + 8

    # -- decode --------------------------------------------------------------

    def from_bytes(self, data: bytes,
                   with_arity_prefix: bool = True) -> Tuple[Any, ...]:
        if with_arity_prefix and len(data) >= 4:
            data = data[4:]
        if not data:
            return tuple([None] * self.arity)
        out: List[Any] = []
        for i, t in enumerate(self.field_types):
            idx = i + _HEADER_BITS
            if data[idx // 8] & (1 << (idx % 8)):
                out.append(None)
                continue
            slot = self._null_bytes + i * 8
            out.append(self._get(data, slot, t))
        return tuple(out)

    def row_kind(self, data: bytes, with_arity_prefix: bool = True) -> int:
        if with_arity_prefix and len(data) >= 4:
            data = data[4:]
        return data[0] if data else 0

    def _get(self, data: bytes, slot: int, t: DataType) -> Any:
        if isinstance(t, BooleanType):
            return data[slot] != 0
        if isinstance(t, TinyIntType):
            return struct.unpack_from("<b", data, slot)[0]
        if isinstance(t, SmallIntType):
            return struct.unpack_from("<h", data, slot)[0]
        if isinstance(t, IntType):
            return struct.unpack_from("<i", data, slot)[0]
        if isinstance(t, DateType):
            return _EPOCH + timedelta(
                days=struct.unpack_from("<i", data, slot)[0])
        if isinstance(t, TimeType):
            ms = struct.unpack_from("<i", data, slot)[0]
            s, msec = divmod(ms, 1000)
            return time(s // 3600, (s % 3600) // 60, s % 60, msec * 1000)
        if isinstance(t, BigIntType):
            return struct.unpack_from("<q", data, slot)[0]
        if isinstance(t, FloatType):
            return struct.unpack_from("<f", data, slot)[0]
        if isinstance(t, DoubleType):
            return struct.unpack_from("<d", data, slot)[0]
        if isinstance(t, DecimalType):
            return self._get_decimal(data, slot, t)
        if isinstance(t, (TimestampType, LocalZonedTimestampType)):
            return self._get_timestamp(data, slot, t)
        if isinstance(t, (CharType, VarCharType)):
            return self._get_var(data, slot).decode("utf-8")
        if isinstance(t, (BinaryType, VarBinaryType, BlobType)):
            return self._get_var(data, slot)
        raise ValueError(f"BinaryRow cannot decode type {t}")

    @staticmethod
    def _get_var(data: bytes, slot: int) -> bytes:
        raw = struct.unpack_from("<q", data, slot)[0]
        if raw & (0x80 << 56):
            n = (raw >> 56) & 0x7F
            return data[slot:slot + n]
        off = (raw >> 32) & 0xFFFFFFFF
        n = raw & 0xFFFFFFFF
        return data[off:off + n]

    def _get_decimal(self, data: bytes, slot: int, t: DecimalType) -> Decimal:
        if _is_compact_decimal(t):
            unscaled = struct.unpack_from("<q", data, slot)[0]
        else:
            raw = struct.unpack_from("<q", data, slot)[0]
            off = (raw >> 32) & 0xFFFFFFFF
            n = raw & 0xFFFFFFFF
            unscaled = int.from_bytes(data[off:off + n], "big", signed=True)
        return Decimal(unscaled).scaleb(-t.scale)

    def _get_timestamp(self, data: bytes, slot: int, t) -> datetime:
        if _is_compact_ts(t):
            millis = struct.unpack_from("<q", data, slot)[0]
            nanos = 0
        else:
            raw = struct.unpack_from("<q", data, slot)[0]
            nanos = raw & 0xFFFFFFFF
            off = (raw >> 32) & 0xFFFFFFFF
            millis = struct.unpack_from("<q", data, off)[0]
        return _from_millis_nanos(millis, nanos)


def _to_int32(v, t) -> int:
    if isinstance(t, DateType):
        if isinstance(v, date) and not isinstance(v, datetime):
            return (v - _EPOCH).days
        return int(v)
    if isinstance(t, TimeType):
        if isinstance(v, time):
            return ((v.hour * 3600 + v.minute * 60 + v.second) * 1000
                    + v.microsecond // 1000)
        return int(v)
    return int(v)


def _to_millis_nanos(v) -> Tuple[int, int]:
    if isinstance(v, datetime):
        epoch = datetime(1970, 1, 1, tzinfo=v.tzinfo)
        delta = v - epoch
        micros = (delta.days * 86400 + delta.seconds) * 1_000_000 \
            + delta.microseconds
        millis, rem_us = divmod(micros, 1000)
        return millis, rem_us * 1000
    return int(v), 0


def _from_millis_nanos(millis: int, nanos: int = 0) -> datetime:
    return (datetime(1970, 1, 1)
            + timedelta(milliseconds=millis, microseconds=nanos // 1000))


# The empty partition row ("no partition"), arity 0.
BINARY_ROW_EMPTY = BinaryRowCodec([]).to_bytes(())
