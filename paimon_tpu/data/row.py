"""Row abstractions.

The reference's ``InternalRow`` (paimon-common/.../data/InternalRow.java:91)
is a positional accessor interface; here rows at API edges are thin tuples
with a row kind. Bulk data never goes through rows -- it flows as Arrow
RecordBatches (host) and jax struct-of-arrays (device).
"""

from __future__ import annotations

from typing import Any, List, Sequence

from paimon_tpu.types import RowKind

__all__ = ["InternalRow", "GenericRow"]


class InternalRow:
    """Positional row view."""

    def get_field(self, pos: int) -> Any:
        raise NotImplementedError

    def get_row_kind(self) -> int:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class GenericRow(InternalRow):
    __slots__ = ("values", "row_kind")

    def __init__(self, *values, row_kind: int = RowKind.INSERT):
        if len(values) == 1 and isinstance(values[0], (list, tuple)):
            values = tuple(values[0])
        self.values: tuple = tuple(values)
        self.row_kind = row_kind

    @staticmethod
    def of(*values) -> "GenericRow":
        return GenericRow(*values)

    @staticmethod
    def of_kind(kind: int, *values) -> "GenericRow":
        return GenericRow(*values, row_kind=kind)

    def get_field(self, pos: int) -> Any:
        return self.values[pos]

    def get_row_kind(self) -> int:
        return self.row_kind

    def __len__(self):
        return len(self.values)

    def __iter__(self):
        return iter(self.values)

    def __getitem__(self, i):
        return self.values[i]

    def __eq__(self, other):
        return (isinstance(other, GenericRow)
                and self.values == other.values
                and self.row_kind == other.row_kind)

    def __hash__(self):
        return hash((self.values, self.row_kind))

    def __repr__(self):
        return (f"{RowKind.short_string(self.row_kind)}"
                f"{list(self.values)}")
