"""Config system.

Analog of the reference's typed option system
(paimon-api/.../options/ConfigOption.java, Options.java) and the table-level
``CoreOptions`` (paimon-api/.../CoreOptions.java, 5498 lines). Only options
with behavior in this framework are declared; unknown keys round-trip through
``Options`` untouched so schemas remain forward-compatible.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, Iterable, Optional

__all__ = ["ConfigOption", "Options", "CoreOptions", "MergeEngine",
           "ChangelogProducer", "StartupMode", "SortEngine", "BucketMode",
           "MemorySize", "parse_memory_size"]


_SIZE_RE = re.compile(r"^\s*(\d+)\s*([kKmMgGtT]?)[bB]?\s*$")
_UNITS = {"": 1, "k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}


def parse_memory_size(v) -> int:
    """'128 mb' / '1g' / 1024 -> bytes (reference options/MemorySize.java)."""
    if isinstance(v, int):
        return v
    m = _SIZE_RE.match(str(v))
    if not m:
        raise ValueError(f"Cannot parse memory size: {v!r}")
    return int(m.group(1)) * _UNITS[m.group(2).lower()]


MemorySize = parse_memory_size


def _parse_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).lower() in ("true", "1", "yes")


def _parse_duration_ms(v) -> int:
    """'1 s' / '5 min' / '100ms' -> milliseconds."""
    if isinstance(v, (int, float)):
        return int(v)
    s = str(v).strip().lower()
    m = re.match(r"^(\d+)\s*([a-z]*)$", s)
    if not m:
        raise ValueError(f"Cannot parse duration: {v!r}")
    n, unit = int(m.group(1)), m.group(2)
    mult = {"": 1, "ms": 1, "s": 1000, "sec": 1000, "min": 60000,
            "m": 60000, "h": 3600000, "d": 86400000}[unit]
    return n * mult


class ConfigOption:
    """A typed option with key, default, and description."""

    def __init__(self, key: str, typ: Callable[[Any], Any], default: Any,
                 description: str = ""):
        self.key = key
        self.typ = typ
        self.default = default
        self.description = description

    def parse(self, raw: Any) -> Any:
        if raw is None:
            return self.default
        return self.typ(raw)

    def __repr__(self):
        return f"ConfigOption({self.key!r}, default={self.default!r})"


class Options:
    """String->string map with typed access (reference options/Options.java)."""

    def __init__(self, conf: Optional[Dict[str, Any]] = None):
        self._map: Dict[str, str] = {}
        if conf:
            for k, v in conf.items():
                self.set(k, v)

    def set(self, key, value) -> "Options":
        if isinstance(key, ConfigOption):
            key = key.key
        self._map[key] = str(value) if not isinstance(value, str) else value
        return self

    def get(self, option):
        if isinstance(option, ConfigOption):
            return option.parse(self._map.get(option.key))
        return self._map.get(option)

    def get_or(self, key: str, default):
        return self._map.get(key, default)

    def contains(self, key) -> bool:
        if isinstance(key, ConfigOption):
            key = key.key
        return key in self._map

    def remove(self, key: str):
        self._map.pop(key, None)

    def keys(self) -> Iterable[str]:
        return self._map.keys()

    def to_map(self) -> Dict[str, str]:
        return dict(self._map)

    def copy(self) -> "Options":
        return Options(dict(self._map))

    def __eq__(self, other):
        return isinstance(other, Options) and self._map == other._map

    def __repr__(self):
        return f"Options({self._map})"


# -- enums (reference CoreOptions.java:4590,4619,4759) -----------------------

class MergeEngine:
    DEDUPLICATE = "deduplicate"
    PARTIAL_UPDATE = "partial-update"
    AGGREGATE = "aggregation"
    FIRST_ROW = "first-row"


class ChangelogProducer:
    NONE = "none"
    INPUT = "input"
    FULL_COMPACTION = "full-compaction"
    LOOKUP = "lookup"


class StartupMode:
    DEFAULT = "default"
    LATEST_FULL = "latest-full"
    FULL = "full"
    LATEST = "latest"
    COMPACTED_FULL = "compacted-full"
    FROM_TIMESTAMP = "from-timestamp"
    FROM_FILE_CREATION_TIME = "from-file-creation-time"
    FROM_SNAPSHOT = "from-snapshot"
    FROM_SNAPSHOT_FULL = "from-snapshot-full"
    INCREMENTAL = "incremental"


class SortEngine:
    LOSER_TREE = "loser-tree"     # reference default
    MIN_HEAP = "min-heap"
    TPU_SEGMENTED = "tpu-segmented"  # ours: device sort + segmented reduce


class BucketMode:
    """reference paimon-common/.../table/BucketMode.java:30"""
    HASH_FIXED = "hash-fixed"
    HASH_DYNAMIC = "hash-dynamic"
    KEY_DYNAMIC = "key-dynamic"
    BUCKET_UNAWARE = "bucket-unaware"
    POSTPONE = "postpone"

    POSTPONE_BUCKET = -2
    UNAWARE_BUCKET = -1


class CoreOptions:
    """Typed view over table options (reference CoreOptions.java)."""

    BUCKET = ConfigOption("bucket", int, -1, "Bucket count; -1 = unaware/dynamic")
    BUCKET_KEY = ConfigOption("bucket-key", str, None, "Comma-separated bucket key")
    PATH = ConfigOption("path", str, None, "Table path")
    FILE_FORMAT = ConfigOption("file.format", str, "parquet", "Data file format")
    FILE_FORMAT_PER_LEVEL = ConfigOption(
        "file.format.per.level", str, None,
        "Per-LSM-level format overrides, e.g. '0:avro,5:parquet' — "
        "fast row codec for hot L0, columnar for settled levels "
        "(reference CoreOptions file.format.per.level)")
    FILE_COMPRESSION_ZSTD_LEVEL = ConfigOption(
        "file.compression.zstd-level", int, None,
        "zstd level for data files (reference CoreOptions"
        ".FILE_COMPRESSION_ZSTD_LEVEL); None = codec default")
    FILE_COMPRESSION = ConfigOption("file.compression", str, "zstd",
                                    "Data file compression")
    MANIFEST_FORMAT = ConfigOption("manifest.format", str, "avro",
                                   "Manifest file format")
    MANIFEST_TARGET_FILE_SIZE = ConfigOption("manifest.target-file-size",
                                             parse_memory_size, 8 << 20, "")
    MANIFEST_MERGE_MIN_COUNT = ConfigOption("manifest.merge-min-count", int, 30,
                                            "Min manifests to trigger full rewrite")
    MERGE_ENGINE = ConfigOption("merge-engine", str, MergeEngine.DEDUPLICATE,
                                "deduplicate | partial-update | aggregation | first-row")
    IGNORE_DELETE = ConfigOption("ignore-delete", _parse_bool, False, "")
    CHANGELOG_PRODUCER = ConfigOption("changelog-producer", str,
                                      ChangelogProducer.NONE, "")
    SEQUENCE_FIELD = ConfigOption("sequence.field", str, None,
                                  "User-defined sequence column(s)")
    ROWKIND_FIELD = ConfigOption("rowkind.field", str, None, "")
    PARTITION_DEFAULT_NAME = ConfigOption("partition.default-name", str,
                                          "__DEFAULT_PARTITION__", "")
    TARGET_FILE_SIZE = ConfigOption("target-file-size", parse_memory_size,
                                    128 << 20, "Target data file size")
    WRITE_BUFFER_SPILLABLE = ConfigOption(
        "write-buffer-spillable", _parse_bool, False,
        "Primary-key writers only: spill full write buffers to local "
        "sorted runs (zstd Arrow IPC) and merge them into L0 at "
        "prepare-commit — fewer, larger L0 files than flushing one "
        "file per buffer-full")
    WRITE_BUFFER_SIZE = ConfigOption("write-buffer-size", parse_memory_size,
                                     256 << 20, "Sort buffer memory")
    WRITE_ONLY = ConfigOption("write-only", _parse_bool, False,
                              "Skip compaction on write")
    NUM_SORTED_RUNS_COMPACTION_TRIGGER = ConfigOption(
        "num-sorted-run.compaction-trigger", int, 5,
        "Sorted runs triggering compaction (reference CoreOptions.java:876)")
    NUM_SORTED_RUNS_STOP_TRIGGER = ConfigOption(
        "num-sorted-run.stop-trigger", int, None, "Write-stall threshold")
    NUM_LEVELS = ConfigOption("num-levels", int, None, "LSM levels")
    COMPACTION_MAX_SIZE_AMPLIFICATION_PERCENT = ConfigOption(
        "compaction.max-size-amplification-percent", int, 200, "")
    COMPACTION_SIZE_RATIO = ConfigOption("compaction.size-ratio", int, 1, "")
    COMPACTION_MIN_FILE_NUM = ConfigOption("compaction.min.file-num", int, 5, "")
    COMPACTION_OPTIMIZATION_INTERVAL = ConfigOption(
        "compaction.optimization-interval", _parse_duration_ms, None, "")
    FULL_COMPACTION_DELTA_COMMITS = ConfigOption(
        "full-compaction.delta-commits", int, None, "")
    SNAPSHOT_NUM_RETAINED_MIN = ConfigOption("snapshot.num-retained.min",
                                             int, 10, "")
    SNAPSHOT_NUM_RETAINED_MAX = ConfigOption("snapshot.num-retained.max",
                                             int, 2147483647, "")
    SNAPSHOT_TIME_RETAINED = ConfigOption("snapshot.time-retained",
                                          _parse_duration_ms, 3600000, "")
    SNAPSHOT_EXPIRE_LIMIT = ConfigOption("snapshot.expire.limit", int, 50, "")
    CHANGELOG_NUM_RETAINED_MIN = ConfigOption("changelog.num-retained.min",
                                              int, None, "")
    CHANGELOG_NUM_RETAINED_MAX = ConfigOption("changelog.num-retained.max",
                                              int, None, "")
    SCAN_MODE = ConfigOption("scan.mode", str, StartupMode.DEFAULT, "")
    SCAN_SNAPSHOT_ID = ConfigOption("scan.snapshot-id", int, None, "")
    SCAN_TAG_NAME = ConfigOption("scan.tag-name", str, None, "")
    SCAN_TIMESTAMP_MILLIS = ConfigOption("scan.timestamp-millis", int, None, "")
    SCAN_FALLBACK_BRANCH = ConfigOption("scan.fallback-branch", str, None, "")
    INCREMENTAL_BETWEEN = ConfigOption("incremental-between", str, None, "")
    CONSUMER_ID = ConfigOption("consumer-id", str, None, "")
    CONSUMER_EXPIRATION_TIME = ConfigOption("consumer.expiration-time",
                                            _parse_duration_ms, None, "")
    # NOTE: reads always honor DVs once written (DELETE FROM); this flag
    # reserves the reference's compaction-time DV production mode
    DELETION_VECTORS_ENABLED = ConfigOption("deletion-vectors.enabled",
                                            _parse_bool, False, "")
    DYNAMIC_BUCKET_TARGET_ROW_NUM = ConfigOption(
        "dynamic-bucket.target-row-num", int, 2_000_000, "")
    DYNAMIC_BUCKET_INITIAL_BUCKETS = ConfigOption(
        "dynamic-bucket.initial-buckets", int, None, "")
    DYNAMIC_BUCKET_ASSIGNER_PARALLELISM = ConfigOption(
        "dynamic-bucket.assigner-parallelism", int, None, "")
    SORT_ENGINE = ConfigOption("sort-engine", str, SortEngine.TPU_SEGMENTED, "")
    SORT_SPILL_THRESHOLD = ConfigOption("sort-spill-threshold", int, None, "")
    WRITE_BATCH_ROWS = ConfigOption("tpu.write-batch-rows", int, 1 << 20,
                                    "Device merge batch rows (ours)")
    KEY_PREFIX_LANES = ConfigOption("tpu.key-prefix-lanes", int, 2,
                                    "u64 lanes of normalized key prefix (ours)")
    MERGE_STREAM_THRESHOLD_ROWS = ConfigOption(
        "tpu.merge.stream-threshold-rows", int, 8 << 20,
        "Above this many input rows a compaction merges in streamed key "
        "windows instead of one whole-bucket kernel: the streamed "
        "pipeline overlaps decode/encode with the merge (measured ~1.4x "
        "host-side at 8M rows) and bounds memory; windows stay "
        "chunk-rows-sized, large enough to amortize device transfers "
        "when the link-adaptive model offloads (ours)")
    MERGE_CHUNK_ROWS = ConfigOption(
        "tpu.merge.chunk-rows", int, 2 << 20,
        "Decoded chunk rows per run for the streamed merge (ours)")
    BRANCH = ConfigOption("branch", str, "main", "")
    METASTORE_PARTITIONED_TABLE = ConfigOption("metastore.partitioned-table",
                                               _parse_bool, False, "")
    PRIMARY_KEY = ConfigOption("primary-key", str, None,
                               "Comma-separated pk (schema-level)")
    PARTITION = ConfigOption("partition", str, None, "")
    TYPE = ConfigOption("type", str, "table", "")
    AUTO_CREATE = ConfigOption("auto-create", _parse_bool, False, "")
    COMMIT_USER_PREFIX = ConfigOption("commit.user-prefix", str, None, "")
    COMMIT_FORCE_COMPACT = ConfigOption("commit.force-compact", _parse_bool,
                                        False, "")
    LOOKUP_CACHE_MAX_DISK_SIZE = ConfigOption("lookup.cache-max-disk-size",
                                              parse_memory_size,
                                              9223372036854775807, "")
    RECORD_LEVEL_EXPIRE_TIME = ConfigOption("record-level.expire-time",
                                            _parse_duration_ms, None, "")
    RECORD_LEVEL_TIME_FIELD = ConfigOption("record-level.time-field", str,
                                           None, "")
    FIELDS_DEFAULT_AGG_FUNC = ConfigOption("fields.default-aggregate-function",
                                           str, None, "")
    PARTITION_EXPIRATION_TIME = ConfigOption("partition.expiration-time",
                                             _parse_duration_ms, None, "")
    PARTITION_EXPIRATION_CHECK_INTERVAL = ConfigOption(
        "partition.expiration-check-interval", _parse_duration_ms,
        3600000, "")
    PARTITION_TIMESTAMP_FORMATTER = ConfigOption(
        "partition.timestamp-formatter", str, None, "")
    PARTITION_TIMESTAMP_PATTERN = ConfigOption(
        "partition.timestamp-pattern", str, None, "")
    PARTITION_MARK_DONE_ACTION = ConfigOption(
        "partition.mark-done-action", str, "success-file",
        "csv of success-file|done-partition|mark-event|http-report|custom")
    PARTITION_MARK_DONE_CUSTOM_CLASS = ConfigOption(
        "partition.mark-done-action.custom.class", str, None,
        "module:Class implementing PartitionMarkDoneAction")
    PARTITION_MARK_DONE_HTTP_URL = ConfigOption(
        "partition.mark-done-action.http.url", str, None, "")
    PARTITION_MARK_DONE_HTTP_PARAMS = ConfigOption(
        "partition.mark-done-action.http.params", str, None, "")
    PARTITION_MARK_DONE_WHEN_END_INPUT = ConfigOption(
        "partition.mark-done-when-end-input", _parse_bool, False, "")
    PARTITION_IDLE_TIME_TO_DONE = ConfigOption(
        "partition.idle-time-to-done", _parse_duration_ms, None, "")
    PARTITION_TIME_INTERVAL = ConfigOption(
        "partition.time-interval", _parse_duration_ms, None, "")
    TAG_AUTOMATIC_CREATION = ConfigOption("tag.automatic-creation", str,
                                          "none", "")
    FILE_INDEX_BLOOM_COLUMNS = ConfigOption(
        "file-index.bloom-filter.columns", str, None,
        "Columns to build per-file bloom filters for")
    FILE_INDEX_BLOOM_FPP = ConfigOption(
        "file-index.bloom-filter.fpp", float, 0.01, "")
    FILE_INDEX_IN_MANIFEST_THRESHOLD = ConfigOption(
        "file-index.in-manifest-threshold", parse_memory_size, 500, "")
    FILE_INDEX_BITMAP_COLUMNS = ConfigOption(
        "file-index.bitmap.columns", str, None,
        "Columns to build per-file value->row-position bitmap indexes "
        "for (reference fileindex/bitmap/BitmapFileIndex.java)")
    FILE_INDEX_BSI_COLUMNS = ConfigOption(
        "file-index.bsi.columns", str, None,
        "Integer columns to build per-file bit-sliced indexes for "
        "(reference fileindex/bsi/BitSliceIndexBitmap.java)")
    FILE_INDEX_RANGE_BITMAP_COLUMNS = ConfigOption(
        "file-index.range-bitmap.columns", str, None,
        "Numeric columns to build per-file range-encoded bin bitmaps "
        "for (reference fileindex/rangebitmap/RangeBitmap.java)")
    ROW_TRACKING_ENABLED = ConfigOption("row-tracking.enabled", _parse_bool,
                                        False, "")
    DATA_EVOLUTION_ENABLED = ConfigOption("data-evolution.enabled",
                                          _parse_bool, False, "")
    FORCE_LOOKUP = ConfigOption("force-lookup", _parse_bool, False, "")
    LOCAL_MERGE_BUFFER_SIZE = ConfigOption("local-merge-buffer-size",
                                           parse_memory_size, None, "")
    METADATA_STATS_MODE = ConfigOption("metadata.stats-mode", str, "truncate(16)", "")
    MANIFEST_COMPRESSION = ConfigOption("manifest.compression", str, "zstd", "")

    # -- commit / retry (reference CoreOptions.java:919-933) -----------------
    COMMIT_MAX_RETRIES = ConfigOption(
        "commit.max-retries", int, 10,
        "CAS attempts before the commit raises a conflict")
    COMMIT_MIN_RETRY_WAIT = ConfigOption(
        "commit.min-retry-wait", _parse_duration_ms, 10, "")
    COMMIT_MAX_RETRY_WAIT = ConfigOption(
        "commit.max-retry-wait", _parse_duration_ms, 10_000, "")
    COMMIT_FORCE_CREATE_SNAPSHOT = ConfigOption(
        "commit.force-create-snapshot", _parse_bool, False, "")
    SNAPSHOT_IGNORE_EMPTY_COMMIT = ConfigOption(
        "snapshot.ignore-empty-commit", _parse_bool, None,
        "Skip the snapshot when a commit carries no changes (defaults "
        "on for batch writers, off for streaming exactly-once "
        "progress; reference CoreOptions.java:2497)")

    # -- scan / read (reference CoreOptions.java:1416,2120-2200) -------------
    SCAN_PLAN_SORT_PARTITION = ConfigOption(
        "scan.plan-sort-partition", _parse_bool, False,
        "Sort plan splits by partition value")
    SCAN_BOUNDED_WATERMARK = ConfigOption(
        "scan.bounded.watermark", int, None,
        "End a stream once a snapshot watermark passes this bound")
    STREAMING_READ_OVERWRITE = ConfigOption(
        "streaming-read-overwrite", _parse_bool, False,
        "Follow-up scanners also read OVERWRITE snapshots' deltas")
    CONSUMER_IGNORE_PROGRESS = ConfigOption(
        "consumer.ignore-progress", _parse_bool, False,
        "Start fresh instead of resuming the consumer's progress")

    # -- sequence / merge (reference CoreOptions.java:1090) ------------------
    SEQUENCE_FIELD_SORT_ORDER = ConfigOption(
        "sequence.field.sort-order", str, "ascending",
        "ascending: larger sequence wins; descending: smaller wins")
    PARTIAL_UPDATE_REMOVE_RECORD_ON_DELETE = ConfigOption(
        "partial-update.remove-record-on-delete", _parse_bool, False,
        "-D on a partial-update table drops the whole row instead of "
        "being ignored")

    # -- compaction tuning (reference CoreOptions.java:1018-1080) ------------
    COMPACTION_TOTAL_SIZE_THRESHOLD = ConfigOption(
        "compaction.total-size-threshold", parse_memory_size, None,
        "Full-compact a bucket whenever its total size is below this")
    COMPACTION_FILE_NUM_LIMIT = ConfigOption(
        "compaction.file-num-limit", int, None,
        "Force a compaction pick once a bucket holds this many files")

    # -- changelog files (reference CoreOptions.java:640-690) ----------------
    CHANGELOG_FILE_FORMAT = ConfigOption(
        "changelog-file.format", str, None,
        "Changelog files' format; defaults to file.format")
    CHANGELOG_FILE_COMPRESSION = ConfigOption(
        "changelog-file.compression", str, None,
        "Changelog files' compression; defaults to file.compression")
    CHANGELOG_FILE_PREFIX = ConfigOption("changelog-file.prefix", str,
                                         "changelog-", "")

    # -- maintenance (reference CoreOptions.java:1330-1340) ------------------
    PARTITION_EXPIRATION_MAX_NUM = ConfigOption(
        "partition.expiration-max-num", int, 100,
        "Partitions expired per expire_partitions() call, oldest first")

    # -- manifests (reference CoreOptions.java:560-600) ----------------------
    MANIFEST_TARGET_FILE_SIZE = ConfigOption(
        "manifest.target-file-size", parse_memory_size, 8 << 20, "")
    SCAN_MANIFEST_PARALLELISM = ConfigOption(
        "scan.manifest.parallelism", int, None,
        "Threads for reading manifest files during scan planning "
        "(None = serial)")
    SNAPSHOT_CLEAN_EMPTY_DIRECTORIES = ConfigOption(
        "snapshot.clean-empty-directories", _parse_bool, False,
        "Remove emptied partition/bucket directories after snapshot "
        "expiration")
    DELETE_FILE_THREAD_NUM = ConfigOption(
        "delete-file.thread-num", int, None,
        "Threads for deleting dead files during snapshot expiration "
        "(None = serial)")

    # -- source splits (reference CoreOptions.java:2230-2250) ----------------
    SOURCE_SPLIT_TARGET_SIZE = ConfigOption(
        "source.split.target-size", parse_memory_size, 128 << 20,
        "Append-table buckets bin into splits of about this size")
    SOURCE_SPLIT_OPEN_FILE_COST = ConfigOption(
        "source.split.open-file-cost", parse_memory_size, 4 << 20, "")

    def __init__(self, options):
        if isinstance(options, dict):
            options = Options(options)
        self.options: Options = options

    # -- convenience accessors ----------------------------------------------

    def get(self, option: ConfigOption):
        return self.options.get(option)

    @property
    def bucket(self) -> int:
        return self.options.get(CoreOptions.BUCKET)

    @property
    def bucket_key(self):
        v = self.options.get(CoreOptions.BUCKET_KEY)
        return [s.strip() for s in v.split(",")] if v else []

    @property
    def file_format(self) -> str:
        return self.options.get(CoreOptions.FILE_FORMAT)

    @property
    def file_format_per_level(self):
        """{level: format} overrides (reference
        CoreOptions.fileFormatPerLevel)."""
        v = self.options.get(CoreOptions.FILE_FORMAT_PER_LEVEL)
        out = {}
        if v:
            for part in v.split(","):
                lvl, sep, fmt = part.partition(":")
                if not sep or not fmt.strip() or not lvl.strip():
                    raise ValueError(
                        f"file.format.per.level entry {part!r} must be "
                        f"'<level>:<format>' (e.g. '0:avro,5:parquet')")
                try:
                    level = int(lvl.strip())
                except ValueError:
                    raise ValueError(
                        f"file.format.per.level level {lvl.strip()!r} "
                        f"is not an integer") from None
                out[level] = fmt.strip().lower()
        return out

    @property
    def format_options(self):
        """Raw format-writer tuning options, forwarded to the format SPI
        (reference FileFormat factories receive the full options and
        read their own prefix, e.g. parquet.enable.dictionary)."""
        return {k: v for k, v in self.options._map.items()
                if k.startswith(("parquet.", "orc.", "avro."))}

    @property
    def file_compression(self) -> str:
        codec = self.options.get(CoreOptions.FILE_COMPRESSION)
        level = self.options.get(CoreOptions.FILE_COMPRESSION_ZSTD_LEVEL)
        if level is not None and codec == "zstd":
            # "codec:level" spec understood by the format writers
            return f"zstd:{level}"
        return codec

    @property
    def merge_engine(self) -> str:
        return self.options.get(CoreOptions.MERGE_ENGINE)

    @property
    def changelog_producer(self) -> str:
        return self.options.get(CoreOptions.CHANGELOG_PRODUCER)

    @property
    def sequence_field(self):
        v = self.options.get(CoreOptions.SEQUENCE_FIELD)
        return [s.strip() for s in v.split(",")] if v else []

    @property
    def sequence_field_descending(self) -> bool:
        return self.options.get(
            CoreOptions.SEQUENCE_FIELD_SORT_ORDER) == "descending"

    @property
    def changelog_file_format(self) -> str:
        return self.options.get(CoreOptions.CHANGELOG_FILE_FORMAT) or \
            self.file_format

    @property
    def changelog_file_compression(self) -> str:
        return self.options.get(
            CoreOptions.CHANGELOG_FILE_COMPRESSION) or \
            self.file_compression

    @property
    def changelog_file_prefix(self) -> str:
        return self.options.get(CoreOptions.CHANGELOG_FILE_PREFIX)

    @property
    def target_file_size(self) -> int:
        return self.options.get(CoreOptions.TARGET_FILE_SIZE)

    @property
    def write_buffer_size(self) -> int:
        return self.options.get(CoreOptions.WRITE_BUFFER_SIZE)

    @property
    def write_only(self) -> bool:
        return self.options.get(CoreOptions.WRITE_ONLY)

    @property
    def num_sorted_runs_compaction_trigger(self) -> int:
        return self.options.get(CoreOptions.NUM_SORTED_RUNS_COMPACTION_TRIGGER)

    @property
    def num_sorted_runs_stop_trigger(self) -> int:
        v = self.options.get(CoreOptions.NUM_SORTED_RUNS_STOP_TRIGGER)
        if v is None:
            return self.num_sorted_runs_compaction_trigger + 3
        return v

    @property
    def num_levels(self) -> int:
        v = self.options.get(CoreOptions.NUM_LEVELS)
        if v is None:
            return self.num_sorted_runs_compaction_trigger + 1
        return v

    @property
    def max_level(self) -> int:
        """The LSM's top level — the single definition shared by the
        read-optimized view (system.py, iceberg/metadata.py) and the
        sharded compaction/rescale output level."""
        return self.num_levels - 1

    @property
    def max_size_amplification_percent(self) -> int:
        return self.options.get(
            CoreOptions.COMPACTION_MAX_SIZE_AMPLIFICATION_PERCENT)

    @property
    def size_ratio(self) -> int:
        return self.options.get(CoreOptions.COMPACTION_SIZE_RATIO)

    @property
    def compaction_min_file_num(self) -> int:
        return self.options.get(CoreOptions.COMPACTION_MIN_FILE_NUM)

    @property
    def bloom_filter_columns(self):
        v = self.options.get(CoreOptions.FILE_INDEX_BLOOM_COLUMNS)
        return [c.strip() for c in v.split(",")] if v else []

    @property
    def file_index_spec(self):
        """index-type name -> column list, for every configured
        file-index kind (consumed by index/file_index.py)."""
        spec = {}
        for name, opt in (
                ("bloom-filter", CoreOptions.FILE_INDEX_BLOOM_COLUMNS),
                ("bitmap", CoreOptions.FILE_INDEX_BITMAP_COLUMNS),
                ("bsi", CoreOptions.FILE_INDEX_BSI_COLUMNS),
                ("range-bitmap",
                 CoreOptions.FILE_INDEX_RANGE_BITMAP_COLUMNS)):
            v = self.options.get(opt)
            cols = [c.strip() for c in v.split(",") if c.strip()] \
                if v else []
            if cols:
                spec[name] = cols
        return spec

    @property
    def deletion_vectors_enabled(self) -> bool:
        return self.options.get(CoreOptions.DELETION_VECTORS_ENABLED)

    @property
    def snapshot_num_retained_min(self) -> int:
        return self.options.get(CoreOptions.SNAPSHOT_NUM_RETAINED_MIN)

    @property
    def snapshot_num_retained_max(self) -> int:
        return self.options.get(CoreOptions.SNAPSHOT_NUM_RETAINED_MAX)

    @property
    def snapshot_time_retained_ms(self) -> int:
        return self.options.get(CoreOptions.SNAPSHOT_TIME_RETAINED)

    @property
    def branch(self) -> str:
        return self.options.get(CoreOptions.BRANCH)

    @property
    def scan_mode(self) -> str:
        return self.options.get(CoreOptions.SCAN_MODE)

    @property
    def consumer_id(self):
        return self.options.get(CoreOptions.CONSUMER_ID)

    @property
    def startup_mode(self) -> str:
        mode = self.options.get(CoreOptions.SCAN_MODE)
        if mode == StartupMode.DEFAULT:
            if self.options.get(CoreOptions.SCAN_SNAPSHOT_ID) is not None:
                return StartupMode.FROM_SNAPSHOT
            if self.options.get(CoreOptions.SCAN_TIMESTAMP_MILLIS) is not None:
                return StartupMode.FROM_TIMESTAMP
            if self.options.get(CoreOptions.INCREMENTAL_BETWEEN) is not None:
                return StartupMode.INCREMENTAL
            return StartupMode.LATEST_FULL
        return mode

    @property
    def key_prefix_lanes(self) -> int:
        return self.options.get(CoreOptions.KEY_PREFIX_LANES)

    @property
    def write_batch_rows(self) -> int:
        return self.options.get(CoreOptions.WRITE_BATCH_ROWS)

    @property
    def dynamic_bucket_target_row_num(self) -> int:
        return self.options.get(CoreOptions.DYNAMIC_BUCKET_TARGET_ROW_NUM)

    @property
    def full_compaction_delta_commits(self):
        return self.options.get(CoreOptions.FULL_COMPACTION_DELTA_COMMITS)

    @property
    def record_level_expire_time_ms(self):
        return self.options.get(CoreOptions.RECORD_LEVEL_EXPIRE_TIME)

    @property
    def record_level_time_field(self):
        return self.options.get(CoreOptions.RECORD_LEVEL_TIME_FIELD)

    def to_map(self) -> Dict[str, str]:
        return self.options.to_map()
