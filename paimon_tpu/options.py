"""Config system.

Analog of the reference's typed option system
(paimon-api/.../options/ConfigOption.java, Options.java) and the table-level
``CoreOptions`` (paimon-api/.../CoreOptions.java, 5498 lines). Only options
with behavior in this framework are declared; unknown keys round-trip through
``Options`` untouched so schemas remain forward-compatible.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, Iterable, Optional

__all__ = ["ConfigOption", "Options", "CoreOptions", "MergeEngine",
           "ChangelogProducer", "StartupMode", "SortEngine", "BucketMode",
           "MemorySize", "parse_memory_size"]


_SIZE_RE = re.compile(r"^\s*(\d+)\s*([kKmMgGtT]?)[bB]?\s*$")
_UNITS = {"": 1, "k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}


def parse_memory_size(v) -> int:
    """'128 mb' / '1g' / 1024 -> bytes (reference options/MemorySize.java)."""
    if isinstance(v, int):
        return v
    m = _SIZE_RE.match(str(v))
    if not m:
        raise ValueError(f"Cannot parse memory size: {v!r}")
    return int(m.group(1)) * _UNITS[m.group(2).lower()]


MemorySize = parse_memory_size


def _parse_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).lower() in ("true", "1", "yes")


def _validate_enum(v, allowed):
    s = str(v).upper()
    if s not in allowed:
        raise ValueError(f"{v!r} not in {allowed}")
    return s


def _enum(*allowed):
    """Named enum validator (the name renders in generated docs)."""
    def validate(v):
        return _validate_enum(v, allowed)
    validate.__name__ = "enum[" + "|".join(allowed) + "]"
    return validate


def _parse_duration_ms(v) -> int:
    """'1 s' / '5 min' / '100ms' -> milliseconds."""
    if isinstance(v, (int, float)):
        return int(v)
    s = str(v).strip().lower()
    m = re.match(r"^(\d+)\s*([a-z]*)$", s)
    if not m:
        raise ValueError(f"Cannot parse duration: {v!r}")
    n, unit = int(m.group(1)), m.group(2)
    mult = {"": 1, "ms": 1, "s": 1000, "sec": 1000, "min": 60000,
            "m": 60000, "h": 3600000, "d": 86400000}[unit]
    return n * mult


class ConfigOption:
    """A typed option with key, default, and description."""

    def __init__(self, key: str, typ: Callable[[Any], Any], default: Any,
                 description: str = ""):
        self.key = key
        self.typ = typ
        self.default = default
        self.description = description

    def parse(self, raw: Any) -> Any:
        if raw is None:
            return self.default
        return self.typ(raw)

    def __repr__(self):
        return f"ConfigOption({self.key!r}, default={self.default!r})"


class Options:
    """String->string map with typed access (reference options/Options.java)."""

    def __init__(self, conf: Optional[Dict[str, Any]] = None):
        self._map: Dict[str, str] = {}
        if conf:
            for k, v in conf.items():
                self.set(k, v)

    def set(self, key, value) -> "Options":
        if isinstance(key, ConfigOption):
            key = key.key
        self._map[key] = str(value) if not isinstance(value, str) else value
        return self

    def get(self, option):
        if isinstance(option, ConfigOption):
            return option.parse(self._map.get(option.key))
        return self._map.get(option)

    def get_or(self, key: str, default):
        return self._map.get(key, default)

    def contains(self, key) -> bool:
        if isinstance(key, ConfigOption):
            key = key.key
        return key in self._map

    def remove(self, key: str):
        self._map.pop(key, None)

    def keys(self) -> Iterable[str]:
        return self._map.keys()

    def to_map(self) -> Dict[str, str]:
        return dict(self._map)

    def copy(self) -> "Options":
        return Options(dict(self._map))

    def __eq__(self, other):
        return isinstance(other, Options) and self._map == other._map

    def __repr__(self):
        return f"Options({self._map})"


# -- enums (reference CoreOptions.java:4590,4619,4759) -----------------------

class MergeEngine:
    DEDUPLICATE = "deduplicate"
    PARTIAL_UPDATE = "partial-update"
    AGGREGATE = "aggregation"
    FIRST_ROW = "first-row"


class ChangelogProducer:
    NONE = "none"
    INPUT = "input"
    FULL_COMPACTION = "full-compaction"
    LOOKUP = "lookup"


class StartupMode:
    DEFAULT = "default"
    LATEST_FULL = "latest-full"
    FULL = "full"
    LATEST = "latest"
    COMPACTED_FULL = "compacted-full"
    FROM_TIMESTAMP = "from-timestamp"
    FROM_FILE_CREATION_TIME = "from-file-creation-time"
    FROM_SNAPSHOT = "from-snapshot"
    FROM_SNAPSHOT_FULL = "from-snapshot-full"
    INCREMENTAL = "incremental"


class SortEngine:
    LOSER_TREE = "loser-tree"     # reference default
    MIN_HEAP = "min-heap"
    TPU_SEGMENTED = "tpu-segmented"  # ours: device sort + segmented reduce


class BucketMode:
    """reference paimon-common/.../table/BucketMode.java:30"""
    HASH_FIXED = "hash-fixed"
    HASH_DYNAMIC = "hash-dynamic"
    KEY_DYNAMIC = "key-dynamic"
    BUCKET_UNAWARE = "bucket-unaware"
    POSTPONE = "postpone"

    POSTPONE_BUCKET = -2
    UNAWARE_BUCKET = -1


class CoreOptions:
    """Typed view over table options (reference CoreOptions.java)."""

    BUCKET = ConfigOption("bucket", int, -1, "Bucket count; -1 = unaware/dynamic")
    BUCKET_KEY = ConfigOption("bucket-key", str, None, "Comma-separated bucket key")
    PATH = ConfigOption("path", str, None, "Table path")
    FILE_FORMAT = ConfigOption("file.format", str, "parquet", "Data file format")
    FILE_FORMAT_PER_LEVEL = ConfigOption(
        "file.format.per.level", str, None,
        "Per-LSM-level format overrides, e.g. '0:avro,5:parquet' — "
        "fast row codec for hot L0, columnar for settled levels "
        "(reference CoreOptions file.format.per.level)")
    FILE_COMPRESSION_ZSTD_LEVEL = ConfigOption(
        "file.compression.zstd-level", int, None,
        "zstd level for data files (reference CoreOptions"
        ".FILE_COMPRESSION_ZSTD_LEVEL); None = codec default")
    FILE_COMPRESSION = ConfigOption("file.compression", str, "zstd",
                                    "Data file compression")
    MANIFEST_FORMAT = ConfigOption("manifest.format", str, "avro",
                                   "Manifest file format")
    MANIFEST_MERGE_MIN_COUNT = ConfigOption("manifest.merge-min-count", int, 30,
                                            "Min manifests to trigger full rewrite")
    MERGE_ENGINE = ConfigOption("merge-engine", str, MergeEngine.DEDUPLICATE,
                                "deduplicate | partial-update | aggregation | first-row")
    IGNORE_DELETE = ConfigOption("ignore-delete", _parse_bool, False, "")
    CHANGELOG_PRODUCER = ConfigOption("changelog-producer", str,
                                      ChangelogProducer.NONE, "")
    SEQUENCE_FIELD = ConfigOption("sequence.field", str, None,
                                  "User-defined sequence column(s)")
    ROWKIND_FIELD = ConfigOption("rowkind.field", str, None, "")
    PARTITION_DEFAULT_NAME = ConfigOption("partition.default-name", str,
                                          "__DEFAULT_PARTITION__", "")
    TARGET_FILE_SIZE = ConfigOption("target-file-size", parse_memory_size,
                                    128 << 20, "Target data file size")
    WRITE_BUFFER_SPILLABLE = ConfigOption(
        "write-buffer-spillable", _parse_bool, False,
        "Primary-key writers only: spill full write buffers to local "
        "sorted runs (zstd Arrow IPC) and merge them into L0 at "
        "prepare-commit — fewer, larger L0 files than flushing one "
        "file per buffer-full")
    WRITE_BUFFER_SIZE = ConfigOption("write-buffer-size", parse_memory_size,
                                     256 << 20, "Sort buffer memory")
    WRITE_ONLY = ConfigOption("write-only", _parse_bool, False,
                              "Skip compaction on write")
    NUM_SORTED_RUNS_COMPACTION_TRIGGER = ConfigOption(
        "num-sorted-run.compaction-trigger", int, 5,
        "Sorted runs triggering compaction (reference CoreOptions.java:876)")
    NUM_SORTED_RUNS_STOP_TRIGGER = ConfigOption(
        "num-sorted-run.stop-trigger", int, None, "Write-stall threshold")
    NUM_LEVELS = ConfigOption("num-levels", int, None, "LSM levels")
    COMPACTION_MAX_SIZE_AMPLIFICATION_PERCENT = ConfigOption(
        "compaction.max-size-amplification-percent", int, 200, "")
    COMPACTION_SIZE_RATIO = ConfigOption("compaction.size-ratio", int, 1, "")
    COMPACTION_MIN_FILE_NUM = ConfigOption("compaction.min.file-num", int, 5, "")
    COMPACTION_OPTIMIZATION_INTERVAL = ConfigOption(
        "compaction.optimization-interval", _parse_duration_ms, None, "")
    FULL_COMPACTION_DELTA_COMMITS = ConfigOption(
        "full-compaction.delta-commits", int, None, "")
    SNAPSHOT_NUM_RETAINED_MIN = ConfigOption("snapshot.num-retained.min",
                                             int, 10, "")
    SNAPSHOT_NUM_RETAINED_MAX = ConfigOption("snapshot.num-retained.max",
                                             int, 2147483647, "")
    SNAPSHOT_TIME_RETAINED = ConfigOption("snapshot.time-retained",
                                          _parse_duration_ms, 3600000, "")
    SNAPSHOT_EXPIRE_LIMIT = ConfigOption("snapshot.expire.limit", int, 50, "")
    CHANGELOG_NUM_RETAINED_MIN = ConfigOption("changelog.num-retained.min",
                                              int, None, "")
    CHANGELOG_NUM_RETAINED_MAX = ConfigOption("changelog.num-retained.max",
                                              int, None, "")
    SCAN_MODE = ConfigOption("scan.mode", str, StartupMode.DEFAULT, "")
    SCAN_SNAPSHOT_ID = ConfigOption("scan.snapshot-id", int, None, "")
    SCAN_TAG_NAME = ConfigOption("scan.tag-name", str, None, "")
    SCAN_TIMESTAMP_MILLIS = ConfigOption("scan.timestamp-millis", int, None, "")
    SCAN_FALLBACK_BRANCH = ConfigOption("scan.fallback-branch", str, None, "")
    INCREMENTAL_BETWEEN = ConfigOption("incremental-between", str, None, "")
    CONSUMER_ID = ConfigOption("consumer-id", str, None, "")
    CONSUMER_EXPIRATION_TIME = ConfigOption("consumer.expiration-time",
                                            _parse_duration_ms, None, "")
    # NOTE: reads always honor DVs once written (DELETE FROM); this flag
    # reserves the reference's compaction-time DV production mode
    DELETION_VECTORS_ENABLED = ConfigOption("deletion-vectors.enabled",
                                            _parse_bool, False, "")
    DYNAMIC_BUCKET_TARGET_ROW_NUM = ConfigOption(
        "dynamic-bucket.target-row-num", int, 2_000_000, "")
    DYNAMIC_BUCKET_INITIAL_BUCKETS = ConfigOption(
        "dynamic-bucket.initial-buckets", int, None, "")
    DYNAMIC_BUCKET_ASSIGNER_PARALLELISM = ConfigOption(
        "dynamic-bucket.assigner-parallelism", int, None, "")
    SORT_ENGINE = ConfigOption("sort-engine", str, SortEngine.TPU_SEGMENTED, "")
    SORT_SPILL_THRESHOLD = ConfigOption("sort-spill-threshold", int, None, "")
    WRITE_BATCH_ROWS = ConfigOption("tpu.write-batch-rows", int, 1 << 20,
                                    "Device merge batch rows (ours)")
    KEY_PREFIX_LANES = ConfigOption("tpu.key-prefix-lanes", int, 2,
                                    "u64 lanes of normalized key prefix (ours)")
    MERGE_STREAM_THRESHOLD_ROWS = ConfigOption(
        "tpu.merge.stream-threshold-rows", int, 8 << 20,
        "Above this many input rows a compaction merges in streamed key "
        "windows instead of one whole-bucket kernel: the streamed "
        "pipeline overlaps decode/encode with the merge (measured ~1.4x "
        "host-side at 8M rows) and bounds memory; windows stay "
        "chunk-rows-sized, large enough to amortize device transfers "
        "when the link-adaptive model offloads (ours)")
    MERGE_CHUNK_ROWS = ConfigOption(
        "tpu.merge.chunk-rows", int, 4 << 20,
        "Decoded chunk rows per run for the streamed merge (ours); "
        "larger windows amortize per-window sync/flush overhead "
        "(~20% at 30M rows/10 runs measured in-env) at ~runs x rows "
        "x row-bytes peak memory")
    MERGE_WINDOW_ROWS = ConfigOption(
        "tpu.merge.window-rows", int, 1 << 18,
        "Per-run row cap of one streamed merge key window (ours): the "
        "window bound is lowered to the smallest buffered key at this "
        "row index, so a window carries ~runs x this many rows and "
        "adjacent windows overlap on the merge workers instead of one "
        "window swallowing the whole bucket; a key group wider than "
        "the cap falls back to the natural bound (keys never straddle "
        "windows)")
    MESH_COMPACT = ConfigOption(
        "tpu.mesh.compact", _parse_bool, False,
        "Route full compactions of primary-key tables through the "
        "streaming mesh engine (parallel/mesh_engine.py): all buckets "
        "compact in one mesh program, streamed in bounded key windows "
        "with skew-aware bucket->device packing (ours)")
    MESH_WINDOW_ROWS = ConfigOption(
        "tpu.mesh.window-rows", int, 1 << 20,
        "Decoded chunk rows per sorted run for the mesh engine's "
        "bounded key-window streaming; per-bucket peak host memory is "
        "~ runs x window-rows x row-bytes, independent of bucket size "
        "(ours)")
    BRANCH = ConfigOption("branch", str, "main", "")
    METASTORE_PARTITIONED_TABLE = ConfigOption("metastore.partitioned-table",
                                               _parse_bool, False, "")
    PRIMARY_KEY = ConfigOption("primary-key", str, None,
                               "Comma-separated pk (schema-level)")
    PARTITION = ConfigOption("partition", str, None, "")
    TYPE = ConfigOption("type", str, "table", "")
    AUTO_CREATE = ConfigOption("auto-create", _parse_bool, False, "")
    COMMIT_USER_PREFIX = ConfigOption("commit.user-prefix", str, None, "")
    COMMIT_FORCE_COMPACT = ConfigOption("commit.force-compact", _parse_bool,
                                        False, "")
    LOOKUP_CACHE_MAX_DISK_SIZE = ConfigOption("lookup.cache-max-disk-size",
                                              parse_memory_size,
                                              9223372036854775807, "")
    RECORD_LEVEL_EXPIRE_TIME = ConfigOption("record-level.expire-time",
                                            _parse_duration_ms, None, "")
    RECORD_LEVEL_TIME_FIELD = ConfigOption("record-level.time-field", str,
                                           None, "")
    FIELDS_DEFAULT_AGG_FUNC = ConfigOption("fields.default-aggregate-function",
                                           str, None, "")
    PARTITION_EXPIRATION_TIME = ConfigOption("partition.expiration-time",
                                             _parse_duration_ms, None, "")
    PARTITION_EXPIRATION_CHECK_INTERVAL = ConfigOption(
        "partition.expiration-check-interval", _parse_duration_ms,
        3600000, "")
    PARTITION_TIMESTAMP_FORMATTER = ConfigOption(
        "partition.timestamp-formatter", str, None, "")
    PARTITION_TIMESTAMP_PATTERN = ConfigOption(
        "partition.timestamp-pattern", str, None, "")
    PARTITION_MARK_DONE_ACTION = ConfigOption(
        "partition.mark-done-action", str, "success-file",
        "csv of success-file|done-partition|mark-event|http-report|custom")
    PARTITION_MARK_DONE_CUSTOM_CLASS = ConfigOption(
        "partition.mark-done-action.custom.class", str, None,
        "module:Class implementing PartitionMarkDoneAction")
    PARTITION_MARK_DONE_HTTP_URL = ConfigOption(
        "partition.mark-done-action.http.url", str, None, "")
    PARTITION_MARK_DONE_HTTP_PARAMS = ConfigOption(
        "partition.mark-done-action.http.params", str, None, "")
    PARTITION_MARK_DONE_WHEN_END_INPUT = ConfigOption(
        "partition.mark-done-when-end-input", _parse_bool, False, "")
    PARTITION_IDLE_TIME_TO_DONE = ConfigOption(
        "partition.idle-time-to-done", _parse_duration_ms, None, "")
    PARTITION_TIME_INTERVAL = ConfigOption(
        "partition.time-interval", _parse_duration_ms, None, "")
    TAG_AUTOMATIC_CREATION = ConfigOption("tag.automatic-creation", str,
                                          "none", "")
    FILE_INDEX_BLOOM_COLUMNS = ConfigOption(
        "file-index.bloom-filter.columns", str, None,
        "Columns to build per-file bloom filters for")
    FILE_INDEX_BLOOM_FPP = ConfigOption(
        "file-index.bloom-filter.fpp", float, 0.01, "")
    FILE_INDEX_IN_MANIFEST_THRESHOLD = ConfigOption(
        "file-index.in-manifest-threshold", parse_memory_size, 500, "")
    FILE_INDEX_BITMAP_COLUMNS = ConfigOption(
        "file-index.bitmap.columns", str, None,
        "Columns to build per-file value->row-position bitmap indexes "
        "for (reference fileindex/bitmap/BitmapFileIndex.java)")
    FILE_INDEX_BSI_COLUMNS = ConfigOption(
        "file-index.bsi.columns", str, None,
        "Integer columns to build per-file bit-sliced indexes for "
        "(reference fileindex/bsi/BitSliceIndexBitmap.java)")
    FILE_INDEX_RANGE_BITMAP_COLUMNS = ConfigOption(
        "file-index.range-bitmap.columns", str, None,
        "Numeric columns to build per-file range-encoded bin bitmaps "
        "for (reference fileindex/rangebitmap/RangeBitmap.java)")
    ROW_TRACKING_ENABLED = ConfigOption("row-tracking.enabled", _parse_bool,
                                        False, "")
    DATA_EVOLUTION_ENABLED = ConfigOption("data-evolution.enabled",
                                          _parse_bool, False, "")
    FORCE_LOOKUP = ConfigOption("force-lookup", _parse_bool, False, "")
    LOCAL_MERGE_BUFFER_SIZE = ConfigOption("local-merge-buffer-size",
                                           parse_memory_size, None, "")
    METADATA_STATS_MODE = ConfigOption("metadata.stats-mode", str, "truncate(16)", "")
    MANIFEST_COMPRESSION = ConfigOption("manifest.compression", str, "zstd", "")

    # -- commit / retry (reference CoreOptions.java:919-933) -----------------
    COMMIT_MAX_RETRIES = ConfigOption(
        "commit.max-retries", int, 10,
        "CAS attempts before the commit raises a conflict")
    COMMIT_MIN_RETRY_WAIT = ConfigOption(
        "commit.min-retry-wait", _parse_duration_ms, 10, "")
    COMMIT_MAX_RETRY_WAIT = ConfigOption(
        "commit.max-retry-wait", _parse_duration_ms, 10_000, "")
    COMMIT_FORCE_CREATE_SNAPSHOT = ConfigOption(
        "commit.force-create-snapshot", _parse_bool, False, "")
    SNAPSHOT_IGNORE_EMPTY_COMMIT = ConfigOption(
        "snapshot.ignore-empty-commit", _parse_bool, None,
        "Skip the snapshot when a commit carries no changes (defaults "
        "on for batch writers, off for streaming exactly-once "
        "progress; reference CoreOptions.java:2497)")

    # -- maintenance fault tolerance (ours) ----------------------------------
    COMPACTION_RETRY_MAX_ATTEMPTS = ConfigOption(
        "compaction.retry.max-attempts", int, 3,
        "Per-bucket attempts a mesh compaction makes on a transient "
        "failure (503 storms, injected IO faults, lane/device loss) "
        "before degrading that bucket to the single-chip path")
    COMPACTION_RETRY_BACKOFF = ConfigOption(
        "compaction.retry.backoff", _parse_duration_ms, 10,
        "Base wait between per-bucket compaction retries; actual "
        "waits use capped decorrelated jitter (utils/backoff.py)")
    COMPACTION_MESH_FALLBACK = ConfigOption(
        "compaction.mesh.fallback", _parse_bool, True,
        "After retries are exhausted, degrade the failing bucket to "
        "the single-chip compact/manager.py path instead of failing "
        "the whole mesh job; false = raise once retries run out")

    # -- pipelined merge-on-read scan (ours; parallel/scan_pipeline.py) ------
    SCAN_SPLIT_PARALLELISM = ConfigOption(
        "scan.split.parallelism", int, None,
        "Worker threads reading/decoding splits concurrently in the "
        "pipelined scan executor (Arrow C++ decode and file IO release "
        "the GIL); None = min(8, cpu count), 1 = serial read path")
    READ_PREFETCH_SPLITS = ConfigOption(
        "read.prefetch.splits", int, 2,
        "Extra splits submitted beyond the worker pool width so the "
        "next split's files download while the current one merges")
    READ_PREFETCH_MAX_BYTES = ConfigOption(
        "read.prefetch.max-bytes", parse_memory_size, 1 << 30,
        "Hard budget on the estimated bytes (sum of data-file sizes) "
        "of splits in flight at once; at least one split is always "
        "admitted so a budget below one split's size cannot stall")
    READ_RETRY_MAX_ATTEMPTS = ConfigOption(
        "read.retry.max-attempts", int, 3,
        "Attempts per data-file read on a transient store fault (503 "
        "storms, IO errors — parallel/fault.py taxonomy) before the "
        "scan raises; non-transient errors never retry")
    READ_RETRY_BACKOFF = ConfigOption(
        "read.retry.backoff", _parse_duration_ms, 10,
        "Base wait between data-file read retries; actual waits use "
        "capped decorrelated jitter (utils/backoff.py)")
    READ_CACHE_FOOTER = ConfigOption(
        "read.cache.footer", _parse_bool, True,
        "Cache parsed parquet footers of immutable data files in a "
        "process-wide LRU so repeated scans and lookup joins skip "
        "metadata decode (fs/caching.py)")
    READ_CACHE_RANGE = ConfigOption(
        "read.cache.range", _parse_bool, False,
        "Wrap the table's FileIO in a block-range cache keyed by "
        "(path, offset, length) for immutable files read by range "
        "(mosaic footers/blobs); whole-file reads are unaffected")
    READ_CACHE_RANGE_MAX_BYTES = ConfigOption(
        "read.cache.range.max-bytes", parse_memory_size, 128 << 20,
        "Capacity of the block-range cache enabled by read.cache.range")
    READ_DEVICE_DECODE = ConfigOption(
        "read.device-decode", _parse_bool, False,
        "Route parquet data-file reads through the device decode plane "
        "(format/rawpage.py + ops/decode.py): undecoded column-chunk "
        "pages are sliced via ranged reads (riding the block-range "
        "cache and SSD tier) and every per-value transform — "
        "RLE/bit-packed level expansion, dictionary gather, PLAIN "
        "reinterpret — runs as vectorized device ops; files outside "
        "the covered encodings fall back to the pyarrow host path "
        "(scan group device_decode_files/_fallbacks counters)")

    # -- pipelined write/ingest (ours; parallel/write_pipeline.py) -----------
    WRITE_FLUSH_PARALLELISM = ConfigOption(
        "write.flush.parallelism", int, None,
        "Worker threads running per-(partition,bucket) flushes (sort + "
        "encode + upload) concurrently in the pipelined write engine; "
        "None = min(8, cpu count), 1 = the serial inline write path")
    WRITE_FLUSH_MAX_BYTES = ConfigOption(
        "write.flush.max-bytes", parse_memory_size, 1 << 30,
        "Hard budget on the estimated buffered bytes of flushes in "
        "flight at once; producers block at write() until the pool "
        "drains below it, and at least one flush is always admitted so "
        "a budget below one buffer's size cannot deadlock")
    WRITE_RETRY_MAX_ATTEMPTS = ConfigOption(
        "write.retry.max-attempts", int, 3,
        "Attempts per bucket flush on a transient store fault (503 "
        "storms, IO errors — parallel/fault.py taxonomy) before the "
        "write raises; non-transient errors never retry, and an "
        "exhausted flush always raises — never silently dropped")
    WRITE_RETRY_BACKOFF = ConfigOption(
        "write.retry.backoff", _parse_duration_ms, 10,
        "Base wait between bucket-flush retries; actual waits use "
        "capped decorrelated jitter (utils/backoff.py)")

    # -- tiered host-SSD storage (ours; fs/caching.py + fs/staging.py +
    #    parallel/write_pipeline.py UploadStager) ----------------------------
    CACHE_DISK_DIR = ConfigOption(
        "cache.disk.dir", str, None,
        "Directory of the host-SSD second cache tier under the "
        "in-memory byte caches (fs/caching.py DiskCacheTier): whole-"
        "file and block-range entries are promoted here on repeated "
        "hits or memory demotion and served on memory miss, each "
        "validated by a stored key/length/crc32 header so a stale or "
        "corrupted cache dir degrades to the object store instead of "
        "serving wrong bytes.  One tier per directory per process; "
        "None disables the disk tier")
    CACHE_DISK_MAX_BYTES = ConfigOption(
        "cache.disk.max-bytes", parse_memory_size, 1 << 30,
        "Hard bound on the on-disk bytes of the cache.disk.dir tier; "
        "space is reserved under the tier lock before any entry file "
        "is written, so concurrent readers can never overshoot it "
        "(oldest entries evict first)")
    CACHE_DISK_PROMOTE_HITS = ConfigOption(
        "cache.disk.promote-after-hits", int, 2,
        "In-memory hits of one entry after which it is also written "
        "to the disk tier (so a later memory demotion costs nothing); "
        "entries evicted from memory under pressure are demoted to "
        "disk regardless of hit count")
    WRITE_STAGE_DIR = ConfigOption(
        "write.stage.dir", str, None,
        "When set, flush workers encode data/changelog files to a "
        "staged local file here (fsync'd), publish their metas, and "
        "hand the object-store upload to an async upload pool — "
        "upload retries re-read the staged bytes instead of "
        "re-sorting/re-encoding, and a completed upload seeds the "
        "cache.disk read tier.  prepare_commit() still waits for "
        "every object-store ack (the commit durability contract is "
        "unchanged); None = the legacy inline upload path")
    WRITE_STAGE_PARALLELISM = ConfigOption(
        "write.stage.parallelism", int, None,
        "Worker threads uploading staged files concurrently; None = "
        "min(8, cpu count).  More workers hide more object-store "
        "latency since staged uploads are independent PUTs to "
        "writer-unique names")

    # -- tail tolerance (ours; utils/deadline.py + fs/resilience.py +
    #    service/brownout.py) -------------------------------------------------
    REQUEST_TIMEOUT = ConfigOption(
        "request.timeout", _parse_duration_ms, None,
        "End-to-end deadline for table entry points (reads, commits, "
        "CLI ops): a Deadline is installed at entry and honored by "
        "every blocking wait downstream — retry-ladder sleeps, "
        "scan/write byte-budget blocks, admission queues, store IO — "
        "raising the typed DeadlineExceededError once spent (never "
        "retried, never orphan-committed).  None = no deadline")
    SERVICE_REQUEST_TIMEOUT = ConfigOption(
        "service.request.timeout", _parse_duration_ms, None,
        "Default end-to-end deadline for /lookup, /scan and "
        "/changelog requests (clients may override per request with "
        "'timeout_ms'); an exceeded deadline answers HTTP 504 with "
        "all in-flight work for that request abandoned.  None = no "
        "server-side deadline")
    READ_HEDGE_ENABLED = ConfigOption(
        "read.hedge.enabled", _parse_bool, False,
        "Hedge slow store reads (fs/resilience.py): GET/ranged-GET/"
        "HEAD/LIST track an online per-op-class latency quantile and "
        "a call still in flight past that delay issues ONE duplicate "
        "request — first success wins, the loser is abandoned.  Never "
        "applied to mutating ops; disabled automatically under "
        "brownout")
    READ_HEDGE_QUANTILE = ConfigOption(
        "read.hedge.quantile", float, 95.0,
        "Latency percentile of the op class's recent successes at "
        "which the hedge fires (95 = hedge the slowest ~5% of reads)")
    READ_HEDGE_MIN_DELAY = ConfigOption(
        "read.hedge.min-delay", _parse_duration_ms, 1,
        "Floor on the adaptive hedge delay, so a very fast store "
        "cannot drive the hedge trigger into micro-duplication")
    READ_HEDGE_MAX_RATIO = ConfigOption(
        "read.hedge.max-ratio", float, 0.05,
        "Hard cap on hedges as a fraction of hedgeable calls (0.05 = "
        "at most 5% extra load on the store, the classic tail-at-"
        "scale budget)")
    STORE_BREAKER_ENABLED = ConfigOption(
        "store.breaker.enabled", _parse_bool, False,
        "Per-backend circuit breaker (fs/resilience.py): a sick store "
        "trips closed->open and calls fail fast (<10ms, "
        "CircuitOpenError) instead of queueing retry ladders onto it; "
        "half-open probes re-close after store.breaker.open-ms")
    STORE_BREAKER_FAILURE_THRESHOLD = ConfigOption(
        "store.breaker.failure-threshold", int, 5,
        "Consecutive store failures that trip the breaker open")
    STORE_BREAKER_ERROR_RATE = ConfigOption(
        "store.breaker.error-rate", float, 0.5,
        "Windowed error-rate trip wire: the breaker also opens when "
        "at least this fraction of the last store.breaker.window "
        "outcomes failed (catches sustained partial sickness that "
        "never produces a long consecutive run)")
    STORE_BREAKER_WINDOW = ConfigOption(
        "store.breaker.window", int, 32,
        "Outcome window for the error-rate trip wire (must be full "
        "before the rate can trip)")
    STORE_BREAKER_OPEN_MS = ConfigOption(
        "store.breaker.open-ms", _parse_duration_ms, 5000,
        "How long an open breaker rejects before letting half-open "
        "probes through; a failed probe re-arms the full window")
    STORE_BREAKER_HALF_OPEN_PROBES = ConfigOption(
        "store.breaker.half-open-probes", int, 1,
        "Concurrent trial calls admitted in the half-open state; the "
        "first success re-closes the breaker")
    SERVICE_BROWNOUT_ENABLED = ConfigOption(
        "service.brownout.enabled", _parse_bool, True,
        "Graceful load shedding for the serving plane (service/"
        "brownout.py): under breaker-open or queue pressure the "
        "service climbs a degradation ladder — rung 1 disables "
        "hedging and shrinks prefetch windows, rung 2 also sheds "
        "lowest-priority requests with HTTP 429 — and reports it all "
        "on /healthz")
    SERVICE_BROWNOUT_QUEUE_RATIO = ConfigOption(
        "service.brownout.queue-ratio", float, 0.5,
        "Admission-queue fill fraction (waiters / service.queue."
        "depth) past which the brownout ladder starts climbing")
    SERVICE_BROWNOUT_SHED_PRIORITY = ConfigOption(
        "service.brownout.shed-priority", int, 100,
        "At brownout rung 2, requests with priority below this are "
        "shed with HTTP 429 (clients send 'priority'; the default "
        "request priority is 100, so only explicitly lower-priority "
        "traffic sheds by default)")
    SERVICE_BROWNOUT_HOLD_MS = ConfigOption(
        "service.brownout.hold-ms", _parse_duration_ms, 1000,
        "Hysteresis: once entered, a brownout rung holds at least "
        "this long before the ladder may step back down (prevents "
        "flapping between shed and un-shed at the pressure boundary)")
    SERVICE_SLO_ENABLED = ConfigOption(
        "service.slo.enabled", _parse_bool, True,
        "Evaluate declarative SLOs on the serving plane (obs/slo.py): "
        "every response feeds an availability and a latency-p99 "
        "objective as multi-window burn rates, served at GET /slo per "
        "replica, aggregated fleet-wide on the router, rendered by "
        "`paimon fleet status`, and exported as the `slo` Prometheus "
        "group")
    SERVICE_SLO_AVAILABILITY_TARGET = ConfigOption(
        "service.slo.availability-target", float, 0.999,
        "Availability objective: the fraction of requests that must "
        "succeed (429 load-sheds and 5xx count against the budget; "
        "other 4xx are the caller's fault).  0.999 leaves a 0.1% "
        "error budget")
    SERVICE_SLO_LATENCY_P99_MS = ConfigOption(
        "service.slo.latency-p99-ms", float, 250.0,
        "Latency objective: 99% of requests must finish within this "
        "many milliseconds; the over-threshold fraction burns the 1% "
        "latency budget")
    SERVICE_SLO_FAST_WINDOW_S = ConfigOption(
        "service.slo.fast-window-s", float, 300.0,
        "Fast burn-rate window (seconds): detects a budget-burning "
        "incident quickly but flaps easily — the alert fires only "
        "when the slow window agrees")
    SERVICE_SLO_SLOW_WINDOW_S = ConfigOption(
        "service.slo.slow-window-s", float, 3600.0,
        "Slow burn-rate window (seconds): stable confirmation leg of "
        "the multi-window alert; clamped to at least the fast window")
    SERVICE_SLO_BURN_THRESHOLD = ConfigOption(
        "service.slo.burn-threshold", float, 2.0,
        "Burn-rate level both windows must reach to flip the alert: "
        "1.0 spends the budget exactly at objective pace, 2.0 spends "
        "a month's budget in ~15 days — the conventional page "
        "threshold for a combined fast+slow pair")

    # -- multi-host write plane (ours; parallel/multihost.py +
    #    parallel/distributed.py) --------------------------------------------
    MULTIHOST_COMMIT_ARBITRATION = ConfigOption(
        "multihost.commit.arbitration", str, "cas",
        "How concurrent per-process commits publish on a multi-host "
        "mesh (parallel/distributed.py): 'cas' = every process "
        "commits its own messages and the snapshot CAS serializes "
        "them with conflict re-resolution (reference FileStoreCommit "
        "optimistic retry); 'coordinator' = commit messages are "
        "gathered to an elected committer process over the mesh and "
        "published as ONE snapshot per global checkpoint (reference "
        "committer-operator singleton)")
    MULTIHOST_WRITE_ROUTING = ConfigOption(
        "multihost.write.routing", str, "exchange",
        "What a distributed writer does with rows whose "
        "(partition,bucket) is owned by another process: 'exchange' = "
        "reroute them to their owners with one cross-host allgather "
        "per batch (input streams must be DISJOINT across processes); "
        "'spmd' = silently keep only owned rows (every process must "
        "see the IDENTICAL global batch — the jax SPMD shape); "
        "'local-only' = raise, for pre-partitioned pipelines where a "
        "foreign row is a routing bug")
    MULTIHOST_SCAN_PIN = ConfigOption(
        "multihost.scan.pin-snapshot", _parse_bool, True,
        "Snapshot-consistent cross-host scans: all processes agree on "
        "ONE pinned snapshot id (broadcast from process 0) before "
        "planning, so every host reads the same table version and "
        "split ownership covers exactly one consistent state.  false "
        "= each process plans its own latest snapshot (scans may "
        "straddle concurrent commits)")
    MULTIHOST_LEASE_INTERVAL = ConfigOption(
        "multihost.lease.interval", _parse_duration_ms, 10000,
        "Target lease-renewal cadence of the multi-host maintenance "
        "plane (parallel/maintenance_plane.py): every plane-issued "
        "commit renews the committer's lease as snapshot properties; "
        "when no commit happened within this interval the plane "
        "publishes a small heartbeat snapshot so an idle-but-alive "
        "host is never mistaken for a dead one")
    MULTIHOST_LEASE_TIMEOUT = ConfigOption(
        "multihost.lease.timeout", _parse_duration_ms, 60000,
        "Failure-detector threshold: a maintenance-plane participant "
        "whose newest lease renewal (max-merged over the recent "
        "snapshot chain) is older than this is presumed DEAD, and its "
        "(partition,bucket) groups are deterministically re-assigned "
        "to the survivors (ownership version bump, dead set recorded "
        "in snapshot properties).  Must comfortably exceed "
        "multihost.lease.interval plus worst-case commit latency — a "
        "premature declaration splits ownership of live buckets")
    MULTIHOST_MAINTENANCE_TAKEOVER = ConfigOption(
        "multihost.maintenance.takeover", _parse_bool, True,
        "Whether survivors automatically adopt a dead host's buckets "
        "(compaction, expiry election, changelog serving and — for "
        "distributed stream daemons — its committed CDC offsets, "
        "exactly-once).  false = the failure detector still reports "
        "lease_expired, but ownership stays frozen until an operator "
        "intervenes")
    MULTIHOST_MAINTENANCE_LEASE_WALK = ConfigOption(
        "multihost.maintenance.lease-walk", int, 16,
        "How many recent snapshots the lease reader max-merges to "
        "build the failure-detector view.  One snapshot would race "
        "concurrent committers (each stamps the view IT knew); a "
        "small window resolves the interleaving by max()")
    MULTIHOST_REJOIN_ENABLED = ConfigOption(
        "multihost.rejoin.enabled", _parse_bool, True,
        "Whether a restarted host that the ownership map records DEAD "
        "enters the coordinated rejoin protocol (publish a rejoin "
        "request, wait for the elected survivor to readmit it into a "
        "new ownership generation, replay its offset gap up to the "
        "granted floor, resume).  false restores the PR 11 behavior: "
        "plane construction refuses the resurrected host with "
        "OwnershipError and rejoin needs an operator-driven "
        "whole-cohort restart (docs/multihost.md)")

    # -- observability (ours; paimon_tpu/obs/) -------------------------------
    METRICS_ENABLED = ConfigOption(
        "metrics.enabled", _parse_bool, True,
        "Record per-stage latency histograms + counters into the "
        "process metric registry (metrics.py), the source of the "
        "$metrics system table, the Prometheus /metrics endpoint and "
        "bench snapshots; false turns the span timers into no-ops. "
        "Process-global switch, synced from table options at pipeline "
        "entry — an explicitly-set value wins, an absent key leaves "
        "the current process state")
    TRACE_ENABLED = ConfigOption(
        "trace.enabled", _parse_bool, False,
        "Collect structured spans (obs/trace.py) from the scan/write/"
        "compaction/commit planes into the bounded in-process ring, "
        "queryable via the $traces system table and exportable as "
        "Chrome trace-event JSON (Perfetto).  Off by default: the "
        "disabled call path is a no-op measured <2% of scan wall time "
        "(benchmarks/micro.py obs).  Process-global switch like "
        "metrics.enabled")
    TRACE_BUFFER_SPANS = ConfigOption(
        "trace.buffer.spans", int, 8192,
        "Capacity of the bounded span ring; the oldest spans evict "
        "first, so a long-running traced service cannot grow without "
        "bound")
    TRACE_EXPORT_PATH = ConfigOption(
        "trace.export.path", str, None,
        "When set (with trace.enabled), the span ring is flushed to "
        "this file as Chrome trace-event JSON at pipeline completion "
        "points (scan drained, write pool shut down, mesh compaction "
        "finished); the CLI --trace flag is the one-shot equivalent")
    TRACE_EXPORT_DIR = ConfigOption(
        "trace.export.dir", str, None,
        "Shared spool directory for FLEET traces: every process with "
        "this set appends its spans (tagged host/pid/replica, with a "
        "wall-clock anchor) to its own <dir>/<process-tag>.jsonl at "
        "the same completion points plus daemon shutdown/SIGTERM; "
        "`paimon fleet trace --merge <dir>` stitches the spools into "
        "one Perfetto file with per-process tracks and flow arrows at "
        "every serving hop and store-carried context boundary")
    OBS_FLIGHT_ENABLED = ConfigOption(
        "obs.flight.enabled", _parse_bool, True,
        "Black-box flight recorder (obs/flight.py): keep an always-on "
        "bounded ring of operational events — retry arms, breaker "
        "flips, brownout transitions, 429/504 sheds, commit conflicts, "
        "lease expiries, takeovers, rejoin grants, loop crashes — "
        "dumped atomically on crash/SIGTERM and by `paimon table "
        "debug-bundle`.  Recording is one dict append under a leaf "
        "lock; disable only if that is too much")
    OBS_FLIGHT_EVENTS = ConfigOption(
        "obs.flight.events", int, 512,
        "Capacity of the flight-recorder event ring; oldest events "
        "evict first")
    OBS_FLIGHT_DUMP_DIR = ConfigOption(
        "obs.flight.dump.dir", str, None,
        "When set, installs crash hooks (sys.excepthook + atexit + the "
        "stream daemon's signal handler) that dump the flight ring to "
        "flight-<host>-<pid>-<ms>.json under this directory, so a "
        "crashed or SIGTERM'd process leaves its last events behind "
        "for `paimon fleet trace` forensics")

    # -- streaming daemon (ours; service/stream_daemon.py) -------------------
    STREAM_CHECKPOINT_INTERVAL = ConfigOption(
        "stream.checkpoint.interval", _parse_duration_ms, 1000,
        "How often the ingest loop commits a checkpoint: one snapshot "
        "carrying the data AND the CDC source offset in its commit "
        "properties (atomic, exactly-once across restarts)")
    STREAM_INGEST_MAX_BATCH = ConfigOption(
        "stream.ingest.max-batch", int, 1024,
        "Max CDC events pulled from the source per poll; together with "
        "the writer's write.flush.max-bytes budget (which blocks "
        "write_events) this bounds ingest memory — the daemon never "
        "queues events internally")
    STREAM_INGEST_POLL_INTERVAL = ConfigOption(
        "stream.ingest.poll-interval", _parse_duration_ms, 25,
        "Idle sleep between source polls when the source has no events")
    STREAM_COMPACTION_INTERVAL = ConfigOption(
        "stream.compaction.interval", _parse_duration_ms, 2000,
        "How often the compaction loop checks the per-bucket sorted-run "
        "trigger (num-sorted-run.compaction-trigger) and, when over it, "
        "runs a compaction")
    STREAM_COMPACTION_FULL = ConfigOption(
        "stream.compaction.full", _parse_bool, True,
        "Triggered compactions run full (eligible for the mesh engine "
        "with its retry/fallback ladder); false picks incremental "
        "units through the single-chip universal-compaction manager")
    STREAM_MANIFEST_COMPACTION_INTERVAL = ConfigOption(
        "stream.manifest-compaction.interval", _parse_duration_ms,
        60_000,
        "How often the compaction loop probes the manifest "
        "full-compaction trigger (the probe reads the snapshot's "
        "manifest lists — too frequent is wasted metadata IO); "
        "None disables the probe")
    STREAM_COMPACTION_PAUSE_RATIO = ConfigOption(
        "stream.compaction.pause-ratio", float, 0.5,
        "Graceful degradation: the compaction loop SKIPS its round "
        "while the write pipeline's in-flight bytes exceed this "
        "fraction of write.flush.max-bytes (ingest pressure wins)")
    STREAM_COMPACTION_PAUSE_BACKLOG = ConfigOption(
        "stream.compaction.pause-backlog", int, 8192,
        "Also pause compaction while more than this many source events "
        "are waiting to be pulled (ingest is behind)")
    STREAM_SERVE_POLL_INTERVAL = ConfigOption(
        "stream.serve.poll-interval", _parse_duration_ms, 50,
        "Changelog-serving loop sleep between stream-scan polls once "
        "caught up")
    STREAM_SERVE_BUFFER_ROWS = ConfigOption(
        "stream.serve.buffer.rows", int, 65536,
        "Bound on buffered changelog rows awaiting consumers; the "
        "serving loop BLOCKS (backpressure) instead of dropping or "
        "growing without bound when consumers lag")
    STREAM_RESTART_BACKOFF = ConfigOption(
        "stream.restart.backoff", _parse_duration_ms, 200,
        "Base wait before a crashed daemon loop (ingest/compact/serve) "
        "is restarted by its supervisor; waits use capped decorrelated "
        "jitter (utils/backoff.py)")
    STREAM_RESTART_BACKOFF_CAP = ConfigOption(
        "stream.restart.backoff.cap", _parse_duration_ms, 10_000,
        "Cap on the jittered supervised-restart wait")
    STREAM_RESTART_HEALTHY_MS = ConfigOption(
        "stream.restart.healthy-threshold", _parse_duration_ms, 30_000,
        "A loop that ran at least this long counts as healthy and "
        "resets its restart backoff schedule")
    STREAM_RESTART_MAX = ConfigOption(
        "stream.restart.max-restarts", int, None,
        "Give up supervising a loop after this many consecutive "
        "unhealthy restarts (None = restart forever); the daemon "
        "records the terminal error in its status")
    STREAM_EXPIRE_INTERVAL = ConfigOption(
        "stream.expire.interval", _parse_duration_ms, None,
        "When set, the compaction loop also expires old snapshots at "
        "this interval (bounds metadata growth on long-running "
        "daemons); None leaves snapshot expiry to external maintenance")

    # -- query serving plane (ours; service/query_service.py +
    #    service/admission.py) --------------------------------------------
    SERVICE_MAX_INFLIGHT_BYTES = ConfigOption(
        "service.max-inflight-bytes", parse_memory_size, 1 << 30,
        "Hard budget on the estimated bytes of requests admitted to "
        "the query service at once (the serving-side analog of "
        "read.prefetch.max-bytes); further requests queue instead of "
        "oversubscribing, and an idle service always admits one "
        "request so a single request larger than the budget cannot "
        "stall forever")
    SERVICE_TENANT_MAX_INFLIGHT_BYTES = ConfigOption(
        "service.tenant.max-inflight-bytes", parse_memory_size, None,
        "Per-tenant slice of the admission byte budget (tenants are "
        "named by the request's 'tenant' field / the client's tenant "
        "id); None = every tenant may use the whole "
        "service.max-inflight-bytes.  A tenant with nothing in flight "
        "is always eligible for one request (anti-starvation)")
    SERVICE_QUEUE_DEPTH = ConfigOption(
        "service.queue.depth", int, 256,
        "Bound on requests waiting for admission; a request arriving "
        "to a full queue is rejected immediately with HTTP 429 "
        "instead of growing server memory without bound")
    SERVICE_QUEUE_TIMEOUT = ConfigOption(
        "service.queue.timeout", _parse_duration_ms, 10_000,
        "How long a queued request waits for byte budget before the "
        "service answers HTTP 429 (clients see ServiceBusyError and "
        "may retry with backoff)")
    SERVICE_LOOKUP_REFRESH_INTERVAL = ConfigOption(
        "service.lookup.refresh-interval", _parse_duration_ms, 100,
        "Snapshot-refresh TTL of the serving-side point-lookup "
        "engine: within the TTL, point gets are answered from the "
        "cached plan without touching the snapshot hint or manifest "
        "chain (lookups may trail commits by up to this long; 0 = "
        "check the latest snapshot on every call, the embedded "
        "LocalTableQuery default)")
    SERVICE_CACHE_SHARED = ConfigOption(
        "service.cache.shared", _parse_bool, True,
        "Serve all requests through the process-wide shared cache "
        "tier (footer cache + whole-file/block-range byte cache, "
        "fs/caching.py) so concurrent /scan, /lookup and /changelog "
        "requests warm each other instead of rebuilding per-request "
        "state; false leaves the table's own FileIO untouched")
    SERVICE_SCAN_ROW_BYTES = ConfigOption(
        "service.scan.row-bytes-estimate", int, 256,
        "Estimated serving-cost bytes per row for admission control "
        "of LIMIT'd scans and changelog polls (the admission charge "
        "is limit x this, known before any plan or read IO runs)")
    SERVICE_LOOKUP_KEY_BYTES = ConfigOption(
        "service.lookup.key-bytes-estimate", int, 4096,
        "Estimated serving-cost bytes per point-get key for admission "
        "control (roughly one SST block read per cold key)")
    SERVICE_WORKERS = ConfigOption(
        "service.workers", int, 16,
        "Handler threads behind the event-loop request engine "
        "(service/async_server.py): request bodies execute on this "
        "bounded pool while the single loop thread owns every socket "
        "— concurrent connections cost file descriptors, not threads")
    SERVICE_MAX_CONNECTIONS = ConfigOption(
        "service.max-connections", int, 1024,
        "Bound on concurrently open client connections per server; "
        "accepts past it answer HTTP 503 and close immediately (file "
        "descriptors are the budgeted resource of the event-loop "
        "engine, and even those are bounded)")
    SERVICE_REPLICAS = ConfigOption(
        "service.replicas", int, 1,
        "Read replicas started by ReplicaSet (service/router.py): N "
        "query servers over one table — sharing the process-wide "
        "byte-cache tier and the host-SSD tier — fronted by a router "
        "that consistent-hashes tenants across them; 1 = the classic "
        "single-server plane, no router")
    SERVICE_REPLICA_VNODES = ConfigOption(
        "service.replicas.virtual-nodes", int, 64,
        "Virtual nodes per replica on the router's consistent-hash "
        "ring: more vnodes = smoother tenant spread and smaller "
        "reassignment when the replica count changes")
    SERVICE_REPLICA_HEALTH_INTERVAL = ConfigOption(
        "service.replicas.health-interval", _parse_duration_ms, 1_000,
        "How often the router health-checks REMOTE replicas "
        "(processes on other machines registered via POST /register): "
        "an unreachable replica is taken out of the hash ring after "
        "two consecutive failures and re-admitted on the first "
        "successful check; in-process replicas are never checked — "
        "their liveness is the process's")
    SERVICE_PROBE_NATIVE = ConfigOption(
        "service.probe.native", _parse_bool, True,
        "Resolve SST point-probe batches with the native C path "
        "(native/probe.c): bloom filter + binary search over the "
        "flat sorted key buffer laid out at SST build time, one call "
        "per (bucket, sorted-run) file with the GIL released.  "
        "Degrades silently to the vectorized numpy walk — counting "
        "lookup.native_fallbacks — when no compiler is available, "
        "PAIMON_DISABLE_NATIVE=1, or the cached .so predates the "
        "probe symbols; false forces the numpy walk")
    SERVICE_WARMBOOT_ENABLED = ConfigOption(
        "service.warmboot.enabled", _parse_bool, False,
        "Boot serving replicas WARM from state persisted through the "
        "shared SSD tier: on stop (or an explicit POST /warmboot) a "
        "replica serializes its plan-cache state and hard-links its "
        "built SST files under service.warmboot.dir; the next replica "
        "over the same table restores them at query-engine "
        "construction and serves its first lookup with zero reader "
        "builds and no manifest walk.  Requires service.warmboot.dir "
        "or cache.disk.dir")
    SERVICE_WARMBOOT_DIR = ConfigOption(
        "service.warmboot.dir", str, None,
        "Directory the warm-boot state persists into — a shared SSD "
        "mount reachable by every machine's replicas (the same "
        "sharing contract as cache.disk.dir, which is also the "
        "default location: <cache.disk.dir>/warmboot)")
    SERVICE_DELTA_ENABLED = ConfigOption(
        "service.delta.enabled", _parse_bool, True,
        "Serve point lookups from the hot in-memory delta tier "
        "(service/delta.py): rows written through a serving writer "
        "are readable in microseconds — before any flush or commit — "
        "merged newest-first over the LSM with the same tombstone "
        "semantics; requires deduplicate merge semantics (no "
        "sequence.field / record-level expire)")
    SERVICE_DELTA_MAX_BYTES = ConfigOption(
        "service.delta.max-bytes", parse_memory_size, 256 << 20,
        "Soft bound on the delta tier's resident bytes: crossing it "
        "counts delta_overflow and is the signal to commit (sealed "
        "generations are pruned as soon as every attached reader's "
        "plan covers them; uncommitted rows are never dropped — "
        "dropping them would un-publish an acknowledged write)")

    # -- scan / read (reference CoreOptions.java:1416,2120-2200) -------------
    SCAN_PLAN_SORT_PARTITION = ConfigOption(
        "scan.plan-sort-partition", _parse_bool, False,
        "Sort plan splits by partition value")
    SCAN_BOUNDED_WATERMARK = ConfigOption(
        "scan.bounded.watermark", int, None,
        "End a stream once a snapshot watermark passes this bound")
    STREAMING_READ_OVERWRITE = ConfigOption(
        "streaming-read-overwrite", _parse_bool, False,
        "Follow-up scanners also read OVERWRITE snapshots' deltas")
    CONSUMER_IGNORE_PROGRESS = ConfigOption(
        "consumer.ignore-progress", _parse_bool, False,
        "Start fresh instead of resuming the consumer's progress")

    # -- sequence / merge (reference CoreOptions.java:1090) ------------------
    SEQUENCE_FIELD_SORT_ORDER = ConfigOption(
        "sequence.field.sort-order", str, "ascending",
        "ascending: larger sequence wins; descending: smaller wins")
    PARTIAL_UPDATE_REMOVE_RECORD_ON_DELETE = ConfigOption(
        "partial-update.remove-record-on-delete", _parse_bool, False,
        "-D on a partial-update table drops the whole row instead of "
        "being ignored")

    # -- compaction tuning (reference CoreOptions.java:1018-1080) ------------
    COMPACTION_TOTAL_SIZE_THRESHOLD = ConfigOption(
        "compaction.total-size-threshold", parse_memory_size, None,
        "Full-compact a bucket whenever its total size is below this")
    COMPACTION_FILE_NUM_LIMIT = ConfigOption(
        "compaction.file-num-limit", int, None,
        "Force a compaction pick once a bucket holds this many files")

    # -- changelog files (reference CoreOptions.java:640-690) ----------------
    CHANGELOG_FILE_FORMAT = ConfigOption(
        "changelog-file.format", str, None,
        "Changelog files' format; defaults to file.format")
    CHANGELOG_FILE_COMPRESSION = ConfigOption(
        "changelog-file.compression", str, None,
        "Changelog files' compression; defaults to file.compression")
    CHANGELOG_FILE_PREFIX = ConfigOption("changelog-file.prefix", str,
                                         "changelog-", "")

    # -- maintenance (reference CoreOptions.java:1330-1340) ------------------
    PARTITION_EXPIRATION_MAX_NUM = ConfigOption(
        "partition.expiration-max-num", int, 100,
        "Partitions expired per expire_partitions() call, oldest first")

    # -- manifests (reference CoreOptions.java:560-600) ----------------------
    MANIFEST_TARGET_FILE_SIZE = ConfigOption(
        "manifest.target-file-size", parse_memory_size, 8 << 20, "")
    SCAN_MANIFEST_PARALLELISM = ConfigOption(
        "scan.manifest.parallelism", int, None,
        "Threads for reading manifest files during scan planning "
        "(None = serial)")
    MANIFEST_FULL_COMPACTION_THRESHOLD = ConfigOption(
        "manifest.full-compaction.threshold", int, 50,
        "Full-rewrite manifests once the chain holds this many small "
        "(sub-half-target-size) manifests (None disables the trigger)")
    MANIFEST_STATS_SIDECAR = ConfigOption(
        "manifest.stats.sidecar", _parse_bool, True,
        "Write a columnar partition/bucket/key-range stats sidecar "
        "next to every manifest list (vectorized manifest pruning)")
    SCAN_PLAN_CACHE = ConfigOption(
        "scan.plan.cache", _parse_bool, True,
        "Reuse cached plans across snapshots by applying only the new "
        "snapshots' delta manifests (invalidated by overwrites)")
    SCAN_PLAN_CACHE_MAX_ENTRIES = ConfigOption(
        "scan.plan.cache.max-entries", int, 4_000_000,
        "Largest live-entry count the delta-apply plan cache will hold "
        "for one table; bigger tables fall back to cold walks")
    SNAPSHOT_CLEAN_EMPTY_DIRECTORIES = ConfigOption(
        "snapshot.clean-empty-directories", _parse_bool, False,
        "Remove emptied partition/bucket directories after snapshot "
        "expiration")
    DELETE_FILE_THREAD_NUM = ConfigOption(
        "delete-file.thread-num", int, None,
        "Threads for deleting dead files during snapshot expiration "
        "(None = serial)")

    # -- source splits (reference CoreOptions.java:2230-2250) ----------------
    SOURCE_SPLIT_TARGET_SIZE = ConfigOption(
        "source.split.target-size", parse_memory_size, 128 << 20,
        "Append-table buckets bin into splits of about this size")
    SOURCE_SPLIT_OPEN_FILE_COST = ConfigOption(
        "source.split.open-file-cost", parse_memory_size, 4 << 20, "")
    SCAN_MAX_SPLITS_PER_TASK = ConfigOption(
        "scan.max-splits-per-task", int, 10,
        "Cap on files binned into one append-table split")

    # -- data file layout (reference CoreOptions.java:300-420) ---------------
    DATA_FILE_PREFIX = ConfigOption(
        "data-file.prefix", str, "data-",
        "File-name prefix of data files")
    DATA_FILE_PATH_DIRECTORY = ConfigOption(
        "data-file.path-directory", str, None,
        "Subdirectory (under the table path) holding data files; "
        "None = partition/bucket directories at the table root")
    FILE_BLOCK_SIZE = ConfigOption(
        "file.block-size", parse_memory_size, None,
        "Format block granularity: parquet row-group bytes / orc "
        "stripe bytes; None = format default")
    TARGET_FILE_ROW_NUM = ConfigOption(
        "target-file-row-num", int, None,
        "Roll data files at this many rows, in addition to "
        "target-file-size")
    FILE_COMPRESSION_PER_LEVEL = ConfigOption(
        "file.compression.per.level", str, None,
        "Per-LSM-level compression overrides, e.g. '0:lz4,5:zstd' — "
        "cheap codec for hot L0, dense for settled levels")
    FILE_SUFFIX_INCLUDE_COMPRESSION = ConfigOption(
        "file.suffix.include.compression", _parse_bool, False,
        "Data file extension carries the codec, e.g. '.zstd.parquet'")
    ASYNC_FILE_WRITE = ConfigOption(
        "async-file-write", _parse_bool, True,
        "Encode output files on background threads so file writes "
        "overlap the next window's merge (streamed compaction)")
    FILE_READER_ASYNC_THRESHOLD = ConfigOption(
        "file-reader-async-threshold", parse_memory_size, 10 << 20,
        "Files above this size decode with readahead prefetch")
    FILE_OPERATION_THREAD_NUM = ConfigOption(
        "file-operation.thread-num", int, None,
        "Threads for bulk file copy/delete maintenance operations")
    READ_BATCH_SIZE = ConfigOption(
        "read.batch-size", int, 1024,
        "Record-batch rows for format readers")
    WRITE_BATCH_SIZE = ConfigOption(
        "write.batch-size", int, 1024,
        "Record-batch rows for format writers")
    PAGE_SIZE = ConfigOption(
        "page-size", parse_memory_size, 64 << 10,
        "Memory page granularity for spill/lookup buffers")
    CACHE_PAGE_SIZE = ConfigOption(
        "cache-page-size", parse_memory_size, 64 << 10,
        "Page granularity of the lookup block cache")

    # -- stats (reference CoreOptions.java:520-560) --------------------------
    METADATA_STATS_MODE_PER_LEVEL = ConfigOption(
        "metadata.stats-mode.per.level", str, None,
        "Per-level stats-mode overrides, e.g. '0:none,5:full' — skip "
        "stats work for short-lived L0 files")
    METADATA_STATS_KEEP_FIRST_N_COLUMNS = ConfigOption(
        "metadata.stats-keep-first-n-columns", int, None,
        "Collect file stats only for the first N value columns")
    METADATA_STATS_DENSE_STORE = ConfigOption(
        "metadata.stats-dense-store", _parse_bool, True,
        "Store manifest stats densely (skip all-null stats columns)")
    MANIFEST_DELETE_FILE_DROP_STATS = ConfigOption(
        "manifest.delete-file-drop-stats", _parse_bool, False,
        "DELETE manifest entries drop value stats (smaller manifests)")
    MANIFEST_FULL_COMPACTION_THRESHOLD_SIZE = ConfigOption(
        "manifest.full-compaction-threshold-size", parse_memory_size,
        16 << 20,
        "Full-rewrite manifests once total delta size passes this")

    # -- spill (reference CoreOptions.java:860-930) --------------------------
    SPILL_COMPRESSION = ConfigOption(
        "spill-compression", str, "zstd",
        "Codec for spilled sorted runs (zstd | lz4 | none)")
    SPILL_COMPRESSION_ZSTD_LEVEL = ConfigOption(
        "spill-compression.zstd-level", int, 1,
        "zstd level for spill files (speed matters more than ratio)")
    SORT_SPILL_BUFFER_SIZE = ConfigOption(
        "sort-spill-buffer-size", parse_memory_size, 64 << 20,
        "In-memory rows buffered before a sorted run spills")
    WRITE_BUFFER_SPILL_MAX_DISK_SIZE = ConfigOption(
        "write-buffer-spill.max-disk-size", parse_memory_size,
        9223372036854775807,
        "Disk budget for spilled write-buffer runs; reaching it forces "
        "an early flush to L0 instead of more spill")
    LOCAL_SORT_MAX_NUM_FILE_HANDLES = ConfigOption(
        "local-sort.max-num-file-handles", int, 128,
        "Max spilled runs merged at once; more runs first fold into "
        "one (the reference's external-merge fan-in bound)")
    WRITE_MAX_WRITERS_TO_SPILL = ConfigOption(
        "write-max-writers-to-spill", int, 10,
        "Batch writers beyond this count turn on spill to bound RAM")

    # -- lookup store (reference CoreOptions.java:1740-1860) -----------------
    LOOKUP_CACHE_MAX_MEMORY_SIZE = ConfigOption(
        "lookup.cache-max-memory-size", parse_memory_size, 256 << 20,
        "Block-cache memory bound of the SST lookup store")
    LOOKUP_CACHE_FILE_RETENTION = ConfigOption(
        "lookup.cache-file-retention", _parse_duration_ms, 3600000,
        "Cached lookup SST files expire after this idle time")
    LOOKUP_CACHE_SPILL_COMPRESSION = ConfigOption(
        "lookup.cache-spill-compression", str, "zstd",
        "Codec for lookup SST block files")
    LOOKUP_CACHE_BLOOM_FILTER_ENABLED = ConfigOption(
        "lookup.cache.bloom.filter.enabled", _parse_bool, True,
        "Per-SST bloom filter to skip files on point lookups")
    LOOKUP_CACHE_BLOOM_FILTER_FPP = ConfigOption(
        "lookup.cache.bloom.filter.fpp", float, 0.05,
        "False-positive rate of the lookup SST bloom filter")
    LOOKUP_CACHE_HIGH_PRIORITY_POOL_RATIO = ConfigOption(
        "lookup.cache.high-priority-pool-ratio", float, 0.25,
        "Share of the block cache reserved for index/filter blocks")
    LOOKUP_HASH_LOAD_FACTOR = ConfigOption(
        "lookup.hash-load-factor", float, 0.75,
        "Fill factor of in-memory lookup hash overlays")
    LOOKUP_MERGE_RECORDS_THRESHOLD = ConfigOption(
        "lookup.merge-records-threshold", int, 10_000_000,
        "Row bound per lookup-changelog merge batch")
    LOOKUP_MERGE_BUFFER_SIZE = ConfigOption(
        "lookup.merge-buffer-size", parse_memory_size, 256 << 20,
        "Byte bound per lookup-changelog merge batch")
    LOOKUP_WAIT = ConfigOption(
        "lookup-wait", _parse_bool, True,
        "Commit waits for lookup compaction; False defers it to the "
        "next compaction cycle")

    # -- scan variants (reference CoreOptions.java:1380-1600) ----------------
    SCAN_TIMESTAMP = ConfigOption(
        "scan.timestamp", str, None,
        "ISO-8601 travel point, e.g. '2026-07-29T12:00:00' "
        "(scan.timestamp-millis takes precedence)")
    SCAN_WATERMARK = ConfigOption(
        "scan.watermark", int, None,
        "Travel to the first snapshot whose watermark >= this")
    SCAN_CREATION_TIME_MILLIS = ConfigOption(
        "scan.creation-time-millis", int, None,
        "Alias of scan.file-creation-time-millis")
    SCAN_FILE_CREATION_TIME_MILLIS = ConfigOption(
        "scan.file-creation-time-millis", int, None,
        "from-file-creation-time startup: only files created after "
        "this instant")
    SCAN_BUCKET = ConfigOption(
        "scan.bucket", int, None,
        "Restrict the scan to one bucket (debug / targeted replay)")
    SCAN_VERSION = ConfigOption(
        "scan.version", str, None,
        "Unified travel point: a tag name or a snapshot id")
    FILE_INDEX_READ_ENABLED = ConfigOption(
        "file-index.read.enabled", _parse_bool, True,
        "Evaluate per-file indexes (bloom/bitmap/bsi) during planning; "
        "False scans every file (index debugging)")
    BATCH_SCAN_MODE = ConfigOption(
        "batch-scan-mode", str, "none",
        "none | postpone: batch reads of postpone-bucket tables")
    STREAM_SCAN_MODE = ConfigOption(
        "stream-scan-mode", str, "none",
        "none | compacted-changes | file-monitor: follow-up source")
    STREAMING_READ_APPEND_OVERWRITE = ConfigOption(
        "streaming-read-append-overwrite", _parse_bool, False,
        "Streaming reads treat OVERWRITE snapshots as appends")
    CONTINUOUS_DISCOVERY_INTERVAL = ConfigOption(
        "continuous.discovery-interval", _parse_duration_ms, 10_000,
        "Streaming source poll interval for new snapshots")
    SCAN_IGNORE_LOST_FILES = ConfigOption(
        "scan.ignore-lost-files", _parse_bool, False,
        "Skip (not fail on) data files missing from storage")
    INCREMENTAL_BETWEEN_SCAN_MODE = ConfigOption(
        "incremental-between-scan-mode", str, "auto",
        "auto | delta | changelog | diff: how incremental-between "
        "computes the row set")
    INCREMENTAL_BETWEEN_TIMESTAMP = ConfigOption(
        "incremental-between-timestamp", str, None,
        "Incremental read between two commit timestamps 't1,t2'")
    INCREMENTAL_TO_AUTO_TAG = ConfigOption(
        "incremental-to-auto-tag", str, None,
        "Incremental read from the previous auto-tag to this one")

    # -- consumers (reference CoreOptions.java:2060-2100) --------------------
    CONSUMER_MODE = ConfigOption(
        "consumer.mode", str, "exactly-once",
        "exactly-once | at-least-once consumer progress semantics")
    CONSUMER_CHANGELOG_ONLY = ConfigOption(
        "consumer.changelog-only", _parse_bool, False,
        "Consumer protects only changelogs, not snapshots, from expiry")

    # -- commit (reference CoreOptions.java:919-1010) ------------------------
    COMMIT_TIMEOUT = ConfigOption(
        "commit.timeout", _parse_duration_ms, None,
        "Give up CAS retries after this long (None = retries only)")
    COMMIT_DISCARD_DUPLICATE_FILES = ConfigOption(
        "commit.discard-duplicate-files", _parse_bool, False,
        "Filter files already committed by a retried message")
    DYNAMIC_PARTITION_OVERWRITE = ConfigOption(
        "dynamic-partition-overwrite", _parse_bool, True,
        "INSERT OVERWRITE replaces only partitions present in the new "
        "data; False truncates the whole table")

    # -- changelog (reference CoreOptions.java:640-760) ----------------------
    CHANGELOG_TIME_RETAINED = ConfigOption(
        "changelog.time-retained", _parse_duration_ms, None,
        "Age bound for decoupled changelogs (expire_changelogs)")
    CHANGELOG_FILE_STATS_MODE = ConfigOption(
        "changelog-file.stats-mode", str, "none",
        "Stats collection for changelog files (they are never planned "
        "against, so 'none' skips the work)")
    CHANGELOG_ROW_DEDUPLICATE = ConfigOption(
        "changelog-producer.row-deduplicate", _parse_bool, False,
        "Suppress -U/+U changelog pairs whose values are identical")
    CHANGELOG_ROW_DEDUPLICATE_IGNORE_FIELDS = ConfigOption(
        "changelog-producer.row-deduplicate-ignore-fields", str, None,
        "Columns ignored by the -U/+U equality check (csv)")
    DELETE_FORCE_PRODUCE_CHANGELOG = ConfigOption(
        "delete.force-produce-changelog", _parse_bool, False,
        "DELETE emits changelog rows even with changelog-producer=none")
    IGNORE_UPDATE_BEFORE = ConfigOption(
        "ignore-update-before", _parse_bool, False,
        "Drop incoming -U rows at write time (they are redundant for "
        "last-wins merge engines)")

    # -- merge engines (reference CoreOptions.java:1090-1200) ----------------
    AGGREGATION_REMOVE_RECORD_ON_DELETE = ConfigOption(
        "aggregation.remove-record-on-delete", _parse_bool, False,
        "-D on an aggregation table drops the accumulated row")
    PARTIAL_UPDATE_REMOVE_RECORD_ON_SEQUENCE_GROUP = ConfigOption(
        "partial-update.remove-record-on-sequence-group", str, None,
        "-D carrying these sequence-group columns (csv) drops the row")

    # -- dynamic bucket (reference CoreOptions.java:1650-1700) ---------------
    DYNAMIC_BUCKET_MAX_BUCKETS = ConfigOption(
        "dynamic-bucket.max-buckets", int, -1,
        "Upper bound on auto-created buckets (-1 = unbounded)")
    BUCKET_FUNCTION_TYPE = ConfigOption(
        "bucket-function.type", str, "default",
        "default (murmur-style hash) | mod (int key modulo — keeps "
        "numeric locality, reference BucketFunctionType.MOD)")
    BUCKET_APPEND_ORDERED = ConfigOption(
        "bucket-append-ordered", _parse_bool, True,
        "Fixed-bucket append tables keep per-bucket write order")

    # -- cross-partition upsert (reference CoreOptions.java:1930) ------------
    CROSS_PARTITION_UPSERT_INDEX_TTL = ConfigOption(
        "cross-partition-upsert.index-ttl", _parse_duration_ms, None,
        "Drop global-index entries idle past this (bounds index size; "
        "late rows for dropped keys create new partitions)")
    CROSS_PARTITION_UPSERT_BOOTSTRAP_PARALLELISM = ConfigOption(
        "cross-partition-upsert.bootstrap-parallelism", int, 10,
        "Parallel readers bootstrapping the cross-partition index")

    # -- deletion vectors (reference CoreOptions.java:2330-2380) -------------
    DELETION_VECTORS_BITMAP64 = ConfigOption(
        "deletion-vectors.bitmap64", _parse_bool, False,
        "64-bit roaring containers for DVs over files >2^32 rows")
    DELETION_VECTOR_INDEX_FILE_TARGET_SIZE = ConfigOption(
        "deletion-vector.index-file.target-size", parse_memory_size,
        2 << 20, "Roll DV index files at this size")

    # -- tags (reference CoreOptions.java:2400-2520) -------------------------
    TAG_CREATION_PERIOD = ConfigOption(
        "tag.creation-period", str, "daily",
        "daily | hourly | two-hours: auto-tag period")
    TAG_CREATION_DELAY = ConfigOption(
        "tag.creation-delay", _parse_duration_ms, 0,
        "Wait this long past the period end before tagging")
    TAG_CREATION_PERIOD_DURATION = ConfigOption(
        "tag.creation-period-duration", _parse_duration_ms, None,
        "Custom period length (overrides tag.creation-period)")
    TAG_PERIOD_FORMATTER = ConfigOption(
        "tag.period-formatter", str, "with_dashes",
        "with_dashes | without_dashes[_colons]: auto-tag name format")
    TAG_NUM_RETAINED_MAX = ConfigOption(
        "tag.num-retained-max", int, None,
        "Oldest auto-tags beyond this count are deleted")
    TAG_DEFAULT_TIME_RETAINED = ConfigOption(
        "tag.default-time-retained", _parse_duration_ms, None,
        "Auto/SQL tags expire after this age")
    TAG_AUTOMATIC_COMPLETION = ConfigOption(
        "tag.automatic-completion", _parse_bool, False,
        "Backfill missed periodic tags, not just the newest period")
    TAG_CREATE_SUCCESS_FILE = ConfigOption(
        "tag.create-success-file", _parse_bool, False,
        "Write a _SUCCESS marker next to each auto-tag")
    TAG_TIME_EXPIRE_ENABLED = ConfigOption(
        "tag.time-expire-enabled", _parse_bool, False,
        "Sweep time-retained tags past expiry at auto-tag time")

    # -- snapshot expiry (reference CoreOptions.java:470-520) ----------------
    SNAPSHOT_EXPIRE_EXECUTION_MODE = ConfigOption(
        "snapshot.expire.execution-mode", str, "sync",
        "sync | async: expire inline at commit or on a worker thread")
    PARTITION_EXPIRATION_STRATEGY = ConfigOption(
        "partition.expiration-strategy", str, "values-time",
        "values-time (partition value as timestamp) | update-time "
        "(last data update)")
    PARTITION_EXPIRATION_BATCH_SIZE = ConfigOption(
        "partition.expiration-batch-size", int, 1000,
        "Partitions dropped per expire commit")
    END_INPUT_CHECK_PARTITION_EXPIRE = ConfigOption(
        "end-input.check-partition-expire", _parse_bool, False,
        "Run partition expiry when a batch/bounded-stream job ends")

    # -- sort compaction (reference CoreOptions.java:2560-2600) --------------
    SORT_COMPACTION_RANGE_STRATEGY = ConfigOption(
        "sort-compaction.range-strategy", str, "quantity",
        "quantity | size: how sort-compaction partitions key ranges")
    SORT_COMPACTION_LOCAL_SAMPLE_MAGNIFICATION = ConfigOption(
        "sort-compaction.local-sample.magnification", int, 1000,
        "Sample count multiplier for range boundary estimation")
    CLUSTERING_COLUMNS = ConfigOption(
        "clustering.columns", str, None,
        "Columns for clustered (z-order/hilbert/order) layout (csv)")
    CLUSTERING_STRATEGY = ConfigOption(
        "clustering.strategy", str, "auto",
        "auto | zorder | hilbert | order: curve for clustering.columns "
        "(auto: zorder <= 4 columns, hilbert <= 8, else order)")
    ZORDER_VAR_LENGTH_CONTRIBUTION = ConfigOption(
        "zorder.var-length-contribution", int, 8,
        "Prefix bytes a var-length column contributes to the z-curve")

    # -- variant shredding (reference CoreOptions.java:3210-3280) ------------
    VARIANT_SHREDDING_SCHEMA = ConfigOption(
        "variant.shreddingSchema", str, None,
        "Explicit shredding paths per variant column, "
        "'col:$.a,$.b;col2:$.x'")
    VARIANT_INFER_SHREDDING_SCHEMA = ConfigOption(
        "variant.inferShreddingSchema", _parse_bool, False,
        "Infer shredded columns from a buffered row sample")
    VARIANT_SHREDDING_MAX_INFER_BUFFER_ROW = ConfigOption(
        "variant.shredding.maxInferBufferRow", int, 1000,
        "Rows sampled for shredding-schema inference")
    VARIANT_SHREDDING_MAX_SCHEMA_DEPTH = ConfigOption(
        "variant.shredding.maxSchemaDepth", int, 5,
        "Max nesting depth of inferred shredded paths")
    VARIANT_SHREDDING_MAX_SCHEMA_WIDTH = ConfigOption(
        "variant.shredding.maxSchemaWidth", int, 50,
        "Max inferred shredded paths per variant column")
    VARIANT_SHREDDING_MIN_FIELD_CARDINALITY_RATIO = ConfigOption(
        "variant.shredding.minFieldCardinalityRatio", float, 0.5,
        "A path must appear in at least this share of sampled rows")

    # -- global index (reference CoreOptions.java:3010-3120) -----------------
    GLOBAL_INDEX_ENABLED = ConfigOption(
        "global-index.enabled", _parse_bool, False,
        "Maintain the persisted sorted key->row-id global index at "
        "commit time (else built lazily on first use)")
    GLOBAL_INDEX_ROW_COUNT_PER_SHARD = ConfigOption(
        "global-index.row-count-per-shard", int, 10_000_000,
        "Shard bound of a global index build")
    GLOBAL_INDEX_BUILD_MAX_PARALLELISM = ConfigOption(
        "global-index.build.max-parallelism", int, 8,
        "Parallel shard builders for a global index build")
    GLOBAL_INDEX_SEARCH_MODE = ConfigOption(
        "global-index.search-mode", str, "auto",
        "auto | memory | sst: where point lookups probe the index")

    # -- blobs (reference CoreOptions.java:3300-3400) ------------------------
    BLOB_FIELD = ConfigOption(
        "blob-field", str, None,
        "Column stored as .blob sidecar files (auto-detected from the "
        "BLOB type when unset)")
    BLOB_TARGET_FILE_SIZE = ConfigOption(
        "blob.target-file-size", parse_memory_size, None,
        "Roll blob sidecar files at this size (default: "
        "target-file-size)")
    BLOB_AS_DESCRIPTOR = ConfigOption(
        "blob-as-descriptor", _parse_bool, False,
        "Reads return blob descriptors (uri, offset, length) instead "
        "of materialized bytes")

    FIELDS_DEFAULT_VALUE = ConfigOption(
        "fields.#.default-value", str, None,
        "Default for column '#': NULL incoming values are replaced at "
        "write time (reference DefaultValueRow / fields.*.default-value)")

    def field_default_values(self) -> Dict[str, str]:
        """{column: raw default} from fields.<col>.default-value keys."""
        out = {}
        for k in self.options.keys():
            if k.startswith("fields.") and k.endswith(".default-value"):
                col = k[len("fields."):-len(".default-value")]
                if col and col != "#":
                    out[col] = self.options.get_or(k, None)
        return out

    # -- streaming / incremental variants ------------------------------------
    STREAMING_READ_SNAPSHOT_DELAY = ConfigOption(
        "streaming.read.snapshot.delay", _parse_duration_ms, None,
        "Incremental snapshots become visible to streaming reads only "
        "after aging this long (absorbs small out-of-order commits)")
    INCREMENTAL_BETWEEN_TAG_TO_SNAPSHOT = ConfigOption(
        "incremental-between-tag-to-snapshot", str, None,
        "'tagName,snapshotId': batch-read the deltas from a tag's "
        "snapshot (exclusive) to a snapshot id (inclusive)")
    PARTITION_END_INPUT_TO_DONE = ConfigOption(
        "partition.end-input-to-done", _parse_bool, False,
        "Mark the partitions a batch write touched as done when its "
        "commit lands")

    # -- external data paths (reference CoreOptions.java:210-236) ------------
    DATA_FILE_EXTERNAL_PATHS = ConfigOption(
        "data-file.external-paths", str, None,
        "Comma-separated storage roots for NEW data files; readers "
        "follow the per-file external path recorded in the manifest")
    DATA_FILE_EXTERNAL_PATHS_STRATEGY = ConfigOption(
        "data-file.external-paths.strategy",
        _enum("NONE", "ROUND-ROBIN", "SPECIFIC-FS"), "NONE",
        "none: ignore external paths; round-robin: rotate across "
        "them; specific-fs: only roots whose scheme matches "
        "data-file.external-paths.specific-fs")
    DATA_FILE_EXTERNAL_PATHS_SPECIFIC_FS = ConfigOption(
        "data-file.external-paths.specific-fs", str, None,
        "Scheme filter (e.g. 'oss', 's3') for strategy=specific-fs")

    # -- callbacks (reference CoreOptions commit.callbacks /
    # tag.callbacks + CommitCallback/TagCallback SPIs) -----------------------
    COMMIT_CALLBACKS = ConfigOption(
        "commit.callbacks", str, None,
        "Comma-separated import paths ('pkg.mod:Class') instantiated "
        "and invoked after every successful commit")
    COMMIT_CALLBACK_PARAM = ConfigOption(
        "commit.callback.#.param", str, None,
        "Constructor parameter for the callback class named '#' "
        "(template key: substitute the class path)")
    TAG_CALLBACKS = ConfigOption(
        "tag.callbacks", str, None,
        "Comma-separated import paths invoked after tag creation")
    TAG_CALLBACK_PARAM = ConfigOption(
        "tag.callback.#.param", str, None,
        "Constructor parameter for the tag callback named '#'")

    # -- read-side toggles ---------------------------------------------------
    TABLE_READ_SEQUENCE_NUMBER = ConfigOption(
        "table-read.sequence-number.enabled", _parse_bool, False,
        "Expose _SEQUENCE_NUMBER as a metadata column in merge-on-read "
        "scans")
    KV_SEQUENCE_NUMBER_ENABLED = ConfigOption(
        "key-value.sequence_number.enabled", _parse_bool, True,
        "Maintain per-record sequence numbers in the KV plane (false: "
        "arrival order within a commit is the only order)")
    SCAN_IGNORE_CORRUPT_FILES = ConfigOption(
        "scan.ignore-corrupt-files", _parse_bool, False,
        "Skip unreadable data files during scans (warn) instead of "
        "failing the query")
    DELETION_VECTORS_MERGE_ON_READ = ConfigOption(
        "deletion-vectors.merge-on-read", _parse_bool, True,
        "Apply deletion vectors during reads (false: raw rows visible, "
        "for debugging/audit scans)")
    PARQUET_ENABLE_DICTIONARY = ConfigOption(
        "parquet.enable.dictionary", _parse_bool, True,
        "Dictionary-encode parquet columns (disable for "
        "high-cardinality data)")

    # -- compaction picking knobs (reference CoreOptions.java
    # compaction.* family) ---------------------------------------------------
    COMPACTION_FORCE_REWRITE_ALL_FILES = ConfigOption(
        "compaction.force-rewrite-all-files", _parse_bool, False,
        "Full compaction rewrites every file even when the bucket is "
        "already a single top-level run (forces DV folding / format "
        "upgrades)")
    COMPACTION_DELETE_RATIO_THRESHOLD = ConfigOption(
        "compaction.delete-ratio-threshold", float, 0.2,
        "Append tables: force-compact a data file once deletion "
        "vectors mark more than this share of its rows deleted")
    COMPACTION_SMALL_FILE_RATIO = ConfigOption(
        "compaction.small-file-ratio", float, 0.7,
        "Files below target-file-size * this ratio are picked for "
        "compaction rewriting (avoids re-compacting outputs that "
        "compressed slightly under target)")
    COMPACTION_OFFPEAK_START_HOUR = ConfigOption(
        "compaction.offpeak.start.hour", int, -1,
        "Start hour (0-23) of the off-peak window; -1 disables")
    COMPACTION_OFFPEAK_END_HOUR = ConfigOption(
        "compaction.offpeak.end.hour", int, -1,
        "End hour (0-23, exclusive) of the off-peak window; -1 "
        "disables")
    COMPACTION_OFFPEAK_RATIO = ConfigOption(
        "compaction.offpeak-ratio", int, 0,
        "compaction.size-ratio used during off-peak hours (larger = "
        "more aggressive merges while the cluster is idle)")

    # -- postpone bucket mode (reference postpone.* family) ------------------
    POSTPONE_DEFAULT_BUCKET_NUM = ConfigOption(
        "postpone.default-bucket-num", int, 4,
        "Bucket count chosen when rescale_postpone runs without an "
        "explicit target")
    POSTPONE_TARGET_ROW_NUM_PER_BUCKET = ConfigOption(
        "postpone.target-row-num-per-bucket", int, 5_000_000,
        "Rows per bucket targeted when sizing the rescale of postponed "
        "data")

    # -- schema evolution toggles --------------------------------------------
    ALTER_NULL_TO_NOT_NULL_DISABLED = ConfigOption(
        "alter-column-null-to-not-null.disabled", _parse_bool, True,
        "Refuse ALTER that tightens a nullable column to NOT NULL "
        "(existing nulls would break readers)")
    DISABLE_EXPLICIT_TYPE_CASTING = ConfigOption(
        "disable-explicit-type-casting", _parse_bool, False,
        "Refuse ALTER column-type changes that require a value cast "
        "(only metadata-compatible widenings allowed)")
    ADD_COLUMN_BEFORE_PARTITION = ConfigOption(
        "add-column-before-partition", _parse_bool, False,
        "New columns are inserted before the partition columns instead "
        "of appended at the end")

    # -- materialized table metadata (reference CoreOptions.java
    # materialized-table.* — engine-facing refresh contract carried in
    # table options; validated here, consumed by engines) --------------------
    MATERIALIZED_TABLE_DEFINITION_QUERY = ConfigOption(
        "materialized-table.definition-query", str, None,
        "The SELECT defining the materialized table's content")
    MATERIALIZED_TABLE_INTERVAL_FRESHNESS = ConfigOption(
        "materialized-table.interval-freshness", str, None,
        "Freshness interval value, e.g. '5'")
    MATERIALIZED_TABLE_INTERVAL_FRESHNESS_TIME_UNIT = ConfigOption(
        "materialized-table.interval-freshness.time-unit",
        _enum("SECOND", "MINUTE", "HOUR", "DAY"),
        None, "Unit of interval-freshness")
    MATERIALIZED_TABLE_LOGICAL_REFRESH_MODE = ConfigOption(
        "materialized-table.logical-refresh-mode",
        _enum("CONTINUOUS", "FULL", "AUTOMATIC"),
        None, "Declared refresh mode")
    MATERIALIZED_TABLE_REFRESH_MODE = ConfigOption(
        "materialized-table.refresh-mode",
        _enum("CONTINUOUS", "FULL"),
        None, "Resolved physical refresh mode")
    MATERIALIZED_TABLE_REFRESH_STATUS = ConfigOption(
        "materialized-table.refresh-status",
        _enum("INITIALIZING", "ACTIVATED", "SUSPENDED"),
        None, "Refresh pipeline status")
    MATERIALIZED_TABLE_REFRESH_HANDLER_DESCRIPTION = ConfigOption(
        "materialized-table.refresh-handler-description", str, None,
        "Human-readable locator of the refresh job")
    MATERIALIZED_TABLE_REFRESH_HANDLER_BYTES = ConfigOption(
        "materialized-table.refresh-handler-bytes", str, None,
        "Serialized refresh handler (base64)")

    def __init__(self, options):
        if isinstance(options, dict):
            options = Options(options)
        self.options: Options = options

    # -- convenience accessors ----------------------------------------------

    def get(self, option: ConfigOption):
        return self.options.get(option)

    @property
    def bucket(self) -> int:
        return self.options.get(CoreOptions.BUCKET)

    @property
    def bucket_key(self):
        v = self.options.get(CoreOptions.BUCKET_KEY)
        return [s.strip() for s in v.split(",")] if v else []

    @property
    def file_format(self) -> str:
        return self.options.get(CoreOptions.FILE_FORMAT)

    @property
    def file_format_per_level(self):
        """{level: format} overrides (reference
        CoreOptions.fileFormatPerLevel)."""
        v = self.options.get(CoreOptions.FILE_FORMAT_PER_LEVEL)
        out = {}
        if v:
            for part in v.split(","):
                lvl, sep, fmt = part.partition(":")
                if not sep or not fmt.strip() or not lvl.strip():
                    raise ValueError(
                        f"file.format.per.level entry {part!r} must be "
                        f"'<level>:<format>' (e.g. '0:avro,5:parquet')")
                try:
                    level = int(lvl.strip())
                except ValueError:
                    raise ValueError(
                        f"file.format.per.level level {lvl.strip()!r} "
                        f"is not an integer") from None
                out[level] = fmt.strip().lower()
        return out

    @property
    def format_options(self):
        """Raw format-writer tuning options, forwarded to the format SPI
        (reference FileFormat factories receive the full options and
        read their own prefix, e.g. parquet.enable.dictionary).
        file.block-size rides along as the cross-format block/stripe
        granularity."""
        out = {k: v for k, v in self.options._map.items()
               if k.startswith(("parquet.", "orc.", "avro."))}
        bs = self.options.get(CoreOptions.FILE_BLOCK_SIZE)
        if bs is not None:
            out["file.block-size"] = str(bs)
        return out

    @property
    def file_compression_per_level(self):
        """{level: codec} overrides (reference
        CoreOptions.fileCompressionPerLevel)."""
        v = self.options.get(CoreOptions.FILE_COMPRESSION_PER_LEVEL)
        out = {}
        if v:
            for part in v.split(","):
                lvl, sep, codec = part.partition(":")
                if not sep or not codec.strip() or not lvl.strip():
                    raise ValueError(
                        f"file.compression.per.level entry {part!r} "
                        f"must be '<level>:<codec>'")
                out[int(lvl.strip())] = codec.strip().lower()
        return out

    @property
    def stats_mode_per_level(self):
        """{level: stats-mode} overrides (reference
        CoreOptions.statsModePerLevel)."""
        v = self.options.get(CoreOptions.METADATA_STATS_MODE_PER_LEVEL)
        out = {}
        if v:
            for part in v.split(","):
                lvl, sep, mode = part.partition(":")
                if not sep or not mode.strip() or not lvl.strip():
                    raise ValueError(
                        f"metadata.stats-mode.per.level entry {part!r} "
                        f"must be '<level>:<mode>'")
                out[int(lvl.strip())] = mode.strip().lower()
        return out

    def kv_writer_kwargs(self) -> Dict[str, Any]:
        """The per-level / stats / rolling tuning shared by every
        KeyValueFileWriter construction site."""
        return {
            "compression_per_level": self.file_compression_per_level,
            "target_file_row_num": self.options.get(
                CoreOptions.TARGET_FILE_ROW_NUM),
            "stats_mode_per_level": self.stats_mode_per_level,
            "stats_keep_first_n": self.options.get(
                CoreOptions.METADATA_STATS_KEEP_FIRST_N_COLUMNS),
        }

    @property
    def file_compression(self) -> str:
        codec = self.options.get(CoreOptions.FILE_COMPRESSION)
        level = self.options.get(CoreOptions.FILE_COMPRESSION_ZSTD_LEVEL)
        if level is not None and codec == "zstd":
            # "codec:level" spec understood by the format writers
            return f"zstd:{level}"
        return codec

    @property
    def merge_engine(self) -> str:
        return self.options.get(CoreOptions.MERGE_ENGINE)

    @property
    def changelog_producer(self) -> str:
        return self.options.get(CoreOptions.CHANGELOG_PRODUCER)

    @property
    def sequence_field(self):
        v = self.options.get(CoreOptions.SEQUENCE_FIELD)
        return [s.strip() for s in v.split(",")] if v else []

    @property
    def sequence_field_descending(self) -> bool:
        return self.options.get(
            CoreOptions.SEQUENCE_FIELD_SORT_ORDER) == "descending"

    @property
    def changelog_file_format(self) -> str:
        return self.options.get(CoreOptions.CHANGELOG_FILE_FORMAT) or \
            self.file_format

    @property
    def changelog_file_compression(self) -> str:
        return self.options.get(
            CoreOptions.CHANGELOG_FILE_COMPRESSION) or \
            self.file_compression

    @property
    def changelog_file_prefix(self) -> str:
        return self.options.get(CoreOptions.CHANGELOG_FILE_PREFIX)

    @property
    def target_file_size(self) -> int:
        return self.options.get(CoreOptions.TARGET_FILE_SIZE)

    @property
    def write_buffer_size(self) -> int:
        return self.options.get(CoreOptions.WRITE_BUFFER_SIZE)

    @property
    def write_only(self) -> bool:
        return self.options.get(CoreOptions.WRITE_ONLY)

    @property
    def num_sorted_runs_compaction_trigger(self) -> int:
        return self.options.get(CoreOptions.NUM_SORTED_RUNS_COMPACTION_TRIGGER)

    @property
    def num_sorted_runs_stop_trigger(self) -> int:
        v = self.options.get(CoreOptions.NUM_SORTED_RUNS_STOP_TRIGGER)
        if v is None:
            return self.num_sorted_runs_compaction_trigger + 3
        return v

    @property
    def num_levels(self) -> int:
        v = self.options.get(CoreOptions.NUM_LEVELS)
        if v is None:
            return self.num_sorted_runs_compaction_trigger + 1
        return v

    @property
    def max_level(self) -> int:
        """The LSM's top level — the single definition shared by the
        read-optimized view (system.py, iceberg/metadata.py) and the
        sharded compaction/rescale output level."""
        return self.num_levels - 1

    @property
    def max_size_amplification_percent(self) -> int:
        return self.options.get(
            CoreOptions.COMPACTION_MAX_SIZE_AMPLIFICATION_PERCENT)

    @property
    def size_ratio(self) -> int:
        return self.options.get(CoreOptions.COMPACTION_SIZE_RATIO)

    @property
    def compaction_min_file_num(self) -> int:
        return self.options.get(CoreOptions.COMPACTION_MIN_FILE_NUM)

    @property
    def bloom_filter_columns(self):
        v = self.options.get(CoreOptions.FILE_INDEX_BLOOM_COLUMNS)
        return [c.strip() for c in v.split(",")] if v else []

    @property
    def file_index_spec(self):
        """index-type name -> column list, for every configured
        file-index kind (consumed by index/file_index.py)."""
        spec = {}
        for name, opt in (
                ("bloom-filter", CoreOptions.FILE_INDEX_BLOOM_COLUMNS),
                ("bitmap", CoreOptions.FILE_INDEX_BITMAP_COLUMNS),
                ("bsi", CoreOptions.FILE_INDEX_BSI_COLUMNS),
                ("range-bitmap",
                 CoreOptions.FILE_INDEX_RANGE_BITMAP_COLUMNS)):
            v = self.options.get(opt)
            cols = [c.strip() for c in v.split(",") if c.strip()] \
                if v else []
            if cols:
                spec[name] = cols
        return spec

    @property
    def deletion_vectors_enabled(self) -> bool:
        return self.options.get(CoreOptions.DELETION_VECTORS_ENABLED)

    @property
    def snapshot_num_retained_min(self) -> int:
        return self.options.get(CoreOptions.SNAPSHOT_NUM_RETAINED_MIN)

    @property
    def snapshot_num_retained_max(self) -> int:
        return self.options.get(CoreOptions.SNAPSHOT_NUM_RETAINED_MAX)

    @property
    def snapshot_time_retained_ms(self) -> int:
        return self.options.get(CoreOptions.SNAPSHOT_TIME_RETAINED)

    @property
    def branch(self) -> str:
        return self.options.get(CoreOptions.BRANCH)

    @property
    def scan_mode(self) -> str:
        return self.options.get(CoreOptions.SCAN_MODE)

    @property
    def consumer_id(self):
        return self.options.get(CoreOptions.CONSUMER_ID)

    @property
    def startup_mode(self) -> str:
        mode = self.options.get(CoreOptions.SCAN_MODE)
        if mode == StartupMode.DEFAULT:
            if self.options.get(CoreOptions.SCAN_SNAPSHOT_ID) is not None:
                return StartupMode.FROM_SNAPSHOT
            if self.options.get(CoreOptions.SCAN_TIMESTAMP_MILLIS) is not None:
                return StartupMode.FROM_TIMESTAMP
            if self.options.get(CoreOptions.INCREMENTAL_BETWEEN) is not None:
                return StartupMode.INCREMENTAL
            return StartupMode.LATEST_FULL
        return mode

    @property
    def key_prefix_lanes(self) -> int:
        return self.options.get(CoreOptions.KEY_PREFIX_LANES)

    @property
    def write_batch_rows(self) -> int:
        return self.options.get(CoreOptions.WRITE_BATCH_ROWS)

    @property
    def dynamic_bucket_target_row_num(self) -> int:
        return self.options.get(CoreOptions.DYNAMIC_BUCKET_TARGET_ROW_NUM)

    @property
    def full_compaction_delta_commits(self):
        return self.options.get(CoreOptions.FULL_COMPACTION_DELTA_COMMITS)

    @property
    def record_level_expire_time_ms(self):
        return self.options.get(CoreOptions.RECORD_LEVEL_EXPIRE_TIME)

    @property
    def record_level_time_field(self):
        return self.options.get(CoreOptions.RECORD_LEVEL_TIME_FIELD)

    def to_map(self) -> Dict[str, str]:
        return self.options.to_map()
