"""Process-shared delta-apply plan cache (the incremental metadata
plane's state holder).

At production scale (10^6+ live files under continuous streaming
commits) re-walking and re-decoding every manifest on every
`FileStoreScan.plan` makes PLANNING the bottleneck no data cache
hides.  This cache applies the delta/main split that already won the
serving tier (Fast Updates on Read-Optimized Databases, arxiv
1109.6885) to *metadata*: the merged live-entry set of snapshot N is
kept in memory, grouped by (partition, bucket), and a plan for
snapshot N+k advances it by reading ONLY the delta manifest lists of
snapshots N+1..N+k — steady-state streaming re-plans touch O(delta)
metadata.  A second level caches the GENERATED splits per filter
signature, so untouched groups do not even re-run split generation.

Correctness contract (enforced by core/scan.py's advance logic and
the entry-identity oracle in tests/test_metadata_plane.py):

* OVERWRITE commits (INSERT OVERWRITE, dropped partitions, bucket
  rescale) INVALIDATE the state instead of delta-applying — their
  delete set was computed against a racing latest and must never be
  folded blind.
* a missing snapshot in the walk (expired under us), a DELETE entry
  whose identifier is not live, or a cached tip whose manifest-list
  names no longer match the presented snapshot (rollback/fast-forward
  recreated the id) all invalidate.
* states are immutable after publish: advancing copies the outer
  group dict (O(#groups)) and only the touched groups' entry dicts
  (O(delta)), so concurrent planners never observe a torn state.

The cache is advisory only — every invalidation falls back to the
cold full walk, and `scan.plan.cache.max-entries` bounds how big a
table it will hold (plus a process-wide LRU over tables).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

__all__ = ["PlanState", "SplitState", "TablePlanCache",
           "shared_plan_cache", "reset_plan_caches"]

# (partition_bytes, bucket) group key
GroupKey = Tuple[bytes, int]

_MAX_TABLES = 16          # process-wide LRU over per-table caches
_MAX_SPLIT_SIGS = 8       # per-table LRU over filter signatures


class PlanState:
    """Immutable-after-publish live-entry set at one snapshot."""

    __slots__ = ("snapshot_id", "base_list", "delta_list",
                 "index_manifest", "groups", "entry_count")

    def __init__(self, snapshot_id: int, base_list: str, delta_list: str,
                 index_manifest: Optional[str],
                 groups: Dict[GroupKey, Dict[tuple, object]],
                 entry_count: int):
        self.snapshot_id = snapshot_id
        self.base_list = base_list
        self.delta_list = delta_list
        self.index_manifest = index_manifest
        self.groups = groups
        self.entry_count = entry_count

    def matches_tip(self, snapshot) -> bool:
        """Guards recreated snapshot ids (rollback_to / fast_forward
        can delete and REWRITE an id with different content)."""
        return (snapshot.base_manifest_list == self.base_list
                and snapshot.delta_manifest_list == self.delta_list)


class SplitState:
    """Generated splits per group for ONE filter signature, valid at
    one (snapshot, index_manifest) point."""

    __slots__ = ("snapshot_id", "index_manifest", "group_splits")

    def __init__(self, snapshot_id: int, index_manifest: Optional[str],
                 group_splits: Dict[GroupKey, tuple]):
        self.snapshot_id = snapshot_id
        self.index_manifest = index_manifest
        self.group_splits = group_splits


class TablePlanCache:
    """One table+branch's plan state; all access under `lock`."""

    def __init__(self):
        self.lock = threading.Lock()
        self._state: Optional[PlanState] = None
        self._splits: "OrderedDict[tuple, SplitState]" = OrderedDict()
        # memoized UNFILTERED deletion-vector index, keyed by index
        # manifest name (None name -> {} without any IO)
        self._dv_key: Optional[str] = None
        self._dv_index: Optional[dict] = None
        # tip snapshot known to exceed scan.plan.cache.max-entries:
        # planners skip the cold-state attempt (whose full walk they
        # would discard) and go straight to the pruned fallback
        self._over_bound_id: Optional[int] = None

    # -- entry state ---------------------------------------------------------

    def state(self) -> Optional[PlanState]:
        with self.lock:
            return self._state

    def put_state(self, new: PlanState,
                  expect: Optional[PlanState]) -> None:
        """Publish `new` unless a concurrent planner advanced past it
        (never regress the cached snapshot)."""
        with self.lock:
            cur = self._state
            if cur is None or cur is expect or \
                    cur.snapshot_id < new.snapshot_id:
                self._state = new

    def drop_state(self, expect: Optional[PlanState]) -> None:
        """Invalidate (only the observed state: a fresher concurrent
        publish survives).  Split states die with it — they were
        derived from the same walk."""
        with self.lock:
            if expect is None or self._state is expect:
                self._state = None
                self._splits.clear()
                self._dv_key = None
                self._dv_index = None

    def over_bound(self, snapshot_id: int) -> bool:
        with self.lock:
            return self._over_bound_id == snapshot_id

    def mark_over_bound(self, snapshot_id: int) -> None:
        with self.lock:
            self._over_bound_id = snapshot_id

    # -- split states --------------------------------------------------------

    def split_state(self, sig: tuple) -> Optional[SplitState]:
        with self.lock:
            st = self._splits.get(sig)
            if st is not None:
                self._splits.move_to_end(sig)
            return st

    def put_split_state(self, sig: tuple, st: SplitState) -> None:
        with self.lock:
            self._splits[sig] = st
            self._splits.move_to_end(sig)
            while len(self._splits) > _MAX_SPLIT_SIGS:
                self._splits.popitem(last=False)

    # -- deletion-vector memo ------------------------------------------------

    def dv_memo(self, key: Optional[str]):
        """(hit, dv_index) for the given index-manifest name."""
        with self.lock:
            if self._dv_key == key and self._dv_index is not None:
                return True, self._dv_index
            return False, None

    def put_dv_memo(self, key: Optional[str], dv_index: dict) -> None:
        with self.lock:
            self._dv_key = key
            self._dv_index = dv_index


_LOCK = threading.Lock()
_CACHES: "OrderedDict[tuple, TablePlanCache]" = OrderedDict()


def shared_plan_cache(table_path: str, branch: str) -> TablePlanCache:
    """The process-wide cache for one table+branch (LRU-bounded: a
    long test/serving process touching many tables stays bounded)."""
    key = (table_path.rstrip("/"), branch or "main")
    with _LOCK:
        cache = _CACHES.get(key)
        if cache is None:
            cache = TablePlanCache()
            _CACHES[key] = cache
        _CACHES.move_to_end(key)
        while len(_CACHES) > _MAX_TABLES:
            _CACHES.popitem(last=False)
        return cache


def reset_plan_caches() -> None:
    """Drop every cached plan state (test / bench hook)."""
    with _LOCK:
        _CACHES.clear()
