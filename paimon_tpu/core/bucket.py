"""Bucket assignment.

reference: paimon-common/.../utils/MurmurHashUtils + table/sink/
KeyAndBucketExtractor: bucket = abs(javaRem(murmur32_words(binaryRow bytes
without arity prefix, seed=42), numBuckets)). Matching the reference hash
bit-for-bit keeps our data files bucket-compatible with JVM/pypaimon
readers and writers.

The hash is vectorized over rows with numpy when the bucket key serializes
to fixed-width BinaryRows (int/float/date keys); variable-width keys fall
back to a per-row loop.
"""

from __future__ import annotations

import struct
from typing import Any, List, Sequence, Tuple

import numpy as np
import pyarrow as pa

from paimon_tpu.data.binary_row import BinaryRowCodec
from paimon_tpu.types import (
    BigIntType, BooleanType, DataType, DateType, DoubleType, FloatType,
    IntType, SmallIntType, TimeType, TinyIntType,
)

__all__ = ["murmur_hash_bytes", "KeyHasher", "FixedBucketAssigner",
           "bucket_of"]

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_SEED = 42
_M32 = 0xFFFFFFFF


def murmur_hash_bytes(data: bytes, seed: int = _SEED) -> int:
    """Murmur3-style word hash over complete 4-byte words (tail bytes
    ignored, matching the reference's hashBytesByWords)."""
    n = len(data)
    h1 = seed
    for i in range(0, n - (n % 4), 4):
        k1 = struct.unpack_from("<I", data, i)[0]
        k1 = (k1 * _C1) & _M32
        k1 = ((k1 << 15) | (k1 >> 17)) & _M32
        k1 = (k1 * _C2) & _M32
        h1 = (h1 ^ k1) & _M32
        h1 = ((h1 << 13) | (h1 >> 19)) & _M32
        h1 = (h1 * 5 + 0xE6546B64) & _M32
    return _fmix(h1, n)


def _fmix(h1: int, length: int) -> int:
    h1 = (h1 ^ length) & _M32
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & _M32
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & _M32
    h1 ^= h1 >> 16
    return h1


def _bucket_from_hash(h: np.ndarray, num_buckets: int) -> np.ndarray:
    """Java `Math.abs(h % n)` with truncated division, vectorized."""
    signed = h.astype(np.int64)
    signed = np.where(signed >= 1 << 31, signed - (1 << 32), signed)
    rem = signed - np.trunc(signed / num_buckets).astype(np.int64) \
        * num_buckets
    return np.abs(rem).astype(np.int32)


def bucket_of(values: Sequence[Any], types: Sequence[DataType],
              num_buckets: int) -> int:
    codec = BinaryRowCodec(types)
    data = codec.to_bytes(values, with_arity_prefix=False)
    h = murmur_hash_bytes(data)
    return int(_bucket_from_hash(np.array([h], dtype=np.uint64),
                                 num_buckets)[0])


_FIXED_SLOT_TYPES = (BooleanType, TinyIntType, SmallIntType, IntType,
                     BigIntType, FloatType, DoubleType, DateType, TimeType)


class KeyHasher:
    """Vectorized reference-compatible murmur hash of bucket-key rows
    (the shared base of fixed and dynamic bucket assignment)."""

    def __init__(self, bucket_key_names: Sequence[str],
                 bucket_key_types: Sequence[DataType]):
        self.names = list(bucket_key_names)
        self.types = list(bucket_key_types)
        self._codec = BinaryRowCodec(self.types)
        self._fixed_width = all(isinstance(t, _FIXED_SLOT_TYPES)
                                for t in self.types)

    def hashes(self, table: pa.Table) -> np.ndarray:
        """uint64[N] murmur hashes (low 32 bits significant)."""
        # the numpy path's fixed setup (byte matrix + casts) costs more
        # than row-at-a-time hashing below ~10 rows — point-lookup
        # batches take the scalar codec path, ingest batches the
        # vectorized one; both produce identical reference hashes
        if self._fixed_width and table.num_rows > 8:
            return self._hash_vectorized(table)
        return self._hash_rows(table)

    def _hash_rows(self, table: pa.Table) -> np.ndarray:
        cols = [table.column(n).to_pylist() for n in self.names]
        out = np.empty(table.num_rows, dtype=np.uint64)
        for i in range(table.num_rows):
            values = tuple(c[i] for c in cols)
            data = self._codec.to_bytes(values, with_arity_prefix=False)
            out[i] = murmur_hash_bytes(data)
        return out

    def _hash_vectorized(self, table: pa.Table) -> np.ndarray:
        """Build the BinaryRow byte matrix for all rows at once, then run
        murmur word-mixing across rows with numpy."""
        n = table.num_rows
        arity = len(self.types)
        null_bytes = ((arity + 63 + 8) // 64) * 8
        row_len = null_bytes + arity * 8
        mat = np.zeros((n, row_len), dtype=np.uint8)
        for i, (name, t) in enumerate(zip(self.names, self.types)):
            col = table.column(name).combine_chunks()
            null_mask = np.asarray(col.is_null())
            slot = null_bytes + i * 8
            if isinstance(t, (BooleanType,)):
                vals = np.asarray(col.cast(pa.int8()).fill_null(0))
                mat[:, slot] = vals.astype(np.uint8)
            elif isinstance(t, TinyIntType):
                v = np.asarray(col.fill_null(0)).astype(np.int8)
                mat[:, slot:slot + 1] = v.view(np.uint8)[:, None]
            elif isinstance(t, SmallIntType):
                v = np.asarray(col.fill_null(0)).astype("<i2")
                mat[:, slot:slot + 2] = v.view(np.uint8).reshape(n, 2)
            elif isinstance(t, (IntType, DateType, TimeType)):
                v = np.asarray(col.cast(pa.int32()).fill_null(0)) \
                    .astype("<i4")
                mat[:, slot:slot + 4] = v.view(np.uint8).reshape(n, 4)
            elif isinstance(t, BigIntType):
                v = np.asarray(col.cast(pa.int64()).fill_null(0)) \
                    .astype("<i8")
                mat[:, slot:slot + 8] = v.view(np.uint8).reshape(n, 8)
            elif isinstance(t, FloatType):
                v = np.asarray(col.fill_null(0)).astype("<f4")
                mat[:, slot:slot + 4] = v.view(np.uint8).reshape(n, 4)
            elif isinstance(t, DoubleType):
                v = np.asarray(col.fill_null(0)).astype("<f8")
                mat[:, slot:slot + 8] = v.view(np.uint8).reshape(n, 8)
            if null_mask.any():
                idx = i + 8
                mat[null_mask, idx // 8] |= np.uint8(1 << (idx % 8))
                mat[null_mask, slot:slot + 8] = 0
        return self._murmur_rows(mat)

    def _murmur_rows(self, mat: np.ndarray) -> np.ndarray:
        n, row_len = mat.shape
        if n == 0:
            return np.empty(0, dtype=np.uint64)
        words = mat[:, :row_len - (row_len % 4)] \
            .reshape(n, -1, 4).view("<u4")[:, :, 0].astype(np.uint64)
        h1 = np.full(n, _SEED, dtype=np.uint64)
        m32 = np.uint64(_M32)
        for w in range(words.shape[1]):
            k1 = words[:, w]
            k1 = (k1 * np.uint64(_C1)) & m32
            k1 = ((k1 << np.uint64(15)) | (k1 >> np.uint64(17))) & m32
            k1 = (k1 * np.uint64(_C2)) & m32
            h1 = (h1 ^ k1) & m32
            h1 = ((h1 << np.uint64(13)) | (h1 >> np.uint64(19))) & m32
            h1 = (h1 * np.uint64(5) + np.uint64(0xE6546B64)) & m32
        h1 = (h1 ^ np.uint64(row_len)) & m32
        h1 ^= h1 >> np.uint64(16)
        h1 = (h1 * np.uint64(0x85EBCA6B)) & m32
        h1 ^= h1 >> np.uint64(13)
        h1 = (h1 * np.uint64(0xC2B2AE35)) & m32
        h1 ^= h1 >> np.uint64(16)
        return h1


class FixedBucketAssigner:
    """Vectorized fixed-bucket assignment for Arrow batches."""

    def __init__(self, bucket_key_names: Sequence[str],
                 bucket_key_types: Sequence[DataType], num_buckets: int):
        if num_buckets <= 0:
            raise ValueError(f"bucket must be > 0, got {num_buckets}")
        self.names = list(bucket_key_names)
        self.types = list(bucket_key_types)
        self.num_buckets = num_buckets
        self._hasher = KeyHasher(bucket_key_names, bucket_key_types)

    def assign(self, table: pa.Table) -> np.ndarray:
        return _bucket_from_hash(self._hasher.hashes(table),
                                 self.num_buckets)
