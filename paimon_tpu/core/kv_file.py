"""KeyValue data-file writer/reader.

reference: paimon-core/.../io/KeyValueDataFileWriter.java (flattens
KeyValue to `_KEY_<k...>, _SEQUENCE_NUMBER, _VALUE_KIND, value...`),
RollingFileWriter (target-size rolling), KeyValueFileReaderFactory.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from paimon_tpu.data.binary_row import BinaryRowCodec
from paimon_tpu.format import get_format
from paimon_tpu.format.format import extract_simple_stats
from paimon_tpu.fs import FileIO
from paimon_tpu.manifest import DataFileMeta, FileSource, SimpleStats
from paimon_tpu.options import CoreOptions
from paimon_tpu.ops.merge import KIND_COL, SEQ_COL
from paimon_tpu.schema.table_schema import TableSchema
from paimon_tpu.types import DataType, SpecialFields
from paimon_tpu.utils.path_factory import FileStorePathFactory

__all__ = ["KeyValueFileWriter", "read_kv_file", "KEY_PREFIX"]

KEY_PREFIX = SpecialFields.KEY_FIELD_PREFIX


class KeyValueFileWriter:
    """Writes sorted KV tables into rolling data files with stats."""

    def __init__(self, file_io: FileIO, path_factory: FileStorePathFactory,
                 table_schema: TableSchema, file_format: str = "parquet",
                 compression: str = "zstd",
                 target_file_size: int = 128 << 20,
                 index_spec: Optional[Dict[str, List[str]]] = None,
                 bloom_fpp: float = 0.01,
                 index_in_manifest_threshold: int = 500,
                 format_per_level: Optional[Dict[int, str]] = None,
                 format_options: Optional[Dict[str, str]] = None,
                 compression_per_level: Optional[Dict[int, str]] = None,
                 target_file_row_num: Optional[int] = None,
                 stats_mode_per_level: Optional[Dict[int, str]] = None,
                 stats_keep_first_n: Optional[int] = None):
        self.file_io = file_io
        self.path_factory = path_factory
        self.schema = table_schema
        self.file_format = file_format
        self.format_per_level = format_per_level or {}
        self.format_options = format_options or {}
        self.compression = compression
        self.compression_per_level = compression_per_level or {}
        self.target_file_size = target_file_size
        self.target_file_row_num = target_file_row_num
        self.stats_mode_per_level = stats_mode_per_level or {}
        self.stats_keep_first_n = stats_keep_first_n
        self.index_spec = index_spec or {}
        self.bloom_fpp = bloom_fpp
        self.index_in_manifest_threshold = index_in_manifest_threshold
        self.trimmed_pk = table_schema.trimmed_primary_keys()
        self.key_cols = [KEY_PREFIX + k for k in self.trimmed_pk]
        rt = table_schema.logical_row_type()
        self.key_types: List[DataType] = [rt.get_field(k).type
                                          for k in self.trimmed_pk]
        self._key_codec = BinaryRowCodec(
            [t.copy(False) for t in self.key_types])

    def write(self, partition: Tuple, bucket: int, kv_table: pa.Table,
              level: int,
              file_source: int = FileSource.APPEND) -> List[DataFileMeta]:
        """Write a sorted KV table, rolling at target_file_size.
        Returns DataFileMeta per file written."""
        if kv_table.num_rows == 0:
            return []
        n = kv_table.num_rows
        bytes_per_row = max(1, kv_table.nbytes // n)
        rows_per_file = max(1024, self.target_file_size // bytes_per_row)
        if self.target_file_row_num:
            # target-file-row-num: roll by rows too
            rows_per_file = min(rows_per_file, self.target_file_row_num)
        metas = []
        for start in range(0, n, rows_per_file):
            chunk = kv_table.slice(start, min(rows_per_file, n - start))
            metas.append(self._write_one(partition, bucket, chunk, level,
                                         file_source))
        return metas

    def _write_one(self, partition: Tuple, bucket: int, chunk: pa.Table,
                   level: int, file_source: int) -> DataFileMeta:
        fmt = get_format(self.format_per_level.get(level,
                                                   self.file_format))
        compression = self.compression_per_level.get(level,
                                                     self.compression)
        name = self.path_factory.new_data_file_name(fmt.extension)
        path, external = self.path_factory.new_data_file_location(
            partition, bucket, name)
        from paimon_tpu.format.blob import blob_column_names
        blob_cols = blob_column_names(self.schema)
        blob_extras: List[str] = []
        if blob_cols:
            from paimon_tpu.format.blob import externalize_blobs
            chunk, blob_extras = externalize_blobs(
                self.file_io, self.path_factory, partition, bucket, name,
                chunk, blob_cols)
        size = fmt.create_writer(compression,
                                 self.format_options).write(
            self.file_io, path, chunk)

        # key stats + min/max key (first/last row: chunk is key-sorted)
        kmins, kmaxs, knulls = extract_simple_stats(chunk, self.key_cols)
        key_stats = SimpleStats.from_values(
            [t.copy(False) for t in self.key_types], kmins, kmaxs, knulls)
        first = [chunk.column(c)[0].as_py() for c in self.key_cols]
        last = [chunk.column(c)[-1].as_py() for c in self.key_cols]

        value_cols = [f.name for f in self.schema.fields]
        value_types = [f.type for f in self.schema.fields]
        stats_mode = self.stats_mode_per_level.get(level)
        if stats_mode == "none":
            # metadata.stats-mode.per.level 'N:none': skip stats work
            # for short-lived files (planning treats absent stats as
            # unknown and never prunes on them)
            nil = [None] * len(value_cols)
            value_stats = _safe_stats(value_types, nil, nil,
                                      [None] * len(value_cols))
        else:
            vmins, vmaxs, vnulls = extract_simple_stats(chunk, value_cols)
            if self.stats_keep_first_n is not None:
                # metadata.stats-keep-first-n-columns: null out the rest
                k = self.stats_keep_first_n
                vmins = list(vmins[:k]) + [None] * (len(value_cols) - k)
                vmaxs = list(vmaxs[:k]) + [None] * (len(value_cols) - k)
            value_stats = _safe_stats(value_types, vmins, vmaxs, vnulls)

        seq = chunk.column(SEQ_COL)
        import pyarrow.compute as pc
        seq_min = pc.min(seq).as_py()
        seq_max = pc.max(seq).as_py()
        kinds = np.asarray(chunk.column(KIND_COL).combine_chunks()
                           .cast(pa.int8()))
        delete_rows = int(((kinds == 1) | (kinds == 3)).sum())

        embedded_index, extra_files = None, []
        if self.index_spec:
            from paimon_tpu.index.bloom import place_file_index
            from paimon_tpu.index.file_index import build_indexes_blob
            blob = build_indexes_blob(chunk, self.index_spec,
                                      self.bloom_fpp)
            embedded_index, extra_files = place_file_index(
                self.file_io, self.path_factory, partition, bucket, name,
                blob, self.index_in_manifest_threshold)

        return DataFileMeta(
            file_name=name,
            file_size=size,
            row_count=chunk.num_rows,
            min_key=self._key_codec.to_bytes(first),
            max_key=self._key_codec.to_bytes(last),
            key_stats=key_stats,
            value_stats=value_stats,
            min_sequence_number=seq_min,
            max_sequence_number=seq_max,
            schema_id=self.schema.id,
            level=level,
            delete_row_count=delete_rows,
            file_source=file_source,
            embedded_index=embedded_index,
            extra_files=extra_files + blob_extras,
            external_path=external,
        )


def _safe_stats(types: Sequence[DataType], mins, maxs, nulls) -> SimpleStats:
    """Encode stats, nulling out values BinaryRow can't carry (arrays,
    maps, rows) -- mirrors the reference's stats-mode truncation."""
    safe_mins, safe_maxs, safe_types = [], [], []
    for t, mn, mx in zip(types, mins, maxs):
        try:
            BinaryRowCodec([t]).to_bytes((mn,))
            BinaryRowCodec([t]).to_bytes((mx,))
            safe_mins.append(mn)
            safe_maxs.append(mx)
        except (ValueError, TypeError, OverflowError):
            safe_mins.append(None)
            safe_maxs.append(None)
        safe_types.append(t.as_nullable())
    codec = BinaryRowCodec(safe_types)
    return SimpleStats(codec.to_bytes(safe_mins), codec.to_bytes(safe_maxs),
                       list(nulls))


def write_changelog_file(file_io: FileIO,
                         path_factory: FileStorePathFactory,
                         schema: TableSchema, file_format: str,
                         compression: str, partition: Tuple, bucket: int,
                         table: pa.Table,
                         prefix: Optional[str] = None,
                         format_options: Optional[Dict[str, str]] = None
                         ) -> List[DataFileMeta]:
    """Write a changelog file (KV layout with _VALUE_KIND kinds kept).
    Shared by changelog-producer=input (write path) and the compaction
    changelog producers."""
    import pyarrow.compute as pc

    fmt = get_format(file_format)
    name = path_factory.new_changelog_file_name(fmt.extension, prefix)
    path, external = path_factory.new_data_file_location(
        partition, bucket, name)
    size = fmt.create_writer(compression, format_options).write(
        file_io, path, table)
    return [DataFileMeta(
        file_name=name, file_size=size, row_count=table.num_rows,
        min_key=b"", max_key=b"",
        key_stats=SimpleStats.EMPTY,
        value_stats=SimpleStats.EMPTY,
        min_sequence_number=pc.min(table.column(SEQ_COL)).as_py(),
        max_sequence_number=pc.max(table.column(SEQ_COL)).as_py(),
        schema_id=schema.id, level=0, external_path=external)]


def read_kv_file(file_io: FileIO, path_factory: FileStorePathFactory,
                 partition: Tuple, bucket: int, meta: DataFileMeta,
                 file_format: Optional[str] = None,
                 projection: Optional[List[str]] = None,
                 schema=None, schema_manager=None,
                 wanted=None, options=None) -> pa.Table:
    """Read one KV data file into Arrow. When `schema` is given, blob
    descriptor columns resolve against their .blob sidecars here — every
    reader is blob-safe by construction.  `options` gates the read-side
    footer cache (read.cache.footer, on by default)."""
    ext = meta.file_name.rsplit(".", 1)[-1]
    fmt = get_format(file_format or ext)
    path = path_factory.data_file_path(partition, bucket, meta.file_name)
    if meta.external_path:
        path = meta.external_path
    table = None
    if fmt.identifier == "parquet" and options is not None \
            and options.get(CoreOptions.READ_DEVICE_DECODE):
        from paimon_tpu.format.rawpage import maybe_read_device
        table = maybe_read_device(file_io, path, projection, options)
    if table is None:
        from paimon_tpu.fs.caching import footer_cache_scope
        with footer_cache_scope(options):
            table = fmt.create_reader().read(file_io, path,
                                             projection=projection)
    if schema is not None:
        from paimon_tpu.format.blob import maybe_resolve_blobs
        table = maybe_resolve_blobs(file_io, path_factory, partition,
                                    bucket, meta, table, schema,
                                    schema_manager=schema_manager,
                                    wanted=wanted)
    return table
