"""Row tracking + data evolution for append tables.

reference:
- row-id assignment at commit: `FileStoreCommitImpl.assignRowTracking`
  (paimon-core/.../operation/FileStoreCommitImpl.java:1046) — every ADD
  file of an append snapshot gets `firstRowId`, the snapshot records
  `nextRowId`, and a file's rows own ids [firstRowId, firstRowId+rows).
- column-level updates: evolution files carry a SUBSET of columns
  (`DataFileMeta.writeCols`) for an existing row range; reads group
  files by row range and take each column from the newest file that
  wrote it, anchored on the oldest full file
  (operation/DataEvolutionSplitRead.java:190 mergeRangesAndSort +
  utils/DataEvolutionUtils.retrieveAnchorFile:41).
- row-id deletes: deletion vectors resolved by row id
  (append/dataevolution/DataEvolutionCompactDeletionVectorRewriter.java).

TPU-first shape: ranges are dense, so every mapping here is arithmetic
on numpy vectors — row id -> (file, position) is a searchsorted over
range starts, update application is one scatter per file, and the
evolution read assembles Arrow columns without touching row data of
unchanged columns.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from paimon_tpu.manifest import (
    DataFileMeta, FileKind, FileSource, ManifestEntry, SimpleStats,
)

ROW_ID_COL = "_ROW_ID"

__all__ = ["ROW_ID_COL", "assign_row_ids", "group_row_ranges",
           "read_evolution_group", "update_columns", "delete_by_row_ids"]


def assign_row_ids(entries: List[ManifestEntry], start: int
                   ) -> Tuple[List[ManifestEntry], int]:
    """Give every ADD entry without a first_row_id a dense id range
    starting at `start`; returns the rewritten entries and the next free
    row id (reference FileStoreCommitImpl.assignRowTracking)."""
    out = []
    nxt = start
    for e in entries:
        if e.kind == FileKind.ADD and e.file.first_row_id is None:
            out.append(ManifestEntry(
                e.kind, e.partition, e.bucket, e.total_buckets,
                replace(e.file, first_row_id=nxt)))
            nxt += e.file.row_count
        else:
            out.append(e)
    return out, nxt


def group_row_ranges(files: Sequence[DataFileMeta]
                     ) -> List[List[DataFileMeta]]:
    """Group files whose [first_row_id, first_row_id + rows) ranges
    overlap; groups come back sorted by range start (reference
    DataEvolutionSplitRead.mergeRangesAndSort).  Files without a row id
    each form their own group."""
    untracked = [f for f in files if f.first_row_id is None]
    tracked = sorted((f for f in files if f.first_row_id is not None),
                     key=lambda f: (f.first_row_id, f.max_sequence_number,
                                    f.file_name))
    groups: List[List[DataFileMeta]] = [[f] for f in untracked]
    cur: List[DataFileMeta] = []
    cur_end = -1
    for f in tracked:
        if cur and f.first_row_id < cur_end:
            cur.append(f)
            cur_end = max(cur_end, f.first_row_id + f.row_count)
        else:
            if cur:
                groups.append(cur)
            cur = [f]
            cur_end = f.first_row_id + f.row_count
    if cur:
        groups.append(cur)
    return groups


def anchor_of(group: Sequence[DataFileMeta]) -> DataFileMeta:
    """The oldest file of a range group — the full-row base every
    evolution file overlays (reference DataEvolutionUtils
    .retrieveAnchorFile: min by (maxSequenceNumber, fileName))."""
    return min(group, key=lambda f: (f.max_sequence_number, f.file_name))


def _column_source(group: Sequence[DataFileMeta], column: str,
                   schema_cols: Sequence[str]) -> Optional[DataFileMeta]:
    """Newest file in the group that wrote `column`."""
    best = None
    for f in group:
        cols = f.write_cols if f.write_cols is not None else schema_cols
        if column in cols:
            if best is None or (f.max_sequence_number, f.file_name) > \
                    (best.max_sequence_number, best.file_name):
                best = f
    return best


def read_evolution_group(read, split, group: Sequence[DataFileMeta],
                         wanted: Sequence[str]) -> pa.Table:
    """Assemble the current rows of one row-range group: each wanted
    column comes whole from its newest writer; `_ROW_ID` (when in
    `wanted`) derives from the anchor's first_row_id.  `read` is the
    AppendSplitRead (supplies file reading + schema evolution)."""
    anchor = anchor_of(group)
    schema_cols = [f.name for f in read.schema.fields]

    # plan column -> source file first so every file is read exactly
    # once with only the columns it supplies (projection pushdown)
    sources: Dict[str, DataFileMeta] = {}
    per_file: Dict[str, List[str]] = {}
    metas: Dict[str, DataFileMeta] = {}
    for c in wanted:
        if c == ROW_ID_COL:
            continue
        src = _column_source(group, c, schema_cols)
        if src is None:                   # column added after every file
            src = anchor
        sources[c] = src
        metas[src.file_name] = src
        per_file.setdefault(src.file_name, []).append(c)

    cache: Dict[str, pa.Table] = {
        fname: read.read_file(split, metas[fname], wanted=cols)
        for fname, cols in per_file.items()}

    cols, names = [], []
    for c in wanted:
        if c == ROW_ID_COL:
            continue
        t = cache[sources[c].file_name]
        if c in t.column_names:
            col = t.column(c)
        else:
            arrow_t = read.arrow_type_of(c)
            col = pa.nulls(anchor.row_count, arrow_t)
        names.append(c)
        cols.append(col)
    out = pa.table(dict(zip(names, cols))) if names else \
        pa.table({"__dummy": pa.nulls(anchor.row_count)}) \
        .drop_columns(["__dummy"])
    if ROW_ID_COL in wanted and anchor.first_row_id is not None:
        rid = pa.array(np.arange(anchor.first_row_id,
                                 anchor.first_row_id + anchor.row_count,
                                 dtype=np.int64), pa.int64())
        out = out.append_column(ROW_ID_COL, rid)
    return out


def _load_bucket_dv_state(table, fs_scan, snapshot):
    """(prev DV index-manifest entries, DV index file writer) — the
    bootstrap shared by row-id deletes and evolution compaction."""
    from paimon_tpu.index.deletion_vector import DeletionVectorsIndexFile
    from paimon_tpu.index.dv_maintainer import DELETION_VECTORS_INDEX

    prev_entries = []
    if snapshot.index_manifest:
        prev_entries = [
            e for e in
            fs_scan.index_manifest_file.read(snapshot.index_manifest)
            if e.index_file.index_type == DELETION_VECTORS_INDEX]
    dv_index = DeletionVectorsIndexFile(table.file_io,
                                        f"{table.path}/index")
    return prev_entries, dv_index


def _write_tracked_file(table, fs_scan, split, chunk, *, row_count,
                        first_row_id, min_seq, max_seq, level=0,
                        file_source=None, write_cols=None,
                        stats_cols=None):
    """Encode one row-tracked data file + its DataFileMeta (shared by
    update_columns overlays and evolution compaction)."""
    from paimon_tpu.format import get_format
    from paimon_tpu.format.format import extract_simple_stats
    from paimon_tpu.core.kv_file import _safe_stats

    cols = stats_cols or [f.name for f in table.schema.fields]
    fmt = get_format(table.options.file_format)
    name = fs_scan.path_factory.new_data_file_name(fmt.extension)
    path, external = fs_scan.path_factory.new_data_file_location(
        split.partition, split.bucket, name)
    size = fmt.create_writer(table.options.file_compression,
                             table.options.format_options).write(
        table.file_io, path, chunk)
    mins, maxs, nulls = extract_simple_stats(chunk, cols)
    by_name = {f.name: f.type for f in table.schema.fields}
    types = [by_name[c] for c in cols]
    meta = DataFileMeta(
        file_name=name, file_size=size, row_count=row_count,
        min_key=b"", max_key=b"", key_stats=SimpleStats.EMPTY,
        value_stats=_safe_stats(types, mins, maxs, nulls),
        min_sequence_number=min_seq, max_sequence_number=max_seq,
        schema_id=table.schema.id, level=level,
        file_source=FileSource.APPEND if file_source is None
        else file_source,
        value_stats_cols=stats_cols,
        first_row_id=first_row_id, write_cols=write_cols,
        external_path=external)
    return meta, path


# -- update by row id --------------------------------------------------------

def update_columns(table, row_ids: np.ndarray, updates: pa.Table,
                   max_retries: int = 5) -> Optional[int]:
    """Column-level UPDATE: rewrite only the updated columns of the
    row-range groups that contain `row_ids`, as evolution files sharing
    the group's first_row_id with write_cols = updated columns
    (reference append/dataevolution write path).  Unchanged columns'
    bytes are never rewritten.

    Optimistic: the overlay bakes in the CURRENT values of untouched
    rows, so the commit asserts the planning snapshot is still latest
    and replans on conflict — otherwise two concurrent updates of one
    range would silently revert each other's rows."""
    from paimon_tpu.core.commit import CommitConflictError

    for _ in range(max_retries):
        try:
            return _update_columns_once(table, row_ids, updates)
        except CommitConflictError:
            continue
    raise CommitConflictError(
        f"update_columns lost the race {max_retries} times")


def _update_columns_once(table, row_ids: np.ndarray,
                         updates: pa.Table) -> Optional[int]:
    from paimon_tpu.core.commit import FileStoreCommit
    from paimon_tpu.format import get_format
    from paimon_tpu.format.format import extract_simple_stats

    if len(row_ids) != updates.num_rows:
        raise ValueError("row_ids and updates must align")
    if table.primary_keys:
        raise ValueError("update_columns is for append tables "
                         "(row-tracking.enabled)")
    upd_cols = list(updates.column_names)
    for c in upd_cols:
        if c not in [f.name for f in table.schema.fields]:
            raise ValueError(f"unknown column {c!r}")

    order = np.argsort(row_ids, kind="stable")
    row_ids = np.asarray(row_ids, dtype=np.int64)[order]
    updates = updates.take(pa.array(order))

    snapshot = table.latest_snapshot()
    if snapshot is None:
        return None
    fs_scan = table.new_scan()
    plan = fs_scan.plan(snapshot)
    read = table.new_read_builder().new_read()._read
    max_seq = max((f.max_sequence_number for s in plan.splits
                   for f in s.data_files), default=-1) + 1

    # coverage first (pure range arithmetic, no IO): unknown row ids
    # must fail BEFORE any overlay file is written
    targets = []
    covered = np.zeros(len(row_ids), dtype=bool)
    for split in plan.splits:
        for group in group_row_ranges(split.data_files):
            anchor = anchor_of(group)
            if anchor.first_row_id is None:
                continue
            lo = anchor.first_row_id
            hi = lo + anchor.row_count
            a = np.searchsorted(row_ids, lo, side="left")
            b = np.searchsorted(row_ids, hi, side="left")
            if a == b:
                continue
            covered[a:b] = True
            targets.append((split, group, anchor, a, b))
    if not covered.all():
        missing = row_ids[~covered][:5].tolist()
        raise ValueError(f"row ids not found in any tracked range "
                         f"(e.g. {missing}); is row-tracking.enabled on?")

    new_msgs = []
    written_paths = []
    for split, group, anchor, a, b in targets:
        lo = anchor.first_row_id
        local = (row_ids[a:b] - lo).astype(np.int64)
        current = read_evolution_group(read, split, group, upd_cols)
        cols_out = {}
        for c in upd_cols:
            old = current.column(c).combine_chunks()
            new_vals = updates.column(c).slice(
                a, b - a).combine_chunks().cast(old.type)
            # vectorized scatter: concat old+new, take with an index
            # vector whose updated slots point into the new tail
            combined = pa.concat_arrays([old, new_vals])
            idx = np.arange(len(old), dtype=np.int64)
            idx[local] = len(old) + np.arange(len(new_vals),
                                              dtype=np.int64)
            cols_out[c] = combined.take(pa.array(idx))
        chunk = pa.table(cols_out)

        meta, path = _write_tracked_file(
            table, fs_scan, split, chunk, row_count=anchor.row_count,
            first_row_id=anchor.first_row_id, min_seq=max_seq,
            max_seq=max_seq, write_cols=upd_cols, stats_cols=upd_cols)
        written_paths.append(path)
        from paimon_tpu.core.write import CommitMessage
        new_msgs.append(CommitMessage(
            split.partition, split.bucket, split.total_buckets,
            new_files=[meta]))
    if not new_msgs:
        return None
    commit = FileStoreCommit(table.file_io, table.path, table.schema,
                             table.options, branch=table.branch)
    try:
        return commit.commit(new_msgs, expected_latest_id=snapshot.id)
    except BaseException:
        # the retry wrapper replans and rewrites: this attempt's overlay
        # files would otherwise linger until orphan cleanup
        for p in written_paths:
            table.file_io.delete_quietly(p)
        raise


def delete_by_row_ids(table, row_ids: Sequence[int],
                      max_retries: int = 5) -> Optional[int]:
    """Row-id DELETE on a tracked append table: ids resolve to (anchor
    file, position) by pure range arithmetic — no data reads — and merge
    into the deletion-vector index (reference row-id keyed DVs).
    Optimistic like predicate deletes: replans on commit conflicts."""
    from paimon_tpu.core.commit import CommitConflictError

    for _ in range(max_retries):
        try:
            return _delete_by_row_ids_once(table, row_ids)
        except CommitConflictError:
            continue
    raise CommitConflictError(
        f"delete_by_row_ids lost the race {max_retries} times")


def _delete_by_row_ids_once(table, row_ids: Sequence[int]
                            ) -> Optional[int]:
    from paimon_tpu.index.deletion_vector import (
        DeletionVector, DeletionVectorsIndexFile,
    )
    from paimon_tpu.index.dv_maintainer import (
        DELETION_VECTORS_INDEX, replace_bucket_dv_entries,
    )
    from paimon_tpu.core.commit import FileStoreCommit

    row_ids = np.unique(np.asarray(list(row_ids), dtype=np.int64))
    if len(row_ids) == 0:
        return None
    snapshot = table.latest_snapshot()
    if snapshot is None:
        return None
    fs_scan = table.new_scan()
    plan = fs_scan.plan(snapshot)

    prev_entries, dv_index = _load_bucket_dv_state(table, fs_scan,
                                                    snapshot)
    index_entries = []
    any_change = False
    covered = np.zeros(len(row_ids), dtype=bool)
    for split in plan.splits:
        pbytes = fs_scan._partition_codec.to_bytes(split.partition)
        bucket_dvs = dict(split.deletion_vectors or {})
        changed = False
        for group in group_row_ranges(split.data_files):
            anchor = anchor_of(group)
            if anchor.first_row_id is None:
                continue
            lo = anchor.first_row_id
            a = np.searchsorted(row_ids, lo, side="left")
            b = np.searchsorted(row_ids, lo + anchor.row_count, "left")
            if a == b:
                continue
            covered[a:b] = True
            positions = (row_ids[a:b] - lo).astype(np.int64)
            existing = bucket_dvs.get(anchor.file_name)
            dv = DeletionVector(positions)
            bucket_dvs[anchor.file_name] = existing.merge(dv) \
                if existing is not None else dv
            changed = True
        if not changed:
            continue
        any_change = True
        index_entries.extend(replace_bucket_dv_entries(
            fs_scan, pbytes, split.bucket, bucket_dvs, prev_entries,
            dv_index))
    if not covered.all():
        missing = row_ids[~covered][:5].tolist()
        raise ValueError(f"row ids not found (e.g. {missing})")
    if not any_change:
        return None
    commit = FileStoreCommit(table.file_io, table.path, table.schema,
                             table.options, branch=table.branch)
    return commit.commit([], index_entries=index_entries,
                         expected_latest_id=snapshot.id)


def compact_row_tracked(table, partition_filter=None,
                        max_retries: int = 5) -> Optional[int]:
    """Retry wrapper: a concurrent commit between plan and publish
    replans instead of surfacing, like the other tracked mutations."""
    from paimon_tpu.core.commit import CommitConflictError

    for _ in range(max_retries):
        try:
            return _compact_row_tracked_once(table, partition_filter)
        except CommitConflictError:
            continue
    raise CommitConflictError(
        f"evolution compaction lost the race {max_retries} times")


def _compact_row_tracked_once(table, partition_filter=None
                              ) -> Optional[int]:
    """Data-evolution compaction: fold each row-range group's overlay
    files into ONE full file that keeps the group's firstRowId (row ids
    never move — reference append/dataevolution/
    DataEvolutionCompactTask.java / DataEvolutionNormalCompactTask).
    Deletion vectors stay row-position keyed: they re-key from the old
    anchor file to the rewritten file in the same commit.  Groups with
    a single file are already settled and stay untouched."""
    from paimon_tpu.core.append import AppendSplitRead
    from paimon_tpu.core.commit import FileStoreCommit
    from paimon_tpu.core.write import CommitMessage
    from paimon_tpu.index.dv_maintainer import replace_bucket_dv_entries

    snapshot = table.latest_snapshot()
    if snapshot is None:
        return None
    fs_scan = table.new_scan()
    if partition_filter:
        fs_scan.with_partition_filter(partition_filter)
    plan = fs_scan.plan(snapshot)
    read = AppendSplitRead(table.file_io, table.path, table.schema,
                           table.options,
                           schema_manager=table.schema_manager)
    value_cols = [f.name for f in table.schema.fields]

    prev_dv_entries, dv_index = _load_bucket_dv_state(table, fs_scan,
                                                       snapshot)

    messages = []
    index_entries = []
    written_paths = []
    for split in plan.splits:
        groups = [g for g in group_row_ranges(split.data_files)
                  if len(g) > 1]
        if not groups:
            continue
        bucket_dvs = dict(split.deletion_vectors or {})
        dv_changed = False
        before: List[DataFileMeta] = []
        after: List[DataFileMeta] = []
        for group in groups:
            anchor = anchor_of(group)
            merged = read_evolution_group(read, split, group, value_cols)
            meta, path = _write_tracked_file(
                table, fs_scan, split, merged,
                row_count=anchor.row_count,
                first_row_id=anchor.first_row_id,
                min_seq=anchor.min_sequence_number,
                max_seq=max(f.max_sequence_number for f in group),
                level=max(f.level for f in group),
                file_source=FileSource.COMPACT)
            written_paths.append(path)
            before.extend(group)
            after.append(meta)
            dv = bucket_dvs.pop(anchor.file_name, None)
            if dv is not None:
                # positions are unchanged: the DV just follows the file
                bucket_dvs[meta.file_name] = dv
                dv_changed = True
        if not before:
            continue
        messages.append(CommitMessage(
            split.partition, split.bucket, split.total_buckets,
            compact_before=before, compact_after=after))
        if dv_changed:
            pbytes = fs_scan._partition_codec.to_bytes(split.partition)
            index_entries.extend(replace_bucket_dv_entries(
                fs_scan, pbytes, split.bucket, bucket_dvs,
                prev_dv_entries, dv_index))
    if not messages:
        return None
    commit = FileStoreCommit(table.file_io, table.path, table.schema,
                             table.options, branch=table.branch)
    try:
        return commit.commit(messages, index_entries=index_entries,
                             expected_latest_id=snapshot.id)
    except BaseException:
        for p in written_paths:
            table.file_io.delete_quietly(p)
        raise
