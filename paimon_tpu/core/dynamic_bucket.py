"""Dynamic bucket mode: key-hash -> bucket index grown on demand.

reference: index/HashBucketAssigner.java + PartitionIndex.java (per
partition: a persistent set of key hashes per bucket, stored as raw
4-byte big-endian ints in HASH index files referenced from the index
manifest; new keys fill the active bucket until
dynamic-bucket.target-row-num, then a new bucket opens),
index/HashIndexFile.java (int file format).

Assignment is vectorized: a batch's key hashes resolve against the
in-memory {hash -> bucket} map in one numpy pass.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from paimon_tpu.manifest import FileKind
from paimon_tpu.manifest.index_manifest import (
    HASH_INDEX, IndexFileMeta, IndexManifestEntry,
)

__all__ = ["PartitionIndex", "DynamicBucketAssigner"]


class PartitionIndex:
    """One partition's hash -> bucket mapping (reference
    index/PartitionIndex.java). Known hashes resolve in one vectorized
    searchsorted; Python iteration only touches UNSEEN keys."""

    def __init__(self, target_row_num: int):
        self.target_row_num = target_row_num
        self._sorted_hashes = np.zeros(0, dtype=np.int64)
        self._sorted_buckets = np.zeros(0, dtype=np.int32)
        self._pending: Dict[int, int] = {}     # not yet merged into sorted
        self.bucket_counts: Dict[int, int] = {}
        self.dirty_buckets: set = set()

    def load_bucket(self, bucket: int, hashes: np.ndarray):
        h = np.asarray(hashes, dtype=np.int64)
        self._sorted_hashes = np.concatenate([self._sorted_hashes, h])
        self._sorted_buckets = np.concatenate(
            [self._sorted_buckets, np.full(len(h), bucket, np.int32)])
        order = np.argsort(self._sorted_hashes, kind="stable")
        self._sorted_hashes = self._sorted_hashes[order]
        self._sorted_buckets = self._sorted_buckets[order]
        self.bucket_counts[bucket] = \
            self.bucket_counts.get(bucket, 0) + len(h)

    def _compact_pending(self):
        if len(self._pending) < 65536:
            return
        ph = np.fromiter(self._pending.keys(), dtype=np.int64,
                         count=len(self._pending))
        pb = np.fromiter(self._pending.values(), dtype=np.int32,
                         count=len(self._pending))
        self._sorted_hashes = np.concatenate([self._sorted_hashes, ph])
        self._sorted_buckets = np.concatenate([self._sorted_buckets, pb])
        order = np.argsort(self._sorted_hashes, kind="stable")
        self._sorted_hashes = self._sorted_hashes[order]
        self._sorted_buckets = self._sorted_buckets[order]
        self._pending = {}

    def assign(self, hashes: np.ndarray) -> np.ndarray:
        """hashes -> buckets; unseen hashes go to the first bucket with
        capacity (new buckets open as needed)."""
        h = np.asarray(hashes, dtype=np.int64)
        out = np.empty(len(h), dtype=np.int32)
        # vectorized resolve against the persisted index
        if len(self._sorted_hashes):
            pos = np.searchsorted(self._sorted_hashes, h)
            pos_c = np.minimum(pos, len(self._sorted_hashes) - 1)
            known = self._sorted_hashes[pos_c] == h
            out[known] = self._sorted_buckets[pos_c[known]]
        else:
            known = np.zeros(len(h), dtype=bool)
        # remainder: pending dict, then truly new keys
        for i in np.flatnonzero(~known):
            hv = int(h[i])
            b = self._pending.get(hv)
            if b is None:
                b = self._bucket_with_capacity()
                self._pending[hv] = b
                self.bucket_counts[b] = self.bucket_counts.get(b, 0) + 1
                self.dirty_buckets.add(b)
            out[i] = b
        self._compact_pending()
        return out

    def _bucket_with_capacity(self) -> int:
        for b in sorted(self.bucket_counts):
            if self.bucket_counts[b] < self.target_row_num:
                return b
        return max(self.bucket_counts, default=-1) + 1

    def bucket_hashes(self, bucket: int) -> List[int]:
        out = self._sorted_hashes[self._sorted_buckets == bucket].tolist()
        out.extend(hv for hv, b in self._pending.items() if b == bucket)
        return out


class DynamicBucketAssigner:
    """Loads per-partition hash indexes from the latest snapshot, assigns
    buckets for new rows, and produces the replacement index-manifest
    entries at prepare-commit."""

    def __init__(self, scan, target_row_num: int):
        self.scan = scan
        self.target_row_num = target_row_num
        self._indexes: Dict[Tuple, PartitionIndex] = {}
        self._prev_entries: Optional[List[IndexManifestEntry]] = None

    # -- persistent index ----------------------------------------------------

    def _load_prev_entries(self) -> List[IndexManifestEntry]:
        if self._prev_entries is not None:
            return self._prev_entries
        out: List[IndexManifestEntry] = []
        snapshot = self.scan.snapshot_manager.latest_snapshot()
        if snapshot is not None and snapshot.index_manifest:
            out = [e for e in self.scan.index_manifest_file.read(
                       snapshot.index_manifest)
                   if e.index_file.index_type == HASH_INDEX]
        self._prev_entries = out
        return out

    def _index(self, partition: Tuple) -> PartitionIndex:
        idx = self._indexes.get(partition)
        if idx is not None:
            return idx
        idx = PartitionIndex(self.target_row_num)
        pbytes = self.scan._partition_codec.to_bytes(partition)
        for e in self._load_prev_entries():
            if e.partition != pbytes:
                continue
            path = self.scan.path_factory.index_file_path(
                e.index_file.file_name)
            data = self.scan.file_io.read_bytes(path)
            hashes = np.frombuffer(data, dtype=">i4")
            idx.load_bucket(e.bucket, hashes)
        self._indexes[partition] = idx
        return idx

    # -- assignment ----------------------------------------------------------

    def assign(self, partition: Tuple, hashes: np.ndarray) -> np.ndarray:
        h32 = hashes.astype(np.uint64).astype(np.uint32).view(np.int32) \
            if hashes.dtype != np.int32 else hashes
        return self._index(partition).assign(
            np.asarray(h32, dtype=np.int64))

    # -- commit --------------------------------------------------------------

    def index_entries(self) -> List[IndexManifestEntry]:
        """Replacement HASH index entries for every dirty bucket (old
        entry deleted, full rewritten file added — reference
        DynamicBucketIndexMaintainer.prepareCommit)."""
        # re-read the live entry list: a previous prepare_commit from this
        # writer may have committed entries the DELETE list must cover
        self._prev_entries = None
        entries: List[IndexManifestEntry] = []
        for partition, idx in self._indexes.items():
            if not idx.dirty_buckets:
                continue
            pbytes = self.scan._partition_codec.to_bytes(partition)
            for e in self._load_prev_entries():
                if e.partition == pbytes and e.bucket in idx.dirty_buckets:
                    entries.append(IndexManifestEntry(
                        FileKind.DELETE, e.partition, e.bucket,
                        e.index_file))
            for b in sorted(idx.dirty_buckets):
                hashes = np.array(idx.bucket_hashes(b), dtype=">i4")
                name = self.scan.path_factory.new_index_file_name()
                path = self.scan.path_factory.index_file_path(name)
                self.scan.file_io.write_bytes(path, hashes.tobytes(),
                                              overwrite=False)
                entries.append(IndexManifestEntry(
                    FileKind.ADD, pbytes, b,
                    IndexFileMeta(HASH_INDEX, name, hashes.nbytes,
                                  len(hashes))))
            idx.dirty_buckets = set()
        return entries
