"""Write path: buffered per-(partition,bucket) writers producing L0 files.

reference call stack (SURVEY §3.1): TableWriteImpl.write ->
AbstractFileStoreWrite.write (operation/AbstractFileStoreWrite.java:186)
-> MergeTreeWriter.write/flushMemory (mergetree/MergeTreeWriter.java:164,
203) -> sort + merge-dedup -> KeyValueFileWriterFactory rolling write.

TPU deviation: instead of a binary sort buffer with normalized-key
insertion (SortBufferWriteBuffer.java:59), rows accumulate as Arrow
batches; at flush the whole buffer is sorted/deduped by the device kernel
in one shot and written columnar.
"""

from __future__ import annotations

import os
from collections import deque
from dataclasses import dataclass, field as dc_field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from paimon_tpu.core.bucket import FixedBucketAssigner
from paimon_tpu.core.kv_file import KEY_PREFIX, KeyValueFileWriter
from paimon_tpu.fs import FileIO
from paimon_tpu.manifest import DataFileMeta, SimpleStats
from paimon_tpu.options import CoreOptions, MergeEngine
from paimon_tpu.ops.merge import KIND_COL, SEQ_COL, merge_runs, sort_table
from paimon_tpu.schema.table_schema import TableSchema
from paimon_tpu.types import RowKind
from paimon_tpu.utils.deadline import wait_future
from paimon_tpu.utils.path_factory import FileStorePathFactory

__all__ = ["CommitMessage", "KeyValueFileStoreWrite", "build_kv_table"]

ROW_KIND_COL = "_ROW_KIND"


@dataclass
class CommitMessage:
    """reference: table/sink/CommitMessageImpl.java."""
    partition: Tuple
    bucket: int
    total_buckets: int
    new_files: List[DataFileMeta] = dc_field(default_factory=list)
    compact_before: List[DataFileMeta] = dc_field(default_factory=list)
    compact_after: List[DataFileMeta] = dc_field(default_factory=list)
    changelog_files: List[DataFileMeta] = dc_field(default_factory=list)
    compact_changelog: List[DataFileMeta] = dc_field(default_factory=list)
    # dynamic-bucket hash index updates (reference indexIncrement)
    index_entries: List = dc_field(default_factory=list)

    def is_empty(self) -> bool:
        return not (self.new_files or self.compact_before
                    or self.compact_after or self.changelog_files
                    or self.compact_changelog or self.index_entries)


def group_by_partition_bucket(table: pa.Table, buckets: np.ndarray,
                              partition_keys: Sequence[str]):
    """Split rows into (partition_tuple, bucket) groups.
    Returns [((part, bucket), row_indices)] — shared by the pk and
    append write paths (reference RowKeyExtractor + ChannelComputer)."""
    group_codes = [buckets]
    part_dicts = []
    for pk in partition_keys:
        enc = table.column(pk).combine_chunks().dictionary_encode()
        part_dicts.append(enc.dictionary)
        group_codes.append(np.asarray(enc.indices))
    if len(group_codes) == 1:
        uniq, inverse = np.unique(buckets, return_inverse=True)
        groups = [((), int(b)) for b in uniq]
    else:
        stacked = np.stack(group_codes, axis=1)
        uniq, inverse = np.unique(stacked, axis=0, return_inverse=True)
        groups = []
        for row in uniq:
            part = tuple(part_dicts[i][int(row[i + 1])].as_py()
                         for i in range(len(partition_keys)))
            groups.append((part, int(row[0])))
    order = np.argsort(inverse, kind="stable")
    bounds = np.searchsorted(inverse[order], np.arange(len(groups) + 1))
    return [(groups[gi], order[bounds[gi]:bounds[gi + 1]])
            for gi in range(len(groups))]


def build_kv_table(raw: pa.Table, schema: TableSchema,
                   seq: np.ndarray, kinds: np.ndarray) -> pa.Table:
    """Flatten rows into the KV file layout:
    _KEY_<pk...>, _SEQUENCE_NUMBER, _VALUE_KIND, <all value columns>."""
    cols = []
    names = []
    for k in schema.trimmed_primary_keys():
        cols.append(raw.column(k))
        names.append(KEY_PREFIX + k)
    cols.append(pa.array(seq, pa.int64()))
    names.append(SEQ_COL)
    cols.append(pa.array(kinds, pa.int8()))
    names.append(KIND_COL)
    for f in schema.fields:
        cols.append(raw.column(f.name))
        names.append(f.name)
    return pa.table(dict(zip(names, cols)))


class _BucketWriter:
    """One (partition, bucket)'s buffered state.

    Concurrency contract (parallel/write_pipeline.py): `write`,
    `_spill` and the flush *scheduling* run on the caller thread —
    sequence ranges are reserved at write() time, single-threaded, so
    pipelined flushes can never duplicate or reorder them.  The
    sort/encode/upload bodies run as FlushPool tasks; tasks for this
    bucket execute strictly in submission order (per-key actor), so
    `new_files`/`changelog_files`/`spills` are only ever touched by one
    task at a time and publish deterministically."""

    def __init__(self, parent: "KeyValueFileStoreWrite", partition: Tuple,
                 bucket: int):
        self.parent = parent
        self.partition = partition
        self.bucket = bucket
        self.buffers: List[pa.Table] = []
        self.kind_buffers: List[np.ndarray] = []
        self.seq_buffers: List[np.ndarray] = []   # reserved at write()
        self.buffered_bytes = 0
        self.next_seq: Optional[int] = None   # lazily restored
        self.new_files: List[DataFileMeta] = []
        self.changelog_files: List[DataFileMeta] = []
        self.spills: List[str] = []           # key-sorted local runs
        self._spill_dir: Optional[str] = None
        self._spill_bytes = 0                 # on-disk spill footprint
        self._spills_scheduled = 0            # caller-side (see _spill)
        self._spill_seq = 0                   # monotonic name counter:
        # names derived from len(spills)/listdir counts can REPEAT
        # after a fold shrinks both, truncating a live run (actor-
        # serialized, so a plain int is safe)
        self._spill_sched_bytes = 0           # scheduled-not-yet-written
        # spill payload bytes: the disk-budget check must see queued
        # spills too, or async workers let /tmp overshoot the cap

    @property
    def _key(self) -> Tuple:
        return (self.partition, self.bucket)

    def pending_bytes(self) -> int:
        """Flush-cost estimate for LPT scheduling (buffered + spilled)."""
        return self.buffered_bytes + self._spill_bytes

    def write(self, table: pa.Table, kinds: np.ndarray):
        self.buffers.append(table)
        self.kind_buffers.append(kinds)
        # sequence numbers are reserved HERE, on the single-threaded
        # caller, never inside a pooled flush task
        seqs = self._assign_seq(table.num_rows)
        self.seq_buffers.append(seqs)
        if self.parent.delta_listener is not None:
            # serving-plane hot delta tier (service/delta.py): the
            # batch becomes point-lookup-visible the moment it is
            # buffered — AFTER sequence reservation, so delta
            # newest-wins order is exactly flush order
            self.parent.delta_listener(self.partition, self.bucket,
                                       table, kinds, seqs)
        self.buffered_bytes += table.nbytes
        opts = self.parent.options
        if self.parent.spillable:
            # sorted runs spill at sort-spill-buffer-size cadence,
            # bounded overall by write-buffer-size
            threshold = min(opts.write_buffer_size,
                            opts.get(CoreOptions.SORT_SPILL_BUFFER_SIZE))
            if self.buffered_bytes >= threshold:
                # queued-but-unwritten spill payloads count toward the
                # disk budget (their on-disk size is at most the in-RAM
                # estimate), else async workers let /tmp overshoot it
                if self._spill_bytes + self._spill_sched_bytes >= \
                        opts.get(
                            CoreOptions.WRITE_BUFFER_SPILL_MAX_DISK_SIZE):
                    # disk budget exhausted: flush to L0 instead of
                    # spilling further (reference MaxDiskSize cap)
                    self.flush()
                else:
                    self._spill()
        elif self.buffered_bytes >= opts.write_buffer_size:
            self.flush()

    def _restore_seq(self) -> int:
        if self.next_seq is None:
            if not self.parent.options.get(
                    CoreOptions.KV_SEQUENCE_NUMBER_ENABLED):
                # key-value.sequence_number.enabled=false: no per-record
                # sequence restore — all rows carry seq 0 and merge
                # order falls back to run (commit) order
                self.next_seq = 0
                return 0
            self.next_seq = self.parent.restore_max_seq(
                self.partition, self.bucket) + 1
        return self.next_seq

    def _assign_seq(self, n: int) -> np.ndarray:
        start = self._restore_seq()
        if not self.parent.options.get(
                CoreOptions.KV_SEQUENCE_NUMBER_ENABLED):
            return np.zeros(n, dtype=np.int64)
        self.next_seq = start + n
        return np.arange(start, start + n, dtype=np.int64)

    def _snapshot(self):
        """Detach the in-RAM buffer into an immutable flush payload
        (caller thread): (raw, kinds, seq) or None.  `pa.concat_tables`
        is zero-copy, so the snapshot is cheap; the expensive
        sort/encode happens in the pooled task that receives it."""
        if not self.buffers:
            return None
        raw = pa.concat_tables(self.buffers, promote_options="none")
        kinds = np.concatenate(self.kind_buffers)
        seq = np.concatenate(self.seq_buffers)
        self.buffers, self.kind_buffers, self.seq_buffers = [], [], []
        self.buffered_bytes = 0
        return raw, kinds, seq

    def _sorted_chunk(self, snap) -> Tuple[Optional[pa.Table],
                                           List[DataFileMeta]]:
        """Sort/merge one flush payload into a key-sorted KV chunk and
        write its changelog-producer=input file (arrival order).
        Worker-side and retry-safe: nothing on `self` is mutated —
        returns (sorted_kv, changelog_metas) for the caller to publish
        after the whole task succeeded."""
        if snap is None:
            return None, []
        raw, kinds, seq = snap

        schema = self.parent.schema
        from paimon_tpu.metrics import WRITE_SORT_MS
        from paimon_tpu.obs.trace import span
        with span("write.sort", cat="write", group="write",
                  metric=WRITE_SORT_MS, partition=self.partition,
                  bucket=self.bucket, rows=raw.num_rows):
            kv = build_kv_table(raw, schema, seq, kinds)
            key_cols = [KEY_PREFIX + k
                        for k in schema.trimmed_primary_keys()]
            engine = self.parent.options.merge_engine
            if engine in (MergeEngine.DEDUPLICATE, MergeEngine.FIRST_ROW):
                res = merge_runs([kv], key_cols, merge_engine=engine,
                                 drop_deletes=False,
                                 key_encoder=self.parent.key_encoder,
                                 seq_fields=self.parent.options
                                 .sequence_field or None,
                                 seq_desc=self.parent.options
                                 .sequence_field_descending)
                sorted_kv = res.take()
            else:
                order = sort_table(kv, key_cols,
                                   key_encoder=self.parent.key_encoder)
                sorted_kv = kv.take(pa.array(order))

        changelog: List[DataFileMeta] = []
        if self.parent.changelog_input:
            # changelog-producer=input: raw rows in arrival order
            cl = build_kv_table(raw, schema, seq, kinds)
            changelog = self.parent.write_changelog(
                self.partition, self.bucket, cl)
        return sorted_kv, changelog

    def flush(self):
        """Snapshot the buffer (caller thread) and hand the
        sort/encode/upload to the flush pool; bucket k+1's hashing and
        buffering proceed while this bucket encodes and uploads."""
        snap = self._snapshot()
        if snap is None:
            return

        def task(snap=snap):
            sorted_kv, changelog = self._sorted_chunk(snap)
            metas = self.parent.kv_writer.write(
                self.partition, self.bucket, sorted_kv, level=0)
            # publish only after the upload succeeded: a retried
            # attempt rewrites under fresh names, never double-counts
            self.new_files.extend(metas)
            self.changelog_files.extend(changelog)

        self.parent.flush_pool().submit(self._key, snap[0].nbytes, task)

    # -- spillable buffer (reference SortBufferWriteBuffer:59 spill via
    # MergeSorter/BinaryExternalSortBuffer: full buffers become local
    # sorted runs, merged into L0 once at prepareCommit — fewer, larger
    # L0 files than one flush file per buffer-full) ----------------------

    def _spill_codec(self):
        """IPC compression per spill-compression(+zstd-level)."""
        codec = self.parent.options.get(CoreOptions.SPILL_COMPRESSION)
        if codec in (None, "none"):
            return None
        if codec == "zstd":
            level = self.parent.options.get(
                CoreOptions.SPILL_COMPRESSION_ZSTD_LEVEL)
            return pa.Codec("zstd", compression_level=level)
        return pa.Codec(codec)

    def _write_spill_file(self, sorted_kv: pa.Table) -> str:
        import tempfile
        if self._spill_dir is None:
            self._spill_dir = tempfile.mkdtemp(prefix="paimon-spill-")
        path = os.path.join(self._spill_dir,
                            f"spill-{self._spill_seq}.arrow")
        self._spill_seq += 1
        opts = pa.ipc.IpcWriteOptions(compression=self._spill_codec())
        # batches are BYTE-capped (~24MB): the k-way merge buffers at
        # least one batch per run, so row-capped batches the size of a
        # whole write buffer would recreate the memory cliff spilling
        # exists to avoid
        per_row = max(1, sorted_kv.nbytes // max(1, sorted_kv.num_rows))
        chunk_rows = max(1024, (24 << 20) // per_row)
        with pa.OSFile(path, "wb") as f, \
                pa.ipc.new_file(f, sorted_kv.schema, options=opts) as wr:
            wr.write_table(sorted_kv, max_chunksize=chunk_rows)
        self._spill_bytes += os.path.getsize(path)
        return path

    def _spill(self):
        """Snapshot (caller thread) + pooled sort/IPC-write; spill
        folding rides the same per-bucket actor so `spills` stays
        append-ordered.  The spill write and the fold are SEPARATE
        tasks (= separate retry domains): a transient fold failure must
        not re-run the spill write after it already published — that
        would duplicate the run (and its changelog events)."""
        snap = self._snapshot()
        if snap is None:
            return
        self._spills_scheduled += 1
        payload = snap[0].nbytes
        self._spill_sched_bytes += payload

        def spill_task(snap=snap):
            sorted_kv, changelog = self._sorted_chunk(snap)
            path = self._write_spill_file(sorted_kv)
            # publish LAST: a retried attempt rewrote under fresh names
            self.spills.append(path)
            self.changelog_files.extend(changelog)
            self._spill_sched_bytes -= payload

        def fold_task():
            max_handles = self.parent.options.get(
                CoreOptions.LOCAL_SORT_MAX_NUM_FILE_HANDLES)
            if len(self.spills) > max_handles:
                self._fold_spills(max_handles)

        pool = self.parent.flush_pool()
        pool.submit(self._key, snap[0].nbytes, spill_task)
        pool.submit(self._key, 1, fold_task)

    def _fold_spills(self, max_handles: int):
        """Merge the oldest runs into one so at most `max_handles`
        stay open at once (local-sort.max-num-file-handles; reference
        BinaryExternalSortBuffer's external-merge fan-in bound)."""
        from paimon_tpu.ops.merge_stream import merge_runs_streamed
        fold, rest = self.spills[:max_handles], self.spills[max_handles:]
        schema = self.parent.schema
        key_cols = [KEY_PREFIX + k for k in schema.trimmed_primary_keys()]
        encoder = self.parent.key_encoder

        out_path: List[str] = []
        writer_box: List = [None, None]       # (OSFile, ipc writer)

        def emit(window: pa.Table):
            if window.num_rows == 0:
                return
            if writer_box[0] is None:
                path = os.path.join(self._spill_dir,
                                    f"spill-fold-{self._spill_seq}"
                                    f".arrow")
                self._spill_seq += 1
                out_path.append(path)
                writer_box[0] = pa.OSFile(path, "wb")
                writer_box[1] = pa.ipc.new_file(
                    writer_box[0], window.schema,
                    options=pa.ipc.IpcWriteOptions(
                        compression=self._spill_codec()))
            writer_box[1].write_table(window)

        merge_runs_streamed([self._ipc_iter(p) for p in fold],
                            key_cols, encoder, emit,
                            self._window_merge_fn())
        if writer_box[1] is not None:
            writer_box[1].close()
            writer_box[0].close()
        # publish the new run list BEFORE unlinking the inputs: a
        # retried fold (transient failure) must re-read a consistent
        # `spills`, never paths it already deleted; an unlink that
        # fails leaves a stray file for _drop_spills' rmtree
        import contextlib
        fold_sizes = sum(os.path.getsize(p) for p in fold)
        self.spills = out_path + rest
        self._spill_bytes -= fold_sizes
        if out_path:
            self._spill_bytes += os.path.getsize(out_path[0])
        for p in fold:
            with contextlib.suppress(OSError):
                os.unlink(p)

    @staticmethod
    def _ipc_iter(path):
        def gen():
            with pa.OSFile(path, "rb") as f:
                rd = pa.ipc.open_file(f)
                for i in range(rd.num_record_batches):
                    yield pa.Table.from_batches([rd.get_batch(i)])
        return gen()

    def _window_merge_fn(self):
        """Window merger shared by spill folding and the final L0
        merge: dedup engines keep winners, deferred engines keep every
        row in (key, seq) order."""
        schema = self.parent.schema
        key_cols = [KEY_PREFIX + k for k in schema.trimmed_primary_keys()]
        engine = self.parent.options.merge_engine
        encoder = self.parent.key_encoder

        def merge_window(tables: List[pa.Table]) -> pa.Table:
            if engine in (MergeEngine.DEDUPLICATE, MergeEngine.FIRST_ROW):
                return merge_runs(
                    tables, key_cols, merge_engine=engine,
                    drop_deletes=False, key_encoder=encoder,
                    seq_fields=self.parent.options.sequence_field or None,
                    seq_desc=self.parent.options
                    .sequence_field_descending).take()
            kv = pa.concat_tables(tables, promote_options="none")
            order = sort_table(kv, key_cols, key_encoder=encoder)
            return kv.take(pa.array(order))
        return merge_window

    def _merge_spills(self, snap):
        """Streamed k-way merge of the spilled runs (+ the live-buffer
        tail `snap`) into rolling L0 files — the same bounded-memory
        machinery the compaction rewrite uses (ops/merge_stream.py).
        Worker-side and retry-safe: output metas accumulate locally and
        publish at the end; spills are dropped only on success, so a
        retried attempt still has its inputs (half-written L0 files of
        the failed attempt are orphans for maintenance)."""
        from paimon_tpu.ops.merge_stream import merge_runs_streamed

        tail, changelog = self._sorted_chunk(snap)
        schema = self.parent.schema
        key_cols = [KEY_PREFIX + k for k in schema.trimmed_primary_keys()]
        encoder = self.parent.key_encoder

        iters = [self._ipc_iter(p) for p in self.spills]
        if tail is not None:
            iters.append(iter([tail]))
        merge_window = self._window_merge_fn()

        out_metas: List[DataFileMeta] = []
        acc: List[pa.Table] = []
        acc_bytes = 0
        target = self.parent.kv_writer.target_file_size

        def write_acc():
            nonlocal acc, acc_bytes
            if not acc:
                return
            merged = pa.concat_tables(acc, promote_options="none")
            out_metas.extend(self.parent.kv_writer.write(
                self.partition, self.bucket, merged, level=0))
            acc, acc_bytes = [], 0

        def emit(window: pa.Table):
            nonlocal acc_bytes
            if window.num_rows == 0:
                return
            if acc and acc_bytes + window.nbytes > target:
                # flush BEFORE overshooting so the rolling writer
                # doesn't split every accumulation into full + sliver
                write_acc()
            acc.append(window)
            acc_bytes += window.nbytes
            if acc_bytes >= target:
                write_acc()

        merge_runs_streamed(iters, key_cols, encoder, emit,
                            merge_window)
        write_acc()
        self.new_files.extend(out_metas)
        self.changelog_files.extend(changelog)
        self._drop_spills()

    def _drop_spills(self):
        import shutil
        self.spills = []
        self._spill_bytes = 0
        if self._spill_dir is not None:
            shutil.rmtree(self._spill_dir, ignore_errors=True)
            self._spill_dir = None

    def schedule_final_flush(self):
        """Queue the end-of-batch drain for this bucket: the tail
        buffer is snapshotted NOW (caller thread, sequence numbers
        already reserved), but the spill-vs-flush decision runs inside
        the task — earlier spill tasks for this bucket may still be in
        flight, and the per-key actor guarantees they land first."""
        snap = self._snapshot()
        if snap is None and self._spills_scheduled == 0:
            # nothing buffered and no spill run queued since the last
            # drain: don't churn a no-op task per bucket per checkpoint
            # (it would also inflate the flushes/flushed_bytes metrics)
            return
        self._spills_scheduled = 0

        def task(snap=snap):
            if self.spills:
                self._merge_spills(snap)
            else:
                sorted_kv, changelog = self._sorted_chunk(snap)
                if sorted_kv is not None:
                    metas = self.parent.kv_writer.write(
                        self.partition, self.bucket, sorted_kv, level=0)
                    self.new_files.extend(metas)
                    self.changelog_files.extend(changelog)

        est = (snap[0].nbytes if snap is not None else 0) + \
            self._spill_bytes
        self.parent.flush_pool().submit(self._key, est, task)

    def take_commit_message(self) -> Optional[CommitMessage]:
        """Assemble this bucket's message AFTER the pool drained (the
        prepare-commit barrier); caller thread only."""
        msg = CommitMessage(self.partition, self.bucket,
                            self.parent.total_buckets,
                            new_files=list(self.new_files),
                            changelog_files=list(self.changelog_files))
        self.new_files = []
        self.changelog_files = []
        return None if msg.is_empty() else msg


def dicts_to_arrow(arrow_schema: pa.Schema, rows: Sequence[dict],
                   row_kinds: Optional[Sequence[int]] = None
                   ) -> Tuple[pa.Table, Optional[np.ndarray]]:
    """Dict rows -> (Arrow table, int8 kinds array or None): the ONE
    conversion behind TableWrite.write_dicts and the distributed
    plane's write_dicts, so coercion/default behavior cannot drift
    between the single-process and multi-host paths."""
    table = pa.Table.from_pylist(list(rows), schema=arrow_schema)
    kinds = np.asarray(row_kinds, dtype=np.int8) \
        if row_kinds is not None else None
    return table, kinds


def extract_row_kinds(table: pa.Table,
                      row_kinds: Optional[np.ndarray]
                      ) -> Tuple[pa.Table, np.ndarray]:
    """Honor an inline `_ROW_KIND` column or an explicit kinds array;
    defaults to all-INSERT."""
    if ROW_KIND_COL in table.column_names:
        row_kinds = np.asarray(table.column(ROW_KIND_COL)
                               .combine_chunks().cast(pa.int8()))
        table = table.drop_columns([ROW_KIND_COL])
    if row_kinds is None:
        row_kinds = np.zeros(table.num_rows, dtype=np.int8)
    return table, np.asarray(row_kinds, dtype=np.int8)


class LocalMerger:
    """Pre-shuffle hot-key dedup (reference mergetree/localmerge/
    HashMapLocalMerger.java): rows buffer BEFORE bucket routing; when
    the buffer reaches `local-merge-buffer-size`, duplicate keys
    collapse to their winning version with the device merge kernel, so
    a hot key reaches the bucket writers once per flush instead of once
    per update.  Row kinds ride along — a DELETE that wins the merge
    still propagates as a DELETE."""

    def __init__(self, store: "KeyValueFileStoreWrite",
                 buffer_bytes: int):
        self.store = store
        self.buffer_bytes = buffer_bytes
        self._tables: List[pa.Table] = []
        self._kinds: List[np.ndarray] = []
        self._buckets: List[Optional[np.ndarray]] = []
        self._nbytes = 0

    def add(self, table: pa.Table, kinds: np.ndarray,
            buckets: Optional[np.ndarray] = None):
        self._tables.append(table)
        self._kinds.append(kinds)
        self._buckets.append(buckets)
        self._nbytes += table.nbytes
        if self._nbytes >= self.buffer_bytes:
            self.flush()

    def flush(self):
        if not self._tables:
            return
        raw = pa.concat_tables(self._tables, promote_options="none")
        kinds = np.concatenate(self._kinds)
        # precomputed bucket assignments survive the fold when every
        # buffered batch carried them (the topology shuffle always does)
        buckets = np.concatenate(self._buckets) \
            if all(b is not None for b in self._buckets) else None
        self._tables, self._kinds, self._buckets = [], [], []
        self._nbytes = 0
        if raw.num_rows == 0:
            return
        schema = self.store.schema
        engine = self.store.options.merge_engine
        kv = build_kv_table(raw, schema,
                            np.arange(raw.num_rows, dtype=np.int64),
                            kinds)
        # the merge runs BEFORE partition routing, so the fold key must
        # include the partition columns — trimmed pks alone would
        # collapse distinct rows across partitions (and swallow
        # cross-partition reroute deletes)
        key_cols = list(schema.partition_keys) + \
            [KEY_PREFIX + k for k in schema.trimmed_primary_keys()]
        res = merge_runs(
            [kv], key_cols, merge_engine=engine, drop_deletes=False,
            seq_fields=self.store.options.sequence_field or None,
            seq_desc=self.store.options.sequence_field_descending)
        idx = res.indices
        self.store._dispatch(raw.take(pa.array(idx)), kinds[idx],
                             None if buckets is None else buckets[idx])


class KeyValueFileStoreWrite:
    """Routes rows to per-(partition,bucket) writers.

    reference: operation/KeyValueFileStoreWrite.java:70."""

    def __init__(self, file_io: FileIO, table_path: str,
                 table_schema: TableSchema, options: CoreOptions,
                 restore_max_seq: Optional[Callable[[Tuple, int], int]]
                 = None, branch: str = "main",
                 bucket_files_map: Optional[Callable[[], Dict]]
                 = None, schema_manager=None):
        from paimon_tpu.parallel.write_pipeline import maybe_wrap_staging
        file_io, self._stager = maybe_wrap_staging(file_io, options)
        self.file_io = file_io
        self.table_path = table_path
        self.schema = table_schema
        self.options = options
        self.branch = branch
        self._bucket_files_map = bucket_files_map
        self._schema_manager = schema_manager
        self.partition_keys = table_schema.partition_keys
        self.path_factory = FileStorePathFactory.from_options(
            table_path, self.partition_keys, options)
        self.kv_writer = KeyValueFileWriter(
            file_io, self.path_factory, table_schema,
            file_format=options.file_format,
            compression=options.file_compression,
            target_file_size=options.target_file_size,
            index_spec=options.file_index_spec,
            bloom_fpp=options.get(CoreOptions.FILE_INDEX_BLOOM_FPP),
            index_in_manifest_threshold=options.get(
                CoreOptions.FILE_INDEX_IN_MANIFEST_THRESHOLD),
            format_per_level=options.file_format_per_level,
            format_options=options.format_options,
            **options.kv_writer_kwargs())
        rt = table_schema.logical_row_type()
        self.total_buckets = options.bucket
        bucket_keys = table_schema.bucket_keys()
        self._dynamic = None
        self._postpone = options.bucket == -2
        if self._postpone:
            # postpone mode (reference postpone/PostponeBucketFileStoreWrite):
            # rows stage un-hashed under bucket-postpone; rescale_postpone
            # redistributes them later
            self.bucket_assigner = None
        elif options.bucket < 1:
            # dynamic bucket mode (reference BucketMode.HASH_DYNAMIC)
            from paimon_tpu.core.bucket import KeyHasher
            from paimon_tpu.core.dynamic_bucket import DynamicBucketAssigner
            from paimon_tpu.core.scan import FileStoreScan
            self._key_hasher = KeyHasher(
                bucket_keys, [rt.get_field(k).type for k in bucket_keys])
            self._dynamic = DynamicBucketAssigner(
                FileStoreScan(file_io, table_path, table_schema, options,
                              branch=branch),
                options.dynamic_bucket_target_row_num)
            self.bucket_assigner = None
        else:
            self.bucket_assigner = FixedBucketAssigner(
                bucket_keys, [rt.get_field(k).type for k in bucket_keys],
                options.bucket)
        from paimon_tpu.ops.normkey import NormalizedKeyEncoder
        from paimon_tpu.types import data_type_to_arrow
        self.key_encoder = NormalizedKeyEncoder(
            [data_type_to_arrow(rt.get_field(k).type)
             for k in table_schema.trimmed_primary_keys()],
            nullable=[rt.get_field(k).type.nullable
                      for k in table_schema.trimmed_primary_keys()])
        self._writers: Dict[Tuple, _BucketWriter] = {}
        # serving-plane hook (service/delta.py ServingWriter): called
        # with (partition, bucket, table, kinds, seqs) for every
        # buffered batch, on the single-threaded caller
        self.delta_listener = None
        self._flush_pool = None       # lazily built (write_pipeline)
        # bounded dispatch lookahead: batch N+1's hash/group-by/take
        # runs on a prep worker while batch N routes (seq reservation
        # stays on the caller, strictly in batch order)
        self._prep_pool = None
        self._prep = deque()
        self._restore_max_seq = restore_max_seq
        self.changelog_input = (
            options.changelog_producer == "input")
        self.spillable = options.get(CoreOptions.WRITE_BUFFER_SPILLABLE)
        self._changelog_counter = 0
        self._local_merger: Optional[LocalMerger] = None
        lm_size = options.get(CoreOptions.LOCAL_MERGE_BUFFER_SIZE)
        if lm_size:
            from paimon_tpu.options import MergeEngine
            if options.merge_engine not in (MergeEngine.DEDUPLICATE,
                                            MergeEngine.FIRST_ROW):
                raise ValueError(
                    "local-merge-buffer-size supports deduplicate / "
                    "first-row merge engines (reference "
                    "HashMapLocalMerger applies whole-row merges)")
            if self.changelog_input:
                raise ValueError(
                    "local-merge-buffer-size folds input rows, which "
                    "would drop changelog-producer=input events")
            self._local_merger = LocalMerger(self, lm_size)

    def flush_pool(self):
        """The shared bucket-flush executor (parallel/write_pipeline.py);
        write.flush.parallelism=1 degrades it to the inline serial path."""
        if self._flush_pool is None:
            from paimon_tpu.parallel.write_pipeline import FlushPool
            self._flush_pool = FlushPool.from_options(self.options)
        return self._flush_pool

    # -- seam for restore (reference operation/WriteRestore.java) ------------

    def restore_max_seq(self, partition: Tuple, bucket: int) -> int:
        if self._restore_max_seq is None:
            return -1
        return self._restore_max_seq(partition, bucket)

    def write_changelog(self, partition: Tuple, bucket: int,
                        table: pa.Table) -> List[DataFileMeta]:
        from paimon_tpu.core.kv_file import write_changelog_file
        return write_changelog_file(
            self.file_io, self.path_factory, self.schema,
            self.options.changelog_file_format,
            self.options.changelog_file_compression,
            partition, bucket, table,
            prefix=self.options.changelog_file_prefix,
            format_options=self.options.format_options)

    # -- writes --------------------------------------------------------------

    def write_arrow(self, table: pa.Table,
                    row_kinds: Optional[np.ndarray] = None,
                    buckets: Optional[np.ndarray] = None):
        """Write a batch of rows (full table schema). Optional `row_kinds`
        int8[N] (RowKind codes); a `_ROW_KIND` column is also honored.
        `buckets` skips re-hashing when the caller already assigned
        them (the multi-writer topology's shuffle)."""
        table, row_kinds = extract_row_kinds(table, row_kinds)

        if self._local_merger is not None and not self._postpone:
            self._local_merger.add(table, row_kinds, buckets)
            return
        self._dispatch(table, row_kinds, buckets)

    def _dispatch(self, table: pa.Table, row_kinds: np.ndarray,
                  precomputed_buckets: Optional[np.ndarray] = None):
        from paimon_tpu.parallel.write_pipeline import lpt_order
        if self._postpone:
            self._drain_prep()
            buckets = np.full(table.num_rows, -2, dtype=np.int32)
            for (part, bucket), idx in lpt_order(
                    group_by_partition_bucket(
                        table, buckets, self.partition_keys)):
                sub = table.take(pa.array(idx))
                self._writer(part, bucket).write(sub, row_kinds[idx])
            return
        if self._dynamic is not None:
            # partition-first grouping: bucket assignment depends on the
            # partition's hash index (stateful — no lookahead here)
            self._drain_prep()
            zeros = np.zeros(table.num_rows, dtype=np.int32)
            for (part, _), idx in group_by_partition_bucket(
                    table, zeros, self.partition_keys):
                sub = table.take(pa.array(idx))
                sub_kinds = row_kinds[idx]
                buckets = self._dynamic.assign(
                    part, self._key_hasher.hashes(sub))
                for (_, bucket), idx2 in lpt_order(
                        group_by_partition_bucket(sub, buckets, [])):
                    self._writer(part, bucket).write(
                        sub.take(pa.array(idx2)), sub_kinds[idx2])
            return

        # fixed-bucket hot path: the hash/group-by/take is a PURE
        # function of the batch, so it runs on a prep worker while the
        # previous batch routes — the "incoming batch's hash overlaps
        # bucket flushes" leg of the pipeline.  Routing (and therefore
        # sequence reservation) stays on this thread, in batch order.
        def prep(table=table, kinds=row_kinds,
                 pre=precomputed_buckets):
            buckets = pre if pre is not None \
                else self.bucket_assigner.assign(table)
            out = []
            for (part, bucket), idx in lpt_order(
                    group_by_partition_bucket(
                        table, buckets, self.partition_keys)):
                out.append(((part, bucket), table.take(pa.array(idx)),
                            kinds[idx]))
            return out

        pool = self._prep_executor()
        if pool is None:
            self._route(prep())
            return
        self._prep.append(pool.submit(prep))
        # bounded lookahead: at most 4 batches prepped ahead (each holds
        # a batch-sized copy), routed strictly in submission order
        while len(self._prep) > 4:
            self._route(wait_future(self._prep.popleft(),
                                    "write prep backpressure"))
        while self._prep and self._prep[0].done():
            self._route(wait_future(self._prep.popleft(),
                                    "write prep drain"))

    def _route(self, groups):
        for (part, bucket), sub, kinds in groups:
            self._writer(part, bucket).write(sub, kinds)

    def _drain_prep(self):
        while self._prep:
            self._route(wait_future(self._prep.popleft(),
                                    "write prep drain"))

    def _prep_executor(self):
        """Lookahead pool (up to 4 workers, bounded by the flush
        parallelism); None (inline) on the serial path so
        write.flush.parallelism=1 stays byte-for-byte legacy.  Also
        None with a delta listener attached: the serving plane's
        visibility contract is 'readable when write() returns', which
        requires synchronous in-order routing — deferred prep would
        publish the batch to the delta tier whole batches late."""
        from paimon_tpu.parallel.write_pipeline import (
            resolve_flush_parallelism,
        )
        par = resolve_flush_parallelism(self.options)
        if par <= 1 or self.delta_listener is not None:
            return None
        if self._prep_pool is None:
            from paimon_tpu.parallel.executors import new_thread_pool
            self._prep_pool = new_thread_pool(min(4, par),
                                              "paimon-write-prep")
        return self._prep_pool

    def _writer(self, partition: Tuple, bucket: int) -> _BucketWriter:
        key = (partition, bucket)
        if key not in self._writers:
            self._writers[key] = _BucketWriter(self, partition, bucket)
        return self._writers[key]

    def prepare_commit(self) -> List[CommitMessage]:
        """The pipeline barrier: schedule every bucket's final drain
        (largest pending bytes first, LPT like parallel/packing.py),
        wait for the pool, then assemble messages on the caller thread.
        The first worker error re-raises here with the remaining queued
        flushes cancelled — a failed prepare commits nothing."""
        if self._local_merger is not None:
            self._local_merger.flush()
        self._drain_prep()
        for w in sorted(self._writers.values(),
                        key=lambda w: -w.pending_bytes()):
            w.schedule_final_flush()
        self.flush_pool().drain()
        out = []
        auto_compact = not self.options.write_only and not self._postpone
        existing_map = None
        if auto_compact and self._bucket_files_map is not None:
            # ONE manifest read for the whole commit, not one per bucket
            existing_map = self._bucket_files_map()
        for w in self._writers.values():
            msg = w.take_commit_message()
            if msg is not None:
                if auto_compact:
                    self._maybe_compact(msg, existing_map or {})
                out.append(msg)
        if self._dynamic is not None:
            entries = self._dynamic.index_entries()
            if entries:
                if out:
                    out[0].index_entries.extend(entries)
                else:
                    out.append(CommitMessage((), 0, self.total_buckets,
                                             index_entries=entries))
        if self._stager is not None:
            # durability barrier LAST: every file a message names must
            # be acked by the object store before the caller may commit
            # (staged uploads overlapped all the sorting/encoding and
            # the compaction above; an upload failure raises here and
            # poisons the stager — commit nothing, close the writer)
            self._stager.drain()
        return out

    def _maybe_compact(self, msg: CommitMessage, existing_map: Dict):
        """Inline compaction at prepare-commit when the bucket's sorted
        runs exceed the trigger (reference MergeTreeWriter: compaction
        fires at flush unless write-only). The picked unit may include
        the message's own new L0 files: commit() publishes APPEND before
        COMPACT, so the conflict check still sees them."""
        existing = existing_map.get((msg.partition, msg.bucket), [])
        files = existing + msg.new_files
        if len(files) < 2:
            return
        from paimon_tpu.compact.manager import MergeTreeCompactManager
        mgr = MergeTreeCompactManager(
            self.file_io, self.table_path, self.schema, self.options,
            msg.partition, msg.bucket, files,
            schema_manager=self._schema_manager)
        result = mgr.compact(full=False)
        if result is None or result.is_empty():
            return
        msg.compact_before = result.before
        msg.compact_after = result.after
        msg.compact_changelog = result.changelog

    def close(self):
        if self._prep_pool is not None:
            self._prep_pool.shutdown(wait=True, cancel_futures=True)
            self._prep_pool = None
        self._prep.clear()
        if self._flush_pool is not None:
            # join the workers FIRST so no task mutates spill state
            # while we clean it; abandoned flushes are dropped (their
            # uploads become orphans for maintenance)
            self._flush_pool.shutdown(wait=True)
            self._flush_pool = None
        if self._stager is not None:
            # after the flush pool: no worker stages once we shut the
            # upload pool; abandoned staged files are removed with the
            # stage dir (their half-done uploads are orphans, like
            # abandoned inline uploads)
            self._stager.close()
        for w in self._writers.values():
            w._drop_spills()         # aborted writes must not leak /tmp
        self._writers.clear()
