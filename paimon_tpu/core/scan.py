"""Scan planning: snapshot -> manifests -> pruned ManifestEntries ->
DataSplits.

reference: operation/AbstractFileStoreScan.java (manifest pruning),
table/source/SnapshotReaderImpl.java:87 (generateSplits:412),
MergeTreeSplitGenerator.java:38, DataSplit.java:62.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from paimon_tpu.data.binary_row import BinaryRowCodec
from paimon_tpu.fs import FileIO
from paimon_tpu.manifest import (
    DataFileMeta, FileKind, IndexManifestFile, ManifestEntry, ManifestFile,
    ManifestList, merge_manifest_entries,
)
from paimon_tpu.options import CoreOptions
from paimon_tpu.predicate import Predicate
from paimon_tpu.schema.table_schema import TableSchema
from paimon_tpu.snapshot import Snapshot, SnapshotManager
from paimon_tpu.utils.path_factory import FileStorePathFactory

__all__ = ["DataSplit", "ScanPlan", "FileStoreScan"]


@dataclass
class DataSplit:
    """reference table/source/DataSplit.java:62."""
    snapshot_id: int
    partition: Tuple
    bucket: int
    total_buckets: int
    data_files: List[DataFileMeta]
    raw_convertible: bool = False
    deletion_vectors: Optional[Dict[str, Any]] = None   # file -> DV
    # streaming split: reads emit a _ROW_KIND column
    for_streaming: bool = False
    # delta/changelog split: true row kinds preserved (-U/-D survive);
    # full-phase streaming splits emit merged state as all +I instead
    is_delta: bool = False

    @property
    def row_count(self) -> int:
        return sum(f.row_count for f in self.data_files)


@dataclass
class ScanPlan:
    snapshot_id: Optional[int]
    splits: List[DataSplit]
    # plan produced by a streaming scan (reads stay schema-stable with a
    # _ROW_KIND column even when splits is empty)
    streaming: bool = False

    @property
    def row_count(self) -> int:
        return sum(s.row_count for s in self.splits)


class FileStoreScan:
    def __init__(self, file_io: FileIO, table_path: str,
                 schema: TableSchema, options: CoreOptions,
                 branch: str = "main"):
        self.file_io = file_io
        self.table_path = table_path
        self.schema = schema
        self.options = options
        self.snapshot_manager = SnapshotManager(file_io, table_path, branch)
        self.path_factory = FileStorePathFactory.from_options(
            table_path, schema.partition_keys, options)
        rt = schema.logical_row_type()
        self.partition_types = [rt.get_field(k).type
                                for k in schema.partition_keys]
        self._partition_codec = BinaryRowCodec(self.partition_types)
        compression = options.get(CoreOptions.MANIFEST_COMPRESSION)
        codec = {"zstd": "zstandard", "none": "null"}.get(compression,
                                                          compression)
        mdir = self.path_factory.manifest_dir
        self.manifest_file = ManifestFile(file_io, mdir, codec,
                                          self.partition_types)
        self.manifest_list = ManifestList(file_io, mdir, codec)
        self.index_manifest_file = IndexManifestFile(file_io, mdir, codec)
        self._partition_filter: Optional[dict] = None
        self._bucket_filter: Optional[set] = None
        self._file_index_cache: Dict[str, object] = {}
        self._arrow_types: Optional[Dict[str, object]] = None
        self._key_filter: Optional[Predicate] = None
        self._value_filter: Optional[Predicate] = None
        self._level_filter: Optional[Callable[[int], bool]] = None

    # -- fluent filters ------------------------------------------------------

    def with_partition_filter(self, spec: dict) -> "FileStoreScan":
        self._partition_filter = spec
        return self

    def with_buckets(self, buckets: Sequence[int]) -> "FileStoreScan":
        self._bucket_filter = set(buckets)
        return self

    def with_key_filter(self, predicate: Predicate) -> "FileStoreScan":
        self._key_filter = predicate
        return self

    def with_value_filter(self, predicate: Predicate) -> "FileStoreScan":
        self._value_filter = predicate
        return self

    def with_level_filter(self, fn) -> "FileStoreScan":
        self._level_filter = fn
        return self

    # -- planning ------------------------------------------------------------

    def plan(self, snapshot: Optional[Snapshot] = None,
             streaming: bool = False) -> ScanPlan:
        from paimon_tpu.metrics import global_registry
        import time as _time

        t0 = _time.perf_counter()
        if snapshot is None:
            snapshot = self.snapshot_manager.latest_snapshot()
        if snapshot is None:
            return ScanPlan(None, [], streaming=streaming)
        entries = self.read_entries(snapshot)
        plan = ScanPlan(snapshot.id, self.generate_splits(
            snapshot.id, entries, for_streaming=streaming,
            snapshot=snapshot),
            streaming=streaming)
        g = global_registry().group("scan")
        g.histogram("plan_ms").update((_time.perf_counter() - t0) * 1000)
        g.counter("plans").inc()
        return plan

    def plan_delta(self, snapshot: Snapshot,
                   streaming: bool = False) -> ScanPlan:
        """Only this snapshot's delta files (for incremental/streaming
        reads, reference DeltaFollowUpScanner). With streaming=True the
        splits preserve row kinds for changelog consumers."""
        metas = self.manifest_list.read(snapshot.delta_manifest_list)
        entries = self._read_manifests(metas)
        adds = [e for e in entries if e.kind == FileKind.ADD]
        return ScanPlan(snapshot.id,
                        self.generate_splits(snapshot.id, adds,
                                             for_delta=True,
                                             for_streaming=streaming,
                                             snapshot=snapshot),
                        streaming=streaming)

    def plan_changelog(self, snapshot: Snapshot,
                       streaming: bool = False) -> ScanPlan:
        if not snapshot.changelog_manifest_list:
            return ScanPlan(snapshot.id, [], streaming=streaming)
        metas = self.manifest_list.read(snapshot.changelog_manifest_list)
        entries = self._read_manifests(metas)
        adds = [e for e in entries if e.kind == FileKind.ADD]
        return ScanPlan(snapshot.id,
                        self.generate_splits(snapshot.id, adds,
                                             for_delta=True,
                                             for_streaming=streaming,
                                             snapshot=snapshot),
                        streaming=streaming)

    def read_entries(self, snapshot: Snapshot) -> List[ManifestEntry]:
        metas = self.manifest_list.read_all(snapshot.base_manifest_list,
                                            snapshot.delta_manifest_list)
        metas = self._prune_manifests(metas)
        entries = merge_manifest_entries(self._read_manifests(metas))
        return [e for e in entries if e.kind == FileKind.ADD]

    def _read_manifests(self, metas) -> List[ManifestEntry]:
        # scan.manifest.parallelism (reference
        # AbstractFileStoreScan#parallelism): manifest decode overlaps
        # file reads; order is preserved by mapping in meta order
        par = self.options.get(CoreOptions.SCAN_MANIFEST_PARALLELISM) \
            if self.options is not None else None
        if par and par > 1 and len(metas) > 1:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(max_workers=par) as pool:
                per = list(pool.map(
                    lambda m: self.manifest_file.read(m.file_name),
                    metas))
            return [e for chunk in per for e in chunk]
        entries: List[ManifestEntry] = []
        for m in metas:
            entries.extend(self.manifest_file.read(m.file_name))
        return entries

    def _prune_manifests(self, metas):
        """Skip whole manifests via partition stats
        (reference AbstractFileStoreScan manifest-level pruning)."""
        if not self._partition_filter or not self.partition_types:
            return metas
        out = []
        for m in metas:
            stats = m.partition_stats
            if not stats.null_counts and stats.min_values == b"":
                out.append(m)
                continue
            try:
                mins, maxs = stats.decode(self.partition_types)
            except Exception:
                out.append(m)
                continue
            keep = True
            for i, k in enumerate(self.schema.partition_keys):
                if k in self._partition_filter:
                    v = self._partition_filter[k]
                    if mins[i] is not None and maxs[i] is not None and \
                            not (str(mins[i]) <= str(v) <= str(maxs[i])):
                        keep = False
                        break
            if keep:
                out.append(m)
        return out

    def _partition_matches(self, pbytes: bytes) -> bool:
        """Shared partition-filter check for data entries and DV index
        entries."""
        if not self._partition_filter:
            return True
        values = self._partition_codec.from_bytes(pbytes)
        for i, k in enumerate(self.schema.partition_keys):
            if k in self._partition_filter and \
                    str(values[i]) != str(self._partition_filter[k]):
                return False
        return True

    def _arrow_type_map(self) -> Dict[str, object]:
        if self._arrow_types is None:
            from paimon_tpu.types import data_type_to_arrow
            out = {}
            for f in self.schema.fields:
                try:
                    out[f.name] = data_type_to_arrow(f.type)
                except ValueError:
                    pass
            self._arrow_types = out
        return self._arrow_types

    def _file_indexes(self, e: ManifestEntry):
        """Load a file's column indexes (bloom/bitmap/bsi/range-bitmap):
        embedded blob, or the .index sidecar recorded in extra_files
        (above the in-manifest threshold).  Cached per data file for the
        scan's lifetime."""
        from paimon_tpu.index.file_index import read_indexes_blob
        cached = self._file_index_cache.get(e.file.file_name)
        if cached is not None:
            return cached
        fi = read_indexes_blob(e.file.embedded_index)
        if not fi:
            for extra in e.file.extra_files:
                if extra.endswith(".index"):
                    partition = self._partition_codec.from_bytes(
                        e.partition)
                    path = self.path_factory.data_file_path(
                        partition, e.bucket, extra)
                    try:
                        fi = read_indexes_blob(
                            self.file_io.read_bytes(path))
                    except FileNotFoundError:
                        pass
                    break
        self._file_index_cache[e.file.file_name] = fi
        return fi

    def _bloom_rejects(self, e: ManifestEntry, pred) -> bool:
        """Per-file index skip: bloom equality misses plus bitmap/BSI/
        range-bitmap emptiness proofs (role of reference
        io/FileIndexEvaluator + FileIndexPredicate)."""
        if pred is None:
            return False
        fi = self._file_indexes(e)
        if not fi:
            return False
        from paimon_tpu.index.file_index import evaluate_skip
        return evaluate_skip(fi, pred, self._arrow_type_map())

    def _entry_visible(self, e: ManifestEntry) -> bool:
        """Per-file visibility. NOTE: value-predicate pruning for
        primary-key tables is NOT applied here — a file without matching
        values may still hold the newest version of a key whose older
        version matches, so dropping it would corrupt the merge; value
        pruning for pk tables happens at bucket granularity in
        generate_splits (reference applies value filters per
        non-overlapping section for the same reason)."""
        if e.bucket == -2 and (self._bucket_filter is None
                               or -2 not in self._bucket_filter):
            # postpone staging data is invisible until rescaled
            return False
        if self._bucket_filter is not None and \
                e.bucket not in self._bucket_filter:
            return False
        if self._level_filter is not None and \
                not self._level_filter(e.file.level):
            return False
        if not self._partition_matches(e.partition):
            return False
        if self._bloom_rejects(e, self._key_filter):
            return False
        if self._key_filter is not None and self.schema.primary_keys:
            key_types = [t.copy(False) for t in (
                self.schema.logical_row_type().get_field(k).type
                for k in self.schema.trimmed_primary_keys())]
            try:
                mins, maxs = e.file.key_stats.decode(key_types)
            except Exception:
                return True
            names = self.schema.trimmed_primary_keys()
            if not self._key_filter.test_stats(
                    dict(zip(names, mins)), dict(zip(names, maxs)),
                    dict(zip(names, e.file.key_stats.null_counts
                             or [0] * len(names))),
                    e.file.row_count):
                return False
        if self._value_filter is not None and not self.schema.primary_keys:
            if self.options.get(CoreOptions.ROW_TRACKING_ENABLED):
                # row-tracked append files form row-range groups whose
                # columns merge across files (evolution overlays); a
                # per-file stats prune could drop the anchor while its
                # overlay survives, null-filling every other column on
                # read — so tracked tables prune only at read time
                return True
            # append tables: safe to drop individual files on value stats
            if not self._value_stats_match(e):
                return False
            if self._bloom_rejects(e, self._value_filter):
                return False
        return True

    def _value_stats_match(self, e: ManifestEntry) -> bool:
        value_types = [f.type.as_nullable() for f in self.schema.fields]
        names = [f.name for f in self.schema.fields]
        try:
            mins, maxs = e.file.value_stats.decode(value_types)
        except Exception:
            return True
        return self._value_filter.test_stats(
            dict(zip(names, mins)), dict(zip(names, maxs)),
            dict(zip(names, e.file.value_stats.null_counts
                     or [0] * len(names))),
            e.file.row_count)

    def _bucket_value_match(self, group: List[ManifestEntry]) -> bool:
        """Whole-bucket value pruning for pk tables: skip the bucket only
        when NO file could match (merge-safe — if any file might match,
        every file must be read so newer versions participate)."""
        if self._value_filter is None or not self.schema.primary_keys:
            return True
        return any(self._value_stats_match(e)
                   and not self._bloom_rejects(e, self._value_filter)
                   for e in group)

    def generate_splits(self, snapshot_id: int,
                        entries: List[ManifestEntry],
                        for_delta: bool = False,
                        for_streaming: bool = False,
                        snapshot: Optional[Snapshot] = None
                        ) -> List[DataSplit]:
        groups: Dict[Tuple, List[ManifestEntry]] = {}
        for e in entries:
            if not self._entry_visible(e):
                continue
            groups.setdefault((e.partition, e.bucket), []).append(e)
        splits = []
        # DVs are semantically required once written (DELETE FROM), so
        # they always load; no-op when the snapshot carries no index
        # manifest, and pruned by the scan's partition/bucket filters
        dv_index = self._load_deletion_vectors(snapshot_id, snapshot)
        for (pbytes, bucket), group in sorted(
                groups.items(), key=lambda kv: (kv[0][0], kv[0][1])):
            if not self._bucket_value_match(group):
                continue
            partition = self._partition_codec.from_bytes(pbytes)
            files = [g.file for g in group]
            total_buckets = group[0].total_buckets
            max_level = max(f.level for f in files)
            # append tables never merge; pk tables are raw-convertible only
            # when a single non-L0 run fully covers the bucket
            raw = (not self.schema.primary_keys) or \
                  (not for_delta
                   and all(f.level == max_level and max_level > 0
                           for f in files)
                   and all((f.delete_row_count or 0) == 0 for f in files)
                   and (pbytes, bucket) not in dv_index)
            # append tables never merge across files, so a big bucket
            # bins into several size-bounded splits for parallel readers
            # (reference source.split.target-size / open-file-cost in
            # append splits; pk buckets must stay whole for the merge)
            file_bins = [files]
            if not self.schema.primary_keys and len(files) > 1:
                target = self.options.get(
                    CoreOptions.SOURCE_SPLIT_TARGET_SIZE)
                open_cost = self.options.get(
                    CoreOptions.SOURCE_SPLIT_OPEN_FILE_COST)
                file_bins = []
                cur, cur_size = [], 0
                for f in files:
                    sz = max(f.file_size, open_cost)
                    if cur and cur_size + sz > target:
                        file_bins.append(cur)
                        cur, cur_size = [], 0
                    cur.append(f)
                    cur_size += sz
                if cur:
                    file_bins.append(cur)
            for bin_files in file_bins:
                splits.append(DataSplit(
                    snapshot_id=snapshot_id,
                    partition=partition,
                    bucket=bucket,
                    total_buckets=total_buckets,
                    data_files=bin_files,
                    raw_convertible=raw or for_delta,
                    deletion_vectors=dv_index.get((pbytes, bucket)),
                    for_streaming=for_streaming,
                    is_delta=for_delta,
                ))
        return splits

    def _load_deletion_vectors(self, snapshot_id: int,
                               snapshot: Optional[Snapshot] = None):
        if snapshot is None:
            try:
                snapshot = self.snapshot_manager.snapshot(snapshot_id)
            except OSError:
                return {}
        if not snapshot.index_manifest:
            return {}
        from paimon_tpu.index.deletion_vector import read_deletion_vectors
        out: Dict[Tuple, Dict[str, Any]] = {}
        for e in self.index_manifest_file.read(snapshot.index_manifest):
            if e.index_file.index_type != "DELETION_VECTORS":
                continue
            # honor the scan's bucket/partition filters: skip whole DV
            # files for buckets this plan will never read
            if self._bucket_filter is not None and \
                    e.bucket not in self._bucket_filter:
                continue
            if not self._partition_matches(e.partition):
                continue
            dvs = read_deletion_vectors(
                self.file_io,
                self.path_factory.index_file_path(e.index_file.file_name),
                e.index_file.dv_ranges or {})
            out.setdefault((e.partition, e.bucket), {}).update(dvs)
        return out

    # -- helpers for writers -------------------------------------------------

    def max_sequence_number(self, partition: Tuple, bucket: int) -> int:
        snapshot = self.snapshot_manager.latest_snapshot()
        if snapshot is None:
            return -1
        pbytes = self._partition_codec.to_bytes(partition)
        best = -1
        for e in self.read_entries(snapshot):
            if e.partition == pbytes and e.bucket == bucket:
                best = max(best, e.file.max_sequence_number)
        return best

    def bucket_files(self, partition: Tuple,
                     bucket: int) -> List[DataFileMeta]:
        snapshot = self.snapshot_manager.latest_snapshot()
        if snapshot is None:
            return []
        pbytes = self._partition_codec.to_bytes(partition)
        return [e.file for e in self.read_entries(snapshot)
                if e.partition == pbytes and e.bucket == bucket]
