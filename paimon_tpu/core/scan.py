"""Scan planning: snapshot -> manifests -> pruned ManifestEntries ->
DataSplits.

reference: operation/AbstractFileStoreScan.java (manifest pruning),
table/source/SnapshotReaderImpl.java:87 (generateSplits:412),
MergeTreeSplitGenerator.java:38, DataSplit.java:62.

Incremental metadata plane (ours; ROADMAP item 4):

* **Delta-apply plan reuse** — `plan()` consults the process-shared
  plan cache (core/plan_cache.py): with a cached live-entry state at
  snapshot N, a plan for N+k reads ONLY the delta manifest lists of
  snapshots N+1..N+k and folds ADD/DELETE entries into the cached
  groups; OVERWRITE commits, expired snapshots, unknown DELETEs and
  recreated snapshot ids invalidate back to the cold walk.  A second
  level reuses GENERATED splits per filter signature, regenerating
  only the (partition, bucket) groups the deltas touched — the
  steady-state streaming re-plan is O(delta) end to end.
* **Vectorized manifest pruning** — `_prune_manifests` evaluates
  partition/bucket/key-range predicates against whole manifest lists
  at once via the columnar stats sidecar
  (manifest/stats_sidecar.py), so pruned manifests are never fetched
  and none of their entries are decoded (the `plan` metric group's
  entries_decoded counter is the proof meter).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field, replace as dc_replace
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from paimon_tpu.data.binary_row import BinaryRowCodec
from paimon_tpu.fs import FileIO
from paimon_tpu.manifest import (
    DataFileMeta, FileKind, IndexManifestFile, ManifestEntry, ManifestFile,
    ManifestList, merge_manifest_entries,
)
from paimon_tpu.options import CoreOptions
from paimon_tpu.predicate import Predicate
from paimon_tpu.schema.table_schema import TableSchema
from paimon_tpu.snapshot import CommitKind, Snapshot, SnapshotManager
from paimon_tpu.utils.path_factory import FileStorePathFactory

__all__ = ["DataSplit", "ScanPlan", "FileStoreScan"]


@dataclass
class DataSplit:
    """reference table/source/DataSplit.java:62."""
    snapshot_id: int
    partition: Tuple
    bucket: int
    total_buckets: int
    data_files: List[DataFileMeta]
    raw_convertible: bool = False
    deletion_vectors: Optional[Dict[str, Any]] = None   # file -> DV
    # streaming split: reads emit a _ROW_KIND column
    for_streaming: bool = False
    # delta/changelog split: true row kinds preserved (-U/-D survive);
    # full-phase streaming splits emit merged state as all +I instead
    is_delta: bool = False

    @property
    def row_count(self) -> int:
        return sum(f.row_count for f in self.data_files)


@dataclass
class ScanPlan:
    snapshot_id: Optional[int]
    splits: List[DataSplit]
    # plan produced by a streaming scan (reads stay schema-stable with a
    # _ROW_KIND column even when splits is empty)
    streaming: bool = False

    @property
    def row_count(self) -> int:
        return sum(s.row_count for s in self.splits)


class FileStoreScan:
    def __init__(self, file_io: FileIO, table_path: str,
                 schema: TableSchema, options: CoreOptions,
                 branch: str = "main"):
        self.file_io = file_io
        self.table_path = table_path
        self.schema = schema
        self.options = options
        self.snapshot_manager = SnapshotManager(file_io, table_path, branch)
        self.path_factory = FileStorePathFactory.from_options(
            table_path, schema.partition_keys, options)
        self.branch = branch
        rt = schema.logical_row_type()
        self.partition_types = [rt.get_field(k).type
                                for k in schema.partition_keys]
        self.key_types = [rt.get_field(k).type
                          for k in schema.trimmed_primary_keys()]
        self._partition_codec = BinaryRowCodec(self.partition_types)
        compression = options.get(CoreOptions.MANIFEST_COMPRESSION)
        codec = {"zstd": "zstandard", "none": "null"}.get(compression,
                                                          compression)
        mdir = self.path_factory.manifest_dir
        sidecar = bool(options.get(CoreOptions.MANIFEST_STATS_SIDECAR))
        self.manifest_file = ManifestFile(file_io, mdir, codec,
                                          self.partition_types,
                                          key_types=self.key_types,
                                          sidecar=sidecar)
        self.manifest_list = ManifestList(
            file_io, mdir, codec, partition_types=self.partition_types,
            key_types=self.key_types, sidecar=sidecar)
        self.index_manifest_file = IndexManifestFile(file_io, mdir, codec)
        # plan metric group, pre-allocated so the Prometheus endpoint
        # always renders the series (the whole incremental metadata
        # plane reports here; manifest_compactions' producer is
        # maintenance/manifest_compact.py)
        from paimon_tpu.metrics import (
            PLAN_DELTA_APPLIES, PLAN_ENTRIES_DECODED,
            PLAN_MANIFEST_COMPACTIONS, PLAN_MANIFESTS_PRUNED,
            PLAN_MANIFESTS_READ, PLAN_MS, PLAN_PLANS, global_registry,
        )
        pm = global_registry().plan_metrics()
        self._m_plans = pm.counter(PLAN_PLANS)
        self._m_plan_ms = pm.histogram(PLAN_MS)
        self._m_delta_applies = pm.counter(PLAN_DELTA_APPLIES)
        self._m_manifests_read = pm.counter(PLAN_MANIFESTS_READ)
        self._m_manifests_pruned = pm.counter(PLAN_MANIFESTS_PRUNED)
        self._m_entries_decoded = pm.counter(PLAN_ENTRIES_DECODED)
        pm.counter(PLAN_MANIFEST_COMPACTIONS)
        self._partition_filter: Optional[dict] = None
        self._bucket_filter: Optional[set] = None
        self._file_index_cache: Dict[str, object] = {}
        self._arrow_types: Optional[Dict[str, object]] = None
        self._key_filter: Optional[Predicate] = None
        self._value_filter: Optional[Predicate] = None
        self._level_filter: Optional[Callable[[int], bool]] = None

    # -- fluent filters ------------------------------------------------------

    def with_partition_filter(self, spec: dict) -> "FileStoreScan":
        self._partition_filter = spec
        return self

    def with_buckets(self, buckets: Sequence[int]) -> "FileStoreScan":
        self._bucket_filter = set(buckets)
        return self

    def with_key_filter(self, predicate: Predicate) -> "FileStoreScan":
        self._key_filter = predicate
        return self

    def with_value_filter(self, predicate: Predicate) -> "FileStoreScan":
        self._value_filter = predicate
        return self

    def with_level_filter(self, fn) -> "FileStoreScan":
        self._level_filter = fn
        return self

    # -- planning ------------------------------------------------------------

    def plan(self, snapshot: Optional[Snapshot] = None,
             streaming: bool = False) -> ScanPlan:
        from paimon_tpu.metrics import global_registry
        import time as _time

        t0 = _time.perf_counter()
        if snapshot is None:
            snapshot = self.snapshot_manager.latest_snapshot()
        if snapshot is None:
            return ScanPlan(None, [], streaming=streaming)
        splits = self._plan_splits(snapshot, streaming)
        plan = ScanPlan(snapshot.id, splits, streaming=streaming)
        from paimon_tpu.obs.trace import (
            STAGE_PLAN_LINK, span, tracing_enabled,
        )
        if tracing_enabled():
            ctx = (snapshot.properties or {}).get("trace.context")
            if ctx:
                # store-carried boundary: this plan consumed a
                # snapshot committed (possibly) elsewhere — the merge
                # tool draws the committer-span -> plan flow arrow
                with span(STAGE_PLAN_LINK, cat="scan", link=ctx,
                          snapshot=snapshot.id):
                    pass
        dt_ms = (_time.perf_counter() - t0) * 1000
        self._m_plans.inc()
        self._m_plan_ms.update(dt_ms)
        g = global_registry().group("scan")
        g.histogram("plan_ms").update(dt_ms)
        g.counter("plans").inc()
        return plan

    def _plan_splits(self, snapshot: Snapshot,
                     streaming: bool) -> List[DataSplit]:
        """Split set for one snapshot, via the plan cache when a state
        can be served/advanced, else the classic pruned cold walk."""
        cache = self._plan_cache()
        if cache is not None:
            state = cache.state()
            if state is not None:
                if state.snapshot_id == snapshot.id:
                    if state.matches_tip(snapshot):
                        return self._splits_from_state(
                            cache, state, snapshot, streaming,
                            touched=frozenset(),
                            split_base_id=snapshot.id)
                    # recreated snapshot id (rollback/fast-forward):
                    # the cached state describes different content
                    cache.drop_state(state)
                elif state.snapshot_id < snapshot.id:
                    adv = self._advance_state(state, snapshot)
                    if adv is not None:
                        new_state, touched = adv
                        cache.put_state(new_state, state)
                        self._m_delta_applies.inc()
                        return self._splits_from_state(
                            cache, new_state, snapshot, streaming,
                            touched=touched,
                            split_base_id=state.snapshot_id)
                    cache.drop_state(state)
                elif self._state_anchor_alive(state):
                    # genuine time travel to an OLDER snapshot: serve
                    # it from a cold walk without disturbing the
                    # cached tip
                    cache = None
                else:
                    # ROLLED-BACK tip: our higher-id anchor snapshot
                    # is gone — drop the dead state (else every plan
                    # pays an uncached cold walk until the id climbs
                    # back past it) and rebuild at this snapshot
                    cache.drop_state(state)
        if cache is not None and self._partition_filter is None \
                and self._bucket_filter is None and \
                self._key_prune_bounds() is None and \
                not cache.over_bound(snapshot.id):
            # unfiltered cold walk: the full live-entry set is exactly
            # the cache state — build it once, then generate from it.
            # (prunable key bounds take the fallback instead: the
            # sidecar can skip whole manifests there, while this walk
            # would fetch every one)
            state, live = self._cold_state(snapshot)
            if state is not None:
                cache.put_state(state, None)
                return self._splits_from_state(
                    cache, state, snapshot, streaming,
                    touched=None, split_base_id=None)
            # over scan.plan.cache.max-entries: the walk already
            # decoded the full live set — generate from it instead of
            # re-walking, and remember the verdict so later plans on
            # this tip go straight to the pruned fallback
            cache.mark_over_bound(snapshot.id)
            return self.generate_splits(snapshot.id, live,
                                        for_streaming=streaming,
                                        snapshot=snapshot)
        # filtered (or cache-disabled / over-bound) cold walk: the
        # vectorized manifest prune keeps whole manifests unfetched
        entries = self.read_entries(snapshot, _use_cache=False)
        return self.generate_splits(snapshot.id, entries,
                                    for_streaming=streaming,
                                    snapshot=snapshot)

    def plan_delta(self, snapshot: Snapshot,
                   streaming: bool = False) -> ScanPlan:
        """Only this snapshot's delta files (for incremental/streaming
        reads, reference DeltaFollowUpScanner). With streaming=True the
        splits preserve row kinds for changelog consumers."""
        metas = self.manifest_list.read(snapshot.delta_manifest_list)
        entries = self._read_manifests(metas)
        adds = [e for e in entries if e.kind == FileKind.ADD]
        return ScanPlan(snapshot.id,
                        self.generate_splits(snapshot.id, adds,
                                             for_delta=True,
                                             for_streaming=streaming,
                                             snapshot=snapshot),
                        streaming=streaming)

    def plan_changelog(self, snapshot: Snapshot,
                       streaming: bool = False) -> ScanPlan:
        if not snapshot.changelog_manifest_list:
            return ScanPlan(snapshot.id, [], streaming=streaming)
        metas = self.manifest_list.read(snapshot.changelog_manifest_list)
        entries = self._read_manifests(metas)
        adds = [e for e in entries if e.kind == FileKind.ADD]
        return ScanPlan(snapshot.id,
                        self.generate_splits(snapshot.id, adds,
                                             for_delta=True,
                                             for_streaming=streaming,
                                             snapshot=snapshot),
                        streaming=streaming)

    def read_entries(self, snapshot: Snapshot,
                     _use_cache: bool = True) -> List[ManifestEntry]:
        """Live (merged, ADD-only) entry set at one snapshot.  Served
        from — and feeding — the delta-apply plan cache when it can;
        may return a SUPERSET of a filtered scan's visible entries
        (manifest-level pruning is conservative; callers apply their
        own per-entry filters, and `plan()` runs `_entry_visible`)."""
        cache = self._plan_cache() if _use_cache else None
        if cache is not None:
            state = cache.state()
            if state is not None:
                if state.snapshot_id == snapshot.id:
                    if state.matches_tip(snapshot):
                        return [e for d in state.groups.values()
                                for e in d.values()]
                    # recreated snapshot id (rollback/fast-forward):
                    # drop it, or every read re-walks and the rebuilt
                    # state can never publish over the stale one
                    cache.drop_state(state)
                elif state.snapshot_id < snapshot.id:
                    adv = self._advance_state(state, snapshot)
                    if adv is not None:
                        new_state, _ = adv
                        cache.put_state(new_state, state)
                        self._m_delta_applies.inc()
                        return [e for d in new_state.groups.values()
                                for e in d.values()]
                    cache.drop_state(state)
                elif self._state_anchor_alive(state):
                    # genuine time travel to an OLDER snapshot: serve
                    # it from the pruned fallback without disturbing
                    # (or futilely rebuilding under) the cached tip
                    cache = None
                else:
                    # rolled-back tip: drop the dead state and
                    # rebuild at this snapshot (mirrors _plan_splits)
                    cache.drop_state(state)
            if cache is not None and self._partition_filter is None \
                    and self._bucket_filter is None and \
                    self._key_prune_bounds() is None and \
                    not cache.over_bound(snapshot.id):
                state, live = self._cold_state(snapshot)
                if state is not None:
                    cache.put_state(state, None)
                    return live
                # over bound: reuse this walk's live set, and skip
                # the attempt for later reads of the same tip
                cache.mark_over_bound(snapshot.id)
                return live
        metas = self.manifest_list.read_all(snapshot.base_manifest_list,
                                            snapshot.delta_manifest_list)
        metas = self._prune_manifests(metas, snapshot)
        entries = merge_manifest_entries(self._read_manifests(metas))
        return [e for e in entries if e.kind == FileKind.ADD]

    # -- delta-apply plan cache ----------------------------------------------

    def _plan_cache(self):
        """The process-shared TablePlanCache, or None when disabled."""
        if self.options is None or \
                not self.options.get(CoreOptions.SCAN_PLAN_CACHE):
            return None
        from paimon_tpu.core.plan_cache import shared_plan_cache
        return shared_plan_cache(self.table_path, self.branch)

    def _state_anchor_alive(self, state) -> bool:
        """True when the cached state's anchor snapshot still exists
        with the same content — distinguishes genuine time travel
        (cached tip stays) from a rolled-back tip (the state is dead
        and must drop)."""
        try:
            anchor = self.snapshot_manager.snapshot(state.snapshot_id)
        except (OSError, ValueError):
            return False
        return anchor is not None and state.matches_tip(anchor)

    def _fold_entry(self, groups, copied, touched, e) -> bool:
        """Apply one delta entry to the copy-on-write group map.
        False = a DELETE whose file is not live (the delta was
        computed against a state we do not hold — invalidate)."""
        g = (e.partition, e.bucket)
        d = groups.get(g)
        if g not in copied:
            d = dict(d) if d is not None else {}
            groups[g] = d
            copied.add(g)
        touched.add(g)
        ident = e.identifier()
        if e.kind == FileKind.ADD:
            d[ident] = e
            return True
        if ident in d:
            del d[ident]
            return True
        return False

    def _cold_state(self, snapshot: Snapshot):
        """Full UNPRUNED walk building the cacheable live-entry state
        (no scan filters applied — the state serves any filter; they
        run per entry at split generation).  Returns (state, live
        entries); state is None when the table exceeds
        scan.plan.cache.max-entries, but the decoded live-entry set is
        ALWAYS returned so the caller never re-walks the chain it just
        paid for."""
        from paimon_tpu.core.plan_cache import PlanState
        metas = self.manifest_list.read_all(snapshot.base_manifest_list,
                                            snapshot.delta_manifest_list)
        entries = self._read_manifests(metas)
        groups: Dict[Tuple[bytes, int], Dict[tuple, ManifestEntry]] = {}
        copied: set = set()
        for e in entries:
            self._fold_entry(groups, copied, set(), e)
        groups = {g: d for g, d in groups.items() if d}
        count = sum(len(d) for d in groups.values())
        live = [e for d in groups.values() for e in d.values()]
        if count > self.options.get(
                CoreOptions.SCAN_PLAN_CACHE_MAX_ENTRIES):
            return None, live
        return PlanState(snapshot.id, snapshot.base_manifest_list,
                         snapshot.delta_manifest_list,
                         snapshot.index_manifest, groups, count), live

    def _advance_state(self, state, snapshot: Snapshot):
        """Advance a cached state to `snapshot` by folding ONLY the
        delta manifest lists of the intermediate snapshots — the
        O(delta) steady-state re-plan.  Returns (new_state,
        frozenset(touched group keys)) or None to invalidate:
        OVERWRITE commits (INSERT OVERWRITE, dropped partitions,
        bucket rescale — their delete set was computed against a
        racing latest and must never be folded blind), an expired or
        recreated snapshot along the walk, a DELETE of a file we do
        not hold, or outgrowing the entry bound."""
        from paimon_tpu.core.plan_cache import PlanState
        try:
            prev = self.snapshot_manager.snapshot(state.snapshot_id)
        except (OSError, ValueError):
            return None
        if prev is None or not state.matches_tip(prev):
            # rollback/fast-forward recreated our anchor id with
            # different content: the chain above it is not ours
            return None
        groups = dict(state.groups)          # copy-on-write outer map
        copied: set = set()
        touched: set = set()
        max_entries = self.options.get(
            CoreOptions.SCAN_PLAN_CACHE_MAX_ENTRIES)
        for sid in range(state.snapshot_id + 1, snapshot.id + 1):
            if sid == snapshot.id:
                snap = snapshot
            else:
                try:
                    snap = self.snapshot_manager.snapshot(sid)
                except (OSError, ValueError):
                    return None
                if snap is None:
                    return None
            if snap.commit_kind == CommitKind.OVERWRITE:
                return None
            try:
                metas = self.manifest_list.read(snap.delta_manifest_list)
                entries = self._read_manifests(metas)
            except (OSError, ValueError):
                # list OR manifest file gone mid-walk (expired or
                # repaired under us): invalidate to the cold walk
                return None
            for e in entries:
                if not self._fold_entry(groups, copied, touched, e):
                    return None
        for g in list(touched):
            if not groups.get(g):
                groups.pop(g, None)
        count = sum(len(d) for d in groups.values())
        if count > max_entries:
            return None
        return (PlanState(snapshot.id, snapshot.base_manifest_list,
                          snapshot.delta_manifest_list,
                          snapshot.index_manifest, groups, count),
                frozenset(touched))

    def _split_signature(self, streaming: bool):
        """Hashable identity of the filters AND options split
        generation depends on, or None when key/value/level
        predicates (not identity-comparable across scan objects) make
        split states unreusable.  The binning options matter because
        the cache is shared per (table, branch) across handles whose
        dynamic options may differ (table.copy)."""
        if self._key_filter is not None or \
                self._value_filter is not None or \
                self._level_filter is not None:
            return None
        pf = None
        if self._partition_filter:
            pf = frozenset((k, str(v))
                           for k, v in self._partition_filter.items())
        bf = frozenset(self._bucket_filter) \
            if self._bucket_filter is not None else None
        return (streaming, pf, bf,
                self.options.get(CoreOptions.SOURCE_SPLIT_TARGET_SIZE),
                self.options.get(
                    CoreOptions.SOURCE_SPLIT_OPEN_FILE_COST))

    def _dv_from_state(self, cache, snapshot: Snapshot):
        """UNFILTERED deletion-vector index, memoized per index
        manifest name (splits look up their own (partition, bucket)
        key, so extra groups are inert)."""
        key = snapshot.index_manifest
        hit, dv = cache.dv_memo(key)
        if hit:
            return dv
        dv = self._load_deletion_vectors(snapshot.id, snapshot,
                                         unfiltered=True)
        cache.put_dv_memo(key, dv)
        return dv

    def _splits_from_state(self, cache, state, snapshot: Snapshot,
                           streaming: bool, touched, split_base_id):
        """Generate this scan's splits from a cached live-entry state
        — zero manifest IO.  With a reusable filter signature and a
        split state generated at `split_base_id`, only `touched`
        groups re-run split generation (None = all)."""
        from paimon_tpu.core.plan_cache import SplitState
        sig = self._split_signature(streaming)
        base = None
        if sig is not None and touched is not None:
            st = cache.split_state(sig)
            if st is not None and \
                    st.index_manifest == snapshot.index_manifest:
                if st.snapshot_id == snapshot.id:
                    base, regen = st.group_splits, frozenset()
                elif split_base_id is not None and \
                        st.snapshot_id == split_base_id:
                    base, regen = st.group_splits, touched
        dv_index = self._dv_from_state(cache, snapshot)
        group_splits: Dict[Tuple[bytes, int], tuple] = {}
        for g in state.groups:
            if base is not None and g not in regen and g in base:
                old = base[g]
                if old and old[0].snapshot_id != snapshot.id:
                    old = tuple(dc_replace(s, snapshot_id=snapshot.id)
                                for s in old)
                group_splits[g] = old
                continue
            visible = [e for e in state.groups[g].values()
                       if self._entry_visible(e)]
            group_splits[g] = tuple(self._group_splits(
                snapshot.id, g, visible, dv_index,
                for_delta=False, for_streaming=streaming))
        if sig is not None:
            cache.put_split_state(sig, SplitState(
                snapshot.id, snapshot.index_manifest, group_splits))
        out: List[DataSplit] = []
        for g in sorted(group_splits):
            out.extend(group_splits[g])
        return out

    # -- manifest IO ---------------------------------------------------------

    def _read_manifests(self, metas) -> List[ManifestEntry]:
        # scan.manifest.parallelism (reference
        # AbstractFileStoreScan#parallelism): manifest decode overlaps
        # file reads; order is preserved by mapping in meta order.
        # Routed through parallel/executors so the submitter's request
        # deadline propagates into the manifest-read workers.
        par = self.options.get(CoreOptions.SCAN_MANIFEST_PARALLELISM) \
            if self.options is not None else None
        if par and par > 1 and len(metas) > 1:
            from paimon_tpu.parallel.executors import new_thread_pool
            pool = new_thread_pool(par, "paimon-scan-manifest")
            try:
                per = list(pool.map(
                    lambda m: self.manifest_file.read(m.file_name),
                    metas))
            finally:
                pool.shutdown(wait=True)
            entries = [e for chunk in per for e in chunk]
        else:
            entries = []
            for m in metas:
                entries.extend(self.manifest_file.read(m.file_name))
        self._m_manifests_read.inc(len(metas))
        self._m_entries_decoded.inc(len(entries))
        return entries

    def _key_prune_bounds(self):
        """(lo, hi) bounds the key filter puts on the FIRST trimmed
        primary key (the sidecar's k_min/k_max column), or None."""
        if self._key_filter is None or not self.schema.primary_keys:
            return None
        from paimon_tpu.predicate import conjunctive_bounds
        names = self.schema.trimmed_primary_keys()
        if not names:
            return None
        b = conjunctive_bounds(self._key_filter, names[0])
        if b is None or (b[0] is None and b[1] is None):
            return None
        return b

    def _prune_manifests(self, metas, snapshot: Optional[Snapshot] = None):
        """Skip whole manifests before any fetch (reference
        AbstractFileStoreScan manifest-level pruning).  With a
        columnar stats sidecar next to the snapshot's manifest lists
        (manifest/stats_sidecar.py) the partition/bucket/key-range
        predicates evaluate VECTORIZED over the whole list; metas the
        sidecar does not cover fall back to the per-meta python
        partition check.  Pruned manifests are never fetched and none
        of their entries are decoded (plan group's entries_decoded is
        the proof meter)."""
        key_bounds = self._key_prune_bounds()
        if (not self._partition_filter or not self.partition_types) \
                and self._bucket_filter is None and key_bounds is None:
            return metas
        masks: Dict[str, bool] = {}
        if snapshot is not None and self.options.get(
                CoreOptions.MANIFEST_STATS_SIDECAR):
            from paimon_tpu.manifest.stats_sidecar import prune_keep_mask
            for list_name in (snapshot.base_manifest_list,
                              snapshot.delta_manifest_list):
                if not list_name:
                    continue
                stats = self.manifest_list.read_sidecar(list_name)
                if stats is None:
                    continue
                keep = prune_keep_mask(
                    stats, self.schema.partition_keys,
                    self._partition_filter, self._bucket_filter,
                    key_bounds)
                masks.update(zip(stats["file_name"].to_pylist(),
                                 keep.tolist()))
        out = []
        pruned = 0
        for m in metas:
            k = masks.get(m.file_name)
            if k is None:
                k = self._python_prune_keep(m)
            if k:
                out.append(m)
            else:
                pruned += 1
        self._m_manifests_pruned.inc(pruned)
        return out

    def _python_prune_keep(self, m) -> bool:
        """Per-meta fallback for manifests without sidecar stats
        (partition equality against decoded partition stats only)."""
        if not self._partition_filter or not self.partition_types:
            return True
        stats = m.partition_stats
        if not stats.null_counts and stats.min_values == b"":
            return True
        try:
            mins, maxs = stats.decode(self.partition_types)
        except Exception:
            return True
        for i, k in enumerate(self.schema.partition_keys):
            if k in self._partition_filter:
                v = self._partition_filter[k]
                if mins[i] is not None and maxs[i] is not None and \
                        not (str(mins[i]) <= str(v) <= str(maxs[i])):
                    return False
        return True

    def _partition_matches(self, pbytes: bytes) -> bool:
        """Shared partition-filter check for data entries and DV index
        entries."""
        if not self._partition_filter:
            return True
        values = self._partition_codec.from_bytes(pbytes)
        for i, k in enumerate(self.schema.partition_keys):
            if k in self._partition_filter and \
                    str(values[i]) != str(self._partition_filter[k]):
                return False
        return True

    def _arrow_type_map(self) -> Dict[str, object]:
        if self._arrow_types is None:
            from paimon_tpu.types import data_type_to_arrow
            out = {}
            for f in self.schema.fields:
                try:
                    out[f.name] = data_type_to_arrow(f.type)
                except ValueError:
                    pass
            self._arrow_types = out
        return self._arrow_types

    def _file_indexes(self, e: ManifestEntry):
        """Load a file's column indexes (bloom/bitmap/bsi/range-bitmap):
        embedded blob, or the .index sidecar recorded in extra_files
        (above the in-manifest threshold).  Cached per data file for the
        scan's lifetime."""
        from paimon_tpu.index.file_index import read_indexes_blob
        cached = self._file_index_cache.get(e.file.file_name)
        if cached is not None:
            return cached
        fi = read_indexes_blob(e.file.embedded_index)
        if not fi:
            for extra in e.file.extra_files:
                if extra.endswith(".index"):
                    partition = self._partition_codec.from_bytes(
                        e.partition)
                    path = self.path_factory.data_file_path(
                        partition, e.bucket, extra)
                    try:
                        fi = read_indexes_blob(
                            self.file_io.read_bytes(path))
                    except FileNotFoundError:
                        pass
                    break
        self._file_index_cache[e.file.file_name] = fi
        return fi

    def _bloom_rejects(self, e: ManifestEntry, pred) -> bool:
        """Per-file index skip: bloom equality misses plus bitmap/BSI/
        range-bitmap emptiness proofs (role of reference
        io/FileIndexEvaluator + FileIndexPredicate)."""
        if pred is None:
            return False
        fi = self._file_indexes(e)
        if not fi:
            return False
        from paimon_tpu.index.file_index import evaluate_skip
        return evaluate_skip(fi, pred, self._arrow_type_map())

    def _entry_visible(self, e: ManifestEntry) -> bool:
        """Per-file visibility. NOTE: value-predicate pruning for
        primary-key tables is NOT applied here — a file without matching
        values may still hold the newest version of a key whose older
        version matches, so dropping it would corrupt the merge; value
        pruning for pk tables happens at bucket granularity in
        generate_splits (reference applies value filters per
        non-overlapping section for the same reason)."""
        if e.bucket == -2 and (self._bucket_filter is None
                               or -2 not in self._bucket_filter):
            # postpone staging data is invisible until rescaled
            return False
        if self._bucket_filter is not None and \
                e.bucket not in self._bucket_filter:
            return False
        if self._level_filter is not None and \
                not self._level_filter(e.file.level):
            return False
        if not self._partition_matches(e.partition):
            return False
        if self._bloom_rejects(e, self._key_filter):
            return False
        if self._key_filter is not None and self.schema.primary_keys:
            key_types = [t.copy(False) for t in (
                self.schema.logical_row_type().get_field(k).type
                for k in self.schema.trimmed_primary_keys())]
            try:
                mins, maxs = e.file.key_stats.decode(key_types)
            except Exception:
                return True
            names = self.schema.trimmed_primary_keys()
            if not self._key_filter.test_stats(
                    dict(zip(names, mins)), dict(zip(names, maxs)),
                    dict(zip(names, e.file.key_stats.null_counts
                             or [0] * len(names))),
                    e.file.row_count):
                return False
        if self._value_filter is not None and not self.schema.primary_keys:
            if self.options.get(CoreOptions.ROW_TRACKING_ENABLED):
                # row-tracked append files form row-range groups whose
                # columns merge across files (evolution overlays); a
                # per-file stats prune could drop the anchor while its
                # overlay survives, null-filling every other column on
                # read — so tracked tables prune only at read time
                return True
            # append tables: safe to drop individual files on value stats
            if not self._value_stats_match(e):
                return False
            if self._bloom_rejects(e, self._value_filter):
                return False
        return True

    def _value_stats_match(self, e: ManifestEntry) -> bool:
        value_types = [f.type.as_nullable() for f in self.schema.fields]
        names = [f.name for f in self.schema.fields]
        try:
            mins, maxs = e.file.value_stats.decode(value_types)
        except Exception:
            return True
        return self._value_filter.test_stats(
            dict(zip(names, mins)), dict(zip(names, maxs)),
            dict(zip(names, e.file.value_stats.null_counts
                     or [0] * len(names))),
            e.file.row_count)

    def _bucket_value_match(self, group: List[ManifestEntry]) -> bool:
        """Whole-bucket value pruning for pk tables: skip the bucket only
        when NO file could match (merge-safe — if any file might match,
        every file must be read so newer versions participate)."""
        if self._value_filter is None or not self.schema.primary_keys:
            return True
        return any(self._value_stats_match(e)
                   and not self._bloom_rejects(e, self._value_filter)
                   for e in group)

    def generate_splits(self, snapshot_id: int,
                        entries: List[ManifestEntry],
                        for_delta: bool = False,
                        for_streaming: bool = False,
                        snapshot: Optional[Snapshot] = None
                        ) -> List[DataSplit]:
        groups: Dict[Tuple, List[ManifestEntry]] = {}
        for e in entries:
            if not self._entry_visible(e):
                continue
            groups.setdefault((e.partition, e.bucket), []).append(e)
        splits = []
        # DVs are semantically required once written (DELETE FROM), so
        # they always load; no-op when the snapshot carries no index
        # manifest, and pruned by the scan's partition/bucket filters
        dv_index = self._load_deletion_vectors(snapshot_id, snapshot)
        for key, group in sorted(
                groups.items(), key=lambda kv: (kv[0][0], kv[0][1])):
            splits.extend(self._group_splits(snapshot_id, key, group,
                                             dv_index, for_delta,
                                             for_streaming))
        return splits

    def _group_splits(self, snapshot_id: int, key: Tuple[bytes, int],
                      group: List[ManifestEntry], dv_index,
                      for_delta: bool, for_streaming: bool
                      ) -> List[DataSplit]:
        """Splits for ONE (partition, bucket) group of already-visible
        entries — the unit the split-level plan cache regenerates when
        a delta touches the group."""
        if not group or not self._bucket_value_match(group):
            return []
        pbytes, bucket = key
        partition = self._partition_codec.from_bytes(pbytes)
        files = [g.file for g in group]
        total_buckets = group[0].total_buckets
        max_level = max(f.level for f in files)
        # append tables never merge; pk tables are raw-convertible only
        # when a single non-L0 run fully covers the bucket
        raw = (not self.schema.primary_keys) or \
              (not for_delta
               and all(f.level == max_level and max_level > 0
                       for f in files)
               and all((f.delete_row_count or 0) == 0 for f in files)
               and (pbytes, bucket) not in dv_index)
        # append tables never merge across files, so a big bucket
        # bins into several size-bounded splits for parallel readers
        # (reference source.split.target-size / open-file-cost in
        # append splits; pk buckets must stay whole for the merge)
        file_bins = [files]
        if not self.schema.primary_keys and len(files) > 1:
            target = self.options.get(
                CoreOptions.SOURCE_SPLIT_TARGET_SIZE)
            open_cost = self.options.get(
                CoreOptions.SOURCE_SPLIT_OPEN_FILE_COST)
            file_bins = []
            cur, cur_size = [], 0
            for f in files:
                sz = max(f.file_size, open_cost)
                if cur and cur_size + sz > target:
                    file_bins.append(cur)
                    cur, cur_size = [], 0
                cur.append(f)
                cur_size += sz
            if cur:
                file_bins.append(cur)
        return [DataSplit(
            snapshot_id=snapshot_id,
            partition=partition,
            bucket=bucket,
            total_buckets=total_buckets,
            data_files=bin_files,
            raw_convertible=raw or for_delta,
            deletion_vectors=dv_index.get((pbytes, bucket)),
            for_streaming=for_streaming,
            is_delta=for_delta,
        ) for bin_files in file_bins]

    def _load_deletion_vectors(self, snapshot_id: int,
                               snapshot: Optional[Snapshot] = None,
                               unfiltered: bool = False):
        if snapshot is None:
            try:
                snapshot = self.snapshot_manager.snapshot(snapshot_id)
            except OSError:
                return {}
        if not snapshot.index_manifest:
            return {}
        from paimon_tpu.index.deletion_vector import read_deletion_vectors
        out: Dict[Tuple, Dict[str, Any]] = {}
        for e in self.index_manifest_file.read(snapshot.index_manifest):
            if e.index_file.index_type != "DELETION_VECTORS":
                continue
            # honor the scan's bucket/partition filters: skip whole DV
            # files for buckets this plan will never read (`unfiltered`
            # loads everything — the plan cache's memoized index serves
            # any scan; splits look up their own (partition, bucket))
            if not unfiltered:
                if self._bucket_filter is not None and \
                        e.bucket not in self._bucket_filter:
                    continue
                if not self._partition_matches(e.partition):
                    continue
            dvs = read_deletion_vectors(
                self.file_io,
                self.path_factory.index_file_path(e.index_file.file_name),
                e.index_file.dv_ranges or {})
            out.setdefault((e.partition, e.bucket), {}).update(dvs)
        return out

    # -- helpers for writers -------------------------------------------------

    def max_sequence_number(self, partition: Tuple, bucket: int) -> int:
        snapshot = self.snapshot_manager.latest_snapshot()
        if snapshot is None:
            return -1
        pbytes = self._partition_codec.to_bytes(partition)
        best = -1
        for e in self.read_entries(snapshot):
            if e.partition == pbytes and e.bucket == bucket:
                best = max(best, e.file.max_sequence_number)
        return best

    def bucket_files(self, partition: Tuple,
                     bucket: int) -> List[DataFileMeta]:
        snapshot = self.snapshot_manager.latest_snapshot()
        if snapshot is None:
            return []
        pbytes = self._partition_codec.to_bytes(partition)
        return [e.file for e in self.read_entries(snapshot)
                if e.partition == pbytes and e.bucket == bucket]
