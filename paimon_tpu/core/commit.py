"""FileStoreCommit: two-phase snapshot commit with optimistic retry.

reference: operation/FileStoreCommitImpl.java:139 (javadoc :122-132:
conflict check -> CAS publish; tryCommit retry loop :756), conflict
detection in operation/commit/ConflictDetection.java, atomicity provider
catalog/SnapshotCommit.java:27 (rename CAS here).
"""

from __future__ import annotations

import time as _time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

from paimon_tpu.core.write import CommitMessage
from paimon_tpu.data.binary_row import BinaryRowCodec
from paimon_tpu.fs import FileIO
from paimon_tpu.manifest import (
    DataFileMeta, FileKind, IndexManifestFile, ManifestEntry, ManifestFile,
    ManifestFileMeta, ManifestList, merge_manifest_entries,
)
from paimon_tpu.options import CoreOptions
from paimon_tpu.schema.table_schema import TableSchema
from paimon_tpu.snapshot import CommitKind, Snapshot, SnapshotManager
from paimon_tpu.snapshot.snapshot import BATCH_COMMIT_IDENTIFIER
from paimon_tpu.utils.path_factory import FileStorePathFactory

__all__ = ["FileStoreCommit", "CommitConflictError"]


class CommitConflictError(RuntimeError):
    pass


class FileStoreCommit:
    def __init__(self, file_io: FileIO, table_path: str,
                 table_schema: TableSchema, options: CoreOptions,
                 commit_user: Optional[str] = None,
                 branch: str = "main"):
        self.file_io = file_io
        self.table_path = table_path.rstrip("/")
        self.schema = table_schema
        self.options = options
        self.commit_user = commit_user or str(uuid.uuid4())
        self.snapshot_manager = SnapshotManager(file_io, table_path, branch)
        self.path_factory = FileStorePathFactory.from_options(
            table_path, table_schema.partition_keys, options)
        rt = table_schema.logical_row_type()
        self.partition_types = [rt.get_field(k).type
                                for k in table_schema.partition_keys]
        self._partition_codec = BinaryRowCodec(self.partition_types)
        compression = options.get(CoreOptions.MANIFEST_COMPRESSION)
        codec = {"zstd": "zstandard", "none": "null"}.get(compression,
                                                          compression)
        mdir = self.path_factory.manifest_dir
        key_types = [rt.get_field(k).type
                     for k in table_schema.trimmed_primary_keys()]
        sidecar = bool(options.get(CoreOptions.MANIFEST_STATS_SIDECAR))
        self.manifest_file = ManifestFile(file_io, mdir, codec,
                                          self.partition_types,
                                          key_types=key_types,
                                          sidecar=sidecar)
        self.manifest_list = ManifestList(
            file_io, mdir, codec, partition_types=self.partition_types,
            key_types=key_types, sidecar=sidecar)
        self.index_manifest_file = IndexManifestFile(file_io, mdir, codec)
        self.manifest_target_size = options.get(
            CoreOptions.MANIFEST_TARGET_FILE_SIZE)
        self.manifest_merge_min = options.get(
            CoreOptions.MANIFEST_MERGE_MIN_COUNT)
        # append tables with row-tracking.enabled get dense row ids
        # assigned at commit (reference FileStoreCommitImpl
        # .assignRowTracking:1046)
        self.row_tracking = (
            options.get(CoreOptions.ROW_TRACKING_ENABLED)
            and not table_schema.primary_keys)
        # optional lost-CAS observer (attempt number per loss): the
        # multi-host write plane (parallel/distributed.py) hangs its
        # commit_conflicts / commit_retries accounting here — commit
        # arbitration is THIS retry loop, observed from outside
        self.conflict_listener: Optional[callable] = None
        # optional () -> {str: str} merged into EVERY snapshot this
        # commit object publishes (explicit per-call properties win on
        # key collisions).  The multi-host maintenance plane hangs its
        # lease-renewal + ownership-generation stamps here so every
        # plane-issued commit — data checkpoints, compactions,
        # heartbeats — carries them: under plane-only traffic the tip
        # is always stamped and ownership/lease recovery never has to
        # walk past foreign snapshots.  Called once per CAS attempt,
        # so lease timestamps stay fresh across commit retries.
        self.properties_provider: Optional[callable] = None

    # -- public API ----------------------------------------------------------

    def commit(self, messages: Sequence[CommitMessage],
               commit_identifier: int = BATCH_COMMIT_IDENTIFIER,
               kind: Optional[str] = None,
               index_entries: Optional[list] = None,
               properties: Optional[Dict[str, str]] = None,
               expected_latest_id: Optional[int] = ...,
               watermark: Optional[int] = None,
               force_create: bool = False) -> Optional[int]:
        """Commit append + compact changes. Returns snapshot id (or None if
        nothing to commit). Append and compact deltas are committed as
        separate snapshots like the reference (APPEND then COMPACT)."""
        append_entries: List[ManifestEntry] = []
        compact_entries: List[ManifestEntry] = []
        changelog_entries: List[ManifestEntry] = []
        compact_changelog_entries: List[ManifestEntry] = []
        for msg in messages:
            pbytes = self._partition_codec.to_bytes(msg.partition)
            for f in msg.new_files:
                append_entries.append(ManifestEntry(
                    FileKind.ADD, pbytes, msg.bucket, msg.total_buckets, f))
            for f in msg.changelog_files:
                changelog_entries.append(ManifestEntry(
                    FileKind.ADD, pbytes, msg.bucket, msg.total_buckets, f))
            for f in msg.compact_before:
                compact_entries.append(ManifestEntry(
                    FileKind.DELETE, pbytes, msg.bucket, msg.total_buckets,
                    f))
            for f in msg.compact_after:
                compact_entries.append(ManifestEntry(
                    FileKind.ADD, pbytes, msg.bucket, msg.total_buckets, f))
            for f in msg.compact_changelog:
                compact_changelog_entries.append(ManifestEntry(
                    FileKind.ADD, pbytes, msg.bucket, msg.total_buckets, f))

        last_id = None
        force_empty = (
            force_create or
            self.options.get(CoreOptions.COMMIT_FORCE_CREATE_SNAPSHOT) or
            self.options.get(
                CoreOptions.SNAPSHOT_IGNORE_EMPTY_COMMIT) is False)
        if append_entries or changelog_entries or index_entries or \
                (force_empty and not compact_entries):
            last_id = self._try_commit(
                append_entries, changelog_entries, commit_identifier,
                kind or CommitKind.APPEND, index_entries=index_entries,
                properties=properties,
                expected_latest_id=expected_latest_id,
                watermark=watermark)
            index_entries = None
        if compact_entries or compact_changelog_entries:
            last_id = self._try_commit(
                compact_entries, compact_changelog_entries,
                commit_identifier, CommitKind.COMPACT,
                check_deleted_files=True, index_entries=index_entries,
                properties=properties, watermark=watermark)
        return last_id

    def overwrite(self, messages: Sequence[CommitMessage],
                  partition_filter: Optional[dict] = None,
                  commit_identifier: int = BATCH_COMMIT_IDENTIFIER,
                  index_entries: Optional[list] = None,
                  watermark: Optional[int] = None,
                  properties: Optional[Dict[str, str]] = None
                  ) -> Optional[int]:
        """INSERT OVERWRITE: delete current files (optionally restricted to
        a partition spec) and add new ones atomically
        (reference FileStoreCommitImpl.overwrite). The delete set is
        recomputed from the latest snapshot on every CAS attempt so files
        committed concurrently between planning and publish do not
        survive the overwrite."""
        adds: List[ManifestEntry] = []
        for msg in messages:
            pbytes = self._partition_codec.to_bytes(msg.partition)
            for f in msg.new_files:
                adds.append(ManifestEntry(
                    FileKind.ADD, pbytes, msg.bucket, msg.total_buckets, f))

        def entries_fn(latest: Optional[Snapshot]) -> List[ManifestEntry]:
            entries: List[ManifestEntry] = []
            if latest is not None:
                for e in self._read_all_entries(latest):
                    if e.kind != FileKind.ADD:
                        continue
                    if partition_filter and not self._partition_matches(
                            e.partition, partition_filter):
                        continue
                    entries.append(ManifestEntry(
                        FileKind.DELETE, e.partition, e.bucket,
                        e.total_buckets, e.file))
            return entries + adds

        return self._try_commit([], [], commit_identifier,
                                CommitKind.OVERWRITE, entries_fn=entries_fn,
                                index_entries=index_entries,
                                properties=properties,
                                watermark=watermark)

    def filter_committed(self, commit_identifiers: Sequence[int]
                         ) -> List[int]:
        """Drop identifiers already committed by this user (exactly-once
        replay dedup, reference FileStoreCommit.filterCommitted:52)."""
        committed = set()
        for snap in self.snapshot_manager.snapshots():
            if snap.commit_user == self.commit_user:
                committed.add(snap.commit_identifier)
        return [c for c in commit_identifiers if c not in committed]

    # -- internals -----------------------------------------------------------

    def _read_all_entries(self, snapshot: Snapshot) -> List[ManifestEntry]:
        metas = self.manifest_list.read_all(snapshot.base_manifest_list,
                                            snapshot.delta_manifest_list)
        entries: List[ManifestEntry] = []
        for m in metas:
            entries.extend(self.manifest_file.read(m.file_name))
        return merge_manifest_entries(entries)

    def _partition_matches(self, pbytes: bytes, spec: dict) -> bool:
        values = self._partition_codec.from_bytes(pbytes)
        for i, k in enumerate(self.schema.partition_keys):
            if k in spec and str(values[i]) != str(spec[k]):
                return False
        return True

    def _try_commit(self, entries: List[ManifestEntry],
                    changelog_entries: List[ManifestEntry],
                    commit_identifier: int, kind: str,
                    check_deleted_files: bool = False,
                    index_entries: Optional[list] = None,
                    properties: Optional[Dict[str, str]] = None,
                    entries_fn=None,
                    expected_latest_id: Optional[int] = ...,
                    statistics: Optional[str] = None,
                    watermark: Optional[int] = None,
                    force_full_manifest_merge: bool = False,
                    skip_missing_manifests: bool = False) -> int:
        from paimon_tpu.metrics import global_registry
        import time as _time

        from paimon_tpu.obs.trace import span as _span, sync_from_options
        from paimon_tpu.utils.backoff import Backoff
        from paimon_tpu.utils.deadline import DeadlineExceededError

        sync_from_options(self.options)
        _metrics = global_registry().group("commit")
        _t0 = _time.perf_counter()
        _attempts = 0
        _max_retries = self.options.get(CoreOptions.COMMIT_MAX_RETRIES)
        _min_wait = self.options.get(CoreOptions.COMMIT_MIN_RETRY_WAIT)
        _max_wait = self.options.get(CoreOptions.COMMIT_MAX_RETRY_WAIT)
        # decorrelated jitter between the retry-wait bounds, bounded in
        # total time by commit.timeout (utils/backoff.py — shared with
        # RetryingObjectStoreBackend and the mesh bucket-retry ladder)
        _backoff = Backoff(_min_wait, _max_wait,
                           self.options.get(CoreOptions.COMMIT_TIMEOUT))
        new_manifest: Optional[ManifestFileMeta] = None
        changelog_manifest: Optional[ManifestFileMeta] = None
        entries_orig = list(entries)
        # per-attempt artifacts, pre-bound so the deadline-abort
        # handler below can delete whatever the CURRENT attempt
        # had written when the deadline tripped: a
        # DeadlineExceededError can surface from ANY store read
        # inside an attempt (every FileIO read checks the
        # deadline), not only at the CAS gate — an abort must
        # never leave this attempt's manifests orphaned
        base_name = delta_name = changelog_name = None
        index_manifest = prev_index = None
        merged_manifests: List[ManifestFileMeta] = []

        def _delete_attempt_lists():
            """Drop the CURRENT attempt's manifest lists, index
            manifest and merged manifests — shared by the lost-CAS
            retry and the deadline-abort handler so the two abort
            paths cannot drift (closure: reads the attempt's latest
            bindings; every delete is quiet + deadline-shielded)."""
            if base_name:
                self.manifest_list.delete(base_name)
            if delta_name:
                self.manifest_list.delete(delta_name)
            if changelog_name:
                self.manifest_list.delete(changelog_name)
            if index_manifest is not None and \
                    index_manifest != prev_index:
                self.file_io.delete_quietly(
                    self.index_manifest_file.path(index_manifest))
            for m in merged_manifests:
                self.file_io.delete_quietly(
                    self.manifest_file.path(m.file_name))

        try:
            while True:
                if _attempts > _max_retries or \
                        (_attempts > 0 and _backoff.budget_exhausted()):
                    # the per-attempt cleanup keeps the (reusable) delta and
                    # changelog manifest FILES; on giving up they would be
                    # orphaned with no snapshot referencing them
                    for m in (new_manifest, changelog_manifest):
                        if m is not None:
                            self.file_io.delete_quietly(
                                self.manifest_file.path(m.file_name))
                    raise CommitConflictError(
                        f"Commit lost the snapshot CAS race "
                        f"{_attempts - 1} times (commit.max-retries="
                        f"{_max_retries}, commit.timeout); giving up")
                if _attempts > 0:
                    with _span("commit.backoff", cat="commit",
                               attempt=_attempts, table=self.table_path):
                        _backoff.pause()
                _attempts += 1
                latest = self.snapshot_manager.latest_snapshot()
                if expected_latest_id is not ... and \
                        (latest.id if latest else None) != expected_latest_id:
                    # the caller's plan is stale (e.g. deletion vectors built
                    # against an older snapshot): surface a conflict so it can
                    # replan instead of silently losing concurrent changes
                    raise CommitConflictError(
                        f"Snapshot advanced past "
                        f"{expected_latest_id} before commit; replan required")
                if entries_fn is not None:
                    # delete/add set depends on the latest snapshot (e.g.
                    # overwrite): recompute per attempt; per-attempt manifests
                    # are cleaned up on CAS loss below
                    entries = entries_fn(latest)
                    new_manifest = None
                next_row_id = latest.next_row_id if latest else None
                candidates = entries if entries_fn is not None else \
                    entries_orig
                ids_assigned = False
                if self.row_tracking and any(
                        e.kind == FileKind.ADD and e.file.first_row_id is None
                        for e in candidates):
                    # row-id start depends on the latest snapshot, so the
                    # assignment re-runs from the pre-assignment entries
                    # (and the manifest is rewritten) on every CAS attempt
                    from paimon_tpu.core.row_tracking import assign_row_ids
                    start = next_row_id
                    if start is None:
                        # tracking enabled on an existing table: ids for old
                        # files stay unassigned; new ids start past all rows
                        start = latest.total_record_count if latest else 0
                    entries, next_row_id = assign_row_ids(candidates, start)
                    new_manifest = None
                    ids_assigned = True
                if check_deleted_files and latest is not None:
                    self._assert_files_exist(latest, entries)

                from paimon_tpu.metrics import COMMIT_MANIFEST_ENCODE_MS

                def _write_manifest(manifest_entries, which):
                    with _span("commit.manifest_encode", cat="commit",
                               group="commit",
                               metric=COMMIT_MANIFEST_ENCODE_MS,
                               which=which, attempt=_attempts,
                               entries=len(manifest_entries)):
                        return self.manifest_file.write(
                            manifest_entries, schema_id=self.schema.id)

                if new_manifest is None and entries and \
                        changelog_manifest is None and changelog_entries:
                    # both manifests are needed and independent: encode +
                    # upload the delta manifest on a worker while the
                    # changelog manifest encodes here, so commit prep waits
                    # on completion, not initiation (write-pipeline PR)
                    from paimon_tpu.parallel.executors import new_thread_pool
                    pool = new_thread_pool(1, "paimon-commit")
                    try:
                        fut = pool.submit(_write_manifest, entries, "delta")
                        changelog_manifest = _write_manifest(
                            changelog_entries, "changelog")
                        from paimon_tpu.utils.deadline import wait_future
                        new_manifest = wait_future(
                            fut, "commit delta manifest write")
                    finally:
                        pool.shutdown(wait=True)
                if new_manifest is None and entries:
                    new_manifest = _write_manifest(entries, "delta")
                if changelog_manifest is None and changelog_entries:
                    changelog_manifest = _write_manifest(changelog_entries,
                                                         "changelog")

                if latest is None:
                    base_metas: List[ManifestFileMeta] = []
                    new_id = 1
                    prev_total = 0
                    prev_index = None
                else:
                    base_metas = self.manifest_list.read_all(
                        latest.base_manifest_list, latest.delta_manifest_list)
                    new_id = latest.id + 1
                    prev_total = latest.total_record_count
                    prev_index = latest.index_manifest

                base_metas, merged_manifests = \
                    self._maybe_merge_manifests(
                        base_metas, force=force_full_manifest_merge,
                        skip_missing=skip_missing_manifests)
                base_name, base_size = self.manifest_list.write(base_metas)
                delta_metas = [new_manifest] if new_manifest else []
                delta_name, delta_size = self.manifest_list.write(delta_metas)
                changelog_name = None
                changelog_size = None
                if changelog_manifest is not None:
                    changelog_name, changelog_size = self.manifest_list.write(
                        [changelog_manifest])

                index_manifest = self.index_manifest_file.combine(
                    prev_index, index_entries or [])

                # watermarks only advance (reference FileStoreCommitImpl:
                # max of provided and previous)
                wm_vals = [w for w in
                           (watermark, latest.watermark if latest else None)
                           if w is not None]
                new_watermark = max(wm_vals) if wm_vals else None
                if force_full_manifest_merge and \
                        getattr(self, "_force_merge_total", None) is not None:
                    # the full rewrite recounted every live entry — use the
                    # true total (skip_missing may have dropped manifests)
                    prev_total = self._force_merge_total
                    self._force_merge_total = None
                delta_rows = sum(
                    (e.file.row_count if e.kind == FileKind.ADD
                     else -e.file.row_count) for e in entries)
                changelog_rows = sum(e.file.row_count
                                     for e in changelog_entries)
                eff_properties = properties
                if self.properties_provider is not None:
                    # provider stamps merge UNDER the explicit ones;
                    # evaluated per attempt so lease renewals reflect
                    # the attempt that actually publishes
                    merged_props = dict(self.properties_provider() or {})
                    merged_props.update(properties or {})
                    eff_properties = merged_props or None
                from paimon_tpu.obs.trace import current_context_token
                _ctx = current_context_token()
                if _ctx is not None:
                    # store-carried trace context: readers of this
                    # snapshot (scan plans, lease folds) link their
                    # spans back to the committing process's span in
                    # the merged fleet trace.  setdefault — an
                    # explicit/provider-stamped context (takeover
                    # attribution) wins over the ambient span.
                    eff_properties = dict(eff_properties or {})
                    eff_properties.setdefault("trace.context", _ctx)
                snapshot = Snapshot(
                    id=new_id,
                    schema_id=self.schema.id,
                    base_manifest_list=base_name,
                    base_manifest_list_size=base_size,
                    delta_manifest_list=delta_name,
                    delta_manifest_list_size=delta_size,
                    changelog_manifest_list=changelog_name,
                    changelog_manifest_list_size=changelog_size,
                    index_manifest=index_manifest,
                    commit_user=self.commit_user,
                    commit_identifier=commit_identifier,
                    commit_kind=kind,
                    time_millis=int(_time.time() * 1000),
                    total_record_count=prev_total + delta_rows,
                    delta_record_count=delta_rows,
                    changelog_record_count=changelog_rows or None,
                    properties=eff_properties,
                    statistics=statistics,
                    next_row_id=next_row_id,
                    watermark=new_watermark,
                )
                from paimon_tpu.metrics import COMMIT_CAS_MS
                from paimon_tpu.utils.deadline import check_deadline
                # the point of no return is the CAS itself: a request
                # whose deadline is already spent must raise HERE, before
                # publishing — a 504'd caller can clean up / retry an
                # UNcommitted attempt, but an orphan-committed snapshot
                # would make the timeout a lie (the except handler around
                # the whole retry loop cleans this attempt's artifacts)
                check_deadline("commit CAS")
                with _span("commit.cas", cat="commit", group="commit",
                           metric=COMMIT_CAS_MS, attempt=_attempts,
                           snapshot=new_id, table=self.table_path) as _cas:
                    _won = self.snapshot_manager.try_commit(snapshot)
                    _cas.set(won=_won)
                if _won:
                    _metrics.counter("commits").inc()
                    if _attempts > 1:
                        _metrics.counter("retries").inc(_attempts - 1)
                    _metrics.histogram("duration_ms").update(
                        (_time.perf_counter() - _t0) * 1000)
                    return new_id
                # lost the race: clean up everything written for this attempt
                # and retry against the new latest (the delta manifest is
                # reusable across attempts unless the entry set is dynamic)
                if self.conflict_listener is not None:
                    self.conflict_listener(_attempts)
                from paimon_tpu.obs.flight import (
                    EV_COMMIT_CONFLICT, record,
                )
                record(EV_COMMIT_CONFLICT, attempt=_attempts,
                       snapshot=new_id, user=self.commit_user)
                _delete_attempt_lists()
                if (entries_fn is not None or ids_assigned) and \
                        new_manifest is not None:
                    # the entry set was rebuilt for this attempt (dynamic
                    # entries or per-attempt row-id assignment): its manifest
                    # is stale too, and must not be referenced by the retry
                    self.file_io.delete_quietly(
                        self.manifest_file.path(new_manifest.file_name))
                    new_manifest = None

        except DeadlineExceededError:
            # same cleanup as a lost CAS, plus the manifests the
            # exhausted-retries path would drop: nothing written
            # for this attempt may outlive the abort (deletes are
            # deadline-shielded via delete_quietly)
            _delete_attempt_lists()
            for m in (new_manifest, changelog_manifest):
                if m is not None:
                    self.file_io.delete_quietly(
                        self.manifest_file.path(m.file_name))
            raise

    def _assert_files_exist(self, latest: Snapshot,
                            entries: List[ManifestEntry]):
        """Compaction conflict checks (reference
        operation/commit/ConflictDetection.java):
        1. every file we delete must still be live
        2. files we add at level > 0 must not overlap the key range of a
           concurrent live file at the same level (two racing
           compactions writing the same level would corrupt the
           no-overlap invariant levels >= 1 rely on)"""
        deletes = [e for e in entries if e.kind == FileKind.DELETE]
        adds_upper = [e for e in entries
                      if e.kind == FileKind.ADD and e.file.level > 0]
        if not deletes and not adds_upper:
            return
        live_entries = [e for e in self._read_all_entries(latest)
                        if e.kind == FileKind.ADD]
        live = {e.identifier() for e in live_entries}
        for d in deletes:
            ident = (d.partition, d.bucket, d.file.level, d.file.file_name,
                     tuple(d.file.extra_files), d.file.embedded_index,
                     d.file.external_path)
            if ident not in live:
                raise CommitConflictError(
                    f"File to delete no longer exists: "
                    f"{d.file.file_name} (level {d.file.level}); "
                    f"a concurrent compaction won. Retry the compaction "
                    f"from the new snapshot.")
        if not adds_upper:
            return
        key_types = [
            self.schema.logical_row_type().get_field(k).type.copy(False)
            for k in self.schema.trimmed_primary_keys()]
        if not key_types:
            return
        key_codec = BinaryRowCodec(key_types)

        def decode_key(b: bytes):
            # BinaryRow bytes are NOT order-comparable (little-endian
            # slots); decode to value tuples like the reference's typed
            # comparator
            if not b:
                return None
            try:
                return tuple(key_codec.from_bytes(b))
            except Exception:
                return None

        deleted_names = {(d.partition, d.bucket, d.file.file_name)
                         for d in deletes}
        for a in adds_upper:
            a_min = decode_key(a.file.min_key)
            a_max = decode_key(a.file.max_key)
            if a_min is None or a_max is None:
                continue
            for e in live_entries:
                if (e.partition, e.bucket, e.file.level) != \
                        (a.partition, a.bucket, a.file.level):
                    continue
                if (e.partition, e.bucket, e.file.file_name) \
                        in deleted_names:
                    continue       # replaced by this very commit
                e_min = decode_key(e.file.min_key)
                e_max = decode_key(e.file.max_key)
                if e_min is None or e_max is None:
                    continue
                if a_min <= e_max and e_min <= a_max:
                    raise CommitConflictError(
                        f"Key range of new file {a.file.file_name} "
                        f"(level {a.file.level}) overlaps live file "
                        f"{e.file.file_name}; a concurrent compaction "
                        f"wrote this level. Retry from the new snapshot.")

    def compact_manifests(self, skip_missing: bool = False,
                          properties: Optional[Dict[str, str]] = None
                          ) -> Optional[int]:
        """Force one full manifest rewrite: every base+delta manifest is
        read, DELETE entries are folded away, and the merged entry set
        is committed as a COMPACT snapshot with an empty delta — the
        base rewritten as sorted, partition-clustered, size-bounded
        manifests (reference flink/procedure/CompactManifestProcedure +
        manifest full-compaction). Returns the new snapshot id, or None
        when the table has no snapshot.  `skip_missing` tolerates
        manifest FILES deleted out of band (reference
        RemoveUnexistingManifestsProcedure) — entries they held are
        lost, which is the point of that repair.

        A pure full-compaction commits as COMPACT with an empty delta
        — the live-entry set is unchanged, so the delta-apply plan
        cache folds it as a no-op.  The `skip_missing` repair DROPS
        entries without DELETE records, so it commits as OVERWRITE:
        every cached plan (this process or any other) invalidates
        instead of serving ghost entries for files the repair
        removed."""
        if self.snapshot_manager.latest_snapshot() is None:
            return None
        return self._try_commit([], [], BATCH_COMMIT_IDENTIFIER,
                                CommitKind.OVERWRITE if skip_missing
                                else CommitKind.COMPACT,
                                properties=properties,
                                force_full_manifest_merge=True,
                                skip_missing_manifests=skip_missing)

    def _maybe_merge_manifests(self, metas: List[ManifestFileMeta],
                               force: bool = False,
                               skip_missing: bool = False
                               ) -> Tuple[List[ManifestFileMeta],
                                          List[ManifestFileMeta]]:
        """Full-rewrite small manifests when there are too many
        (reference manifest/ManifestFileMerger); `force` merges
        EVERYTHING and folds DELETE entries (compact_manifests).
        Returns (metas, newly_written) so the caller can delete fresh
        files if the commit attempt loses the CAS."""
        if force:
            entries: List[ManifestEntry] = []
            for m in metas:
                try:
                    entries.extend(self.manifest_file.read(m.file_name))
                except FileNotFoundError:
                    if not skip_missing:
                        raise
                    # repair mode: the manifest is gone, its entries
                    # are unrecoverable — drop it from the chain
            merged = merge_manifest_entries(entries)
            # the rewrite KNOWS the true row total; expose it so the
            # snapshot does not inherit counts from dropped manifests
            self._force_merge_total = sum(
                e.file.row_count for e in merged
                if e.kind == FileKind.ADD)
            if not merged:
                return [], []
            # sorted, partition-clustered, size-bounded base manifests
            # (reference Paimon manifest full-compaction): each output
            # manifest covers a narrow (partition, bucket, key) band,
            # so the per-manifest stats the columnar sidecar persists
            # stay selective and the vectorized prune keeps whole
            # manifests unfetched.  Raw-byte key order is a clustering
            # heuristic only — correctness never depends on it.
            merged.sort(key=lambda e: (e.partition, e.bucket,
                                       e.file.min_key or b""))
            total_size = sum(m.file_size for m in metas)
            total_entries = sum(m.num_added_files + m.num_deleted_files
                                for m in metas) or 1
            per_entry = max(64, total_size // total_entries) \
                if total_size else 256
            chunk = max(1, int(self.manifest_target_size // per_entry))
            out = []
            for i in range(0, len(merged), chunk):
                out.append(self.manifest_file.write(
                    merged[i:i + chunk], schema_id=self.schema.id))
            return out, list(out)
        if len(metas) < self.manifest_merge_min:
            return metas, []
        small = [m for m in metas if m.file_size < self.manifest_target_size]
        if len(small) < 2:
            return metas, []
        big = [m for m in metas if m.file_size >= self.manifest_target_size]
        entries: List[ManifestEntry] = []
        for m in small:
            entries.extend(self.manifest_file.read(m.file_name))
        merged = merge_manifest_entries(entries)
        out = list(big)
        written = []
        if merged:
            meta = self.manifest_file.write(merged, schema_id=self.schema.id)
            out.append(meta)
            written.append(meta)
        return out, written
