"""Read path: merge-on-read over DataSplits.

reference call stack (SURVEY §3.2): KeyValueTableRead ->
MergeFileSplitRead.createMergeReader (operation/MergeFileSplitRead.java:
269,287) -> MergeTreeReaders.readerForMergeTree -> per-section
SortMergeReaderWithLoserTree -> MergeFunctionWrapper -> DropDeleteReader;
fast path RawFileSplitRead.java:74.

TPU deviation: a split's runs are decoded to Arrow (Arrow C++ parquet),
then merged in one device kernel (ops/merge.py) instead of a record
iterator stack. Sections (IntervalPartition) are unnecessary: the sort
handles arbitrary overlap; non-overlapping byte ranges just sort cheaply.
"""

from __future__ import annotations

import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from paimon_tpu.core.kv_file import KEY_PREFIX, read_kv_file
from paimon_tpu.core.scan import DataSplit
from paimon_tpu.fs import FileIO
from paimon_tpu.manifest import DataFileMeta
from paimon_tpu.options import CoreOptions, MergeEngine
from paimon_tpu.ops.merge import KIND_COL, SEQ_COL, merge_runs
from paimon_tpu.ops.normkey import NormalizedKeyEncoder
from paimon_tpu.predicate import Predicate
from paimon_tpu.schema.schema_manager import SchemaManager
from paimon_tpu.schema.table_schema import TableSchema
from paimon_tpu.types import RowKind, data_type_to_arrow
from paimon_tpu.utils.path_factory import FileStorePathFactory

__all__ = ["MergeFileSplitRead", "assemble_runs", "ROW_KIND_COL",
           "evolve_table"]

ROW_KIND_COL = "_ROW_KIND"


def record_level_expire_filter(options: CoreOptions, table: pa.Table,
                               now_ms: Optional[int] = None) -> pa.Table:
    """Hide rows whose time field passed record-level.expire-time
    (reference io/RecordLevelExpire wrapping every reader; physical
    removal happens at compaction rewrite).  `now_ms` pins the wall
    clock for deterministic tests (same injectable-clock contract as
    remove_orphan_files)."""
    import pyarrow.compute as pc

    expire_ms = options.record_level_expire_time_ms
    field = options.record_level_time_field
    if not expire_ms or not field or field not in table.column_names:
        return table
    col = table.column(field).combine_chunks()
    t = col.type
    if pa.types.is_timestamp(t):
        vals_ms = np.asarray(col.cast(pa.int64()).fill_null(0))
        unit = {"s": 1000, "ms": 1, "us": 1 / 1000,
                "ns": 1 / 1_000_000}[t.unit]
        vals_ms = (vals_ms * unit).astype(np.int64)
    elif pa.types.is_int32(t):
        vals_ms = np.asarray(col.fill_null(0)).astype(np.int64) * 1000
    else:
        vals_ms = np.asarray(col.cast(pa.int64()).fill_null(0))
    if now_ms is None:
        now_ms = int(time.time() * 1000)
    cutoff = now_ms - expire_ms
    keep = (vals_ms >= cutoff) | np.asarray(pc.is_null(col))
    if keep.all():
        return table
    return table.filter(pa.array(keep))


def evolve_table(table: pa.Table, file_schema_id: int, schema: TableSchema,
                 schema_manager: Optional[SchemaManager],
                 cache: Dict[int, TableSchema],
                 keep_sys_cols: bool = False) -> pa.Table:
    """Map an old-schema file onto the read schema by field id
    (reference schema/SchemaEvolutionUtil.java index+cast mapping).
    Shared by both split readers and both compaction rewriters.

    Same-schema files still get a cheap per-column type check + cast:
    schema-inferring formats (csv/json) may decode e.g. float32 as
    float64 or timestamps as strings."""
    if file_schema_id == schema.id:
        needs_cast = False
        for f in schema.fields:
            if f.name in table.column_names and \
                    table.column(f.name).type != data_type_to_arrow(f.type):
                needs_cast = True
                break
        if not needs_cast:
            return table
        cols = {}
        for name in table.column_names:
            col = table.column(name)
            if name.startswith(KEY_PREFIX) or name in (SEQ_COL, KIND_COL):
                cols[name] = col
                continue
            f = next((x for x in schema.fields if x.name == name), None)
            if f is None:
                cols[name] = col
                continue
            at = data_type_to_arrow(f.type)
            cols[name] = col.cast(at) if col.type != at else col
        return pa.table(cols)
    old = cache.get(file_schema_id)
    if old is None:
        if schema_manager is None:
            return table
        old = schema_manager.schema(file_schema_id)
        cache[file_schema_id] = old
    old_by_id = {f.id: f for f in old.fields}
    cols = {}
    n = table.num_rows
    if keep_sys_cols:
        for name in table.column_names:
            if name.startswith(KEY_PREFIX) or name in (SEQ_COL, KIND_COL):
                cols[name] = table.column(name)
    for f in schema.fields:
        old_f = old_by_id.get(f.id)
        arrow_t = data_type_to_arrow(f.type)
        if old_f is None or old_f.name not in table.column_names:
            cols[f.name] = pa.nulls(n, arrow_t)
        else:
            col = table.column(old_f.name)
            if col.type != arrow_t:
                # evolve-time type change: apply the CastExecutor rule
                # matrix (Java narrowing/parse/temporal semantics), not
                # the bare Arrow cast (paimon-common casting/)
                from paimon_tpu.data.casting import cast_array
                col = cast_array(col, old_f.type, f.type)
            cols[f.name] = col
    return pa.table(cols)


def assemble_runs(files: Sequence[DataFileMeta]) -> List[List[DataFileMeta]]:
    """Order a bucket's files into sorted runs, oldest first.

    Levels >=1 are each one key-sorted non-overlapping run (older = higher
    level). Each L0 file is its own run, ordered by max sequence number
    (reference mergetree/Levels.java:39 + MergeTreeReaders.readerForMergeTree).
    """
    by_level: Dict[int, List[DataFileMeta]] = {}
    for f in files:
        by_level.setdefault(f.level, []).append(f)
    runs: List[List[DataFileMeta]] = []
    for level in sorted((l for l in by_level if l > 0), reverse=True):
        level_files = sorted(by_level[level], key=lambda f: f.min_key)
        runs.append(level_files)
    for f in sorted(by_level.get(0, []),
                    key=lambda f: (f.max_sequence_number,
                                   f.min_sequence_number)):
        runs.append([f])
    return runs


class MergeFileSplitRead:
    """Reads DataSplits with merge (or raw when safe)."""

    def __init__(self, file_io: FileIO, table_path: str,
                 schema: TableSchema, options: CoreOptions,
                 schema_manager: Optional[SchemaManager] = None):
        self.file_io = file_io
        self.table_path = table_path
        self.schema = schema
        self.options = options
        self.schema_manager = schema_manager
        self.path_factory = FileStorePathFactory.from_options(
            table_path, schema.partition_keys, options)
        self.trimmed_pk = schema.trimmed_primary_keys()
        self.key_cols = [KEY_PREFIX + k for k in self.trimmed_pk]
        rt = schema.logical_row_type()
        self.key_encoder = NormalizedKeyEncoder(
            [data_type_to_arrow(rt.get_field(k).type)
             for k in self.trimmed_pk],
            nullable=[rt.get_field(k).type.nullable
                      for k in self.trimmed_pk])
        self._schema_cache: Dict[int, TableSchema] = {schema.id: schema}
        self._projection: Optional[List[str]] = None
        self._predicate: Optional[Predicate] = None

    def with_projection(self, columns: Optional[List[str]]
                        ) -> "MergeFileSplitRead":
        self._projection = list(columns) if columns else None
        return self

    def with_filter(self, predicate: Optional[Predicate]
                    ) -> "MergeFileSplitRead":
        self._predicate = predicate
        return self

    # -- split read ----------------------------------------------------------

    def read_split(self, split: DataSplit) -> pa.Table:
        value_cols = self._value_columns()
        if self.options.get(CoreOptions.TABLE_READ_SEQUENCE_NUMBER):
            # expose _SEQUENCE_NUMBER as a metadata column (reference
            # table-read.sequence-number.enabled)
            value_cols = value_cols + [SEQ_COL]
        read_cols = self.key_cols + [SEQ_COL, KIND_COL] + value_cols
        read_cols = list(dict.fromkeys(read_cols))
        if split.raw_convertible:
            out = self._read_raw(split, read_cols, value_cols)
        else:
            out = self._read_merged(split, read_cols, value_cols)
        out = record_level_expire_filter(self.options, out)
        if self._predicate is not None:
            out = out.filter(self._predicate.to_arrow())
        return out

    def iter_splits(self, splits: Sequence[DataSplit], *,
                    ordered: bool = True
                    ) -> Iterator[Tuple[int, DataSplit, pa.Table]]:
        """(index, split, table) through the bounded prefetch pipeline
        (parallel/scan_pipeline.py); ordered=False yields in completion
        order."""
        from paimon_tpu.parallel.scan_pipeline import iter_split_tables
        return iter_split_tables(self, splits, self.options,
                                 ordered=ordered)

    def read_splits(self, splits: Sequence[DataSplit],
                    streaming: Optional[bool] = None) -> pa.Table:
        tables = [t for _, _, t in self.iter_splits(splits)
                  if t.num_rows > 0]
        if not tables:
            if streaming is None:
                streaming = any(s.for_streaming for s in splits)
            return self._empty_table(streaming)
        return pa.concat_tables(tables, promote_options="default")

    def _empty_table(self, streaming: bool) -> pa.Table:
        """Typed empty result with a schema identical to non-empty reads
        (streaming polls always carry _ROW_KIND)."""
        by_name = {f.name: f for f in self.schema.fields}
        cols = {c: pa.array([], data_type_to_arrow(by_name[c].type))
                for c in self._value_columns()}
        if self.options.get(CoreOptions.TABLE_READ_SEQUENCE_NUMBER):
            cols[SEQ_COL] = pa.array([], pa.int64())
        if streaming:
            cols[ROW_KIND_COL] = pa.array([], pa.int8())
        return pa.table(cols)

    def _value_columns(self) -> List[str]:
        names = [f.name for f in self.schema.fields]
        if self._projection:
            # key, pk, user-sequence and record-expire time columns are
            # read regardless; output honors the projection
            keep = set(self._projection) | set(self.trimmed_pk) \
                | set(self.options.sequence_field)
            if self.options.record_level_time_field:
                keep.add(self.options.record_level_time_field)
            return [n for n in names if n in keep]
        return names

    def _read_file(self, split: DataSplit, meta: DataFileMeta,
                   read_cols: List[str]) -> Optional[pa.Table]:
        from paimon_tpu.parallel.scan_pipeline import read_or_skip_corrupt
        table = read_or_skip_corrupt(
            lambda: read_kv_file(
                self.file_io, self.path_factory, split.partition,
                split.bucket, meta, file_format=None, projection=None,
                schema=self.schema, schema_manager=self.schema_manager,
                wanted=set(read_cols), options=self.options),
            self.options, f"data file {meta.file_name}")
        if table is None:
            return None
        table = self._evolve(table, meta.schema_id)
        if split.deletion_vectors and \
                meta.file_name in split.deletion_vectors and \
                self.options.get(CoreOptions.DELETION_VECTORS_MERGE_ON_READ):
            dv = split.deletion_vectors[meta.file_name]
            mask = dv.keep_mask(table.num_rows)
            table = table.filter(pa.array(mask))
        return table.select(read_cols)

    def _read_raw(self, split: DataSplit, read_cols: List[str],
                  value_cols: List[str]) -> pa.Table:
        tables = [t for t in (self._read_file(split, f, read_cols)
                              for f in sorted(split.data_files,
                                              key=lambda f: f.min_key))
                  if t is not None]
        if not tables:
            return self._empty_table(bool(split.for_streaming))
        merged = pa.concat_tables(tables, promote_options="none")
        if split.for_streaming and split.is_delta:
            # changelog consumers observe every row with its kind
            # (reference streaming read preserves RowKind; -U/-D survive)
            out = merged.select(value_cols)
            return out.append_column(
                ROW_KIND_COL,
                merged.column(KIND_COL).combine_chunks().cast(pa.int8()))
        kinds = np.asarray(merged.column(KIND_COL).combine_chunks()
                           .cast(pa.int8()))
        keep = (kinds == RowKind.INSERT) | (kinds == RowKind.UPDATE_AFTER)
        if not keep.all():
            merged = merged.filter(pa.array(keep))
        out = merged.select(value_cols)
        if split.for_streaming:
            # full-phase streaming rows are the merged state: all +I
            out = out.append_column(
                ROW_KIND_COL,
                pa.array(np.zeros(out.num_rows, np.int8), pa.int8()))
        return out

    def _read_merged(self, split: DataSplit, read_cols: List[str],
                     value_cols: List[str]) -> pa.Table:
        runs_meta = assemble_runs(split.data_files)
        runs = []
        for run_files in runs_meta:
            tables = [t for t in (self._read_file(split, f, read_cols)
                                  for f in run_files) if t is not None]
            if not tables:
                continue                  # whole run corrupt + ignored
            runs.append(pa.concat_tables(tables, promote_options="none")
                        if len(tables) > 1 else tables[0])
        if not runs:
            return self._empty_table(bool(split.for_streaming))
        engine = self.options.merge_engine
        seq_fields = self.options.sequence_field or None
        seq_desc = self.options.sequence_field_descending
        from paimon_tpu.metrics import SCAN_MERGE_MS
        from paimon_tpu.obs.trace import span
        with span("scan.merge", cat="scan", group="scan",
                  metric=SCAN_MERGE_MS, engine=engine,
                  partition=split.partition, bucket=split.bucket,
                  runs=len(runs),
                  rows=sum(r.num_rows for r in runs)):
            if engine == MergeEngine.FIRST_ROW:
                res = merge_runs(runs, self.key_cols,
                                 merge_engine="first-row",
                                 key_encoder=self.key_encoder,
                                 seq_fields=seq_fields, seq_desc=seq_desc)
                out = res.take(value_cols)
            elif engine in (MergeEngine.DEDUPLICATE,):
                res = merge_runs(runs, self.key_cols,
                                 key_encoder=self.key_encoder,
                                 seq_fields=seq_fields, seq_desc=seq_desc)
                out = res.take(value_cols)
            else:
                from paimon_tpu.ops.agg import merge_runs_agg
                out = merge_runs_agg(runs, self.key_cols, self.schema,
                                     self.options,
                                     key_encoder=self.key_encoder,
                                     seq_fields=seq_fields
                                     ).select(value_cols)
        if split.for_streaming:
            out = out.append_column(
                ROW_KIND_COL,
                pa.array(np.zeros(out.num_rows, np.int8), pa.int8()))
        return out

    # -- schema evolution ----------------------------------------------------

    def _evolve(self, table: pa.Table, file_schema_id: int) -> pa.Table:
        return evolve_table(table, file_schema_id, self.schema,
                            self.schema_manager, self._schema_cache,
                            keep_sys_cols=True)
