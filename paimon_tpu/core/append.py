"""Append-only tables: write, read and compaction without keys.

reference: paimon-core/.../append/AppendOnlyWriter.java (rolling plain
files, inserts only), BucketedAppendCompactManager.java (contiguous
small-file grouping per bucket), AppendOnlyFileStoreTable /
AppendOnlySplitGenerator; unaware-bucket mode (BucketMode.BUCKET_UNAWARE,
bucket = -1) stores every file under bucket-0 with no shuffle.

Data files carry the plain value columns only (no _KEY_/_SEQUENCE_NUMBER/
_VALUE_KIND); ordering comes from DataFileMeta sequence ranges.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from paimon_tpu.core.bucket import FixedBucketAssigner
from paimon_tpu.core.kv_file import _safe_stats
from paimon_tpu.core.scan import DataSplit
from paimon_tpu.core.write import (
    CommitMessage, ROW_KIND_COL, group_by_partition_bucket,
)
from paimon_tpu.format import get_format
from paimon_tpu.format.format import extract_simple_stats
from paimon_tpu.fs import FileIO
from paimon_tpu.manifest import DataFileMeta, FileSource, SimpleStats
from paimon_tpu.options import CoreOptions
from paimon_tpu.predicate import Predicate
from paimon_tpu.schema.schema_manager import SchemaManager
from paimon_tpu.schema.table_schema import TableSchema
from paimon_tpu.types import RowKind, data_type_to_arrow
from paimon_tpu.utils.path_factory import FileStorePathFactory

__all__ = ["AppendOnlyFileStoreWrite", "AppendSplitRead",
           "append_compact_plan"]


class AppendFileWriter:
    """Rolling writer for plain-column append files."""

    def __init__(self, file_io: FileIO, path_factory: FileStorePathFactory,
                 table_schema: TableSchema, file_format: str,
                 compression: str, target_file_size: int,
                 index_spec: Optional[Dict[str, List[str]]] = None,
                 bloom_fpp: float = 0.01,
                 index_in_manifest_threshold: int = 500,
                 format_options: Optional[Dict[str, str]] = None):
        self.file_io = file_io
        self.path_factory = path_factory
        self.schema = table_schema
        self.file_format = file_format
        self.format_options = format_options or {}
        self.compression = compression
        self.target_file_size = target_file_size
        self.index_spec = index_spec or {}
        self.bloom_fpp = bloom_fpp
        self.index_in_manifest_threshold = index_in_manifest_threshold

    def write(self, partition: Tuple, bucket: int, table: pa.Table,
              first_seq: int,
              file_source: int = FileSource.APPEND) -> List[DataFileMeta]:
        if table.num_rows == 0:
            return []
        n = table.num_rows
        bytes_per_row = max(1, table.nbytes // n)
        rows_per_file = max(1024, self.target_file_size // bytes_per_row)
        metas = []
        seq = first_seq
        for start in range(0, n, rows_per_file):
            chunk = table.slice(start, min(rows_per_file, n - start))
            metas.append(self._write_one(partition, bucket, chunk, seq,
                                         file_source))
            seq += chunk.num_rows
        return metas

    def _write_one(self, partition: Tuple, bucket: int, chunk: pa.Table,
                   first_seq: int, file_source: int) -> DataFileMeta:
        fmt = get_format(self.file_format)
        name = self.path_factory.new_data_file_name(fmt.extension)
        path, external = self.path_factory.new_data_file_location(
            partition, bucket, name)
        from paimon_tpu.format.blob import blob_column_names
        blob_cols = blob_column_names(self.schema)
        blob_extras: List[str] = []
        if blob_cols:
            from paimon_tpu.format.blob import externalize_blobs
            chunk, blob_extras = externalize_blobs(
                self.file_io, self.path_factory, partition, bucket, name,
                chunk, blob_cols)
        size = fmt.create_writer(self.compression,
                                 self.format_options).write(
            self.file_io, path, chunk)
        value_cols = [f.name for f in self.schema.fields]
        vmins, vmaxs, vnulls = extract_simple_stats(chunk, value_cols)
        value_stats = _safe_stats([f.type for f in self.schema.fields],
                                  vmins, vmaxs, vnulls)
        embedded_index, extra_files = None, []
        if self.index_spec:
            from paimon_tpu.index.bloom import place_file_index
            from paimon_tpu.index.file_index import build_indexes_blob
            blob = build_indexes_blob(chunk, self.index_spec,
                                      self.bloom_fpp)
            embedded_index, extra_files = place_file_index(
                self.file_io, self.path_factory, partition, bucket, name,
                blob, self.index_in_manifest_threshold)
        return DataFileMeta(
            file_name=name,
            file_size=size,
            row_count=chunk.num_rows,
            min_key=b"",
            max_key=b"",
            key_stats=SimpleStats.EMPTY,
            value_stats=value_stats,
            min_sequence_number=first_seq,
            max_sequence_number=first_seq + chunk.num_rows - 1,
            schema_id=self.schema.id,
            level=0,
            file_source=file_source,
            embedded_index=embedded_index,
            extra_files=extra_files + blob_extras,
            external_path=external,
        )


class _AppendBucketWriter:
    """Buffered state for one (partition, bucket) of an append table.

    Same concurrency contract as the pk `_BucketWriter`
    (parallel/write_pipeline.py): sequence ranges are reserved at
    flush-*scheduling* time on the single-threaded caller, the
    encode/upload body runs as a pooled task, and tasks for this bucket
    execute in submission order so `new_files` publishes
    deterministically."""

    def __init__(self, parent: "AppendOnlyFileStoreWrite", partition: Tuple,
                 bucket: int):
        self.parent = parent
        self.partition = partition
        self.bucket = bucket
        self.buffers: List[pa.Table] = []
        self.buffered_bytes = 0
        self.next_seq: Optional[int] = None
        self.new_files: List[DataFileMeta] = []

    def pending_bytes(self) -> int:
        return self.buffered_bytes

    def write(self, table: pa.Table):
        self.buffers.append(table)
        self.buffered_bytes += table.nbytes
        if self.buffered_bytes >= self.parent.options.write_buffer_size:
            self.flush()

    def flush(self):
        if not self.buffers:
            return
        raw = pa.concat_tables(self.buffers, promote_options="none")
        self.buffers = []
        est = self.buffered_bytes
        self.buffered_bytes = 0
        if self.next_seq is None:
            self.next_seq = self.parent.restore_max_seq(
                self.partition, self.bucket) + 1
        # the sequence range is reserved HERE (caller thread), so
        # pipelined flushes can never duplicate or reorder ranges
        first_seq = self.next_seq
        self.next_seq += raw.num_rows

        def task(raw=raw, first_seq=first_seq):
            metas = self.parent.file_writer.write(
                self.partition, self.bucket, raw, first_seq)
            # publish after the upload succeeded (retry-safe: retried
            # attempts pick fresh file names)
            self.new_files.extend(metas)

        self.parent.flush_pool().submit((self.partition, self.bucket),
                                        est, task)

    def take_commit_message(self) -> Optional[CommitMessage]:
        msg = CommitMessage(self.partition, self.bucket,
                            self.parent.total_buckets,
                            new_files=list(self.new_files))
        self.new_files = []
        return None if msg.is_empty() else msg


class AppendOnlyFileStoreWrite:
    """reference operation/AppendFileStoreWrite.java + AppendOnlyWriter:
    inserts only, bucket by bucket-key hash (or single unaware bucket)."""

    def __init__(self, file_io: FileIO, table_path: str,
                 table_schema: TableSchema, options: CoreOptions,
                 restore_max_seq: Optional[Callable[[Tuple, int], int]]
                 = None):
        from paimon_tpu.parallel.write_pipeline import maybe_wrap_staging
        file_io, self._stager = maybe_wrap_staging(file_io, options)
        self.file_io = file_io
        self.schema = table_schema
        self.options = options
        self.partition_keys = table_schema.partition_keys
        self.path_factory = FileStorePathFactory.from_options(
            table_path, self.partition_keys, options)
        self.file_writer = AppendFileWriter(
            file_io, self.path_factory, table_schema,
            file_format=options.file_format,
            compression=options.file_compression,
            target_file_size=options.target_file_size,
            index_spec=options.file_index_spec,
            bloom_fpp=options.get(CoreOptions.FILE_INDEX_BLOOM_FPP),
            index_in_manifest_threshold=options.get(
                CoreOptions.FILE_INDEX_IN_MANIFEST_THRESHOLD),
            format_options=options.format_options)
        self.total_buckets = options.bucket
        self._unaware = options.bucket < 1
        if not self._unaware:
            bucket_keys = table_schema.bucket_keys()
            if not bucket_keys:
                raise ValueError(
                    "append table with bucket >= 1 requires 'bucket-key' "
                    "(reference SchemaValidation)")
            rt = table_schema.logical_row_type()
            self.bucket_assigner = FixedBucketAssigner(
                bucket_keys, [rt.get_field(k).type for k in bucket_keys],
                options.bucket)
        self._writers: Dict[Tuple, _AppendBucketWriter] = {}
        self._flush_pool = None       # lazily built (write_pipeline)
        self._restore_max_seq = restore_max_seq

    def flush_pool(self):
        """The shared bucket-flush executor (parallel/write_pipeline.py);
        write.flush.parallelism=1 degrades it to the inline serial path."""
        if self._flush_pool is None:
            from paimon_tpu.parallel.write_pipeline import FlushPool
            self._flush_pool = FlushPool.from_options(self.options)
        return self._flush_pool

    def restore_max_seq(self, partition: Tuple, bucket: int) -> int:
        if self._restore_max_seq is None:
            return -1
        return self._restore_max_seq(partition, bucket)

    def write_arrow(self, table: pa.Table,
                    row_kinds: Optional[np.ndarray] = None):
        if ROW_KIND_COL in table.column_names:
            row_kinds = np.asarray(table.column(ROW_KIND_COL)
                                   .combine_chunks().cast(pa.int8()))
            table = table.drop_columns([ROW_KIND_COL])
        if row_kinds is not None and \
                (np.asarray(row_kinds, np.int8) != RowKind.INSERT).any():
            raise ValueError("append-only table accepts INSERT rows only "
                             "(reference AppendOnlyWriter)")

        if self._unaware:
            buckets = np.zeros(table.num_rows, dtype=np.int32)
        else:
            buckets = self.bucket_assigner.assign(table)
        from paimon_tpu.parallel.write_pipeline import lpt_order
        groups = group_by_partition_bucket(table, buckets,
                                           self.partition_keys)
        for (part, bucket), idx in lpt_order(groups):
            sub = table.take(pa.array(idx))
            key = (part, bucket)
            if key not in self._writers:
                self._writers[key] = _AppendBucketWriter(self, part, bucket)
            self._writers[key].write(sub)

    def prepare_commit(self) -> List[CommitMessage]:
        # barrier: schedule the final flushes largest-first, drain the
        # pool (first worker error re-raises), then assemble messages
        for w in sorted(self._writers.values(),
                        key=lambda w: -w.pending_bytes()):
            w.flush()
        self.flush_pool().drain()
        out = []
        for w in self._writers.values():
            msg = w.take_commit_message()
            if msg is not None:
                out.append(msg)
        if self._stager is not None:
            # durability barrier: all staged uploads acked before any
            # commit message leaves (see core/write.py)
            self._stager.drain()
        return out

    def close(self):
        if self._flush_pool is not None:
            self._flush_pool.shutdown(wait=True)
            self._flush_pool = None
        if self._stager is not None:
            self._stager.close()
        self._writers.clear()


class AppendSplitRead:
    """No-merge read over append splits (reference RawFileSplitRead used
    by AppendOnlyFileStoreTable)."""

    def __init__(self, file_io: FileIO, table_path: str,
                 schema: TableSchema, options: CoreOptions,
                 schema_manager: Optional[SchemaManager] = None):
        self.file_io = file_io
        self.schema = schema
        self.options = options
        self.schema_manager = schema_manager
        self.path_factory = FileStorePathFactory.from_options(
            table_path, schema.partition_keys, options)
        self._schema_cache: Dict[int, TableSchema] = {schema.id: schema}
        self._projection: Optional[List[str]] = None
        self._predicate: Optional[Predicate] = None
        self._file_index_cache: Dict[str, object] = {}
        self._arrow_types: Optional[Dict[str, object]] = None

    def with_projection(self, columns) -> "AppendSplitRead":
        self._projection = list(columns) if columns else None
        return self

    def with_filter(self, predicate) -> "AppendSplitRead":
        self._predicate = predicate
        return self

    def with_row_ids(self, flag: bool = True) -> "AppendSplitRead":
        """Materialize `_ROW_ID` (file first_row_id + offset) on reads
        of row-tracked tables (reference SpecialFields.ROW_ID)."""
        self._with_row_ids = flag
        return self

    def arrow_type_of(self, column: str):
        for f in self.schema.fields:
            if f.name == column:
                return data_type_to_arrow(f.type)
        raise KeyError(column)

    def read_file(self, split: DataSplit, meta,
                  wanted=None) -> pa.Table:
        """One file, schema-evolved, unfiltered (evolution groups need
        whole ranges so row positions stay aligned); `wanted` pushes
        column projection into the format reader.  Transient store
        faults retry under read.retry.* (parallel/scan_pipeline.py)."""
        from paimon_tpu.core.kv_file import read_kv_file
        from paimon_tpu.parallel.scan_pipeline import read_file_retrying
        t = read_file_retrying(
            lambda: read_kv_file(self.file_io, self.path_factory,
                                 split.partition, split.bucket, meta,
                                 None, None, schema=self.schema,
                                 schema_manager=self.schema_manager,
                                 wanted=set(wanted) if wanted else None,
                                 options=self.options),
            self.options, what=meta.file_name)
        return self._evolve(t, meta.schema_id)

    def _value_columns(self) -> List[str]:
        names = [f.name for f in self.schema.fields]
        if self._projection:
            return [n for n in names if n in set(self._projection)]
        return names

    def _index_selection(self, split: DataSplit, meta, num_rows: int):
        """Superset row mask from the file's bitmap/BSI/range-bitmap
        indexes (reference fileindex/bitmap/BitmapIndexResult.java row
        filtering); None when no index narrows the file.  The exact
        predicate is re-applied after, so supersets are safe."""
        if self._predicate is None:
            return None
        from paimon_tpu.index.file_index import (
            read_indexes_blob, row_selection,
        )
        fi = self._file_index_cache.get(meta.file_name)
        if fi is None:
            fi = read_indexes_blob(meta.embedded_index)
            if not fi:
                for extra in meta.extra_files:
                    if extra.endswith(".index"):
                        path = self.path_factory.data_file_path(
                            split.partition, split.bucket, extra)
                        try:
                            fi = read_indexes_blob(
                                self.file_io.read_bytes(path))
                        except FileNotFoundError:
                            pass
                        break
            self._file_index_cache[meta.file_name] = fi
        if not fi:
            return None
        if self._arrow_types is None:
            self._arrow_types = {}
            for f in self.schema.fields:
                try:
                    self._arrow_types[f.name] = data_type_to_arrow(f.type)
                except ValueError:
                    pass
        return row_selection(fi, self._predicate, num_rows,
                             self._arrow_types)

    def read_split(self, split: DataSplit) -> pa.Table:
        from paimon_tpu.core.kv_file import read_kv_file
        from paimon_tpu.core.read import ROW_KIND_COL as RK
        from paimon_tpu.core.row_tracking import (
            ROW_ID_COL, anchor_of, group_row_ranges, read_evolution_group,
        )
        from paimon_tpu.parallel.scan_pipeline import read_or_skip_corrupt

        wanted = set(self._value_columns())
        want_rid = getattr(self, "_with_row_ids", False)
        groups = group_row_ranges(split.data_files)
        has_evolution = any(len(g) > 1 for g in groups)

        tables = []
        if has_evolution or want_rid:
            # row-range path (reference DataEvolutionSplitRead): each
            # group yields its current rows, columns from newest writers
            cols = list(self._value_columns())
            if want_rid:
                cols.append(ROW_ID_COL)
            for group in sorted(
                    groups,
                    key=lambda g: (anchor_of(g).first_row_id
                                   if anchor_of(g).first_row_id is not None
                                   else -1,
                                   anchor_of(g).min_sequence_number)):
                anchor = anchor_of(group)

                def load(group=group, anchor=anchor):
                    if len(group) == 1 and anchor.first_row_id is None:
                        t = self.read_file(
                            split, anchor,
                            wanted=self._value_columns())
                        t = self._fill_partition_columns(
                            t, set(t.column_names), split.partition) \
                            .select(self._value_columns())
                        if want_rid:
                            t = t.append_column(
                                ROW_ID_COL,
                                pa.nulls(t.num_rows, pa.int64()))
                        return t
                    t = read_evolution_group(self, split, group, cols)
                    return self._fill_partition_columns(
                        t, set(t.column_names), split.partition)

                # corrupt -> skip the WHOLE group (row positions inside
                # a group must stay aligned, partial reads cannot);
                # retry=False: read_file already retries transients
                t = read_or_skip_corrupt(
                    load, self.options,
                    f"evolution group at {anchor.file_name}",
                    retry=False)
                if t is None:
                    continue
                if split.deletion_vectors and \
                        anchor.file_name in split.deletion_vectors and \
                        self.options.get(
                            CoreOptions.DELETION_VECTORS_MERGE_ON_READ):
                    dv = split.deletion_vectors[anchor.file_name]
                    t = t.filter(pa.array(dv.keep_mask(t.num_rows)))
                tables.append(t)
        else:
            for meta in sorted(split.data_files,
                               key=lambda f: f.min_sequence_number):
                t = read_or_skip_corrupt(
                    lambda meta=meta: read_kv_file(
                        self.file_io, self.path_factory,
                        split.partition, split.bucket, meta,
                        None, None, schema=self.schema,
                        schema_manager=self.schema_manager,
                        wanted=wanted, options=self.options),
                    self.options, f"data file {meta.file_name}")
                if t is None:
                    continue
                raw_cols = set(t.column_names)
                t = self._evolve(t, meta.schema_id)
                t = self._fill_partition_columns(t, raw_cols,
                                                 split.partition)
                keep = self._index_selection(split, meta, t.num_rows)
                if split.deletion_vectors and \
                        meta.file_name in split.deletion_vectors and \
                        self.options.get(
                            CoreOptions.DELETION_VECTORS_MERGE_ON_READ):
                    dv = split.deletion_vectors[meta.file_name]
                    dv_keep = np.asarray(dv.keep_mask(t.num_rows))
                    keep = dv_keep if keep is None else (keep & dv_keep)
                if keep is not None:
                    t = t.filter(pa.array(keep))
                tables.append(t)
        out = pa.concat_tables(tables, promote_options="none") if tables \
            else self._empty()
        if self._predicate is not None:
            out = out.filter(self._predicate.to_arrow())
        keep_cols = self._value_columns()
        if want_rid and ROW_ID_COL in out.column_names:
            keep_cols = keep_cols + [ROW_ID_COL]
        out = out.select(keep_cols)
        if split.for_streaming:
            out = out.append_column(
                RK, pa.array(np.zeros(out.num_rows, np.int8), pa.int8()))
        return out

    def iter_splits(self, splits: Sequence[DataSplit], *,
                    ordered: bool = True):
        """(index, split, table) through the bounded prefetch pipeline
        (parallel/scan_pipeline.py)."""
        from paimon_tpu.parallel.scan_pipeline import iter_split_tables
        return iter_split_tables(self, splits, self.options,
                                 ordered=ordered)

    def read_splits(self, splits: Sequence[DataSplit],
                    streaming: Optional[bool] = None) -> pa.Table:
        tables = [t for _, _, t in self.iter_splits(splits)
                  if t.num_rows > 0]
        if not tables:
            from paimon_tpu.core.read import ROW_KIND_COL as RK
            if streaming is None:
                streaming = any(s.for_streaming for s in splits)
            out = self._empty().select(self._value_columns())
            if streaming:
                out = out.append_column(RK, pa.array([], pa.int8()))
            return out
        return pa.concat_tables(tables, promote_options="default")

    def _empty(self) -> pa.Table:
        return pa.table({f.name: pa.array([], data_type_to_arrow(f.type))
                         for f in self.schema.fields})

    def _evolve(self, table: pa.Table, file_schema_id: int) -> pa.Table:
        from paimon_tpu.core.read import evolve_table
        return evolve_table(table, file_schema_id, self.schema,
                            self.schema_manager, self._schema_cache)

    def _fill_partition_columns(self, t: pa.Table, raw_cols: set,
                                partition: Tuple) -> pa.Table:
        """Partition columns ABSENT from the stored file are constants
        derived from the partition path — fill them (reference
        PartitionInfo patching in the data-file readers; this is what
        makes migrated hive files, which never store partition values,
        readable as paimon rows)."""
        pkeys = self.schema.partition_keys
        if not pkeys or not partition:
            return t
        by_name = {f.name: f for f in self.schema.fields}
        for k, v in zip(pkeys, partition):
            if k in raw_cols or k not in by_name:
                continue
            typ = data_type_to_arrow(by_name[k].type)
            const = pa.repeat(pa.scalar(v).cast(typ), t.num_rows)
            if k in t.column_names:
                t = t.set_column(t.column_names.index(k), k, const)
            else:
                t = t.append_column(k, const)
        return t


@dataclass
class AppendCompactResult:
    before: List[DataFileMeta]
    after: List[DataFileMeta]
    changelog: List[DataFileMeta] = dc_field(default_factory=list)
    # DV index rewrites accompanying the data rewrite
    index_entries: List = dc_field(default_factory=list)

    def is_empty(self) -> bool:
        return not self.before


def append_compact_plan(files: List[DataFileMeta], options: CoreOptions,
                        full: bool = False,
                        dvs: Optional[dict] = None
                        ) -> Optional[List[DataFileMeta]]:
    """Pick the files to rewrite (reference
    BucketedAppendCompactManager.pickCompactBefore: contiguous run of
    small files, oldest first, at least compaction.min.file-num, stopping
    once the accumulated size reaches the target).

    'Small' = below target-file-size * compaction.small-file-ratio, so
    outputs that compressed slightly under target are not re-compacted
    forever; files whose deletion vectors exceed
    compaction.delete-ratio-threshold count as compactable regardless
    of size, and are force-picked even alone (reference
    CoreOptions.COMPACTION_DELETE_RATIO_THRESHOLD)."""
    if not files or (len(files) < 2 and not dvs):
        return None
    ordered = sorted(files, key=lambda f: f.min_sequence_number)
    if full:
        return ordered if len(ordered) > 1 or dvs else None
    target = options.target_file_size
    small_limit = target * options.get(
        CoreOptions.COMPACTION_SMALL_FILE_RATIO)
    del_threshold = options.get(
        CoreOptions.COMPACTION_DELETE_RATIO_THRESHOLD)

    def delete_heavy(f: DataFileMeta) -> bool:
        if not dvs or f.file_name not in dvs:
            return False
        return dvs[f.file_name].cardinality() > \
            del_threshold * max(f.row_count, 1)

    min_num = options.get(CoreOptions.COMPACTION_MIN_FILE_NUM)
    picked: List[DataFileMeta] = []
    size = 0
    for f in ordered:
        if f.file_size < small_limit or delete_heavy(f):
            picked.append(f)
            size += f.file_size
            if size >= target and len(picked) >= min_num:
                return picked
        else:
            if len(picked) >= min_num:
                return picked
            picked, size = [], 0
    if len(picked) >= min_num:
        return picked
    # delete-heavy files are force-compacted even below min-file-num:
    # reclaiming dead rows beats file-count heuristics. The pick MUST
    # stay a contiguous slice of the sequence order — rewriting a
    # non-adjacent set would emit a file whose sequence range overlaps
    # the files in between — so take the first maximal run of
    # consecutive delete-heavy files only.
    for i, f in enumerate(ordered):
        if delete_heavy(f):
            j = i + 1
            while j < len(ordered) and delete_heavy(ordered[j]):
                j += 1
            return ordered[i:j]
    return None
