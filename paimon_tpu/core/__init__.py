"""Table-format core (L3): write, commit, scan, read operations.

reference: paimon-core/.../operation/ (AbstractFileStoreWrite,
FileStoreCommitImpl, FileStoreScan, MergeFileSplitRead, RawFileSplitRead).
"""
