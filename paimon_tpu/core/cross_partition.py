"""Cross-partition upsert: primary keys that do NOT contain the
partition keys.

reference: crosspartition/GlobalIndexAssigner.java (RocksDB-backed
key -> (partition, bucket); on partition change routes a -D to the old
partition then the +I to the new one), IndexBootstrap.java (bootstrap
the index from the table), KEY_DYNAMIC bucket mode.

TPU-first shape: the global index bootstraps as ONE projected columnar
scan (pk + partition columns) into a host dict keyed by pk tuples —
IndexBootstrap as a single vectorized read instead of row-at-a-time
RocksDB loads. Batches update the index with a dict pass proportional to
the batch, not the table.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np
import pyarrow as pa

from paimon_tpu.types import RowKind

__all__ = ["CrossPartitionUpsertWrite"]


class CrossPartitionUpsertWrite:
    """Wraps the (dynamic-bucket) KeyValueFileStoreWrite: incoming rows
    whose key already lives in another partition first retract the old
    row (reference ExistingProcessor#DELETE semantics)."""

    def __init__(self, inner, table):
        self.inner = inner
        self.table = table
        self.pk = table.schema.trimmed_primary_keys()
        self.partition_keys = table.schema.partition_keys
        self._index: Optional[Dict[Tuple, Tuple]] = None

    # -- bootstrap (reference IndexBootstrap) --------------------------------

    def _bootstrap(self) -> Dict[Tuple, Tuple]:
        if self._index is not None:
            return self._index
        index: Dict[Tuple, Tuple] = {}
        snapshot = self.table.snapshot_manager.latest_snapshot()
        if snapshot is not None:
            cols = list(dict.fromkeys(self.pk + self.partition_keys))
            data = self.table.to_arrow(projection=cols)
            pk_cols = [data.column(k).to_pylist() for k in self.pk]
            part_cols = [data.column(k).to_pylist()
                         for k in self.partition_keys]
            for i in range(data.num_rows):
                key = tuple(c[i] for c in pk_cols)
                index[key] = tuple(c[i] for c in part_cols)
        self._index = index
        return index

    # -- writes --------------------------------------------------------------

    def write_arrow(self, table: pa.Table,
                    row_kinds: Optional[np.ndarray] = None):
        from paimon_tpu.core.write import ROW_KIND_COL

        if ROW_KIND_COL in table.column_names:
            row_kinds = np.asarray(table.column(ROW_KIND_COL)
                                   .combine_chunks().cast(pa.int8()))
            table = table.drop_columns([ROW_KIND_COL])
        if row_kinds is None:
            row_kinds = np.zeros(table.num_rows, dtype=np.int8)
        row_kinds = np.asarray(row_kinds, dtype=np.int8)

        index = self._bootstrap()
        n = table.num_rows
        pk_cols = [table.column(k).to_pylist() for k in self.pk]
        part_cols = [table.column(k).to_pylist()
                     for k in self.partition_keys]

        drop = np.zeros(n, dtype=bool)   # superseded within this batch
        # key -> (i, part, was_insert)
        batch_last: Dict[Tuple, Tuple[int, Tuple, bool]] = {}
        retracts: Dict[Tuple, Tuple[int, Tuple]] = {}    # key -> (i, old)
        for i in range(n):
            key = tuple(c[i] for c in pk_cols)
            new_part = tuple(c[i] for c in part_cols)
            kind = int(row_kinds[i])
            prev = batch_last.get(key)
            if prev is not None and prev[1] != new_part and prev[2]:
                # an earlier in-batch INSERT moved partitions before any
                # flush: it never materializes. Earlier RETRACTS must
                # still be written — they delete persisted rows.
                drop[prev[0]] = True
            persisted_old = index.get(key)
            if kind in (RowKind.DELETE, RowKind.UPDATE_BEFORE):
                # a retract routes to wherever the key actually lives
                if persisted_old is not None and \
                        persisted_old != new_part and key not in retracts:
                    retracts[key] = (i, persisted_old)
                    drop[i] = True       # rerouted copy replaces it
                index.pop(key, None)
                batch_last[key] = (i, new_part, False)
                continue
            if persisted_old is not None and persisted_old != new_part \
                    and key not in retracts:
                retracts[key] = (i, persisted_old)
            index[key] = new_part
            batch_last[key] = (i, new_part, True)

        if retracts:
            items = list(retracts.values())
            idx = [i for i, _ in items]
            old = table.take(pa.array(idx))
            # rewrite the partition columns to the OLD partition so the
            # delete routes there (keep the original FIELD incl. the
            # non-null flag so buffered batches concat)
            for ci, kname in enumerate(self.partition_keys):
                vals = [p[ci] for _, p in items]
                col = pa.array(vals, old.column(kname).type)
                old = old.set_column(old.column_names.index(kname),
                                     old.schema.field(kname), col)
            self.inner.write_arrow(
                old, np.full(old.num_rows, RowKind.DELETE, np.int8))

        keep = ~drop
        if not keep.all():
            table = table.filter(pa.array(keep))
            row_kinds = row_kinds[keep]
        if table.num_rows:
            self.inner.write_arrow(table, row_kinds)

    def prepare_commit(self):
        return self.inner.prepare_commit()

    def close(self):
        self.inner.close()
