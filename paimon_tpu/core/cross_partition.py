"""Cross-partition upsert: primary keys that do NOT contain the
partition keys.

reference: crosspartition/GlobalIndexAssigner.java (RocksDB-backed
key -> (partition, bucket); on partition change routes a -D to the old
partition then the +I to the new one), IndexBootstrap.java (bootstrap
the index from the table), KEY_DYNAMIC bucket mode.

TPU-first shape: the global index bootstraps as ONE projected columnar
scan (pk + partition columns) into a host dict keyed by pk tuples —
IndexBootstrap as a single vectorized read instead of row-at-a-time
RocksDB loads. Batches update the index with a dict pass proportional to
the batch, not the table.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from paimon_tpu.types import RowKind

__all__ = ["CrossPartitionUpsertWrite"]


class CrossPartitionUpsertWrite:
    """Wraps the (dynamic-bucket) KeyValueFileStoreWrite: incoming rows
    whose key already lives in another partition first retract the old
    row (reference ExistingProcessor#DELETE semantics)."""

    def __init__(self, inner, table):
        self.inner = inner
        self.table = table
        self.pk = table.schema.trimmed_primary_keys()
        self.partition_keys = table.schema.partition_keys
        # two-tier index: a PERSISTENT sorted base (SST spilled next to
        # the table, shared across writers at the same snapshot —
        # reference GlobalIndexAssigner's RocksDB) plus an in-RAM
        # overlay of this writer's own changes (None = deleted)
        self._overlay: Dict[Tuple, Optional[Tuple]] = {}
        self._reader = None
        self._encoder = None
        self._dict_index: Optional[Dict[Tuple, Tuple]] = None
        self._bootstrapped = False

    # -- bootstrap (reference IndexBootstrap) --------------------------------

    def _index_dir(self) -> str:
        return f"{self.table.path}/index/cross-partition"

    def _bootstrap_store(self):
        """Build or load the persistent base index for the latest
        snapshot.  Non-local FileIO (e.g. memory://) falls back to the
        in-RAM dict bootstrap."""
        if self._bootstrapped:
            return
        self._bootstrapped = True
        import os

        from paimon_tpu.fs import LocalFileIO
        from paimon_tpu.lookup.sst import SstReader, SstWriter, pack_lanes
        from paimon_tpu.ops.normkey import NormalizedKeyEncoder
        from paimon_tpu.types import data_type_to_arrow

        snapshot = self.table.snapshot_manager.latest_snapshot()
        rt = self.table.schema.logical_row_type()
        self._encoder = NormalizedKeyEncoder(
            [data_type_to_arrow(rt.get_field(k).type) for k in self.pk],
            nullable=[rt.get_field(k).type.nullable for k in self.pk])
        if snapshot is None:
            return
        if not isinstance(self.table.file_io, LocalFileIO):
            self._dict_index = self._scan_index()
            return
        path = os.path.join(self._index_dir(),
                            f"snapshot-{snapshot.id}.sst")
        if os.path.exists(path):
            self._reader = SstReader(path)
            return
        cols = list(dict.fromkeys(self.pk + self.partition_keys))
        data = self.table.to_arrow(projection=cols)
        lanes, _ = self._encoder.encode_table(data, self.pk)
        order = np.argsort(pack_lanes(lanes), kind="stable")
        os.makedirs(self._index_dir(), exist_ok=True)
        tmp = path + ".tmp"
        SstWriter().write(tmp, lanes[order],
                          data.take(pa.array(order)))
        try:
            os.rename(tmp, path)         # atomic publish; racers agree
        except OSError:
            pass
        self._reader = SstReader(path)
        # trim spilled indexes well behind the head; a trailing window
        # stays so concurrent writers still probing a recent snapshot's
        # file never lose it mid-write
        from paimon_tpu.lookup.sst import _GLOBAL_BLOCK_CACHE
        for name in os.listdir(self._index_dir()):
            if not name.endswith(".sst"):
                continue
            try:
                sid = int(name[len("snapshot-"):-len(".sst")])
            except ValueError:
                continue
            if sid < snapshot.id - 5:
                stale = os.path.join(self._index_dir(), name)
                _GLOBAL_BLOCK_CACHE.drop_file(stale)
                try:
                    os.remove(stale)
                except OSError:
                    pass

    def _scan_index(self) -> Dict[Tuple, Tuple]:
        index: Dict[Tuple, Tuple] = {}
        cols = list(dict.fromkeys(self.pk + self.partition_keys))
        data = self.table.to_arrow(projection=cols)
        pk_cols = [data.column(k).to_pylist() for k in self.pk]
        part_cols = [data.column(k).to_pylist()
                     for k in self.partition_keys]
        for i in range(data.num_rows):
            index[tuple(c[i] for c in pk_cols)] = \
                tuple(c[i] for c in part_cols)
        return index

    def _probe_batch(self, table: pa.Table,
                     pk_cols) -> Dict[Tuple, Optional[Tuple]]:
        """Current partition of every key in the batch: overlay first,
        then ONE vectorized SST probe for the rest."""
        self._bootstrap_store()
        n = table.num_rows
        keys = [tuple(c[i] for c in pk_cols) for i in range(n)]
        view: Dict[Tuple, Optional[Tuple]] = {}
        need: List[int] = []
        for i, k in enumerate(keys):
            if k in view:
                continue
            if k in self._overlay:
                view[k] = self._overlay[k]
            elif self._dict_index is not None:
                view[k] = self._dict_index.get(k)
            else:
                view[k] = None
                need.append(i)
        if need and self._reader is not None:
            sub = table.take(pa.array(need)).select(self.pk)
            lanes, _ = self._encoder.encode_table(sub, self.pk)
            try:
                hit_pos, rows = self._reader.probe(lanes)
            except FileNotFoundError:
                # a newer writer trimmed our snapshot's spilled index
                # from behind the safety window: re-bootstrap at the
                # current snapshot and retry once
                self._bootstrapped = False
                self._reader = None
                self._bootstrap_store()
                if self._reader is None:
                    return view
                hit_pos, rows = self._reader.probe(lanes)
            if rows is not None:
                row_dicts = rows.to_pylist()
                for pos, row in zip(hit_pos, row_dicts):
                    k = keys[need[int(pos)]]
                    if tuple(row[c] for c in self.pk) == k:
                        view[k] = tuple(row[c]
                                        for c in self.partition_keys)
        return view

    # -- writes --------------------------------------------------------------

    def write_arrow(self, table: pa.Table,
                    row_kinds: Optional[np.ndarray] = None):
        from paimon_tpu.core.write import ROW_KIND_COL

        if ROW_KIND_COL in table.column_names:
            row_kinds = np.asarray(table.column(ROW_KIND_COL)
                                   .combine_chunks().cast(pa.int8()))
            table = table.drop_columns([ROW_KIND_COL])
        if row_kinds is None:
            row_kinds = np.zeros(table.num_rows, dtype=np.int8)
        row_kinds = np.asarray(row_kinds, dtype=np.int8)

        n = table.num_rows
        pk_cols = [table.column(k).to_pylist() for k in self.pk]
        part_cols = [table.column(k).to_pylist()
                     for k in self.partition_keys]
        index = self._probe_batch(table, pk_cols)
        overlay = self._overlay

        drop = np.zeros(n, dtype=bool)   # superseded within this batch
        # key -> (i, part, was_insert)
        batch_last: Dict[Tuple, Tuple[int, Tuple, bool]] = {}
        retracts: Dict[Tuple, Tuple[int, Tuple]] = {}    # key -> (i, old)
        for i in range(n):
            key = tuple(c[i] for c in pk_cols)
            new_part = tuple(c[i] for c in part_cols)
            kind = int(row_kinds[i])
            prev = batch_last.get(key)
            if prev is not None and prev[1] != new_part and prev[2]:
                # an earlier in-batch INSERT moved partitions before any
                # flush: it never materializes. Earlier RETRACTS must
                # still be written — they delete persisted rows.
                drop[prev[0]] = True
            persisted_old = index.get(key)
            if kind in (RowKind.DELETE, RowKind.UPDATE_BEFORE):
                # a retract routes to wherever the key actually lives
                if persisted_old is not None and \
                        persisted_old != new_part and key not in retracts:
                    retracts[key] = (i, persisted_old)
                    drop[i] = True       # rerouted copy replaces it
                index[key] = None
                overlay[key] = None
                batch_last[key] = (i, new_part, False)
                continue
            if persisted_old is not None and persisted_old != new_part \
                    and key not in retracts:
                retracts[key] = (i, persisted_old)
            index[key] = new_part
            overlay[key] = new_part
            batch_last[key] = (i, new_part, True)

        if retracts:
            items = list(retracts.values())
            idx = [i for i, _ in items]
            old = table.take(pa.array(idx))
            # rewrite the partition columns to the OLD partition so the
            # delete routes there (keep the original FIELD incl. the
            # non-null flag so buffered batches concat)
            for ci, kname in enumerate(self.partition_keys):
                vals = [p[ci] for _, p in items]
                col = pa.array(vals, old.column(kname).type)
                old = old.set_column(old.column_names.index(kname),
                                     old.schema.field(kname), col)
            self.inner.write_arrow(
                old, np.full(old.num_rows, RowKind.DELETE, np.int8))

        keep = ~drop
        if not keep.all():
            table = table.filter(pa.array(keep))
            row_kinds = row_kinds[keep]
        if table.num_rows:
            self.inner.write_arrow(table, row_kinds)

    def prepare_commit(self):
        return self.inner.prepare_commit()

    def close(self):
        self.inner.close()
