"""Command-line interface: `python -m paimon_tpu ...`.

The reference ships a `paimon` CLI over the Python catalog
(pypaimon/cli/cli.py: table/db/catalog/sql/branch/tag subcommands with
a yaml catalog config).  This is the same surface over paimon_tpu:

  paimon --warehouse /wh db list|create|drop
  paimon --warehouse /wh table list|get|read|snapshot|create|drop|
                           compact|import|rename|set-option|add-column
  paimon --warehouse /wh tag list|create|delete <db.table> [...]
  paimon --warehouse /wh branch list|create|delete|fast-forward ...
  paimon --warehouse /wh sql "SELECT ..." | sql   (interactive REPL)

Catalog selection: --warehouse PATH (filesystem), or --config FILE — a
JSON file of catalog options ({"warehouse": ..., "metastore": ...}),
or the PAIMON_WAREHOUSE environment variable.
"""

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

import pyarrow as pa


def _load_catalog(args):
    from paimon_tpu.catalog.catalog import create_catalog
    opts = {}
    if getattr(args, "config", None):
        with open(args.config) as f:
            opts.update(json.load(f))
    if getattr(args, "warehouse", None):
        opts["warehouse"] = args.warehouse
    if not opts.get("warehouse") and os.environ.get("PAIMON_WAREHOUSE"):
        opts["warehouse"] = os.environ["PAIMON_WAREHOUSE"]
    if not opts:
        raise SystemExit("no catalog configured: pass --warehouse, "
                         "--config, or set PAIMON_WAREHOUSE")
    return create_catalog(opts)


def _print_table(t: pa.Table, fmt: str, out=None):
    out = out or sys.stdout
    if fmt == "json":
        for row in t.to_pylist():
            out.write(json.dumps(row, default=str) + "\n")
        return
    if fmt == "csv":
        import pyarrow.csv as pacsv
        buf = pa.BufferOutputStream()
        pacsv.write_csv(t, buf)
        out.write(buf.getvalue().to_pybytes().decode())
        return
    # plain aligned text table
    cols = t.column_names
    rows = [[("" if v is None else str(v)) for v in row.values()]
            for row in t.to_pylist()]
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(cols)]
    line = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    out.write(line + "\n")
    out.write("|" + "|".join(f" {c.ljust(w)} "
                             for c, w in zip(cols, widths)) + "|\n")
    out.write(line + "\n")
    for r in rows:
        out.write("|" + "|".join(f" {v.ljust(w)} "
                                 for v, w in zip(r, widths)) + "|\n")
    out.write(line + "\n")
    out.write(f"{t.num_rows} row(s)\n")


def _table(catalog, name: str):
    from paimon_tpu.catalog.catalog import Identifier
    if "." not in name:
        raise SystemExit(f"table must be db.table, got {name!r}")
    return catalog.get_table(Identifier.parse(name))


# -- subcommand handlers ----------------------------------------------------

def cmd_db(args):
    catalog = _load_catalog(args)
    if args.db_cmd == "list":
        for d in sorted(catalog.list_databases()):
            print(d)
    elif args.db_cmd == "create":
        catalog.create_database(args.name, ignore_if_exists=args.if_not_exists)
        print("OK")
    elif args.db_cmd == "drop":
        catalog.drop_database(args.name, ignore_if_not_exists=True,
                              cascade=getattr(args, "cascade", False))
        print("OK")


class _TraceScope:
    """`--trace out.json`: enable span tracing for the command and
    export the ring as Chrome trace-event JSON (Perfetto) on the way
    out — the CLI's one-shot equivalent of trace.export.path."""

    def __init__(self, path: Optional[str]):
        self.path = path

    def __enter__(self):
        if self.path:
            from paimon_tpu.obs import enable_tracing
            enable_tracing()
        return self

    def __exit__(self, *exc):
        if self.path:
            from paimon_tpu.obs import disable_tracing, export_chrome_trace
            export_chrome_trace(self.path)
            disable_tracing()
            print(f"trace written to {self.path}", file=sys.stderr)
        return False


def cmd_table(args):
    catalog = _load_catalog(args)
    cmd = args.table_cmd
    if cmd == "list":
        for t in sorted(catalog.list_tables(args.database)):
            print(t)
        return
    table = None
    if cmd == "get":
        table = _table(catalog, args.table)
        schema = table.schema
        info = {
            "name": args.table,
            "fields": [{"name": f.name, "type": str(f.type),
                        "comment": getattr(f, "description", None)}
                       for f in schema.fields],
            "primary_keys": schema.primary_keys,
            "partition_keys": schema.partition_keys,
            "options": schema.options,
        }
        print(json.dumps(info, indent=2, default=str))
    elif cmd == "read":
        table = _table(catalog, args.table)
        from paimon_tpu import predicate as P  # noqa: F401
        projection = args.columns.split(",") if args.columns else None
        with _TraceScope(getattr(args, "trace", None)):
            out = table.to_arrow(projection=projection)
        if args.limit:
            out = out.slice(0, args.limit)
        _print_table(out, args.format)
    elif cmd == "snapshot":
        table = _table(catalog, args.table)
        snap = table.latest_snapshot()
        if snap is None:
            print("no snapshots")
        else:
            print(snap.to_json())
    elif cmd == "snapshots":
        table = _table(catalog, args.table)
        _print_table(table.system_table("snapshots"), args.format)
    elif cmd == "create":
        from paimon_tpu.catalog.catalog import Identifier
        from paimon_tpu.schema import Schema
        from paimon_tpu.types import parse_data_type
        b = Schema.builder()
        for coldef in args.column:
            name, _, typ = coldef.partition(":")
            b.column(name, parse_data_type(typ or "STRING"))
        if args.primary_key:
            b.primary_key(*args.primary_key.split(","))
        if args.partition_by:
            b.partition_keys(*args.partition_by.split(","))
        for opt in args.option or []:
            k, _, v = opt.partition("=")
            b.option(k, v)
        catalog.create_table(Identifier.parse(args.table), b.build(),
                             ignore_if_exists=args.if_not_exists)
        print("OK")
    elif cmd == "drop":
        from paimon_tpu.catalog.catalog import Identifier
        catalog.drop_table(Identifier.parse(args.table),
                           ignore_if_not_exists=True)
        print("OK")
    elif cmd == "rename":
        from paimon_tpu.catalog.catalog import Identifier
        catalog.rename_table(Identifier.parse(args.table),
                             Identifier.parse(args.to))
        print("OK")
    elif cmd == "compact":
        table = _table(catalog, args.table)
        with _TraceScope(getattr(args, "trace", None)):
            sid = table.compact(full=args.full)
        print(f"snapshot {sid}" if sid else "nothing to do")
    elif cmd == "compact-manifests":
        table = _table(catalog, args.table)
        sid = table.compact_manifests(force=not args.if_needed)
        print(f"snapshot {sid}" if sid else "nothing to do")
    elif cmd == "import":
        table = _table(catalog, args.table)
        path = args.file
        if path.endswith(".csv"):
            import pyarrow.csv as pacsv
            data = pacsv.read_csv(path)
        elif path.endswith(".json") or path.endswith(".jsonl"):
            import pyarrow.json as pajson
            data = pajson.read_json(path)
        elif path.endswith(".parquet"):
            import pyarrow.parquet as pq
            data = pq.read_table(path)
        else:
            raise SystemExit(f"unsupported import format: {path}")
        schema = table.arrow_schema()
        data = data.select([c for c in data.column_names
                            if c in schema.names]).cast(
            pa.schema([schema.field(c) for c in data.column_names
                       if c in schema.names]))
        wb = table.new_batch_write_builder()
        with _TraceScope(getattr(args, "trace", None)), \
                wb.new_write() as w:
            w.write_arrow(data)
            wb.new_commit().commit(w.prepare_commit())
        print(f"{data.num_rows} rows imported")
    elif cmd == "set-option":
        from paimon_tpu.catalog.catalog import Identifier
        from paimon_tpu.schema.schema_manager import SchemaChange
        catalog.alter_table(Identifier.parse(args.table),
                            [SchemaChange.set_option(args.key, args.value)])
        print("OK")
    elif cmd == "remove-option":
        from paimon_tpu.catalog.catalog import Identifier
        from paimon_tpu.schema.schema_manager import SchemaChange
        catalog.alter_table(Identifier.parse(args.table),
                            [SchemaChange.remove_option(args.key)])
        print("OK")
    elif cmd == "add-column":
        from paimon_tpu.catalog.catalog import Identifier
        from paimon_tpu.schema.schema_manager import SchemaChange
        from paimon_tpu.types import parse_data_type
        catalog.alter_table(
            Identifier.parse(args.table),
            [SchemaChange.add_column(args.name,
                                     parse_data_type(args.type))])
        print("OK")
    elif cmd == "expire-snapshots":
        table = _table(catalog, args.table)
        n = table.expire_snapshots(retain_max=args.retain_max)
        print(f"{n or 0} snapshots expired")
    elif cmd == "metrics":
        table = _table(catalog, args.table)
        out = table.system_table("metrics")
        if args.group:
            import pyarrow.compute as pc
            out = out.filter(pc.equal(out.column("group"), args.group))
        _print_table(out, args.format)
    elif cmd == "stream":
        table = _table(catalog, args.table)
        from paimon_tpu.cdc.source import FileCdcSource
        from paimon_tpu.service.stream_daemon import StreamDaemon
        dynamic = {}
        for opt in args.option or []:
            k, _, v = opt.partition("=")
            dynamic[k] = v
        source = FileCdcSource(args.source)
        daemon = StreamDaemon(
            table, source, format=args.cdc_format,
            commit_user=args.commit_user,
            compact=not args.no_compact, serve=not args.no_serve,
            dynamic_options=dynamic or None)
        server = None
        with _TraceScope(getattr(args, "trace", None)):
            daemon.install_signal_handlers()
            daemon.start()
            if not args.no_serve:
                # the CLI has no in-process consumer; drain the bounded
                # changelog buffer (keeping the serve loop + freshness
                # measurement live) — remote consumers use /changelog,
                # which runs its own resumable per-consumer scans
                from paimon_tpu.parallel.executors import spawn_thread

                def _drain_buffer():
                    while daemon.poll_changelog(timeout=1.0) or \
                            daemon._serve_alive():
                        pass

                spawn_thread(_drain_buffer,
                             name="paimon-stream-cli-drain")
            if args.serve_port is not None:
                from paimon_tpu.service.query_service import (
                    KvQueryServer,
                )
                server = KvQueryServer(table,
                                       port=args.serve_port).start()
                print(f"query service (with /changelog) at "
                      f"{server.address}", file=sys.stderr)
            try:
                status = daemon.run_forever(args.duration)
            finally:
                if server is not None:
                    server.stop()
        print(json.dumps(status, indent=2, default=str))
        if any(lp["failed"] for lp in status["loops"].values()):
            raise SystemExit(1)
    elif cmd == "debug-bundle":
        table = _table(catalog, args.table)
        out_path = args.out or "debug-bundle.json"
        bundle = build_debug_bundle(table, serving=args.serving,
                                    base_user=args.base_user,
                                    lease_walk=args.lease_walk)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, indent=2, default=str)
        os.replace(tmp, out_path)
        print(json.dumps({
            "out": out_path,
            "flight_events": len(bundle["flight"]["events"]),
            "metric_rows": len(bundle["metrics"]),
            "distributed": bundle["fleet"].get("distributed", False),
            "serving": bundle["healthz"] is not None,
        }, indent=2))
    elif cmd == "fsck":
        table = _table(catalog, args.table)
        report = table.fsck(snapshot_id=args.snapshot, deep=args.deep,
                            incremental=args.incremental,
                            stamp_watermark=args.stamp_watermark)
        if args.fix and not report.ok:
            from paimon_tpu.maintenance import fix_violations
            actions = fix_violations(table, report)
            report = table.fsck(snapshot_id=args.snapshot,
                                deep=args.deep,
                                incremental=args.incremental,
                                stamp_watermark=args.stamp_watermark)
            out = report.to_dict()
            out["fix_actions"] = actions
        else:
            out = report.to_dict()
        print(json.dumps(out, indent=2, default=str))
        if not report.ok:
            raise SystemExit(1)


def cmd_tag(args):
    catalog = _load_catalog(args)
    table = _table(catalog, args.table)
    if args.tag_cmd == "list":
        _print_table(table.system_table("tags"), args.format)
    elif args.tag_cmd == "create":
        table.create_tag(args.name, args.snapshot)
        print("OK")
    elif args.tag_cmd == "delete":
        table.delete_tag(args.name)
        print("OK")


def cmd_branch(args):
    catalog = _load_catalog(args)
    table = _table(catalog, args.table)
    if args.branch_cmd == "list":
        _print_table(table.system_table("branches"), args.format)
    elif args.branch_cmd == "create":
        table.create_branch(args.name, args.tag)
        print("OK")
    elif args.branch_cmd == "delete":
        table.delete_branch(args.name)
        print("OK")
    elif args.branch_cmd == "fast-forward":
        table.fast_forward(args.name)
        print("OK")


def _fleet_view(table, base_user: str, lease_walk: int) -> Dict:
    """Fleet-plane introspection, read purely from snapshot
    properties through the sanctioned history API
    (parallel/distributed.py — the `ownership-history` lint rule
    forbids raw `multihost.ownership.*` parsing here too)."""
    import time as _time

    from paimon_tpu.parallel.distributed import (
        merge_lease_view, merge_rejoin_requests,
        resume_generation_history,
    )
    from paimon_tpu.service.stream_daemon import recover_plane_stamps

    hist = resume_generation_history(table)
    if hist is None:
        return {"distributed": False}
    current = hist.current()
    now = int(_time.time() * 1000)
    leases = merge_lease_view(table, max_walk=lease_walk)
    requests = merge_rejoin_requests(table)
    hosts = {}
    for p in range(current.num_processes):
        ledger, floors = recover_plane_stamps(
            table, f"{base_user}-p{p}")
        # bucket shares for the default partition — partitioned
        # tables shard per (partition, bucket), so per-partition
        # ownership can differ; this is the representative view
        owned = [b for b in range(current.num_buckets)
                 if current.owner_of((), b) == p]
        lease_ms = leases.get(p)
        hosts[str(p)] = {
            "dead": p in current.dead,
            "rejoin_requested": p in requests,
            "lease_age_ms": None if lease_ms is None
            else max(0, now - lease_ms),
            "adopted": sorted(ledger),
            "floors": {str(k): v for k, v in sorted(floors.items())},
            "owned_buckets": owned,
        }
    out = {
        "distributed": True,
        "version": current.version,
        "processes": current.num_processes,
        "buckets": current.num_buckets,
        "dead": sorted(current.dead),
        "rejoining": sorted(p for p in requests if p in current.dead),
        "hosts": hosts,
        "generations": [
            {"version": m.version, "processes": m.num_processes,
             "buckets": m.num_buckets, "dead": sorted(m.dead)}
            for m in hist.entries],
    }
    return out


def _options_diff(table) -> Dict:
    """Explicitly-set table options vs their registered defaults —
    the 'what is different about THIS table' half of a debug bundle."""
    from paimon_tpu.options import ConfigOption, CoreOptions

    defaults = {v.key: v.default for v in vars(CoreOptions).values()
                if isinstance(v, ConfigOption)}
    diff = {}
    for k, v in sorted(table.options.to_map().items()):
        d = defaults.get(k)
        if d is not None and str(d) == v:
            continue                       # explicitly set to default
        diff[k] = {"value": v,
                   "default": None if d is None else str(d),
                   "known": k in defaults}
    return diff


def build_debug_bundle(table, serving: Optional[str] = None,
                       base_user: str = "stream-daemon",
                       lease_walk: int = 16) -> Dict:
    """One support artifact with everything a post-mortem starts
    from: the black-box flight ring, a full metrics snapshot, the
    serving plane's /healthz + /slo (best-effort — the server may be
    the thing that died), the store-derived fleet view, and the
    table's options diff vs defaults."""
    import time as _time

    from paimon_tpu.metrics import global_registry
    from paimon_tpu.obs import flight
    from paimon_tpu.obs.trace import process_tag

    bundle: Dict = {
        "created_ms": int(_time.time() * 1000),
        "table": table.name,
        "process": process_tag(),
        "flight": {"events": flight.recorder().snapshot()},
        "metrics": global_registry().snapshot_rows(),
        "healthz": None,
        "slo": None,
        "fleet": {},
        "options": _options_diff(table),
    }
    try:
        from paimon_tpu.service.query_service import KvQueryClient
        client = KvQueryClient(
            table=None if serving else table, address=serving,
            follow_topology=False)
        bundle["healthz"] = client.healthz()
        bundle["slo"] = client.slo()
    except Exception as e:                 # noqa: BLE001 — diagnostic
        bundle["serving_error"] = f"{type(e).__name__}: {e}"
    try:
        bundle["fleet"] = _fleet_view(table, base_user, lease_walk)
    except Exception as e:                 # noqa: BLE001 — diagnostic
        bundle["fleet"] = {"error": f"{type(e).__name__}: {e}"}
    return bundle


def cmd_fleet(args):
    if args.fleet_cmd == "trace":
        from paimon_tpu.obs.merge import export_merged
        stats = export_merged(args.merge, args.out)
        print(json.dumps(stats, indent=2))
        if stats["processes"] == 0:
            raise SystemExit(1)
        return
    catalog = _load_catalog(args)
    table = _table(catalog, args.table)
    out = _fleet_view(table, args.base_user, args.lease_walk)
    if getattr(args, "serving", None):
        # the store plane above is read from snapshots; the serving
        # plane (SLO burn rates) lives behind HTTP — best-effort so a
        # downed router never hides the store-side view
        try:
            from paimon_tpu.service.query_service import KvQueryClient
            out["slo"] = KvQueryClient(address=args.serving,
                                       follow_topology=False).slo()
        except Exception as e:             # noqa: BLE001 — diagnostic
            out["slo"] = {"error": f"{type(e).__name__}: {e}"}
    print(json.dumps(out, indent=2))


def cmd_sql(args):
    from paimon_tpu.sql import SQLContext
    catalog = _load_catalog(args)
    ctx = SQLContext(catalog, database=args.database)
    if args.query:
        out = ctx.sql(args.query)
        _print_table(out, args.format)
        return
    # interactive REPL (reference cli_sql.py _interactive_repl)
    print("paimon sql — ';' terminates a statement, exit/quit leaves")
    buf: List[str] = []
    while True:
        try:
            prompt = "paimon> " if not buf else "   ...> "
            line = input(prompt)
        except EOFError:
            break
        if not buf and line.strip().lower() in ("exit", "quit", "\\q"):
            break
        buf.append(line)
        if line.rstrip().endswith(";"):
            query = "\n".join(buf).rstrip().rstrip(";")
            buf = []
            if not query.strip():
                continue
            try:
                _print_table(ctx.sql(query), args.format)
            except Exception as e:                 # noqa: BLE001
                print(f"error: {e}", file=sys.stderr)


def cmd_lint(args):
    """Whole-program static analysis: the same engine pass tier-1
    runs (paimon_tpu/analysis/), for humans and external CI.  Exit 1
    when any unsuppressed finding exists."""
    import os

    from paimon_tpu.analysis import all_rules, run_package

    if args.list_rules:
        for r in all_rules():
            print(f"{r.id:22s} {r.title}")
        return
    if args.rules:
        from paimon_tpu.analysis import META_RULES
        known = {r.id for r in all_rules()} | set(META_RULES)
        unknown = sorted(set(args.rules) - known)
        if unknown:
            raise SystemExit(
                f"unknown rule id(s): {', '.join(unknown)} "
                f"(see `paimon lint --list-rules`)")
    package_dir = args.package_dir or os.path.dirname(
        os.path.abspath(__file__))
    report = run_package(package_dir,
                         rule_ids=args.rules if args.rules else None)
    if args.json:
        print(report.to_json())
    else:
        for f in report.findings:
            if f.suppressed and not args.show_suppressed:
                continue
            tag = " [suppressed]" if f.suppressed else ""
            print(f"{f.file}:{f.line}: [{f.rule}]{tag} {f.message}")
        s = report.to_dict()["summary"]
        print(f"{len(report.model.modules)} files, "
              f"{len(report.rules)} rules: "
              f"{s['unsuppressed']} finding(s), "
              f"{s['suppressed']} suppressed")
    if report.unsuppressed:
        raise SystemExit(1)


# -- parser -----------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="paimon", description="paimon_tpu command line interface")
    p.add_argument("--warehouse", "-w", help="filesystem warehouse path")
    p.add_argument("--config", "-c", help="JSON file of catalog options")
    p.add_argument("--format", "-f", default="table",
                   choices=["table", "csv", "json"], help="output format")
    sub = p.add_subparsers(dest="command")

    db = sub.add_parser("db", help="database operations")
    dbsub = db.add_subparsers(dest="db_cmd", required=True)
    dbsub.add_parser("list")
    c = dbsub.add_parser("create")
    c.add_argument("name")
    c.add_argument("--if-not-exists", action="store_true")
    c = dbsub.add_parser("drop")
    c.add_argument("name")
    c.add_argument("--cascade", action="store_true")
    db.set_defaults(func=cmd_db)

    t = sub.add_parser("table", help="table operations")
    tsub = t.add_subparsers(dest="table_cmd", required=True)
    c = tsub.add_parser("list")
    c.add_argument("database")
    c = tsub.add_parser("get")
    c.add_argument("table")
    c = tsub.add_parser("read")
    c.add_argument("table")
    c.add_argument("--columns", help="comma-separated projection")
    c.add_argument("--limit", type=int)
    c.add_argument("--trace", metavar="OUT.json",
                   help="trace the scan; write Chrome trace-event "
                        "JSON (opens in Perfetto)")
    c = tsub.add_parser("snapshot")
    c.add_argument("table")
    c = tsub.add_parser("snapshots")
    c.add_argument("table")
    c = tsub.add_parser("create")
    c.add_argument("table")
    c.add_argument("--column", action="append", default=[],
                   metavar="NAME:TYPE", help="repeatable column def")
    c.add_argument("--primary-key")
    c.add_argument("--partition-by")
    c.add_argument("--option", action="append", metavar="K=V")
    c.add_argument("--if-not-exists", action="store_true")
    c = tsub.add_parser("drop")
    c.add_argument("table")
    c = tsub.add_parser("rename")
    c.add_argument("table")
    c.add_argument("to")
    c = tsub.add_parser("compact")
    c.add_argument("table")
    c.add_argument("--full", action="store_true")
    c.add_argument("--trace", metavar="OUT.json",
                   help="trace the compaction; write Chrome "
                        "trace-event JSON (opens in Perfetto)")
    c = tsub.add_parser(
        "compact-manifests",
        help="fold accumulated delta manifests into sorted, "
             "partition-clustered base manifests")
    c.add_argument("table")
    c.add_argument("--if-needed", action="store_true",
                   help="run only when the manifest.full-compaction."
                        "threshold trigger fires")
    c = tsub.add_parser("import")
    c.add_argument("table")
    c.add_argument("file", help="csv/json/parquet file")
    c.add_argument("--trace", metavar="OUT.json",
                   help="trace the ingest; write Chrome trace-event "
                        "JSON (opens in Perfetto)")
    c = tsub.add_parser(
        "metrics", help="live process metric registry ($metrics)")
    c.add_argument("table")
    c.add_argument("--group",
                   help="filter to one metric group "
                        "(scan/write/compaction/commit/io/...)")
    c = tsub.add_parser("set-option")
    c.add_argument("table")
    c.add_argument("key")
    c.add_argument("value")
    c = tsub.add_parser("remove-option")
    c.add_argument("table")
    c.add_argument("key")
    c = tsub.add_parser("add-column")
    c.add_argument("table")
    c.add_argument("name")
    c.add_argument("type")
    c = tsub.add_parser("expire-snapshots")
    c.add_argument("table")
    c.add_argument("--retain-max", type=int)
    c = tsub.add_parser(
        "stream",
        help="run the streaming daemon: checkpointed exactly-once CDC "
             "ingest + triggered compaction + changelog serving")
    c.add_argument("table")
    c.add_argument("--source", required=True,
                   help="JSONL file of CDC envelopes (tailed; offset = "
                        "line number, checkpointed in snapshot "
                        "properties)")
    c.add_argument("--cdc-format", default="debezium",
                   help="debezium/canal/maxwell/ogg/dms/aliyun")
    c.add_argument("--commit-user", default="stream-daemon",
                   help="STABLE id keying exactly-once replay dedup "
                        "and offset recovery")
    c.add_argument("--duration", type=float,
                   help="seconds to run (default: until SIGTERM)")
    c.add_argument("--serve-port", type=int,
                   help="also start the query service (adds the "
                        "/changelog endpoint) on this port")
    c.add_argument("--no-compact", action="store_true",
                   help="disable the compaction loop")
    c.add_argument("--no-serve", action="store_true",
                   help="disable the changelog-serving loop")
    c.add_argument("--option", action="append", metavar="K=V",
                   help="dynamic table options (stream.*, write.*, ...)")
    c.add_argument("--trace", metavar="OUT.json",
                   help="trace the daemon; write Chrome trace-event "
                        "JSON (opens in Perfetto)")
    c = tsub.add_parser(
        "debug-bundle",
        help="write one support artifact: flight-recorder ring, "
             "metrics snapshot, /healthz + /slo (best-effort), fleet "
             "status, and the table's options diff vs defaults")
    c.add_argument("table")
    c.add_argument("--out", metavar="OUT.json",
                   help="bundle path (default: debug-bundle.json)")
    c.add_argument("--serving", metavar="HOST:PORT",
                   help="query service / router to probe for "
                        "/healthz + /slo (default: the table's "
                        "registered service address)")
    c.add_argument("--base-user", default="stream-daemon")
    c.add_argument("--lease-walk", type=int, default=16)
    c = tsub.add_parser(
        "fsck", help="verify the snapshot/manifest/file graph")
    c.add_argument("table")
    c.add_argument("--snapshot", type=int,
                   help="check one snapshot only")
    c.add_argument("--deep", action="store_true",
                   help="also read data files and verify stats")
    c.add_argument("--fix", action="store_true",
                   help="repair fixable violations "
                        "(maintenance/repair.py), then re-check")
    c.add_argument("--incremental", action="store_true",
                   help="verify only the delta since the last clean "
                        "sweep's watermark (silently runs full when "
                        "it is absent or invalidated)")
    c.add_argument("--stamp-watermark", action="store_true",
                   help="record a clean full-chain verification at "
                        "the tip, arming the next incremental run")
    t.set_defaults(func=cmd_table)

    tg = sub.add_parser("tag", help="tag operations")
    tgsub = tg.add_subparsers(dest="tag_cmd", required=True)
    c = tgsub.add_parser("list")
    c.add_argument("table")
    c = tgsub.add_parser("create")
    c.add_argument("table")
    c.add_argument("name")
    c.add_argument("--snapshot", type=int)
    c = tgsub.add_parser("delete")
    c.add_argument("table")
    c.add_argument("name")
    tg.set_defaults(func=cmd_tag)

    br = sub.add_parser("branch", help="branch operations")
    brsub = br.add_subparsers(dest="branch_cmd", required=True)
    c = brsub.add_parser("list")
    c.add_argument("table")
    c = brsub.add_parser("create")
    c.add_argument("table")
    c.add_argument("name")
    c.add_argument("--tag")
    c = brsub.add_parser("delete")
    c.add_argument("table")
    c.add_argument("name")
    c = brsub.add_parser("fast-forward")
    c.add_argument("table")
    c.add_argument("name")
    br.set_defaults(func=cmd_branch)

    fl = sub.add_parser("fleet", help="multi-host fleet plane")
    flsub = fl.add_subparsers(dest="fleet_cmd", required=True)
    c = flsub.add_parser(
        "status",
        help="ownership-generation history, lease view, dead/"
             "adopted/rejoining sets, per-host bucket shares")
    c.add_argument("table")
    c.add_argument("--base-user", default="stream-daemon",
                   help="the daemons' commit-user base (per-host "
                        "users are <base>-p<i>)")
    c.add_argument("--lease-walk", type=int, default=16,
                   help="newest-first snapshots merged into the "
                        "lease view")
    c.add_argument("--serving", metavar="HOST:PORT",
                   help="router (or single replica) to fold the "
                        "serving plane's /slo burn rates into the "
                        "status (best-effort)")
    c = flsub.add_parser(
        "trace",
        help="stitch per-process trace spools (trace.export.dir) "
             "into ONE Perfetto-loadable file: a track per process, "
             "flow arrows across every serving hop and store-carried "
             "link")
    c.add_argument("--merge", required=True, metavar="SPOOL_DIR",
                   help="the fleet's shared trace.export.dir")
    c.add_argument("--out", default="fleet-trace.json",
                   metavar="OUT.json",
                   help="merged Chrome trace-event JSON "
                        "(default: fleet-trace.json)")
    fl.set_defaults(func=cmd_fleet)

    s = sub.add_parser("sql", help="run SQL (or start a REPL)")
    s.add_argument("query", nargs="?", help="statement; omit for a REPL")
    s.add_argument("--database", "-d", default="default")
    s.set_defaults(func=cmd_sql)

    ln = sub.add_parser(
        "lint", help="whole-program static analysis (the tier-1 "
                     "rule engine); exit 1 on unsuppressed findings")
    ln.add_argument("--json", action="store_true",
                    help="machine-readable report (findings incl. "
                         "suppressed, summary counts)")
    ln.add_argument("--rule", action="append", dest="rules",
                    metavar="ID",
                    help="run only this rule id (repeatable; "
                         "see --list-rules)")
    ln.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ln.add_argument("--show-suppressed", action="store_true",
                    help="include suppressed findings in text output")
    ln.add_argument("--package-dir", metavar="DIR",
                    help="package root to analyse (default: the "
                         "installed paimon_tpu)")
    ln.set_defaults(func=cmd_lint)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.command is None:
        parser.print_help()
        return 0
    try:
        args.func(args)
    except SystemExit as e:
        if isinstance(e.code, int):
            return e.code
        print(f"error: {e.code}", file=sys.stderr)
        return 1
    except Exception as e:                         # noqa: BLE001
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
