"""Per-file bloom filter indexes.

reference: paimon-common/.../fileindex/bloomfilter/ (BloomFilterFileIndex
+ FastHash, written by io/DataFileIndexWriter either embedded in the
data-file metadata or as .index sidecars, evaluated by
io/FileIndexEvaluator to skip whole files on equality predicates).

TPU-first shape: values hash to 64 bits vectorized (splitmix64 for
fixed-width columns), the k probe positions derive from (h1, h2)
double-hashing, and the bit array builds with one np.bitwise_or.at —
no per-record loop for numeric columns. The filter serializes into
DataFileMeta.embedded_index as a tiny tagged blob per column.

Enable with `file-index.bloom-filter.columns = a,b` (fpp via
`file-index.bloom-filter.fpp`, default 0.01).
"""

from __future__ import annotations

import math
import struct
from typing import Dict, List, Optional

import numpy as np
import pyarrow as pa

__all__ = ["BloomFilter", "build_file_index", "read_file_index",
           "hash_column"]

_MAGIC = b"PTFI"          # paimon-tpu file index blob
_VERSION = 1


def _splitmix64(x: np.ndarray) -> np.ndarray:
    x = (x + np.uint64(0x9E3779B97F4A7C15)) & np.uint64(0xFFFFFFFFFFFFFFFF)
    x = ((x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)) \
        & np.uint64(0xFFFFFFFFFFFFFFFF)
    x = ((x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)) \
        & np.uint64(0xFFFFFFFFFFFFFFFF)
    return x ^ (x >> np.uint64(31))


def hash_column(col: pa.ChunkedArray) -> np.ndarray:
    """Stable uint64 hash per row (nulls hash to a sentinel that is
    never probed)."""
    arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    t = arr.type
    if pa.types.is_integer(t) or pa.types.is_temporal(t) or \
            pa.types.is_boolean(t):
        try:
            vals = np.asarray(arr.cast(pa.int64()).fill_null(0))
        except pa.ArrowNotImplementedError:
            vals = np.asarray(arr.cast(pa.int32()).fill_null(0)) \
                .astype(np.int64)
        return _splitmix64(vals.view(np.uint64))
    if pa.types.is_floating(t):
        vals = np.asarray(arr.cast(pa.float64()).fill_null(0.0))
        return _splitmix64(vals.view(np.uint64))
    if pa.types.is_string(t) or pa.types.is_large_string(t) or \
            pa.types.is_binary(t) or pa.types.is_large_binary(t):
        from paimon_tpu.core.bucket import murmur_hash_bytes
        out = np.empty(len(arr), dtype=np.uint64)
        for i, v in enumerate(arr.to_pylist()):
            if v is None:
                out[i] = 0
                continue
            b = v.encode("utf-8") if isinstance(v, str) else v
            out[i] = np.uint64(murmur_hash_bytes(b)) | \
                (np.uint64(murmur_hash_bytes(b, seed=77)) << np.uint64(32))
        return out
    raise ValueError(f"bloom filter unsupported for type {t}")


def hash_value(value, arrow_type: pa.DataType) -> int:
    """Hash one literal consistently with hash_column."""
    return int(hash_column(pa.chunked_array(
        [pa.array([value], arrow_type)]))[0])


class BloomFilter:
    def __init__(self, bits: np.ndarray, k: int):
        self.bits = bits            # uint64 words
        self.k = k

    @property
    def num_bits(self) -> int:
        return len(self.bits) * 64

    @staticmethod
    def build(hashes: np.ndarray, fpp: float = 0.01) -> "BloomFilter":
        n = max(1, len(hashes))
        m = max(64, int(-n * math.log(fpp) / (math.log(2) ** 2)))
        m = ((m + 63) // 64) * 64
        k = max(1, round(m / n * math.log(2)))
        bits = np.zeros(m // 64, dtype=np.uint64)
        h1 = hashes
        h2 = _splitmix64(hashes)
        for i in range(k):
            pos = (h1 + np.uint64(i) * h2) % np.uint64(m)
            np.bitwise_or.at(bits, (pos >> np.uint64(6)).astype(np.int64),
                             np.uint64(1) << (pos & np.uint64(63)))
        return BloomFilter(bits, k)

    def might_contain_many(self, hashes: np.ndarray) -> np.ndarray:
        """Vectorized membership test: bool[n] for uint64 hashes[n] —
        the same double-hash probe sequence as build(), no per-key
        Python loop."""
        m = np.uint64(self.num_bits)
        h1 = hashes.astype(np.uint64)
        h2 = _splitmix64(h1)
        out = np.ones(len(h1), dtype=bool)
        for i in range(self.k):
            pos = (h1 + np.uint64(i) * h2) % m
            words = self.bits[(pos >> np.uint64(6)).astype(np.int64)]
            out &= (words >> (pos & np.uint64(63))) & np.uint64(1) != 0
        return out

    def might_contain(self, h: int) -> bool:
        m = self.num_bits
        h1 = int(h) & 0xFFFFFFFFFFFFFFFF
        h2 = int(_splitmix64(np.array([h1], dtype=np.uint64))[0])
        for i in range(self.k):
            pos = (h1 + i * h2) % ((1 << 64)) % m
            word = int(self.bits[pos >> 6])
            if not (word >> (pos & 63)) & 1:
                return False
        return True

    def serialize(self) -> bytes:
        return struct.pack("<HI", self.k, len(self.bits)) + \
            self.bits.astype("<u8").tobytes()

    @staticmethod
    def deserialize(data: bytes) -> "BloomFilter":
        k, nwords = struct.unpack_from("<HI", data, 0)
        bits = np.frombuffer(data, "<u8", nwords, 6).copy()
        return BloomFilter(bits, k)


def build_file_index(table: pa.Table, columns: List[str],
                     fpp: float = 0.01) -> Optional[bytes]:
    """Serialize per-column bloom filters into one embedded-index blob."""
    entries = []
    for c in columns:
        if c not in table.column_names:
            continue
        try:
            hashes = hash_column(table.column(c))
        except ValueError:
            continue
        bf = BloomFilter.build(hashes, fpp)
        blob = bf.serialize()
        cname = c.encode("utf-8")
        entries.append(struct.pack("<HI", len(cname), len(blob))
                       + cname + blob)
    if not entries:
        return None
    return _MAGIC + bytes([_VERSION]) + b"".join(entries)


def place_file_index(file_io, path_factory, partition, bucket,
                     data_file_name: str, blob: Optional[bytes],
                     threshold: int):
    """-> (embedded_index, extra_files): small blobs embed in the
    manifest entry, larger ones become a `<data-file>.index` sidecar
    (reference io/DataFileIndexWriter + file-index.in-manifest-threshold)."""
    if blob is None:
        return None, []
    if len(blob) <= threshold:
        return blob, []
    sidecar = data_file_name + ".index"
    file_io.write_bytes(
        path_factory.data_file_path(partition, bucket, sidecar), blob,
        overwrite=False)
    return None, [sidecar]


def read_file_index(data: Optional[bytes]) -> Dict[str, BloomFilter]:
    if not data or data[:4] != _MAGIC:
        return {}
    out: Dict[str, BloomFilter] = {}
    p = 5
    while p < len(data):
        nlen, blen = struct.unpack_from("<HI", data, p)
        p += 6
        name = data[p:p + nlen].decode("utf-8")
        p += nlen
        out[name] = BloomFilter.deserialize(data[p:p + blen])
        p += blen
    return out
