"""Full-text search index.

reference capability: paimon-full-text (native tantivy-like inverted
indexer behind NativeFullTextGlobalIndexer.java) + paimon-eslib (Lucene
analyzers). Here: an in-process inverted index with TF-IDF ranking —
postings are numpy arrays, scoring one vectorized pass per query term.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

__all__ = ["FullTextIndex", "full_text_search"]

_TOKEN = re.compile(r"[a-z0-9]+")


def tokenize(text: str) -> List[str]:
    return _TOKEN.findall(text.lower())


class FullTextIndex:
    """Inverted index over one text column: term -> (row ids, term
    frequencies). Ranking: TF-IDF with length normalization."""

    def __init__(self, texts: List[Optional[str]]):
        self.n = len(texts)
        postings: Dict[str, Dict[int, int]] = {}
        self.doc_len = np.zeros(self.n, dtype=np.float32)
        for i, t in enumerate(texts):
            if not t:
                continue
            toks = tokenize(t)
            self.doc_len[i] = len(toks)
            for tok in toks:
                d = postings.setdefault(tok, {})
                d[i] = d.get(i, 0) + 1
        self.postings: Dict[str, Tuple[np.ndarray, np.ndarray]] = {
            term: (np.fromiter(d.keys(), dtype=np.int64, count=len(d)),
                   np.fromiter(d.values(), dtype=np.float32,
                               count=len(d)))
            for term, d in postings.items()}

    def search(self, query: str, k: int = 10
               ) -> Tuple[np.ndarray, np.ndarray]:
        """-> (row_ids, scores) ranked best-first."""
        scores = np.zeros(self.n, dtype=np.float32)
        for term in tokenize(query):
            p = self.postings.get(term)
            if p is None:
                continue
            rows, tf = p
            idf = math.log(1 + self.n / len(rows))
            scores[rows] += tf * idf
        norm = np.where(self.doc_len > 0, np.sqrt(self.doc_len), 1.0)
        scores = scores / norm
        hit = np.flatnonzero(scores > 0)
        if len(hit) == 0:
            return (np.zeros(0, np.int64), np.zeros(0, np.float32))
        order = hit[np.argsort(-scores[hit], kind="stable")][:k]
        return order, scores[order]


def full_text_search(table, column: str, query: str, k: int = 10,
                     index: Optional[FullTextIndex] = None) -> pa.Table:
    """Search a table's text column; returns the top-k rows with a
    `_score` column (reference FullTextSearchTable /
    FullTextSearchSplit)."""
    data = table.to_arrow()
    idx = index or FullTextIndex(data.column(column).to_pylist())
    rows, scores = idx.search(query, k)
    out = data.take(pa.array(rows))
    return out.append_column("_score", pa.array(scores, pa.float32()))
