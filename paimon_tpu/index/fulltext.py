"""Full-text search index: BM25, persisted postings, incremental build.

reference capability: paimon-full-text (native tantivy-like inverted
indexer behind NativeFullTextGlobalIndexer.java) + paimon-eslib (Lucene
analyzers, ESIndexGlobalIndexerFactory.java:32, ESIndexOptions.java:28).

TPU-first shape: postings are columnar arrays scored in one vectorized
pass per query term (no per-doc scoring loop), and the persisted layout
is Parquet segments under the table's index directory —
`{table}/index/fulltext/{column}/seg-*.parquet` sorted by term with
small row groups, so a term query decodes only the row groups whose
[min,max] term range covers it (O(matched postings), not O(corpus)).
Segments are immutable; an incremental refresh indexes only rows whose
`_ROW_ID` is beyond the last indexed id and appends one new segment
(`optimize()` folds them back into one).
"""

from __future__ import annotations

import io
import json
import math
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

__all__ = ["Analyzer", "FullTextIndex", "PersistedFullTextIndex",
           "full_text_search", "tokenize"]

_WORD = re.compile(r"\w+", re.UNICODE)

# BM25 constants (the standard Robertson defaults, same as Lucene's
# BM25Similarity)
K1 = 1.2
B = 0.75

_SUFFIXES = ("ational", "iveness", "fulness", "ousness", "ization",
             "sses", "ments", "ingly", "ation", "ness", "ment", "ies",
             "ing", "ed", "es", "s")


def _is_cjk(ch: str) -> bool:
    cp = ord(ch)
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x3040 <= cp <= 0x30FF or 0xAC00 <= cp <= 0xD7AF)


class Analyzer:
    """Configurable tokenizer: lowercase folding, optional light
    suffix-stripping stemmer, CJK-safe segmentation (contiguous CJK
    runs emit overlapping bigrams — the Lucene CJKAnalyzer approach —
    since CJK text has no word delimiters)."""

    def __init__(self, lowercase: bool = True, stem: bool = False,
                 min_token_len: int = 1,
                 stopwords: Optional[Sequence[str]] = None):
        self.lowercase = lowercase
        self.stem = stem
        self.min_token_len = min_token_len
        self.stopwords = frozenset(stopwords or ())

    # -- config (persisted in meta.json so queries re-analyze
    #    identically across processes) --------------------------------
    def to_json(self) -> dict:
        return {"lowercase": self.lowercase, "stem": self.stem,
                "min_token_len": self.min_token_len,
                "stopwords": sorted(self.stopwords)}

    @classmethod
    def from_json(cls, j: dict) -> "Analyzer":
        return cls(lowercase=j.get("lowercase", True),
                   stem=j.get("stem", False),
                   min_token_len=j.get("min_token_len", 1),
                   stopwords=j.get("stopwords") or None)

    def _stem(self, tok: str) -> str:
        for suf in _SUFFIXES:
            if tok.endswith(suf) and len(tok) - len(suf) >= 3:
                return tok[: len(tok) - len(suf)]
        return tok

    def tokens(self, text: str) -> List[str]:
        if not text:
            return []
        if self.lowercase:
            text = text.lower()
        out: List[str] = []
        for m in _WORD.finditer(text):
            w = m.group(0)
            # split the word into non-CJK spans and CJK bigram runs
            i, n = 0, len(w)
            while i < n:
                if _is_cjk(w[i]):
                    j = i
                    while j < n and _is_cjk(w[j]):
                        j += 1
                    run = w[i:j]
                    if len(run) == 1:
                        out.append(run)
                    else:
                        out.extend(run[p:p + 2]
                                   for p in range(len(run) - 1))
                    i = j
                else:
                    j = i
                    while j < n and not _is_cjk(w[j]):
                        j += 1
                    tok = w[i:j]
                    if len(tok) >= self.min_token_len and \
                            tok not in self.stopwords:
                        out.append(self._stem(tok) if self.stem else tok)
                    i = j
        return out


_DEFAULT = Analyzer()


def tokenize(text: str) -> List[str]:
    return _DEFAULT.tokens(text)


def _parse_query(query: str) -> Tuple[List[str], str]:
    """'a b' -> (terms, 'or'); '+a +b' / 'a AND b' -> 'and';
    '"a b"' -> phrase."""
    q = query.strip()
    if len(q) >= 2 and q[0] == '"' and q[-1] == '"':
        return q[1:-1].split(), "phrase"
    if " AND " in q:
        return [t for t in q.split() if t != "AND"], "and"
    if any(t.startswith("+") for t in q.split()):
        return [t.lstrip("+") for t in q.split()], "and"
    return q.split(), "or"


def _bm25(tf: np.ndarray, df: int, n_docs: int, dl: np.ndarray,
          avgdl: float) -> np.ndarray:
    idf = math.log(1.0 + (n_docs - df + 0.5) / (df + 0.5))
    denom = tf + K1 * (1.0 - B + B * dl / max(avgdl, 1e-9))
    return (idf * tf * (K1 + 1.0) / denom).astype(np.float32)


class _Scorer:
    """Shared BM25 + AND/phrase machinery over per-term postings:
    `fetch(term) -> (doc_ids, tf, positions_list_or_None)`."""

    def __init__(self, n_docs: int, avgdl: float, doc_len_of):
        self.n_docs = n_docs
        self.avgdl = avgdl
        self.doc_len_of = doc_len_of      # (doc_ids) -> lengths

    def score(self, postings: List[Tuple[np.ndarray, np.ndarray,
                                         Optional[list]]],
              mode: str, k: int) -> Tuple[np.ndarray, np.ndarray]:
        live = [(ids, tf, pos) for ids, tf, pos in postings
                if len(ids) > 0]
        if not live or (mode in ("and", "phrase")
                        and len(live) != len(postings)):
            return (np.zeros(0, np.int64), np.zeros(0, np.float32))
        ids_cat = np.concatenate([p[0] for p in live])
        contribs = []
        for ids, tf, _ in live:
            dl = self.doc_len_of(ids)
            contribs.append(_bm25(tf.astype(np.float64), len(ids),
                                  self.n_docs, dl, self.avgdl))
        contrib_cat = np.concatenate(contribs)
        uniq, inverse = np.unique(ids_cat, return_inverse=True)
        scores = np.zeros(len(uniq), dtype=np.float64)
        np.add.at(scores, inverse, contrib_cat)
        if mode in ("and", "phrase"):
            hits = np.zeros(len(uniq), dtype=np.int32)
            np.add.at(hits, inverse, 1)
            keep = hits == len(live)
            if mode == "phrase":
                keep &= self._phrase_ok(uniq, live)
            uniq, scores = uniq[keep], scores[keep]
        if len(uniq) == 0:
            return (np.zeros(0, np.int64), np.zeros(0, np.float32))
        order = np.lexsort((uniq, -scores))[:k]
        return uniq[order].astype(np.int64), \
            scores[order].astype(np.float32)

    def _phrase_ok(self, docs: np.ndarray,
                   live: List[Tuple]) -> np.ndarray:
        """docs that contain the terms at consecutive positions."""
        pos_maps = []
        for ids, _, pos_list in live:
            if pos_list is None:
                # positions unavailable: degrade to AND semantics
                return np.ones(len(docs), dtype=bool)
            pos_maps.append({int(d): pos_list[i]
                             for i, d in enumerate(ids)})
        ok = np.zeros(len(docs), dtype=bool)
        for i, d in enumerate(docs):
            d = int(d)
            if any(d not in m for m in pos_maps):
                continue
            cand = set(int(p) for p in pos_maps[0][d])
            for t in range(1, len(pos_maps)):
                nxt = set(int(p) - t for p in pos_maps[t][d])
                cand &= nxt
                if not cand:
                    break
            ok[i] = bool(cand)
        return ok


class FullTextIndex:
    """In-memory inverted index over one text column (doc id = row
    position).  BM25 ranking; AND / phrase query modes."""

    def __init__(self, texts: List[Optional[str]],
                 analyzer: Optional[Analyzer] = None):
        self.analyzer = analyzer or _DEFAULT
        self.n = len(texts)
        postings: Dict[str, Dict[int, List[int]]] = {}
        self.doc_len = np.zeros(self.n, dtype=np.float32)
        for i, t in enumerate(texts):
            if not t:
                continue
            toks = self.analyzer.tokens(t)
            self.doc_len[i] = len(toks)
            for p, tok in enumerate(toks):
                postings.setdefault(tok, {}).setdefault(i, []).append(p)
        self.postings: Dict[str, Tuple[np.ndarray, np.ndarray, list]] = {}
        for term, d in postings.items():
            ids = np.fromiter(d.keys(), dtype=np.int64, count=len(d))
            tf = np.array([len(v) for v in d.values()], dtype=np.float32)
            self.postings[term] = (ids, tf, list(d.values()))
        self.avgdl = float(self.doc_len.sum() / max(self.n, 1))

    def _fetch(self, term: str):
        p = self.postings.get(term)
        if p is None:
            return (np.zeros(0, np.int64), np.zeros(0, np.float32), [])
        return p

    def search(self, query: str, k: int = 10,
               mode: Optional[str] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """-> (row_positions, scores) ranked best-first.  Query syntax:
        plain terms = OR; `a AND b` / `+a +b` = AND; `"a b"` = phrase."""
        terms, parsed_mode = _parse_query(query)
        mode = mode or parsed_mode
        terms = [t for ts in terms for t in self.analyzer.tokens(ts)]
        scorer = _Scorer(max(self.n, 1), self.avgdl,
                         lambda ids: self.doc_len[ids])
        return scorer.score([self._fetch(t) for t in terms], mode, k)


class PersistedFullTextIndex:
    """Segmented on-disk inverted index for row-tracked tables
    (doc id = `_ROW_ID`).  Survives process restart; `refresh()`
    incrementally indexes only rows beyond the last indexed row id.

    Layout under `{table}/index/fulltext/{column}/`:
      meta.json                  {version, column, snapshot_id,
                                  max_row_id, analyzer, segments: [...]}
      seg-<n>.parquet            (term, row_id, tf, positions)
                                 sorted by term, small row groups
      seg-<n>-docs.parquet       (row_id, doc_len) sorted by row_id
    """

    VERSION = 1
    ROW_GROUP = 4096

    def __init__(self, table, column: str,
                 analyzer: Optional[Analyzer] = None):
        self.table = table
        self.column = column
        self.analyzer = analyzer or Analyzer()
        self.meta: Optional[dict] = None
        self._doc_cache: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}

    # -- storage ------------------------------------------------------
    @property
    def _dir(self) -> str:
        return f"{self.table.path}/index/fulltext/{self.column}"

    def _read(self, name: str) -> bytes:
        return self.table.file_io.read_bytes(f"{self._dir}/{name}")

    def _write(self, name: str, data: bytes) -> None:
        self.table.file_io.write_bytes(f"{self._dir}/{name}", data,
                                       overwrite=True)

    @classmethod
    def open(cls, table, column: str,
             analyzer: Optional[Analyzer] = None
             ) -> "PersistedFullTextIndex":
        idx = cls(table, column, analyzer)
        try:
            meta = json.loads(idx._read("meta.json"))
            if meta.get("version") == cls.VERSION and \
                    meta.get("column") == column:
                idx.meta = meta
                idx.analyzer = Analyzer.from_json(meta["analyzer"])
        except (FileNotFoundError, OSError, ValueError, KeyError):
            pass
        return idx

    # -- build --------------------------------------------------------
    def _rows_beyond(self, min_row_id_excl: int) -> pa.Table:
        from paimon_tpu.core.row_tracking import ROW_ID_COL
        import pyarrow.compute as pc
        t = self.table.to_arrow(projection=[self.column],
                                with_row_ids=True)
        t = t.filter(pc.is_valid(t.column(ROW_ID_COL)))
        if min_row_id_excl >= 0:
            t = t.filter(pc.greater(t.column(ROW_ID_COL),
                                    min_row_id_excl))
        return t

    def _build_segment(self, texts: List[Optional[str]],
                       row_ids: np.ndarray, seg_name: str) -> dict:
        import pyarrow.parquet as pq
        postings: Dict[str, List[Tuple[int, List[int]]]] = {}
        doc_len = np.zeros(len(texts), dtype=np.int32)
        for i, txt in enumerate(texts):
            if not txt:
                continue
            toks = self.analyzer.tokens(txt)
            doc_len[i] = len(toks)
            per: Dict[str, List[int]] = {}
            for p, tok in enumerate(toks):
                per.setdefault(tok, []).append(p)
            rid = int(row_ids[i])
            for tok, pos in per.items():
                postings.setdefault(tok, []).append((rid, pos))
        terms, rids, tfs, poss = [], [], [], []
        for term in sorted(postings):
            for rid, pos in postings[term]:
                terms.append(term)
                rids.append(rid)
                tfs.append(len(pos))
                poss.append(pos)
        seg = pa.table({
            "term": pa.array(terms, pa.string()),
            "row_id": pa.array(rids, pa.int64()),
            "tf": pa.array(tfs, pa.int32()),
            "positions": pa.array(poss, pa.list_(pa.int32())),
        })
        buf = io.BytesIO()
        pq.write_table(seg, buf, row_group_size=self.ROW_GROUP)
        self._write(f"{seg_name}.parquet", buf.getvalue())
        order = np.argsort(row_ids, kind="stable")
        dbuf = io.BytesIO()
        pq.write_table(pa.table({
            "row_id": pa.array(row_ids[order], pa.int64()),
            "doc_len": pa.array(doc_len[order], pa.int32()),
        }), dbuf)
        self._write(f"{seg_name}-docs.parquet", dbuf.getvalue())
        return {"file": f"{seg_name}.parquet",
                "docs_file": f"{seg_name}-docs.parquet",
                "num_docs": int(len(texts)),
                "sum_len": int(doc_len.sum()),
                "num_postings": int(len(terms))}

    def refresh(self) -> int:
        """Index rows not yet covered; returns docs added.  Builds one
        new immutable segment (reference incremental indexer shape)."""
        latest = self.table.latest_snapshot()
        if latest is None:
            return 0
        if self.meta is not None and \
                self.meta["snapshot_id"] == latest.id:
            return 0
        max_rid = self.meta["max_row_id"] if self.meta else -1
        t = self._rows_beyond(max_rid)
        if t.num_rows == 0:
            if self.meta is not None:
                self.meta["snapshot_id"] = latest.id
                self._write("meta.json",
                            json.dumps(self.meta).encode())
            return 0
        from paimon_tpu.core.row_tracking import ROW_ID_COL
        row_ids = np.asarray(t.column(ROW_ID_COL).combine_chunks()
                             .cast(pa.int64()))
        texts = t.column(self.column).to_pylist()
        seg_no = len(self.meta["segments"]) if self.meta else 0
        seg = self._build_segment(texts, row_ids,
                                  f"seg-{latest.id}-{seg_no}")
        if self.meta is None:
            self.meta = {"version": self.VERSION, "column": self.column,
                         "analyzer": self.analyzer.to_json(),
                         "segments": []}
        self.meta["segments"].append(seg)
        self.meta["snapshot_id"] = latest.id
        self.meta["max_row_id"] = int(max(max_rid, row_ids.max()))
        self._write("meta.json", json.dumps(self.meta).encode())
        self._doc_cache.clear()
        return t.num_rows

    def optimize(self) -> None:
        """Fold all segments into one (Lucene force-merge analog)."""
        import pyarrow.parquet as pq
        if not self.meta or len(self.meta["segments"]) <= 1:
            return
        segs = self.meta["segments"]
        posts = [pq.read_table(io.BytesIO(self._read(s["file"])))
                 for s in segs]
        docs = [pq.read_table(io.BytesIO(self._read(s["docs_file"])))
                for s in segs]
        post = pa.concat_tables(posts).sort_by([("term", "ascending"),
                                                ("row_id", "ascending")])
        doc = pa.concat_tables(docs).sort_by("row_id")
        buf = io.BytesIO()
        pq.write_table(post, buf, row_group_size=self.ROW_GROUP)
        name = f"seg-merged-{self.meta['snapshot_id']}"
        self._write(f"{name}.parquet", buf.getvalue())
        dbuf = io.BytesIO()
        pq.write_table(doc, dbuf)
        self._write(f"{name}-docs.parquet", dbuf.getvalue())
        self.meta["segments"] = [{
            "file": f"{name}.parquet",
            "docs_file": f"{name}-docs.parquet",
            "num_docs": int(sum(s["num_docs"] for s in segs)),
            "sum_len": int(sum(s["sum_len"] for s in segs)),
            "num_postings": int(post.num_rows)}]
        self._write("meta.json", json.dumps(self.meta).encode())
        self._doc_cache.clear()

    # -- query --------------------------------------------------------
    def _seg_postings(self, seg: dict, terms: List[str]
                      ) -> Dict[str, Tuple[np.ndarray, np.ndarray,
                                           list]]:
        """Read only the row groups whose term range intersects the
        query terms — O(matched postings + row-group overhead)."""
        import pyarrow.parquet as pq
        pf = pq.ParquetFile(io.BytesIO(self._read(seg["file"])))
        tcol = pf.schema_arrow.get_field_index("term")
        want: List[int] = []
        for g in range(pf.num_row_groups):
            st = pf.metadata.row_group(g).column(tcol).statistics
            if st is None or st.min is None:
                want.append(g)
                continue
            if any(st.min <= t <= st.max for t in terms):
                want.append(g)
        out: Dict[str, Tuple[np.ndarray, np.ndarray, list]] = {}
        if not want:
            return out
        t = pf.read_row_groups(want)
        import pyarrow.compute as pc
        m = pc.is_in(t.column("term"), value_set=pa.array(terms))
        t = t.filter(m)
        if t.num_rows == 0:
            return out
        term_np = t.column("term").to_pylist()
        rid = np.asarray(t.column("row_id").combine_chunks())
        tf = np.asarray(t.column("tf").combine_chunks()
                        .cast(pa.float32()))
        pos = t.column("positions").to_pylist()
        for term in set(term_np):
            sel = [i for i, x in enumerate(term_np) if x == term]
            out[term] = (rid[sel], tf[sel], [pos[i] for i in sel])
        return out

    def _doc_lens(self, seg: dict) -> Tuple[np.ndarray, np.ndarray]:
        key = seg["docs_file"]
        if key not in self._doc_cache:
            import pyarrow.parquet as pq
            t = pq.read_table(io.BytesIO(self._read(key)))
            self._doc_cache[key] = (
                np.asarray(t.column("row_id").combine_chunks()),
                np.asarray(t.column("doc_len").combine_chunks()
                           .cast(pa.float32())))
        return self._doc_cache[key]

    def search(self, query: str, k: int = 10,
               mode: Optional[str] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """-> (row_ids, scores) best-first across all segments."""
        if not self.meta or not self.meta["segments"]:
            return (np.zeros(0, np.int64), np.zeros(0, np.float32))
        terms, parsed_mode = _parse_query(query)
        mode = mode or parsed_mode
        terms = [t for ts in terms for t in self.analyzer.tokens(ts)]
        if not terms:
            return (np.zeros(0, np.int64), np.zeros(0, np.float32))
        segs = self.meta["segments"]
        n_docs = sum(s["num_docs"] for s in segs)
        avgdl = sum(s["sum_len"] for s in segs) / max(n_docs, 1)
        # gather per-term postings across segments (row-id spaces are
        # disjoint, so concatenation is a valid union)
        merged: Dict[str, List[Tuple]] = {t: [] for t in terms}
        dl_keys, dl_vals = [], []
        for seg in segs:
            found = self._seg_postings(seg, terms)
            for t, p in found.items():
                merged[t].append(p)
            ks, vs = self._doc_lens(seg)
            dl_keys.append(ks)
            dl_vals.append(vs)
        dlk = np.concatenate(dl_keys)
        dlv = np.concatenate(dl_vals)
        order = np.argsort(dlk, kind="stable")
        dlk, dlv = dlk[order], dlv[order]

        def doc_len_of(ids: np.ndarray) -> np.ndarray:
            pos = np.searchsorted(dlk, ids)
            pos = np.minimum(pos, max(len(dlk) - 1, 0))
            return dlv[pos] if len(dlk) else \
                np.zeros(len(ids), np.float32)

        postings = []
        for t in terms:
            parts = merged[t]
            if not parts:
                postings.append((np.zeros(0, np.int64),
                                 np.zeros(0, np.float32), []))
                continue
            ids = np.concatenate([p[0] for p in parts])
            tf = np.concatenate([p[1] for p in parts])
            pos = [x for p in parts for x in p[2]]
            postings.append((ids, tf, pos))
        scorer = _Scorer(max(n_docs, 1), avgdl, doc_len_of)
        return scorer.score(postings, mode, k)


def full_text_search(table, column: str, query: str, k: int = 10,
                     index: Optional[FullTextIndex] = None) -> pa.Table:
    """Search a table's text column; returns the top-k rows with a
    `_score` column (reference FullTextSearchTable /
    FullTextSearchSplit)."""
    data = table.to_arrow()
    idx = index or FullTextIndex(data.column(column).to_pylist())
    rows, scores = idx.search(query, k)
    out = data.take(pa.array(rows))
    return out.append_column("_score", pa.array(scores, pa.float32()))
