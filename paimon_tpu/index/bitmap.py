"""Bitmap, bit-sliced (BSI) and range-bitmap per-file column indexes.

reference: paimon-common/src/main/java/org/apache/paimon/fileindex/
bitmap/BitmapFileIndex.java (distinct value -> row-position bitmap),
bsi/BitSliceIndexBitmap.java (O'Neil bit-sliced arithmetic for range
predicates over integers), rangebitmap/RangeBitmap.java (range-encoded
bins).  All three serialize row positions with the portable roaring32
codec shared with deletion vectors (index/roaring.py).

TPU-first shape: builds are whole-column vectorized (Arrow
dictionary_encode / np.unique + one stable argsort; bit-slices peel off
with shifts over the full value vector), and predicate evaluation works
on dense numpy bool masks so AND/OR/NOT over selections are single
vector ops — no per-row loops anywhere.

Evaluation contract:
  eval(op, literal) -> (mask, exact)
where mask is a bool[num_rows] selection (None = cannot evaluate) and
exact says whether the mask is precise or a conservative superset (the
read path always re-applies the predicate exactly, so supersets only
cost unpruned rows, never correctness).
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from paimon_tpu.index.roaring import (
    deserialize_roaring32, serialize_roaring32,
)

__all__ = ["BitmapIndex", "BSIIndex", "RangeBitmapIndex"]


# -- typed literal codec -----------------------------------------------------

_KIND_INT, _KIND_FLOAT, _KIND_STR, _KIND_BYTES = 0, 1, 2, 3


def _column_values(col) -> Tuple[np.ndarray, "pa.Array", int, np.ndarray]:
    """-> (valid_positions, values_array, kind, null_positions)."""
    arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    t = arr.type
    nulls = np.asarray(pc.is_null(arr))
    null_pos = np.flatnonzero(nulls).astype(np.uint32)
    valid_pos = np.flatnonzero(~nulls).astype(np.uint32)
    vals = arr.drop_null()
    if pa.types.is_integer(t) or pa.types.is_boolean(t) or \
            pa.types.is_temporal(t):
        try:
            vals = vals.cast(pa.int64())
        except pa.ArrowInvalid:
            vals = vals.cast(pa.int64(), safe=False)
        return valid_pos, vals, _KIND_INT, null_pos
    if pa.types.is_floating(t):
        return valid_pos, vals.cast(pa.float64()), _KIND_FLOAT, null_pos
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return valid_pos, vals.cast(pa.large_string()), _KIND_STR, null_pos
    if pa.types.is_binary(t) or pa.types.is_large_binary(t):
        return valid_pos, vals.cast(pa.large_binary()), _KIND_BYTES, null_pos
    raise ValueError(f"bitmap index unsupported for type {t}")


def _encode_literal(v, kind: int) -> bytes:
    if kind == _KIND_INT:
        return struct.pack("<q", int(v))
    if kind == _KIND_FLOAT:
        return struct.pack("<d", float(v))
    b = v.encode("utf-8") if isinstance(v, str) else bytes(v)
    return struct.pack("<I", len(b)) + b


def _norm_literal(v, kind: int):
    if kind == _KIND_INT:
        if isinstance(v, bool):
            return int(v)
        if not isinstance(v, int):
            return None
        return v
    if kind == _KIND_FLOAT:
        return float(v) if isinstance(v, (int, float)) else None
    if kind == _KIND_STR:
        return v if isinstance(v, str) else None
    return bytes(v) if isinstance(v, (bytes, bytearray)) else None


def _prefix_successor(prefix: str):
    """Smallest string greater than every string with this prefix:
    drop trailing U+10FFFF chars, bump the last remaining code point.
    None = no upper bound (prefix was all U+10FFFF)."""
    trimmed = prefix.rstrip(chr(0x10FFFF))
    if not trimmed:
        return None
    return trimmed[:-1] + chr(ord(trimmed[-1]) + 1)


def _mask_of(positions: np.ndarray, n: int) -> np.ndarray:
    m = np.zeros(n, dtype=bool)
    m[positions] = True
    return m


# -- bitmap index ------------------------------------------------------------

class BitmapIndex:
    """Distinct value -> roaring bitmap of row positions.  Distinct
    values are kept sorted, so range predicates evaluate as a contiguous
    union of position lists (beyond the reference's eq/in surface)."""

    TYPE_TAG = 1

    def __init__(self, num_rows: int, kind: int, distinct: list,
                 pos_lists: List[np.ndarray], null_pos: np.ndarray):
        self.num_rows = num_rows
        self.kind = kind
        self.distinct = distinct          # sorted python values
        self.pos_lists = pos_lists        # uint32 positions per distinct
        self.null_pos = null_pos

    # build ------------------------------------------------------------------

    @staticmethod
    def build(col, max_distinct: int = 1 << 16) -> Optional["BitmapIndex"]:
        n = len(col)
        valid_pos, vals, kind, null_pos = _column_values(col)
        if len(vals) == 0:
            return BitmapIndex(n, kind, [], [], null_pos)
        dictionary = pc.dictionary_encode(vals)
        if isinstance(dictionary, pa.ChunkedArray):
            dictionary = dictionary.combine_chunks()
        codes = np.asarray(dictionary.indices)
        dict_vals = dictionary.dictionary
        if len(dict_vals) > max_distinct:
            return None                   # too high cardinality
        # sort dictionary so eval can binary-search / range-slice
        sort_idx = np.asarray(pc.sort_indices(dict_vals)).astype(np.int64)
        rank = np.empty(len(sort_idx), dtype=np.int64)
        rank[sort_idx] = np.arange(len(sort_idx))
        sorted_codes = rank[codes]
        order = np.argsort(sorted_codes, kind="stable")
        counts = np.bincount(sorted_codes, minlength=len(dict_vals))
        bounds = np.concatenate([[0], np.cumsum(counts)])
        pos_sorted = valid_pos[order]
        pos_lists = [pos_sorted[bounds[i]:bounds[i + 1]]
                     for i in range(len(dict_vals))]
        distinct = dict_vals.take(pa.array(sort_idx)).to_pylist()
        if kind == _KIND_BYTES:
            distinct = [bytes(d) for d in distinct]
        return BitmapIndex(n, kind, distinct, pos_lists, null_pos)

    # eval -------------------------------------------------------------------

    def _find(self, v) -> int:
        import bisect
        return bisect.bisect_left(self.distinct, v)

    def _union(self, lo: int, hi: int) -> np.ndarray:
        if lo >= hi:
            return np.zeros(0, dtype=np.uint32)
        return np.concatenate(self.pos_lists[lo:hi]) \
            if hi - lo > 1 else self.pos_lists[lo]

    def eval(self, op: str, literal) -> Tuple[Optional[np.ndarray], bool]:
        n = self.num_rows
        if op == "is_null":
            return _mask_of(self.null_pos, n), True
        if op == "is_not_null":
            return ~_mask_of(self.null_pos, n), True
        if op in ("eq", "ne"):
            v = _norm_literal(literal, self.kind)
            if v is None:
                return None, False
            i = self._find(v)
            hit = i < len(self.distinct) and self.distinct[i] == v
            m = _mask_of(self.pos_lists[i], n) if hit \
                else np.zeros(n, dtype=bool)
            if op == "ne":
                m = ~m & ~_mask_of(self.null_pos, n)
            return m, True
        if op in ("in", "not_in"):
            m = np.zeros(n, dtype=bool)
            for raw in literal:
                v = _norm_literal(raw, self.kind)
                if v is None:
                    return None, False
                i = self._find(v)
                if i < len(self.distinct) and self.distinct[i] == v:
                    m |= _mask_of(self.pos_lists[i], n)
            if op == "not_in":
                m = ~m & ~_mask_of(self.null_pos, n)
            return m, True
        if op in ("lt", "le", "gt", "ge", "between"):
            if op == "between":
                lo_v = _norm_literal(literal[0], self.kind)
                hi_v = _norm_literal(literal[1], self.kind)
                if lo_v is None or hi_v is None:
                    return None, False
                import bisect
                lo = bisect.bisect_left(self.distinct, lo_v)
                hi = bisect.bisect_right(self.distinct, hi_v)
            else:
                v = _norm_literal(literal, self.kind)
                if v is None:
                    return None, False
                import bisect
                if op == "lt":
                    lo, hi = 0, bisect.bisect_left(self.distinct, v)
                elif op == "le":
                    lo, hi = 0, bisect.bisect_right(self.distinct, v)
                elif op == "gt":
                    lo, hi = bisect.bisect_right(self.distinct, v), \
                        len(self.distinct)
                else:
                    lo, hi = bisect.bisect_left(self.distinct, v), \
                        len(self.distinct)
            return _mask_of(self._union(lo, hi), n), True
        if op == "starts_with" and self.kind == _KIND_STR:
            import bisect
            lo = bisect.bisect_left(self.distinct, literal)
            succ = _prefix_successor(literal)
            # half-open [literal, successor): covers EVERY continuation
            # including U+10FFFF (an appended-sentinel bound would not)
            hi = bisect.bisect_left(self.distinct, succ) \
                if succ is not None else len(self.distinct)
            return _mask_of(self._union(lo, hi), n), True
        return None, False

    # serde ------------------------------------------------------------------

    def serialize(self) -> bytes:
        parts = [struct.pack("<IBI", self.num_rows, self.kind,
                             len(self.distinct))]
        nulls = serialize_roaring32(self.null_pos)
        parts.append(struct.pack("<I", len(nulls)))
        parts.append(nulls)
        for v, pos in zip(self.distinct, self.pos_lists):
            vb = _encode_literal(v, self.kind)
            pb = serialize_roaring32(pos)
            parts.append(struct.pack("<II", len(vb), len(pb)))
            parts.append(vb)
            parts.append(pb)
        return b"".join(parts)

    @staticmethod
    def deserialize(data: bytes) -> "BitmapIndex":
        num_rows, kind, nd = struct.unpack_from("<IBI", data, 0)
        p = 9
        (nlen,) = struct.unpack_from("<I", data, p)
        p += 4
        null_pos = deserialize_roaring32(data[p:p + nlen])
        p += nlen
        distinct, pos_lists = [], []
        for _ in range(nd):
            vlen, plen = struct.unpack_from("<II", data, p)
            p += 8
            vb = data[p:p + vlen]
            p += vlen
            if kind == _KIND_INT:
                distinct.append(struct.unpack("<q", vb)[0])
            elif kind == _KIND_FLOAT:
                distinct.append(struct.unpack("<d", vb)[0])
            else:
                (blen,) = struct.unpack_from("<I", vb, 0)
                raw = vb[4:4 + blen]
                distinct.append(raw.decode("utf-8")
                                if kind == _KIND_STR else raw)
            pos_lists.append(deserialize_roaring32(data[p:p + plen]))
            p += plen
        return BitmapIndex(num_rows, kind, distinct, pos_lists, null_pos)


# -- bit-sliced index --------------------------------------------------------

class BSIIndex:
    """Bit-sliced index over integer-like columns: values shift to
    non-negative deltas from the file min, and slice b holds the rows
    whose bit b is set.  Range predicates evaluate with the O'Neil
    slice recurrence — O(bits) vectorized mask ops, no value
    reconstruction (reference fileindex/bsi/BitSliceIndexBitmap.java)."""

    TYPE_TAG = 2

    def __init__(self, num_rows: int, min_val: int,
                 slices: List[np.ndarray], exists_pos: np.ndarray):
        self.num_rows = num_rows
        self.min_val = min_val
        self.slices = slices              # uint32 position lists per bit
        self.exists_pos = exists_pos

    @staticmethod
    def build(col) -> Optional["BSIIndex"]:
        n = len(col)
        valid_pos, vals, kind, _ = _column_values(col)
        if kind != _KIND_INT:
            return None
        if len(vals) == 0:
            return BSIIndex(n, 0, [], valid_pos)
        v = np.asarray(vals, dtype=np.int64)
        mn = int(v.min())
        delta = (v - mn).astype(np.uint64)
        bits = max(1, int(delta.max()).bit_length())
        slices = []
        for b in range(bits):
            hit = (delta >> np.uint64(b)) & np.uint64(1) == 1
            slices.append(valid_pos[hit])
        return BSIIndex(n, mn, slices, valid_pos)

    # -- O'Neil comparisons on dense masks -----------------------------------

    def _exists(self) -> np.ndarray:
        return _mask_of(self.exists_pos, self.num_rows)

    def _le(self, c: int) -> np.ndarray:
        """rows with delta <= c among existing rows."""
        n = self.num_rows
        if c < 0:
            return np.zeros(n, dtype=bool)
        nbits = len(self.slices)
        if c >= (1 << nbits):
            return self._exists()         # c above every stored delta
        lt = np.zeros(n, dtype=bool)
        eq = self._exists()
        for b in range(nbits - 1, -1, -1):
            slice_mask = _mask_of(self.slices[b], n)
            if (c >> b) & 1:
                lt |= eq & ~slice_mask
            else:
                eq &= ~slice_mask
        # eq now = rows equal to c on all inspected bits
        return lt | eq

    def eval(self, op: str, literal) -> Tuple[Optional[np.ndarray], bool]:
        n = self.num_rows
        if op == "is_not_null":
            return self._exists(), True
        if op == "is_null":
            return ~self._exists(), True
        if op == "between":
            lo = _norm_literal(literal[0], _KIND_INT)
            hi = _norm_literal(literal[1], _KIND_INT)
            if lo is None or hi is None:
                return None, False
            m = self._le(hi - self.min_val) & \
                ~self._le(lo - self.min_val - 1)
            return m & self._exists(), True
        v = _norm_literal(literal, _KIND_INT) \
            if op in ("eq", "ne", "lt", "le", "gt", "ge") else None
        if v is None:
            return None, False
        c = v - self.min_val
        ex = self._exists()
        if op == "eq":
            return (self._le(c) & ~self._le(c - 1)) & ex, True
        if op == "ne":
            return ~(self._le(c) & ~self._le(c - 1)) & ex, True
        if op == "lt":
            return self._le(c - 1) & ex, True
        if op == "le":
            return self._le(c) & ex, True
        if op == "gt":
            return ~self._le(c) & ex, True
        if op == "ge":
            return ~self._le(c - 1) & ex, True
        return None, False

    def serialize(self) -> bytes:
        parts = [struct.pack("<IqI", self.num_rows, self.min_val,
                             len(self.slices))]
        ex = serialize_roaring32(self.exists_pos)
        parts.append(struct.pack("<I", len(ex)))
        parts.append(ex)
        for s in self.slices:
            sb = serialize_roaring32(s)
            parts.append(struct.pack("<I", len(sb)))
            parts.append(sb)
        return b"".join(parts)

    @staticmethod
    def deserialize(data: bytes) -> "BSIIndex":
        num_rows, mn, nb = struct.unpack_from("<IqI", data, 0)
        p = 16
        (elen,) = struct.unpack_from("<I", data, p)
        p += 4
        exists_pos = deserialize_roaring32(data[p:p + elen])
        p += elen
        slices = []
        for _ in range(nb):
            (slen,) = struct.unpack_from("<I", data, p)
            p += 4
            slices.append(deserialize_roaring32(data[p:p + slen]))
            p += slen
        return BSIIndex(num_rows, mn, slices, exists_pos)


# -- range bitmap ------------------------------------------------------------

class RangeBitmapIndex:
    """Range-encoded binned bitmap: values bucket into <=64 quantile
    bins; bin b stores the rows with value <= upper_bound(b)
    (cumulative, so any range predicate is one or two bitmap lookups).
    Boundary bins make the selection a conservative superset — callers
    get exact=False and re-check rows (reference
    fileindex/rangebitmap/RangeBitmap.java)."""

    TYPE_TAG = 3

    def __init__(self, num_rows: int, kind: int, uppers: list,
                 cum_pos: List[np.ndarray], exists_pos: np.ndarray,
                 min_val=0):
        self.num_rows = num_rows
        self.kind = kind
        self.uppers = uppers              # sorted bin upper bounds
        self.cum_pos = cum_pos            # rows with value <= uppers[i]
        self.exists_pos = exists_pos
        self.min_val = min_val            # exact file min for lower bound

    @staticmethod
    def build(col, max_bins: int = 64) -> Optional["RangeBitmapIndex"]:
        n = len(col)
        valid_pos, vals, kind, _ = _column_values(col)
        if kind not in (_KIND_INT, _KIND_FLOAT):
            return None
        if len(vals) == 0:
            return RangeBitmapIndex(n, kind, [], [], valid_pos)
        v = np.asarray(vals, dtype=np.int64 if kind == _KIND_INT
                       else np.float64)
        qs = np.unique(np.quantile(
            v, np.linspace(0, 1, max_bins + 1)[1:]))
        bin_of = np.searchsorted(qs, v, side="left")
        order = np.argsort(bin_of, kind="stable")
        counts = np.bincount(bin_of, minlength=len(qs))
        bounds = np.concatenate([[0], np.cumsum(counts)])
        pos_sorted = valid_pos[order]
        cum_pos = [np.sort(pos_sorted[:bounds[i + 1]])
                   for i in range(len(qs))]
        # integer values satisfy v <= q iff v <= floor(q), so floor keeps
        # "cum_pos[i] == rows with value <= uppers[i]" exact; int() would
        # truncate toward zero and break it for negative boundaries
        import math
        uppers = [math.floor(q) if kind == _KIND_INT else float(q)
                  for q in qs]
        mn = int(v.min()) if kind == _KIND_INT else float(v.min())
        return RangeBitmapIndex(n, kind, uppers, cum_pos, valid_pos, mn)

    def _cum_mask(self, i: int) -> np.ndarray:
        """mask of rows with value <= uppers[i]; i < 0 or no bins
        (all-null column) -> empty."""
        if i < 0 or not self.uppers:
            return np.zeros(self.num_rows, dtype=bool)
        i = min(i, len(self.uppers) - 1)
        return _mask_of(self.cum_pos[i], self.num_rows)

    def eval(self, op: str, literal) -> Tuple[Optional[np.ndarray], bool]:
        import bisect
        if op == "is_not_null":
            return _mask_of(self.exists_pos, self.num_rows), True
        if op == "is_null":
            return ~_mask_of(self.exists_pos, self.num_rows), True
        if op == "between":
            lo = _norm_literal(literal[0], self.kind)
            hi = _norm_literal(literal[1], self.kind)
            if lo is None or hi is None:
                return None, False
            # superset: everything <= bin(hi) minus everything below the
            # bin strictly under lo
            hi_bin = bisect.bisect_left(self.uppers, hi)
            lo_bin = bisect.bisect_left(self.uppers, lo)
            m = self._cum_mask(hi_bin) & ~self._cum_mask(lo_bin - 1)
            exact = hi_bin < len(self.uppers) and \
                self.uppers[hi_bin] == hi and self.kind == _KIND_INT \
                and lo_bin == 0
            return m & _mask_of(self.exists_pos, self.num_rows), exact
        v = _norm_literal(literal, self.kind) \
            if op in ("eq", "lt", "le", "gt", "ge") else None
        if v is None:
            return None, False
        ex = _mask_of(self.exists_pos, self.num_rows)
        empty = np.zeros(self.num_rows, dtype=bool)
        mn = self.min_val
        mx = self.uppers[-1] if self.uppers else mn
        if self.uppers:
            # exact bound short-circuits: outside [min, max] is provable
            if (op == "lt" and v <= mn) or (op == "le" and v < mn) or \
                    (op == "gt" and v >= mx) or (op == "ge" and v > mx) \
                    or (op == "eq" and (v < mn or v > mx)):
                return empty, True
        i = bisect.bisect_left(self.uppers, v)
        if op in ("lt", "le"):
            return self._cum_mask(i) & ex, False
        if op in ("gt", "ge"):
            return ~self._cum_mask(i - 1) & ex, False
        if op == "eq":
            return (self._cum_mask(i) & ~self._cum_mask(i - 1)) & ex, False
        return None, False

    def serialize(self) -> bytes:
        parts = [struct.pack("<IBI", self.num_rows, self.kind,
                             len(self.uppers)),
                 struct.pack("<q" if self.kind == _KIND_INT else "<d",
                             self.min_val)]
        ex = serialize_roaring32(self.exists_pos)
        parts.append(struct.pack("<I", len(ex)))
        parts.append(ex)
        for u, pos in zip(self.uppers, self.cum_pos):
            ub = _encode_literal(u, self.kind)
            pb = serialize_roaring32(pos)
            parts.append(struct.pack("<HI", len(ub), len(pb)))
            parts.append(ub)
            parts.append(pb)
        return b"".join(parts)

    @staticmethod
    def deserialize(data: bytes) -> "RangeBitmapIndex":
        num_rows, kind, nb = struct.unpack_from("<IBI", data, 0)
        p = 9
        (min_val,) = struct.unpack_from(
            "<q" if kind == _KIND_INT else "<d", data, p)
        p += 8
        (elen,) = struct.unpack_from("<I", data, p)
        p += 4
        exists_pos = deserialize_roaring32(data[p:p + elen])
        p += elen
        uppers, cum_pos = [], []
        for _ in range(nb):
            ulen, plen = struct.unpack_from("<HI", data, p)
            p += 6
            ub = data[p:p + ulen]
            p += ulen
            uppers.append(struct.unpack("<q" if kind == _KIND_INT
                                        else "<d", ub)[0])
            cum_pos.append(deserialize_roaring32(data[p:p + plen]))
            p += plen
        return RangeBitmapIndex(num_rows, kind, uppers, cum_pos,
                                exists_pos, min_val)
