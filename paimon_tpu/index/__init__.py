"""Table indexes: deletion vectors, dynamic-bucket hash index, file
indexes (bloom/bitmap).

reference: paimon-core/.../deletionvectors/, index/, fileindex/.
"""

from paimon_tpu.index.deletion_vector import (  # noqa: F401
    DeletionVector, DeletionVectorsIndexFile, read_deletion_vectors,
)
