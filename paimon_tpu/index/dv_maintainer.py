"""Row-level DELETE.

reference semantics:
- append tables: deletion vectors keyed by row position
  (deletionvectors/BucketedDvMaintainer.java + append DV support;
  flink DeleteAction / spark DeleteFromTableCommand)
- primary-key tables: -D records through the normal merge path

The DV path evaluates the predicate per physical file (vectorized Arrow
compute), merges the matching positions into the bucket's existing
deletion vectors, writes ONE roaring-wire index file per bucket and
commits index-manifest entries (old bucket DV entries deleted, new one
added) — readers then mask those positions during scan.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from paimon_tpu.index.deletion_vector import (
    DeletionVector, DeletionVectorsIndexFile,
)
from paimon_tpu.manifest import FileKind
from paimon_tpu.manifest.index_manifest import (
    DELETION_VECTORS_INDEX, IndexFileMeta, IndexManifestEntry,
)
from paimon_tpu.types import RowKind

__all__ = ["delete_where"]


def delete_where(table, predicate) -> Optional[int]:
    """Delete all rows matching `predicate`. Returns the snapshot id, or
    None when nothing matched."""
    if table.primary_keys:
        return _delete_pk(table, predicate)
    return _delete_append_dv(table, predicate)


def _delete_pk(table, predicate) -> Optional[int]:
    rows = table.to_arrow(predicate=predicate)
    if rows.num_rows == 0:
        return None
    wb = table.new_batch_write_builder()
    with wb.new_write(apply_defaults=False) as w:
        w.write_arrow(rows.select([f.name for f in table.schema.fields]),
                      row_kinds=np.full(rows.num_rows, RowKind.DELETE,
                                        np.int8))
        sid = wb.new_commit().commit(w.prepare_commit())
    return sid


def _delete_append_dv(table, predicate, max_retries: int = 5
                      ) -> Optional[int]:
    """Optimistic: DVs are computed against the latest snapshot and the
    commit asserts that snapshot is still latest — a concurrent commit
    forces a full replan so no concurrent deletes are lost."""
    from paimon_tpu.core.commit import CommitConflictError

    for _ in range(max_retries):
        try:
            return _delete_append_dv_once(table, predicate)
        except CommitConflictError:
            continue
    raise CommitConflictError(
        f"delete_where lost the race {max_retries} times; retry later")


def replace_bucket_dv_entries(fs_scan, pbytes, bucket: int,
                              bucket_dvs: Dict[str, DeletionVector],
                              prev_entries: List[IndexManifestEntry],
                              dv_index: DeletionVectorsIndexFile
                              ) -> List[IndexManifestEntry]:
    """Write the merged per-bucket DV file and emit the index-manifest
    DELETE (previous files of this bucket) + ADD (new file) entries —
    shared by predicate deletes and row-id deletes."""
    name, size, ranges = dv_index.write(bucket_dvs,
                                        path_factory=fs_scan.path_factory)
    total = sum(dv.cardinality() for dv in bucket_dvs.values())
    entries = [IndexManifestEntry(FileKind.DELETE, e.partition, e.bucket,
                                  e.index_file)
               for e in prev_entries
               if e.partition == pbytes and e.bucket == bucket]
    entries.append(IndexManifestEntry(
        FileKind.ADD, pbytes, bucket,
        IndexFileMeta(DELETION_VECTORS_INDEX, name, size, total,
                      dv_ranges=ranges)))
    return entries


def _delete_append_dv_once(table, predicate) -> Optional[int]:
    from paimon_tpu.core.kv_file import read_kv_file
    from paimon_tpu.core.read import evolve_table

    snapshot = table.snapshot_manager.latest_snapshot()
    if snapshot is None:
        return None
    # value-stats pruning: files that cannot match keep their DVs as-is
    scan = table.new_scan().with_value_filter(predicate)
    plan = scan.plan(snapshot)

    # previous DV entries per bucket (to merge + replace)
    prev_entries: List[IndexManifestEntry] = []
    if snapshot.index_manifest:
        prev_entries = [
            e for e in scan.index_manifest_file.read(snapshot.index_manifest)
            if e.index_file.index_type == DELETION_VECTORS_INDEX]

    dv_index = DeletionVectorsIndexFile(table.file_io,
                                        f"{table.path}/index")
    schema_cache = {table.schema.id: table.schema}
    index_entries: List[IndexManifestEntry] = []
    any_change = False
    from paimon_tpu.options import CoreOptions
    tracked = table.options.get(CoreOptions.ROW_TRACKING_ENABLED)
    for split in plan.splits:
        pbytes = scan._partition_codec.to_bytes(split.partition)
        bucket_dvs: Dict[str, DeletionVector] = dict(
            split.deletion_vectors or {})
        changed = False
        if tracked:
            # row-tracked files form evolution groups whose CURRENT
            # values merge across overlays; evaluate the predicate on
            # the merged view and key the DV on the group's anchor
            # (the only file whose DV the evolution read applies)
            changed = _delete_tracked_groups(
                table, split, predicate, bucket_dvs)
            if changed:
                any_change = True
                index_entries.extend(replace_bucket_dv_entries(
                    scan, pbytes, split.bucket, bucket_dvs,
                    prev_entries, dv_index))
            continue
        for meta in split.data_files:
            t = read_kv_file(table.file_io, scan.path_factory,
                             split.partition, split.bucket, meta, None,
                             None, schema=table.schema,
                             schema_manager=table.schema_manager)
            t = evolve_table(t, meta.schema_id, table.schema,
                             table.schema_manager, schema_cache)
            mask = _eval_predicate(predicate, t)
            existing = bucket_dvs.get(meta.file_name)
            if existing is not None:
                mask[existing.positions[existing.positions
                                        < len(mask)]] = False
            positions = np.flatnonzero(mask)
            if len(positions) == 0:
                continue
            changed = True
            dv = DeletionVector(positions)
            bucket_dvs[meta.file_name] = existing.merge(dv) \
                if existing is not None else dv
        if not changed:
            continue
        any_change = True
        index_entries.extend(replace_bucket_dv_entries(
            scan, pbytes, split.bucket, bucket_dvs, prev_entries,
            dv_index))

    if not any_change:
        return None
    from paimon_tpu.core.commit import FileStoreCommit
    commit = FileStoreCommit(table.file_io, table.path, table.schema,
                             table.options, branch=table.branch)
    return commit.commit([], index_entries=index_entries,
                         expected_latest_id=snapshot.id)


def _delete_tracked_groups(table, split, predicate, bucket_dvs) -> bool:
    """Predicate delete over evolution groups: read each row-range
    group's merged current values, mask, and DV the anchor file."""
    from paimon_tpu.core.append import AppendSplitRead
    from paimon_tpu.core.row_tracking import (
        anchor_of, group_row_ranges, read_evolution_group,
    )

    read = AppendSplitRead(table.file_io, table.path, table.schema,
                           table.options,
                           schema_manager=table.schema_manager)
    fields = sorted(set(predicate.fields()))
    changed = False
    for group in group_row_ranges(split.data_files):
        anchor = anchor_of(group)
        current = read_evolution_group(read, split, group, fields) \
            if anchor.first_row_id is not None or len(group) > 1 \
            else read.read_file(split, anchor, wanted=fields)
        mask = _eval_predicate(predicate, current)
        existing = bucket_dvs.get(anchor.file_name)
        if existing is not None:
            mask[existing.positions[existing.positions < len(mask)]] = \
                False
        positions = np.flatnonzero(mask)
        if len(positions) == 0:
            continue
        changed = True
        dv = DeletionVector(positions)
        bucket_dvs[anchor.file_name] = existing.merge(dv) \
            if existing is not None else dv
    return changed


def _eval_predicate(predicate, t: pa.Table) -> np.ndarray:
    """Boolean row mask of `predicate` over `t` (null -> False)."""
    import pyarrow.dataset as ds

    expr = predicate.to_arrow()
    out = ds.dataset(t).scanner(columns={"m": expr}).to_table()
    return np.asarray(out.column("m").combine_chunks().cast(pa.bool_())
                      .fill_null(False))
