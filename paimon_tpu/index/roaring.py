"""RoaringBitmap32 wire codec (org.roaringbitmap portable format).

reference: paimon-common/.../utils/RoaringBitmap32.java delegates to
org.roaringbitmap.RoaringBitmap.serialize/deserialize; the portable spec
(https://github.com/RoaringBitmap/RoaringFormatSpec) is:

little-endian; cookie 12346 (no run containers):
  [u32 cookie][u32 n_containers]
  n x [u16 key][u16 cardinality-1]
  n x [u32 byte offset of container from stream start]
  containers...
cookie low-16 == 12347 (has run containers): cookie high-16 = n-1,
  then a run-flag bitset of ceil(n/8) bytes, keys/cards, offsets only
  when n >= 4, containers.
Containers: array (sorted u16s) when cardinality <= 4096, else a 1024 x
u64 bitset; run containers are [u16 n_runs] + n_runs x [u16 start,
u16 length-1].

The codec works on numpy arrays of uint32 positions — vectorized
pack/unpack per container, no per-bit python loops.
"""

from __future__ import annotations

import struct
from typing import List

import numpy as np

__all__ = ["serialize_roaring32", "deserialize_roaring32"]

SERIAL_COOKIE_NO_RUNCONTAINER = 12346
SERIAL_COOKIE = 12347
NO_OFFSET_THRESHOLD = 4
ARRAY_MAX = 4096


def serialize_roaring32(positions: np.ndarray) -> bytes:
    """Serialize sorted unique uint32 positions (no run containers —
    always valid for any conforming reader)."""
    pos = np.unique(np.asarray(positions, dtype=np.uint64))
    if len(pos) and pos[-1] > 0xFFFFFFFF:
        raise ValueError(
            f"position {int(pos[-1])} exceeds the 32-bit roaring range "
            f"(reference BitmapDeletionVector rejects it too)")
    pos = pos.astype(np.uint32)
    keys = (pos >> np.uint32(16)).astype(np.uint16)
    lows = (pos & np.uint32(0xFFFF)).astype(np.uint16)
    uk, starts = np.unique(keys, return_index=True)
    n = len(uk)
    bounds = np.append(starts, len(pos))

    header = struct.pack("<II", SERIAL_COOKIE_NO_RUNCONTAINER, n)
    keycards = b"".join(
        struct.pack("<HH", int(uk[i]),
                    int(bounds[i + 1] - bounds[i] - 1))
        for i in range(n))
    containers: List[bytes] = []
    for i in range(n):
        vals = lows[bounds[i]:bounds[i + 1]]
        if len(vals) <= ARRAY_MAX:
            containers.append(vals.astype("<u2").tobytes())
        else:
            words = np.zeros(1024, dtype=np.uint64)
            v = vals.astype(np.uint32)
            np.bitwise_or.at(words, v >> np.uint32(6),
                             np.uint64(1) << (v & np.uint32(63)).astype(
                                 np.uint64))
            containers.append(words.astype("<u8").tobytes())
    offset0 = len(header) + len(keycards) + 4 * n
    offsets = []
    off = offset0
    for c in containers:
        offsets.append(off)
        off += len(c)
    offsets_b = b"".join(struct.pack("<I", o) for o in offsets)
    return header + keycards + offsets_b + b"".join(containers)


def deserialize_roaring32(data: bytes) -> np.ndarray:
    """-> sorted uint32 positions. Handles array, bitmap and run
    containers, both cookie layouts."""
    (cookie,) = struct.unpack_from("<I", data, 0)
    if (cookie & 0xFFFF) == SERIAL_COOKIE:
        n = (cookie >> 16) + 1
        has_run = True
        p = 4
        bitset_len = (n + 7) // 8
        run_flags = np.unpackbits(
            np.frombuffer(data, np.uint8, bitset_len, p),
            bitorder="little")[:n].astype(bool)
        p += bitset_len
    elif cookie == SERIAL_COOKIE_NO_RUNCONTAINER:
        (n,) = struct.unpack_from("<I", data, 4)
        has_run = False
        run_flags = np.zeros(n, dtype=bool)
        p = 8
    else:
        raise ValueError(f"Not a RoaringBitmap32 (cookie {cookie})")

    kc = np.frombuffer(data, "<u2", 2 * n, p).reshape(n, 2)
    keys = kc[:, 0].astype(np.uint32)
    cards = kc[:, 1].astype(np.int64) + 1
    p += 4 * n
    if not has_run or n >= NO_OFFSET_THRESHOLD:
        p += 4 * n          # offsets (containers follow sequentially)

    out: List[np.ndarray] = []
    for i in range(n):
        base = keys[i] << np.uint32(16)
        if run_flags[i]:
            (n_runs,) = struct.unpack_from("<H", data, p)
            p += 2
            runs = np.frombuffer(data, "<u2", 2 * n_runs, p) \
                .reshape(n_runs, 2).astype(np.int64)
            p += 4 * n_runs
            vals = np.concatenate([
                np.arange(s, s + ln + 1, dtype=np.uint32)
                for s, ln in runs]) if n_runs else \
                np.zeros(0, np.uint32)
        elif cards[i] <= ARRAY_MAX:
            vals = np.frombuffer(data, "<u2", int(cards[i]), p) \
                .astype(np.uint32)
            p += 2 * int(cards[i])
        else:
            words = np.frombuffer(data, "<u8", 1024, p)
            p += 8 * 1024
            bits = np.unpackbits(words.view(np.uint8),
                                 bitorder="little")
            vals = np.flatnonzero(bits).astype(np.uint32)
        out.append(base | vals)
    if not out:
        return np.zeros(0, dtype=np.uint32)
    return np.concatenate(out)


def serialize_roaring64(positions: "np.ndarray") -> bytes:
    """RoaringBitmap64 portable wire format (reference
    utils/RoaringBitmap64.java -> Roaring64NavigableMap portable
    serialization): u64 LE bucket count, then per bucket the u32 high
    word + the bucket's roaring32 bytes, highs ascending."""
    positions = np.asarray(positions, dtype=np.uint64)
    positions = np.unique(positions)
    highs = (positions >> np.uint64(32)).astype(np.uint32)
    lows = (positions & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    parts = [struct.pack("<Q", len(np.unique(highs)))]
    for h in np.unique(highs):
        sel = highs == h
        parts.append(struct.pack("<I", int(h)))
        parts.append(serialize_roaring32(lows[sel]))
    return b"".join(parts)


def deserialize_roaring64(data: bytes) -> "np.ndarray":
    mv = memoryview(data)
    (n,) = struct.unpack_from("<Q", data, 0)
    p = 8
    out: List[np.ndarray] = []
    for _ in range(n):
        (high,) = struct.unpack_from("<I", data, p)
        p += 4
        end = p + _roaring32_size(data, p)
        # memoryview slice: no tail copy per bucket
        lows = deserialize_roaring32(mv[p:end])
        p = end
        out.append((np.uint64(high) << np.uint64(32))
                   | lows.astype(np.uint64))
    if not out:
        return np.zeros(0, dtype=np.uint64)
    return np.concatenate(out)


def _roaring32_size(data: bytes, off: int) -> int:
    """Byte length of the roaring32 stream starting at `off` (needed
    when streams are concatenated, as in roaring64); computed from the
    header + per-container cardinalities without copying the payload."""
    (cookie,) = struct.unpack_from("<I", data, off)
    if (cookie & 0xFFFF) == SERIAL_COOKIE:
        n = (cookie >> 16) + 1
        p = off + 4 + (n + 7) // 8
        has_offsets = n >= NO_OFFSET_THRESHOLD
        flags = np.frombuffer(data, np.uint8, (n + 7) // 8, off + 4)
        run_flags = np.unpackbits(flags, bitorder="little")[:n]
    elif cookie == SERIAL_COOKIE_NO_RUNCONTAINER:
        (n,) = struct.unpack_from("<I", data, off + 4)
        p = off + 8
        has_offsets = True
        run_flags = None
    else:
        raise ValueError(f"bad roaring cookie {cookie}")
    keys_cards = np.frombuffer(data, "<u2", 2 * n, p).reshape(n, 2)
    p += 4 * n
    if has_offsets:
        p += 4 * n
    end = p
    for i in range(n):
        card = int(keys_cards[i, 1]) + 1
        if run_flags is not None and run_flags[i]:
            (n_runs,) = struct.unpack_from("<H", data, end)
            end += 2 + 4 * n_runs
        elif card <= ARRAY_MAX:
            end += 2 * card
        else:
            end += 8 * 1024
    return end - off
