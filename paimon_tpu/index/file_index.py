"""Unified per-file column-index container + predicate evaluator.

reference: paimon-common/src/main/java/org/apache/paimon/fileindex/
FileIndexFormat.java (multi-column multi-index container),
FileIndexPredicate.java + io/FileIndexEvaluator (skip decision), and
reader row-selection via bitmap results
(fileindex/bitmap/BitmapIndexResult.java).

Blob layout (versioned, superset of the round-1 bloom-only v1 format):

  v1: "PTFI" 0x01 then (name_len u16, blob_len u32, name, bloom_blob)*
  v2: "PTFI" 0x02 then (type u8, name_len u16, blob_len u32, name, blob)*

Types: 0 bloom, 1 bitmap, 2 bit-sliced, 3 range-bitmap.  Small blobs
embed in the manifest entry (DataFileMeta.embedded_index); large ones
spill to a `<data-file>.index` sidecar — same placement rule as v1.

Evaluation returns dense bool selections with superset semantics: every
mask is a superset of the truly-matching rows, so an empty mask proves
the file irrelevant (skip) and a non-empty mask is a safe row prefilter
(the read path re-applies the exact predicate after).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import pyarrow as pa

from paimon_tpu.index.bitmap import BSIIndex, BitmapIndex, RangeBitmapIndex
from paimon_tpu.index.bloom import BloomFilter, hash_value

__all__ = ["FileIndexes", "build_indexes_blob", "read_indexes_blob",
           "evaluate_skip", "row_selection", "INDEX_TYPES"]

_MAGIC = b"PTFI"
_V2 = 2

TYPE_BLOOM, TYPE_BITMAP, TYPE_BSI, TYPE_RANGE = 0, 1, 2, 3
INDEX_TYPES = {
    "bloom-filter": TYPE_BLOOM,
    "bitmap": TYPE_BITMAP,
    "bsi": TYPE_BSI,
    "range-bitmap": TYPE_RANGE,
}
_DESERIALIZERS = {
    TYPE_BLOOM: BloomFilter.deserialize,
    TYPE_BITMAP: BitmapIndex.deserialize,
    TYPE_BSI: BSIIndex.deserialize,
    TYPE_RANGE: RangeBitmapIndex.deserialize,
}


class FileIndexes:
    """column -> {type tag -> index object}."""

    def __init__(self):
        self.by_column: Dict[str, Dict[int, object]] = {}

    def add(self, column: str, type_tag: int, index):
        self.by_column.setdefault(column, {})[type_tag] = index

    def __bool__(self):
        return bool(self.by_column)


def build_indexes_blob(table: pa.Table, spec: Dict[str, List[str]],
                       bloom_fpp: float = 0.01) -> Optional[bytes]:
    """spec: index-type name -> column list (e.g. {"bitmap": ["city"]})."""
    import struct
    entries = []

    def emit(type_tag: int, column: str, blob: bytes):
        cname = column.encode("utf-8")
        entries.append(struct.pack("<BHI", type_tag, len(cname), len(blob))
                       + cname + blob)

    for c in spec.get("bloom-filter", []):
        if c not in table.column_names:
            continue
        try:
            from paimon_tpu.index.bloom import hash_column
            hashes = hash_column(table.column(c))
        except ValueError:
            continue
        emit(TYPE_BLOOM, c, BloomFilter.build(hashes, bloom_fpp).serialize())
    for c in spec.get("bitmap", []):
        if c not in table.column_names:
            continue
        try:
            idx = BitmapIndex.build(table.column(c))
        except ValueError:
            continue
        if idx is not None:
            emit(TYPE_BITMAP, c, idx.serialize())
    for c in spec.get("bsi", []):
        if c not in table.column_names:
            continue
        try:
            idx = BSIIndex.build(table.column(c))
        except ValueError:
            continue
        if idx is not None:
            emit(TYPE_BSI, c, idx.serialize())
    for c in spec.get("range-bitmap", []):
        if c not in table.column_names:
            continue
        try:
            idx = RangeBitmapIndex.build(table.column(c))
        except ValueError:
            continue
        if idx is not None:
            emit(TYPE_RANGE, c, idx.serialize())
    if not entries:
        return None
    return _MAGIC + bytes([_V2]) + b"".join(entries)


def read_indexes_blob(data: Optional[bytes]) -> FileIndexes:
    import struct
    fi = FileIndexes()
    if not data or data[:4] != _MAGIC:
        return fi
    version = data[4]
    p = 5
    if version == 1:                      # bloom-only legacy layout
        while p < len(data):
            nlen, blen = struct.unpack_from("<HI", data, p)
            p += 6
            name = data[p:p + nlen].decode("utf-8")
            p += nlen
            fi.add(name, TYPE_BLOOM,
                   BloomFilter.deserialize(data[p:p + blen]))
            p += blen
        return fi
    while p < len(data):
        type_tag, nlen, blen = struct.unpack_from("<BHI", data, p)
        p += 7
        name = data[p:p + nlen].decode("utf-8")
        p += nlen
        deser = _DESERIALIZERS.get(type_tag)
        if deser is not None:
            fi.add(name, type_tag, deser(data[p:p + blen]))
        p += blen
    return fi


# -- evaluation --------------------------------------------------------------

# structures able to produce row selections, in preference order
_SELECTIVE = (TYPE_BITMAP, TYPE_BSI, TYPE_RANGE)


def _leaf_mask(fi: FileIndexes, leaf, arrow_type=None) \
        -> Optional[np.ndarray]:
    idxs = fi.by_column.get(leaf.field)
    if not idxs:
        return None
    for tag in _SELECTIVE:
        idx = idxs.get(tag)
        if idx is None:
            continue
        mask, _exact = idx.eval(leaf.op, leaf.literal)
        if mask is not None:
            return mask
    bf = idxs.get(TYPE_BLOOM)
    if isinstance(bf, BloomFilter) and arrow_type is not None and \
            leaf.op in ("eq", "in"):
        lits = leaf.literal if leaf.op == "in" else [leaf.literal]
        try:
            hit = any(bf.might_contain(hash_value(v, arrow_type))
                      for v in lits)
        except (ValueError, pa.ArrowInvalid):
            return None
        if not hit:
            return np.zeros(1, dtype=bool)   # provably no match
    return None


def _eval(fi: FileIndexes, pred, types: Dict[str, pa.DataType]) \
        -> Optional[np.ndarray]:
    from paimon_tpu.predicate import Compound, Leaf
    if isinstance(pred, Leaf):
        return _leaf_mask(fi, pred, types.get(pred.field))
    if isinstance(pred, Compound):
        if pred.op == "and":
            masks = [m for m in (_eval(fi, c, types) for c in pred.children)
                     if m is not None]
            if not masks:
                return None
            n = max(len(m) for m in masks)
            out = np.ones(n, dtype=bool)
            for m in masks:
                out &= m if len(m) == n else \
                    (np.zeros(n, bool) if not m.any() else np.ones(n, bool))
            return out
        if pred.op == "or":
            masks = [_eval(fi, c, types) for c in pred.children]
            if any(m is None for m in masks):
                return None
            n = max(len(m) for m in masks)
            out = np.zeros(n, dtype=bool)
            for m in masks:
                out |= m if len(m) == n else \
                    (np.ones(n, bool) if m.any() else np.zeros(n, bool))
            return out
        return None                        # NOT of a superset is unsafe
    return None


def evaluate_skip(fi: FileIndexes, pred,
                  types: Optional[Dict[str, pa.DataType]] = None) -> bool:
    """True when the indexes prove no row of the file can match."""
    if not fi or pred is None:
        return False
    mask = _eval(fi, pred, types or {})
    return mask is not None and not mask.any()


def row_selection(fi: FileIndexes, pred, num_rows: int,
                  types: Optional[Dict[str, pa.DataType]] = None
                  ) -> Optional[np.ndarray]:
    """Superset row mask for prefiltering, or None when indexes cannot
    narrow the file (bloom-only hits, unsupported ops, ...)."""
    if not fi or pred is None:
        return None
    mask = _eval(fi, pred, types or {})
    if mask is None or len(mask) != num_rows:
        return None
    return mask
