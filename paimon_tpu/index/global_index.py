"""Sorted key -> row-id global index for row-tracked append tables.

reference: paimon-common/src/main/java/org/apache/paimon/globalindex/
sorted/ (sorted run files probed by binary search) and btree/ (the
B+-tree variant); union/offset readers combine runs.  The TPU-first
shape collapses this to one sorted columnar run per build: lookups are
a single vectorized np.searchsorted over the key column — one probe
per query key, no tree walks — and rebuilds are a full-column argsort,
which the device sort kernel handles at millions of rows.

Layout: `{table}/index/global/{column}/index-{snapshot_id}.parquet`
holding (key, row_id) sorted by key, plus `meta.json` recording the
snapshot the index was built from (stale indexes rebuild lazily).
"""

from __future__ import annotations

import json
from typing import Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

__all__ = ["SortedGlobalIndex"]


class SortedGlobalIndex:
    def __init__(self, table, column: str, keys: pa.Array,
                 row_ids: np.ndarray, snapshot_id: int):
        self.table = table
        self.column = column
        self.keys = keys                  # sorted
        self.row_ids = row_ids            # aligned to keys
        self.snapshot_id = snapshot_id
        self._np_keys = None

    # -- build / persist -----------------------------------------------------

    @staticmethod
    def _dir(table, column: str) -> str:
        return f"{table.path}/index/global/{column}"

    @classmethod
    def load_or_build(cls, table, column: str,
                      rebuild: bool = False) -> "SortedGlobalIndex":
        latest = table.latest_snapshot()
        if latest is None:
            raise ValueError("empty table has no index")
        d = cls._dir(table, column)
        meta_path = f"{d}/meta.json"
        if not rebuild:
            try:
                meta = json.loads(table.file_io.read_bytes(meta_path))
                if meta["snapshot_id"] == latest.id and \
                        meta["column"] == column:
                    import io as _io
                    import pyarrow.parquet as pq
                    data = table.file_io.read_bytes(
                        f"{d}/{meta['file']}")
                    t = pq.read_table(_io.BytesIO(data))
                    return cls(table, column,
                               t.column("key").combine_chunks(),
                               np.asarray(t.column("row_id")),
                               meta["snapshot_id"])
            except (FileNotFoundError, OSError, KeyError):
                pass
        return cls.build(table, column)

    @classmethod
    def build(cls, table, column: str) -> "SortedGlobalIndex":
        from paimon_tpu.core.row_tracking import ROW_ID_COL
        latest = table.latest_snapshot()
        t = table.to_arrow(projection=[column], with_row_ids=True)
        # files written before row-tracking.enabled have no ids — they
        # cannot be indexed, so they drop out rather than poison the run
        t = t.filter(pc.is_valid(t.column(ROW_ID_COL)))
        keys = t.column(column).combine_chunks()
        rids = np.asarray(t.column(ROW_ID_COL).combine_chunks()
                          .cast(pa.int64()))
        order = np.asarray(pc.sort_indices(keys)).astype(np.int64)
        keys = keys.take(pa.array(order))
        rids = rids[order]

        import io as _io
        import pyarrow.parquet as pq
        buf = _io.BytesIO()
        pq.write_table(pa.table({"key": keys,
                                 "row_id": pa.array(rids, pa.int64())}),
                       buf)
        d = cls._dir(table, column)
        fname = f"index-{latest.id}.parquet"
        table.file_io.write_bytes(f"{d}/{fname}", buf.getvalue(),
                                  overwrite=True)
        table.file_io.write_bytes(
            f"{d}/meta.json",
            json.dumps({"snapshot_id": latest.id, "column": column,
                        "file": fname,
                        "num_rows": len(rids)}).encode(),
            overwrite=True)
        return cls(table, column, keys, rids, latest.id)

    # -- lookups -------------------------------------------------------------

    def _np(self) -> np.ndarray:
        if self._np_keys is None:
            self._np_keys = np.asarray(self.keys)
        return self._np_keys

    def lookup(self, values: Sequence) -> np.ndarray:
        """First row id per query value (-1 = absent), one vectorized
        searchsorted for the whole batch."""
        ks = self._np()
        q = np.asarray(list(values), dtype=ks.dtype if len(ks) else None)
        if len(ks) == 0:
            return np.full(len(q), -1, dtype=np.int64)
        pos = np.searchsorted(ks, q, side="left")
        pos_c = np.minimum(pos, len(ks) - 1)
        hit = (pos < len(ks)) & (ks[pos_c] == q)
        out = np.where(hit, self.row_ids[pos_c], -1)
        return out.astype(np.int64)

    def lookup_all(self, value) -> np.ndarray:
        """Every row id bearing `value` (duplicate keys allowed)."""
        ks = self._np()
        lo = np.searchsorted(ks, value, side="left")
        hi = np.searchsorted(ks, value, side="right")
        return self.row_ids[lo:hi].astype(np.int64)
