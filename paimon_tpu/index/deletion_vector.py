"""Deletion vectors: per-file bitmaps of deleted row positions,
wire-compatible with the reference.

reference: paimon-core/.../deletionvectors/BitmapDeletionVector.java
(RoaringBitmap32 + MAGIC 1581511376), DeletionVectorsIndexFile.java
(VERSION byte 1, then per DV: [i32 BE length][i32 BE magic][roaring
bytes][i32 BE crc32]; index manifest records (offset, length,
cardinality) per data file).

In-memory the positions live as a sorted numpy array — the apply path
(mask rows during scan) is a vectorized mask, which numpy/XLA handle
better than roaring containers; roaring is only the wire format.
"""

from __future__ import annotations

import struct
import uuid
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

from paimon_tpu.fs import FileIO
from paimon_tpu.index.roaring import (
    deserialize_roaring32, serialize_roaring32,
)

__all__ = ["DeletionVector", "DeletionVectorsIndexFile",
           "read_deletion_vectors"]

MAGIC_V1 = 1581511376
VERSION_V1 = 1


class DeletionVector:
    """Sorted set of deleted row positions within one data file."""

    def __init__(self, positions: Optional[np.ndarray] = None):
        if positions is None:
            positions = np.zeros(0, dtype=np.int64)
        self.positions = np.unique(np.asarray(positions, dtype=np.int64))

    def delete(self, pos: int):
        self.positions = np.unique(np.append(self.positions, pos))

    def merge(self, other: "DeletionVector") -> "DeletionVector":
        return DeletionVector(np.concatenate([self.positions,
                                              other.positions]))

    def is_deleted(self, pos: int) -> bool:
        i = np.searchsorted(self.positions, pos)
        return bool(i < len(self.positions) and self.positions[i] == pos)

    def cardinality(self) -> int:
        return len(self.positions)

    def is_empty(self) -> bool:
        return len(self.positions) == 0

    def keep_mask(self, num_rows: int) -> np.ndarray:
        """bool[num_rows], False where deleted -- vectorized apply
        (role of reference ApplyDeletionVectorReader)."""
        mask = np.ones(num_rows, dtype=bool)
        valid = self.positions[(self.positions >= 0)
                               & (self.positions < num_rows)]
        mask[valid] = False
        return mask

    # -- wire format (reference BitmapDeletionVector.serializeTo) ------------

    def serialize(self) -> bytes:
        """[i32 BE length][i32 BE MAGIC + roaring bytes][i32 BE crc32]."""
        # int64 positions pass through unchanged: the roaring codec
        # raises on values beyond the 32-bit range instead of wrapping
        body = struct.pack(">i", MAGIC_V1) + \
            serialize_roaring32(self.positions)
        crc = zlib.crc32(body) & 0xFFFFFFFF
        return struct.pack(">i", len(body)) + body + struct.pack(">I", crc)

    @staticmethod
    def deserialize(data: bytes) -> "DeletionVector":
        (length,) = struct.unpack_from(">i", data, 0)
        (magic,) = struct.unpack_from(">i", data, 4)
        if magic != MAGIC_V1:
            raise ValueError(f"Invalid deletion vector magic {magic}")
        body = data[4:4 + length]
        if len(data) >= 4 + length + 4:
            (crc,) = struct.unpack_from(">I", data, 4 + length)
            actual = zlib.crc32(body) & 0xFFFFFFFF
            if crc != actual:
                raise ValueError(
                    f"Deletion vector checksum mismatch "
                    f"(stored {crc}, computed {actual})")
        positions = deserialize_roaring32(body[4:])
        return DeletionVector(positions.astype(np.int64))


class DeletionVectorsIndexFile:
    """Packs several files' DVs into one index file; ranges recorded in the
    index manifest (reference DeletionVectorsIndexFile.java)."""

    def __init__(self, file_io: FileIO, index_dir: str):
        self.file_io = file_io
        self.index_dir = index_dir.rstrip("/")

    def write(self, dvs: Dict[str, DeletionVector],
              name: Optional[str] = None,
              path_factory=None
              ) -> Tuple[str, int, Dict[str, Tuple[int, int, int]]]:
        """-> (file_name, file_size, ranges {data_file: (offset, len,
        cardinality)}). Layout: VERSION byte then DV entries; offsets
        point at each entry's length field, length covers magic+bitmap
        (reference DeletionVectorMeta semantics)."""
        if name is None:
            name = path_factory.new_index_file_name() if path_factory \
                else f"index-{uuid.uuid4()}-0"
        blobs = [bytes([VERSION_V1])]
        ranges: Dict[str, Tuple[int, int, int]] = {}
        offset = 1
        for data_file, dv in dvs.items():
            blob = dv.serialize()
            # recorded length excludes the 4-byte length prefix and crc
            ranges[data_file] = (offset, len(blob) - 8, dv.cardinality())
            blobs.append(blob)
            offset += len(blob)
        payload = b"".join(blobs)
        path = f"{self.index_dir}/{name}"
        self.file_io.write_bytes(path, payload, overwrite=False)
        return name, len(payload), ranges

    def read(self, name: str,
             ranges: Dict[str, Tuple[int, int, int]]
             ) -> Dict[str, DeletionVector]:
        return read_deletion_vectors(
            self.file_io, f"{self.index_dir}/{name}", ranges)


def read_deletion_vectors(file_io: FileIO, index_path: str,
                          ranges: Dict[str, Tuple[int, int, int]]
                          ) -> Dict[str, DeletionVector]:
    data = file_io.read_bytes(index_path)
    if data[:1] != bytes([VERSION_V1]):
        raise ValueError(f"Unknown DV index version {data[:1]!r}")
    out = {}
    for f, (off, ln, _) in ranges.items():
        out[f] = DeletionVector.deserialize(data[off:off + ln + 8])
    return out
