"""Deletion vectors: per-file bitmaps of deleted row positions.

reference: paimon-core/.../deletionvectors/ (BitmapDeletionVector over
RoaringBitmap32, DeletionVectorsIndexFile packing several bitmaps into one
index file). This implementation stores positions as a sorted uint32/uint64
numpy array serialized little-endian with a small header -- the apply path
(mask rows during scan) is a vectorized isin/searchsorted, which XLA/numpy
handle better than roaring containers.

Serialization is NOT roaring-compatible yet; cross-reading reference DV
files is a follow-up (magic number differs so misreads fail fast).
"""

from __future__ import annotations

import struct
from typing import Dict, Optional, Tuple

import numpy as np

from paimon_tpu.fs import FileIO

__all__ = ["DeletionVector", "DeletionVectorsIndexFile",
           "read_deletion_vectors"]

_MAGIC = 0x50544456  # "PTDV"


class DeletionVector:
    """Sorted set of deleted row positions within one data file."""

    def __init__(self, positions: Optional[np.ndarray] = None):
        if positions is None:
            positions = np.zeros(0, dtype=np.int64)
        self.positions = np.unique(np.asarray(positions, dtype=np.int64))

    def delete(self, pos: int):
        self.positions = np.unique(np.append(self.positions, pos))

    def merge(self, other: "DeletionVector") -> "DeletionVector":
        return DeletionVector(np.concatenate([self.positions,
                                              other.positions]))

    def is_deleted(self, pos: int) -> bool:
        i = np.searchsorted(self.positions, pos)
        return bool(i < len(self.positions) and self.positions[i] == pos)

    def cardinality(self) -> int:
        return len(self.positions)

    def is_empty(self) -> bool:
        return len(self.positions) == 0

    def keep_mask(self, num_rows: int) -> np.ndarray:
        """bool[num_rows], False where deleted -- vectorized apply
        (role of reference ApplyDeletionVectorReader)."""
        mask = np.ones(num_rows, dtype=bool)
        valid = self.positions[(self.positions >= 0)
                               & (self.positions < num_rows)]
        mask[valid] = False
        return mask

    def serialize(self) -> bytes:
        data = self.positions.astype("<i8").tobytes()
        return struct.pack("<II", _MAGIC, len(self.positions)) + data

    @staticmethod
    def deserialize(data: bytes) -> "DeletionVector":
        magic, n = struct.unpack_from("<II", data, 0)
        if magic != _MAGIC:
            raise ValueError("Not a paimon-tpu deletion vector "
                             f"(magic {magic:#x})")
        positions = np.frombuffer(data, dtype="<i8", count=n, offset=8)
        return DeletionVector(positions.copy())


class DeletionVectorsIndexFile:
    """Packs several files' DVs into one index file; ranges recorded in the
    index manifest (reference DeletionVectorsIndexFile.java)."""

    def __init__(self, file_io: FileIO, index_dir: str):
        self.file_io = file_io
        self.index_dir = index_dir.rstrip("/")

    def write(self, name: str, dvs: Dict[str, DeletionVector]
              ) -> Tuple[str, int, Dict[str, Tuple[int, int, int]]]:
        """-> (file_name, file_size, ranges {data_file: (offset, len,
        cardinality)})."""
        blobs = []
        ranges: Dict[str, Tuple[int, int, int]] = {}
        offset = 0
        for data_file, dv in dvs.items():
            blob = dv.serialize()
            ranges[data_file] = (offset, len(blob), dv.cardinality())
            blobs.append(blob)
            offset += len(blob)
        payload = b"".join(blobs)
        path = f"{self.index_dir}/{name}"
        self.file_io.write_bytes(path, payload, overwrite=False)
        return name, len(payload), ranges

    def read(self, name: str,
             ranges: Dict[str, Tuple[int, int, int]]
             ) -> Dict[str, DeletionVector]:
        data = self.file_io.read_bytes(f"{self.index_dir}/{name}")
        return {f: DeletionVector.deserialize(data[off:off + ln])
                for f, (off, ln, _) in ranges.items()}


def read_deletion_vectors(file_io: FileIO, index_path: str,
                          ranges: Dict[str, Tuple[int, int, int]]
                          ) -> Dict[str, DeletionVector]:
    data = file_io.read_bytes(index_path)
    return {f: DeletionVector.deserialize(data[off:off + ln])
            for f, (off, ln, _) in ranges.items()}
