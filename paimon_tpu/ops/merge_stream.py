"""Streamed k-way merge in bounded key windows.

Kills the whole-bucket memory cliff (SURVEY hard part (d)): instead of
concatenating every run of a bucket in RAM and padding to a power of two,
runs stream in as bounded Arrow chunks, and the device kernel merges one
key WINDOW at a time:

1. every run keeps a small buffer of decoded chunks
2. the window bound = MIN over non-exhausted runs of their last buffered
   key — every key strictly below it is fully present in the buffers
3. rows below the bound are cut from all buffers (run order preserved),
   merged with the normal segmented-sort kernel, and emitted
4. buffers refill; repeat until all runs drain, then flush the remainder

Windows partition the keyspace, so per-key semantics (dedup last-by-seq,
partial-update, aggregation) are EXACTLY those of the one-shot merge:
a key's rows never straddle windows (the cut compares normalized-key
lanes, and prefix-equal truncated keys stay in one window together).

Peak memory ~ k_runs x chunk_rows + window, independent of bucket size.
This replaces the reference's record-at-a-time spillable MergeSorter
(mergetree/MergeSorter.java:112) with a columnar pipeline.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from paimon_tpu.ops.merge import merge_runs
from paimon_tpu.ops.normkey import NormalizedKeyEncoder

__all__ = ["merge_runs_streamed", "iter_merge_windows"]


def _cut_point(lanes: np.ndarray, bound: Tuple) -> int:
    """Rows with key lanes lexicographically < bound form a PREFIX of a
    key-sorted buffer, so the cut is a binary search (O(L log n)), not a
    full vectorized compare over the chunk."""
    lo, hi = 0, lanes.shape[0]
    num_lanes = lanes.shape[1]
    while lo < hi:
        mid = (lo + hi) // 2
        row = lanes[mid]
        lt = False
        for i in range(num_lanes):
            ri = int(row[i])
            bi = int(bound[i])
            if ri != bi:
                lt = ri < bi
                break
        if lt:
            lo = mid + 1
        else:
            hi = mid
    return lo


class _RunState:
    def __init__(self, chunks: Iterator, key_cols: Sequence[str],
                 encoder: NormalizedKeyEncoder):
        self._chunks = chunks
        self.key_cols = list(key_cols)
        self.encoder = encoder
        # single-fixed-key tables: bounds and cuts compare the packed
        # u64 (one searchsorted) instead of lane tuples — the window
        # comparator consuming the single-int code (ops/ovc.py is the
        # same idea inside the merge itself)
        self.packed_mode = getattr(encoder, "packs_single_key", False)
        # (table, lanes, truncated, packed-u64-or-None) quads
        self.buffer: List[Tuple] = []
        self.exhausted = False

    @property
    def buffered_rows(self) -> int:
        return sum(item[0].num_rows for item in self.buffer)

    def fill_one(self) -> bool:
        if self.exhausted:
            return False
        try:
            item = next(self._chunks)
        except StopIteration:
            self.exhausted = True
            return False
        if isinstance(item, tuple):
            # pre-encoded upstream (e.g. inside a prefetch thread, so
            # the lane encode overlaps the merge):
            # (table, lanes, trunc[, packed])
            t, lanes, trunc = item[:3]
            packed = item[3] if len(item) > 3 else None
        else:
            t, lanes, trunc, packed = item, None, None, None
        if t.num_rows == 0:
            return self.fill_one()
        if lanes is None:
            lanes, trunc, packed = self.encoder.encode_table_ex(
                t, self.key_cols)
        elif packed is None and self.packed_mode:
            # upstream handed raw lanes: derive the packed key so every
            # buffered chunk cuts through the same u64 comparator
            mat = np.asarray(lanes)
            packed = (mat[:, 0].astype(np.uint64) << np.uint64(32)) \
                | mat[:, 1].astype(np.uint64)
        self.buffer.append((t, lanes, trunc, packed))
        return True

    def last_key(self) -> Optional[Tuple]:
        if not self.buffer:
            return None
        if self.packed_mode:
            return int(self.buffer[-1][3][-1])
        lanes = self.buffer[-1][1]
        return tuple(lanes[-1])

    def key_at(self, idx: int):
        """Key of the idx-th buffered row (run order), or None when
        fewer rows are buffered — the per-run window-size cap probe."""
        for t, lanes, _trunc, packed in self.buffer:
            n = t.num_rows
            if idx < n:
                if self.packed_mode:
                    return int(packed[idx])
                return tuple(lanes[idx])
            idx -= n
        return None

    def cut_lt(self, bound: Tuple) -> List[Tuple]:
        """Remove and return rows with key lanes < bound (a prefix of the
        buffer, since runs are key-sorted)."""
        head: List[Tuple] = []
        new_buffer: List[Tuple] = []
        for t, lanes, trunc, packed in self.buffer:
            if new_buffer:
                new_buffer.append((t, lanes, trunc, packed))  # past bound
                continue
            if self.packed_mode:
                k = int(np.searchsorted(packed, np.uint64(bound),
                                        side="left"))
            else:
                k = _cut_point(lanes, bound)
            if k == t.num_rows:
                head.append((t, lanes, trunc, packed))
            else:
                if k:
                    head.append((t.slice(0, k), lanes[:k], trunc[:k],
                                 packed[:k] if packed is not None
                                 else None))
                new_buffer.append((t.slice(k), lanes[k:], trunc[k:],
                                   packed[k:] if packed is not None
                                   else None))
        self.buffer = new_buffer
        return head

    def take_all(self) -> List[Tuple]:
        out = self.buffer
        self.buffer = []
        return out


def iter_merge_windows(
    run_chunk_iters: Sequence[Iterator],
    key_cols: Sequence[str],
    key_encoder: NormalizedKeyEncoder,
    stats: Optional[Dict[str, int]] = None,
    window_rows: Optional[int] = None,
) -> Iterator[List[Tuple]]:
    """Pull-based window stream: yields one run-ordered item list per key
    window, in ascending key order.  Each item is a (table, lanes,
    truncated, packed-u64-or-None) quad; the concatenation of a window's
    items holds every buffered row whose key is strictly below the
    window bound, so per-key merge semantics applied window-by-window
    equal the one-shot merge (keys never straddle windows).

    This is the generator form of ``merge_runs_streamed`` — the mesh
    compaction engine (parallel/mesh_engine.py) pulls one window per
    bucket lane per mesh step to build its [B, window] device batches,
    while the single-chip streamed rewrite keeps the push (emit) shape.

    `stats`, when given, records "peak_buffered_rows": the max total
    rows buffered across runs at any point — the observable that the
    bounded-host-RAM contract is tested against.

    `window_rows` caps each run's contribution per window: the bound is
    lowered to the smallest buffered key at row `window_rows` of any
    run, so a window holds ~k x window_rows rows instead of everything
    below the natural bound (whole-file chunks otherwise degenerate to
    ONE window holding nearly the entire bucket, serializing the
    downstream merge pipeline behind a single giant sort).  The lowered
    bound is an existing key, so the key-window invariant — a key's
    rows never straddle windows — is unchanged; windows where the cap
    makes no progress (one key group wider than the cap) fall back to
    the natural bound."""
    runs = [_RunState(it, key_cols, key_encoder)
            for it in run_chunk_iters]
    for r in runs:
        r.fill_one()

    while True:
        for r in runs:
            if not r.exhausted and not r.buffer:
                r.fill_one()
        if stats is not None:
            buffered = sum(r.buffered_rows for r in runs)
            if buffered > stats.get("peak_buffered_rows", 0):
                stats["peak_buffered_rows"] = buffered
        non_exhausted = [r for r in runs if not r.exhausted]
        if not non_exhausted:
            tail = []
            for r in runs:
                tail.extend(r.take_all())
            if tail:
                yield tail
            return
        bound = min(r.last_key() for r in non_exhausted)
        heads: List = []
        if window_rows:
            caps = [c for c in (r.key_at(window_rows) for r in runs)
                    if c is not None]
            if caps:
                cap = min(caps)
                if cap < bound:
                    for r in runs:          # run order = merge stability
                        heads.extend(r.cut_lt(cap))
                    if heads:
                        yield heads
                        continue
                    # a single key group wider than the cap: fall back
                    # to the natural bound below so the stream advances
        for r in runs:                      # run order = merge stability
            heads.extend(r.cut_lt(bound))
        if heads:
            yield heads
        else:
            # every buffered row >= bound: a key group spans entire
            # buffers; extend the runs sitting exactly at the bound
            progressed = False
            for r in non_exhausted:
                if r.last_key() == bound:
                    progressed |= r.fill_one()
                    if r.exhausted:
                        progressed = True
            if not progressed:              # defensive: cannot happen
                tail = []
                for r in runs:
                    tail.extend(r.take_all())
                if tail:
                    yield tail
                return


def merge_runs_streamed(
    run_chunk_iters: Sequence[Iterator],
    key_cols: Sequence[str],
    key_encoder: NormalizedKeyEncoder,
    emit: Callable[[pa.Table], None],
    merge_window: Callable[[List], pa.Table],
    pass_encoded: bool = False,
    window_rows: Optional[int] = None,
) -> None:
    """Stream-merge k runs (oldest first) and emit merged key windows in
    ascending key order.

    run_chunk_iters: one iterator of key-sorted KV chunks per run; each
    item is a pa.Table or a pre-encoded (table, lanes, truncated[,
    packed]) tuple.  merge_window: merges a window's run-ordered chunk
    list into the final rows (e.g. a merge_runs(...).take() or
    merge_runs_agg closure).  With pass_encoded=True it receives the
    (table, lanes, truncated, packed) tuples so the kernel can skip
    re-encoding (and re-packing) the window's keys."""
    for items in iter_merge_windows(run_chunk_iters, key_cols,
                                    key_encoder,
                                    window_rows=window_rows):
        emit(merge_window(items if pass_encoded
                          else [item[0] for item in items]))
