"""Keyed diff of two merged (key-unique) KV tables -> changelog rows.

This is the data-parallel heart of the compaction changelog producers:

- changelog-producer=full-compaction diffs the previous top level against
  the new full-compaction result (reference
  FullChangelogMergeTreeCompactRewriter / FullChangelogMergeFunctionWrapper)
- changelog-producer=lookup diffs the pre-compaction visible state of
  levels >0 against the post-compaction state, restricted to the keys
  touched by the incoming L0 records (reference
  LookupChangelogMergeFunctionWrapper.java:54 + LookupLevels.lookup)

Keys are compared via JOINT integer ranks: the key lanes of every input
table go through one np.unique(axis=0) so equal keys share a rank across
tables (exact — prefix-truncated string keys get a disambiguation column
ranked on the full key).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from paimon_tpu.ops.merge import KIND_COL
from paimon_tpu.ops.normkey import NormalizedKeyEncoder
from paimon_tpu.types import RowKind

__all__ = ["joint_key_ranks", "keyed_changelog_diff"]


def joint_key_ranks(tables: Sequence[pa.Table], key_cols: Sequence[str],
                    encoder: NormalizedKeyEncoder) -> List[np.ndarray]:
    """Rank the keys of several tables in ONE order-preserving space:
    equal keys (across tables) share a rank; rank order == key order.
    Truncated string keys are disambiguated by full-key sub-ranks."""
    lanes_list, trunc_list = [], []
    for t in tables:
        lanes, trunc = encoder.encode_table(t, key_cols)
        lanes_list.append(lanes)
        trunc_list.append(trunc)
    sizes = [len(x) for x in lanes_list]
    all_lanes = np.concatenate(lanes_list) if sizes else \
        np.zeros((0, encoder.num_lanes), np.uint32)
    all_trunc = np.concatenate(trunc_list) if sizes else \
        np.zeros(0, bool)
    extra = np.zeros((len(all_lanes), 1), np.int64)
    if all_trunc.any():
        fulls = []
        for t, trunc in zip(tables, trunc_list):
            if not trunc.any():
                continue
            cols = [t.column(c) for c in key_cols]
            for i in np.flatnonzero(trunc):
                fulls.append(tuple(str(c[int(i)].as_py()) for c in cols))
        uniq = sorted(set(fulls))
        rank_of = {k: r + 1 for r, k in enumerate(uniq)}
        pos = 0
        fi = 0
        for t, trunc, n in zip(tables, trunc_list, sizes):
            for i in np.flatnonzero(trunc):
                extra[pos + int(i), 0] = rank_of[fulls[fi]]
                fi += 1
            pos += n
    mat = np.concatenate([all_lanes.astype(np.int64), extra], axis=1)
    _, inv = np.unique(mat, axis=0, return_inverse=True)
    out = []
    pos = 0
    for n in sizes:
        out.append(inv[pos:pos + n].astype(np.int64))
        pos += n
    return out


def keyed_changelog_diff(before: Optional[pa.Table], after: pa.Table,
                         key_cols: Sequence[str],
                         encoder: NormalizedKeyEncoder,
                         value_cols: Sequence[str],
                         restrict_table: Optional[pa.Table] = None
                         ) -> pa.Table:
    """Diff two key-unique KV tables (same KV layout) into changelog rows
    with _VALUE_KIND set to +I / -U / +U / -D.

    `restrict_table`: optional KV table; only keys occurring in it are
    diffed (the lookup producer's "keys touched by L0").
    Output ordered with each -U immediately before its +U."""
    if before is None:
        before = after.slice(0, 0)
    tables = [before, after] + ([restrict_table]
                                if restrict_table is not None else [])
    ranks = joint_key_ranks(tables, key_cols, encoder)
    rk_before, rk_after = ranks[0], ranks[1]

    if restrict_table is not None:
        allowed = np.unique(ranks[2])
        keep_b = np.isin(rk_before, allowed)
        keep_a = np.isin(rk_after, allowed)
        before = before.filter(pa.array(keep_b))
        after = after.filter(pa.array(keep_a))
        rk_before = rk_before[keep_b]
        rk_after = rk_after[keep_a]

    # align: both inputs are key-sorted and key-unique
    pos = np.searchsorted(rk_before, rk_after)
    pos_clipped = np.minimum(pos, max(len(rk_before) - 1, 0))
    in_before = np.zeros(len(rk_after), dtype=bool)
    if len(rk_before):
        in_before = rk_before[pos_clipped] == rk_after
    matched_before_pos = pos_clipped[in_before]
    only_before = np.ones(len(rk_before), dtype=bool)
    only_before[matched_before_pos] = False

    inserts = after.filter(pa.array(~in_before))
    deletes = before.filter(pa.array(only_before))

    # matched keys: emit -U/+U only when the value actually changed
    a_m = after.filter(pa.array(in_before))
    b_m = before.take(pa.array(matched_before_pos))
    if a_m.num_rows:
        differs = np.zeros(a_m.num_rows, dtype=bool)
        for c in value_cols:
            ca = a_m.column(c).combine_chunks()
            cb = b_m.column(c).combine_chunks()
            eq = pc.equal(ca, cb)
            both_null = pc.and_(pc.is_null(ca), pc.is_null(cb))
            same = pc.or_kleene(eq, both_null)
            if pa.types.is_floating(ca.type):
                # NaN != NaN under IEEE; an unchanged NaN is not a diff
                both_nan = pc.and_(pc.is_nan(ca.fill_null(0.0)),
                                   pc.is_nan(cb.fill_null(0.0)))
                same = pc.or_kleene(same, both_nan)
            differs |= ~np.asarray(same.fill_null(False))
        a_m = a_m.filter(pa.array(differs))
        b_m = b_m.filter(pa.array(differs))

    def _with_kind(t: pa.Table, kind: int) -> pa.Table:
        kinds = pa.array(np.full(t.num_rows, kind, np.int8), pa.int8())
        return t.set_column(t.column_names.index(KIND_COL), KIND_COL, kinds)

    parts: List[pa.Table] = []
    if deletes.num_rows:
        parts.append(_with_kind(deletes, RowKind.DELETE))
    if inserts.num_rows:
        parts.append(_with_kind(inserts, RowKind.INSERT))
    if a_m.num_rows:
        ub = _with_kind(b_m, RowKind.UPDATE_BEFORE)
        ua = _with_kind(a_m, RowKind.UPDATE_AFTER)
        idx = np.arange(a_m.num_rows)
        pair = pa.concat_tables([ub, ua], promote_options="none")
        order = np.empty(2 * a_m.num_rows, dtype=np.int64)
        order[0::2] = idx                   # -U
        order[1::2] = idx + a_m.num_rows    # +U
        parts.append(pair.take(pa.array(order)))
    if not parts:
        return after.slice(0, 0)
    return pa.concat_tables(parts, promote_options="none")
