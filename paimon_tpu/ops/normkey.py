"""Normalized keys: order-preserving fixed-width encoding of key columns.

The reference compares keys via codegen'd comparators over BinaryRow's
memcmp-comparable layout (paimon-common/.../codegen NormalizedKeyComputer,
sort/BinaryIndexedSortable). On TPU we need keys as fixed-width vector
lanes instead: each row's key becomes L uint32 lanes such that
lexicographic lane comparison == key comparison.

Encodings (all big-endian style, most-significant lane first):
- signed ints: value XOR sign bit -> unsigned of same width
- floats: IEEE total order trick (negative -> flip all bits, else flip
  sign bit)
- strings/bytes: first `prefix_bytes` bytes as big-endian lanes, zero
  padded; a `truncated` flag marks rows whose key exceeded the prefix, so
  callers can resolve rare prefix-equal ties on the host
- date/time/timestamp: underlying ints

Null ordering: nulls-last via a dedicated leading presence LANE per
nullable column (0 = present, 1 = null), so a null is never byte-identical
to any real value (INT64_MAX, all-0xFF string prefixes). Columns declared
non-nullable (primary keys) skip the lane.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

__all__ = ["NormalizedKeyEncoder"]


def _ints_to_u64(arr: np.ndarray) -> np.ndarray:
    """Signed int array -> order-preserving uint64."""
    a = arr.astype(np.int64, copy=False)
    return (a.view(np.uint64) ^ np.uint64(1 << 63))


def _floats_to_u64(arr: np.ndarray) -> np.ndarray:
    a = arr.astype(np.float64, copy=False)
    bits = a.view(np.uint64)
    neg = bits >> np.uint64(63) != 0
    out = np.where(neg, ~bits, bits ^ np.uint64(1 << 63))
    return out


def _split_u64(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    return ((x >> np.uint64(32)).astype(np.uint32),
            (x & np.uint64(0xFFFFFFFF)).astype(np.uint32))


class LazyPackedLanes:
    """[n, 2] u32 lane-matrix VIEW over a packed u64 key vector.

    The hot single-fixed-key paths (OVC merge, packed radix, the
    searchsorted window cut) sort the packed u64 and never read the
    lane matrix, so the encoder hands back this deferred view instead
    of paying a [n, 2] allocation + two strided column writes per
    chunk; np.asarray(...) materializes with a one-shot cache for the
    paths that do want lanes (device kernels, lexsort fallbacks)."""

    def __init__(self, packed: np.ndarray):
        self.packed = packed
        self.shape = (len(packed), 2)
        self._mat: Optional[np.ndarray] = None

    def _materialize(self) -> np.ndarray:
        if self._mat is None:
            hi, lo = _split_u64(self.packed)
            self._mat = np.stack([hi, lo], axis=1)
        return self._mat

    def __array__(self, dtype=None, copy=None):
        out = self._materialize()
        if dtype is not None:
            out = out.astype(dtype)
        if copy and out is self._mat:
            out = out.copy()
        return out

    def __len__(self) -> int:
        return self.shape[0]

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LazyPackedLanes(self.packed[idx])
        return self._materialize()[idx]


class NormalizedKeyEncoder:
    """Encodes the key columns of Arrow batches into uint32 lane matrices."""

    def __init__(self, key_types: Sequence[pa.DataType],
                 string_prefix_bytes: int = 16,
                 nullable: Optional[Sequence[bool]] = None):
        self.key_types = list(key_types)
        self.string_prefix_bytes = ((string_prefix_bytes + 7) // 8) * 8
        self.nullable = (list(nullable) if nullable is not None
                         else [True] * len(self.key_types))
        assert len(self.nullable) == len(self.key_types)
        self.lanes_per_col: List[int] = []
        self._kinds: List[str] = []
        for t in self.key_types:
            if pa.types.is_integer(t) or pa.types.is_date(t) \
                    or pa.types.is_time(t) or pa.types.is_timestamp(t) \
                    or pa.types.is_boolean(t):
                self._kinds.append("int")
                self.lanes_per_col.append(2)
            elif pa.types.is_floating(t):
                self._kinds.append("float")
                self.lanes_per_col.append(2)
            elif pa.types.is_decimal(t):
                self._kinds.append("decimal")
                self.lanes_per_col.append(2)
            elif (pa.types.is_string(t) or pa.types.is_large_string(t)
                  or pa.types.is_binary(t) or pa.types.is_large_binary(t)):
                self._kinds.append("bytes")
                self.lanes_per_col.append(self.string_prefix_bytes // 4)
            else:
                raise ValueError(f"Unsupported key type {t}")
        # one leading presence lane per nullable column (0=value, 1=null)
        self.lanes_per_col = [
            nl + (1 if nul else 0)
            for nl, nul in zip(self.lanes_per_col, self.nullable)]

    @property
    def num_lanes(self) -> int:
        return sum(self.lanes_per_col)

    @property
    def packs_single_key(self) -> bool:
        """True when this encoder's keys pack into ONE u64 (single
        non-null fixed-width column — the hot pk shape): encode_*_ex
        then returns a LazyPackedLanes view and consumers may compare
        by the packed integer alone."""
        return (self.num_lanes == 2 and len(self.key_types) == 1
                and not self.nullable[0]
                and self._kinds[0] in ("int", "float"))

    def encode_columns(self, columns: Sequence[pa.ChunkedArray],
                       ) -> Tuple[np.ndarray, np.ndarray]:
        """-> (lanes uint32[N, num_lanes], truncated bool[N])."""
        lanes, truncated, _ = self.encode_columns_ex(columns)
        return np.asarray(lanes), truncated

    def encode_columns_ex(self, columns: Sequence[pa.ChunkedArray],
                          ) -> Tuple[np.ndarray, np.ndarray,
                                     Optional[np.ndarray]]:
        """-> (lanes, truncated, packed): like encode_columns, plus the
        u64 packed normalized key when the key is a single two-lane
        fixed-width non-null column (the hot pk shape) — the host merge
        fast path then sorts the u64 we already computed instead of
        re-packing the lanes (3 temporaries saved at bucket scale)."""
        assert len(columns) == len(self.key_types)
        n = len(columns[0]) if columns else 0
        if self.packs_single_key and n > 0:
            # hot pk shape: ONLY the packed u64 is computed; the [n, 2]
            # lane matrix is a deferred view most consumers never touch
            arr = columns[0]
            arr = arr.combine_chunks() \
                if isinstance(arr, pa.ChunkedArray) else arr
            if arr.null_count:
                raise ValueError(
                    "null value in a key column declared NOT NULL")
            if self._kinds[0] == "int":
                u = _ints_to_u64(np.asarray(arr.cast(pa.int64())))
            else:
                u = _floats_to_u64(np.asarray(arr.cast(pa.float64())))
            return LazyPackedLanes(u), np.zeros(n, dtype=bool), u
        lanes = np.zeros((n, self.num_lanes), dtype=np.uint32)
        truncated = np.zeros(n, dtype=bool)
        packed: Optional[np.ndarray] = None
        want_packed = (self.num_lanes == 2 and len(columns) == 1
                       and not self.nullable[0]
                       and self._kinds[0] in ("int", "float", "decimal"))
        lane_pos = 0
        for col, kind, total_nl, t, nul in zip(
                columns, self._kinds, self.lanes_per_col, self.key_types,
                self.nullable):
            arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) \
                else col
            # null_count is O(1) metadata: null-free columns (the
            # common pk case) skip materializing a per-row mask
            has_nulls = bool(arr.null_count)
            null_mask = np.asarray(arr.is_null()) if has_nulls \
                else np.zeros(n, dtype=bool)
            if nul:
                if has_nulls:
                    lanes[:, lane_pos] = null_mask.astype(np.uint32)
                lane_pos += 1
                nl = total_nl - 1
            else:
                if has_nulls:
                    raise ValueError(
                        "null value in a key column declared NOT NULL")
                nl = total_nl
            if kind == "int":
                cast = arr.cast(pa.int64())
                # fill_null is a full copy at millions of rows: skip it
                # for null-free columns (the common pk case)
                if cast.null_count:
                    cast = cast.fill_null(0)
                vals = np.asarray(cast)
                u = _ints_to_u64(vals)
                if want_packed:
                    packed = u
                hi, lo = _split_u64(u)
                lanes[:, lane_pos] = hi
                lanes[:, lane_pos + 1] = lo
            elif kind == "float":
                cast = arr.cast(pa.float64())
                if cast.null_count:
                    cast = cast.fill_null(0)
                vals = np.asarray(cast)
                u = _floats_to_u64(vals)
                if want_packed:
                    packed = u
                hi, lo = _split_u64(u)
                lanes[:, lane_pos] = hi
                lanes[:, lane_pos + 1] = lo
            elif kind == "decimal":
                # scale-preserving: compare by unscaled value (same scale
                # within a column)
                vals = np.array(
                    [0 if v is None else int(v.scaleb(t.scale))
                     for v in arr.to_pylist()], dtype=np.int64)
                u = _ints_to_u64(vals)
                if want_packed:
                    packed = u
                hi, lo = _split_u64(u)
                lanes[:, lane_pos] = hi
                lanes[:, lane_pos + 1] = lo
            else:  # bytes
                trunc_col = self._encode_bytes(arr, lanes, lane_pos, nl)
                truncated |= trunc_col & ~null_mask
            if null_mask.any():
                # value lanes of null rows are zeroed (presence lane alone
                # decides the order; any residue from fill_null is wiped)
                lanes[null_mask, lane_pos:lane_pos + nl] = np.uint32(0)
            lane_pos += nl
        return lanes, truncated, packed

    def _encode_bytes(self, arr: pa.Array, lanes: np.ndarray, lane_pos: int,
                      nl: int) -> np.ndarray:
        pb = self.string_prefix_bytes
        if pa.types.is_string(arr.type) or pa.types.is_large_string(arr.type):
            arr = arr.cast(pa.binary())
        arr = arr.cast(pa.large_binary())
        # vectorized: buffer + offsets
        arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
        offsets = np.asarray(arr.buffers()[1]).view(np.int64)
        data = np.frombuffer(arr.buffers()[2], dtype=np.uint8) \
            if arr.buffers()[2] is not None else np.zeros(0, np.uint8)
        n = len(arr)
        starts = offsets[:-1]
        ends = offsets[1:]
        lengths = ends - starts
        truncated = lengths > pb
        # gather first pb bytes of each value, zero-padded
        take = np.minimum(lengths, pb)
        padded = np.zeros((n, pb), dtype=np.uint8)
        # index matrix trick: for each row, positions starts[i]..starts[i]+take[i]
        col_idx = np.arange(pb)[None, :]
        src_idx = starts[:, None] + col_idx
        valid = col_idx < take[:, None]
        src_idx = np.where(valid, src_idx, 0)
        if len(data):
            padded = np.where(valid, data[src_idx], 0).astype(np.uint8)
        # big-endian u32 lanes
        as_u32 = padded.reshape(n, pb // 4, 4)
        lanes_col = (as_u32[:, :, 0].astype(np.uint32) << 24) | \
                    (as_u32[:, :, 1].astype(np.uint32) << 16) | \
                    (as_u32[:, :, 2].astype(np.uint32) << 8) | \
                    as_u32[:, :, 3].astype(np.uint32)
        lanes[:, lane_pos:lane_pos + nl] = lanes_col
        return truncated

    def encode_table(self, table: pa.Table,
                     key_names: Sequence[str]) -> Tuple[np.ndarray,
                                                        np.ndarray]:
        cols = [table.column(n) for n in key_names]
        return self.encode_columns(cols)

    def encode_table_ex(self, table: pa.Table,
                        key_names: Sequence[str]
                        ) -> Tuple[np.ndarray, np.ndarray,
                                   Optional[np.ndarray]]:
        cols = [table.column(n) for n in key_names]
        return self.encode_columns_ex(cols)
