"""Device-resident decoders for Parquet page primitives.

Grounded in "Do GPUs Really Need New Tabular File Formats?" (arXiv
2602.17335): standard columnar formats saturate accelerators once
decode is restructured as vectorized device ops.  The raw-page reader
(format/rawpage.py) slices UNDECODED column-chunk pages off the store
and uploads the page bytes once; everything per-value happens here as
traced JAX ops, so decode fuses with the downstream normalized-key
transform and the merge kernel into one XLA program — no host
round-trip between "bytes arrived" and "merge ran" (the lowering-proof
tier-1 test compiles exactly that program and asserts no host
callbacks).

Covered primitives (the ones the compaction/scan hot path meets):
  * PLAIN fixed-width values — a bitcast reinterpret of the page bytes
    (INT32/INT64/FLOAT/DOUBLE physical types);
  * RLE/bit-packed hybrid runs — definition levels and dictionary
    indices; run HEADERS are parsed on the host (a few dozen sequential
    varints per page), the per-value expansion is a vectorized
    searchsorted-over-cumulative-counts gather + bitwise unpack;
  * dictionary index gather;
  * definition-level null expansion (values scatter to present slots).

Everything in this module must stay traceable: host materialization
(np.asarray / .tolist() / jax.device_get) is BANNED here by the tier-1
AST lint — the host boundary lives in format/rawpage.py.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["unpack_bits", "expand_rle_hybrid", "plain_to_u64",
           "plain_to_u32", "dict_gather", "expand_nulls",
           "int64_to_key_lanes", "float64_to_key_lanes",
           "int32_to_key_lanes", "fused_decode_merge", "pad_pow2"]


def pad_pow2(n: int, floor: int = 1024) -> int:
    """Shape bucket for jit compile-cache stability (mirrors
    ops/merge._pad_size)."""
    if n <= floor:
        return floor
    return 1 << (n - 1).bit_length()


# ---------------------------------------------------------------------------
# bitwise unpack
# ---------------------------------------------------------------------------


def unpack_bits(words: jnp.ndarray, bit_width: int,
                bit_offsets: jnp.ndarray) -> jnp.ndarray:
    """Gather `bit_width`-bit little-endian values at arbitrary bit
    offsets from a u32 word stream (the parquet bit-packed layout).

    words: uint32[W] little-endian view of the page bytes, with at
    least one word of slack past the last read so the two-word window
    never reads out of bounds.  bit_offsets: int32[n] absolute bit
    positions.  Returns uint32[n]."""
    if bit_width == 0:
        return jnp.zeros(bit_offsets.shape, jnp.uint32)
    word_idx = (bit_offsets >> 5).astype(jnp.int32)
    bit_in = (bit_offsets & 31).astype(jnp.uint32)
    lo = words[word_idx].astype(jnp.uint64)
    hi = words[word_idx + 1].astype(jnp.uint64)
    window = lo | (hi << jnp.uint64(32))
    mask = jnp.uint64((1 << bit_width) - 1)
    return ((window >> bit_in.astype(jnp.uint64)) & mask).astype(
        jnp.uint32)


def expand_rle_hybrid(words: jnp.ndarray,
                      run_is_packed: jnp.ndarray,
                      run_value: jnp.ndarray,
                      run_cum: jnp.ndarray,
                      run_bit_start: jnp.ndarray,
                      bit_width: int,
                      count: int) -> jnp.ndarray:
    """Expand parsed RLE/bit-packed hybrid runs to per-value u32.

    The host parses the run headers (format/rawpage.py — a handful of
    varints); expansion is pure device work: each output position finds
    its run by searchsorted over the cumulative run counts, RLE runs
    broadcast their value, bit-packed runs unpack at
    run_bit_start[run] + (pos - run_start) * bit_width.

    run_is_packed: uint32[R] (1 = bit-packed run)
    run_value:     uint32[R] (RLE repeated value; 0 for packed runs)
    run_cum:       int32[R] INCLUSIVE cumulative value counts
    run_bit_start: int32[R] absolute bit offset of a packed run's data
    count:         static output length (padded positions read run 0)
    """
    pos = jnp.arange(count, dtype=jnp.int32)
    run = jnp.searchsorted(run_cum, pos, side="right").astype(jnp.int32)
    run = jnp.minimum(run, run_cum.shape[0] - 1)
    run_start = jnp.where(run > 0, run_cum[run - 1], 0)
    within = pos - run_start
    bit_offs = run_bit_start[run] + within * bit_width
    packed_vals = unpack_bits(words, bit_width,
                              jnp.maximum(bit_offs, 0))
    return jnp.where(run_is_packed[run] != 0, packed_vals,
                     run_value[run])


# ---------------------------------------------------------------------------
# PLAIN fixed-width reinterpret
# ---------------------------------------------------------------------------


def plain_to_u32(page_bytes: jnp.ndarray, count: int) -> jnp.ndarray:
    """PLAIN INT32/FLOAT page payload -> uint32[count] (little-endian
    bitcast reinterpret; caller slices the byte array to 4*count)."""
    b = page_bytes[:4 * count].reshape(count, 4)
    return jax.lax.bitcast_convert_type(b, jnp.uint32)


def plain_to_u64(page_bytes: jnp.ndarray, count: int) -> jnp.ndarray:
    """PLAIN INT64/DOUBLE page payload -> uint64[count]."""
    b = page_bytes[:8 * count].reshape(count, 8)
    return jax.lax.bitcast_convert_type(b, jnp.uint64)


def dict_gather(dict_values: jnp.ndarray,
                indices: jnp.ndarray) -> jnp.ndarray:
    """Dictionary decode: PLAIN-decoded dictionary page values gathered
    by the data pages' RLE-hybrid indices."""
    idx = jnp.minimum(indices.astype(jnp.int32),
                      dict_values.shape[0] - 1)
    return dict_values[idx]


def expand_nulls(values: jnp.ndarray, present: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter dense (nulls-stripped) values onto their logical slots.

    present: bool[n] from the definition levels (def == max_def).
    Returns (full[n] with zeros at null slots, present) — static
    shapes: position i reads values[cumsum(present)[i] - 1] behind a
    mask instead of a dynamic-shape scatter."""
    vidx = jnp.cumsum(present.astype(jnp.int32)) - 1
    vidx = jnp.clip(vidx, 0, values.shape[0] - 1)
    full = jnp.where(present, values[vidx], 0)
    return full, present


# ---------------------------------------------------------------------------
# fused decode -> normalized-key lanes
# ---------------------------------------------------------------------------


def int64_to_key_lanes(u: jnp.ndarray) -> Tuple[jnp.ndarray, ...]:
    """uint64 raw int64 bits -> (packed u64, hi lane, lo lane): the
    order-preserving sign-bit flip of ops/normkey._ints_to_u64, fused
    into the decode program."""
    packed = u ^ jnp.uint64(1 << 63)
    hi = (packed >> jnp.uint64(32)).astype(jnp.uint32)
    lo = (packed & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    return packed, hi, lo


def float64_to_key_lanes(u: jnp.ndarray) -> Tuple[jnp.ndarray, ...]:
    """uint64 raw double bits -> IEEE-total-order packed key + lanes
    (ops/normkey._floats_to_u64 semantics)."""
    neg = (u >> jnp.uint64(63)) != 0
    packed = jnp.where(neg, ~u, u ^ jnp.uint64(1 << 63))
    hi = (packed >> jnp.uint64(32)).astype(jnp.uint32)
    lo = (packed & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    return packed, hi, lo


def int32_to_key_lanes(v: jnp.ndarray) -> Tuple[jnp.ndarray, ...]:
    """uint32 raw int32 bits -> widened order-preserving u64 key +
    lanes (normkey casts every int kind to int64 first)."""
    s = v.astype(jnp.int32).astype(jnp.int64)
    packed = jax.lax.bitcast_convert_type(s, jnp.uint64) \
        ^ jnp.uint64(1 << 63)
    hi = (packed >> jnp.uint64(32)).astype(jnp.uint32)
    lo = (packed & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    return packed, hi, lo


@partial(jax.jit, static_argnames=("keep", "kind"))
def fused_decode_merge(key_bytes: jnp.ndarray, seq_bytes: jnp.ndarray,
                       invalid: jnp.ndarray, keep: str = "last",
                       kind: str = "int64"):
    """The tentpole program: raw PLAIN page bytes of the key and
    sequence columns in, merge winners out — decode, normalized-key
    transform and segmented winner-select lower as ONE jitted XLA
    program with no host callback anywhere inside (tier-1 lowering
    proof inspects exactly this jaxpr/HLO).

    key_bytes/seq_bytes: uint8[8n] PLAIN page payloads; invalid:
    uint32[n] (1 = padding row).  Returns (perm, winner, packed)."""
    n = invalid.shape[0]
    raw = plain_to_u64(key_bytes, n)
    if kind == "float64":
        packed, hi, lo = float64_to_key_lanes(raw)
    else:
        packed, hi, lo = int64_to_key_lanes(raw)
    seq_u = plain_to_u64(seq_bytes, n)
    seq_hi = (seq_u >> jnp.uint64(32)).astype(jnp.uint32)
    seq_lo = (seq_u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    from paimon_tpu.ops.merge import segmented_merge_body
    perm, winner, _ = segmented_merge_body(
        [hi, lo], seq_hi, seq_lo, invalid, keep, num_key_lanes=2)
    return perm, winner, packed
