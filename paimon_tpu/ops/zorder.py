"""Space-filling-curve keys for clustering.

reference: sort/zorder/ZIndexer.java, sort/hilbert/HilbertIndexer.java,
used by the sort-compact path (flink sorter ZorderSorter etc.) to
cluster append tables for locality-friendly pruning.

TPU-first shape: each order-by column normalizes to an order-preserving
uint32 lane (reusing ops/normkey encodings), the z-index interleaves
those bits into one uint64 with vectorized shift/mask rounds, and the
permutation comes from one argsort — no per-row loops.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np
import pyarrow as pa

from paimon_tpu.ops.normkey import NormalizedKeyEncoder

__all__ = ["z_index", "z_order_permutation", "order_permutation",
           "hilbert_index", "hilbert_permutation"]


def _normalized_u32(table: pa.Table, columns: Sequence[str]) -> np.ndarray:
    """[N, C] uint32: order-preserving 32-bit projection per column.

    Values are RANK-normalized (np.unique inverse, scaled to the full
    32-bit range) rather than truncated value bits: raw high bits are
    near-constant for small numeric domains, which would collapse the
    curve; ranks spread the actual data evenly across the bit budget
    (better locality than the reference's fixed byte prefixes)."""
    enc = NormalizedKeyEncoder([table.schema.field(c).type
                                for c in columns],
                               nullable=[table.schema.field(c).nullable
                                         for c in columns])
    lanes, _ = enc.encode_table(table, columns)
    out = np.zeros((table.num_rows, len(columns)), dtype=np.uint32)
    pos = 0
    for i, nl in enumerate(enc.lanes_per_col):
        sub = lanes[:, pos:pos + nl]
        _, inv = np.unique(sub, axis=0, return_inverse=True)
        mx = max(int(inv.max()) if len(inv) else 0, 1)
        out[:, i] = (inv.astype(np.uint64) * np.uint64(0xFFFFFFFF)
                     // np.uint64(mx)).astype(np.uint32)
        pos += nl
    return out


def z_index(table: pa.Table, columns: Sequence[str]) -> np.ndarray:
    """uint64[N] z-order (Morton) keys over `columns`."""
    mat = _normalized_u32(table, columns)
    n, c = mat.shape
    bits_per_col = 64 // c
    # keep the top bits_per_col bits of each column
    vals = (mat >> np.uint32(32 - min(32, bits_per_col))).astype(np.uint64)
    out = np.zeros(n, dtype=np.uint64)
    for b in range(bits_per_col):
        # bit (bits_per_col-1-b) of each column, interleaved round-robin
        src_bit = np.uint64(bits_per_col - 1 - b)
        for ci in range(c):
            dst_bit = np.uint64(64 - 1 - (b * c + ci))
            bit = (vals[:, ci] >> src_bit) & np.uint64(1)
            out |= bit << dst_bit
    return out


def z_order_permutation(table: pa.Table,
                        columns: Sequence[str]) -> np.ndarray:
    return np.argsort(z_index(table, columns), kind="stable")


def order_permutation(table: pa.Table,
                      columns: Sequence[str]) -> np.ndarray:
    """Plain lexicographic clustering (reference OrderSorter)."""
    mat = _normalized_u32(table, columns)
    return np.lexsort(tuple(mat[:, i] for i in reversed(range(
        mat.shape[1]))))


def hilbert_index(table: pa.Table, columns: Sequence[str]) -> np.ndarray:
    """uint64[N] Hilbert-curve keys (Skilling's transpose algorithm,
    vectorized over rows — loops run over bits x dims only; reference
    sort/hilbert/HilbertIndexer.java)."""
    mat = _normalized_u32(table, columns)
    n_rows, n_dims = mat.shape
    bits = min(32, max(1, 63 // n_dims))
    # rank-normalized values truncated to `bits` per dimension
    X = [(mat[:, i] >> np.uint32(32 - bits)).astype(np.uint64)
         for i in range(n_dims)]

    # AxesToTranspose (Skilling, AIP Conf. Proc. 707, 2004) — public
    # domain algorithm, vectorized per row
    M = np.uint64(1 << (bits - 1))
    Q = int(M)
    while Q > 1:
        P = np.uint64(Q - 1)
        Qu = np.uint64(Q)
        for i in range(n_dims):
            cond = (X[i] & Qu) != 0
            X[0] = np.where(cond, X[0] ^ P, X[0])
            t = np.where(cond, np.uint64(0), (X[0] ^ X[i]) & P)
            X[0] ^= t
            X[i] ^= t
        Q >>= 1
    for i in range(1, n_dims):
        X[i] ^= X[i - 1]
    t = np.zeros(n_rows, dtype=np.uint64)
    Q = int(M)
    while Q > 1:
        has = (X[n_dims - 1] & np.uint64(Q)) != 0
        t = np.where(has, t ^ np.uint64(Q - 1), t)
        Q >>= 1
    for i in range(n_dims):
        X[i] ^= t

    # interleave the transpose bits (most-significant first)
    out = np.zeros(n_rows, dtype=np.uint64)
    for b in range(bits - 1, -1, -1):
        for i in range(n_dims):
            bit = (X[i] >> np.uint64(b)) & np.uint64(1)
            out = (out << np.uint64(1)) | bit
    return out


def hilbert_permutation(table: pa.Table,
                        columns: Sequence[str]) -> np.ndarray:
    return np.argsort(hilbert_index(table, columns), kind="stable")
