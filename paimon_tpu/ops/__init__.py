"""Device compute kernels (the TPU execution core).

This package replaces the reference's record-at-a-time merge machinery --
LoserTree (mergetree/compact/LoserTree.java:45), SortMergeReader
(SortMergeReaderWithLoserTree.java:34), MergeFunction implementations, and
Janino-generated comparators (paimon-codegen) -- with XLA-compiled
data-parallel kernels:

- normkey: memcmp-order-preserving key normalization into uint32 lanes
  (the BinaryRow "normalized key" idea, vectorized)
- merge: k-way sorted-run merge as one stable device sort over
  (key lanes, sequence) + segmented winner/reduce selection per merge
  engine; returns take-indices applied to Arrow on the host

Design notes: all kernels use static shapes (inputs padded to bucketized
sizes), uint32 lanes (TPU-native; 64-bit values split hi/lo), and
jnp-only control flow so XLA can fuse and tile freely.
"""

import jax as _jax

# BIGINT columns aggregate in 64-bit (sum/max of int64 values); without
# x64, jax silently truncates to int32. TPU emulates int64 on the VPU --
# acceptable: the hot sort path uses uint32 lanes regardless.
_jax.config.update("jax_enable_x64", True)

from paimon_tpu.ops.normkey import NormalizedKeyEncoder  # noqa: F401
from paimon_tpu.ops.merge import merge_runs, MergeResult  # noqa: F401
