"""Pallas TPU kernels for the merge plane.

The segmented winner-select that follows the device sort is a chain of
elementwise neighbor comparisons over L lane vectors (ops/merge.py
segmented_merge_body): XLA emits it as several fused VPU loops over
HBM-resident operands.  This kernel fuses the WHOLE chain — L lane
equality compares, the validity guard and the boundary mask — into one
VMEM pass per (8, 128) tile, so each lane element is read from HBM
exactly once and the mask never materializes intermediate arrays.

Layout: 1-D arrays of padded length N (power of two >= 1024, as the
merge plane guarantees) are viewed as [N/128, 128] — the natural
(sublane, lane) tiling for 32-bit data — and the grid walks row blocks
of 8 sublanes.  The neighbor shift happens OUTSIDE the kernel (one XLA
roll), keeping every kernel operand block-aligned.

On non-TPU backends the kernel runs in interpret mode, so CPU tests
exercise the identical program; set PAIMON_DISABLE_PALLAS=1 to force
the plain XLA path.
"""

from __future__ import annotations

import os
from functools import lru_cache, partial
from typing import Sequence

import jax
import jax.numpy as jnp

__all__ = ["eq_next_mask", "pallas_enabled", "PALLAS_TILE"]

_BLOCK_ROWS = 8
_LANE = 128
PALLAS_TILE = _BLOCK_ROWS * _LANE     # N must be a multiple of this

# flipped by disable_pallas_runtime() when a real-hardware Mosaic
# compile fails mid-run: callers retry on the pure-XLA path and every
# later merge skips the kernel for the life of the process
_RUNTIME_DISABLED = False


def disable_pallas_runtime(reason: str = "") -> None:
    """Permanently (for this process) turn the Pallas path off — called
    when Mosaic rejects the kernel on the actual backend so the merge
    plane can recompile without it instead of failing the job."""
    global _RUNTIME_DISABLED
    if not _RUNTIME_DISABLED:
        import sys
        sys.stderr.write(
            f"paimon_tpu: disabling Pallas kernels for this process"
            f"{': ' + reason if reason else ''}\n")
    _RUNTIME_DISABLED = True


def pallas_enabled() -> bool:
    """Kernel on for TPU (compiled) and cpu (interpret mode, so tests
    run the identical program); other accelerators keep the fused XLA
    path — interpret-emulating a grid there would be a regression."""
    if _RUNTIME_DISABLED or os.environ.get("PAIMON_DISABLE_PALLAS") == "1":
        return False
    return jax.default_backend() in ("tpu", "cpu")


@lru_cache(maxsize=16)
def _eq_next_fn(num_lanes: int, n: int, interpret: bool):
    from jax.experimental import pallas as pl

    rows = n // _LANE
    grid = (rows // _BLOCK_ROWS,)
    # the 0 column index MUST be pinned to int32: the package enables
    # jax x64 (ops/__init__.py) and a weak `0` traces to i64, giving
    # the index map a mixed (i32, i64) signature that Mosaic rejects
    # ("failed to legalize operation 'func.return'") on real TPUs
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANE),
                        lambda i: (i, jnp.int32(0)))

    def kernel(*refs):
        # refs: cur lanes... nxt lanes... inv_cur, inv_nxt, out
        cur = refs[:num_lanes]
        nxt = refs[num_lanes:2 * num_lanes]
        inv_cur = refs[2 * num_lanes]
        inv_nxt = refs[2 * num_lanes + 1]
        out = refs[-1]
        eq = cur[0][...] == nxt[0][...]
        for l in range(1, num_lanes):
            eq = jnp.logical_and(eq, cur[l][...] == nxt[l][...])
        eq = jnp.logical_and(eq, inv_cur[...] == inv_nxt[...])
        out[...] = eq.astype(jnp.uint32)

    fn = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[spec] * (2 * num_lanes + 2),
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), jnp.uint32),
        interpret=interpret,
    )

    def run(lane_list, invalid):
        def shaped(a):
            return a.reshape(rows, _LANE)

        def shifted(a):
            return shaped(jnp.roll(a, -1))

        args = ([shaped(a) for a in lane_list]
                + [shifted(a) for a in lane_list]
                + [shaped(invalid), shifted(invalid)])
        eq = fn(*args).reshape(n)
        # the final element wraps around to position 0: never a segment
        # continuation
        return eq.at[n - 1].set(0).astype(jnp.bool_)

    return run


def _eq_next_xla(lane_list, invalid):
    lanes_mat = jnp.stack(list(lane_list))
    eq = jnp.all(lanes_mat[:, :-1] == lanes_mat[:, 1:], axis=0)
    eq = eq & (invalid[:-1] == invalid[1:])
    return jnp.concatenate([eq, jnp.array([False])])


def eq_next_mask(lane_list: Sequence[jnp.ndarray],
                 invalid: jnp.ndarray) -> jnp.ndarray:
    """bool[N]: position i continues the same (validity, lanes...)
    segment at i+1.  Fused Pallas pass on tpu/cpu backends for
    tile-aligned N; every other case takes the equivalent XLA ops, so
    callers never need their own shape/backend gate."""
    n = invalid.shape[0]
    if n == 0 or n % PALLAS_TILE != 0 or not pallas_enabled():
        return _eq_next_xla(lane_list, invalid)
    interpret = jax.default_backend() != "tpu"
    run = _eq_next_fn(len(lane_list), n, interpret)
    return run(list(lane_list), invalid)
