"""Pallas TPU kernels for the merge plane.

The segmented winner-select that follows the device sort is a chain of
elementwise neighbor comparisons over L lane vectors (ops/merge.py
segmented_merge_body): XLA emits it as several fused VPU loops over
HBM-resident operands.  This kernel fuses the WHOLE chain — L lane
equality compares, the validity guard and the boundary mask — into one
VMEM pass per (8, 128) tile, so each lane element is read from HBM
exactly once and the mask never materializes intermediate arrays.

Layout: 1-D arrays of padded length N (power of two >= 1024, as the
merge plane guarantees) are viewed as [N/128, 128] — the natural
(sublane, lane) tiling for 32-bit data — and the grid walks row blocks
of 8 sublanes.  The neighbor shift happens OUTSIDE the kernel (one XLA
roll), keeping every kernel operand block-aligned.

On non-TPU backends the kernel runs in interpret mode, so CPU tests
exercise the identical program; set PAIMON_DISABLE_PALLAS=1 to force
the plain XLA path.
"""

from __future__ import annotations

import os
from functools import lru_cache, partial
from typing import Sequence

import jax
import jax.numpy as jnp

__all__ = ["eq_next_mask", "pallas_enabled", "PALLAS_TILE"]

_BLOCK_ROWS = 8
_LANE = 128
PALLAS_TILE = _BLOCK_ROWS * _LANE     # N must be a multiple of this

# flipped by disable_pallas_runtime() when a real-hardware Mosaic
# compile fails mid-run: callers retry on the pure-XLA path and every
# later merge skips the kernel for the life of the process
_RUNTIME_DISABLED = False


def disable_pallas_runtime(reason: str = "") -> None:
    """Permanently (for this process) turn the Pallas path off — called
    when Mosaic rejects the kernel on the actual backend so the merge
    plane can recompile without it instead of failing the job."""
    global _RUNTIME_DISABLED
    if not _RUNTIME_DISABLED:
        import sys
        sys.stderr.write(
            f"paimon_tpu: disabling Pallas kernels for this process"
            f"{': ' + reason if reason else ''}\n")
    _RUNTIME_DISABLED = True


def pallas_enabled() -> bool:
    """Kernel on for TPU (compiled) and cpu (interpret mode, so tests
    run the identical program); other accelerators keep the fused XLA
    path — interpret-emulating a grid there would be a regression."""
    if _RUNTIME_DISABLED or os.environ.get("PAIMON_DISABLE_PALLAS") == "1":
        return False
    return jax.default_backend() in ("tpu", "cpu")


# ovc_off value marking rows whose offset-value code is unusable (run
# starts: their predecessor is the -inf sentinel, not a real row) —
# keep in sync with ops/ovc.OVC_OFF_SENTINEL
_OVC_SENTINEL = 0xFFFFFFFF


@lru_cache(maxsize=16)
def _eq_next_fn(num_lanes: int, n: int, interpret: bool,
                with_ovc: bool = False, num_key_lanes: int = 0):
    from jax.experimental import pallas as pl

    rows = n // _LANE
    grid = (rows // _BLOCK_ROWS,)
    # the 0 column index MUST be pinned to int32: the package enables
    # jax x64 (ops/__init__.py) and a weak `0` traces to i64, giving
    # the index map a mixed (i32, i64) signature that Mosaic rejects
    # ("failed to legalize operation 'func.return'") on real TPUs
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANE),
                        lambda i: (i, jnp.int32(0)))

    def kernel(*refs):
        # refs: cur lanes... nxt lanes... inv_cur, inv_nxt,
        #       [off_nxt, perm_cur, perm_nxt,] out
        cur = refs[:num_lanes]
        nxt = refs[num_lanes:2 * num_lanes]
        inv_cur = refs[2 * num_lanes]
        inv_nxt = refs[2 * num_lanes + 1]
        out = refs[-1]
        eq = cur[0][...] == nxt[0][...]
        for l in range(1, num_lanes):
            eq = jnp.logical_and(eq, cur[l][...] == nxt[l][...])
        if with_ovc:
            # single-int offset-value codes first: a sorted-adjacent
            # pair that is also run-consecutive resolves key equality
            # from the next row's code alone (offset past the key
            # lanes = same key); only the remaining pairs use the full
            # lane-compare chain above
            off_nxt = refs[2 * num_lanes + 2]
            perm_cur = refs[2 * num_lanes + 3]
            perm_nxt = refs[2 * num_lanes + 4]
            consec = perm_nxt[...] == perm_cur[...] + 1
            known = off_nxt[...] != jnp.uint32(_OVC_SENTINEL)
            eq_code = off_nxt[...] >= jnp.uint32(num_key_lanes)
            eq = jnp.where(jnp.logical_and(consec, known), eq_code, eq)
        eq = jnp.logical_and(eq, inv_cur[...] == inv_nxt[...])
        out[...] = eq.astype(jnp.uint32)

    n_in = 2 * num_lanes + 2 + (3 if with_ovc else 0)
    fn = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[spec] * n_in,
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), jnp.uint32),
        interpret=interpret,
    )

    def run(lane_list, invalid, ovc_off=None, perm=None):
        def shaped(a):
            return a.reshape(rows, _LANE)

        def shifted(a):
            return shaped(jnp.roll(a, -1))

        args = ([shaped(a) for a in lane_list]
                + [shifted(a) for a in lane_list]
                + [shaped(invalid), shifted(invalid)])
        if with_ovc:
            args += [shifted(ovc_off), shaped(perm), shifted(perm)]
        eq = fn(*args).reshape(n)
        # the final element wraps around to position 0: never a segment
        # continuation
        return eq.at[n - 1].set(0).astype(jnp.bool_)

    return run


def _eq_next_xla(lane_list, invalid, ovc_off=None, perm=None,
                 num_key_lanes: int = 0):
    lanes_mat = jnp.stack(list(lane_list))
    eq = jnp.all(lanes_mat[:, :-1] == lanes_mat[:, 1:], axis=0)
    if ovc_off is not None:
        consec = perm[1:] == perm[:-1] + 1
        known = ovc_off[1:] != jnp.uint32(_OVC_SENTINEL)
        eq_code = ovc_off[1:] >= jnp.uint32(num_key_lanes)
        eq = jnp.where(consec & known, eq_code, eq)
    eq = eq & (invalid[:-1] == invalid[1:])
    return jnp.concatenate([eq, jnp.array([False])])


def eq_next_mask(lane_list: Sequence[jnp.ndarray],
                 invalid: jnp.ndarray,
                 ovc_off: jnp.ndarray = None,
                 perm: jnp.ndarray = None) -> jnp.ndarray:
    """bool[N]: position i continues the same (validity, lanes...)
    segment at i+1.  Fused Pallas pass on tpu/cpu backends for
    tile-aligned N; every other case takes the equivalent XLA ops, so
    callers never need their own shape/backend gate.

    `ovc_off`/`perm` (sorted-order offset-value-code offsets + the sort
    permutation) switch on the single-int-code fast path: pairs whose
    codes decide key equality skip the lane-compare chain, the rest
    fall through to it (ops/ovc.run_ovc_offsets documents the code)."""
    n = invalid.shape[0]
    num_key_lanes = len(lane_list)
    if n == 0 or n % PALLAS_TILE != 0 or not pallas_enabled():
        return _eq_next_xla(lane_list, invalid, ovc_off, perm,
                            num_key_lanes)
    interpret = jax.default_backend() != "tpu"
    run = _eq_next_fn(len(lane_list), n, interpret,
                      with_ovc=ovc_off is not None,
                      num_key_lanes=num_key_lanes)
    if ovc_off is not None:
        return run(list(lane_list), invalid, ovc_off, perm)
    return run(list(lane_list), invalid)
