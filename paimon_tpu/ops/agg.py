"""Segmented-reduce merge engines: aggregation and partial-update.

reference: mergetree/compact/PartialUpdateMergeFunction.java,
AggregateMergeFunction + 24 FieldAggregators (mergetree/compact/aggregate/).

The record-at-a-time accumulate loop becomes: device sort by (key, seq)
(shared kernel in ops/merge.py) -> per-key segment ids -> per-column
segmented reduction. Numeric sum/max/min/count/product run on device via
jax.ops.segment_*; order-based aggregates (last/first[-non-null] value,
listagg, strings) reduce to a per-segment index selection computed on
device and a host-side Arrow take, so variable-length data never crosses
to HBM.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from paimon_tpu.options import CoreOptions, MergeEngine
from paimon_tpu.ops.merge import (
    KIND_COL, SEQ_COL, device_sorted_winners,
)
from paimon_tpu.ops.normkey import NormalizedKeyEncoder
from paimon_tpu.schema.table_schema import TableSchema
from paimon_tpu.types import RowKind

__all__ = ["merge_runs_agg", "field_aggregators"]

_NUMERIC_DEVICE_AGGS = {"sum", "max", "min", "product", "count"}


def field_aggregators(schema: TableSchema,
                      options: CoreOptions) -> Dict[str, str]:
    """Resolve per-field aggregate function from options
    (`fields.<name>.aggregate-function`), reference
    CoreOptions.fieldAggFunc."""
    default = options.options.get_or("fields.default-aggregate-function",
                                     None)
    engine = options.merge_engine
    out = {}
    pk = set(schema.primary_keys)
    for f in schema.fields:
        if f.name in pk:
            continue
        func = options.options.get_or(
            f"fields.{f.name}.aggregate-function", None)
        if func is None:
            if engine == MergeEngine.PARTIAL_UPDATE:
                func = "last_non_null_value"
            else:
                func = default or "last_non_null_value"
        out[f.name] = func
    return out


def sequence_groups(schema: TableSchema,
                    options: CoreOptions) -> Dict[str, List[str]]:
    """`fields.<a,b>.sequence-group = c,d` -> {seq_field_key: [cols]}
    (reference PartialUpdateMergeFunction sequence groups)."""
    groups = {}
    for key in options.options.keys():
        if key.startswith("fields.") and key.endswith(".sequence-group"):
            seq_fields = key[len("fields."):-len(".sequence-group")]
            cols = [c.strip()
                    for c in options.options.get(key).split(",")]
            groups[seq_fields] = cols
    return groups


def _segment_ids_from_sort(lanes: np.ndarray, seq: np.ndarray):
    """Shared device sort -> (order over real rows, segment ids)."""
    n = lanes.shape[0]
    perm, winner, _ = device_sorted_winners(lanes, seq, "last")
    real = perm < n
    order = perm[real].astype(np.int64)
    win_sorted = winner[real]
    seg_end = win_sorted.copy()
    if len(seg_end):
        seg_end[-1] = True
    seg_id = np.concatenate([[0], np.cumsum(seg_end[:-1])]) \
        if len(seg_end) else np.zeros(0, np.int64)
    return order, seg_id.astype(np.int64), win_sorted


@jax.jit
def _seg_sum(vals, seg_ids, num_seg):
    return jax.ops.segment_sum(vals, seg_ids, num_segments=num_seg)


@jax.jit
def _seg_max(vals, seg_ids, num_seg):
    return jax.ops.segment_max(vals, seg_ids, num_segments=num_seg)


@jax.jit
def _seg_min(vals, seg_ids, num_seg):
    return jax.ops.segment_min(vals, seg_ids, num_segments=num_seg)


@jax.jit
def _seg_prod(vals, seg_ids, num_seg):
    return jax.ops.segment_prod(vals, seg_ids, num_segments=num_seg)


def _last_index_where(mask: np.ndarray, seg_id: np.ndarray,
                      num_seg: int) -> np.ndarray:
    """Per segment, the position (into sorted order) of the last True;
    -1 if none. Vectorized with segment_max over masked positions."""
    pos = np.arange(len(mask), dtype=np.int64)
    masked = np.where(mask, pos, -1)
    out = np.asarray(_seg_max(jnp.asarray(masked), jnp.asarray(seg_id),
                              num_seg))
    return out


def _first_index_where(mask: np.ndarray, seg_id: np.ndarray,
                       num_seg: int) -> np.ndarray:
    n = len(mask)
    pos = np.arange(n, dtype=np.int64)
    masked = np.where(mask, pos, n + 1)
    out = np.asarray(_seg_min(jnp.asarray(masked), jnp.asarray(seg_id),
                              num_seg))
    return np.where(out > n, -1, out)


_JAX_NUMERIC = {
    pa.int8(): np.int32, pa.int16(): np.int32, pa.int32(): np.int64,
    pa.int64(): np.int64, pa.float32(): np.float32,
    pa.float64(): np.float64, pa.bool_(): np.int32,
}


def merge_runs_agg(runs: Sequence[pa.Table], key_cols: Sequence[str],
                   schema: TableSchema, options: CoreOptions,
                   key_encoder: Optional[NormalizedKeyEncoder] = None
                   ) -> pa.Table:
    """Merge runs under aggregation / partial-update semantics.
    Returns a KV-shaped table (keys + sys cols + aggregated values),
    sorted by key."""
    table = pa.concat_tables(runs, promote_options="none")
    n = table.num_rows
    if n == 0:
        return table
    if key_encoder is None:
        key_encoder = NormalizedKeyEncoder(
            [table.schema.field(k).type for k in key_cols],
            nullable=[table.schema.field(k).nullable for k in key_cols])
    lanes, truncated = key_encoder.encode_table(table, key_cols)
    if truncated.any():
        raise NotImplementedError(
            "aggregation merge with >prefix string keys not supported yet; "
            "raise tpu.key-prefix-lanes")
    seq = np.asarray(table.column(SEQ_COL).combine_chunks().cast(pa.int64()))
    order, seg_id, win_sorted = _segment_ids_from_sort(lanes, seq)
    num_seg = int(seg_id[-1]) + 1 if len(seg_id) else 0
    win_pos = np.flatnonzero(win_sorted)           # last row of each segment

    sorted_tbl = table.take(pa.array(order))
    kinds_sorted = np.asarray(sorted_tbl.column(KIND_COL).combine_chunks()
                              .cast(pa.int8()))
    retract = (kinds_sorted == RowKind.DELETE) | \
              (kinds_sorted == RowKind.UPDATE_BEFORE)

    aggs = field_aggregators(schema, options)
    remove_on_delete = options.options.get_or(
        "partial-update.remove-record-on-delete", "false") == "true"

    out_cols: Dict[str, pa.Array] = {}
    # keys + sequence + kind from the segment winner row
    for name in list(key_cols) + [SEQ_COL, KIND_COL]:
        out_cols[name] = sorted_tbl.column(name).take(pa.array(win_pos))

    add_mask = ~retract
    for f in schema.fields:
        name = f.name
        col_sorted = sorted_tbl.column(name)
        if name not in aggs:   # key column: winner value
            out_cols[name] = col_sorted.take(pa.array(win_pos))
            continue
        func = aggs[name]
        valid = np.asarray(pc.is_valid(col_sorted.combine_chunks()))
        if func in _NUMERIC_DEVICE_AGGS and \
                col_sorted.type in _JAX_NUMERIC:
            np_dtype = _JAX_NUMERIC[col_sorted.type]
            vals = np.asarray(col_sorted.combine_chunks()
                              .fill_null(0)).astype(np_dtype)
            contrib_mask = valid & add_mask
            if func == "count":
                dev = _seg_sum(jnp.asarray(contrib_mask.astype(np.int64)),
                               jnp.asarray(seg_id), num_seg)
                result = np.asarray(dev)
                out_cols[name] = pa.array(result, pa.int64())
                continue
            if func == "sum":
                signed = np.where(retract, -vals, vals)
                signed = np.where(valid, signed, 0)
                dev = _seg_sum(jnp.asarray(signed), jnp.asarray(seg_id),
                               num_seg)
                result = np.asarray(dev)
                any_valid = np.asarray(_seg_max(
                    jnp.asarray(valid.astype(np.int32)),
                    jnp.asarray(seg_id), num_seg)) > 0
                out_cols[name] = pa.array(
                    [result[i].item() if any_valid[i] else None
                     for i in range(num_seg)], col_sorted.type)
                continue
            if func in ("max", "min", "product"):
                ident = {"max": _np_min_ident(np_dtype),
                         "min": _np_max_ident(np_dtype),
                         "product": np_dtype(1)}[func]
                masked = np.where(valid & add_mask, vals, ident)
                dev = {"max": _seg_max, "min": _seg_min,
                       "product": _seg_prod}[func](
                    jnp.asarray(masked), jnp.asarray(seg_id), num_seg)
                result = np.asarray(dev)
                any_valid = np.asarray(_seg_max(
                    jnp.asarray((valid & add_mask).astype(np.int32)),
                    jnp.asarray(seg_id), num_seg)) > 0
                out_cols[name] = pa.array(
                    [result[i].item() if any_valid[i] else None
                     for i in range(num_seg)], col_sorted.type)
                continue
        # order-based aggregates: pick an index per segment, host gather
        if func == "last_non_null_value":
            idx = _last_index_where(valid & add_mask, seg_id, num_seg)
        elif func == "last_value":
            idx = _last_index_where(add_mask, seg_id, num_seg)
        elif func == "first_non_null_value":
            idx = _first_index_where(valid & add_mask, seg_id, num_seg)
        elif func == "first_value":
            idx = _first_index_where(add_mask, seg_id, num_seg)
        elif func == "listagg":
            out_cols[name] = _listagg(col_sorted, valid & add_mask, seg_id,
                                      num_seg, options, name)
            continue
        elif func in ("bool_and", "bool_or"):
            vals = np.asarray(col_sorted.combine_chunks()
                              .fill_null(func == "bool_and"))
            masked = vals if func == "bool_or" else vals | ~(valid & add_mask)
            if func == "bool_or":
                masked = vals & (valid & add_mask)
            dev = (_seg_max if func == "bool_or" else _seg_min)(
                jnp.asarray(masked.astype(np.int32)), jnp.asarray(seg_id),
                num_seg)
            out_cols[name] = pa.array(np.asarray(dev).astype(bool),
                                      pa.bool_())
            continue
        else:
            raise ValueError(f"Unknown aggregate function {func!r} "
                             f"for field {name}")
        taken = col_sorted.take(pa.array(np.where(idx < 0, 0, idx)))
        nulls = pa.array(idx < 0)
        out_cols[name] = pc.if_else(nulls, pa.nulls(num_seg, taken.type),
                                    taken.combine_chunks())

    out = pa.table(out_cols)
    # delete handling: drop segments whose winner is a retract
    winner_kinds = np.asarray(out.column(KIND_COL).combine_chunks()
                              .cast(pa.int8()))
    if options.merge_engine == MergeEngine.PARTIAL_UPDATE \
            and not remove_on_delete:
        return out  # deletes ignored (retracts folded per column)
    drop = (winner_kinds == RowKind.DELETE)
    if drop.any():
        out = out.filter(pa.array(~drop))
    return out


def _listagg(col_sorted, mask, seg_id, num_seg, options, name):
    sep = options.options.get_or(f"fields.{name}.list-agg-delimiter", ",")
    vals = col_sorted.to_pylist()
    acc: List[Optional[str]] = [None] * num_seg
    for i in np.flatnonzero(mask):
        s = vals[i]
        g = seg_id[i]
        acc[g] = s if acc[g] is None else acc[g] + sep + s
    return pa.array(acc, pa.string())


def _np_min_ident(dt):
    if np.issubdtype(dt, np.integer):
        return np.iinfo(dt).min
    return dt(-np.inf)


def _np_max_ident(dt):
    if np.issubdtype(dt, np.integer):
        return np.iinfo(dt).max
    return dt(np.inf)
