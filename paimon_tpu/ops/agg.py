"""Segmented-reduce merge engines: aggregation and partial-update.

reference: mergetree/compact/PartialUpdateMergeFunction.java,
AggregateMergeFunction + 24 FieldAggregators (mergetree/compact/aggregate/).

The record-at-a-time accumulate loop becomes: device sort by (key, seq)
(shared kernel in ops/merge.py) -> per-key segment ids -> per-column
segmented reduction. Numeric sum/max/min/count/product run on device via
jax.ops.segment_*; order-based aggregates (last/first[-non-null] value,
listagg, strings) reduce to a per-segment index selection computed on
device and a host-side Arrow take, so variable-length data never crosses
to HBM.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from paimon_tpu.options import CoreOptions, MergeEngine
from paimon_tpu.ops.merge import (
    KIND_COL, SEQ_COL, device_sorted_winners,
)
from paimon_tpu.ops.normkey import NormalizedKeyEncoder
from paimon_tpu.schema.table_schema import TableSchema
from paimon_tpu.types import RowKind

__all__ = ["merge_runs_agg", "field_aggregators",
           "aggregate_sorted_segments"]

_NUMERIC_DEVICE_AGGS = {"sum", "max", "min", "product", "count"}


def field_aggregators(schema: TableSchema,
                      options: CoreOptions) -> Dict[str, str]:
    """Resolve per-field aggregate function from options
    (`fields.<name>.aggregate-function`), reference
    CoreOptions.fieldAggFunc."""
    default = options.options.get_or("fields.default-aggregate-function",
                                     None)
    engine = options.merge_engine
    out = {}
    pk = set(schema.primary_keys)
    for f in schema.fields:
        if f.name in pk:
            continue
        func = options.options.get_or(
            f"fields.{f.name}.aggregate-function", None)
        if func is None:
            if engine == MergeEngine.PARTIAL_UPDATE:
                func = "last_non_null_value"
            else:
                func = default or "last_non_null_value"
        out[f.name] = func
    return out


def sequence_groups(schema: TableSchema,
                    options: CoreOptions) -> Dict[str, List[str]]:
    """`fields.<a,b>.sequence-group = c,d` -> {seq_field_key: [cols]}
    (reference PartialUpdateMergeFunction sequence groups)."""
    groups = {}
    for key in options.options.keys():
        if key.startswith("fields.") and key.endswith(".sequence-group"):
            seq_fields = key[len("fields."):-len(".sequence-group")]
            cols = [c.strip()
                    for c in options.options.get(key).split(",")]
            groups[seq_fields] = cols
    return groups


def _segment_ids_from_sort(lanes: np.ndarray, seq: np.ndarray,
                           truncated: Optional[np.ndarray] = None,
                           full_key=None, order_lanes=None,
                           packed: Optional[np.ndarray] = None,
                           run_starts: Optional[np.ndarray] = None):
    """Shared device sort -> (order over real rows, segment ids).

    If some rows' string keys exceeded the lane prefix (`truncated`),
    device segments may over-group prefix-equal keys; the affected spans
    are repaired on the host by re-sorting on the full key (`full_key`:
    row index -> comparable tuple) and splitting sub-segments."""
    n = lanes.shape[0]
    perm, winner, _ = device_sorted_winners(
        lanes, seq, "last", order_lanes, packed=packed,
        run_starts=run_starts if order_lanes is None else None)
    real = perm < n
    order = perm[real].astype(np.int64)
    win_sorted = winner[real]
    seg_end = win_sorted.copy()
    if len(seg_end):
        seg_end[-1] = True
    seg_id = np.concatenate([[0], np.cumsum(seg_end[:-1])]) \
        if len(seg_end) else np.zeros(0, np.int64)
    seg_id = seg_id.astype(np.int64)

    if truncated is not None and truncated.any() and full_key is not None:
        aff_ids = np.unique(seg_id[truncated[order]])
        m = len(order)
        if len(aff_ids) and m:
            # seg_id is sorted, so each affected segment is one contiguous
            # span located in O(log n); only those spans pay host work
            starts = np.searchsorted(seg_id, aff_ids, side="left")
            ends = np.searchsorted(seg_id, aff_ids, side="right")
            new_order = order.copy()
            boundaries = np.empty(m, dtype=bool)   # True = segment start
            boundaries[0] = True
            boundaries[1:] = seg_id[1:] != seg_id[:-1]
            for s, e in zip(starts, ends):
                span = order[s:e].tolist()
                fk = {r: full_key(r) for r in span}
                # within a key: user sequence first (when present), then
                # internal sequence — same order the device sort used
                resorted = sorted(
                    span,
                    key=lambda r: (fk[r],
                                   tuple(order_lanes[r])
                                   if order_lanes is not None else (),
                                   int(seq[r])))
                new_order[s:e] = resorted
                prev_key = None
                for k, r in enumerate(resorted):
                    boundaries[s + k] = (fk[r] != prev_key)
                    prev_key = fk[r]
            order = new_order
            seg_id = np.cumsum(boundaries) - 1
            win_sorted = np.empty(m, dtype=bool)
            win_sorted[:-1] = seg_id[:-1] != seg_id[1:]
            win_sorted[-1] = True
    return order, seg_id, win_sorted


@partial(jax.jit, static_argnums=2)
def _seg_sum_jit(vals, seg_ids, num_seg):
    return jax.ops.segment_sum(vals, seg_ids, num_segments=num_seg)


@partial(jax.jit, static_argnums=2)
def _seg_max_jit(vals, seg_ids, num_seg):
    return jax.ops.segment_max(vals, seg_ids, num_segments=num_seg)


@partial(jax.jit, static_argnums=2)
def _seg_min_jit(vals, seg_ids, num_seg):
    return jax.ops.segment_min(vals, seg_ids, num_segments=num_seg)


@partial(jax.jit, static_argnums=2)
def _seg_prod_jit(vals, seg_ids, num_seg):
    return jax.ops.segment_prod(vals, seg_ids, num_segments=num_seg)


def _padded_seg(fn_jit):
    """BOTH the row count and num_segments pad to powers of two, so XLA
    compiles O(log^2) distinct shapes across a whole compaction instead
    of one per window (a streamed merge emits hundreds of distinct
    (rows, segments) pairs; each used to recompile).  Padding rows
    point at a dedicated dummy segment past num_seg, which the final
    slice drops — their values never touch a real segment."""
    def call(vals, seg_ids, num_seg):
        vals = np.asarray(vals)
        seg_ids = np.asarray(seg_ids)
        n = len(vals)
        # strictly greater than num_seg so the dummy segment exists
        padded_seg = 1 << max(4, int(num_seg).bit_length())
        m = 1 << max(10, int(n - 1).bit_length()) if n > 1 else 1024
        if m > n:
            vals = np.concatenate(
                [vals, np.zeros(m - n, dtype=vals.dtype)])
            seg_ids = np.concatenate(
                [seg_ids, np.full(m - n, padded_seg - 1,
                                  dtype=seg_ids.dtype)])
        out = fn_jit(jnp.asarray(vals), jnp.asarray(seg_ids), padded_seg)
        return jnp.asarray(out)[:num_seg]
    return call


_seg_sum = _padded_seg(_seg_sum_jit)
_seg_max = _padded_seg(_seg_max_jit)
_seg_min = _padded_seg(_seg_min_jit)
_seg_prod = _padded_seg(_seg_prod_jit)


def _last_index_where(mask: np.ndarray, seg_id: np.ndarray,
                      num_seg: int) -> np.ndarray:
    """Per segment, the position (into sorted order) of the last True;
    -1 if none. Vectorized with segment_max over masked positions."""
    pos = np.arange(len(mask), dtype=np.int64)
    masked = np.where(mask, pos, -1)
    out = np.asarray(_seg_max(masked, seg_id, num_seg))
    return out


def _first_index_where(mask: np.ndarray, seg_id: np.ndarray,
                       num_seg: int) -> np.ndarray:
    n = len(mask)
    pos = np.arange(n, dtype=np.int64)
    masked = np.where(mask, pos, n + 1)
    out = np.asarray(_seg_min(masked, seg_id, num_seg))
    return np.where(out > n, -1, out)


def _masked_numeric(result: np.ndarray, any_valid: np.ndarray,
                    out_type: pa.DataType) -> pa.Array:
    """Vectorized (values, null-mask) -> typed Arrow array; a per-row
    `.item()` comprehension here was the agg plane's hottest line."""
    arr = pa.array(result, mask=~any_valid)
    if arr.type != out_type:
        arr = arr.cast(out_type)
    return arr


_JAX_NUMERIC = {
    pa.int8(): np.int32, pa.int16(): np.int32, pa.int32(): np.int64,
    pa.int64(): np.int64, pa.float32(): np.float32,
    pa.float64(): np.float64, pa.bool_(): np.int32,
}


def merge_runs_agg(runs: Sequence[pa.Table], key_cols: Sequence[str],
                   schema: TableSchema, options: CoreOptions,
                   key_encoder: Optional[NormalizedKeyEncoder] = None,
                   seq_fields: Optional[Sequence[str]] = None
                   ) -> pa.Table:
    """Merge runs under aggregation / partial-update semantics.
    Returns a KV-shaped table (keys + sys cols + aggregated values),
    sorted by key."""
    table = pa.concat_tables(runs, promote_options="none")
    n = table.num_rows
    if n == 0:
        return table
    if key_encoder is None:
        key_encoder = NormalizedKeyEncoder(
            [table.schema.field(k).type for k in key_cols],
            nullable=[table.schema.field(k).nullable for k in key_cols])
    lanes, truncated, packed = key_encoder.encode_table_ex(table,
                                                           key_cols)
    seq = np.asarray(table.column(SEQ_COL).combine_chunks().cast(pa.int64()))
    full_key = None
    if truncated.any():
        kcols = [table.column(k) for k in key_cols]

        def full_key(i: int):
            return tuple(c[int(i)].as_py() for c in kcols)

    from paimon_tpu.ops.merge import user_seq_order_lanes
    order_lanes = user_seq_order_lanes(
        table, seq_fields, options.sequence_field_descending) \
        if seq_fields else None
    run_starts = np.concatenate(
        [[0], np.cumsum([r.num_rows for r in runs])]).astype(np.int64)
    order, seg_id, win_sorted = _segment_ids_from_sort(
        lanes, seq, truncated, full_key, order_lanes, packed=packed,
        run_starts=run_starts)
    return aggregate_sorted_segments(table, order, seg_id, win_sorted,
                                     key_cols, schema, options)


def aggregate_sorted_segments(table: pa.Table, order: np.ndarray,
                              seg_id: np.ndarray, win_sorted: np.ndarray,
                              key_cols: Sequence[str],
                              schema: TableSchema,
                              options: CoreOptions) -> pa.Table:
    """Engine-parameterized aggregation epilogue shared by the
    single-chip merge (``merge_runs_agg``, which computes the sort
    itself) and the mesh window engine (parallel/mesh_engine.py, whose
    [B, window] kernel hands back each lane's sorted order).

    `order`: positions into `table` in (key, user-seq, seq, arrival)
    order; `seg_id`: per-sorted-row key-segment id (ascending, dense);
    `win_sorted`: True at the last row of each segment.  Folds every
    segment per the table's merge engine and returns the KV-shaped
    merged rows in key order."""
    num_seg = int(seg_id[-1]) + 1 if len(seg_id) else 0
    win_pos = np.flatnonzero(win_sorted)           # last row of each segment

    sorted_tbl = table.take(pa.array(order))
    kinds_sorted = np.asarray(sorted_tbl.column(KIND_COL).combine_chunks()
                              .cast(pa.int8()))
    retract = (kinds_sorted == RowKind.DELETE) | \
              (kinds_sorted == RowKind.UPDATE_BEFORE)

    aggs = field_aggregators(schema, options)
    remove_on_delete = options.get(
        CoreOptions.PARTIAL_UPDATE_REMOVE_RECORD_ON_DELETE)

    out_cols: Dict[str, pa.Array] = {}
    # keys + sequence + kind from the segment winner row
    for name in list(key_cols) + [SEQ_COL, KIND_COL]:
        out_cols[name] = sorted_tbl.column(name).take(pa.array(win_pos))

    add_mask = ~retract

    # sequence groups (partial-update): each group's member columns take
    # their values from the row with the LARGEST group-sequence value
    # instead of the global sequence order (reference
    # PartialUpdateMergeFunction sequence groups; ties -> later row wins)
    seq_group_idx: Dict[str, np.ndarray] = {}
    if options.merge_engine == MergeEngine.PARTIAL_UPDATE:
        for gkey, cols in sequence_groups(schema, options).items():
            seq_fields = [s.strip() for s in gkey.split(",")]
            idx = _seq_group_winner_index(sorted_tbl, seq_fields, seg_id,
                                          num_seg, add_mask)
            for colname in dict.fromkeys(list(cols) + seq_fields):
                if options.options.get_or(
                        f"fields.{colname}.aggregate-function",
                        None) is not None:
                    raise NotImplementedError(
                        f"aggregate-function on sequence-group member "
                        f"{colname!r} (reference: aggregation within "
                        f"sequence groups) is not supported yet")
                seq_group_idx[colname] = idx

    for f in schema.fields:
        name = f.name
        col_sorted = sorted_tbl.column(name)
        if name not in aggs:   # key column: winner value
            out_cols[name] = col_sorted.take(pa.array(win_pos))
            continue
        if name in seq_group_idx:
            idx = seq_group_idx[name]
            taken = col_sorted.take(pa.array(np.where(idx < 0, 0, idx)))
            nulls = pa.array(idx < 0)
            out_cols[name] = pc.if_else(
                nulls, pa.nulls(num_seg, taken.type),
                taken.combine_chunks())
            continue
        func = aggs[name]
        valid = np.asarray(pc.is_valid(col_sorted.combine_chunks()))
        if func in _NUMERIC_DEVICE_AGGS and \
                col_sorted.type in _JAX_NUMERIC:
            np_dtype = _JAX_NUMERIC[col_sorted.type]
            vals = np.asarray(col_sorted.combine_chunks()
                              .fill_null(0)).astype(np_dtype)
            contrib_mask = valid & add_mask
            if func == "count":
                dev = _seg_sum(contrib_mask.astype(np.int64), seg_id,
                               num_seg)
                result = np.asarray(dev)
                out_cols[name] = pa.array(result, pa.int64())
                continue
            if func == "sum":
                ignore_retract = options.options.get_or(
                    f"fields.{name}.ignore-retract", "false") == "true"
                if ignore_retract:
                    # reference FieldIgnoreRetractAgg: retracts are
                    # no-ops instead of subtracting, and do not count
                    # as a contribution (all-retract segment -> null)
                    signed = np.where(retract, 0, vals)
                    contributed = valid & ~retract
                else:
                    signed = np.where(retract, -vals, vals)
                    contributed = valid
                signed = np.where(valid, signed, 0)
                dev = _seg_sum(signed, seg_id, num_seg)
                result = np.asarray(dev)
                any_valid = np.asarray(_seg_max(
                    contributed.astype(np.int32), seg_id, num_seg)) > 0
                out_cols[name] = _masked_numeric(result, any_valid,
                                                 col_sorted.type)
                continue
            if func in ("max", "min", "product"):
                ident = {"max": _np_min_ident(np_dtype),
                         "min": _np_max_ident(np_dtype),
                         "product": np_dtype(1)}[func]
                masked = np.where(valid & add_mask, vals, ident)
                dev = {"max": _seg_max, "min": _seg_min,
                       "product": _seg_prod}[func](masked, seg_id,
                                                   num_seg)
                result = np.asarray(dev)
                any_valid = np.asarray(_seg_max(
                    (valid & add_mask).astype(np.int32), seg_id,
                    num_seg)) > 0
                out_cols[name] = _masked_numeric(result, any_valid,
                                                 col_sorted.type)
                continue
        # order-based aggregates: pick an index per segment, host gather
        if func == "last_non_null_value":
            idx = _last_index_where(valid & add_mask, seg_id, num_seg)
        elif func == "last_value":
            idx = _last_index_where(add_mask, seg_id, num_seg)
        elif func == "first_non_null_value":
            idx = _first_index_where(valid & add_mask, seg_id, num_seg)
        elif func == "first_value":
            idx = _first_index_where(add_mask, seg_id, num_seg)
        elif func == "listagg":
            out_cols[name] = _listagg(col_sorted, valid & add_mask, seg_id,
                                      num_seg, options, name)
            continue
        elif func == "collect":
            if not pa.types.is_list(col_sorted.type) and \
                    not pa.types.is_large_list(col_sorted.type):
                raise ValueError(
                    f"collect aggregate requires field {name!r} to be "
                    f"declared ARRAY<...>, got {f.type} (reference "
                    f"FieldCollectAgg)")
            out_cols[name] = _collect(col_sorted, valid & add_mask, seg_id,
                                      num_seg, options, name)
            continue
        elif func == "merge_map":
            out_cols[name] = _merge_map(col_sorted, valid & add_mask,
                                        seg_id, num_seg)
            continue
        elif func == "primary_key":
            # reference FieldPrimaryKeyAgg: the first value sticks
            idx = _first_index_where(valid & add_mask, seg_id, num_seg)
        elif func in ("rbm32", "rbm64"):
            out_cols[name] = _rbm_agg(col_sorted, valid & add_mask,
                                      seg_id, num_seg, func, name)
            continue
        elif func in ("hll_sketch", "theta_sketch"):
            out_cols[name] = _sketch_agg(col_sorted, valid & add_mask,
                                         seg_id, num_seg, func, name)
            continue
        elif func == "nested_update":
            out_cols[name] = _nested_update(col_sorted, valid & add_mask,
                                            seg_id, num_seg, options,
                                            name, f)
            continue
        elif func in ("bool_and", "bool_or"):
            vals = np.asarray(col_sorted.combine_chunks()
                              .fill_null(func == "bool_and"))
            masked = vals if func == "bool_or" else vals | ~(valid & add_mask)
            if func == "bool_or":
                masked = vals & (valid & add_mask)
            dev = (_seg_max if func == "bool_or" else _seg_min)(
                masked.astype(np.int32), seg_id, num_seg)
            out_cols[name] = pa.array(np.asarray(dev).astype(bool),
                                      pa.bool_())
            continue
        else:
            raise ValueError(f"Unknown aggregate function {func!r} "
                             f"for field {name}")
        taken = col_sorted.take(pa.array(np.where(idx < 0, 0, idx)))
        nulls = pa.array(idx < 0)
        out_cols[name] = pc.if_else(nulls, pa.nulls(num_seg, taken.type),
                                    taken.combine_chunks())

    out = pa.table(out_cols)
    # delete handling: drop segments whose winner is a retract
    winner_kinds = np.asarray(out.column(KIND_COL).combine_chunks()
                              .cast(pa.int8()))
    if options.merge_engine == MergeEngine.PARTIAL_UPDATE \
            and not remove_on_delete:
        return out  # deletes ignored (retracts folded per column)
    drop = (winner_kinds == RowKind.DELETE)
    if drop.any():
        out = out.filter(pa.array(~drop))
    return out


def _seq_group_winner_index(sorted_tbl: pa.Table, seq_fields: List[str],
                            seg_id: np.ndarray, num_seg: int,
                            add_mask: np.ndarray) -> np.ndarray:
    """Per segment: position (into sorted order) of the row with the
    largest non-null group-sequence tuple; -1 if no row qualifies.
    Rows with any null sequence field never update the group (reference
    PartialUpdateMergeFunction: null sequence -> skip)."""
    n = sorted_tbl.num_rows
    valid = np.ones(n, dtype=bool)
    mats = []
    for fname in seq_fields:
        arr = sorted_tbl.column(fname).combine_chunks()
        valid &= np.asarray(pc.is_valid(arr))
        t = arr.type
        if pa.types.is_date32(t) or pa.types.is_time32(t):
            # 32-bit temporals -> int64 is not a direct arrow cast
            vals = np.asarray(arr.cast(pa.int32()).fill_null(0)) \
                .astype(np.int64)
        elif pa.types.is_integer(t) or pa.types.is_temporal(t):
            vals = np.asarray(arr.cast(pa.int64()).fill_null(0))
        elif pa.types.is_floating(t):
            vals = np.asarray(arr.cast(pa.float64()).fill_null(0))
        elif pa.types.is_decimal(t):
            vals = np.array([0 if v is None else int(v.scaleb(t.scale))
                             for v in arr.to_pylist()], dtype=object)
        else:
            raise ValueError(
                f"sequence-group field {fname!r} must be numeric or "
                f"temporal, got {t}")
        # rank per field on its native dtype (no cross-field upcasting,
        # which would collapse int64 values above 2^53 into float64)
        _, field_rank = np.unique(vals, return_inverse=True)
        mats.append(field_rank.astype(np.int64))
    # order-preserving combined rank with tie equality
    stacked = np.stack(mats, axis=1)
    _, rank = np.unique(stacked, axis=0, return_inverse=True)
    mask = valid & add_mask
    masked = np.where(mask, rank.astype(np.int64), -1)
    mx = np.asarray(_seg_max(masked, seg_id, num_seg))
    is_max = mask & (masked == mx[seg_id]) & (mx[seg_id] >= 0)
    return _last_index_where(is_max, seg_id, num_seg)


def _collect(col_sorted, mask, seg_id, num_seg, options, name):
    """reference aggregate/FieldCollectAgg: gather values into an array
    (fields.<name>.distinct=true dedups)."""
    distinct = options.options.get_or(f"fields.{name}.distinct",
                                      "false") == "true"
    vals = col_sorted.to_pylist()
    acc: List[Optional[list]] = [None] * num_seg
    for i in np.flatnonzero(mask):
        g = seg_id[i]
        if acc[g] is None:
            acc[g] = []
        v = vals[i]
        if isinstance(v, list):
            acc[g].extend(v)
        else:
            acc[g].append(v)
    if distinct:
        def _dedup(a):
            try:
                return list(dict.fromkeys(a))
            except TypeError:       # unhashable elements (nested types)
                seen, out = set(), []
                for v in a:
                    r = repr(v)
                    if r not in seen:
                        seen.add(r)
                        out.append(v)
                return out
        acc = [None if a is None else _dedup(a) for a in acc]
    return pa.array(acc, col_sorted.type if pa.types.is_list(
        col_sorted.type) else pa.list_(col_sorted.type))


def _seg_bounds(seg_id: np.ndarray, num_seg: int):
    """[start, end) of each segment in the (seg-sorted) row order."""
    starts = np.searchsorted(seg_id, np.arange(num_seg))
    ends = np.searchsorted(seg_id, np.arange(num_seg), side="right")
    return starts, ends


def _rbm_agg(col_sorted, mask, seg_id, num_seg, func: str, name: str):
    """Roaring-bitmap OR-union aggregate over pre-serialized bitmap
    blobs (reference FieldRoaringBitmap32Agg / FieldRoaringBitmap64Agg;
    wire format index/roaring.py)."""
    from paimon_tpu.index.roaring import (
        deserialize_roaring32, deserialize_roaring64,
        serialize_roaring32, serialize_roaring64,
    )
    deser = deserialize_roaring32 if func == "rbm32" \
        else deserialize_roaring64
    ser = serialize_roaring32 if func == "rbm32" else serialize_roaring64
    t = col_sorted.type
    if not (pa.types.is_binary(t) or pa.types.is_large_binary(t)):
        raise ValueError(f"{func} aggregate requires field {name!r} to "
                         f"be VARBINARY of serialized bitmaps")
    vals = col_sorted.combine_chunks().to_pylist()
    starts, ends = _seg_bounds(seg_id, num_seg)
    out = []
    for s, e in zip(starts, ends):
        parts = [deser(vals[i]) for i in range(s, e)
                 if mask[i] and vals[i] is not None]
        out.append(None if not parts
                   else bytes(ser(np.unique(np.concatenate(parts)))))
    return pa.array(out, t)


def _sketch_agg(col_sorted, mask, seg_id, num_seg, func: str, name: str):
    """HLL / theta sketch union aggregate (reference FieldHllSketchAgg,
    FieldThetaSketchAgg; wire format ops/sketch.py)."""
    from paimon_tpu.ops.sketch import hll_union, theta_union
    union = hll_union if func == "hll_sketch" else theta_union
    t = col_sorted.type
    if not (pa.types.is_binary(t) or pa.types.is_large_binary(t)):
        raise ValueError(f"{func} aggregate requires field {name!r} to "
                         f"be VARBINARY of serialized sketches")
    vals = col_sorted.combine_chunks().to_pylist()
    starts, ends = _seg_bounds(seg_id, num_seg)
    out = []
    for s, e in zip(starts, ends):
        merged = union(vals[i] for i in range(s, e)
                       if mask[i] and vals[i] is not None)
        out.append(merged)
    return pa.array(out, t)


def _nested_update(col_sorted, mask, seg_id, num_seg, options,
                   name: str, field):
    """ARRAY<ROW> accumulation (reference FieldNestedUpdateAgg):
    concatenate nested rows across versions; with
    `fields.<name>.nested-key = a,b` rows dedup by that key, last
    writer wins."""
    t = col_sorted.type
    if not (pa.types.is_list(t) or pa.types.is_large_list(t)) or \
            not pa.types.is_struct(t.value_type):
        raise ValueError(f"nested_update requires field {name!r} to be "
                         f"ARRAY<ROW<...>>, got {field.type}")
    keys_opt = options.options.get_or(f"fields.{name}.nested-key", None)
    nested_keys = [k.strip() for k in keys_opt.split(",")] \
        if keys_opt else None
    if nested_keys:
        struct_fields = {t.value_type.field(i).name
                         for i in range(t.value_type.num_fields)}
        unknown = [k for k in nested_keys if k not in struct_fields]
        if unknown:
            raise ValueError(
                f"fields.{name}.nested-key names {unknown} not in the "
                f"nested row {sorted(struct_fields)} (reference "
                f"FieldNestedUpdateAgg key resolution)")
    vals = col_sorted.combine_chunks().to_pylist()
    starts, ends = _seg_bounds(seg_id, num_seg)
    out = []
    for s, e in zip(starts, ends):
        acc: list = []
        seen = {}
        any_val = False
        for i in range(s, e):
            if not mask[i] or vals[i] is None:
                continue
            any_val = True
            for row in vals[i]:
                if nested_keys is None:
                    acc.append(row)
                    continue
                k = tuple(row.get(c) for c in nested_keys)
                if k in seen:
                    acc[seen[k]] = row    # in-place update keeps order
                else:
                    seen[k] = len(acc)
                    acc.append(row)
        out.append(acc if any_val else None)
    return pa.array(out, t)


def _merge_map(col_sorted, mask, seg_id, num_seg):
    """reference aggregate/FieldMergeMapAgg: later maps overwrite earlier
    keys."""
    vals = col_sorted.to_pylist()
    acc: List[Optional[dict]] = [None] * num_seg
    for i in np.flatnonzero(mask):
        g = seg_id[i]
        v = vals[i]
        if v is None:
            continue
        if acc[g] is None:
            acc[g] = {}
        acc[g].update(dict(v))
    return pa.array([None if a is None else list(a.items()) for a in acc],
                    col_sorted.type)


def _listagg(col_sorted, mask, seg_id, num_seg, options, name):
    sep = options.options.get_or(f"fields.{name}.list-agg-delimiter", ",")
    vals = col_sorted.to_pylist()
    acc: List[Optional[str]] = [None] * num_seg
    for i in np.flatnonzero(mask):
        s = vals[i]
        g = seg_id[i]
        acc[g] = s if acc[g] is None else acc[g] + sep + s
    return pa.array(acc, pa.string())


def _np_min_ident(dt):
    if np.issubdtype(dt, np.integer):
        return np.iinfo(dt).min
    return dt(-np.inf)


def _np_max_ident(dt):
    if np.issubdtype(dt, np.integer):
        return np.iinfo(dt).max
    return dt(np.inf)
