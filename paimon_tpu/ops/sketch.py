"""Mergeable cardinality sketches: HyperLogLog and theta (KMV).

reference: mergetree/compact/aggregate/FieldHllSketchAgg.java and
FieldThetaSketchAgg.java merge pre-built Apache DataSketches blobs.
That library is JVM-only, so these are from-scratch sketches with the
same aggregation contract (binary column in -> merged binary out,
commutative + idempotent union) under a tagged wire format of our own:

  HLL:   "PTHL" u8 p, then 2^p registers (one byte each).  Union is an
         elementwise max — one vectorized np.maximum.
  theta: "PTTH" u16 k, u32 n, then n<=k sorted u64 hashes (the K
         minimum values construction).  Union merges + keeps the k
         smallest; the estimate is (n-1) / theta where theta is the
         k-th smallest hash normalized to (0,1].

Builders hash with splitmix64 (shared with the bloom index), whole
column at a time.
"""

from __future__ import annotations

import struct
from typing import Iterable, Optional

import numpy as np
import pyarrow as pa

from paimon_tpu.index.bloom import hash_column

__all__ = ["hll_build", "hll_union", "hll_estimate",
           "theta_build", "theta_union", "theta_estimate"]

_HLL_MAGIC = b"PTHL"
_THETA_MAGIC = b"PTTH"
_DEFAULT_P = 12
_DEFAULT_K = 4096


# -- HyperLogLog -------------------------------------------------------------

def hll_build(col, p: int = _DEFAULT_P) -> bytes:
    """Sketch a column's values (nulls skipped)."""
    arr = col if isinstance(col, pa.ChunkedArray) else pa.chunked_array(
        [col])
    import pyarrow.compute as pc
    arr = arr.filter(pc.is_valid(arr))
    m = 1 << p
    regs = np.zeros(m, dtype=np.uint8)
    if len(arr):
        h = hash_column(arr)
        idx = (h >> np.uint64(64 - p)).astype(np.int64)
        rest = (h << np.uint64(p)) & np.uint64(0xFFFFFFFFFFFFFFFF)
        rank = np.minimum(_clz64(rest) + 1, 64 - p + 1).astype(np.uint8)
        np.maximum.at(regs, idx, rank)
    return _HLL_MAGIC + bytes([p]) + regs.tobytes()


def _clz64(x: np.ndarray) -> np.ndarray:
    """Exact vectorized count-leading-zeros (6 binary steps)."""
    x = x.astype(np.uint64)
    msb = np.zeros(x.shape, np.int64)     # floor(log2(x)) for x > 0
    cur = x.copy()
    for s in (32, 16, 8, 4, 2, 1):
        big = cur >= (np.uint64(1) << np.uint64(s))
        msb = np.where(big, msb + s, msb)
        cur = np.where(big, cur >> np.uint64(s), cur)
    return np.where(x == 0, 64, 63 - msb).astype(np.int64)


def _hll_regs(blob: bytes):
    if blob[:4] != _HLL_MAGIC:
        raise ValueError("not a PTHL sketch")
    p = blob[4]
    return p, np.frombuffer(blob, np.uint8, 1 << p, 5)


def hll_union(blobs: Iterable[bytes]) -> Optional[bytes]:
    acc = None
    p0 = None
    for b in blobs:
        if b is None:
            continue
        p, regs = _hll_regs(bytes(b))
        if acc is None:
            acc, p0 = regs.copy(), p
        else:
            if p != p0:
                raise ValueError("mismatched HLL precisions")
            acc = np.maximum(acc, regs)
    if acc is None:
        return None
    return _HLL_MAGIC + bytes([p0]) + acc.tobytes()


def hll_estimate(blob: bytes) -> float:
    p, regs = _hll_regs(bytes(blob))
    m = 1 << p
    alpha = 0.7213 / (1 + 1.079 / m)
    est = alpha * m * m / np.sum(np.exp2(-regs.astype(np.float64)))
    zeros = int(np.sum(regs == 0))
    if est <= 2.5 * m and zeros:
        est = m * np.log(m / zeros)       # small-range correction
    return float(est)


# -- theta (K minimum values) ------------------------------------------------

def theta_build(col, k: int = _DEFAULT_K) -> bytes:
    arr = col if isinstance(col, pa.ChunkedArray) else pa.chunked_array(
        [col])
    import pyarrow.compute as pc
    arr = arr.filter(pc.is_valid(arr))
    hashes = np.unique(hash_column(arr)) if len(arr) else \
        np.zeros(0, np.uint64)
    hashes = hashes[:k]
    return (_THETA_MAGIC + struct.pack("<HI", k, len(hashes))
            + hashes.astype("<u8").tobytes())


def _theta_parts(blob: bytes):
    if blob[:4] != _THETA_MAGIC:
        raise ValueError("not a PTTH sketch")
    k, n = struct.unpack_from("<HI", blob, 4)
    return k, np.frombuffer(blob, "<u8", n, 10)


def theta_union(blobs: Iterable[bytes]) -> Optional[bytes]:
    ks, all_h = [], []
    for b in blobs:
        if b is None:
            continue
        k, h = _theta_parts(bytes(b))
        ks.append(k)
        all_h.append(h)
    if not ks:
        return None
    k = min(ks)
    merged = np.unique(np.concatenate(all_h))[:k]
    return (_THETA_MAGIC + struct.pack("<HI", k, len(merged))
            + merged.astype("<u8").tobytes())


def theta_estimate(blob: bytes) -> float:
    k, h = _theta_parts(bytes(blob))
    if len(h) < k:
        return float(len(h))              # exact below capacity
    theta = float(h[-1]) / float(1 << 64)
    return (len(h) - 1) / theta
