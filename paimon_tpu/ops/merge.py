"""K-way sorted-run merge on device.

Replaces the reference's per-record loser tree
(mergetree/compact/SortMergeReaderWithLoserTree.java:34, LoserTree.java:45)
and merge functions with one data-parallel plan:

1. concatenate the k runs oldest-first (keeps input order for stable ties),
2. stable device sort by (validity, key lanes..., seq_hi, seq_lo)
   -- jax.lax.sort lexicographic keys; O(N log N) on the VPU but with
   ~10^3-way parallelism it beats a scalar tournament tree by orders of
   magnitude,
3. segmented winner selection: neighbor-equality mask over sorted lanes
   gives per-key segments; deduplicate keeps the last row of each segment
   (max sequence; stability resolves equal sequences by arrival order),
   first-row keeps the first,
4. return take-indices into the concatenated input; the host applies them
   to the Arrow table (variable-length values never touch the device).

Static shapes: inputs are padded to the next power of two; padding rows
carry validity=1 which sorts after all real rows and never joins a segment.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from paimon_tpu.ops.normkey import NormalizedKeyEncoder
from paimon_tpu.types import RowKind

__all__ = ["merge_runs", "MergeResult", "device_sorted_winners",
           "user_seq_order_lanes", "SEQ_COL", "KIND_COL"]

SEQ_COL = "_SEQUENCE_NUMBER"
KIND_COL = "_VALUE_KIND"


@dataclass
class MergeResult:
    """Indices into the concatenated input table, in key order."""
    table: pa.Table          # concatenated input (runs oldest-first)
    indices: np.ndarray      # winners, sorted by key
    # per-winner previous-version indices (for changelog), -1 if none
    prev_indices: Optional[np.ndarray] = None

    def take(self, columns: Optional[List[str]] = None) -> pa.Table:
        t = self.table.select(columns) if columns else self.table
        return t.take(pa.array(self.indices))


def _pad_size(n: int) -> int:
    if n <= 1024:
        return 1024
    return 1 << (n - 1).bit_length()


def segmented_merge_body(lane_list, seq_hi, seq_lo, invalid, keep: str,
                         num_key_lanes: Optional[int] = None,
                         use_pallas: bool = False, ovc_off=None):
    """Traceable kernel body shared by the single-chip path, the sharded
    multi-bucket path (parallel/sharded_merge.py) and the driver entry.

    lane_list: list of uint32[N] arrays (most-significant lane first).
    The first `num_key_lanes` define SEGMENT identity; any further lanes
    are user-defined sequence order (reference
    utils/UserDefinedSeqComparator: rows within a key order by the
    sequence field first, internal sequence breaks ties).
    `ovc_off`: optional uint32[N] per-row offset-value-code offsets vs
    the run predecessor (ops/ovc.run_ovc_offsets) — rides the sort as a
    payload so the winner-select resolves run-consecutive neighbor
    pairs from the single-int code and only lane-compares the rest.
    Returns (perm, winner, prev_in_seg)."""
    num_lanes = len(lane_list)
    if num_key_lanes is None:
        num_key_lanes = num_lanes
    n = invalid.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    operands = [invalid] + list(lane_list) + [seq_hi, seq_lo, iota]
    if ovc_off is not None:
        operands.append(ovc_off)          # payload, not a sort key
    sorted_ops = jax.lax.sort(operands, num_keys=num_lanes + 3,
                              is_stable=True)
    s_invalid = sorted_ops[0]
    s_lanes = sorted_ops[1:1 + num_key_lanes]
    perm = sorted_ops[num_lanes + 3]
    s_off = sorted_ops[-1] if ovc_off is not None else None

    if use_pallas:
        # fused VMEM pass over all lanes at once; eq_next_mask itself
        # falls back to the identical XLA ops for unsupported shapes or
        # backends (ops/pallas_kernels.py)
        from paimon_tpu.ops.pallas_kernels import eq_next_mask
        eq_next = eq_next_mask(list(s_lanes), s_invalid,
                               ovc_off=s_off, perm=perm)
    else:
        # single source of truth for the mask semantics (incl. the
        # validity guard: a real row whose key encodes like padding
        # must not join the padding segment)
        from paimon_tpu.ops.pallas_kernels import _eq_next_xla
        eq_next = _eq_next_xla(list(s_lanes), s_invalid, s_off, perm,
                               num_key_lanes)
    eq_prev = jnp.concatenate([jnp.array([False]), eq_next[:-1]])
    valid = s_invalid == 0
    if keep == "last":
        winner = (~eq_next) & valid
    else:  # "first"
        winner = (~eq_prev) & valid
    # previous version of each winner: its predecessor within the same
    # segment (highest-seq non-winner), for changelog derivation
    prev_in_seg = jnp.where(eq_prev, jnp.roll(perm, 1), -1)
    return perm, winner, prev_in_seg


@lru_cache(maxsize=64)
def _merge_fn(num_lanes: int, keep: str, num_key_lanes: int,
              use_pallas: bool, with_ovc: bool = False):
    """Build the jitted merge kernel for a lane count.  `use_pallas`
    is part of the cache key so the PAIMON_DISABLE_PALLAS kill switch
    takes effect on the next call, not the next process."""

    if with_ovc:
        @jax.jit
        def fn_ovc(lanes, seq_hi, seq_lo, invalid, ovc_off):
            return segmented_merge_body(
                [lanes[i] for i in range(num_lanes)], seq_hi, seq_lo,
                invalid, keep, num_key_lanes=num_key_lanes,
                use_pallas=use_pallas, ovc_off=ovc_off)

        return fn_ovc

    @jax.jit
    def fn(lanes, seq_hi, seq_lo, invalid):
        return segmented_merge_body(
            [lanes[i] for i in range(num_lanes)], seq_hi, seq_lo, invalid,
            keep, num_key_lanes=num_key_lanes, use_pallas=use_pallas)

    return fn


@lru_cache(maxsize=64)
def _merge_fn_bitmask(num_lanes: int, keep: str, num_key_lanes: int,
                      use_pallas: bool):
    """Winner BITMASK variant: uint32[M/32] output — one BIT per row
    (winner flag scattered back to original row order), 1/32nd of the
    packed-u32 return.  On a tunneled chip where device->host collapses
    to ~8MB/s this is the only return size that keeps the device path
    competitive (TPU_PROFILE.log: d2h 256MB = 31.5s).  The host
    recovers key order by radix-sorting just the winners' packed keys
    (~half the rows), which it can do while the device already works on
    the next window."""

    @jax.jit
    def fn(lanes, seq_hi, seq_lo, invalid):
        perm, winner, _ = segmented_merge_body(
            [lanes[i] for i in range(num_lanes)], seq_hi, seq_lo, invalid,
            keep, num_key_lanes=num_key_lanes, use_pallas=use_pallas)
        m = invalid.shape[0]
        # scatter winner flags from sorted order to original positions
        w_orig = jnp.zeros(m, jnp.bool_).at[perm].set(winner)
        # pack 32 flags per word, little-endian bit order (matches
        # np.unpackbits(..., bitorder="little") on the u8 view)
        w = w_orig.reshape(-1, 32).astype(jnp.uint32)
        return (w << jnp.arange(32, dtype=jnp.uint32)[None, :]).sum(
            axis=1, dtype=jnp.uint32)

    return fn


@lru_cache(maxsize=64)
def _merge_fn_packed(num_lanes: int, keep: str, num_key_lanes: int,
                     use_pallas: bool):
    """Winners-only variant: ONE uint32[N] output, perm in the low 31
    bits and the winner flag in bit 31.  Callers that never read `prev`
    or intra-segment order pull 4 bytes/row off the device instead of
    13 — the dominant cost on PCIe-attached and (especially) tunneled
    chips where device->host is the narrow direction."""

    @jax.jit
    def fn(lanes, seq_hi, seq_lo, invalid):
        perm, winner, _ = segmented_merge_body(
            [lanes[i] for i in range(num_lanes)], seq_hi, seq_lo, invalid,
            keep, num_key_lanes=num_key_lanes, use_pallas=use_pallas)
        return perm.astype(jnp.uint32) | (
            winner.astype(jnp.uint32) << 31)

    return fn


# (host->device bytes/s, device->host bytes/s), measured once per
# process on the live accelerator link: over a network-tunneled chip
# d2h collapses to ~8MB/s (TPU_PROFILE.log) while a PCIe-attached chip
# does GB/s, and the merge path choice hinges on exactly this number
_LINK_BW: Optional[Tuple[float, float]] = None

# merges taken per path this process (observability: bench + metrics)
PATH_COUNTS = {"host": 0, "device": 0, "ovc": 0}

# cost-model constants (rows/s), calibrated from TPU_PROFILE.log and
# the CPU-fallback bench: the device measured ~80M sorted rows/s with
# data resident — 50e6 is a deliberate ~1.6x derate covering dispatch
# and padding overhead; the host packed-key path does ~1.5M rows/s via
# numpy argsort but ~10M via the native C radix sort (measured 25M/s
# isolated at 2M-row windows; derated for pipeline contention), and
# the general lexsort ~0.7M
_DEVICE_SORT_ROWS_PER_SEC = 50e6
_HOST_FAST_NUMPY_ROWS_PER_SEC = 1.5e6
_HOST_FAST_NATIVE_ROWS_PER_SEC = 10e6
_HOST_GENERAL_ROWS_PER_SEC = 0.7e6


def _host_fast_rate() -> float:
    # predict WITHOUT triggering the native build: forcing a gcc
    # compile inside the routing decision would stall first merges on
    # processes that always route to the device
    from paimon_tpu import native
    return (_HOST_FAST_NATIVE_ROWS_PER_SEC
            if native.predicted_available()
            else _HOST_FAST_NUMPY_ROWS_PER_SEC)


def _measure_link_bandwidth() -> Tuple[float, float]:
    global _LINK_BW
    if _LINK_BW is not None:
        return _LINK_BW
    import time as _time
    size = 8 << 20
    # one unmeasured warm-up round: the very first transfers absorb
    # buffer-pool/backend warm-up and would read far below the true
    # bandwidth, permanently misrouting merges to the host path
    warm = jax.device_put(np.zeros(size, np.uint8))
    warm.block_until_ready()
    np.asarray(warm)
    h2d_best = d2h_best = 0.0
    for _ in range(2):                         # best-of-2 measured
        buf = np.zeros(size, np.uint8)
        t0 = _time.perf_counter()
        d = jax.device_put(buf)
        d.block_until_ready()
        h2d_best = max(h2d_best,
                       size / max(_time.perf_counter() - t0, 1e-9))
        t0 = _time.perf_counter()
        np.asarray(d)
        d2h_best = max(d2h_best,
                       size / max(_time.perf_counter() - t0, 1e-9))
    _LINK_BW = (h2d_best, d2h_best)
    return _LINK_BW


def _device_path_pays(n: int, num_lanes: int, winners_only: bool,
                      host_fast: bool) -> bool:
    """Cost model: offload the sort only when transfer+compute beats
    the host sort.  The accelerator wins on wide links; a tunneled chip
    loses on device->host alone and the merge stays host-side."""
    m = _pad_size(n)
    h2d, d2h = _measure_link_bandwidth()
    bytes_in = m * (4 * num_lanes + 12)          # lanes + seq hi/lo + inv
    bytes_out = m * (4 if winners_only else 9)   # packed vs perm+win+prev
    t_dev = bytes_in / h2d + bytes_out / d2h + m / _DEVICE_SORT_ROWS_PER_SEC
    host_rate = _host_fast_rate() if host_fast \
        else _HOST_GENERAL_ROWS_PER_SEC
    return t_dev < n / host_rate


# measured winner fraction of recent merges (adaptive duplicate-ratio
# estimate for the bitmask cost model); starts at the conservative 1.0
# (no dedup benefit assumed until observed)
_WINNER_FRAC = {"num": 0.0, "den": 0.0}


def _observed_winner_frac() -> float:
    if _WINNER_FRAC["den"] < 1.0:
        return 1.0
    return max(0.05, _WINNER_FRAC["num"] / _WINNER_FRAC["den"])


def _bitmask_device_pays(n: int, num_lanes: int,
                         overlapped: bool) -> bool:
    """Cost model for the bitmask return: device sorts + dedups, host
    re-sorts only the winners.  With `overlapped=True` the caller runs
    merges on a pipeline worker so upload/sort/download hide under the
    next window's decode+cut — only the host epilogue stays on the
    merge critical path."""
    m = _pad_size(n)
    h2d, d2h = _measure_link_bandwidth()
    host_rate = _host_fast_rate()
    frac = _observed_winner_frac()
    t_link = (m * (4 * num_lanes + 12)) / h2d \
        + m / _DEVICE_SORT_ROWS_PER_SEC + (m / 8) / d2h
    t_epilogue = frac * n / host_rate      # radix of winners only
    t_dev = t_epilogue + (0.0 if overlapped else t_link)
    # even overlapped, the link must keep up with the pipeline or the
    # worker stalls: charge any link time beyond the host-path budget
    if overlapped:
        budget = n / host_rate
        t_dev += max(0.0, t_link - budget)
    return t_dev < n / host_rate


def _host_sorted_winners_fast(lanes: np.ndarray, seq: np.ndarray,
                              keep: str,
                              packed: Optional[np.ndarray] = None
                              ) -> Tuple[np.ndarray, np.ndarray,
                                         np.ndarray]:
    """Packed-key fast path for the hottest shape (exactly two key
    lanes — a fixed-width 64-bit key, so lanes are never
    prefix-truncated — and no changelog predecessor needed): ONE stable
    argsort on a u64 key instead of a 4-key lexsort, then the winner
    per segment via segmented max/min of (seq, arrival) with reduceat.
    Semantics identical to the full sort: winner = max seq (ties -> the
    later arrival) for keep=last, min seq (ties -> earlier arrival) for
    keep=first.  ~1.6x faster than the lexsort path at 8M rows.

    When the native C library is available the whole thing runs as one
    fused radix sort + segment scan (paimon_tpu/native/radix_sort.c):
    ~3.5x faster again than the numpy pipeline at 8M rows."""
    n = lanes.shape[0]
    # the encoder hands back its pre-packed u64 for single fixed-width
    # keys; repack from the lanes only when it couldn't
    if packed is not None:
        key = packed
    else:
        lanes = np.asarray(lanes)    # materialize if lazily concatenated
        key = (lanes[:, 0].astype(np.uint64) << np.uint64(32)) \
            | lanes[:, 1].astype(np.uint64)
    from paimon_tpu import native
    fused = native.merge_winners(key, seq, keep == "last")
    if fused is not None:
        perm, winner = fused
        _WINNER_FRAC["num"] += float(np.count_nonzero(winner))
        _WINNER_FRAC["den"] += float(n)
        return perm, winner, np.broadcast_to(np.int64(-1), n)
    perm = np.argsort(key, kind="stable").astype(np.int32)
    k_sorted = key[perm]
    starts_mask = np.empty(n, dtype=bool)
    starts_mask[0] = True
    starts_mask[1:] = k_sorted[1:] != k_sorted[:-1]
    seg_starts = np.flatnonzero(starts_mask)
    seg_id = np.cumsum(starts_mask) - 1
    seq_sorted = seq[perm]
    if keep == "last":
        best_seq = np.maximum.reduceat(seq_sorted, seg_starts)
        tie = seq_sorted == best_seq[seg_id]
        cand = np.where(tie, perm, -1)
        best_arrival = np.maximum.reduceat(cand, seg_starts)
    else:
        best_seq = np.minimum.reduceat(seq_sorted, seg_starts)
        tie = seq_sorted == best_seq[seg_id]
        cand = np.where(tie, perm, n)
        best_arrival = np.minimum.reduceat(cand, seg_starts)
    winner = tie & (perm == best_arrival[seg_id])
    # winners_only contract: prev is never read — O(1) placeholder
    prev = np.broadcast_to(np.int64(-1), n)
    return perm, winner, prev


def _winner_epilogue(perm: np.ndarray, eq_neighbors: np.ndarray,
                     keep: str) -> Tuple[np.ndarray, np.ndarray,
                                         np.ndarray]:
    """Shared tail of every sorted-winner host path: `eq_neighbors[i]`
    says sorted rows i and i+1 share a key.  Winner = segment end
    (keep=last) or start (keep=first); prev = in-segment predecessor."""
    eq_next = np.concatenate([eq_neighbors, [False]])
    eq_prev = np.concatenate([[False], eq_neighbors])
    winner = ~eq_next if keep == "last" else ~eq_prev
    prev = np.where(eq_prev, np.roll(perm, 1), -1)
    return perm, winner, prev


def _host_sorted_winners(lanes: np.ndarray, seq: np.ndarray, keep: str,
                         num_key_lanes: int,
                         need_prev: bool = True,
                         packed: Optional[np.ndarray] = None
                         ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """CPU-backend fallback with EXACTLY the kernel's semantics: when no
    accelerator is attached, np.lexsort beats a single-threaded XLA
    host sort ~2x and skips the device round-trip + power-of-two
    padding entirely.  Accelerator runs never take this path."""
    n, num_lanes = lanes.shape
    if num_lanes == 2 and num_key_lanes == 2 and not need_prev \
            and n > 0:
        return _host_sorted_winners_fast(lanes, seq, keep, packed=packed)
    if num_lanes == 2 and num_key_lanes == 2 and n > 0 \
            and packed is not None:
        # full-order variant of the packed fast path (agg/partial-update
        # need every row's position, not just winners): two STABLE C
        # radix passes — by seq, then by key — compose to the exact
        # (key, seq, arrival) order of the lexsort, ~3x faster
        from paimon_tpu import native
        if native.load() is not None and int(seq.min()) >= 0:
            useq = seq.astype(np.int64, copy=False).view(np.uint64)
            p1 = native.radix_argsort(useq)
            p2 = native.radix_argsort(
                np.ascontiguousarray(packed[p1])) \
                if p1 is not None else None
            if p2 is not None:
                perm = p1[p2].astype(np.int32, copy=False)
                k_sorted = packed[perm]
                eq = k_sorted[1:] == k_sorted[:-1]
                return _winner_epilogue(perm, eq, keep)
    lanes = np.asarray(lanes)        # materialize if lazily concatenated
    useq = seq.astype(np.int64, copy=False).view(np.uint64)
    keys = ((useq & np.uint64(0xFFFFFFFF)).astype(np.uint32),
            (useq >> np.uint64(32)).astype(np.uint32),
            *(lanes[:, i] for i in range(num_lanes - 1, -1, -1)))
    perm = np.lexsort(keys).astype(np.int32)
    s_lanes = lanes[:, :num_key_lanes][perm]
    eq = np.all(s_lanes[:-1] == s_lanes[1:], axis=1)
    return _winner_epilogue(perm, eq, keep)


def _bitmask_sorted_winners(lanes, seq: np.ndarray, keep: str,
                            order_lanes: Optional[np.ndarray],
                            packed: np.ndarray
                            ) -> Tuple[np.ndarray, np.ndarray,
                                       np.ndarray]:
    """Device path with the N/8-byte return: upload lanes+seq, device
    sorts and computes the winner mask in ORIGINAL row order, host
    radix-sorts only the winners' packed keys to recover key order.
    Returns (winner_indices_in_key_order, all-true, -1) — valid under
    the winners_only contract (callers select via the mask and never
    read intra-segment order or prev)."""
    PATH_COUNTS["device"] += 1
    n = packed.shape[0]
    lanes = np.asarray(lanes)
    if order_lanes is not None and order_lanes.shape[1] > 0:
        lanes = np.concatenate([lanes, order_lanes], axis=1)
    num_lanes = lanes.shape[1]
    num_key_lanes = 2                     # bitmask requires packed u64
    m = _pad_size(n)
    lanes_p = np.zeros((m, num_lanes), dtype=np.uint32)
    lanes_p[:n] = lanes
    useq = seq.astype(np.int64, copy=False).view(np.uint64)
    seq_hi = np.zeros(m, dtype=np.uint32)
    seq_lo = np.zeros(m, dtype=np.uint32)
    seq_hi[:n] = (useq >> np.uint64(32)).astype(np.uint32)
    seq_lo[:n] = (useq & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    invalid = np.ones(m, dtype=np.uint32)
    invalid[:n] = 0

    from paimon_tpu.ops.pallas_kernels import (disable_pallas_runtime,
                                               pallas_enabled)
    lane_list = tuple(jnp.asarray(lanes_p[:, i]) for i in range(num_lanes))
    use_pallas = pallas_enabled()
    try:
        fn = _merge_fn_bitmask(num_lanes, keep, num_key_lanes, use_pallas)
        words = fn(lane_list, jnp.asarray(seq_hi),
                   jnp.asarray(seq_lo), jnp.asarray(invalid))
    except jax.errors.JaxRuntimeError:
        if not use_pallas:
            raise
        disable_pallas_runtime("Mosaic compile failed")
        fn = _merge_fn_bitmask(num_lanes, keep, num_key_lanes, False)
        words = fn(lane_list, jnp.asarray(seq_hi),
                   jnp.asarray(seq_lo), jnp.asarray(invalid))
    mask = np.unpackbits(np.asarray(words).view(np.uint8),
                         bitorder="little")[:n].astype(bool)
    widx = np.flatnonzero(mask)           # winners, original row order
    _WINNER_FRAC["num"] += float(len(widx))
    _WINNER_FRAC["den"] += float(n)
    wkeys = np.ascontiguousarray(packed[widx])
    from paimon_tpu import native
    perm_w = native.radix_argsort(wkeys)
    if perm_w is None:
        perm_w = np.argsort(wkeys, kind="stable")
    indices = widx[perm_w].astype(np.int32)
    return (indices, np.ones(len(indices), dtype=bool),
            np.broadcast_to(np.int64(-1), len(indices)))


def device_sorted_winners(lanes: np.ndarray, seq: np.ndarray,
                          keep: str = "last",
                          order_lanes: Optional[np.ndarray] = None,
                          winners_only: bool = False,
                          packed: Optional[np.ndarray] = None,
                          overlapped: bool = False,
                          run_starts: Optional[np.ndarray] = None
                          ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Run the device kernel.

    lanes: uint32[N, L] (segment identity); seq: int64[N] (non-negative);
    order_lanes: optional uint32[N, O] user-defined sequence lanes that
    rank within a key BEFORE the internal sequence.
    `winners_only=True` promises the caller uses ONLY the winner rows
    (never full perm ordering within segments nor prev), unlocking the
    packed-key fast path for fixed-width two-lane keys.
    `run_starts`: optional int64[k+1] boundaries marking the input as k
    concatenated (key, seq)-SORTED runs — unlocks the offset-value
    coded O(n log k) tree-of-losers merge (ops/ovc.py) on the host
    path, replacing the full sort; rows need not be pre-validated (the
    OVC path verifies the sort contract and falls back when violated).
    Returns (perm, winner_mask, prev_in_segment) as numpy arrays — of
    the power-of-two padded size on the accelerator path, UNPADDED
    (length N, all rows valid) on the host lexsort path.  Callers must
    select via the winner mask / `perm < n`, never assume a padded
    length.

    Path selection is LINK-ADAPTIVE on accelerator backends: the first
    call measures h2d/d2h bandwidth and each merge offloads only when
    the modeled transfer+sort time beats the host sort
    (_device_path_pays) — a PCIe chip takes the device path, a slow
    tunnel keeps data-heavy merges host-side.  Overrides:
    PAIMON_FORCE_DEVICE_SORT=1 pins the device kernel (also on cpu,
    for padding/validity tests); PAIMON_FORCE_HOST_SORT=1 pins the
    host path.
    """
    import os as _os
    n, num_key_lanes = lanes.shape
    force_device = _os.environ.get("PAIMON_FORCE_DEVICE_SORT") == "1"
    force_bitmask = _os.environ.get("PAIMON_FORCE_BITMASK_SORT") == "1"
    force_host = _os.environ.get("PAIMON_FORCE_HOST_SORT") == "1"
    host_fast = (num_key_lanes == 2 and winners_only
                 and (order_lanes is None or order_lanes.shape[1] == 0))
    # bitmask return: winners-only callers with a pre-packed u64 key
    # (the host epilogue recovers key order by radix-sorting winners)
    bitmask_ok = winners_only and packed is not None and n > 0
    nl_total = lanes.shape[1] + (order_lanes.shape[1]
                                 if order_lanes is not None else 0)
    use_bitmask = force_bitmask and bitmask_ok
    use_host = force_host
    if not use_host and not force_device and not force_bitmask and n > 0:
        if jax.default_backend() == "cpu":
            use_host = True
        else:
            use_bitmask = bitmask_ok and _bitmask_device_pays(
                n, nl_total, overlapped)
            if not use_bitmask:
                use_host = not _device_path_pays(n, nl_total,
                                                 winners_only, host_fast)
    if use_bitmask:
        return _bitmask_sorted_winners(lanes, seq, keep, order_lanes,
                                       np.asarray(packed))
    if use_host:
        no_user_order = order_lanes is None or order_lanes.shape[1] == 0
        if run_starts is not None and no_user_order and len(run_starts) > 1:
            # sorted-run inputs: offset-value coded merge replaces the
            # sort (single-int compares, segment boundaries for free)
            from paimon_tpu.ops.ovc import ovc_sorted_winners
            res = ovc_sorted_winners(lanes, seq, keep, run_starts,
                                     num_key_lanes, packed=packed)
            if res is not None:
                PATH_COUNTS["ovc"] += 1
                return res
        PATH_COUNTS["host"] += 1
        full = lanes if no_user_order \
            else np.concatenate([lanes, order_lanes], axis=1)
        return _host_sorted_winners(full, seq, keep, num_key_lanes,
                                    need_prev=not winners_only,
                                    packed=packed if no_user_order
                                    else None)
    PATH_COUNTS["device"] += 1
    lanes = np.asarray(lanes)        # materialize if lazily concatenated
    if order_lanes is not None and order_lanes.shape[1] > 0:
        lanes = np.concatenate([lanes, order_lanes], axis=1)
    num_lanes = lanes.shape[1]
    m = _pad_size(n)
    lanes_p = np.full((m, num_lanes), 0, dtype=np.uint32)
    lanes_p[:n] = lanes
    useq = seq.astype(np.int64, copy=False).view(np.uint64)
    seq_hi = np.zeros(m, dtype=np.uint32)
    seq_lo = np.zeros(m, dtype=np.uint32)
    seq_hi[:n] = (useq >> np.uint64(32)).astype(np.uint32)
    seq_lo[:n] = (useq & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    invalid = np.ones(m, dtype=np.uint32)
    invalid[:n] = 0

    from paimon_tpu.ops.pallas_kernels import (disable_pallas_runtime,
                                               pallas_enabled)
    lane_list = tuple(jnp.asarray(lanes_p[:, i]) for i in range(num_lanes))
    use_pallas = pallas_enabled()
    # sorted-run inputs ship their offset-value codes to the device:
    # the winner-select consumes the single-int offsets first and only
    # lane-compares pairs the codes cannot decide (full variant only —
    # the packed/bitmask returns already collapse keys to one u64)
    ovc_args = ()
    with_ovc = run_starts is not None and not winners_only
    if with_ovc:
        from paimon_tpu.ops.ovc import OVC_OFF_SENTINEL, run_ovc_offsets
        off = np.full(m, OVC_OFF_SENTINEL, dtype=np.uint32)
        off[:n] = run_ovc_offsets(lanes, run_starts)
        ovc_args = (jnp.asarray(off),)
    builder = _merge_fn_packed if winners_only else _merge_fn
    try:
        fn = builder(num_lanes, keep, num_key_lanes, use_pallas,
                     with_ovc) if builder is _merge_fn \
            else builder(num_lanes, keep, num_key_lanes, use_pallas)
        out = fn(lane_list, jnp.asarray(seq_hi),
                 jnp.asarray(seq_lo), jnp.asarray(invalid), *ovc_args)
    except jax.errors.JaxRuntimeError:
        # a Mosaic compile rejection on the real backend must not fail
        # the merge: drop to the pure-XLA kernel for the whole process
        if not use_pallas:
            raise
        disable_pallas_runtime("Mosaic compile failed")
        fn = builder(num_lanes, keep, num_key_lanes, False,
                     with_ovc) if builder is _merge_fn \
            else builder(num_lanes, keep, num_key_lanes, False)
        out = fn(lane_list, jnp.asarray(seq_hi),
                 jnp.asarray(seq_lo), jnp.asarray(invalid), *ovc_args)
    if winners_only:
        # one 4-byte word/row off the device: perm | (winner << 31)
        packed = np.asarray(out)
        perm = (packed & np.uint32(0x7FFFFFFF)).astype(np.int32)
        winner = (packed >> np.uint32(31)).astype(bool)
        prev = np.broadcast_to(np.int64(-1), m)
        return perm, winner, prev
    perm, winner, prev = out
    return (np.asarray(perm), np.asarray(winner), np.asarray(prev))


def user_seq_order_lanes(table: pa.Table,
                         seq_fields: Sequence[str],
                         descending: bool = False) -> np.ndarray:
    """uint32[N, O] order lanes for user-defined sequence columns
    (reference utils/UserDefinedSeqComparator). Nulls rank FIRST — a row
    with a null sequence always loses to any non-null one (in either
    sort order).  `descending` implements
    sequence.field.sort-order=descending: the SMALLER user sequence
    wins, via bitwise inversion of the value lanes."""
    for f in seq_fields:
        t = table.schema.field(f).type
        if pa.types.is_string(t) or pa.types.is_large_string(t) or \
                pa.types.is_binary(t) or pa.types.is_large_binary(t):
            raise ValueError(
                f"sequence.field {f!r} must be numeric/temporal; string "
                f"sequences would compare only by a fixed-width prefix")
    enc = NormalizedKeyEncoder(
        [table.schema.field(f).type for f in seq_fields],
        nullable=[True] * len(seq_fields))
    lanes, _ = enc.encode_table(table, seq_fields)
    pos = 0
    for nl in enc.lanes_per_col:
        # encoder presence lane sorts nulls last; sequences need the
        # opposite (null = smallest, so null always loses)
        lanes[:, pos] = 1 - lanes[:, pos]
        if descending:
            for p in range(pos + 1, pos + nl):
                lanes[:, p] = np.uint32(0xFFFFFFFF) - lanes[:, p]
        pos += nl
    return lanes


def sort_table(table: pa.Table, key_names: Sequence[str],
               key_encoder: Optional[NormalizedKeyEncoder] = None
               ) -> np.ndarray:
    """Full sort permutation by (key, seq) -- used to lay out write-buffer
    flushes when the merge engine defers merging to read time. Returns
    indices into `table` in sorted order (stable: arrival order for ties)."""
    n = table.num_rows
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    if key_encoder is None:
        key_encoder = NormalizedKeyEncoder(
            [table.schema.field(k).type for k in key_names],
            nullable=[table.schema.field(k).nullable for k in key_names])
    lanes, truncated = key_encoder.encode_table(table, key_names)
    seq = np.asarray(table.column(SEQ_COL).combine_chunks().cast(pa.int64()))
    perm, _, _ = device_sorted_winners(lanes, seq, "last")
    order = perm[perm < n].astype(np.int64)
    if truncated.any():
        # prefix ties may misorder full keys; host re-sort of affected rows
        key_cols = [table.column(k) for k in key_names]

        def full_key(i):
            return tuple(c[int(i)].as_py() for c in key_cols)

        order = np.array(
            sorted(order.tolist(),
                   key=lambda i: (full_key(i), int(seq[i]))),
            dtype=np.int64)
    return order


class _LazyLanes:
    """Deferred np.concatenate of per-run lane matrices.  The packed-key
    host fast path sorts the pre-packed u64 and never reads the lane
    matrix; this defers (and usually skips) an 8N-byte copy per window.
    Exposes .shape; np.asarray(...) materializes with a one-shot cache."""

    def __init__(self, parts: List[np.ndarray]):
        self._parts = parts
        n = sum(p.shape[0] for p in parts)
        self.shape = (n, parts[0].shape[1] if parts else 0)
        self._mat: Optional[np.ndarray] = None

    def __array__(self, dtype=None, copy=None):
        if self._mat is None:
            self._mat = (np.concatenate(self._parts)
                         if len(self._parts) > 1 else self._parts[0])
        out = self._mat if dtype is None else self._mat.astype(dtype)
        if copy and out is self._mat:
            out = out.copy()         # honor the NumPy 2 copy request —
            # the cache (and parts[0]) stay owned by the streamed buffer
        return out


def merge_runs(runs: Sequence[pa.Table], key_names: Sequence[str],
               merge_engine: str = "deduplicate",
               drop_deletes: bool = True,
               key_encoder: Optional[NormalizedKeyEncoder] = None,
               with_prev: bool = False,
               seq_fields: Optional[Sequence[str]] = None,
               seq_desc: bool = False,
               encoded: Optional[Sequence[Tuple[np.ndarray, np.ndarray]]]
               = None,
               overlapped: bool = False) -> MergeResult:
    """Merge k sorted runs (oldest first) into the latest row per key.

    Equivalent reference path: MergeTreeReaders.readerForMergeTree
    (mergetree/MergeTreeReaders.java:44) + DeduplicateMergeFunction /
    FirstRowMergeFunction + DropDeleteReader.
    """
    if not runs:
        raise ValueError("No runs to merge")
    table = pa.concat_tables(runs, promote_options="none")
    n = table.num_rows
    if n == 0:
        return MergeResult(table, np.zeros(0, dtype=np.int64))

    if key_encoder is None:
        key_encoder = NormalizedKeyEncoder(
            [table.schema.field(k).type for k in key_names],
            nullable=[table.schema.field(k).nullable for k in key_names])
    packed = None
    if encoded is not None:
        # caller already lane-encoded each run (streamed windows encode
        # once for the window cut — don't pay the encode twice); items
        # are (lanes, truncated[, packed-u64])
        truncated = (np.concatenate([e[1] for e in encoded])
                     if len(encoded) > 1 else np.asarray(encoded[0][1]))
        packs = [e[2] if len(e) > 2 else None for e in encoded]
        if all(p is not None for p in packs):
            packed = (np.concatenate(packs) if len(packs) > 1
                      else np.asarray(packs[0]))
        if packed is not None:
            # the packed-key host fast path never reads the lane matrix:
            # concatenating it up front would copy 8N bytes per window
            # for nothing, so defer until a path actually wants it
            lane_parts = [e[0] for e in encoded]
            lanes = _LazyLanes(lane_parts)
        else:
            lanes = (np.concatenate([e[0] for e in encoded])
                     if len(encoded) > 1 else np.asarray(encoded[0][0]))
    else:
        lanes, truncated, packed = key_encoder.encode_table_ex(
            table, key_names)
    seq = np.asarray(table.column(SEQ_COL).combine_chunks().cast(pa.int64()))

    # sorted-run boundaries for the OVC merge path: every input run
    # (or pre-cut window chunk — chunks of one run arrive in run order,
    # so treating each as its own run preserves arrival order) is
    # individually (key, seq)-sorted by the write/compact invariants;
    # the OVC path re-verifies and falls back if a caller violates that
    if encoded is not None:
        run_lens = [e[0].shape[0] for e in encoded]
    else:
        run_lens = [r.num_rows for r in runs]
    run_starts = np.concatenate(
        [[0], np.cumsum(run_lens)]).astype(np.int64)

    keep = "first" if merge_engine == "first-row" else "last"
    if seq_fields and keep == "first":
        # reference forbids the combo: "first by user sequence" would
        # let later commits replace the retained first row
        raise ValueError(
            "sequence.field cannot be used with merge-engine first-row")
    order_lanes = user_seq_order_lanes(table, seq_fields, seq_desc) \
        if seq_fields else None
    # without changelog derivation the caller consumes only winner
    # rows, so the packed-key fast path is admissible — unless any key
    # was prefix-truncated: _refine_truncated needs the full path's
    # seq-ordered segments with winners at segment boundaries
    perm, winner, prev = device_sorted_winners(
        lanes, seq, keep, order_lanes,
        winners_only=not with_prev and not truncated.any(),
        packed=packed, overlapped=overlapped,
        run_starts=run_starts if order_lanes is None else None)

    win_pos = np.flatnonzero(winner)
    indices = perm[win_pos].astype(np.int64)
    prev_idx = prev[win_pos].astype(np.int64) if with_prev else None

    if truncated.any():
        indices, prev_idx = _refine_truncated(
            table, key_names, perm, winner, truncated, seq, keep,
            with_prev, prev)

    if drop_deletes and KIND_COL in table.column_names:
        # cheap min/max scan beats materializing the kinds array when
        # the batch is uniformly +I or uniformly +U (the common
        # compaction window has only +I): RowKind is +I=0 < -U=1 <
        # +U=2 < -D=3, and only lo==hi in {0,2} proves no -U/-D hides
        # in between
        import pyarrow.compute as pc
        mm = pc.min_max(table.column(KIND_COL))
        lo, hi = mm["min"].as_py(), mm["max"].as_py()
        if not (lo == hi and lo in (RowKind.INSERT,
                                    RowKind.UPDATE_AFTER)):
            kinds = np.asarray(table.column(KIND_COL).combine_chunks()
                               .cast(pa.int8()))
            keep_mask = (kinds[indices] == RowKind.INSERT) | \
                        (kinds[indices] == RowKind.UPDATE_AFTER)
            indices = indices[keep_mask]
            if prev_idx is not None:
                prev_idx = prev_idx[keep_mask]

    return MergeResult(table, indices, prev_idx)


def _refine_truncated(table: pa.Table, key_names, perm, winner, truncated,
                      seq, keep: str, with_prev: bool, prev=None):
    """Host fallback for prefix-truncated string keys: rows whose prefix
    collided may belong to different real keys, so device segments can
    over-group. Only the sorted spans that contain a truncated row are
    re-grouped by full key on the host; all other winners keep the device
    result. Rare path (keys longer than the prefix sharing a prefix)."""
    n = len(seq)
    winner = np.asarray(winner)
    sorted_real_mask = perm < n
    sorted_real = perm[sorted_real_mask]              # sorted positions
    win_sorted = winner[sorted_real_mask]
    s_trunc = truncated[sorted_real]

    # segment spans in sorted order: a span ends at each winner/last-of-
    # segment boundary for keep="last"; reconstruct spans via winner mask
    # (device winners mark segment boundaries regardless of keep by
    # construction when keep == "last"; for "first" they mark starts).
    m = len(sorted_real)
    if keep == "last":
        seg_end = win_sorted.copy()
        seg_end[-1] = True
        seg_id = np.concatenate([[0], np.cumsum(seg_end[:-1])])
    else:
        seg_start = win_sorted.copy()
        seg_start[0] = True
        seg_id = np.cumsum(seg_start) - 1

    # spans affected by truncation
    affected_segs = set(np.unique(seg_id[s_trunc]).tolist())
    if not affected_segs:
        win_pos = np.flatnonzero(winner)
        prev_idx = (np.asarray(prev)[win_pos].astype(np.int64)
                    if with_prev and prev is not None else None)
        return (perm[win_pos].astype(np.int64), prev_idx)

    key_cols = [table.column(k) for k in key_names]

    def full_key(i: int):
        return tuple(c[int(i)].as_py() for c in key_cols)

    idx_out: List[int] = []
    prev_out: List[int] = []
    i = 0
    while i < m:
        sid = seg_id[i]
        j = i
        while j < m and seg_id[j] == sid:
            j += 1
        span = sorted_real[i:j]
        if sid not in affected_segs:
            for p, w in zip(span, win_sorted[i:j]):
                if w:
                    idx_out.append(int(p))
                    if with_prev:
                        # predecessor within span
                        pos = list(span).index(p)
                        prev_out.append(int(span[pos - 1]) if pos > 0 else -1)
        else:
            # re-group by full key; span order is (prefix, seq) so within a
            # real key rows remain seq-ordered
            groups: dict = {}
            for p in span:
                groups.setdefault(full_key(p), []).append(int(p))
            for k in sorted(groups):
                g = groups[k]
                if keep == "last":
                    idx_out.append(g[-1])
                    prev_out.append(g[-2] if len(g) > 1 else -1)
                else:
                    idx_out.append(g[0])
                    prev_out.append(-1)
        i = j
    return (np.array(idx_out, dtype=np.int64),
            np.array(prev_out, dtype=np.int64) if with_prev else None)
