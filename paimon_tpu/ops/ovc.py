"""Offset-value coding over normalized-key lanes.

Graefe et al., "Robust and Efficient Sorting with Offset-Value Coding"
(arXiv 2209.08420): when merging SORTED runs, each row carries a single
integer code — the offset of its first difference from its run
predecessor plus the value at that offset — and merge comparisons
collapse to one integer compare, falling through to lane compares only
on code ties.  Each output row's final code ends up relative to the
previous output row, so key-equality (the segment boundaries the
dedup/agg winner selection needs) falls out of the merge for free.

This module drives the native merge in native/radix_sort.c, which
computes the initial per-run codes in ONE sequential C pass
(ovc_codes_u64/ovc_codes_lanes — the pass also verifies the runs
actually honor their (key, seq) sort contract; a violated contract
silently falls back to the sort paths instead of producing a wrong
merge) and then runs the single-int-compare merge.  ops/merge.py
routes eligible host merges here: the O(n log n) radix/lexsort of a
merge window becomes an O(n log k) merge, and the separate
neighbor-equality pass disappears.

Code layout for an L-lane u32 key row r relative to base row z:
    offset = first lane where r differs from z   (L = all equal)
    code   = (L - offset) << 32 | r[offset]      (0 when equal)
Larger code = larger row.  The first row of each run is coded relative
to an imaginary -infinity row (offset 0), which every first-tournament
comparison shares as its base.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

__all__ = ["ovc_enabled", "ovc_sorted_winners", "run_ovc_offsets",
           "OVC_OFF_SENTINEL", "OVC_PATH_ROWS"]

# run-start rows carry no usable code (their predecessor is the -inf
# sentinel, not a real row): the device winner-select must fall through
# to full lane compares exactly there
OVC_OFF_SENTINEL = np.uint32(0xFFFFFFFF)

# rows merged through the OVC path this process (observability: bench)
OVC_PATH_ROWS = {"rows": 0, "merges": 0}


def ovc_enabled() -> bool:
    """OVC merge on unless explicitly disabled (kill switch mirrors
    PAIMON_DISABLE_PALLAS / PAIMON_DISABLE_NATIVE)."""
    return os.environ.get("PAIMON_DISABLE_OVC") != "1"


def run_ovc_offsets(lanes, run_starts: np.ndarray) -> np.ndarray:
    """uint32[n] per-row OVC OFFSETS vs the run predecessor: the first
    lane index where the row differs (num_lanes = all lanes equal),
    OVC_OFF_SENTINEL at run starts.  This is the single-int code the
    device winner-select consumes: a sorted-adjacent pair that is also
    run-consecutive resolves key-(in)equality from the offset alone —
    offset >= num_key_lanes means same key — and only the remaining
    pairs fall through to the full lane-compare chain
    (ops/pallas_kernels.eq_next_mask)."""
    mat = np.asarray(lanes)
    n, num_lanes = mat.shape
    out = np.full(n, np.uint32(num_lanes), dtype=np.uint32)
    if n:
        diff = mat[1:] != mat[:-1]
        any_diff = diff.any(axis=1)
        off = np.argmax(diff, axis=1).astype(np.uint32)
        out[1:] = np.where(any_diff, off, np.uint32(num_lanes))
        starts = np.asarray(run_starts)[:-1]
        out[starts[starts < n]] = OVC_OFF_SENTINEL
    return out


def ovc_sorted_winners(lanes, seq: np.ndarray, keep: str,
                       run_starts: np.ndarray, num_key_lanes: int,
                       packed: Optional[np.ndarray] = None
                       ) -> Optional[Tuple[np.ndarray, np.ndarray,
                                           np.ndarray]]:
    """(perm, winner, prev) — same contract as the unpadded host paths
    of ops/merge.device_sorted_winners — via the native OVC merge, or
    None when ineligible (native runtime unavailable, empty input, or a
    run that is not actually (key, seq)-sorted; the caller falls back
    to the sort paths)."""
    from paimon_tpu import native

    n = len(seq)
    if n == 0 or not ovc_enabled() or not native.predicted_available():
        return None
    seq = np.ascontiguousarray(seq, dtype=np.int64)
    starts = np.ascontiguousarray(run_starts, dtype=np.int64)
    if packed is not None and num_key_lanes == 2:
        res = native.ovc_merge_u64(
            np.ascontiguousarray(packed, dtype=np.uint64), seq, starts)
        num_lanes = 2
    else:
        mat = np.ascontiguousarray(np.asarray(lanes), dtype=np.uint32)
        if mat.shape[1] == 0:
            return None
        res = native.ovc_merge_lanes(mat, seq, starts)
        num_lanes = mat.shape[1]
    if res is None:
        return None
    perm, out_codes = res
    OVC_PATH_ROWS["rows"] += n
    OVC_PATH_ROWS["merges"] += 1
    # output code i is relative to output row i-1: neighbor rows share
    # a KEY iff the first difference sits past the key lanes
    eq = (out_codes[1:] >> np.uint64(32)) \
        <= np.uint64(num_lanes - num_key_lanes)
    from paimon_tpu.ops.merge import _winner_epilogue
    return _winner_epilogue(perm, eq, keep)
