"""Low-overhead structured span tracing.

Design constraints, in priority order:

1. **No-op when disabled.**  ``span(...)`` is called on every pipeline
   stage of every hot path; with tracing off it must cost one module
   flag check.  The disabled call returns a shared singleton context
   manager (no allocation beyond the caller's kwargs dict, which is
   built per *stage* — per split / per flush / per window — never per
   row).  `benchmarks/micro.py` ``obs`` measures the disabled path at
   <2% of scan wall time vs an uninstrumented baseline, asserted by a
   tier-1 test (tests/test_obs.py).
2. **Thread-safe bounded collection.**  Spans land in a ring
   (`collections.deque(maxlen=trace.buffer.spans)`) under one lock;
   an unbounded trace can never OOM a long-running service.
3. **Nestable.**  A `contextvars.ContextVar` tracks the current span,
   so children record their parent id without any caller plumbing.
   Worker threads start fresh contexts, which is exactly right: each
   pool thread is its own track in the Chrome trace.
4. **One timing, two sinks.**  A span that names a ``group``/``metric``
   also lands its duration in that metric group's latency histogram
   (`metrics.py`), so the registry snapshot and the trace timeline can
   never disagree about what was measured.

Enabling is process-global (the planes share thread pools, so
per-table tracing would tear one timeline into halves): call
`enable_tracing()` / `disable_tracing()` directly (CLI `--trace`,
tests), or set the `trace.enabled` / `metrics.enabled` table options —
every pipeline entry point calls `sync_from_options`, where an
explicitly-set key wins and an absent key leaves the current state
untouched (so an explicit `enable_tracing()` is not silently reverted
by the next untraced table's scan).
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import platform
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["Span", "TraceCollector", "span", "enable_tracing",
           "disable_tracing", "tracing_enabled", "set_metrics_enabled",
           "metrics_enabled", "collector", "take_spans",
           "sync_from_options", "export_path", "export_dir",
           "set_export_dir", "process_tag", "set_replica_id",
           "new_trace_id", "current_trace_id", "current_context_token",
           "inject_headers", "server_span", "spool_flush",
           "reset_spool"]

DEFAULT_BUFFER_SPANS = 8192

# Span stage names introduced by the fleet plane.  Producers use
# these BY NAME (the analysis-plane obs-drift rule checks that every
# STAGE_* constant has a producer in the package), so a renamed
# stage that loses its producer fails analysis instead of silently
# vanishing from the merged timeline.
STAGE_SERVE_REQUEST = "serve.request"
STAGE_CLIENT_REQUEST = "client.request"
STAGE_PLAN_LINK = "plan.link"
STAGE_LEASE_FOLD = "lease.fold"

# Header names of the W3C-style context carried on every serving hop.
HDR_TRACE_ID = "X-Trace-Id"
HDR_PARENT_SPAN = "X-Parent-Span"


class Span:
    """One completed timed region. `start_us` is microseconds on the
    process-wide perf_counter timeline (Chrome trace ts unit)."""

    __slots__ = ("span_id", "parent_id", "name", "cat", "start_us",
                 "dur_us", "tid", "thread", "attrs")

    def __init__(self, span_id: int, parent_id: Optional[int], name: str,
                 cat: str, start_us: float, dur_us: float, tid: int,
                 thread: str, attrs: Dict):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.cat = cat
        self.start_us = start_us
        self.dur_us = dur_us
        self.tid = tid
        self.thread = thread
        self.attrs = attrs

    def overlaps(self, other: "Span") -> bool:
        """Wall-clock interval intersection (tests/benchmarks)."""
        return self.start_us < other.start_us + other.dur_us and \
            other.start_us < self.start_us + self.dur_us

    def __repr__(self):
        return (f"Span({self.name!r}, {self.dur_us / 1000.0:.3f}ms, "
                f"thread={self.thread!r}, attrs={self.attrs})")


class TraceCollector:
    """Thread-safe bounded span ring; oldest spans evict first."""

    def __init__(self, max_spans: int = DEFAULT_BUFFER_SPANS):
        self.max_spans = max(1, int(max_spans))
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=self.max_spans)
        self.dropped = 0          # evicted by the ring bound

    def add(self, s: Span):
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
            self._spans.append(s)

    def snapshot(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self):
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    def resize(self, max_spans: int):
        max_spans = max(1, int(max_spans))
        with self._lock:
            if max_spans != self.max_spans:
                self.max_spans = max_spans
                self._spans = deque(self._spans, maxlen=max_spans)

    def __len__(self):
        with self._lock:
            return len(self._spans)


# -- process-global state ---------------------------------------------------

_enabled = False
_metrics_on = True
_collector = TraceCollector()
_export_path: Optional[str] = None
_export_dir: Optional[str] = None
_ids = itertools.count(1)
_current: contextvars.ContextVar = contextvars.ContextVar(
    "paimon_current_span", default=None)
_trace_id: contextvars.ContextVar = contextvars.ContextVar(
    "paimon_trace_id", default=None)

# Process identity for cross-process span references.  The OS reuses
# pids, so a random salt keeps tokens unique across a fleet's whole
# lifetime (a crashed worker's pid can be handed to its replacement).
_PROC = "%s-%d-%s" % (platform.node(), os.getpid(),
                      os.urandom(3).hex())
_replica_id: Optional[str] = None

# Spool bookkeeping: the per-process .jsonl under `trace.export.dir`
# is append-only; `_spooled_through` is the highest span id already on
# disk so repeated flushes never duplicate lines.
_spool_lock = threading.Lock()
_spooled_through = 0
_spool_header_done = False


class _NoopSpan:
    """Shared do-nothing context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NOOP = _NoopSpan()


class _MetricSpan:
    """Tracing disabled, metrics enabled: time the region into its
    latency histogram only — no ring append, no contextvar."""

    __slots__ = ("group", "metric", "t0")

    def __init__(self, group: str, metric: str):
        self.group = group
        self.metric = metric

    def set(self, **attrs):
        return self

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        from paimon_tpu.metrics import global_registry
        global_registry().group(self.group).histogram(self.metric) \
            .update((time.perf_counter() - self.t0) * 1000.0)
        return False


class _LiveSpan:
    """Tracing enabled: full span with nesting + ring + histogram."""

    __slots__ = ("name", "cat", "group", "metric", "attrs", "t0",
                 "span_id", "_token")

    def __init__(self, name: str, cat: str, group: Optional[str],
                 metric: Optional[str], attrs: Dict):
        self.name = name
        self.cat = cat
        self.group = group
        self.metric = metric
        self.attrs = attrs

    def set(self, **attrs):
        """Attach attrs mid-span (e.g. a result size known at the end)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        self.span_id = next(_ids)
        self._token = _current.set(self.span_id)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        _current.reset(self._token)
        parent = _current.get()
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        t = threading.current_thread()
        _collector.add(Span(
            self.span_id, parent, self.name, self.cat,
            self.t0 * 1e6, (t1 - self.t0) * 1e6,
            t.ident or 0, t.name, self.attrs))
        if self.group is not None and _metrics_on:
            from paimon_tpu.metrics import global_registry
            global_registry().group(self.group).histogram(self.metric) \
                .update((t1 - self.t0) * 1000.0)
        return False


def span(name: str, *, cat: str = "", group: Optional[str] = None,
         metric: Optional[str] = None, **attrs):
    """Context manager timing one stage.

    `cat` buckets spans for the Chrome trace; `group`+`metric` also
    land the duration in `global_registry().group(group)`'s
    `histogram(metric)` (use the *_MS constants from metrics.py so the
    name-drift test sees the producer).  Extra kwargs become span
    attributes (table/partition/bucket/snapshot/attempt...) — pass raw
    values, stringification happens at export time.
    """
    if not _enabled:
        if group is not None and _metrics_on:
            return _MetricSpan(group, metric or name)
        return _NOOP
    return _LiveSpan(name, cat, group, metric or name, attrs)


# -- cross-process trace context --------------------------------------------

def process_tag() -> str:
    """Stable identity of this process inside a fleet trace:
    ``<host>-<pid>-<salt>``.  Span references across process
    boundaries are ``<process_tag>:<span_id>`` tokens."""
    return _PROC


def set_replica_id(replica_id: Optional[str]) -> None:
    """Tag this process's spool with a serving replica id so merged
    traces name tracks by replica, not just host-pid."""
    global _replica_id
    _replica_id = replica_id


def new_trace_id() -> str:
    """Fresh 128-bit trace id (32 hex chars, W3C trace-id shaped)."""
    return os.urandom(16).hex()


def current_trace_id() -> Optional[str]:
    return _trace_id.get()


def current_context_token() -> Optional[str]:
    """``<process_tag>:<span_id>`` of the current span, or None when
    no span is open (or tracing is off).  This is what gets stamped
    into snapshot commit properties and the X-Parent-Span header."""
    if not _enabled:
        return None
    sid = _current.get()
    if sid is None:
        return None
    return f"{_PROC}:{sid}"


def inject_headers(headers: Dict[str, str]) -> Dict[str, str]:
    """Add the W3C-style context headers to an outbound request.  A
    no-op unless tracing is on and a span is current; allocates a
    trace id lazily so the first hop of a request mints it."""
    if not _enabled:
        return headers
    sid = _current.get()
    if sid is None:
        return headers
    tid = _trace_id.get()
    if tid is None:
        tid = new_trace_id()
        _trace_id.set(tid)
    headers[HDR_TRACE_ID] = tid
    headers[HDR_PARENT_SPAN] = f"{_PROC}:{sid}"
    return headers


class _AdoptedSpan:
    """Server-side request span that adopts the remote caller's
    context: the trace id rides the contextvar for the handler's
    duration, and the remote parent token lands in the span attrs
    (``remote_parent``), where the fleet merge tool turns it into a
    flow arrow between the two processes' tracks."""

    __slots__ = ("_headers", "_attrs", "_inner", "_tid_token")

    def __init__(self, headers: Dict[str, str], attrs: Dict):
        self._headers = headers
        self._attrs = attrs

    def __enter__(self):
        tid = self._headers.get("x-trace-id")
        parent = self._headers.get("x-parent-span")
        self._tid_token = _trace_id.set(tid) if tid else None
        if tid:
            self._attrs["trace_id"] = tid
        if parent:
            self._attrs["remote_parent"] = parent
        self._inner = _LiveSpan(STAGE_SERVE_REQUEST, "serve", None,
                                None, self._attrs)
        self._inner.__enter__()
        return self._inner

    def __exit__(self, exc_type, exc, tb):
        r = self._inner.__exit__(exc_type, exc, tb)
        if self._tid_token is not None:
            _trace_id.reset(self._tid_token)
        return r


def server_span(headers: Optional[Dict[str, str]], **attrs):
    """Context manager wrapping one inbound request's handler; the
    shared no-op when tracing is off (one flag check on the serving
    hot path).  `headers` are the request's lower-cased headers."""
    if not _enabled:
        return _NOOP
    return _AdoptedSpan(headers or {}, attrs)


# -- per-process spool under trace.export.dir -------------------------------

def _jsonable(v):
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return str(v)


def spool_flush() -> Optional[str]:
    """Append spans newer than the last flush to this process's
    ``<trace.export.dir>/<process_tag>.jsonl``; returns the spool path
    or None when no dir is configured.  The first line is a process
    header carrying identity plus a (wall clock, perf_counter) anchor
    pair — span timestamps are on the process-local perf_counter
    timeline, and the merge tool uses the anchor to re-base every
    process onto one shared wall-clock timeline.

    Like `maybe_export`, a spool failure warns instead of raising: the
    recorder must never fail the data path it observes."""
    global _spooled_through, _spool_header_done
    if _export_dir is None:
        return None
    spans = _collector.snapshot()
    path = os.path.join(_export_dir, _PROC + ".jsonl")
    with _spool_lock:
        fresh = [s for s in spans if s.span_id > _spooled_through]
        try:
            os.makedirs(_export_dir, exist_ok=True)
            with open(path, "a") as f:
                if not _spool_header_done:
                    f.write(json.dumps({
                        "proc": _PROC, "pid": os.getpid(),
                        "host": platform.node(),
                        "replica": _replica_id,
                        "wall_s": time.time(),
                        "perf_s": time.perf_counter(),
                    }) + "\n")
                    _spool_header_done = True
                for s in fresh:
                    f.write(json.dumps({
                        "sid": s.span_id, "parent": s.parent_id,
                        "name": s.name, "cat": s.cat,
                        "ts": round(s.start_us, 3),
                        "dur": round(s.dur_us, 3),
                        "tid": s.tid, "thread": s.thread,
                        "attrs": {k: _jsonable(v)
                                  for k, v in s.attrs.items()},
                    }) + "\n")
        except OSError as e:
            import warnings
            warnings.warn(f"trace spool to {path!r} failed: {e}",
                          RuntimeWarning)
            return None
        if fresh:
            _spooled_through = max(_spooled_through,
                                   max(s.span_id for s in fresh))
    return path


def reset_spool() -> None:
    """Forget spool state (tests): the next flush rewrites the header
    and re-spools the whole ring to a fresh file."""
    global _spooled_through, _spool_header_done
    with _spool_lock:
        _spooled_through = 0
        _spool_header_done = False


def set_export_dir(d: Optional[str]) -> None:
    global _export_dir
    if d != _export_dir:
        _export_dir = d
        reset_spool()


def export_dir() -> Optional[str]:
    return _export_dir


# -- switches ----------------------------------------------------------------

def enable_tracing(max_spans: Optional[int] = None):
    global _enabled
    if max_spans is not None:
        _collector.resize(max_spans)
    _enabled = True


def disable_tracing():
    global _enabled
    _enabled = False


def tracing_enabled() -> bool:
    return _enabled


def set_metrics_enabled(flag: bool):
    global _metrics_on
    _metrics_on = bool(flag)


def metrics_enabled() -> bool:
    return _metrics_on


def collector() -> TraceCollector:
    return _collector


def take_spans(clear: bool = False) -> List[Span]:
    out = _collector.snapshot()
    if clear:
        _collector.clear()
    return out


def export_path() -> Optional[str]:
    return _export_path


def sync_from_options(options) -> None:
    """Sync the process-global switches from a table's options at a
    pipeline entry point.  Explicitly-set keys win; absent keys leave
    the current state untouched.  `options` is a CoreOptions (or
    anything exposing `.options` with contains/get), or None."""
    global _export_path
    if options is None:
        return
    raw = getattr(options, "options", None)
    if raw is None or not hasattr(raw, "contains"):
        return
    from paimon_tpu.options import CoreOptions
    if raw.contains(CoreOptions.TRACE_ENABLED):
        if raw.get(CoreOptions.TRACE_ENABLED):
            # only resize when the key is explicitly set — the option
            # DEFAULT must not shrink a ring a caller enlarged via
            # enable_tracing(max_spans=...) (resizing drops spans)
            enable_tracing(
                raw.get(CoreOptions.TRACE_BUFFER_SPANS)
                if raw.contains(CoreOptions.TRACE_BUFFER_SPANS)
                else None)
        else:
            disable_tracing()
    if raw.contains(CoreOptions.METRICS_ENABLED):
        set_metrics_enabled(bool(raw.get(CoreOptions.METRICS_ENABLED)))
    if raw.contains(CoreOptions.TRACE_EXPORT_PATH):
        _export_path = raw.get(CoreOptions.TRACE_EXPORT_PATH)
    if raw.contains(CoreOptions.TRACE_EXPORT_DIR):
        set_export_dir(raw.get(CoreOptions.TRACE_EXPORT_DIR))


def maybe_export() -> Optional[str]:
    """Flush the ring to `trace.export.path` if configured (called at
    pipeline completion points); returns the path written, or None.

    An export failure (unwritable path) must never fail — or, from a
    `finally`, MASK the error of — the data path it observes: it
    warns and returns None instead."""
    if not _enabled:
        return None
    if _export_dir is not None:
        spool_flush()
    if _export_path is None:
        return None
    from paimon_tpu.obs.export import export_chrome_trace
    try:
        export_chrome_trace(_export_path)
    except OSError as e:
        import warnings
        warnings.warn(f"trace export to {_export_path!r} failed: {e}",
                      RuntimeWarning)
        return None
    return _export_path
