"""Low-overhead structured span tracing.

Design constraints, in priority order:

1. **No-op when disabled.**  ``span(...)`` is called on every pipeline
   stage of every hot path; with tracing off it must cost one module
   flag check.  The disabled call returns a shared singleton context
   manager (no allocation beyond the caller's kwargs dict, which is
   built per *stage* — per split / per flush / per window — never per
   row).  `benchmarks/micro.py` ``obs`` measures the disabled path at
   <2% of scan wall time vs an uninstrumented baseline, asserted by a
   tier-1 test (tests/test_obs.py).
2. **Thread-safe bounded collection.**  Spans land in a ring
   (`collections.deque(maxlen=trace.buffer.spans)`) under one lock;
   an unbounded trace can never OOM a long-running service.
3. **Nestable.**  A `contextvars.ContextVar` tracks the current span,
   so children record their parent id without any caller plumbing.
   Worker threads start fresh contexts, which is exactly right: each
   pool thread is its own track in the Chrome trace.
4. **One timing, two sinks.**  A span that names a ``group``/``metric``
   also lands its duration in that metric group's latency histogram
   (`metrics.py`), so the registry snapshot and the trace timeline can
   never disagree about what was measured.

Enabling is process-global (the planes share thread pools, so
per-table tracing would tear one timeline into halves): call
`enable_tracing()` / `disable_tracing()` directly (CLI `--trace`,
tests), or set the `trace.enabled` / `metrics.enabled` table options —
every pipeline entry point calls `sync_from_options`, where an
explicitly-set key wins and an absent key leaves the current state
untouched (so an explicit `enable_tracing()` is not silently reverted
by the next untraced table's scan).
"""

from __future__ import annotations

import contextvars
import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["Span", "TraceCollector", "span", "enable_tracing",
           "disable_tracing", "tracing_enabled", "set_metrics_enabled",
           "metrics_enabled", "collector", "take_spans",
           "sync_from_options", "export_path"]

DEFAULT_BUFFER_SPANS = 8192


class Span:
    """One completed timed region. `start_us` is microseconds on the
    process-wide perf_counter timeline (Chrome trace ts unit)."""

    __slots__ = ("span_id", "parent_id", "name", "cat", "start_us",
                 "dur_us", "tid", "thread", "attrs")

    def __init__(self, span_id: int, parent_id: Optional[int], name: str,
                 cat: str, start_us: float, dur_us: float, tid: int,
                 thread: str, attrs: Dict):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.cat = cat
        self.start_us = start_us
        self.dur_us = dur_us
        self.tid = tid
        self.thread = thread
        self.attrs = attrs

    def overlaps(self, other: "Span") -> bool:
        """Wall-clock interval intersection (tests/benchmarks)."""
        return self.start_us < other.start_us + other.dur_us and \
            other.start_us < self.start_us + self.dur_us

    def __repr__(self):
        return (f"Span({self.name!r}, {self.dur_us / 1000.0:.3f}ms, "
                f"thread={self.thread!r}, attrs={self.attrs})")


class TraceCollector:
    """Thread-safe bounded span ring; oldest spans evict first."""

    def __init__(self, max_spans: int = DEFAULT_BUFFER_SPANS):
        self.max_spans = max(1, int(max_spans))
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=self.max_spans)
        self.dropped = 0          # evicted by the ring bound

    def add(self, s: Span):
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
            self._spans.append(s)

    def snapshot(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self):
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    def resize(self, max_spans: int):
        max_spans = max(1, int(max_spans))
        with self._lock:
            if max_spans != self.max_spans:
                self.max_spans = max_spans
                self._spans = deque(self._spans, maxlen=max_spans)

    def __len__(self):
        with self._lock:
            return len(self._spans)


# -- process-global state ---------------------------------------------------

_enabled = False
_metrics_on = True
_collector = TraceCollector()
_export_path: Optional[str] = None
_ids = itertools.count(1)
_current: contextvars.ContextVar = contextvars.ContextVar(
    "paimon_current_span", default=None)


class _NoopSpan:
    """Shared do-nothing context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NOOP = _NoopSpan()


class _MetricSpan:
    """Tracing disabled, metrics enabled: time the region into its
    latency histogram only — no ring append, no contextvar."""

    __slots__ = ("group", "metric", "t0")

    def __init__(self, group: str, metric: str):
        self.group = group
        self.metric = metric

    def set(self, **attrs):
        return self

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        from paimon_tpu.metrics import global_registry
        global_registry().group(self.group).histogram(self.metric) \
            .update((time.perf_counter() - self.t0) * 1000.0)
        return False


class _LiveSpan:
    """Tracing enabled: full span with nesting + ring + histogram."""

    __slots__ = ("name", "cat", "group", "metric", "attrs", "t0",
                 "span_id", "_token")

    def __init__(self, name: str, cat: str, group: Optional[str],
                 metric: Optional[str], attrs: Dict):
        self.name = name
        self.cat = cat
        self.group = group
        self.metric = metric
        self.attrs = attrs

    def set(self, **attrs):
        """Attach attrs mid-span (e.g. a result size known at the end)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        self.span_id = next(_ids)
        self._token = _current.set(self.span_id)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        _current.reset(self._token)
        parent = _current.get()
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        t = threading.current_thread()
        _collector.add(Span(
            self.span_id, parent, self.name, self.cat,
            self.t0 * 1e6, (t1 - self.t0) * 1e6,
            t.ident or 0, t.name, self.attrs))
        if self.group is not None and _metrics_on:
            from paimon_tpu.metrics import global_registry
            global_registry().group(self.group).histogram(self.metric) \
                .update((t1 - self.t0) * 1000.0)
        return False


def span(name: str, *, cat: str = "", group: Optional[str] = None,
         metric: Optional[str] = None, **attrs):
    """Context manager timing one stage.

    `cat` buckets spans for the Chrome trace; `group`+`metric` also
    land the duration in `global_registry().group(group)`'s
    `histogram(metric)` (use the *_MS constants from metrics.py so the
    name-drift test sees the producer).  Extra kwargs become span
    attributes (table/partition/bucket/snapshot/attempt...) — pass raw
    values, stringification happens at export time.
    """
    if not _enabled:
        if group is not None and _metrics_on:
            return _MetricSpan(group, metric or name)
        return _NOOP
    return _LiveSpan(name, cat, group, metric or name, attrs)


# -- switches ----------------------------------------------------------------

def enable_tracing(max_spans: Optional[int] = None):
    global _enabled
    if max_spans is not None:
        _collector.resize(max_spans)
    _enabled = True


def disable_tracing():
    global _enabled
    _enabled = False


def tracing_enabled() -> bool:
    return _enabled


def set_metrics_enabled(flag: bool):
    global _metrics_on
    _metrics_on = bool(flag)


def metrics_enabled() -> bool:
    return _metrics_on


def collector() -> TraceCollector:
    return _collector


def take_spans(clear: bool = False) -> List[Span]:
    out = _collector.snapshot()
    if clear:
        _collector.clear()
    return out


def export_path() -> Optional[str]:
    return _export_path


def sync_from_options(options) -> None:
    """Sync the process-global switches from a table's options at a
    pipeline entry point.  Explicitly-set keys win; absent keys leave
    the current state untouched.  `options` is a CoreOptions (or
    anything exposing `.options` with contains/get), or None."""
    global _export_path
    if options is None:
        return
    raw = getattr(options, "options", None)
    if raw is None or not hasattr(raw, "contains"):
        return
    from paimon_tpu.options import CoreOptions
    if raw.contains(CoreOptions.TRACE_ENABLED):
        if raw.get(CoreOptions.TRACE_ENABLED):
            # only resize when the key is explicitly set — the option
            # DEFAULT must not shrink a ring a caller enlarged via
            # enable_tracing(max_spans=...) (resizing drops spans)
            enable_tracing(
                raw.get(CoreOptions.TRACE_BUFFER_SPANS)
                if raw.contains(CoreOptions.TRACE_BUFFER_SPANS)
                else None)
        else:
            disable_tracing()
    if raw.contains(CoreOptions.METRICS_ENABLED):
        set_metrics_enabled(bool(raw.get(CoreOptions.METRICS_ENABLED)))
    if raw.contains(CoreOptions.TRACE_EXPORT_PATH):
        _export_path = raw.get(CoreOptions.TRACE_EXPORT_PATH)


def maybe_export() -> Optional[str]:
    """Flush the ring to `trace.export.path` if configured (called at
    pipeline completion points); returns the path written, or None.

    An export failure (unwritable path) must never fail — or, from a
    `finally`, MASK the error of — the data path it observes: it
    warns and returns None instead."""
    if _export_path is None or not _enabled:
        return None
    from paimon_tpu.obs.export import export_chrome_trace
    try:
        export_chrome_trace(_export_path)
    except OSError as e:
        import warnings
        warnings.warn(f"trace export to {_export_path!r} failed: {e}",
                      RuntimeWarning)
        return None
    return _export_path
