"""Trace + metrics serialization: Chrome trace-event JSON (opens in
Perfetto / chrome://tracing) and Prometheus text exposition.

Both surfaces render from the shared collection points — the span ring
(obs/trace.py) and `MetricRegistry.snapshot_rows()` — so the timeline,
the `$metrics`/`$traces` system tables, the `/metrics` endpoint and the
bench snapshots can never disagree.
"""

from __future__ import annotations

import json
import re
from typing import Dict, List, Optional, Sequence

__all__ = ["to_chrome_trace", "export_chrome_trace",
           "render_prometheus"]

_PID = 1          # one process per trace; threads are the tracks


def _jsonable(v):
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return str(v)


def to_chrome_trace(spans: Sequence) -> Dict:
    """Chrome trace-event JSON object for a span list.  Every span
    becomes a complete ("X") event on its thread's track, so worker
    threads render as parallel tracks and IO/decode/merge overlap is
    visible (and machine-checkable) directly from the file.

    Tracks are keyed by (thread name, ident): the OS reuses idents as
    pools come and go, so ident alone would fold a scan worker onto a
    dead write worker's track — while two concurrently-live pools can
    both own a "paimon-scan_0", so name alone would merge two distinct
    workers into bogus nesting.  The pair is unique per live thread
    and stable across the span list."""
    events: List[Dict] = []
    track_ids: Dict[tuple, int] = {}
    track_names: Dict[int, str] = {}
    for s in spans:
        tid = track_ids.setdefault((s.thread, s.tid),
                                   len(track_ids) + 1)
        track_names[tid] = s.thread
        events.append({
            "name": s.name,
            "cat": s.cat or "span",
            "ph": "X",
            "ts": round(s.start_us, 3),
            "dur": round(max(s.dur_us, 0.001), 3),
            "pid": _PID,
            "tid": tid,
            "args": {k: _jsonable(v) for k, v in s.attrs.items()},
        })
    for tid, name in track_names.items():
        events.append({"ph": "M", "name": "thread_name", "pid": _PID,
                       "tid": tid, "args": {"name": name}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_chrome_trace(path: str, spans: Optional[Sequence] = None,
                        clear: bool = False) -> str:
    """Write the span ring (or an explicit span list) as Chrome trace
    JSON; returns the path."""
    from paimon_tpu.obs.trace import take_spans
    if spans is None:
        spans = take_spans(clear=clear)
    with open(path, "w") as f:
        json.dump(to_chrome_trace(spans), f)
    return path


# -- Prometheus text exposition ---------------------------------------------

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(group: str, metric: str) -> str:
    return _NAME_RE.sub("_", f"paimon_{group}_{metric}")


def _prom_labels(table: str) -> str:
    if not table:
        return ""
    esc = table.replace("\\", "\\\\").replace('"', '\\"')
    return '{table="' + esc + '"}'


def _fmt(v) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def render_prometheus(rows: Optional[List[Dict]] = None) -> str:
    """Prometheus text exposition (format 0.0.4) of the registry.

    Counters/gauges map 1:1; histograms render as summaries — the p95
    quantile comes from the sliding window, while `_sum`/`_count` are
    the histogram's CUMULATIVE totals (monotonic, as rate()/increase()
    require; window-derived values would cap at the window size) —
    plus a `_max` gauge over the window.  Rows that carry cumulative
    `buckets` additionally render a REAL `le`-bucket histogram family
    under `<base>_hist` (0.0.4 forbids mixing summary and histogram
    samples in one family, and the summary name is the compatibility
    surface), so an external Prometheus can pool
    `histogram_quantile(0.99, sum by (le) (rate(..._hist_bucket[5m])))`
    across replicas — per-replica quantiles can't be aggregated, shared
    fixed buckets can.  `rows` defaults to
    `global_registry().snapshot_rows()`, THE shared serialization
    point.
    """
    if rows is None:
        from paimon_tpu.metrics import global_registry
        rows = global_registry().snapshot_rows()
    # family name -> (kind, [(labels, line-suffix, value)])
    families: Dict[str, List] = {}
    kinds: Dict[str, str] = {}
    for r in rows:
        labels = _prom_labels(r.get("table", ""))
        if r["kind"] == "histogram":
            base = _prom_name(r["group"], r["metric"])
            kinds[base] = "summary"
            fam = families.setdefault(base, [])
            q = '{quantile="0.95"}' if not labels else \
                labels[:-1] + ',quantile="0.95"}'
            fam.append((base + q, r["p95"]))
            fam.append((base + "_sum" + labels,
                        r.get("total_sum", r["mean"] * r["count"])))
            fam.append((base + "_count" + labels,
                        r.get("total_count", r["count"])))
            mx = base + "_max"
            kinds[mx] = "gauge"
            families.setdefault(mx, []).append((mx + labels, r["max"]))
            if r.get("buckets"):
                hist = base + "_hist"
                kinds[hist] = "histogram"
                hf = families.setdefault(hist, [])
                for bound, n in r["buckets"]:
                    le = "+Inf" if bound == float("inf") \
                        else _fmt(bound)
                    lb = '{le="%s"}' % le if not labels else \
                        labels[:-1] + ',le="%s"}' % le
                    hf.append((hist + "_bucket" + lb, n))
                hf.append((hist + "_sum" + labels,
                           r.get("total_sum", 0.0)))
                hf.append((hist + "_count" + labels,
                           r.get("total_count", 0)))
        else:
            name = _prom_name(r["group"], r["metric"])
            kinds[name] = "counter" if r["kind"] == "counter" else "gauge"
            families.setdefault(name, []).append(
                (name + labels, r["value"]))
    lines: List[str] = []
    for fam in sorted(families):
        lines.append(f"# TYPE {fam} {kinds[fam]}")
        for series, value in families[fam]:
            lines.append(f"{series} {_fmt(value)}")
    return "\n".join(lines) + ("\n" if lines else "")
