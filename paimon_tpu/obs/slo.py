"""Declarative SLOs evaluated as multi-window burn rates.

An objective is a statement like "99.9% of requests succeed" or "99%
of requests finish under 250ms".  The evaluator turns the serving
plane's per-request outcomes into **burn rates**: the observed
bad-event rate divided by the error budget ``1 - target``.  Burn 1.0
means the budget is being spent exactly as fast as the objective
allows; burn 10 means a month-long budget is gone in three days.

Alerting follows the multi-window multi-burn-rate recipe (Google SRE
workbook): the alert fires only when BOTH a fast window (detects
quickly, flaps easily) and a slow window (stable, detects slowly)
burn above the threshold, and clears as soon as either cools.  Both
windows slide over one bounded event deque, so a replica's evaluator
is O(window) memory no matter how long it serves.

Surfaces: ``GET /slo`` per replica (query_service), the router's
fleet-wide aggregate (worst burn wins), `paimon fleet status`, and the
pre-allocated `slo` Prometheus group (metrics.py SLO_* names).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from paimon_tpu.metrics import (
    SLO_ALERT, SLO_AVAILABILITY_BURN_FAST, SLO_AVAILABILITY_BURN_SLOW,
    SLO_BAD_EVENTS, SLO_GOOD_EVENTS, SLO_LATENCY_BURN_FAST,
    SLO_LATENCY_BURN_SLOW,
)

__all__ = ["SloConfig", "SloEvaluator", "aggregate_slo"]

# Availability bad-events: everything the objective's user would call
# a failed request — load-shed (429) and server errors including
# deadline 504s.  4xx caller mistakes don't spend the server's budget.
_BAD_STATUS_FLOOR = 500
_BAD_STATUS_EXTRA = (429,)

MAX_EVENTS = 65536


class SloConfig:
    """Parsed `service.slo.*` options with the declared objectives."""

    def __init__(self, enabled: bool = True,
                 availability_target: float = 0.999,
                 latency_p99_ms: float = 250.0,
                 fast_window_s: float = 300.0,
                 slow_window_s: float = 3600.0,
                 burn_threshold: float = 2.0):
        self.enabled = enabled
        self.availability_target = min(max(availability_target, 0.0),
                                       0.999999)
        self.latency_p99_ms = latency_p99_ms
        self.fast_window_s = fast_window_s
        self.slow_window_s = max(slow_window_s, fast_window_s)
        self.burn_threshold = burn_threshold

    @classmethod
    def from_options(cls, options) -> "SloConfig":
        from paimon_tpu.options import CoreOptions
        o = options.options if hasattr(options, "options") else options
        return cls(
            enabled=o.get(CoreOptions.SERVICE_SLO_ENABLED),
            availability_target=o.get(
                CoreOptions.SERVICE_SLO_AVAILABILITY_TARGET),
            latency_p99_ms=o.get(CoreOptions.SERVICE_SLO_LATENCY_P99_MS),
            fast_window_s=o.get(CoreOptions.SERVICE_SLO_FAST_WINDOW_S),
            slow_window_s=o.get(CoreOptions.SERVICE_SLO_SLOW_WINDOW_S),
            burn_threshold=o.get(
                CoreOptions.SERVICE_SLO_BURN_THRESHOLD))


class SloEvaluator:
    """Per-replica burn-rate evaluator fed one (status, duration)
    pair per served request.  `clock` is injectable so storm tests can
    march time instead of sleeping."""

    def __init__(self, config: Optional[SloConfig] = None,
                 table: str = "", clock=time.monotonic):
        self.config = config or SloConfig()
        self._clock = clock
        self._lock = threading.Lock()
        # (t, ok, over_latency) per request, oldest first
        self._events: deque = deque(maxlen=MAX_EVENTS)
        self._good = 0
        self._bad = 0
        from paimon_tpu.metrics import global_registry
        g = global_registry().slo_metrics(table)
        self._g_av_fast = g.gauge(SLO_AVAILABILITY_BURN_FAST)
        self._g_av_slow = g.gauge(SLO_AVAILABILITY_BURN_SLOW)
        self._g_lat_fast = g.gauge(SLO_LATENCY_BURN_FAST)
        self._g_lat_slow = g.gauge(SLO_LATENCY_BURN_SLOW)
        self._g_alert = g.gauge(SLO_ALERT)
        self._c_good = g.counter(SLO_GOOD_EVENTS)
        self._c_bad = g.counter(SLO_BAD_EVENTS)

    def observe(self, status: int, dur_ms: float) -> None:
        if not self.config.enabled:
            return
        ok = status < _BAD_STATUS_FLOOR and \
            status not in _BAD_STATUS_EXTRA
        over = dur_ms > self.config.latency_p99_ms
        now = self._clock()
        horizon = now - self.config.slow_window_s
        with self._lock:
            self._events.append((now, ok, over))
            while self._events and self._events[0][0] < horizon:
                self._events.popleft()
            if ok:
                self._good += 1
            else:
                self._bad += 1
        (self._c_good if ok else self._c_bad).inc()

    @staticmethod
    def _burn(bad: int, total: int, budget: float) -> float:
        if total == 0:
            return 0.0
        return (bad / total) / budget

    def evaluate(self) -> Dict:
        """Burn rates + alert state now; also refreshes the `slo`
        metric gauges so a scrape and this dict can't disagree."""
        cfg = self.config
        now = self._clock()
        with self._lock:
            events = list(self._events)
        win: Dict[str, List] = {
            "fast": [e for e in events
                     if e[0] >= now - cfg.fast_window_s],
            "slow": [e for e in events
                     if e[0] >= now - cfg.slow_window_s],
        }
        av_budget = 1.0 - cfg.availability_target
        lat_budget = 0.01          # latency objective is a p99
        burns = {}
        for wname, evs in win.items():
            total = len(evs)
            burns["availability_" + wname] = self._burn(
                sum(1 for e in evs if not e[1]), total, av_budget)
            burns["latency_" + wname] = self._burn(
                sum(1 for e in evs if e[2]), total, lat_budget)
        thr = cfg.burn_threshold
        av_alert = burns["availability_fast"] >= thr and \
            burns["availability_slow"] >= thr
        lat_alert = burns["latency_fast"] >= thr and \
            burns["latency_slow"] >= thr
        alert = av_alert or lat_alert
        self._g_av_fast.set(burns["availability_fast"])
        self._g_av_slow.set(burns["availability_slow"])
        self._g_lat_fast.set(burns["latency_fast"])
        self._g_lat_slow.set(burns["latency_slow"])
        self._g_alert.set(1.0 if alert else 0.0)
        return {
            "enabled": cfg.enabled,
            "objectives": {
                "availability": {
                    "target": cfg.availability_target,
                    "burn_fast": round(burns["availability_fast"], 4),
                    "burn_slow": round(burns["availability_slow"], 4),
                    "alert": av_alert,
                },
                "latency": {
                    "p99_ms": cfg.latency_p99_ms,
                    "burn_fast": round(burns["latency_fast"], 4),
                    "burn_slow": round(burns["latency_slow"], 4),
                    "alert": lat_alert,
                },
            },
            "windows_s": {"fast": cfg.fast_window_s,
                          "slow": cfg.slow_window_s},
            "burn_threshold": thr,
            "alert": alert,
            "good_events": self._good,
            "bad_events": self._bad,
        }


def aggregate_slo(per_replica: Dict[str, Dict]) -> Dict:
    """Fleet rollup of per-replica `/slo` documents (router): the
    fleet burn for each objective is the WORST replica's burn (an SLO
    is violated wherever any user lands), the alert is the OR, and
    event counts sum.  Replicas that failed to answer are listed in
    `unreachable` instead of poisoning the rollup."""
    worst = {"availability": {"burn_fast": 0.0, "burn_slow": 0.0},
             "latency": {"burn_fast": 0.0, "burn_slow": 0.0}}
    alert = False
    good = bad = 0
    reachable = {}
    unreachable = []
    for rid, doc in sorted(per_replica.items()):
        if not isinstance(doc, dict) or "objectives" not in doc:
            unreachable.append(rid)
            continue
        reachable[rid] = doc
        alert = alert or bool(doc.get("alert"))
        good += int(doc.get("good_events", 0))
        bad += int(doc.get("bad_events", 0))
        for obj in ("availability", "latency"):
            for w in ("burn_fast", "burn_slow"):
                v = float(doc["objectives"][obj].get(w, 0.0))
                worst[obj][w] = max(worst[obj][w], v)
    return {
        "replicas": len(reachable),
        "unreachable": unreachable,
        "alert": alert,
        "objectives": worst,
        "good_events": good,
        "bad_events": bad,
        "per_replica": reachable,
    }
