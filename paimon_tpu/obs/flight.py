"""Black-box flight recorder: an always-on bounded ring of the
*operationally interesting* events — the things an operator wants to
see from the minutes BEFORE a crash, which the span ring (sized for
hot-path stages) has long since evicted.

Feeds (each imports its EV_* constant by name, so the analysis-plane
obs-drift rule proves every event kind still has a producer):

* ``parallel/fault.py``     — retry ladder arms        (EV_RETRY)
* ``fs/resilience.py``      — circuit-breaker flips    (EV_BREAKER)
* ``service/brownout.py``   — brownout rung moves      (EV_BROWNOUT),
                              load-shed responses      (EV_HTTP_429,
                                                        EV_HTTP_504)
* ``core/commit.py``        — CAS conflicts            (EV_COMMIT_CONFLICT)
* ``parallel/maintenance_plane.py``
                            — lease expiries           (EV_LEASE_EXPIRED),
                              takeovers                (EV_TAKEOVER),
                              rejoin grants            (EV_REJOIN_GRANT)
* ``service/stream_daemon.py``
                            — loop crashes             (EV_LOOP_CRASH),
                              SIGTERM/SIGINT           (EV_SIGTERM)
* crash hooks (below)       — uncaught exceptions      (EV_CRASH)

Recording is one dict append under a leaf lock (never acquired around
other locks, so feed sites inside `_set_state_locked`-style critical
sections stay deadlock-free) and is ON by default: the ring is only
useful if it was running before anything went wrong.  Dumps are
atomic (tmp + ``os.replace``) JSON written on demand
(`paimon table debug-bundle`), from the installed crash hooks
(excepthook + atexit), and from the stream daemon's signal handler.
"""

from __future__ import annotations

import atexit
import json
import os
import platform
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = [
    "FlightRecorder", "recorder", "record", "dump", "install_crash_hooks",
    "sync_from_options",
    "EV_RETRY", "EV_BREAKER", "EV_BROWNOUT", "EV_HTTP_429", "EV_HTTP_504",
    "EV_COMMIT_CONFLICT", "EV_LEASE_EXPIRED", "EV_TAKEOVER",
    "EV_REJOIN_GRANT", "EV_LOOP_CRASH", "EV_SIGTERM", "EV_CRASH",
]

DEFAULT_EVENTS = 512

EV_RETRY = "retry"
EV_BREAKER = "breaker"
EV_BROWNOUT = "brownout"
EV_HTTP_429 = "http.429"
EV_HTTP_504 = "http.504"
EV_COMMIT_CONFLICT = "commit.conflict"
EV_LEASE_EXPIRED = "lease.expired"
EV_TAKEOVER = "takeover"
EV_REJOIN_GRANT = "rejoin.grant"
EV_LOOP_CRASH = "loop.crash"
EV_SIGTERM = "sigterm"
EV_CRASH = "crash"


class FlightRecorder:
    """Thread-safe bounded event ring with atomic JSON dumps."""

    def __init__(self, max_events: int = DEFAULT_EVENTS):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(1, int(max_events)))
        self._seq = 0
        self.enabled = True
        self.dump_dir: Optional[str] = None
        self.dropped = 0

    @property
    def max_events(self) -> int:
        return self._events.maxlen or 0

    def record(self, kind: str, **fields) -> None:
        if not self.enabled:
            return
        ev = {"kind": kind, "t": time.time()}
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)

    def snapshot(self) -> List[Dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def resize(self, max_events: int) -> None:
        max_events = max(1, int(max_events))
        with self._lock:
            if max_events != self._events.maxlen:
                self._events = deque(self._events, maxlen=max_events)

    def dump(self, trigger: Optional[Dict] = None,
             path: Optional[str] = None) -> Optional[str]:
        """Write the ring (plus an optional trigger record) to `path`,
        or to an auto-named file under `dump_dir`.  Atomic: readers
        never see a torn file, and a dump racing a crash either fully
        lands or leaves the previous one.  Returns the path, or None
        when there is nowhere to write / the write failed (a recorder
        failure must never mask the crash it is recording)."""
        if path is None:
            if self.dump_dir is None:
                return None
            fname = "flight-%s-%d-%d.json" % (
                platform.node(), os.getpid(),
                int(time.time() * 1000))
            path = os.path.join(self.dump_dir, fname)
        doc = {
            "pid": os.getpid(),
            "host": platform.node(),
            "created_s": time.time(),
            "dropped": self.dropped,
            "trigger": trigger,
            "events": self.snapshot(),
        }
        tmp = path + ".tmp"
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, path)
        except OSError:
            return None
        return path


_recorder = FlightRecorder()


def recorder() -> FlightRecorder:
    return _recorder


def record(kind: str, **fields) -> None:
    """Module-level convenience: one call at every feed site."""
    _recorder.record(kind, **fields)


def dump(trigger: Optional[Dict] = None,
         path: Optional[str] = None) -> Optional[str]:
    return _recorder.dump(trigger, path)


# -- crash hooks -------------------------------------------------------------

_hooks_installed = False


def install_crash_hooks() -> None:
    """Chain onto sys.excepthook and atexit so an uncaught exception
    (or plain process exit with a dump dir configured) flushes the
    ring to disk — and the trace spool with it, so the merged fleet
    timeline includes the crashed process's last spans.  Idempotent;
    only dumps when `dump_dir` is set (a CLI one-shot without the
    option must not spray files)."""
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True
    prev_hook = sys.excepthook

    def _hook(exc_type, exc, tb):
        try:
            record(EV_CRASH, error=exc_type.__name__, message=str(exc))
            _recorder.dump(trigger={"kind": EV_CRASH,
                                    "error": exc_type.__name__,
                                    "message": str(exc)})
            from paimon_tpu.obs.trace import spool_flush
            spool_flush()
        except Exception:   # lint-ok: swallow a failing black-box dump must never mask the original crash being re-raised to prev_hook
            pass
        prev_hook(exc_type, exc, tb)

    sys.excepthook = _hook

    def _at_exit():
        try:
            if self_dump_dir():
                _recorder.dump(trigger={"kind": "atexit"})
            from paimon_tpu.obs.trace import spool_flush
            spool_flush()
        except Exception:   # lint-ok: swallow best-effort flush during interpreter teardown; raising here aborts other atexit handlers
            pass

    atexit.register(_at_exit)


def self_dump_dir() -> Optional[str]:
    return _recorder.dump_dir


def sync_from_options(options) -> None:
    """Sync the recorder from a table's options at a pipeline entry
    point — same explicit-key-wins contract as the trace switches."""
    if options is None:
        return
    raw = getattr(options, "options", None)
    if raw is None or not hasattr(raw, "contains"):
        return
    from paimon_tpu.options import CoreOptions
    if raw.contains(CoreOptions.OBS_FLIGHT_ENABLED):
        _recorder.enabled = bool(raw.get(CoreOptions.OBS_FLIGHT_ENABLED))
    if raw.contains(CoreOptions.OBS_FLIGHT_EVENTS):
        _recorder.resize(raw.get(CoreOptions.OBS_FLIGHT_EVENTS))
    if raw.contains(CoreOptions.OBS_FLIGHT_DUMP_DIR):
        _recorder.dump_dir = raw.get(CoreOptions.OBS_FLIGHT_DUMP_DIR)
        if _recorder.dump_dir:
            install_crash_hooks()
