"""Fleet trace stitching: merge per-process span spools into one
Perfetto-loadable Chrome trace.

Every process that runs with `trace.export.dir` set appends its spans
to ``<dir>/<process_tag>.jsonl`` (header line with identity + clock
anchor, then one span per line — obs/trace.py `spool_flush`).  This
module reads the whole directory and emits a single trace where:

* each spool file becomes one Chrome trace *process* (pid = file
  index), named after its host/pid/replica via ``process_name``
  metadata, with per-thread tracks inside it exactly like the
  single-process export;
* span timestamps are re-based from each process's private
  perf_counter timeline onto a shared wall-clock timeline using the
  (wall_s, perf_s) anchor pair in the spool header — without this,
  two processes' spans would land at unrelated offsets;
* every cross-boundary reference becomes a Perfetto *flow arrow*:
  a span whose attrs carry ``remote_parent`` (serving hops — the
  X-Parent-Span header) or ``link`` (store-carried context — the
  ``trace.context`` snapshot property) points at a
  ``<process_tag>:<span_id>`` token; if the referenced span is present
  in any spool, an "s"/"f" flow-event pair ties the two tracks
  together at the boundary.

`paimon fleet trace --merge <dir>` is the CLI entry.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

__all__ = ["read_spools", "merge_spools", "export_merged"]

# Spool span attrs that reference a span in another process, in
# "<process_tag>:<span_id>" token form.
_REF_ATTRS = ("remote_parent", "link")


def read_spools(directory: str) -> List[Dict]:
    """Parse every ``*.jsonl`` spool in `directory` into
    ``{"meta": <header dict>, "spans": [<span dict>, ...]}`` entries,
    sorted by process tag for a deterministic merge.  Files without a
    valid header line are skipped (a process that died before its
    first flush leaves nothing useful)."""
    procs: List[Dict] = []
    for fname in sorted(os.listdir(directory)):
        if not fname.endswith(".jsonl"):
            continue
        path = os.path.join(directory, fname)
        meta: Optional[Dict] = None
        spans: List[Dict] = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue      # torn tail line from a killed writer
                if meta is None:
                    if "proc" not in rec:
                        break     # not a spool file
                    meta = rec
                elif "sid" in rec:
                    spans.append(rec)
        if meta is not None:
            procs.append({"meta": meta, "spans": spans})
    procs.sort(key=lambda p: p["meta"]["proc"])
    return procs


def _proc_label(meta: Dict) -> str:
    label = f"{meta.get('host', '?')}/{meta.get('pid', '?')}"
    if meta.get("replica"):
        label += f" [{meta['replica']}]"
    return label


def merge_spools(procs: List[Dict]) -> Tuple[Dict, Dict]:
    """Build the merged Chrome trace object plus a stats dict
    ``{"processes", "spans", "flows", "unresolved"}`` from parsed
    spools.  `unresolved` counts cross-boundary references whose
    source span was not found in any spool (evicted from its ring or
    the process never flushed) — the arrow is simply omitted."""
    events: List[Dict] = []
    # token "<proc>:<sid>" -> (pid, tid, start_ts_us) of the source
    # span; arrows leave from the source's START (a client span
    # strictly encloses the server span it spawned, so its end would
    # point backwards in time)
    by_token: Dict[str, Tuple[int, int, float]] = {}
    # (pid, ref attr, token, start_ts_us, tid) per referencing span
    refs: List[Tuple[int, str, str, float, int]] = []
    n_spans = 0

    for pid, proc in enumerate(procs, start=1):
        meta = proc["meta"]
        tag = meta["proc"]
        # perf_counter -> wall rebase: wall_us(ts) = ts + base_us
        base_us = (meta.get("wall_s", 0.0) - meta.get("perf_s", 0.0)) \
            * 1e6
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": _proc_label(meta)}})
        track_ids: Dict[Tuple, int] = {}
        track_names: Dict[int, str] = {}
        for s in proc["spans"]:
            tid = track_ids.setdefault((s.get("thread"), s.get("tid")),
                                       len(track_ids) + 1)
            track_names[tid] = s.get("thread") or f"thread-{s['tid']}"
            ts = s["ts"] + base_us
            attrs = s.get("attrs") or {}
            events.append({
                "name": s["name"], "cat": s.get("cat") or "span",
                "ph": "X", "ts": round(ts, 3),
                "dur": round(max(s.get("dur", 0.0), 0.001), 3),
                "pid": pid, "tid": tid, "args": attrs,
            })
            n_spans += 1
            by_token[f"{tag}:{s['sid']}"] = (pid, tid, ts + 0.001)
            for key in _REF_ATTRS:
                tok = attrs.get(key)
                if tok:
                    refs.append((pid, key, tok, ts, tid))
        for tid, name in track_names.items():
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": name}})

    flows = unresolved = 0
    for dst_pid, key, tok, dst_ts, dst_tid in refs:
        src = by_token.get(tok)
        if src is None:
            unresolved += 1
            continue
        src_pid, src_tid, src_ts = src
        flows += 1
        fid = flows
        # Perfetto pairs "s"/"f" by (cat, name, id); binding point "e"
        # attaches the arrow head to the enclosing slice.
        events.append({"ph": "s", "id": fid, "pid": src_pid,
                       "tid": src_tid, "ts": round(src_ts, 3),
                       "name": key, "cat": "flow"})
        events.append({"ph": "f", "bp": "e", "id": fid, "pid": dst_pid,
                       "tid": dst_tid,
                       "ts": round(max(dst_ts + 0.001, src_ts), 3),
                       "name": key, "cat": "flow"})

    trace = {"traceEvents": events, "displayTimeUnit": "ms"}
    stats = {"processes": len(procs), "spans": n_spans,
             "flows": flows, "unresolved": unresolved}
    return trace, stats


def export_merged(directory: str, out_path: str) -> Dict:
    """Merge every spool under `directory` into one Perfetto file at
    `out_path`; returns the merge stats."""
    procs = read_spools(directory)
    trace, stats = merge_spools(procs)
    with open(out_path, "w") as f:
        json.dump(trace, f)
    stats["out"] = out_path
    return stats
