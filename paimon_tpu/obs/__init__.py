"""Unified observability plane.

One span-tracing layer (`obs/trace.py`) instruments every concurrent
plane — scan pipeline, write pipeline, mesh compaction, fault ladders,
commit — and one serialization point (`MetricRegistry.snapshot_rows`)
feeds every surface:

* Chrome trace-event JSON export (`obs/export.py`, opens in Perfetto);
* `$metrics` / `$traces` system tables (`table/system.py`);
* Prometheus text exposition (`GET /metrics` on the query service);
* CLI: `paimon table metrics <db.table>` and `--trace out.json`.
"""

from paimon_tpu.obs.trace import (  # noqa: F401
    Span, TraceCollector, collector, disable_tracing, enable_tracing,
    metrics_enabled, set_metrics_enabled, span, sync_from_options,
    take_spans, tracing_enabled,
)
from paimon_tpu.obs.export import (  # noqa: F401
    export_chrome_trace, render_prometheus, to_chrome_trace,
)

__all__ = [
    "Span", "TraceCollector", "collector", "disable_tracing",
    "enable_tracing", "export_chrome_trace", "metrics_enabled",
    "render_prometheus", "set_metrics_enabled", "span",
    "sync_from_options", "take_spans", "to_chrome_trace",
    "tracing_enabled",
]
