"""Unified observability plane.

One span-tracing layer (`obs/trace.py`) instruments every concurrent
plane — scan pipeline, write pipeline, mesh compaction, fault ladders,
commit — and one serialization point (`MetricRegistry.snapshot_rows`)
feeds every surface:

* Chrome trace-event JSON export (`obs/export.py`, opens in Perfetto);
* fleet-wide merged traces (`obs/merge.py`): per-process spools under
  `trace.export.dir` stitched into one Perfetto file with flow arrows
  across every serving hop and store-carried context boundary;
* the black-box flight recorder (`obs/flight.py`): an always-on ring
  of operational events dumped on crash/SIGTERM and on demand;
* the SLO burn-rate plane (`obs/slo.py`): declarative availability +
  latency objectives served at /slo and aggregated on the router;
* `$metrics` / `$traces` system tables (`table/system.py`);
* Prometheus text exposition (`GET /metrics` on the query service);
* CLI: `paimon table metrics`, `paimon table debug-bundle`,
  `paimon fleet trace --merge`, and `--trace out.json`.
"""

from paimon_tpu.obs.trace import (  # noqa: F401
    Span, TraceCollector, collector, current_context_token,
    current_trace_id, disable_tracing, enable_tracing, inject_headers,
    metrics_enabled, new_trace_id, process_tag, server_span,
    set_export_dir, set_metrics_enabled, set_replica_id, span,
    spool_flush, sync_from_options, take_spans, tracing_enabled,
)
from paimon_tpu.obs.export import (  # noqa: F401
    export_chrome_trace, render_prometheus, to_chrome_trace,
)
from paimon_tpu.obs.merge import (  # noqa: F401
    export_merged, merge_spools, read_spools,
)

__all__ = [
    "Span", "TraceCollector", "collector", "current_context_token",
    "current_trace_id", "disable_tracing", "enable_tracing",
    "export_chrome_trace", "export_merged", "inject_headers",
    "merge_spools", "metrics_enabled", "new_trace_id", "process_tag",
    "read_spools", "render_prometheus", "server_span",
    "set_export_dir", "set_metrics_enabled", "set_replica_id", "span",
    "spool_flush", "sync_from_options", "take_spans",
    "to_chrome_trace", "tracing_enabled",
]
