"""`python -m paimon_tpu` — the CLI entry point (see cli.py)."""

import sys

from paimon_tpu.cli import main

sys.exit(main())
