"""Hybrid search: fuse vector and full-text routes into one ranked
result.

reference: globalindex/HybridSearchRanker.java:32 (rrf /
weighted_score / mrr fusers, RRF_K=60, per-route min-max normalization
for weighted_score, rank ties by ascending row id, top-k ties keep the
smaller row id), table/source/HybridSearchBuilder.java (addVectorRoute
/ addFullTextRoute with per-route limit + weight),
table/HybridSearchTable.java.

Fusion runs vectorized: per route the rank order is one lexsort and
contributions accumulate with np.add.at over the union of row ids.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

__all__ = ["rank_hybrid", "hybrid_search", "RRF_K", "RANKERS"]

RRF_K = 60.0
RANKERS = ("rrf", "weighted_score", "mrr")


def _normalize_ranker(ranker: Optional[str]) -> str:
    if not ranker or not ranker.strip():
        return "rrf"
    r = ranker.strip().lower()
    if r not in RANKERS:
        raise ValueError(f"Unsupported hybrid ranker: {ranker}")
    return r


def _ranked_order(ids: np.ndarray, scores: np.ndarray) -> np.ndarray:
    """Positions sorted by score desc, ties by ascending row id
    (reference rankedRowIds)."""
    return np.lexsort((ids, -scores.astype(np.float64)))


def rank_hybrid(routes: Sequence[Tuple[np.ndarray, np.ndarray, float]],
                ranker: str = "rrf", limit: int = 10
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Fuse per-route (row_ids, scores, weight) into (row_ids, fused)
    sorted by fused score desc (ties: smaller row id first), capped at
    `limit`."""
    ranker = _normalize_ranker(ranker)
    all_ids: List[np.ndarray] = []
    all_contrib: List[np.ndarray] = []
    for ids, scores, weight in routes:
        ids = np.asarray(ids, dtype=np.int64)
        scores = np.asarray(scores, dtype=np.float32)
        if len(ids) == 0:
            continue
        if ranker in ("rrf", "mrr"):
            order = _ranked_order(ids, scores)
            rank = np.empty(len(ids), dtype=np.float64)
            rank[order] = np.arange(len(ids))
            denom = (RRF_K + rank + 1.0) if ranker == "rrf" \
                else (rank + 1.0)
            contrib = weight / denom
        else:                        # weighted_score: min-max per route
            lo = float(scores.min())
            hi = float(scores.max())
            rng = hi - lo
            # no spread carries no relative signal: every hit maps to
            # 1.0 rather than being zeroed out (reference comment)
            norm = (scores - lo) / rng if rng > 0 \
                else np.ones_like(scores, dtype=np.float64)
            contrib = weight * norm.astype(np.float64)
        all_ids.append(ids)
        all_contrib.append(np.asarray(contrib, dtype=np.float64))

    if not all_ids or limit <= 0:
        return (np.zeros(0, np.int64), np.zeros(0, np.float32))
    ids_cat = np.concatenate(all_ids)
    contrib_cat = np.concatenate(all_contrib)
    uniq, inverse = np.unique(ids_cat, return_inverse=True)
    fused = np.zeros(len(uniq), dtype=np.float64)
    np.add.at(fused, inverse, contrib_cat)
    order = np.lexsort((uniq, -fused))[:limit]
    return uniq[order], fused[order].astype(np.float32)


def hybrid_search(table, routes: Sequence[dict], k: int = 10,
                  ranker: str = "rrf") -> pa.Table:
    """Multi-route search over one table.  Each route is a dict:
      {"type": "vector", "column": c, "query": vec,
       "limit": n, "weight": w, "metric": "cosine"}
      {"type": "text",   "column": c, "query": "terms",
       "limit": n, "weight": w}
    A route may carry a prebuilt "index" (BruteForceIndex /
    IVFFlatIndex / FullTextIndex) so repeated queries amortize index
    construction, mirroring vector_search/full_text_search's index=.
    Returns the fused top-k rows with a `_score` column (reference
    HybridSearchTable read path)."""
    from paimon_tpu.index.fulltext import FullTextIndex
    from paimon_tpu.vector.ann import BruteForceIndex, _as_matrix

    ranker = _normalize_ranker(ranker)   # fail fast, before any index

    # persisted text indexes score by _ROW_ID, not position: when any
    # text route will read one, fetch row ids in the SAME table read so
    # positions and ids stay aligned
    persisted: dict = {}
    for r in routes:
        if r.get("type") == "text" and r.get("index") is None:
            col = r["column"]
            if col not in persisted:
                from paimon_tpu.index.fulltext import \
                    PersistedFullTextIndex as _P
                p = _P.open(table, col)
                persisted[col] = p if p.meta is not None else None
    want_ids = any(v is not None for v in persisted.values())
    data = table.to_arrow(with_row_ids=True) if want_ids \
        else table.to_arrow()
    rowid_pos: Optional[dict] = None

    def _positions_of(row_ids: np.ndarray) -> np.ndarray:
        nonlocal rowid_pos
        if rowid_pos is None:
            from paimon_tpu.core.row_tracking import ROW_ID_COL
            rids = np.asarray(data.column(ROW_ID_COL).combine_chunks()
                              .cast(pa.int64()))
            rowid_pos = {int(r): i for i, r in enumerate(rids)}
        return np.array([rowid_pos.get(int(r), -1) for r in row_ids],
                        dtype=np.int64)

    fused_routes = []
    for r in routes:
        kind = r.get("type")
        col = r["column"]
        route_limit = int(r.get("limit", k))
        weight = float(r.get("weight", 1.0))
        if kind == "vector":
            idx = r.get("index") or BruteForceIndex(
                _as_matrix(data.column(col)), r.get("metric", "cosine"))
            q = np.asarray(r["query"], dtype=np.float32)
            scores, ids = idx.search(q, route_limit)
            valid = ids[0] >= 0
            fused_routes.append((ids[0][valid].astype(np.int64),
                                 scores[0][valid], weight))
        elif kind == "text":
            idx = r.get("index")
            if idx is None and persisted.get(col) is not None:
                # the persisted BM25 index: O(matched postings)
                # instead of re-tokenizing the whole corpus per query
                rids, scores = persisted[col].search(r["query"],
                                                     route_limit)
                pos = _positions_of(rids)
                live = pos >= 0          # deleted rows drop out here
                fused_routes.append((pos[live], scores[live], weight))
                continue
            if idx is None:
                idx = FullTextIndex(data.column(col).to_pylist())
            ids, scores = idx.search(r["query"], route_limit)
            fused_routes.append((ids, scores, weight))
        else:
            raise ValueError(f"Unknown hybrid route type {kind!r}")

    row_ids, fused = rank_hybrid(fused_routes, ranker=ranker, limit=k)
    out = data.take(pa.array(row_ids))
    if want_ids:
        from paimon_tpu.core.row_tracking import ROW_ID_COL
        if ROW_ID_COL in out.column_names:
            out = out.drop_columns([ROW_ID_COL])
    return out.append_column("_score", pa.array(fused, pa.float32()))
