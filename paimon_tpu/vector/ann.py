"""ANN indexes as jitted matmul + top_k.

reference capability: paimon-vector (IVF-Flat / IVF-PQ factories behind
NativeVectorIndexLoader.java:28, JNI to a native library). TPU-first
redesign: the similarity kernel IS a matmul — queries x corpus runs on
the MXU in bf16/f32 and jax.lax.top_k picks candidates; IVF-Flat is a
two-stage matmul (centroids, then gathered cluster members). No graph
walks, no per-vector loops — the hardware's preferred shape.

Metrics: 'dot' | 'cosine' | 'l2' (l2 via the ||a-b||^2 expansion so it
stays one matmul).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

__all__ = ["BruteForceIndex", "IVFFlatIndex", "IVFPQIndex",
           "IVFSQIndex", "HNSWIndex", "PersistedVectorIndex",
           "vector_search"]


def _as_matrix(col: pa.ChunkedArray) -> np.ndarray:
    """fixed_size_list / list<float> column -> float32 [N, D]."""
    arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    if pa.types.is_fixed_size_list(arr.type):
        d = arr.type.list_size
        flat = np.asarray(arr.flatten().cast(pa.float32()))
        return flat.reshape(len(arr), d)
    values = arr.to_pylist()
    return np.asarray(values, dtype=np.float32)


@partial(jax.jit, static_argnames=("k", "metric"))
def _topk_scores(queries, corpus, corpus_sq, k, metric):
    """queries [Q, D] x corpus [N, D] -> (scores [Q, k], idx [Q, k])."""
    sims = queries @ corpus.T                       # MXU
    if metric == "cosine":
        qn = jnp.linalg.norm(queries, axis=1, keepdims=True)
        cn = jnp.sqrt(corpus_sq)[None, :]
        sims = sims / jnp.maximum(qn * cn, 1e-12)
    elif metric == "l2":
        qsq = jnp.sum(queries * queries, axis=1, keepdims=True)
        sims = -(qsq + corpus_sq[None, :] - 2.0 * sims)   # -distance^2
    return jax.lax.top_k(sims, k)


class BruteForceIndex:
    """Exact search: one matmul over the whole corpus."""

    def __init__(self, vectors: np.ndarray, metric: str = "cosine"):
        self.metric = metric
        self._corpus = jnp.asarray(vectors, dtype=jnp.float32)
        self._corpus_sq = jnp.sum(self._corpus * self._corpus, axis=1)

    def __len__(self) -> int:
        return int(self._corpus.shape[0])

    def search(self, queries: np.ndarray, k: int
               ) -> Tuple[np.ndarray, np.ndarray]:
        """-> (scores [Q, k], indices [Q, k]); higher score = closer."""
        q = jnp.atleast_2d(jnp.asarray(queries, dtype=jnp.float32))
        k = min(k, len(self))
        scores, idx = _topk_scores(q, self._corpus, self._corpus_sq, k,
                                   self.metric)
        return np.asarray(scores), np.asarray(idx)


@partial(jax.jit, static_argnames=("iters",))
def _kmeans(vectors, init_centroids, iters):
    """Lloyd's iterations fully on device (assignment = matmul argmin)."""
    def step(centroids, _):
        d = (jnp.sum(vectors ** 2, axis=1, keepdims=True)
             + jnp.sum(centroids ** 2, axis=1)[None, :]
             - 2.0 * vectors @ centroids.T)
        assign = jnp.argmin(d, axis=1)
        one_hot = jax.nn.one_hot(assign, centroids.shape[0],
                                 dtype=vectors.dtype)
        sums = one_hot.T @ vectors
        counts = jnp.sum(one_hot, axis=0)[:, None]
        new = jnp.where(counts > 0, sums / jnp.maximum(counts, 1),
                        centroids)
        return new, None
    out, _ = jax.lax.scan(step, init_centroids, None, length=iters)
    return out


class IVFFlatIndex:
    """IVF-Flat: kmeans coarse quantizer + per-cluster exact search.

    Probing is two matmuls: queries x centroids picks nprobe clusters,
    then queries x (gathered members) ranks candidates."""

    def __init__(self, vectors: np.ndarray, n_clusters: int = 0,
                 metric: str = "cosine", kmeans_iters: int = 8,
                 seed: int = 0):
        n = len(vectors)
        if n_clusters <= 0:
            n_clusters = max(1, int(np.sqrt(n)))
        n_clusters = min(n_clusters, n)
        self.metric = metric
        v = jnp.asarray(vectors, dtype=jnp.float32)
        rng = np.random.default_rng(seed)
        init = v[rng.choice(n, n_clusters, replace=False)]
        self.centroids = np.asarray(_kmeans(v, init, kmeans_iters))
        d = (np.sum(vectors ** 2, axis=1, keepdims=True)
             + np.sum(self.centroids ** 2, axis=1)[None, :]
             - 2.0 * vectors @ self.centroids.T)
        assign = np.argmin(d, axis=1)
        order = np.argsort(assign, kind="stable")
        self._members = order                     # corpus idx sorted by cluster
        self._bounds = np.searchsorted(assign[order],
                                       np.arange(n_clusters + 1))
        self._vectors = np.asarray(vectors, dtype=np.float32)
        self._norms = np.linalg.norm(self._vectors, axis=1)
        self._sq = np.sum(self._vectors ** 2, axis=1)

    def __len__(self) -> int:
        return len(self._vectors)

    def search(self, queries: np.ndarray, k: int, nprobe: int = 4
               ) -> Tuple[np.ndarray, np.ndarray]:
        q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
        nprobe = min(nprobe, len(self._bounds) - 1)
        cd = (np.sum(q ** 2, axis=1, keepdims=True)
              + np.sum(self.centroids ** 2, axis=1)[None, :]
              - 2.0 * q @ self.centroids.T)
        probe = np.argsort(cd, axis=1)[:, :nprobe]
        out_scores = np.full((len(q), k), -np.inf, dtype=np.float32)
        out_idx = np.full((len(q), k), -1, dtype=np.int64)
        for qi in range(len(q)):
            cand = np.concatenate([
                self._members[self._bounds[c]:self._bounds[c + 1]]
                for c in probe[qi]])
            if len(cand) == 0:
                continue
            # candidate sets are small and vary per query: numpy scoring
            # avoids per-query device uploads and jit recompiles
            sub = self._vectors[cand]
            sims = sub @ q[qi]
            if self.metric == "cosine":
                qn = max(float(np.linalg.norm(q[qi])), 1e-12)
                sims = sims / (np.maximum(self._norms[cand], 1e-12) * qn)
            elif self.metric == "l2":
                sims = -(self._sq[cand] + float(q[qi] @ q[qi])
                         - 2.0 * sims)
            kk = min(k, len(cand))
            top = np.argpartition(-sims, kk - 1)[:kk]
            top = top[np.argsort(-sims[top])]
            out_scores[qi, :kk] = sims[top]
            out_idx[qi, :kk] = cand[top]
        return out_scores, out_idx


def _train_coarse(v: np.ndarray, n_clusters: int, kmeans_iters: int,
                  rng) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                np.ndarray]:
    """Shared IVF coarse quantizer (device k-means + cluster layout):
    -> (centroids, assign, members[int32 sorted by cluster], bounds)."""
    n = len(v)
    init = jnp.asarray(v[rng.choice(n, n_clusters, replace=False)])
    centroids = np.asarray(_kmeans(jnp.asarray(v), init, kmeans_iters))
    cd = (np.sum(v ** 2, axis=1, keepdims=True)
          + np.sum(centroids ** 2, axis=1)[None, :]
          - 2.0 * v @ centroids.T)
    assign = np.argmin(cd, axis=1)
    order = np.argsort(assign, kind="stable")
    members = order.astype(np.int32)
    bounds = np.searchsorted(assign[order], np.arange(n_clusters + 1))
    return centroids, assign, members, bounds


def _probe_clusters(q: np.ndarray, centroids: np.ndarray,
                    nprobe: int) -> np.ndarray:
    """queries x centroids -> nearest-`nprobe` cluster ids per query."""
    cd = (np.sum(q ** 2, axis=1, keepdims=True)
          + np.sum(centroids ** 2, axis=1)[None, :]
          - 2.0 * q @ centroids.T)
    return np.argsort(cd, axis=1)[:, :nprobe]


def _select_candidates(cand: np.ndarray, dist: np.ndarray, qv: np.ndarray,
                       raw: Optional[np.ndarray], metric: str, k: int,
                       refine: int) -> Tuple[np.ndarray, np.ndarray]:
    """Shared approximate->exact tail: top-`fetch` by approximate
    distance, optional exact rerank against raw vectors ->
    (selected corpus ids, scores)."""
    fetch = max(k, refine) if refine else k
    kk = min(fetch, len(cand))
    top = np.argpartition(dist, kk - 1)[:kk]
    if refine and raw is not None:
        sub = raw[cand[top]]
        if metric == "dot":
            ex = -(sub @ qv)
        else:                          # l2, and cosine (pre-normalized)
            ex = np.sum((sub - qv) ** 2, axis=1)
        order = np.argsort(ex, kind="stable")[:k]
        return cand[top[order]], -ex[order]
    order = np.argsort(dist[top], kind="stable")[:k]
    return cand[top[order]], -dist[top][order]


@partial(jax.jit, static_argnames=("iters",))
def _kmeans_batch(subvectors, init_centroids, iters):
    """Per-subspace Lloyd's, vmapped over the M subspaces at once:
    subvectors [M, N, dsub], init [M, ksub, dsub] -> [M, ksub, dsub].
    One device program trains every PQ codebook in parallel (the
    assignment step is a batched matmul — MXU shape)."""
    return jax.vmap(lambda v, c: _kmeans(v, c, iters))(subvectors,
                                                       init_centroids)


@jax.jit
def _pq_encode(subvectors, codebooks):
    """subvectors [M, N, dsub] x codebooks [M, ksub, dsub] ->
    codes [N, M] uint8 (argmin distance per subspace, batched)."""
    def enc(v, c):
        d = (jnp.sum(v * v, axis=1, keepdims=True)
             + jnp.sum(c * c, axis=1)[None, :]
             - 2.0 * v @ c.T)
        return jnp.argmin(d, axis=1).astype(jnp.uint8)
    return jax.vmap(enc)(subvectors, codebooks).T


class IVFPQIndex:
    """IVF-PQ: coarse k-means quantizer + product-quantized residuals.

    reference: paimon-vector IVF-PQ factory (NativeVectorIndexLoader
    .java:28, JNI to a native PQ library).  TPU-first: codebook
    training is one vmapped k-means (batched matmuls on the MXU), the
    query LUT build is a batched matmul, and scan-time scoring is a
    uint8 gather + sum — the compressed corpus is N x M BYTES, so a
    billion-scale corpus fits where raw f32 cannot (32x smaller at
    D=128, M=16).

    Asymmetric distance (ADC): for query q probing cluster c with
    residual r = q - centroid[c], LUT[m][j] = ||r_m - codebook[m][j]||²
    and member distance = sum_m LUT[m][code[m]].  `refine > 0` reranks
    the top ADC candidates with exact distances against the raw
    vectors (kept out of the index's memory budget: pass them to
    `search(..., vectors=...)` or let the index hold a reference).
    """

    KSUB = 256                      # 8-bit codes

    def __init__(self, vectors: Optional[np.ndarray],
                 n_clusters: int = 0, m: int = 8,
                 metric: str = "l2", kmeans_iters: int = 8,
                 seed: int = 0, keep_vectors: bool = True,
                 _from_state: Optional[dict] = None):
        if _from_state is not None:
            self.__dict__.update(_from_state)
            return
        n, d = vectors.shape
        if d % m:
            raise ValueError(f"dim {d} not divisible by m={m} subspaces")
        if n_clusters <= 0:
            n_clusters = max(1, int(np.sqrt(n)))
        n_clusters = min(n_clusters, n)
        self.metric = metric
        self.m = m
        self.dsub = d // m
        v = np.asarray(vectors, dtype=np.float32)
        if metric == "cosine":
            # normalized l2 ranks identically to cosine
            v = v / np.maximum(np.linalg.norm(v, axis=1, keepdims=True),
                               1e-12)
        rng = np.random.default_rng(seed)
        self.centroids, assign, self._members, self._bounds = \
            _train_coarse(v, n_clusters, kmeans_iters, rng)

        # PQ codebooks on residuals (train on a sample when huge)
        resid = v - self.centroids[assign]
        sample = resid if n <= 262_144 else \
            resid[rng.choice(n, 262_144, replace=False)]
        sub = sample.reshape(len(sample), m, self.dsub) \
            .transpose(1, 0, 2)                       # [M, S, dsub]
        ksub = min(self.KSUB, len(sample))
        cb_init = np.stack([s[rng.choice(len(sample), ksub,
                                         replace=False)] for s in sub])
        self.codebooks = np.asarray(_kmeans_batch(
            jnp.asarray(sub), jnp.asarray(cb_init), kmeans_iters))
        # encode ALL residuals (batched on device, chunked for memory)
        codes = np.empty((n, m), dtype=np.uint8)
        step = 1 << 18
        for lo in range(0, n, step):
            chunk = resid[lo:lo + step]
            subc = chunk.reshape(len(chunk), m, self.dsub) \
                .transpose(1, 0, 2)
            codes[lo:lo + step] = np.asarray(
                _pq_encode(jnp.asarray(subc),
                           jnp.asarray(self.codebooks)))
        self.codes = codes
        self._vectors = v if keep_vectors else None

    def __len__(self) -> int:
        return int(self.codes.shape[0])

    def memory_bytes(self) -> int:
        """Resident footprint of the compressed index (codes +
        codebooks + coarse centroids + member lists) — what RAM/HBM
        must hold; raw vectors are NOT included (refine streams them)."""
        return (self.codes.nbytes + self.codebooks.nbytes
                + self.centroids.nbytes + self._members.nbytes
                + self._bounds.nbytes)

    # -- persistence --------------------------------------------------
    def state(self) -> Tuple[dict, dict]:
        """(json_meta, named_arrays) for the index layout."""
        meta = {"kind": "ivfpq", "metric": self.metric, "m": self.m,
                "dsub": self.dsub}
        arrays = {"centroids": self.centroids,
                  "codebooks": self.codebooks, "codes": self.codes,
                  "members": self._members, "bounds": self._bounds}
        return meta, arrays

    @classmethod
    def from_state(cls, meta: dict, arrays: dict,
                   vectors: Optional[np.ndarray] = None) -> "IVFPQIndex":
        return cls(None, _from_state={
            "metric": meta["metric"], "m": meta["m"],
            "dsub": meta["dsub"],
            "centroids": arrays["centroids"],
            "codebooks": arrays["codebooks"],
            "codes": arrays["codes"],
            "_members": arrays["members"],
            "_bounds": arrays["bounds"],
            "_vectors": vectors})

    # -- query --------------------------------------------------------
    def search(self, queries: np.ndarray, k: int, nprobe: int = 8,
               refine: int = 0, vectors: Optional[np.ndarray] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """-> (scores [Q, k], indices [Q, k]); higher score = closer.
        `refine`: rerank the top `refine` ADC candidates exactly
        against raw vectors (self's, or the `vectors` argument)."""
        q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
        if self.metric == "cosine":
            q = q / np.maximum(np.linalg.norm(q, axis=1, keepdims=True),
                               1e-12)
        nprobe = min(nprobe, len(self._bounds) - 1)
        probe = _probe_clusters(q, self.centroids, nprobe)
        raw = vectors if vectors is not None else self._vectors
        out_scores = np.full((len(q), k), -np.inf, dtype=np.float32)
        out_idx = np.full((len(q), k), -1, dtype=np.int64)
        cb = self.codebooks                      # [M, ksub, dsub]
        cb_sq = np.sum(cb ** 2, axis=2)          # [M, ksub]
        marange = np.arange(self.m)
        for qi in range(len(q)):
            cand_parts, dist_parts = [], []
            for c in probe[qi]:
                lo, hi = self._bounds[c], self._bounds[c + 1]
                if lo == hi:
                    continue
                members = self._members[lo:hi]
                r = q[qi] - self.centroids[c]
                rsub = r.reshape(self.m, 1, self.dsub)
                # LUT build = batched matmul: [M, 1, dsub]x[M, dsub,
                # ksub]; member distance = gather + sum over subspaces
                lut = (np.sum(rsub ** 2, axis=2) + cb_sq
                       - 2.0 * np.einsum("mod,mkd->mk", rsub, cb))
                codes = self.codes[members]      # [nc, M] uint8
                dist = lut[marange[None, :], codes].sum(axis=1)
                cand_parts.append(members)
                dist_parts.append(dist)
            if not cand_parts:
                continue
            sel, scores = _select_candidates(
                np.concatenate(cand_parts), np.concatenate(dist_parts),
                q[qi], raw, self.metric, k, refine)
            out_idx[qi, :len(sel)] = sel
            out_scores[qi, :len(sel)] = scores
        return out_scores, out_idx


class IVFSQIndex:
    """IVF-SQ8: coarse k-means quantizer + int8 scalar-quantized
    residuals (4x smaller than f32).

    reference: paimon-vector IvfHnswSqVectorGlobalIndexerFactory.java
    (the SQ capability; HNSW's graph half is HNSWIndex below). TPU
    framing: int8 is the MXU's highest-throughput operand type — the
    dequantize-and-score step is `codes * scale + min` folded into the
    distance expansion, so bulk scoring stays a matmul-shaped op; the
    compressed corpus (N x D bytes) has the residency PQ offers with
    far cheaper encode (no codebook training) and better recall at the
    same nprobe.
    """

    def __init__(self, vectors: Optional[np.ndarray],
                 n_clusters: int = 0, metric: str = "l2",
                 kmeans_iters: int = 8, seed: int = 0,
                 keep_vectors: bool = True,
                 _from_state: Optional[dict] = None):
        if _from_state is not None:
            self.__dict__.update(_from_state)
            return
        n, d = vectors.shape
        if n_clusters <= 0:
            n_clusters = max(1, int(np.sqrt(n)))
        n_clusters = min(n_clusters, n)
        self.metric = metric
        v = np.asarray(vectors, dtype=np.float32)
        raw = v
        if metric == "cosine":
            v = raw = v / np.maximum(
                np.linalg.norm(v, axis=1, keepdims=True), 1e-12)
        elif metric == "dot":
            # MIPS -> L2 (Bachrach et al. / ScaNN's standard reduction):
            # append phi = sqrt(M^2 - ||x||^2); with queries padded by 0,
            # l2-NN in the augmented space orders exactly by dot product,
            # making IVF's l2 cluster geometry sound for inner product
            norms_sq = np.sum(v ** 2, axis=1)
            self.mips_max_norm = float(np.sqrt(norms_sq.max(initial=0.0)))
            phi = np.sqrt(np.maximum(
                self.mips_max_norm ** 2 - norms_sq, 0.0))
            v = np.concatenate([v, phi[:, None]], axis=1) \
                .astype(np.float32)
        rng = np.random.default_rng(seed)
        self.centroids, assign, self._members, self._bounds = \
            _train_coarse(v, n_clusters, kmeans_iters, rng)
        # per-dimension affine SQ8 over residuals: code = round(
        # (r - min) / scale), r ~ min + code * scale
        resid = v - self.centroids[assign]
        self.sq_min = resid.min(axis=0)
        span = resid.max(axis=0) - self.sq_min
        self.sq_scale = np.where(span > 0, span / 255.0, 1.0) \
            .astype(np.float32)
        self.codes = np.clip(
            np.rint((resid - self.sq_min) / self.sq_scale), 0, 255
        ).astype(np.uint8)
        # refine reranks against the ORIGINAL vectors (for dot, the
        # augmented space is for candidate generation only)
        self._vectors = raw if keep_vectors else None

    def __len__(self) -> int:
        return int(self.codes.shape[0])

    def memory_bytes(self) -> int:
        return (self.codes.nbytes + self.centroids.nbytes
                + self.sq_min.nbytes + self.sq_scale.nbytes
                + self._members.nbytes + self._bounds.nbytes)

    # -- persistence --------------------------------------------------
    def state(self) -> Tuple[dict, dict]:
        meta = {"kind": "ivfsq", "metric": self.metric}
        if self.metric == "dot":
            meta["mips_max_norm"] = self.mips_max_norm
        arrays = {"centroids": self.centroids, "codes": self.codes,
                  "sq_min": self.sq_min, "sq_scale": self.sq_scale,
                  "members": self._members, "bounds": self._bounds}
        return meta, arrays

    @classmethod
    def from_state(cls, meta: dict, arrays: dict,
                   vectors: Optional[np.ndarray] = None) -> "IVFSQIndex":
        state = {
            "metric": meta["metric"],
            "centroids": arrays["centroids"],
            "codes": arrays["codes"], "sq_min": arrays["sq_min"],
            "sq_scale": arrays["sq_scale"],
            "_members": arrays["members"], "_bounds": arrays["bounds"],
            "_vectors": vectors}
        if "mips_max_norm" in meta:
            state["mips_max_norm"] = meta["mips_max_norm"]
        return cls(None, _from_state=state)

    # -- query --------------------------------------------------------
    def search(self, queries: np.ndarray, k: int, nprobe: int = 8,
               refine: int = 0, vectors: Optional[np.ndarray] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
        if self.metric == "cosine":
            q = q / np.maximum(np.linalg.norm(q, axis=1, keepdims=True),
                               1e-12)
        if self.metric == "dot":
            # augmented-space probe (phi component of a query is 0)
            q_work = np.concatenate(
                [q, np.zeros((len(q), 1), np.float32)], axis=1)
        else:
            q_work = q
        nprobe = min(nprobe, len(self._bounds) - 1)
        probe = _probe_clusters(q_work, self.centroids, nprobe)
        raw = vectors if vectors is not None else self._vectors
        out_scores = np.full((len(q), k), -np.inf, dtype=np.float32)
        out_idx = np.full((len(q), k), -1, dtype=np.int64)
        for qi in range(len(q)):
            cand_parts, dist_parts = [], []
            for c in probe[qi]:
                lo, hi = self._bounds[c], self._bounds[c + 1]
                if lo == hi:
                    continue
                members = self._members[lo:hi]
                r = q_work[qi] - self.centroids[c]
                # dequantized residual distance, vectorized over the
                # cluster: ||r - (min + code*scale)||^2
                deq = self.codes[members] * self.sq_scale + self.sq_min
                diff = deq - r
                dist_parts.append(np.einsum("nd,nd->n", diff, diff))
                cand_parts.append(members)
            if not cand_parts:
                continue
            sel, scores = _select_candidates(
                np.concatenate(cand_parts), np.concatenate(dist_parts),
                q[qi], raw, self.metric, k, refine)
            out_idx[qi, :len(sel)] = sel
            out_scores[qi, :len(sel)] = scores
        return out_scores, out_idx


class HNSWIndex:
    """Hierarchical navigable small-world graph — the HOST-side
    low-latency point-query structure (reference
    IvfHnswFlatVectorGlobalIndexerFactory.java / jvector-style native
    HNSW). Graph walks are pointer-chasing, the one shape an
    accelerator is wrong for, so this lives deliberately on the host
    (same split as the SST lookup path): bulk scans use the matmul
    indexes above, single-query lookups use this.

    Standard construction (Malkov & Yashunin 2016): exponentially
    distributed levels, greedy descent from the top layer, beam search
    (ef) with M-edge neighbor selection per layer."""

    def __init__(self, vectors: Optional[np.ndarray], m: int = 16,
                 ef_construction: int = 100, metric: str = "l2",
                 seed: int = 0, _from_state: Optional[dict] = None):
        if _from_state is not None:
            self.__dict__.update(_from_state)
            return
        if metric not in ("l2", "cosine"):
            # graph edges are built on l2 geometry; cosine reduces to
            # l2 after normalization, but max-inner-product does not —
            # refuse rather than silently rank by the wrong metric
            raise ValueError(f"HNSW supports l2/cosine, not {metric!r}")
        v = np.asarray(vectors, dtype=np.float32)
        if metric == "cosine":
            v = v / np.maximum(np.linalg.norm(v, axis=1, keepdims=True),
                               1e-12)
        self.metric = metric
        self.m = m
        self._vectors = v
        n = len(v)
        rng = np.random.default_rng(seed)
        ml = 1.0 / np.log(max(m, 2))
        levels = np.minimum(
            (-np.log(rng.uniform(size=n)) * ml).astype(np.int64), 8)
        self.levels = levels
        self.max_level = int(levels.max(initial=0))
        # neighbors[level][node] -> int64 array of edges
        self.neighbors = [dict() for _ in range(self.max_level + 1)]
        self.entry = 0
        for i in range(n):
            self._insert(i, ef_construction)

    def _dist(self, q: np.ndarray, ids) -> np.ndarray:
        sub = self._vectors[ids]
        d = sub - q
        return np.einsum("nd,nd->n", d, d)

    def _search_layer(self, q: np.ndarray, entry: int, ef: int,
                      level: int) -> list:
        """Beam search one layer -> [(dist, node)] sorted ascending."""
        import heapq
        d0 = float(self._dist(q, [entry])[0])
        visited = {entry}
        cand = [(d0, entry)]               # min-heap by distance
        best = [(-d0, entry)]              # max-heap (worst of the ef)
        while cand:
            d, node = heapq.heappop(cand)
            if d > -best[0][0]:
                break
            nbrs = [x for x in self.neighbors[level].get(node, ())
                    if x not in visited]
            if not nbrs:
                continue
            visited.update(nbrs)
            for dn, nb in zip(self._dist(q, nbrs), nbrs):
                dn = float(dn)
                if len(best) < ef or dn < -best[0][0]:
                    heapq.heappush(cand, (dn, nb))
                    heapq.heappush(best, (-dn, nb))
                    if len(best) > ef:
                        heapq.heappop(best)
        return sorted((-d, n) for d, n in best)

    def _select(self, found: list) -> np.ndarray:
        return np.asarray([n for _, n in found[:self.m]], np.int64)

    def _insert(self, i: int, ef: int):
        if i == 0:
            for lv in range(self.levels[0] + 1):
                self.neighbors[lv][0] = np.empty(0, np.int64)
            self.entry = 0
            return
        q = self._vectors[i]
        lvl = int(self.levels[i])
        cur = self.entry
        for lv in range(self.max_level, lvl, -1):
            found = self._search_layer(q, cur, 1, lv)
            if found:
                cur = found[0][1]
        for lv in range(min(lvl, self.max_level), -1, -1):
            found = self._search_layer(q, cur, ef, lv)
            sel = self._select(found)
            self.neighbors[lv][i] = sel
            for nb in sel:
                old = self.neighbors[lv].get(int(nb),
                                             np.empty(0, np.int64))
                merged = np.append(old, i)
                if len(merged) > self.m * 2:   # prune worst edges
                    d = self._dist(self._vectors[int(nb)], merged)
                    merged = merged[np.argsort(d)[:self.m * 2]]
                self.neighbors[lv][int(nb)] = merged
            if found:
                cur = found[0][1]
        if lvl > int(self.levels[self.entry]):
            self.entry = i

    def __len__(self) -> int:
        return len(self._vectors)

    def search(self, queries: np.ndarray, k: int, ef: int = 64
               ) -> Tuple[np.ndarray, np.ndarray]:
        q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
        if self.metric == "cosine":
            q = q / np.maximum(np.linalg.norm(q, axis=1, keepdims=True),
                               1e-12)
        out_scores = np.full((len(q), k), -np.inf, dtype=np.float32)
        out_idx = np.full((len(q), k), -1, dtype=np.int64)
        for qi in range(len(q)):
            cur = self.entry
            for lv in range(self.max_level, 0, -1):
                found = self._search_layer(q[qi], cur, 1, lv)
                if found:
                    cur = found[0][1]
            found = self._search_layer(q[qi], cur, max(ef, k), 0)[:k]
            for j, (d, node) in enumerate(found):
                out_scores[qi, j] = -d
                out_idx[qi, j] = node
        return out_scores, out_idx

    # -- persistence --------------------------------------------------
    def state(self) -> Tuple[dict, dict]:
        meta = {"kind": "hnsw", "metric": self.metric, "m": self.m,
                "entry": int(self.entry),
                "max_level": self.max_level}
        arrays = {"vectors": self._vectors, "levels": self.levels}
        for lv, layer in enumerate(self.neighbors):
            nodes = np.asarray(sorted(layer), np.int64)
            flat = np.concatenate(
                [layer[int(x)] for x in nodes]) if len(nodes) \
                else np.empty(0, np.int64)
            offs = np.zeros(len(nodes) + 1, np.int64)
            if len(nodes):
                offs[1:] = np.cumsum(
                    [len(layer[int(x)]) for x in nodes])
            arrays[f"l{lv}_nodes"] = nodes
            arrays[f"l{lv}_flat"] = flat
            arrays[f"l{lv}_offs"] = offs
        return meta, arrays

    @classmethod
    def from_state(cls, meta: dict, arrays: dict,
                   vectors: Optional[np.ndarray] = None) -> "HNSWIndex":
        neighbors = []
        lv = 0
        while f"l{lv}_nodes" in arrays:
            nodes = arrays[f"l{lv}_nodes"]
            flat = arrays[f"l{lv}_flat"]
            offs = arrays[f"l{lv}_offs"]
            neighbors.append({
                int(n): flat[offs[j]:offs[j + 1]]
                for j, n in enumerate(nodes)})
            lv += 1
        return cls(None, _from_state={
            "metric": meta["metric"], "m": meta["m"],
            "entry": meta["entry"], "max_level": meta["max_level"],
            "levels": arrays["levels"],
            "neighbors": neighbors,
            "_vectors": arrays["vectors"]})


_INDEX_KINDS = {"ivfpq": IVFPQIndex, "ivfsq": IVFSQIndex,
                "hnsw": HNSWIndex}


class PersistedVectorIndex:
    """ANN index persisted in the table's index layout:
    `{table}/index/vector/{column}/` holding meta.json + npz arrays
    (reference: the vector index files the native loader mmaps,
    NativeVectorIndexLoader.java:28).  Rebuilds when stale; loads
    without touching raw vectors otherwise."""

    VERSION = 1

    def __init__(self, table, column: str):
        self.table = table
        self.column = column

    @property
    def _dir(self) -> str:
        return f"{self.table.path}/index/vector/{self.column}"

    def build(self, m: int = 8, n_clusters: int = 0,
              metric: str = "l2", seed: int = 0, kind: str = "ivfpq"):
        import io as _io
        import json as _json
        latest = self.table.latest_snapshot()
        if latest is None:
            raise ValueError("empty table has no vector index")
        data = self.table.to_arrow(projection=[self.column])
        vectors = _as_matrix(data.column(self.column))
        if kind == "ivfpq":
            idx = IVFPQIndex(vectors, n_clusters=n_clusters, m=m,
                             metric=metric, seed=seed,
                             keep_vectors=False)
        elif kind == "ivfsq":
            idx = IVFSQIndex(vectors, n_clusters=n_clusters,
                             metric=metric, seed=seed,
                             keep_vectors=False)
        elif kind == "hnsw":
            idx = HNSWIndex(vectors, m=max(m, 8), metric=metric,
                            seed=seed)
        else:
            raise ValueError(f"unknown vector index kind {kind!r}")
        meta, arrays = idx.state()
        buf = _io.BytesIO()
        np.savez_compressed(buf, **arrays)
        fio = self.table.file_io
        fio.write_bytes(f"{self._dir}/index-{latest.id}.npz",
                        buf.getvalue(), overwrite=True)
        meta.update(version=self.VERSION, snapshot_id=latest.id,
                    column=self.column,
                    file=f"index-{latest.id}.npz")
        fio.write_bytes(f"{self._dir}/meta.json",
                        _json.dumps(meta).encode(), overwrite=True)
        return idx

    def load(self):
        import io as _io
        import json as _json
        fio = self.table.file_io
        try:
            meta = _json.loads(fio.read_bytes(f"{self._dir}/meta.json"))
            if meta.get("version") != self.VERSION or \
                    meta.get("column") != self.column:
                return None
            latest = self.table.latest_snapshot()
            if latest is None or meta.get("snapshot_id") != latest.id:
                return None                       # stale: caller rebuilds
            with np.load(_io.BytesIO(
                    fio.read_bytes(f"{self._dir}/{meta['file']}"))) as z:
                arrays = {k: z[k] for k in z.files}
            cls = _INDEX_KINDS.get(meta.get("kind", "ivfpq"))
            if cls is None:
                return None
            return cls.from_state(meta, arrays)
        except (FileNotFoundError, OSError, ValueError, KeyError):
            return None

    def load_or_build(self, **kw) -> IVFPQIndex:
        idx = self.load()
        return idx if idx is not None else self.build(**kw)


def vector_search(table, column: str, query, k: int = 10,
                  metric: str = "cosine",
                  index: Optional[BruteForceIndex] = None) -> pa.Table:
    """Search a table's embedding column; returns the top-k rows with a
    `_score` column (reference VectorSearchTable / VectorSearchSplit).
    A batch of queries ([Q, D]) returns Q*k rows with a `_query` column
    identifying the source query."""
    data = table.to_arrow()
    vectors = _as_matrix(data.column(column))
    idx = index or BruteForceIndex(vectors, metric)
    q = np.asarray(query, dtype=np.float32)
    batched = q.ndim == 2
    scores, ids = idx.search(q, k)
    parts = []
    for qi in range(ids.shape[0]):
        valid = ids[qi] >= 0
        rows = data.take(pa.array(ids[qi][valid]))
        rows = rows.append_column(
            "_score", pa.array(scores[qi][valid], pa.float32()))
        if batched:
            rows = rows.append_column(
                "_query", pa.array([qi] * rows.num_rows, pa.int32()))
        parts.append(rows)
    return pa.concat_tables(parts, promote_options="none")
