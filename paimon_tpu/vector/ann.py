"""ANN indexes as jitted matmul + top_k.

reference capability: paimon-vector (IVF-Flat / IVF-PQ factories behind
NativeVectorIndexLoader.java:28, JNI to a native library). TPU-first
redesign: the similarity kernel IS a matmul — queries x corpus runs on
the MXU in bf16/f32 and jax.lax.top_k picks candidates; IVF-Flat is a
two-stage matmul (centroids, then gathered cluster members). No graph
walks, no per-vector loops — the hardware's preferred shape.

Metrics: 'dot' | 'cosine' | 'l2' (l2 via the ||a-b||^2 expansion so it
stays one matmul).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

__all__ = ["BruteForceIndex", "IVFFlatIndex", "vector_search"]


def _as_matrix(col: pa.ChunkedArray) -> np.ndarray:
    """fixed_size_list / list<float> column -> float32 [N, D]."""
    arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    if pa.types.is_fixed_size_list(arr.type):
        d = arr.type.list_size
        flat = np.asarray(arr.flatten().cast(pa.float32()))
        return flat.reshape(len(arr), d)
    values = arr.to_pylist()
    return np.asarray(values, dtype=np.float32)


@partial(jax.jit, static_argnames=("k", "metric"))
def _topk_scores(queries, corpus, corpus_sq, k, metric):
    """queries [Q, D] x corpus [N, D] -> (scores [Q, k], idx [Q, k])."""
    sims = queries @ corpus.T                       # MXU
    if metric == "cosine":
        qn = jnp.linalg.norm(queries, axis=1, keepdims=True)
        cn = jnp.sqrt(corpus_sq)[None, :]
        sims = sims / jnp.maximum(qn * cn, 1e-12)
    elif metric == "l2":
        qsq = jnp.sum(queries * queries, axis=1, keepdims=True)
        sims = -(qsq + corpus_sq[None, :] - 2.0 * sims)   # -distance^2
    return jax.lax.top_k(sims, k)


class BruteForceIndex:
    """Exact search: one matmul over the whole corpus."""

    def __init__(self, vectors: np.ndarray, metric: str = "cosine"):
        self.metric = metric
        self._corpus = jnp.asarray(vectors, dtype=jnp.float32)
        self._corpus_sq = jnp.sum(self._corpus * self._corpus, axis=1)

    def __len__(self) -> int:
        return int(self._corpus.shape[0])

    def search(self, queries: np.ndarray, k: int
               ) -> Tuple[np.ndarray, np.ndarray]:
        """-> (scores [Q, k], indices [Q, k]); higher score = closer."""
        q = jnp.atleast_2d(jnp.asarray(queries, dtype=jnp.float32))
        k = min(k, len(self))
        scores, idx = _topk_scores(q, self._corpus, self._corpus_sq, k,
                                   self.metric)
        return np.asarray(scores), np.asarray(idx)


@partial(jax.jit, static_argnames=("iters",))
def _kmeans(vectors, init_centroids, iters):
    """Lloyd's iterations fully on device (assignment = matmul argmin)."""
    def step(centroids, _):
        d = (jnp.sum(vectors ** 2, axis=1, keepdims=True)
             + jnp.sum(centroids ** 2, axis=1)[None, :]
             - 2.0 * vectors @ centroids.T)
        assign = jnp.argmin(d, axis=1)
        one_hot = jax.nn.one_hot(assign, centroids.shape[0],
                                 dtype=vectors.dtype)
        sums = one_hot.T @ vectors
        counts = jnp.sum(one_hot, axis=0)[:, None]
        new = jnp.where(counts > 0, sums / jnp.maximum(counts, 1),
                        centroids)
        return new, None
    out, _ = jax.lax.scan(step, init_centroids, None, length=iters)
    return out


class IVFFlatIndex:
    """IVF-Flat: kmeans coarse quantizer + per-cluster exact search.

    Probing is two matmuls: queries x centroids picks nprobe clusters,
    then queries x (gathered members) ranks candidates."""

    def __init__(self, vectors: np.ndarray, n_clusters: int = 0,
                 metric: str = "cosine", kmeans_iters: int = 8,
                 seed: int = 0):
        n = len(vectors)
        if n_clusters <= 0:
            n_clusters = max(1, int(np.sqrt(n)))
        n_clusters = min(n_clusters, n)
        self.metric = metric
        v = jnp.asarray(vectors, dtype=jnp.float32)
        rng = np.random.default_rng(seed)
        init = v[rng.choice(n, n_clusters, replace=False)]
        self.centroids = np.asarray(_kmeans(v, init, kmeans_iters))
        d = (np.sum(vectors ** 2, axis=1, keepdims=True)
             + np.sum(self.centroids ** 2, axis=1)[None, :]
             - 2.0 * vectors @ self.centroids.T)
        assign = np.argmin(d, axis=1)
        order = np.argsort(assign, kind="stable")
        self._members = order                     # corpus idx sorted by cluster
        self._bounds = np.searchsorted(assign[order],
                                       np.arange(n_clusters + 1))
        self._vectors = np.asarray(vectors, dtype=np.float32)
        self._norms = np.linalg.norm(self._vectors, axis=1)
        self._sq = np.sum(self._vectors ** 2, axis=1)

    def __len__(self) -> int:
        return len(self._vectors)

    def search(self, queries: np.ndarray, k: int, nprobe: int = 4
               ) -> Tuple[np.ndarray, np.ndarray]:
        q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
        nprobe = min(nprobe, len(self._bounds) - 1)
        cd = (np.sum(q ** 2, axis=1, keepdims=True)
              + np.sum(self.centroids ** 2, axis=1)[None, :]
              - 2.0 * q @ self.centroids.T)
        probe = np.argsort(cd, axis=1)[:, :nprobe]
        out_scores = np.full((len(q), k), -np.inf, dtype=np.float32)
        out_idx = np.full((len(q), k), -1, dtype=np.int64)
        for qi in range(len(q)):
            cand = np.concatenate([
                self._members[self._bounds[c]:self._bounds[c + 1]]
                for c in probe[qi]])
            if len(cand) == 0:
                continue
            # candidate sets are small and vary per query: numpy scoring
            # avoids per-query device uploads and jit recompiles
            sub = self._vectors[cand]
            sims = sub @ q[qi]
            if self.metric == "cosine":
                qn = max(float(np.linalg.norm(q[qi])), 1e-12)
                sims = sims / (np.maximum(self._norms[cand], 1e-12) * qn)
            elif self.metric == "l2":
                sims = -(self._sq[cand] + float(q[qi] @ q[qi])
                         - 2.0 * sims)
            kk = min(k, len(cand))
            top = np.argpartition(-sims, kk - 1)[:kk]
            top = top[np.argsort(-sims[top])]
            out_scores[qi, :kk] = sims[top]
            out_idx[qi, :kk] = cand[top]
        return out_scores, out_idx


def vector_search(table, column: str, query, k: int = 10,
                  metric: str = "cosine",
                  index: Optional[BruteForceIndex] = None) -> pa.Table:
    """Search a table's embedding column; returns the top-k rows with a
    `_score` column (reference VectorSearchTable / VectorSearchSplit).
    A batch of queries ([Q, D]) returns Q*k rows with a `_query` column
    identifying the source query."""
    data = table.to_arrow()
    vectors = _as_matrix(data.column(column))
    idx = index or BruteForceIndex(vectors, metric)
    q = np.asarray(query, dtype=np.float32)
    batched = q.ndim == 2
    scores, ids = idx.search(q, k)
    parts = []
    for qi in range(ids.shape[0]):
        valid = ids[qi] >= 0
        rows = data.take(pa.array(ids[qi][valid]))
        rows = rows.append_column(
            "_score", pa.array(scores[qi][valid], pa.float32()))
        if batched:
            rows = rows.append_column(
                "_query", pa.array([qi] * rows.num_rows, pa.int32()))
        parts.append(rows)
    return pa.concat_tables(parts, promote_options="none")
