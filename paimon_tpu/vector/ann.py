"""ANN indexes as jitted matmul + top_k.

reference capability: paimon-vector (IVF-Flat / IVF-PQ factories behind
NativeVectorIndexLoader.java:28, JNI to a native library). TPU-first
redesign: the similarity kernel IS a matmul — queries x corpus runs on
the MXU in bf16/f32 and jax.lax.top_k picks candidates; IVF-Flat is a
two-stage matmul (centroids, then gathered cluster members). No graph
walks, no per-vector loops — the hardware's preferred shape.

Metrics: 'dot' | 'cosine' | 'l2' (l2 via the ||a-b||^2 expansion so it
stays one matmul).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

__all__ = ["BruteForceIndex", "IVFFlatIndex", "IVFPQIndex",
           "PersistedVectorIndex", "vector_search"]


def _as_matrix(col: pa.ChunkedArray) -> np.ndarray:
    """fixed_size_list / list<float> column -> float32 [N, D]."""
    arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
    if pa.types.is_fixed_size_list(arr.type):
        d = arr.type.list_size
        flat = np.asarray(arr.flatten().cast(pa.float32()))
        return flat.reshape(len(arr), d)
    values = arr.to_pylist()
    return np.asarray(values, dtype=np.float32)


@partial(jax.jit, static_argnames=("k", "metric"))
def _topk_scores(queries, corpus, corpus_sq, k, metric):
    """queries [Q, D] x corpus [N, D] -> (scores [Q, k], idx [Q, k])."""
    sims = queries @ corpus.T                       # MXU
    if metric == "cosine":
        qn = jnp.linalg.norm(queries, axis=1, keepdims=True)
        cn = jnp.sqrt(corpus_sq)[None, :]
        sims = sims / jnp.maximum(qn * cn, 1e-12)
    elif metric == "l2":
        qsq = jnp.sum(queries * queries, axis=1, keepdims=True)
        sims = -(qsq + corpus_sq[None, :] - 2.0 * sims)   # -distance^2
    return jax.lax.top_k(sims, k)


class BruteForceIndex:
    """Exact search: one matmul over the whole corpus."""

    def __init__(self, vectors: np.ndarray, metric: str = "cosine"):
        self.metric = metric
        self._corpus = jnp.asarray(vectors, dtype=jnp.float32)
        self._corpus_sq = jnp.sum(self._corpus * self._corpus, axis=1)

    def __len__(self) -> int:
        return int(self._corpus.shape[0])

    def search(self, queries: np.ndarray, k: int
               ) -> Tuple[np.ndarray, np.ndarray]:
        """-> (scores [Q, k], indices [Q, k]); higher score = closer."""
        q = jnp.atleast_2d(jnp.asarray(queries, dtype=jnp.float32))
        k = min(k, len(self))
        scores, idx = _topk_scores(q, self._corpus, self._corpus_sq, k,
                                   self.metric)
        return np.asarray(scores), np.asarray(idx)


@partial(jax.jit, static_argnames=("iters",))
def _kmeans(vectors, init_centroids, iters):
    """Lloyd's iterations fully on device (assignment = matmul argmin)."""
    def step(centroids, _):
        d = (jnp.sum(vectors ** 2, axis=1, keepdims=True)
             + jnp.sum(centroids ** 2, axis=1)[None, :]
             - 2.0 * vectors @ centroids.T)
        assign = jnp.argmin(d, axis=1)
        one_hot = jax.nn.one_hot(assign, centroids.shape[0],
                                 dtype=vectors.dtype)
        sums = one_hot.T @ vectors
        counts = jnp.sum(one_hot, axis=0)[:, None]
        new = jnp.where(counts > 0, sums / jnp.maximum(counts, 1),
                        centroids)
        return new, None
    out, _ = jax.lax.scan(step, init_centroids, None, length=iters)
    return out


class IVFFlatIndex:
    """IVF-Flat: kmeans coarse quantizer + per-cluster exact search.

    Probing is two matmuls: queries x centroids picks nprobe clusters,
    then queries x (gathered members) ranks candidates."""

    def __init__(self, vectors: np.ndarray, n_clusters: int = 0,
                 metric: str = "cosine", kmeans_iters: int = 8,
                 seed: int = 0):
        n = len(vectors)
        if n_clusters <= 0:
            n_clusters = max(1, int(np.sqrt(n)))
        n_clusters = min(n_clusters, n)
        self.metric = metric
        v = jnp.asarray(vectors, dtype=jnp.float32)
        rng = np.random.default_rng(seed)
        init = v[rng.choice(n, n_clusters, replace=False)]
        self.centroids = np.asarray(_kmeans(v, init, kmeans_iters))
        d = (np.sum(vectors ** 2, axis=1, keepdims=True)
             + np.sum(self.centroids ** 2, axis=1)[None, :]
             - 2.0 * vectors @ self.centroids.T)
        assign = np.argmin(d, axis=1)
        order = np.argsort(assign, kind="stable")
        self._members = order                     # corpus idx sorted by cluster
        self._bounds = np.searchsorted(assign[order],
                                       np.arange(n_clusters + 1))
        self._vectors = np.asarray(vectors, dtype=np.float32)
        self._norms = np.linalg.norm(self._vectors, axis=1)
        self._sq = np.sum(self._vectors ** 2, axis=1)

    def __len__(self) -> int:
        return len(self._vectors)

    def search(self, queries: np.ndarray, k: int, nprobe: int = 4
               ) -> Tuple[np.ndarray, np.ndarray]:
        q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
        nprobe = min(nprobe, len(self._bounds) - 1)
        cd = (np.sum(q ** 2, axis=1, keepdims=True)
              + np.sum(self.centroids ** 2, axis=1)[None, :]
              - 2.0 * q @ self.centroids.T)
        probe = np.argsort(cd, axis=1)[:, :nprobe]
        out_scores = np.full((len(q), k), -np.inf, dtype=np.float32)
        out_idx = np.full((len(q), k), -1, dtype=np.int64)
        for qi in range(len(q)):
            cand = np.concatenate([
                self._members[self._bounds[c]:self._bounds[c + 1]]
                for c in probe[qi]])
            if len(cand) == 0:
                continue
            # candidate sets are small and vary per query: numpy scoring
            # avoids per-query device uploads and jit recompiles
            sub = self._vectors[cand]
            sims = sub @ q[qi]
            if self.metric == "cosine":
                qn = max(float(np.linalg.norm(q[qi])), 1e-12)
                sims = sims / (np.maximum(self._norms[cand], 1e-12) * qn)
            elif self.metric == "l2":
                sims = -(self._sq[cand] + float(q[qi] @ q[qi])
                         - 2.0 * sims)
            kk = min(k, len(cand))
            top = np.argpartition(-sims, kk - 1)[:kk]
            top = top[np.argsort(-sims[top])]
            out_scores[qi, :kk] = sims[top]
            out_idx[qi, :kk] = cand[top]
        return out_scores, out_idx


@partial(jax.jit, static_argnames=("iters",))
def _kmeans_batch(subvectors, init_centroids, iters):
    """Per-subspace Lloyd's, vmapped over the M subspaces at once:
    subvectors [M, N, dsub], init [M, ksub, dsub] -> [M, ksub, dsub].
    One device program trains every PQ codebook in parallel (the
    assignment step is a batched matmul — MXU shape)."""
    return jax.vmap(lambda v, c: _kmeans(v, c, iters))(subvectors,
                                                       init_centroids)


@jax.jit
def _pq_encode(subvectors, codebooks):
    """subvectors [M, N, dsub] x codebooks [M, ksub, dsub] ->
    codes [N, M] uint8 (argmin distance per subspace, batched)."""
    def enc(v, c):
        d = (jnp.sum(v * v, axis=1, keepdims=True)
             + jnp.sum(c * c, axis=1)[None, :]
             - 2.0 * v @ c.T)
        return jnp.argmin(d, axis=1).astype(jnp.uint8)
    return jax.vmap(enc)(subvectors, codebooks).T


class IVFPQIndex:
    """IVF-PQ: coarse k-means quantizer + product-quantized residuals.

    reference: paimon-vector IVF-PQ factory (NativeVectorIndexLoader
    .java:28, JNI to a native PQ library).  TPU-first: codebook
    training is one vmapped k-means (batched matmuls on the MXU), the
    query LUT build is a batched matmul, and scan-time scoring is a
    uint8 gather + sum — the compressed corpus is N x M BYTES, so a
    billion-scale corpus fits where raw f32 cannot (32x smaller at
    D=128, M=16).

    Asymmetric distance (ADC): for query q probing cluster c with
    residual r = q - centroid[c], LUT[m][j] = ||r_m - codebook[m][j]||²
    and member distance = sum_m LUT[m][code[m]].  `refine > 0` reranks
    the top ADC candidates with exact distances against the raw
    vectors (kept out of the index's memory budget: pass them to
    `search(..., vectors=...)` or let the index hold a reference).
    """

    KSUB = 256                      # 8-bit codes

    def __init__(self, vectors: Optional[np.ndarray],
                 n_clusters: int = 0, m: int = 8,
                 metric: str = "l2", kmeans_iters: int = 8,
                 seed: int = 0, keep_vectors: bool = True,
                 _from_state: Optional[dict] = None):
        if _from_state is not None:
            self.__dict__.update(_from_state)
            return
        n, d = vectors.shape
        if d % m:
            raise ValueError(f"dim {d} not divisible by m={m} subspaces")
        if n_clusters <= 0:
            n_clusters = max(1, int(np.sqrt(n)))
        n_clusters = min(n_clusters, n)
        self.metric = metric
        self.m = m
        self.dsub = d // m
        v = np.asarray(vectors, dtype=np.float32)
        if metric == "cosine":
            # normalized l2 ranks identically to cosine
            v = v / np.maximum(np.linalg.norm(v, axis=1, keepdims=True),
                               1e-12)
        rng = np.random.default_rng(seed)

        # coarse quantizer (device k-means, same kernel as IVF-Flat)
        init = jnp.asarray(v[rng.choice(n, n_clusters, replace=False)])
        self.centroids = np.asarray(_kmeans(jnp.asarray(v), init,
                                            kmeans_iters))
        cd = (np.sum(v ** 2, axis=1, keepdims=True)
              + np.sum(self.centroids ** 2, axis=1)[None, :]
              - 2.0 * v @ self.centroids.T)
        assign = np.argmin(cd, axis=1)
        order = np.argsort(assign, kind="stable")
        self._members = order.astype(np.int64)
        self._bounds = np.searchsorted(assign[order],
                                       np.arange(n_clusters + 1))

        # PQ codebooks on residuals (train on a sample when huge)
        resid = v - self.centroids[assign]
        sample = resid if n <= 262_144 else \
            resid[rng.choice(n, 262_144, replace=False)]
        sub = sample.reshape(len(sample), m, self.dsub) \
            .transpose(1, 0, 2)                       # [M, S, dsub]
        ksub = min(self.KSUB, len(sample))
        cb_init = np.stack([s[rng.choice(len(sample), ksub,
                                         replace=False)] for s in sub])
        self.codebooks = np.asarray(_kmeans_batch(
            jnp.asarray(sub), jnp.asarray(cb_init), kmeans_iters))
        # encode ALL residuals (batched on device, chunked for memory)
        codes = np.empty((n, m), dtype=np.uint8)
        step = 1 << 18
        for lo in range(0, n, step):
            chunk = resid[lo:lo + step]
            subc = chunk.reshape(len(chunk), m, self.dsub) \
                .transpose(1, 0, 2)
            codes[lo:lo + step] = np.asarray(
                _pq_encode(jnp.asarray(subc),
                           jnp.asarray(self.codebooks)))
        self.codes = codes
        self._vectors = v if keep_vectors else None

    def __len__(self) -> int:
        return int(self.codes.shape[0])

    def memory_bytes(self) -> int:
        """Resident footprint of the compressed index (codes +
        codebooks + coarse centroids + member lists) — what RAM/HBM
        must hold; raw vectors are NOT included (refine streams them)."""
        return (self.codes.nbytes + self.codebooks.nbytes
                + self.centroids.nbytes + self._members.nbytes
                + self._bounds.nbytes)

    # -- persistence --------------------------------------------------
    def state(self) -> Tuple[dict, dict]:
        """(json_meta, named_arrays) for the index layout."""
        meta = {"kind": "ivfpq", "metric": self.metric, "m": self.m,
                "dsub": self.dsub}
        arrays = {"centroids": self.centroids,
                  "codebooks": self.codebooks, "codes": self.codes,
                  "members": self._members, "bounds": self._bounds}
        return meta, arrays

    @classmethod
    def from_state(cls, meta: dict, arrays: dict,
                   vectors: Optional[np.ndarray] = None) -> "IVFPQIndex":
        return cls(None, _from_state={
            "metric": meta["metric"], "m": meta["m"],
            "dsub": meta["dsub"],
            "centroids": arrays["centroids"],
            "codebooks": arrays["codebooks"],
            "codes": arrays["codes"],
            "_members": arrays["members"],
            "_bounds": arrays["bounds"],
            "_vectors": vectors})

    # -- query --------------------------------------------------------
    def search(self, queries: np.ndarray, k: int, nprobe: int = 8,
               refine: int = 0, vectors: Optional[np.ndarray] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """-> (scores [Q, k], indices [Q, k]); higher score = closer.
        `refine`: rerank the top `refine` ADC candidates exactly
        against raw vectors (self's, or the `vectors` argument)."""
        q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
        if self.metric == "cosine":
            q = q / np.maximum(np.linalg.norm(q, axis=1, keepdims=True),
                               1e-12)
        nprobe = min(nprobe, len(self._bounds) - 1)
        cd = (np.sum(q ** 2, axis=1, keepdims=True)
              + np.sum(self.centroids ** 2, axis=1)[None, :]
              - 2.0 * q @ self.centroids.T)
        probe = np.argsort(cd, axis=1)[:, :nprobe]
        raw = vectors if vectors is not None else self._vectors
        fetch = max(k, refine) if refine else k
        out_scores = np.full((len(q), k), -np.inf, dtype=np.float32)
        out_idx = np.full((len(q), k), -1, dtype=np.int64)
        cb = self.codebooks                      # [M, ksub, dsub]
        cb_sq = np.sum(cb ** 2, axis=2)          # [M, ksub]
        marange = np.arange(self.m)
        for qi in range(len(q)):
            cand_parts, dist_parts = [], []
            for c in probe[qi]:
                lo, hi = self._bounds[c], self._bounds[c + 1]
                if lo == hi:
                    continue
                members = self._members[lo:hi]
                r = q[qi] - self.centroids[c]
                rsub = r.reshape(self.m, 1, self.dsub)
                # LUT build = batched matmul: [M, 1, dsub]x[M, dsub,
                # ksub]; member distance = gather + sum over subspaces
                lut = (np.sum(rsub ** 2, axis=2) + cb_sq
                       - 2.0 * np.einsum("mod,mkd->mk", rsub, cb))
                codes = self.codes[members]      # [nc, M] uint8
                dist = lut[marange[None, :], codes].sum(axis=1)
                cand_parts.append(members)
                dist_parts.append(dist)
            if not cand_parts:
                continue
            cand = np.concatenate(cand_parts)
            dist = np.concatenate(dist_parts)
            kk = min(fetch, len(cand))
            top = np.argpartition(dist, kk - 1)[:kk]
            if refine and raw is not None:
                sub = raw[cand[top]]
                qv = q[qi]
                if self.metric in ("l2", "cosine"):
                    ex = np.sum((sub - qv) ** 2, axis=1)
                else:                            # dot
                    ex = -(sub @ qv)
                order = np.argsort(ex, kind="stable")[:k]
                sel = top[order]
                scores = -ex[order]
            else:
                order = np.argsort(dist[top], kind="stable")[:k]
                sel = top[order]
                scores = -dist[top][order]
            kk = len(sel)
            out_idx[qi, :kk] = cand[sel]
            out_scores[qi, :kk] = scores
        return out_scores, out_idx


class PersistedVectorIndex:
    """ANN index persisted in the table's index layout:
    `{table}/index/vector/{column}/` holding meta.json + npz arrays
    (reference: the vector index files the native loader mmaps,
    NativeVectorIndexLoader.java:28).  Rebuilds when stale; loads
    without touching raw vectors otherwise."""

    VERSION = 1

    def __init__(self, table, column: str):
        self.table = table
        self.column = column

    @property
    def _dir(self) -> str:
        return f"{self.table.path}/index/vector/{self.column}"

    def build(self, m: int = 8, n_clusters: int = 0,
              metric: str = "l2", seed: int = 0) -> IVFPQIndex:
        import io as _io
        import json as _json
        latest = self.table.latest_snapshot()
        if latest is None:
            raise ValueError("empty table has no vector index")
        data = self.table.to_arrow(projection=[self.column])
        vectors = _as_matrix(data.column(self.column))
        idx = IVFPQIndex(vectors, n_clusters=n_clusters, m=m,
                         metric=metric, seed=seed, keep_vectors=False)
        meta, arrays = idx.state()
        buf = _io.BytesIO()
        np.savez_compressed(buf, **arrays)
        fio = self.table.file_io
        fio.write_bytes(f"{self._dir}/index-{latest.id}.npz",
                        buf.getvalue(), overwrite=True)
        meta.update(version=self.VERSION, snapshot_id=latest.id,
                    column=self.column,
                    file=f"index-{latest.id}.npz")
        fio.write_bytes(f"{self._dir}/meta.json",
                        _json.dumps(meta).encode(), overwrite=True)
        return idx

    def load(self) -> Optional[IVFPQIndex]:
        import io as _io
        import json as _json
        fio = self.table.file_io
        try:
            meta = _json.loads(fio.read_bytes(f"{self._dir}/meta.json"))
            if meta.get("version") != self.VERSION or \
                    meta.get("column") != self.column:
                return None
            latest = self.table.latest_snapshot()
            if latest is None or meta.get("snapshot_id") != latest.id:
                return None                       # stale: caller rebuilds
            with np.load(_io.BytesIO(
                    fio.read_bytes(f"{self._dir}/{meta['file']}"))) as z:
                arrays = {k: z[k] for k in z.files}
            return IVFPQIndex.from_state(meta, arrays)
        except (FileNotFoundError, OSError, ValueError, KeyError):
            return None

    def load_or_build(self, **kw) -> IVFPQIndex:
        idx = self.load()
        return idx if idx is not None else self.build(**kw)


def vector_search(table, column: str, query, k: int = 10,
                  metric: str = "cosine",
                  index: Optional[BruteForceIndex] = None) -> pa.Table:
    """Search a table's embedding column; returns the top-k rows with a
    `_score` column (reference VectorSearchTable / VectorSearchSplit).
    A batch of queries ([Q, D]) returns Q*k rows with a `_query` column
    identifying the source query."""
    data = table.to_arrow()
    vectors = _as_matrix(data.column(column))
    idx = index or BruteForceIndex(vectors, metric)
    q = np.asarray(query, dtype=np.float32)
    batched = q.ndim == 2
    scores, ids = idx.search(q, k)
    parts = []
    for qi in range(ids.shape[0]):
        valid = ids[qi] >= 0
        rows = data.take(pa.array(ids[qi][valid]))
        rows = rows.append_column(
            "_score", pa.array(scores[qi][valid], pa.float32()))
        if batched:
            rows = rows.append_column(
                "_query", pa.array([qi] * rows.num_rows, pa.int32()))
        parts.append(rows)
    return pa.concat_tables(parts, promote_options="none")
