"""Vector search: ANN indexes executed on the MXU.

reference: paimon-vector native index (NativeVectorIndexLoader.java:28
loading IVF-Flat/IVF-PQ/IVF-HNSW factories via JNI),
table/VectorSearchTable + VectorSearchSplit. SURVEY §2.8 marks this the
natural TPU win: brute-force and IVF probing are batched matmul + top_k,
exactly the systolic array's shape.
"""

from paimon_tpu.vector.ann import (  # noqa: F401
    BruteForceIndex, IVFFlatIndex, vector_search,
)
from paimon_tpu.vector.hybrid import (  # noqa: F401
    hybrid_search, rank_hybrid,
)
