"""Engine-agnostic metrics registry.

reference: paimon-core/.../metrics/ (MetricRegistry, Counter, Gauge,
Histogram) with groups CommitMetrics / ScanMetrics / CompactionMetrics
(operation/metrics/). System tables remain the queryable surface; this
registry is the programmatic one.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

__all__ = ["Counter", "Gauge", "Histogram", "MetricGroup",
           "MetricRegistry", "global_registry",
           "COMPACTION_BUCKET_RETRIES", "COMPACTION_BUCKET_FALLBACKS",
           "COMPACTION_BUCKET_FAILURES", "FSCK_VIOLATIONS",
           "SCAN_FILE_CACHE_HITS", "SCAN_FILE_CACHE_MISSES",
           "SCAN_FOOTER_CACHE_HITS", "SCAN_FOOTER_CACHE_MISSES",
           "SCAN_RANGE_CACHE_HITS", "SCAN_RANGE_CACHE_MISSES",
           "SCAN_RANGE_CACHE_HIT_BYTES", "SCAN_PIPELINE_SPLITS",
           "SCAN_PIPELINE_BYTES", "SCAN_READ_RETRIES",
           "SCAN_DEVICE_DECODE_FILES", "SCAN_DEVICE_DECODE_FALLBACKS",
           "WRITE_FLUSHES", "WRITE_FLUSHED_BYTES", "WRITE_FLUSH_WAIT_MS",
           "WRITE_INFLIGHT_BYTES", "WRITE_RETRIES",
           "SCAN_SPLIT_MS", "SCAN_MERGE_MS",
           "WRITE_SORT_MS", "WRITE_FLUSH_TASK_MS",
           "IO_READ_MS", "IO_DECODE_MS", "IO_ENCODE_MS", "IO_UPLOAD_MS",
           "COMPACTION_WINDOW_MS", "COMPACTION_FALLBACK_MS",
           "COMMIT_CAS_MS", "COMMIT_MANIFEST_ENCODE_MS",
           "STREAM_EVENTS_INGESTED", "STREAM_CHECKPOINTS",
           "STREAM_CHECKPOINT_MS", "STREAM_LOOP_RESTARTS",
           "STREAM_FRESHNESS_MS", "STREAM_CHANGELOG_ROWS",
           "STREAM_COMPACTIONS", "STREAM_COMPACTIONS_PAUSED",
           "STREAM_SOURCE_BACKLOG",
           "SERVICE_REQUESTS", "SERVICE_REJECTED",
           "SERVICE_QUEUE_DEPTH", "SERVICE_INFLIGHT_BYTES",
           "SERVICE_TENANT_BYTES", "SERVICE_ADMISSION_WAIT_MS",
           "SERVICE_LOOKUP_MS", "SERVICE_SCAN_MS",
           "SERVICE_CHANGELOG_MS", "SERVICE_LOOKUP_KEYS",
           "SERVICE_LOOKUP_CPU_MS",
           "SERVICE_LOOP_LAG_MS", "SERVICE_CONNECTIONS",
           "SERVICE_DELTA_ROWS", "SERVICE_DELTA_BYTES",
           "SERVICE_DELTA_OVERFLOWS", "SERVICE_ROUTER_FORWARDED",
           "SERVICE_ROUTER_UPSTREAM_ERRORS",
           "SERVICE_ROUTER_RING_CHANGES",
           "SERVICE_SCAN_CACHE_HITS", "SERVICE_SCAN_CACHE_MISSES",
           "LOOKUP_BLOCK_CACHE_HITS", "LOOKUP_BLOCK_CACHE_MISSES",
           "LOOKUP_READER_BUILDS", "LOOKUP_READER_REUSES",
           "LOOKUP_FILES_PRUNED", "LOOKUP_SNAPSHOT_REFRESHES",
           "LOOKUP_DELTA_HITS", "LOOKUP_NATIVE_PROBES",
           "LOOKUP_NATIVE_FALLBACKS",
           "CACHE_DISK_HITS", "CACHE_DISK_MISSES",
           "CACHE_DISK_PROMOTIONS", "CACHE_DISK_DEMOTIONS",
           "CACHE_DISK_EVICTIONS", "CACHE_DISK_BYTES",
           "CACHE_DISK_STAGED_UPLOADS", "CACHE_DISK_STAGE_MS",
           "RESILIENCE_HEDGES_ISSUED", "RESILIENCE_HEDGES_WON",
           "RESILIENCE_HEDGES_ABANDONED", "RESILIENCE_BREAKER_STATE",
           "RESILIENCE_BREAKER_FAST_FAILS",
           "RESILIENCE_DEADLINE_EXCEEDED", "RESILIENCE_BROWNOUT_SHEDS",
           "RESILIENCE_BROWNOUT_LEVEL", "RESILIENCE_HEDGE_WAIT_MS",
           "MULTIHOST_COMMIT_CONFLICTS", "MULTIHOST_COMMIT_RETRIES",
           "MULTIHOST_OWNERSHIP_HANDOFFS", "MULTIHOST_BARRIER_WAIT_MS",
           "MULTIHOST_FOREIGN_ROWS", "MULTIHOST_CONFIG_WARNINGS",
           "MULTIHOST_OWNED_BUCKETS", "MULTIHOST_MAINTENANCE_TAKEOVERS",
           "MULTIHOST_LEASE_RENEWALS", "MULTIHOST_LEASE_EXPIRED",
           "PLAN_PLANS", "PLAN_MS", "PLAN_DELTA_APPLIES",
           "PLAN_MANIFESTS_READ", "PLAN_MANIFESTS_PRUNED",
           "PLAN_ENTRIES_DECODED", "PLAN_MANIFEST_COMPACTIONS",
           "FLEET_REJOINS", "FLEET_GENERATIONS",
           "FLEET_FSCK_INCREMENTAL_RUNS", "FLEET_FSCK_OBJECTS_CHECKED",
           "FLEET_FSCK_WATERMARK_AGE_MS",
           "SLO_AVAILABILITY_BURN_FAST", "SLO_AVAILABILITY_BURN_SLOW",
           "SLO_LATENCY_BURN_FAST", "SLO_LATENCY_BURN_SLOW",
           "SLO_ALERT", "SLO_GOOD_EVENTS", "SLO_BAD_EVENTS"]

# fault-tolerance counter names (one definition; producers in
# parallel/fault.py + mesh_engine.py, consumers in tests/dashboards):
#   bucket_retries   — transient per-bucket failures that were retried
#   bucket_fallbacks — buckets degraded to the single-chip path
#   bucket_failures  — buckets that exhausted the whole ladder (raised)
COMPACTION_BUCKET_RETRIES = "bucket_retries"
COMPACTION_BUCKET_FALLBACKS = "bucket_fallbacks"
COMPACTION_BUCKET_FAILURES = "bucket_failures"
FSCK_VIOLATIONS = "fsck_violations"

# read-side cache + pipeline counter names (scan metric group;
# producers in fs/caching.py + parallel/scan_pipeline.py + core read
# paths, consumers in scan_bench.py / tests / dashboards)
SCAN_FILE_CACHE_HITS = "file_cache_hits"
SCAN_FILE_CACHE_MISSES = "file_cache_misses"
SCAN_FOOTER_CACHE_HITS = "footer_cache_hits"
SCAN_FOOTER_CACHE_MISSES = "footer_cache_misses"
SCAN_RANGE_CACHE_HITS = "range_cache_hits"
SCAN_RANGE_CACHE_MISSES = "range_cache_misses"
SCAN_RANGE_CACHE_HIT_BYTES = "range_cache_hit_bytes"
SCAN_PIPELINE_SPLITS = "pipeline_splits"          # splits prefetched
SCAN_PIPELINE_BYTES = "pipeline_bytes"            # est. bytes admitted
SCAN_READ_RETRIES = "read_retries"                # transient IO retries
SCAN_DEVICE_DECODE_FILES = "device_decode_files"  # raw-page device reads
SCAN_DEVICE_DECODE_FALLBACKS = "device_decode_fallbacks"  # host fallbacks

# write-pipeline counter names (write metric group; producers in
# parallel/write_pipeline.py, consumers in write_bench.py / tests /
# dashboards)
WRITE_FLUSHES = "flushes"                   # flush tasks admitted
WRITE_FLUSHED_BYTES = "flushed_bytes"       # est. buffered bytes flushed
WRITE_FLUSH_WAIT_MS = "flush_wait_ms"       # producer ms blocked on the
                                            # in-flight byte budget
WRITE_INFLIGHT_BYTES = "inflight_bytes"     # gauge: bytes in flight now
WRITE_RETRIES = "write_retries"             # transient flush retries

# per-stage latency HISTOGRAM names (obs plane: every obs.trace span
# that names a (group, metric) lands its duration here, so the trace
# timeline and the registry snapshot can never disagree; producers are
# the span call sites in parallel/{scan,write}_pipeline.py,
# core/{read,write,commit}.py, parallel/mesh_engine.py, format/format.py)
SCAN_SPLIT_MS = "split_ms"                  # scan: whole read_split
SCAN_MERGE_MS = "merge_ms"                  # scan: merge kernel
WRITE_SORT_MS = "sort_ms"                   # write: buffer sort/dedup
WRITE_FLUSH_TASK_MS = "flush_task_ms"       # write: whole flush task
IO_READ_MS = "read_ms"                      # io: store -> bytes
IO_DECODE_MS = "decode_ms"                  # io: bytes -> Arrow
IO_ENCODE_MS = "encode_ms"                  # io: Arrow -> bytes
IO_UPLOAD_MS = "upload_ms"                  # io: bytes -> store
COMPACTION_WINDOW_MS = "window_ms"          # compaction: device window
COMPACTION_FALLBACK_MS = "fallback_ms"      # compaction: 1-chip rescue
COMMIT_CAS_MS = "cas_ms"                    # commit: one CAS publish
COMMIT_MANIFEST_ENCODE_MS = "manifest_encode_ms"

# streaming-daemon counter/gauge/histogram names (stream metric group;
# producer is service/stream_daemon.py, consumers tests/soak_harness.py
# + dashboards).  freshness_ms is END-TO-END: event pulled from the CDC
# source -> its checkpoint's rows visible to a changelog scan.
STREAM_EVENTS_INGESTED = "events_ingested"    # CDC events written
STREAM_CHECKPOINTS = "checkpoints"            # offset commits that landed
STREAM_CHECKPOINT_MS = "checkpoint_ms"        # one checkpoint commit
STREAM_LOOP_RESTARTS = "loop_restarts"        # supervised loop restarts
STREAM_FRESHNESS_MS = "freshness_ms"          # event -> changelog-visible
STREAM_CHANGELOG_ROWS = "changelog_rows_served"
STREAM_COMPACTIONS = "compactions"            # triggered compaction runs
STREAM_COMPACTIONS_PAUSED = "compactions_paused"  # skipped: ingest pressure
STREAM_SOURCE_BACKLOG = "source_backlog"      # gauge: unpulled events

# query-serving-plane counter/gauge/histogram names (service metric
# group; producers are service/admission.py + service/query_service.py,
# consumers benchmarks/serve_bench.py + tests + dashboards).  Per-tenant
# in-flight bytes render as one gauge per tenant keyed like a table:
# group("service", tenant) -> prometheus label table="<tenant>".
SERVICE_REQUESTS = "requests"                 # admitted requests
SERVICE_REJECTED = "rejected"                 # 429s: queue full/timeout
SERVICE_QUEUE_DEPTH = "queue_depth"           # gauge: waiters right now
SERVICE_INFLIGHT_BYTES = "inflight_bytes"     # gauge: admitted bytes now
SERVICE_TENANT_BYTES = "tenant_inflight_bytes"    # gauge, per tenant
SERVICE_ADMISSION_WAIT_MS = "admission_wait_ms"   # queued -> admitted
SERVICE_LOOKUP_MS = "lookup_ms"               # whole /lookup request
SERVICE_SCAN_MS = "scan_ms"                   # whole /scan request
SERVICE_CHANGELOG_MS = "changelog_ms"         # whole /changelog poll
SERVICE_LOOKUP_KEYS = "lookup_keys"           # point-get keys served
# per-key handler CPU (thread_time around the /lookup body, divided
# by the batch's key count): the bench-honesty meter behind the
# "handler CPU per lookup" headline — wall latency can hide in IO,
# this cannot
SERVICE_LOOKUP_CPU_MS = "lookup_cpu_per_key_ms"

# event-loop serving engine + hot delta tier + replica router names
# (same service metric group; producers are service/async_server.py,
# service/delta.py and service/router.py).  loop_lag_ms is THE health
# canary of the event-loop engine: how long a finished response waited
# before the loop picked it up — a starved loop is late at accepting,
# reading and writing all at once.  delta_rows/delta_bytes gauge the
# in-memory delta tier (unflushed serving-writer rows merged into
# point lookups); delta_overflow counts writes that pushed the tier
# past service.delta.max-bytes (the "commit now" signal).
SERVICE_LOOP_LAG_MS = "loop_lag_ms"           # response ready -> flushed
SERVICE_CONNECTIONS = "connections"           # gauge: open sockets now
SERVICE_DELTA_ROWS = "delta_rows"             # gauge: delta-tier rows
SERVICE_DELTA_BYTES = "delta_bytes"           # gauge: delta-tier bytes
SERVICE_DELTA_OVERFLOWS = "delta_overflow"    # writes past max-bytes
SERVICE_ROUTER_FORWARDED = "router_forwarded"     # proxied requests
SERVICE_ROUTER_UPSTREAM_ERRORS = "router_upstream_errors"
SERVICE_ROUTER_RING_CHANGES = "router_ring_changes"   # join/leave/
# suspend/re-admit events — a churning ring is a churning SST cache
SERVICE_SCAN_CACHE_HITS = "scan_cache_hits"       # snapshot-keyed
SERVICE_SCAN_CACHE_MISSES = "scan_cache_misses"   # result cache

# point-lookup-plane counter names (lookup metric group; producers in
# lookup/sst.py + lookup/local_query.py).  block_cache_* watch the
# pinned SST block cache; files_pruned counts data files skipped by
# manifest key-range + bloom stats BEFORE any IO.
LOOKUP_BLOCK_CACHE_HITS = "block_cache_hits"
LOOKUP_BLOCK_CACHE_MISSES = "block_cache_misses"
LOOKUP_READER_BUILDS = "reader_builds"        # SSTs built (file reads)
LOOKUP_READER_REUSES = "reader_reuses"        # SSTs served warm
LOOKUP_FILES_PRUNED = "files_pruned"          # skipped by stats, no IO
LOOKUP_SNAPSHOT_REFRESHES = "snapshot_refreshes"  # plan reloads
LOOKUP_DELTA_HITS = "delta_hits"              # keys answered by delta
# native_probes counts SST probe batches resolved by the C path
# (native/probe.c sst_probe_batch); native_fallbacks counts batches
# that WANTED the native path but degraded to numpy (no compiler,
# PAIMON_DISABLE_NATIVE, or a stale .so predating the probe symbols —
# a nonzero steady-state value is the "serving the slow path" alarm)
LOOKUP_NATIVE_PROBES = "native_probes"
LOOKUP_NATIVE_FALLBACKS = "native_fallbacks"

# tiered host-SSD storage counter/gauge/histogram names (cache_disk
# metric group; producers in fs/caching.py DiskCacheTier + the
# UploadStager in parallel/write_pipeline.py, consumers
# benchmarks/tier_bench.py + tests + dashboards).  promotions are
# memory->disk writes earned by repeated hits, demotions are entries
# pushed to disk by memory-LRU pressure (or too large for memory),
# evictions are disk entries dropped by the max-bytes bound OR failed
# validation (wipe/truncate/bit-flip degrades to the object store).
CACHE_DISK_HITS = "hits"                      # served from SSD
CACHE_DISK_MISSES = "misses"                  # disk tier consulted, absent
CACHE_DISK_PROMOTIONS = "promotions"          # hit-earned mem->disk writes
CACHE_DISK_DEMOTIONS = "demotions"            # pressure-driven mem->disk
CACHE_DISK_EVICTIONS = "evictions"            # bound/validation drops
CACHE_DISK_BYTES = "bytes"                    # gauge: on-disk bytes now
CACHE_DISK_STAGED_UPLOADS = "staged_uploads"  # uploads acked from stage
CACHE_DISK_STAGE_MS = "stage_ms"              # one encode->staged-fsync

# tail-tolerance counter/gauge/histogram names (resilience metric
# group; producers in fs/resilience.py + utils/deadline.py +
# service/brownout.py + service/admission.py, consumers
# benchmarks/chaos_bench.py + tests + dashboards).  The breaker state
# gauge renders one series per backend: group("resilience", backend
# name) -> prometheus label table="<backend>"; 0=closed, 1=half-open,
# 2=open.  brownout_level is the serving plane's degradation rung
# (0 normal, 1 degrade hedging/prefetch, 2 shed low priority).
RESILIENCE_HEDGES_ISSUED = "hedges_issued"      # hedge requests sent
RESILIENCE_HEDGES_WON = "hedges_won"            # hedge beat the primary
RESILIENCE_HEDGES_ABANDONED = "hedges_abandoned"  # loser left running
RESILIENCE_BREAKER_STATE = "breaker_state"      # gauge, per backend
RESILIENCE_BREAKER_FAST_FAILS = "breaker_fast_fails"  # open-circuit rejects
RESILIENCE_DEADLINE_EXCEEDED = "deadline_exceeded"    # tripped scopes
RESILIENCE_BROWNOUT_SHEDS = "brownout_sheds"    # requests shed browned-out
RESILIENCE_BROWNOUT_LEVEL = "brownout_level"    # gauge: current rung
RESILIENCE_HEDGE_WAIT_MS = "hedge_wait_ms"      # delay before the hedge

# multi-host write-plane counter/histogram names (multihost metric
# group; producers in parallel/multihost.py + parallel/distributed.py,
# consumers benchmarks/multihost_bench.py + tests + dashboards).
# commit_conflicts counts snapshot-CAS losses observed by distributed
# commits (each is one peer's concurrent publish); commit_retries
# counts distributed commits that needed >1 CAS attempt before
# winning; ownership_handoffs counts (partition,bucket) owners that
# moved between ownership-map versions (bucket rescale); barrier_wait_ms
# is the per-process wall time spent inside cross-host barriers
# (sync_global_devices) — the direct cost of global agreement.
MULTIHOST_COMMIT_CONFLICTS = "commit_conflicts"
MULTIHOST_COMMIT_RETRIES = "commit_retries"
MULTIHOST_OWNERSHIP_HANDOFFS = "ownership_handoffs"
MULTIHOST_BARRIER_WAIT_MS = "barrier_wait_ms"
MULTIHOST_FOREIGN_ROWS = "foreign_rows_routed"  # rows exchanged to owners
MULTIHOST_CONFIG_WARNINGS = "config_warnings"   # collective-config fallbacks

# multi-host MAINTENANCE-plane names (same multihost group; producer is
# parallel/maintenance_plane.py, consumers the multi-host soak tests +
# dashboards).  owned_buckets is a per-process gauge of the
# (partition,bucket) groups this process currently owns (it JUMPS on a
# takeover — the visible re-lease of a dead peer's buckets);
# lease_renewals counts this process's successful lease stamps
# (commit-carried or heartbeat); lease_expired counts peers this
# process's failure detector declared dead; maintenance_takeovers
# counts completed adoptions (ownership version bumped with the dead
# set recorded — the acceptance signal of host-death tolerance).
MULTIHOST_OWNED_BUCKETS = "owned_buckets"
MULTIHOST_MAINTENANCE_TAKEOVERS = "maintenance_takeovers"
MULTIHOST_LEASE_RENEWALS = "lease_renewals"
MULTIHOST_LEASE_EXPIRED = "lease_expired"

# incremental-metadata-plane counter/histogram names (plan metric
# group; producers in core/scan.py + maintenance/manifest_compact.py,
# consumers benchmarks/plan_bench.py + tests + dashboards).
# plan_delta_applies counts plans served by advancing a cached plan
# with only the new snapshots' delta manifests (the steady-state
# streaming re-plan path); manifests_pruned counts whole manifest
# files skipped by the columnar stats sidecar BEFORE any fetch, and
# entries_decoded is the proof meter — it must not move for pruned
# manifests.  The whole group is pre-allocated at FileStoreScan
# construction so the Prometheus endpoint always renders the series.
PLAN_PLANS = "plans"                          # scan plans produced
PLAN_MS = "plan_ms"                           # one whole plan() call
PLAN_DELTA_APPLIES = "plan_delta_applies"     # cache-advanced plans
PLAN_MANIFESTS_READ = "manifests_read"        # manifest files fetched
PLAN_MANIFESTS_PRUNED = "manifests_pruned"    # skipped before fetch
PLAN_ENTRIES_DECODED = "entries_decoded"      # manifest entries decoded
PLAN_MANIFEST_COMPACTIONS = "manifest_compactions"  # full rewrites

# self-healing fleet-plane counter/gauge names (fleet metric group;
# producers in parallel/maintenance_plane.py + maintenance/fsck.py +
# maintenance/orphan.py, consumers the kill-two-then-rejoin soak tests
# + dashboards).  rejoins counts hosts READMITTED into the ownership
# map by the elected granter (the acceptance signal of operator-free
# healing: two victims rejoining render rejoins 2); generations is a
# gauge of the current ownership-map version (every takeover, rejoin
# and rescale advances it); fsck_incremental_runs counts fsck/orphan
# sweeps that rode the watermark delta walk instead of the full chain;
# fsck_objects_checked counts objects (snapshots, manifest lists,
# manifests, data files) a sweep actually verified — the O(delta)
# proof meter, mirroring plan entries_decoded; fsck_watermark_age_ms
# is a gauge of how stale the last clean-sweep watermark is (an alert
# on this catches a fleet whose verification plane silently stopped).
FLEET_REJOINS = "rejoins"
FLEET_GENERATIONS = "generations"
FLEET_FSCK_INCREMENTAL_RUNS = "fsck_incremental_runs"
FLEET_FSCK_OBJECTS_CHECKED = "fsck_objects_checked"
FLEET_FSCK_WATERMARK_AGE_MS = "fsck_watermark_age_ms"

# SLO burn-rate plane gauge/counter names (slo metric group; producer
# obs/slo.py's SloEvaluator — evaluated per replica over the serving
# histogram windows, consumers GET /slo, the router fleet aggregate,
# `paimon fleet status` and the Prometheus `paimon_slo_*` series).
# burn = (observed bad-event rate) / (error budget); >1 means the
# budget is being spent faster than the objective allows, and the
# alert gauge goes 1 only when BOTH the fast and slow windows burn hot
# (Google SRE multi-window multi-burn-rate alerting: the slow window
# kills flapping, the fast window kills slow detection).
SLO_AVAILABILITY_BURN_FAST = "availability_burn_fast"
SLO_AVAILABILITY_BURN_SLOW = "availability_burn_slow"
SLO_LATENCY_BURN_FAST = "latency_burn_fast"
SLO_LATENCY_BURN_SLOW = "latency_burn_slow"
SLO_ALERT = "alert"
SLO_GOOD_EVENTS = "good_events"
SLO_BAD_EVENTS = "bad_events"

# Fixed cumulative-bucket bounds (milliseconds) for the Prometheus
# `le`-bucket exposition of every latency histogram.  FIXED ON PURPOSE:
# external Prometheus can only aggregate `_bucket` series across
# replicas (histogram_quantile over a sum()) when every replica exports
# the identical bound set.
HISTOGRAM_BUCKET_BOUNDS_MS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0)


class Counter:
    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1):
        with self._lock:
            self._v += n

    @property
    def count(self) -> int:
        with self._lock:
            return self._v


class Gauge:
    def __init__(self, fn: Optional[Callable[[], float]] = None):
        self._fn = fn
        self._v = 0.0

    def set(self, v: float):
        self._v = v

    @property
    def value(self) -> float:
        return self._fn() if self._fn is not None else self._v


class Histogram:
    """Sliding-window histogram (reference DescriptiveStatisticsHistogram
    with window size 100).

    Thread-safe on BOTH sides: the window is a deque(maxlen=window), so
    `update` is O(1) (the old list.pop(0) was O(n)), and every read
    takes the lock — `sum()`/`max()` over a deque that another thread
    is appending to raises "deque mutated during iteration", and even
    the old list version could return torn means.

    Besides the window, a cumulative `total_count`/`total_sum` pair is
    tracked: Prometheus summary `_count`/`_sum` must be MONOTONIC for
    rate()/increase() to work — window-derived values would cap at the
    window size and fluctuate as samples rotate out.
    """

    def __init__(self, window: int = 100):
        self.window = window
        self._values: deque = deque(maxlen=max(1, int(window)))
        self._total_count = 0
        self._total_sum = 0.0
        # cumulative per-bound counts over the FIXED shared bound set
        # (HISTOGRAM_BUCKET_BOUNDS_MS) — the +Inf bucket is
        # total_count.  Stored non-cumulative per slot; bucket_counts()
        # emits the running `le` form Prometheus wants.
        self._bucket_slots = [0] * len(HISTOGRAM_BUCKET_BOUNDS_MS)
        self._lock = threading.Lock()

    def update(self, v: float):
        i = bisect.bisect_left(HISTOGRAM_BUCKET_BOUNDS_MS, v)
        with self._lock:
            self._values.append(v)
            self._total_count += 1
            self._total_sum += v
            if i < len(self._bucket_slots):
                self._bucket_slots[i] += 1

    def bucket_counts(self) -> List[tuple]:
        """Cumulative ``(le_bound_ms, count)`` pairs, monotonic in both
        coordinates, ending with ``(inf, total_count)``."""
        with self._lock:
            slots = list(self._bucket_slots)
            total = self._total_count
        out, run = [], 0
        for bound, n in zip(HISTOGRAM_BUCKET_BOUNDS_MS, slots):
            run += n
            out.append((bound, run))
        out.append((float("inf"), total))
        return out

    @property
    def total_count(self) -> int:
        """Cumulative updates ever (monotonic; window-independent)."""
        with self._lock:
            return self._total_count

    @property
    def total_sum(self) -> float:
        """Cumulative sum of every update ever (monotonic)."""
        with self._lock:
            return self._total_sum

    @property
    def count(self) -> int:
        with self._lock:
            return len(self._values)

    def percentile(self, p: float) -> float:
        with self._lock:
            if not self._values:
                return 0.0
            vals = sorted(self._values)
            i = min(len(vals) - 1, int(p / 100 * len(vals)))
            return vals[i]

    def window_values(self) -> List[float]:
        """The trailing sample window as a list (fleet aggregation:
        pooling several instances' windows gives a TRUE pooled
        percentile, which no combination of per-instance percentiles
        can)."""
        with self._lock:
            return list(self._values)

    @property
    def mean(self) -> float:
        with self._lock:
            if not self._values:
                return 0.0
            return sum(self._values) / len(self._values)

    @property
    def max(self) -> float:
        with self._lock:
            return max(self._values) if self._values else 0.0


class MetricGroup:
    def __init__(self, name: str):
        self.name = name
        self.metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, kind: type, factory: Callable):
        """Lazy allocation (the old `setdefault(name, Kind())` built a
        throwaway metric on every hot-path call once the name existed)
        + kind safety (reusing a name across kinds used to silently
        return the wrong type; now it raises)."""
        with self._lock:
            m = self.metrics.get(name)
            if m is None:
                m = factory()
                self.metrics[name] = m
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric {name!r} in group {self.name!r} is a "
                    f"{type(m).__name__}, not a {kind.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter, Counter)

    def gauge(self, name: str,
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        return self._get(name, Gauge, lambda: Gauge(fn))

    def histogram(self, name: str, window: int = 100) -> Histogram:
        return self._get(name, Histogram, lambda: Histogram(window))

    def timer(self, histogram_name: str):
        """Context manager recording elapsed millis into a histogram."""
        h = self.histogram(histogram_name)

        class _Timer:
            def __enter__(self_t):
                self_t.t0 = time.perf_counter()
                return self_t

            def __exit__(self_t, *exc):
                h.update((time.perf_counter() - self_t.t0) * 1000)
                return False

        return _Timer()


class MetricRegistry:
    """reference metrics/MetricRegistry.java: groups keyed by
    (group_type, table)."""

    def __init__(self):
        self._groups: Dict[str, MetricGroup] = {}
        self._lock = threading.Lock()

    def group(self, group_type: str, table: str = "") -> MetricGroup:
        key = f"{group_type}:{table}" if table else group_type
        with self._lock:
            return self._groups.setdefault(key, MetricGroup(key))

    def commit_metrics(self, table: str = "") -> MetricGroup:
        return self.group("commit", table)

    def scan_metrics(self, table: str = "") -> MetricGroup:
        return self.group("scan", table)

    def compaction_metrics(self, table: str = "") -> MetricGroup:
        return self.group("compaction", table)

    def write_metrics(self, table: str = "") -> MetricGroup:
        """Pipelined write/ingest plane (ours)."""
        return self.group("write", table)

    def maintenance_metrics(self, table: str = "") -> MetricGroup:
        """Expire / orphan-clean / fsck plane (ours)."""
        return self.group("maintenance", table)

    def stream_metrics(self, table: str = "") -> MetricGroup:
        """Streaming-daemon plane (ours; service/stream_daemon.py)."""
        return self.group("stream", table)

    def service_metrics(self, table: str = "") -> MetricGroup:
        """Query-serving plane (ours; service/query_service.py +
        service/admission.py).  `table` doubles as the tenant id for
        per-tenant gauges."""
        return self.group("service", table)

    def lookup_metrics(self, table: str = "") -> MetricGroup:
        """Point-lookup plane (ours; lookup/)."""
        return self.group("lookup", table)

    def cache_disk_metrics(self, table: str = "") -> MetricGroup:
        """Tiered host-SSD storage plane (ours; fs/caching.py disk
        tier + the write path's staged uploads)."""
        return self.group("cache_disk", table)

    def resilience_metrics(self, table: str = "") -> MetricGroup:
        """Tail-tolerance plane (ours; fs/resilience.py hedges +
        breakers, utils/deadline.py, service/brownout.py).  `table`
        doubles as the backend name for per-backend breaker gauges."""
        return self.group("resilience", table)

    def plan_metrics(self, table: str = "") -> MetricGroup:
        """Incremental metadata plane (ours; core/scan.py delta-apply
        plan cache + vectorized manifest pruning +
        maintenance/manifest_compact.py)."""
        return self.group("plan", table)

    def multihost_metrics(self, table: str = "") -> MetricGroup:
        """Multi-host write plane (ours; parallel/multihost.py
        barriers + parallel/distributed.py sharded-ownership writers
        and commit arbitration)."""
        return self.group("multihost", table)

    def fleet_metrics(self, table: str = "") -> MetricGroup:
        """Self-healing fleet plane (ours; coordinated rejoin in
        parallel/maintenance_plane.py + incremental fsck/orphan
        sweeps in maintenance/)."""
        return self.group("fleet", table)

    def slo_metrics(self, table: str = "") -> MetricGroup:
        """SLO burn-rate plane (ours; obs/slo.py SloEvaluator —
        pre-allocated so the `paimon_slo_*` series exist from the
        first scrape, before any request has been judged)."""
        return self.group("slo", table)

    def snapshot_rows(self) -> List[Dict[str, object]]:
        """Flat typed rows — THE single serialization point behind
        every observability surface (`$metrics` system table,
        Prometheus exposition, bench `metrics_snapshot` blocks, the
        CLI, and `snapshot()` itself):

            {"group", "table", "metric", "kind", "value",
             + for histograms: "count", "mean", "p95", "max"}

        `value` is the counter count, the gauge value, or the
        histogram mean.
        """
        with self._lock:
            groups = list(self._groups.items())
        rows: List[Dict[str, object]] = []
        for gkey, group in groups:
            gtype, _, gtable = gkey.partition(":")
            with group._lock:
                metrics = list(group.metrics.items())
            for mname, m in metrics:
                base = {"group": gtype, "table": gtable, "metric": mname}
                if isinstance(m, Counter):
                    rows.append({**base, "kind": "counter",
                                 "value": m.count})
                elif isinstance(m, Gauge):
                    rows.append({**base, "kind": "gauge",
                                 "value": m.value})
                elif isinstance(m, Histogram):
                    mean = m.mean
                    rows.append({**base, "kind": "histogram",
                                 "value": mean, "count": m.count,
                                 "mean": mean,
                                 "p95": m.percentile(95), "max": m.max,
                                 "total_count": m.total_count,
                                 "total_sum": m.total_sum,
                                 "buckets": m.bucket_counts()})
        return rows

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """{group: {metric: value}} for reporting (histograms render as
        {count, mean, p95, max} dicts).  Built from snapshot_rows so
        every surface serializes identically."""
        out: Dict[str, Dict[str, object]] = {}
        for r in self.snapshot_rows():
            gkey = f"{r['group']}:{r['table']}" if r["table"] \
                else r["group"]
            d = out.setdefault(gkey, {})
            if r["kind"] == "histogram":
                d[r["metric"]] = {"count": r["count"], "mean": r["mean"],
                                  "p95": r["p95"], "max": r["max"]}
            else:
                d[r["metric"]] = r["value"]
        return out


_GLOBAL = MetricRegistry()


def global_registry() -> MetricRegistry:
    return _GLOBAL


class CompactTimer:
    """Sliding-window busy-time tracker: how many milliseconds of the
    last `window_ms` were spent compacting (reference
    compact/CompactTimer.java — O(1) amortized interval bookkeeping;
    the numbers feed write-stall decisions and busy gauges)."""

    def __init__(self, window_ms: int = 60_000, clock=None):
        import threading as _threading
        import time as _time
        self.window_ms = window_ms
        self._clock = clock or (lambda: int(_time.time() * 1000))
        self._intervals: list = []      # [start, end or None]
        self._depth = 0                 # overlapping tasks share one
        self._lock = _threading.Lock()  # interval (thread-safe like
                                        # the reference @ThreadSafe)

    @property
    def _active(self) -> bool:
        return self._depth > 0

    def start(self, now: Optional[int] = None):
        now = self._clock() if now is None else now
        with self._lock:
            self._trim(now)
            if self._depth == 0:
                self._intervals.append([now, None])
            self._depth += 1

    def stop(self, now: Optional[int] = None):
        now = self._clock() if now is None else now
        with self._lock:
            if self._depth > 0:
                self._depth -= 1
                if self._depth == 0:
                    self._intervals[-1][1] = now

    def _trim(self, now: int):
        horizon = now - self.window_ms
        self._intervals = [
            iv for iv in self._intervals
            if iv[1] is None or iv[1] > horizon]

    def busy_millis(self, now: Optional[int] = None) -> int:
        """Compaction-busy milliseconds within the trailing window."""
        now = self._clock() if now is None else now
        horizon = now - self.window_ms
        with self._lock:
            self._trim(now)
            total = 0
            for start, end in self._intervals:
                e = now if end is None else min(end, now)
                s = max(start, horizon)
                if e > s:
                    total += e - s
            return total

    def busy_ratio(self, now: Optional[int] = None) -> float:
        return self.busy_millis(now) / self.window_ms
