"""Table-level statistics (ANALYZE).

reference: paimon-core/.../stats/Statistics.java (mergedRecordCount,
mergedRecordSize, colStats: distinctCount/min/max/nullCount/avgLen/
maxLen), StatsFile/StatsFileHandler (JSON file under statistics/,
referenced by an ANALYZE snapshot's `statistics` field).
"""

from __future__ import annotations

import json
import uuid
from typing import Dict, List, Optional

import pyarrow as pa
import pyarrow.compute as pc

__all__ = ["analyze_table", "read_statistics"]


def _col_stats(col: pa.ChunkedArray) -> Dict:
    out: Dict = {"nullCount": col.null_count}
    try:
        out["distinctCount"] = pc.count_distinct(col).as_py()
    except pa.ArrowNotImplementedError:
        pass
    try:
        mm = pc.min_max(col)
        mn, mx = mm["min"].as_py(), mm["max"].as_py()
        out["min"] = str(mn) if mn is not None else None
        out["max"] = str(mx) if mx is not None else None
    except pa.ArrowNotImplementedError:
        pass
    t = col.type
    if pa.types.is_string(t) or pa.types.is_large_string(t) or \
            pa.types.is_binary(t) or pa.types.is_large_binary(t):
        lens = pc.binary_length(col.combine_chunks())
        if col.null_count < len(col):
            out["avgLen"] = int(pc.mean(lens).as_py() or 0)
            out["maxLen"] = int(pc.max(lens).as_py() or 0)
    elif pa.types.is_primitive(t):
        out["avgLen"] = out["maxLen"] = t.bit_width // 8
    return out


def analyze_table(table, columns: Optional[List[str]] = None
                  ) -> Optional[int]:
    """Full-scan ANALYZE: compute table/column stats, write a statistics
    file and commit an ANALYZE snapshot referencing it. Returns the
    snapshot id (reference flink AnalyzeTableProcedure ->
    StatsFileHandler.writeStats)."""
    from paimon_tpu.core.commit import FileStoreCommit
    from paimon_tpu.snapshot import CommitKind
    from paimon_tpu.snapshot.snapshot import BATCH_COMMIT_IDENTIFIER

    snapshot = table.snapshot_manager.latest_snapshot()
    if snapshot is None:
        return None
    # scan pinned to the captured snapshot: concurrent commits must not
    # skew the stats away from the recorded snapshotId
    rb = table.new_read_builder()
    plan = rb.new_scan().plan(snapshot_id=snapshot.id)
    data = rb.new_read().to_arrow(plan)
    names = columns or [f.name for f in table.schema.fields]
    unknown = [n for n in names if n not in data.column_names]
    if unknown:
        raise ValueError(f"Unknown columns for ANALYZE: {unknown}")
    col_stats = {name: _col_stats(data.column(name)) for name in names}
    stats = {
        "snapshotId": snapshot.id,
        "schemaId": table.schema.id,
        "mergedRecordCount": data.num_rows,
        "mergedRecordSize": data.nbytes,
        "colStats": col_stats,
    }
    name = f"stats-{uuid.uuid4()}-0"
    table.file_io.write_bytes(
        f"{table.path}/statistics/{name}",
        json.dumps(stats, indent=2).encode("utf-8"), overwrite=False)

    commit = FileStoreCommit(table.file_io, table.path, table.schema,
                             table.options, branch=table.branch)
    return commit._try_commit([], [], BATCH_COMMIT_IDENTIFIER,
                              CommitKind.ANALYZE, statistics=name)


def read_statistics(table) -> Optional[Dict]:
    """Latest statistics visible from the current snapshot chain
    (reference StatsFileHandler.readStats: walk back to the ANALYZE
    snapshot)."""
    sm = table.snapshot_manager
    latest = sm.latest_snapshot_id()
    earliest = sm.earliest_snapshot_id()
    if latest is None:
        return None
    for sid in range(latest, (earliest or 1) - 1, -1):
        try:
            snap = sm.snapshot(sid)
        except FileNotFoundError:
            break
        if snap.statistics:
            raw = table.file_io.read_bytes(
                f"{table.path}/statistics/{snap.statistics}")
            return json.loads(raw)
    return None
