"""paimon-tpu: a TPU-native streaming lakehouse framework.

A from-scratch reimplementation of the capabilities of Apache Paimon
(reference: /root/reference, 2.0-SNAPSHOT) designed TPU-first:

- Metadata plane (snapshots, manifests, schemas, catalogs) is pure Python on
  the host, wire-compatible with the reference's on-disk layout
  (docs/docs/concepts/spec in the reference).
- Data plane is Arrow on the host and struct-of-arrays jax DeviceArrays in
  HBM; Parquet/ORC decode via Arrow C++.
- The compute core -- k-way sorted-run merge, merge engines (deduplicate,
  partial-update, aggregation, first-row), compaction rewrites -- runs on
  TPU as XLA-compiled segmented sort/reduce kernels instead of the
  reference's record-at-a-time loser tree
  (paimon-core mergetree/compact/SortMergeReaderWithLoserTree.java:34).
- Scale-out is a jax.sharding.Mesh over buckets instead of engine shuffles.
"""

__version__ = "0.1.0"

from paimon_tpu.types import (  # noqa: F401
    DataType, DataField, RowType,
    TinyIntType, SmallIntType, IntType, BigIntType,
    FloatType, DoubleType, BooleanType, CharType, VarCharType,
    BinaryType, VarBinaryType, DecimalType, DateType, TimeType,
    TimestampType, LocalZonedTimestampType, ArrayType, MapType,
    MultisetType, BlobType, VariantType,
)
from paimon_tpu.options import Options, ConfigOption, CoreOptions  # noqa: F401
from paimon_tpu.schema.schema import Schema  # noqa: F401


def create_catalog(options=None, **kwargs):
    """Create a catalog from options (analog of CatalogFactory.createCatalog,
    reference paimon-core catalog/CatalogFactory.java)."""
    from paimon_tpu.catalog import create_catalog as _create
    return _create(options, **kwargs)
