"""SimpleStats: per-file column min/max/null-count triple.

reference: paimon-core/.../stats/SimpleStats.java; min/max are BinaryRow
bytes over the stat'd columns (spec manifest.md appendix).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

from paimon_tpu.data.binary_row import BINARY_ROW_EMPTY, BinaryRowCodec
from paimon_tpu.types import DataType

__all__ = ["SimpleStats"]


@dataclass
class SimpleStats:
    """min/max kept as raw BinaryRow bytes so stats round-trip without
    knowing the schema; decode on demand with a codec."""

    min_values: bytes
    max_values: bytes
    null_counts: Optional[List[Optional[int]]]

    EMPTY: "SimpleStats" = None  # set below

    @staticmethod
    def from_values(field_types: Sequence[DataType],
                    mins: Sequence[Any], maxs: Sequence[Any],
                    null_counts: Sequence[int]) -> "SimpleStats":
        codec = BinaryRowCodec(field_types)
        return SimpleStats(codec.to_bytes(mins), codec.to_bytes(maxs),
                           list(null_counts))

    def decode(self, field_types: Sequence[DataType]) -> Tuple[tuple, tuple]:
        codec = BinaryRowCodec(field_types)
        return (codec.from_bytes(self.min_values),
                codec.from_bytes(self.max_values))

    def to_avro(self) -> dict:
        return {"_MIN_VALUES": self.min_values,
                "_MAX_VALUES": self.max_values,
                "_NULL_COUNTS": self.null_counts}

    @staticmethod
    def from_avro(d: dict) -> "SimpleStats":
        return SimpleStats(bytes(d["_MIN_VALUES"]), bytes(d["_MAX_VALUES"]),
                           d.get("_NULL_COUNTS"))


SimpleStats.EMPTY = SimpleStats(BINARY_ROW_EMPTY, BINARY_ROW_EMPTY, [])
