"""Index manifest: table-index files (dynamic-bucket hash index,
deletion vectors).

reference: paimon-core/.../manifest/IndexManifestFile.java,
index/IndexFileMeta.java; spec manifest.md "Index Manifest".
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from paimon_tpu.format import avro as avro_fmt
from paimon_tpu.fs import FileIO
from paimon_tpu.manifest.manifest_entry import FileKind

__all__ = ["IndexFileMeta", "IndexManifestEntry", "IndexManifestFile",
           "HASH_INDEX", "DELETION_VECTORS_INDEX"]

HASH_INDEX = "HASH"
DELETION_VECTORS_INDEX = "DELETION_VECTORS"


@dataclass
class IndexFileMeta:
    index_type: str
    file_name: str
    file_size: int
    row_count: int
    # file name -> (offset, length, cardinality) for DELETION_VECTORS
    dv_ranges: Optional[Dict[str, Tuple[int, int, int]]] = None


@dataclass
class IndexManifestEntry:
    kind: int            # FileKind
    partition: bytes
    bucket: int
    index_file: IndexFileMeta

    def to_avro(self) -> dict:
        dv = None
        if self.index_file.dv_ranges is not None:
            dv = [{"f0": k, "f1": v[0], "f2": v[1], "_CARDINALITY": v[2]}
                  for k, v in self.index_file.dv_ranges.items()]
        return {
            "_VERSION": 1,
            "_KIND": self.kind,
            "_PARTITION": self.partition,
            "_BUCKET": self.bucket,
            "_INDEX_TYPE": self.index_file.index_type,
            "_FILE_NAME": self.index_file.file_name,
            "_FILE_SIZE": self.index_file.file_size,
            "_ROW_COUNT": self.index_file.row_count,
            "_DELETIONS_VECTORS_RANGES": dv,
        }

    @staticmethod
    def from_avro(d: dict) -> "IndexManifestEntry":
        dv = None
        if d.get("_DELETIONS_VECTORS_RANGES") is not None:
            dv = {r["f0"]: (r["f1"], r["f2"], r.get("_CARDINALITY", -1))
                  for r in d["_DELETIONS_VECTORS_RANGES"]}
        return IndexManifestEntry(
            kind=d["_KIND"],
            partition=bytes(d["_PARTITION"]),
            bucket=d["_BUCKET"],
            index_file=IndexFileMeta(
                index_type=d["_INDEX_TYPE"],
                file_name=d["_FILE_NAME"],
                file_size=d["_FILE_SIZE"],
                row_count=d["_ROW_COUNT"],
                dv_ranges=dv,
            ))


INDEX_MANIFEST_AVRO_SCHEMA = {
    "type": "record",
    "name": "IndexManifestEntry",
    "fields": [
        {"name": "_VERSION", "type": "int"},
        {"name": "_KIND", "type": "int"},
        {"name": "_PARTITION", "type": "bytes"},
        {"name": "_BUCKET", "type": "int"},
        {"name": "_INDEX_TYPE", "type": "string"},
        {"name": "_FILE_NAME", "type": "string"},
        {"name": "_FILE_SIZE", "type": "long"},
        {"name": "_ROW_COUNT", "type": "long"},
        {"name": "_DELETIONS_VECTORS_RANGES",
         "type": ["null", {"type": "array", "items": {
             "type": "record", "name": "DeletionVectorMeta", "fields": [
                 {"name": "f0", "type": "string"},
                 {"name": "f1", "type": "int"},
                 {"name": "f2", "type": "int"},
                 {"name": "_CARDINALITY", "type": ["null", "long"],
                  "default": None},
             ]}}],
         "default": None},
    ],
}


class IndexManifestFile:
    """Reads/writes index-manifest-<uuid>-<n> files. Each snapshot's index
    manifest is the FULL current set of index files (merged)."""

    def __init__(self, file_io: FileIO, manifest_dir: str,
                 compression: str = "zstandard"):
        self.file_io = file_io
        self.manifest_dir = manifest_dir.rstrip("/")
        self.compression = compression

    def path(self, name: str) -> str:
        return f"{self.manifest_dir}/{name}"

    def write(self, entries: Sequence[IndexManifestEntry]) -> str:
        name = f"index-manifest-{uuid.uuid4()}-0"
        data = avro_fmt.write_container(
            INDEX_MANIFEST_AVRO_SCHEMA, [e.to_avro() for e in entries],
            codec=self.compression)
        self.file_io.write_bytes(self.path(name), data, overwrite=False)
        return name

    def read(self, name: str) -> List[IndexManifestEntry]:
        _, records = avro_fmt.read_container(
            self.file_io.read_bytes(self.path(name)))
        return [IndexManifestEntry.from_avro(r) for r in records]

    def combine(self, previous_name: Optional[str],
                new_entries: Sequence[IndexManifestEntry]) -> Optional[str]:
        """Merge previous index manifest with new ADD/DELETE entries and
        write the combined manifest (reference
        IndexManifestFile.writeIndexFiles)."""
        if not new_entries:
            return previous_name
        live: Dict[Tuple, IndexManifestEntry] = {}
        if previous_name:
            for e in self.read(previous_name):
                live[(e.partition, e.bucket, e.index_file.index_type,
                      e.index_file.file_name)] = e
        for e in new_entries:
            key = (e.partition, e.bucket, e.index_file.index_type,
                   e.index_file.file_name)
            if e.kind == FileKind.ADD:
                live[key] = e
            else:
                live.pop(key, None)
        return self.write(list(live.values()))
