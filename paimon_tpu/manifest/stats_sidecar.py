"""Columnar manifest-stats sidecar: vectorized manifest pruning.

Every manifest list `manifest-list-*` may carry a `stats-<name>`
sidecar — ONE Arrow IPC table with a row per manifest file holding the
manifest's partition min/max (typed columns), bucket range and
first-primary-key-field range.  Scan planning reads the sidecar (one
object-store GET per list, riding the byte caches) and evaluates the
scan's partition/bucket/key predicates against the WHOLE batch with
numpy/arrow-compute array comparisons, so a pruned manifest is never
fetched and none of its entries are ever decoded — replacing the old
per-meta python decode loop in `FileStoreScan._prune_manifests`
(reference AbstractFileStoreScan manifest-level pruning; columnar
layout per "An Empirical Evaluation of Columnar Storage Formats",
arxiv 2304.05028).

All pruning here is CONSERVATIVE: a null/missing stat keeps the
manifest, a missing sidecar keeps the python fallback, and only
necessary-condition bounds (predicate.conjunctive_bounds) ever drop
one.
"""

from __future__ import annotations

import io
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

__all__ = ["SIDECAR_PREFIX", "sidecar_name", "sidecar_path",
           "build_sidecar", "read_sidecar", "prune_keep_mask"]

# prefix, not a suffix: nothing pattern-matching `manifest-list-*`
# (tests, repair tooling, ad-hoc scripts) may ever mistake a sidecar
# for a manifest list
SIDECAR_PREFIX = "stats-"


def sidecar_name(list_name: str) -> str:
    return SIDECAR_PREFIX + list_name


def sidecar_path(list_path: str) -> str:
    d, _, base = list_path.rpartition("/")
    return (d + "/" if d else "") + sidecar_name(base)


def _arrow_types(data_types) -> Optional[list]:
    from paimon_tpu.types import data_type_to_arrow
    out = []
    for t in data_types:
        try:
            out.append(data_type_to_arrow(t.as_nullable()))
        except (ValueError, NotImplementedError):
            return None
    return out


def _coerce(values: list, typ: pa.DataType) -> pa.Array:
    """Typed column from python scalars; any coercion failure degrades
    the WHOLE column to nulls (never a wrong bound)."""
    try:
        return pa.array(values, typ)
    except (pa.ArrowInvalid, pa.ArrowTypeError, pa.ArrowNotImplementedError,
            OverflowError, TypeError):
        return pa.nulls(len(values), typ)


def build_sidecar(metas: Sequence, partition_types: list,
                  key_types: Optional[list]) -> Optional[bytes]:
    """Serialize the sidecar table for one manifest list.  Rows are
    derived purely from each ManifestFileMeta (partition_stats +
    min/max bucket + min/max key bytes), so stats survive base-list
    rewrites without the original entries in memory.  Returns None
    when nothing typed can be built (no partition or key columns)."""
    from paimon_tpu.data.binary_row import BinaryRowCodec

    p_arrow = _arrow_types(partition_types) if partition_types else []
    k_arrow = _arrow_types(key_types[:1]) if key_types else []
    if p_arrow is None:
        p_arrow = []
    if k_arrow is None:
        k_arrow = []
    if not p_arrow and not k_arrow:
        return None

    n = len(metas)
    names: List[str] = []
    min_b: List[Optional[int]] = []
    max_b: List[Optional[int]] = []
    p_mins = [[None] * n for _ in p_arrow]
    p_maxs = [[None] * n for _ in p_arrow]
    k_min: List[object] = [None] * n
    k_max: List[object] = [None] * n
    p_codec = BinaryRowCodec(partition_types) if p_arrow else None
    k_codec = BinaryRowCodec([t.copy(False) for t in key_types[:1]]) \
        if k_arrow else None

    for row, m in enumerate(metas):
        names.append(m.file_name)
        min_b.append(getattr(m, "min_bucket", None))
        max_b.append(getattr(m, "max_bucket", None))
        if p_codec is not None:
            stats = m.partition_stats
            if stats is not None and stats.min_values and stats.max_values:
                try:
                    mins = p_codec.from_bytes(stats.min_values)
                    maxs = p_codec.from_bytes(stats.max_values)
                    for i in range(len(p_arrow)):
                        p_mins[i][row] = mins[i]
                        p_maxs[i][row] = maxs[i]
                except Exception:  # lint-ok: swallow stats are advisory — an undecodable partition row leaves the column null, which the prune keeps
                    pass
        if k_codec is not None:
            mk = getattr(m, "min_key", None)
            xk = getattr(m, "max_key", None)
            if mk and xk:
                try:
                    k_min[row] = k_codec.from_bytes(mk)[0]
                    k_max[row] = k_codec.from_bytes(xk)[0]
                except Exception:  # lint-ok: swallow stats are advisory — an undecodable key leaves the bound null, which the prune keeps
                    pass

    cols: Dict[str, pa.Array] = {
        "file_name": pa.array(names, pa.string()),
        "min_bucket": _coerce(min_b, pa.int32()),
        "max_bucket": _coerce(max_b, pa.int32()),
    }
    for i, t in enumerate(p_arrow):
        cols[f"p{i}_min"] = _coerce(p_mins[i], t)
        cols[f"p{i}_max"] = _coerce(p_maxs[i], t)
    if k_arrow:
        cols["k_min"] = _coerce(k_min, k_arrow[0])
        cols["k_max"] = _coerce(k_max, k_arrow[0])
    table = pa.table(cols)
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue()


def read_sidecar(file_io, list_path: str) -> Optional[pa.Table]:
    """The sidecar table for one manifest list, or None when absent or
    undecodable (pruning then falls back to the python path)."""
    try:
        data = file_io.read_bytes(sidecar_path(list_path))
        with pa.ipc.open_stream(io.BytesIO(data)) as r:
            return r.read_all()
    except Exception:                       # noqa: BLE001 — advisory
        return None


def _overlap_mask(min_col: pa.ChunkedArray, max_col: pa.ChunkedArray,
                  lo, hi, typ: pa.DataType) -> Optional[np.ndarray]:
    """keep[i] = [min_i, max_i] may intersect [lo, hi]; nulls keep.
    None when the literals cannot be coerced to the column type."""
    import pyarrow.compute as pc
    keep = np.ones(len(min_col), dtype=bool)
    try:
        if hi is not None:
            m = pc.fill_null(pc.less_equal(min_col, pa.scalar(hi, typ)),
                             True)
            keep &= m.combine_chunks().to_numpy(zero_copy_only=False)
        if lo is not None:
            m = pc.fill_null(pc.greater_equal(max_col, pa.scalar(lo, typ)),
                             True)
            keep &= m.combine_chunks().to_numpy(zero_copy_only=False)
    except (pa.ArrowInvalid, pa.ArrowTypeError, pa.ArrowNotImplementedError,
            OverflowError, TypeError):
        return None
    return keep


def prune_keep_mask(stats: pa.Table, partition_keys: Sequence[str],
                    partition_filter: Optional[dict],
                    bucket_filter: Optional[set],
                    key_bounds: Optional[Tuple]) -> np.ndarray:
    """Vectorized keep mask over one manifest list's sidecar rows.
    Every failure mode (missing column, uncoercible literal) degrades
    to keep for the affected constraint."""
    n = stats.num_rows
    keep = np.ones(n, dtype=bool)
    cols = set(stats.column_names)

    if partition_filter:
        for i, k in enumerate(partition_keys):
            if k not in partition_filter:
                continue
            lo_c, hi_c = f"p{i}_min", f"p{i}_max"
            if lo_c not in cols or hi_c not in cols:
                continue
            v = partition_filter[k]
            m = _overlap_mask(stats[lo_c], stats[hi_c], v, v,
                              stats.schema.field(lo_c).type)
            if m is not None:
                keep &= m

    if bucket_filter:
        real = {b for b in bucket_filter if b >= 0}
        # prune only on an all-real filter: special buckets (-2
        # postpone staging) sit outside the range containment
        if real == set(bucket_filter) and real \
                and "min_bucket" in cols and "max_bucket" in cols:
            m = _overlap_mask(stats["min_bucket"], stats["max_bucket"],
                              min(real), max(real), pa.int32())
            if m is not None:
                keep &= m

    if key_bounds is not None and "k_min" in cols and "k_max" in cols:
        lo, hi = key_bounds
        m = _overlap_mask(stats["k_min"], stats["k_max"], lo, hi,
                          stats.schema.field("k_min").type)
        if m is not None:
            keep &= m

    return keep
