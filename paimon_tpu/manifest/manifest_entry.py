"""ManifestEntry: ADD/DELETE of a data file in a (partition, bucket).

reference: paimon-core/.../manifest/ManifestEntry.java + FileEntry merge
logic (ManifestFileMerger): the same file may be added then deleted across
manifests; the last state wins, and a DELETE cancels its ADD.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Tuple

from paimon_tpu.manifest.data_file_meta import (
    DATA_FILE_META_AVRO_SCHEMA, DataFileMeta,
)

__all__ = ["FileKind", "ManifestEntry", "merge_manifest_entries",
           "MANIFEST_ENTRY_AVRO_SCHEMA"]

ENTRY_VERSION = 2


class FileKind:
    ADD = 0
    DELETE = 1


@dataclass
class ManifestEntry:
    kind: int                 # FileKind
    partition: bytes          # BinaryRow of partition values
    bucket: int
    total_buckets: int
    file: DataFileMeta

    def identifier(self) -> Tuple:
        """Unique id of the file within the table
        (reference FileEntry.identifier)."""
        return (self.partition, self.bucket, self.file.level,
                self.file.file_name, tuple(self.file.extra_files),
                self.file.embedded_index, self.file.external_path)

    def to_avro(self) -> dict:
        return {
            "_VERSION": ENTRY_VERSION,
            "_KIND": self.kind,
            "_PARTITION": self.partition,
            "_BUCKET": self.bucket,
            "_TOTAL_BUCKETS": self.total_buckets,
            "_FILE": self.file.to_avro(),
        }

    @staticmethod
    def from_avro(d: dict) -> "ManifestEntry":
        return ManifestEntry(
            kind=d["_KIND"],
            partition=bytes(d["_PARTITION"]),
            bucket=d["_BUCKET"],
            total_buckets=d["_TOTAL_BUCKETS"],
            file=DataFileMeta.from_avro(d["_FILE"]),
        )


MANIFEST_ENTRY_AVRO_SCHEMA = {
    "type": "record",
    "name": "ManifestEntry",
    "fields": [
        {"name": "_VERSION", "type": "int"},
        {"name": "_KIND", "type": "int"},
        {"name": "_PARTITION", "type": "bytes"},
        {"name": "_BUCKET", "type": "int"},
        {"name": "_TOTAL_BUCKETS", "type": "int"},
        {"name": "_FILE", "type": DATA_FILE_META_AVRO_SCHEMA},
    ],
}


def merge_manifest_entries(
        entries: Iterable[ManifestEntry]) -> List[ManifestEntry]:
    """Collapse ADD/DELETE history: keep live files only
    (reference manifest/FileEntry.mergeEntries)."""
    live: Dict[Tuple, ManifestEntry] = {}
    for e in entries:
        ident = e.identifier()
        if e.kind == FileKind.ADD:
            live[ident] = e
        else:
            if ident in live:
                del live[ident]
            else:
                # DELETE of a file added in an older base: keep the delete
                # so downstream merging can cancel it.
                live[ident] = e
    return list(live.values())
